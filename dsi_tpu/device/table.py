"""Device-resident accumulator service: the cross-step merge table.

The streaming engine (``parallel/streaming.py``) historically mirrored
the reference MapReduce's host-centric shape: every step's reduce output
crossed D2H and was merged into the host accumulator before the next
step could retire — one pull per step, exactly the per-intermediate
round-trip the reference pays in JSON files on a shared filesystem
(``mr/worker.go:81-121``).  On the axon tunnel that pull is ~0.1 s of
latency plus ~25 MB/s of wire per step; the depth-2 pipeline can hide
the *merge* but not the wire.

This module keeps the merged table ON DEVICE instead:

* :class:`DeviceTable` owns a persistent packed key/count table at a
  fixed per-device capacity rung — keys as big-endian uint32 lanes
  (``ops/wordcount.py`` layout, so the host decode path is unchanged),
  counts as uint64 (cross-step sums can exceed uint32 long before a
  sync), occupancy per device.  Every device holds only words of the
  reduce partitions it owns (``parallel/shuffle.py`` routing), so
  per-device tables are disjoint and a host drain is a concatenation.
* ``fold``: ONE compiled program (cached via ``backends/aotcache`` under
  ``aot``) merges a step's packed reduce output into the table in place:
  concat + packed-u64 lexicographic sort + run detection + segment-sum —
  the same grouping idiom as the kernels' reduce, at table+step size.
  The table arrays are DONATED to the fold, so XLA updates the table in
  place and table residency never doubles; the step tensor is NOT
  donated — it is the recovery payload if the fold reports overflow.
* overflow never drops keys silently: a fold whose merged uniques exceed
  the capacity rung is a GLOBAL no-op (an on-device ``pmax`` makes every
  device keep its old shard — a mixed commit would double-count the
  folded devices when the step is recovered) and surfaces a widen signal
  in the fold's tiny ``[n_dev, 2]`` flags output.
* ``widen``: drain the table to the host accumulator (``PackedCounts``),
  reallocate at the next capacity rung (x4, the repo's rung discipline),
  and re-fold the orphaned steps — their packed tensors were kept alive
  exactly for this.  The same protocol re-keys the table when the word
  window widens mid-stream (kk changes, e.g. a >16-byte word forcing the
  64-byte rung).
* flag checks are LAGGED: blocking on a fold's flags the moment it is
  dispatched would wait out every kernel queued behind it on the
  in-order device stream — the serialization the pipeline exists to
  avoid.  Folds are confirmed ``lag`` folds late (the streaming engine
  passes its pipeline depth); folds are commutative count-sums and a
  failed fold is a no-op, so late detection loses nothing.

Sync cadence (pull every K folds) is owned by ``device/policy.py``; the
caller drives ``sync()``/``close()``.  Host pulls therefore number
``ceil(folds / K) + widens`` instead of one per step — the amortization
``pipeline_stats`` reports as ``sync_pulls``/``widens``.

``mesh_shards=n`` makes the table MESH-SHARDED: the fold program gains
an all-to-all exchange (``ops/meshroute.py``) that routes every step row
to its owning shard by the paper's partition rule — ``ihash(key) %
n_shards``, the reference-exact FNV-1a over the key bytes — BEFORE the
concat+sort+segsum merge, so each shard holds the complete, already-
merged state for its hash range and cross-step state scales with
aggregate HBM instead of per-device accidents (without it, key placement
follows the step's ``n_reduce % n_dev`` routing: with the default 10
partitions on 8 devices, two shards carry twice the keys of the rest).
What changes with it:

* the overflow signal becomes PER-SHARD: a fold commits on every shard
  whose merged uniques fit and no-ops only where they don't (safe
  because the exchange is deterministic — a re-fold under an ``apply``
  mask re-delivers exactly the failed shards' rows, and folds are
  commutative count-sums), so a hot shard never blocks the mesh;
* the widen protocol is per-shard: only hot shards drain to the host
  (a single-shard D2H via its addressable shard — cold shards never
  touch the wire), the reallocation copies cold shards ON DEVICE
  (compiled ``mesh_grow_*`` program; the physical rung is shared — XLA
  arrays are rectangular — but only hot shards' content moves), and
  only hot shards re-fold, counted per shard in ``shard_widens``;
* sync pulls the occupied prefix of ONE pre-merged, hash-balanced
  table (``pull_bytes`` counts the actual D2H payload both ways — the
  bench's mesh A/B row reads it), and ``shard_imbalance`` tracks
  max/mean shard occupancy (~1.0 under FNV routing; the skew evidence
  when a corpus is adversarial);
* fold spans land in the tracer's ``shuffle`` lane (the fold IS the
  shuffle there), with ``shard_widen`` events carrying the hot set.

Results are bit-identical to ``mesh_shards=0`` (and to the depth=1
host-merge path): routing changes WHICH shard holds a key, never the
key's count, and every drain ends in the same host accumulator.
"""

from __future__ import annotations

import collections
import contextlib
import functools
import warnings
from typing import Deque, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dsi_tpu.obs import span as _span, trace_event as _trace_event
from dsi_tpu.ops.meshroute import exchange_rows, route_dest
from dsi_tpu.ops.wordcount import (
    _PAD_KEY,
    _PAD_KEY64,
    group_sorted,
    pack_key_lanes,
    unpack_key_rows,
)
from dsi_tpu.parallel.shuffle import AXIS, occupied_prefix
from dsi_tpu.utils.jaxcompat import enable_x64, x64_scoped, shard_map

#: jax.jit donate_argnums for the fold/clear programs: the five table
#: arrays are consumed and rewritten in place.  Shared by the jit path,
#: the AOT compile, the warmer, and the cache-existence probe.
_TABLE_DONATE = (0, 1, 2, 3, 4)


@contextlib.contextmanager
def _quiet_unusable_donation():
    """On backends where XLA declines to alias a donated buffer (XLA:CPU
    does even for shape-matched donations) jax warns once per compiled
    program — expected for OUR dispatches, so the warning is suppressed
    around them only: a process-global filter would hide the same
    warning from the user's unrelated jax programs, where a silently
    unusable donation is real signal.  The single definition for every
    donating dispatch site (the streaming engine imports it from
    here)."""
    with warnings.catch_warnings():
        warnings.filterwarnings(
            "ignore", message="Some donated buffers were not usable")
        yield


def _fold_device(tkeys, tlens, tcnts, tparts, tn, packed, scal, *,
                 cap: int, kk: int):
    """Per-device fold body (runs under shard_map).

    Table shard + this device's slice of the step's packed reduce output
    -> merged table shard + ``[overflow, occupancy]`` flags.  Pad rows
    carry ``_PAD_KEY`` in every lane (u64-max after pairwise packing) so
    they sort last and ``group_sorted``'s max-value pad detection holds —
    the invariant every fold output re-establishes.
    """
    tkeys = tkeys.reshape(cap, kk)
    tlens = tlens.reshape(cap)
    tcnts = tcnts.reshape(cap)
    tparts = tparts.reshape(cap)
    tn0 = tn.reshape(())
    rows = packed.shape[-2]
    packed = packed.reshape(rows, kk + 3)
    scal = scal.reshape(-1)

    # Step rows beyond this device's merged-unique count are garbage
    # (zero keys, not pad): mask them to pad rows before the sort.
    sn = scal[0]
    svalid = jnp.arange(rows, dtype=jnp.int32) < sn
    skeys = jnp.where(svalid[:, None], packed[:, :kk], jnp.uint32(_PAD_KEY))
    slens = jnp.where(svalid, packed[:, kk].astype(jnp.int32), 0)
    sparts = jnp.where(svalid, packed[:, kk + 2].astype(jnp.int32), 0)

    with enable_x64(True):  # every op touching u64 operands needs it
        scnts = jnp.where(svalid, packed[:, kk + 1].astype(jnp.uint64),
                          jnp.uint64(0))
        allkeys = jnp.concatenate([tkeys, skeys], axis=0)
        alllens = jnp.concatenate([tlens, slens])
        allcnts = jnp.concatenate([tcnts, scnts])
        allparts = jnp.concatenate([tparts, sparts])
        keys64 = pack_key_lanes(tuple(allkeys[:, j] for j in range(kk)))
        k64 = len(keys64)
        sorted_ops = lax.sort(keys64 + (alllens, allcnts, allparts),
                              num_keys=k64)
        mkeys64, tot, upos, ovalid, m_unique = group_sorted(
            sorted_ops[:k64], sorted_ops[k64 + 1], cap)
        new_keys64 = jnp.where(ovalid[:, None], mkeys64[upos],
                               jnp.uint64(_PAD_KEY64))
        new_keys = unpack_key_rows(new_keys64, kk)
        new_cnts = jnp.where(ovalid, tot, jnp.uint64(0))
    new_lens = jnp.where(ovalid, sorted_ops[k64][upos], 0)
    new_parts = jnp.where(ovalid, sorted_ops[k64 + 2][upos], 0)

    # Commit is all-or-nothing ACROSS devices: if any shard overflowed,
    # every shard keeps its old table (the step is recovered whole by the
    # widen path; a partial commit would double-count the folded shards).
    ov = lax.pmax((m_unique > cap).astype(jnp.int32), AXIS)
    keep_old = ov > 0
    out_keys = jnp.where(keep_old, tkeys, new_keys)
    out_lens = jnp.where(keep_old, tlens, new_lens)
    out_cnts = jnp.where(keep_old, tcnts, new_cnts)
    out_parts = jnp.where(keep_old, tparts, new_parts)
    out_n = jnp.where(keep_old, tn0, jnp.minimum(m_unique, cap))
    flags = jnp.stack([ov, out_n])
    return (out_keys[None], out_lens[None], out_cnts[None], out_parts[None],
            out_n[None], flags[None])


def _fold_impl(tkeys, tlens, tcnts, tparts, tn, packed, scal, *, mesh: Mesh):
    cap, kk = tkeys.shape[1], tkeys.shape[2]
    body = functools.partial(_fold_device, cap=cap, kk=kk)
    return shard_map(
        body, mesh=mesh,
        in_specs=(P(AXIS, None, None), P(AXIS, None), P(AXIS, None),
                  P(AXIS, None), P(AXIS), P(AXIS, None, None), P(AXIS, None)),
        out_specs=(P(AXIS, None, None), P(AXIS, None), P(AXIS, None),
                   P(AXIS, None), P(AXIS), P(AXIS, None)),
    )(tkeys, tlens, tcnts, tparts, tn, packed, scal)


#: In-process fold program for multi-device meshes / non-aot callers.
fold_step = x64_scoped(jax.jit(_fold_impl, static_argnames=("mesh",),
                               donate_argnums=_TABLE_DONATE))


def _mesh_fold_device(tkeys, tlens, tcnts, tparts, tn, packed, scal, apply,
                      *, cap: int, kk: int, n_dev: int, n_shards: int):
    """Per-shard mesh fold body (runs under shard_map): the paper's
    shuffle as the fold's prologue.  Every valid step row is routed to
    shard ``ihash(key) % n_shards`` over the mesh (one all_to_all), THEN
    merged into that shard's table slice — so the table is always the
    complete pre-merged state of each shard's hash range.  Commit is
    PER-SHARD: ``apply`` masks which shards merge at all (the re-fold
    path re-delivers an orphaned step only to the shards that no-op'd),
    and overflow no-ops only the shard it happened on."""
    tkeys = tkeys.reshape(cap, kk)
    tlens = tlens.reshape(cap)
    tcnts = tcnts.reshape(cap)
    tparts = tparts.reshape(cap)
    tn0 = tn.reshape(())
    rows = packed.shape[-2]
    packed = packed.reshape(rows, kk + 3)
    scal = scal.reshape(-1)
    apply0 = apply.reshape(()) > 0

    # Garbage rows beyond the step's merged-unique count are parked on
    # the exchange's dump row; valid rows route by the reference-exact
    # ihash over their actual key bytes (ops/meshroute.py).
    sn = scal[0]
    svalid = jnp.arange(rows, dtype=jnp.int32) < sn
    skeys = jnp.where(svalid[:, None], packed[:, :kk], jnp.uint32(_PAD_KEY))
    slens = jnp.where(svalid, packed[:, kk].astype(jnp.int32), 0)
    dest = route_dest(skeys, slens, svalid, n_shards=n_shards, park=n_dev)
    recv = exchange_rows(packed, dest, n_dev=n_dev, kk=kk)

    # Received rows are valid-prefix-per-source-block with PAD-key pad
    # rows (zero payload) — they sort last and group as empty, exactly
    # the invariant every fold output re-establishes.
    rlens = recv[:, kk].astype(jnp.int32)
    rparts = recv[:, kk + 2].astype(jnp.int32)
    with enable_x64(True):  # every op touching u64 operands needs it
        rcnts = recv[:, kk + 1].astype(jnp.uint64)
        allkeys = jnp.concatenate([tkeys, recv[:, :kk]], axis=0)
        alllens = jnp.concatenate([tlens, rlens])
        allcnts = jnp.concatenate([tcnts, rcnts])
        allparts = jnp.concatenate([tparts, rparts])
        keys64 = pack_key_lanes(tuple(allkeys[:, j] for j in range(kk)))
        k64 = len(keys64)
        sorted_ops = lax.sort(keys64 + (alllens, allcnts, allparts),
                              num_keys=k64)
        mkeys64, tot, upos, ovalid, m_unique = group_sorted(
            sorted_ops[:k64], sorted_ops[k64 + 1], cap)
        new_keys64 = jnp.where(ovalid[:, None], mkeys64[upos],
                               jnp.uint64(_PAD_KEY64))
        new_keys = unpack_key_rows(new_keys64, kk)
        new_cnts = jnp.where(ovalid, tot, jnp.uint64(0))
    new_lens = jnp.where(ovalid, sorted_ops[k64][upos], 0)
    new_parts = jnp.where(ovalid, sorted_ops[k64 + 2][upos], 0)

    # Per-shard commit — no pmax: an overflowed shard keeps its old
    # slice and reports its own flag; everyone else commits.  Safe
    # because the exchange is deterministic (a re-fold re-delivers the
    # same rows to the same shards) and folds commute, so the recovery
    # re-fold under ``apply = failed shards`` double-counts nothing.
    ov = jnp.where(apply0, (m_unique > cap).astype(jnp.int32),
                   jnp.int32(0))
    keep_old = (ov > 0) | ~apply0
    out_keys = jnp.where(keep_old, tkeys, new_keys)
    out_lens = jnp.where(keep_old, tlens, new_lens)
    out_cnts = jnp.where(keep_old, tcnts, new_cnts)
    out_parts = jnp.where(keep_old, tparts, new_parts)
    out_n = jnp.where(keep_old, tn0, jnp.minimum(m_unique, cap))
    flags = jnp.stack([ov, out_n])
    return (out_keys[None], out_lens[None], out_cnts[None], out_parts[None],
            out_n[None], flags[None])


def _mesh_fold_impl(tkeys, tlens, tcnts, tparts, tn, packed, scal, apply, *,
                    mesh: Mesh, n_shards: int):
    cap, kk = tkeys.shape[1], tkeys.shape[2]
    n_dev = int(mesh.devices.size)
    body = functools.partial(_mesh_fold_device, cap=cap, kk=kk,
                             n_dev=n_dev, n_shards=n_shards)
    return shard_map(
        body, mesh=mesh,
        in_specs=(P(AXIS, None, None), P(AXIS, None), P(AXIS, None),
                  P(AXIS, None), P(AXIS), P(AXIS, None, None), P(AXIS, None),
                  P(AXIS)),
        out_specs=(P(AXIS, None, None), P(AXIS, None), P(AXIS, None),
                   P(AXIS, None), P(AXIS), P(AXIS, None)),
    )(tkeys, tlens, tcnts, tparts, tn, packed, scal, apply)


#: In-process mesh fold (the shuffle-fold) for non-aot callers.
mesh_fold_step = x64_scoped(
    jax.jit(_mesh_fold_impl, static_argnames=("mesh", "n_shards"),
            donate_argnums=_TABLE_DONATE))


def _grow_device(tkeys, tlens, tcnts, tparts, tn, keep, *, old_cap: int,
                 new_cap: int, kk: int):
    """Per-shard widen reallocation body: kept shards carry their rows
    into the wider allocation ON DEVICE (no wire), dropped (hot) shards
    come back empty — their rows were just drained to the host."""
    tkeys = tkeys.reshape(old_cap, kk)
    tlens = tlens.reshape(old_cap)
    tcnts = tcnts.reshape(old_cap)
    tparts = tparts.reshape(old_cap)
    tn0 = tn.reshape(())
    keep0 = keep.reshape(()) > 0

    gkeys = jnp.full((new_cap, kk), jnp.uint32(_PAD_KEY), jnp.uint32) \
        .at[:old_cap].set(tkeys)
    glens = jnp.zeros((new_cap,), jnp.int32).at[:old_cap].set(tlens)
    with enable_x64(True):
        gcnts = jnp.zeros((new_cap,), jnp.uint64).at[:old_cap].set(tcnts)
        out_cnts = jnp.where(keep0, gcnts, jnp.zeros_like(gcnts))
    gparts = jnp.zeros((new_cap,), jnp.int32).at[:old_cap].set(tparts)
    out_keys = jnp.where(keep0, gkeys,
                         jnp.full_like(gkeys, jnp.uint32(_PAD_KEY)))
    out_lens = jnp.where(keep0, glens, jnp.zeros_like(glens))
    out_parts = jnp.where(keep0, gparts, jnp.zeros_like(gparts))
    out_n = jnp.where(keep0, tn0, jnp.int32(0))
    return (out_keys[None], out_lens[None], out_cnts[None], out_parts[None],
            out_n[None])


def _grow_impl(tkeys, tlens, tcnts, tparts, tn, keep, *, mesh: Mesh,
               new_cap: int):
    old_cap, kk = tkeys.shape[1], tkeys.shape[2]
    body = functools.partial(_grow_device, old_cap=old_cap,
                             new_cap=new_cap, kk=kk)
    return shard_map(
        body, mesh=mesh,
        in_specs=(P(AXIS, None, None), P(AXIS, None), P(AXIS, None),
                  P(AXIS, None), P(AXIS), P(AXIS)),
        out_specs=(P(AXIS, None, None), P(AXIS, None), P(AXIS, None),
                   P(AXIS, None), P(AXIS)),
    )(tkeys, tlens, tcnts, tparts, tn, keep)


grow_table = x64_scoped(
    jax.jit(_grow_impl, static_argnames=("mesh", "new_cap"),
            donate_argnums=_TABLE_DONATE))


@functools.partial(jax.jit, static_argnames=("mp",))
def _rows_prefix(rows, *, mp: int):
    """Fresh-buffer prefix slice of a ``[n_dev, rows, ...]`` tensor
    (shared with ``device/postings.py``): the output aliases nothing
    (no donation), so a retained slice — a delta capture, a snapshot
    pull — survives every later fold/clear/grow that donates the live
    state, and its D2H can drain under the next pipeline window."""
    return rows[:, :mp]


def _copy_to_host_async(arr) -> None:
    """Kick an async D2H on a jax array if the runtime supports it (the
    capture half of the overlapped snapshot); materialization later in
    the commit writer then finds the transfer already draining."""
    fn = getattr(arr, "copy_to_host_async", None)
    if fn is not None:
        try:
            fn()
        except Exception:
            pass  # overlap is an optimization; np.asarray still works


def _pull_shard(arr, d: int) -> np.ndarray:
    """D2H of ONE mesh shard: the per-shard widen's drain pulls only the
    hot shard's slice via its addressable shard — cold shards never
    touch the wire (the whole point of widening per shard)."""
    for s in arr.addressable_shards:
        idx = s.index[0]
        start = idx.start or 0
        if start == d and (idx.stop is None or idx.stop - start == 1):
            return np.asarray(s.data)[0]
    return np.asarray(arr[d])  # replicated/odd layout: plain slice pull


def _clear_device(tkeys, tlens, tcnts, tparts, tn):
    return (jnp.full_like(tkeys, jnp.uint32(_PAD_KEY)),
            jnp.zeros_like(tlens), jnp.zeros_like(tcnts),
            jnp.zeros_like(tparts), jnp.zeros_like(tn))


def _clear_impl(tkeys, tlens, tcnts, tparts, tn, *, mesh: Mesh):
    """Reset the table to empty ON DEVICE (donated, in place): a sync
    must not re-upload a capacity-sized block of pads over the tunnel
    just to start the next window."""
    return shard_map(
        _clear_device, mesh=mesh,
        in_specs=(P(AXIS, None, None), P(AXIS, None), P(AXIS, None),
                  P(AXIS, None), P(AXIS)),
        out_specs=(P(AXIS, None, None), P(AXIS, None), P(AXIS, None),
                   P(AXIS, None), P(AXIS)),
    )(tkeys, tlens, tcnts, tparts, tn)


clear_table = x64_scoped(jax.jit(_clear_impl, static_argnames=("mesh",),
                                 donate_argnums=_TABLE_DONATE))


@functools.partial(jax.jit, static_argnames=("mp",))
def _pack_prefix_impl(tkeys, tlens, tparts, tcnts, *, mp: int):
    """Device-side prefix slice + pack for a table drain: one uint32
    tensor [D, mp, kk+2] (keys + len + part) plus the uint64 count
    prefix — two D2H transfers per SYNC, versus (historically) one pull
    per STEP.  ``mp`` is the pow2-rounded occupied prefix under jit,
    the full capacity under aot (deterministic shapes, same trade as the
    stream's pulls)."""
    packed = jnp.concatenate(
        [tkeys[:, :mp],
         tlens[:, :mp, None].astype(jnp.uint32),
         tparts[:, :mp, None].astype(jnp.uint32)], axis=2)
    return packed, tcnts[:, :mp]


_pack_prefix = x64_scoped(_pack_prefix_impl)


def _fold_program(*, mesh: Mesh, n_dev: int, cap: int, kk: int, rows: int):
    """(name, fn) for one compiled fold shape — single definition shared
    by the cached-compile path, the warmer, and the cache-existence
    probe (same discipline as ``streaming._step_program``)."""
    import dsi_tpu.ops.wordcount as _wc

    def fn(tkeys, tlens, tcnts, tparts, tn, packed, scal):
        return _fold_impl(tkeys, tlens, tcnts, tparts, tn, packed, scal,
                          mesh=mesh)

    fn._aot_code_deps = (_wc,)
    return f"dacc_fold_d{n_dev}_c{cap}_k{kk}_r{rows}", fn


def _clear_program(*, mesh: Mesh, n_dev: int, cap: int, kk: int):
    def fn(tkeys, tlens, tcnts, tparts, tn):
        return _clear_impl(tkeys, tlens, tcnts, tparts, tn, mesh=mesh)

    return f"dacc_clear_d{n_dev}_c{cap}_k{kk}", fn


def _pack_program(*, n_dev: int, cap: int, kk: int, mp: int):
    def fn(tkeys, tlens, tparts, tcnts):
        return _pack_prefix_impl(tkeys, tlens, tparts, tcnts, mp=mp)

    return f"dacc_pack_d{n_dev}_c{cap}_k{kk}_m{mp}", fn


def _mesh_fold_program(*, mesh: Mesh, n_dev: int, n_shards: int, cap: int,
                       kk: int, rows: int):
    """(name, fn) for one compiled shuffle-fold shape — the ``mesh_*``
    warm-ladder entries, same single-definition discipline as
    :func:`_fold_program`."""
    import dsi_tpu.ops.meshroute as _mr
    import dsi_tpu.ops.wordcount as _wc

    def fn(tkeys, tlens, tcnts, tparts, tn, packed, scal, apply):
        return _mesh_fold_impl(tkeys, tlens, tcnts, tparts, tn, packed,
                               scal, apply, mesh=mesh, n_shards=n_shards)

    fn._aot_code_deps = (_wc, _mr)
    return (f"mesh_fold_d{n_dev}_s{n_shards}_c{cap}_k{kk}_r{rows}", fn)


def _grow_program(*, mesh: Mesh, n_dev: int, old_cap: int, new_cap: int,
                  kk: int):
    def fn(tkeys, tlens, tcnts, tparts, tn, keep):
        return _grow_impl(tkeys, tlens, tcnts, tparts, tn, keep,
                          mesh=mesh, new_cap=new_cap)

    return f"mesh_grow_d{n_dev}_c{old_cap}to{new_cap}_k{kk}", fn


def _table_structs(n_dev: int, cap: int, kk: int):
    sds = jax.ShapeDtypeStruct
    return (sds((n_dev, cap, kk), jnp.uint32),
            sds((n_dev, cap), jnp.int32),
            sds((n_dev, cap), jnp.uint64),
            sds((n_dev, cap), jnp.int32),
            sds((n_dev,), jnp.int32))


def _step_structs(n_dev: int, rows: int, kk: int):
    sds = jax.ShapeDtypeStruct
    return (sds((n_dev, rows, kk + 3), jnp.uint32),
            sds((n_dev, 5), jnp.int32))


def _apply_struct(n_dev: int):
    return jax.ShapeDtypeStruct((n_dev,), jnp.int32)


def _warm_mesh_fold_rung(mesh: Mesh, *, n_dev: int, n_shards: int,
                         cap: int, kk: int, rows: int,
                         grow: bool) -> None:
    """Compile + persist one mesh capacity rung: the ``mesh_fold_*``
    shuffle-fold at ``cap`` plus, with ``grow``, the ``mesh_grow_*``
    c→4c per-shard widen reallocation to the next rung.  The single
    source of the mesh warm-ladder shapes — ``warm_device_fold`` and
    ``topk.warm_topk_service`` both call it, so the compiled keys
    cannot drift between the word table and the top-k service."""
    from dsi_tpu.backends import aotcache

    table = _table_structs(n_dev, cap, kk)
    step = _step_structs(n_dev, rows, kk)
    name, fn = _mesh_fold_program(mesh=mesh, n_dev=n_dev,
                                  n_shards=n_shards, cap=cap, kk=kk,
                                  rows=rows)
    with _quiet_unusable_donation():
        aotcache.cached_compile(
            name, fn, table + step + (_apply_struct(n_dev),),
            donate_argnums=_TABLE_DONATE, x64=True)
    if grow:
        name, fn = _grow_program(mesh=mesh, n_dev=n_dev, old_cap=cap,
                                 new_cap=cap * 4, kk=kk)
        with _quiet_unusable_donation():
            aotcache.cached_compile(
                name, fn, table + (_apply_struct(n_dev),),
                donate_argnums=_TABLE_DONATE, x64=True)


def _warm_pack_shapes(*, n_dev: int, cap: int, kk: int,
                      mesh_shards: int) -> None:
    """Compile + persist the drain pack program(s) for one capacity
    rung.  The non-mesh aot path pulls at the deterministic full
    capacity (one shape); mesh syncs pull the occupied PREFIX (the
    pre-merged table is hash-balanced, so the prefix tracks
    vocabulary/shards) — a data-dependent but pow2-bounded mp ladder
    (``occupied_prefix``: 64..cap, log2(cap) tiny slice+concat
    programs).  Warm the whole ladder so no prefix rung ever
    cold-compiles on the tunnel."""
    from dsi_tpu.backends import aotcache

    table = _table_structs(n_dev, cap, kk)
    mp = 64 if mesh_shards else cap
    while True:
        mp = min(mp, cap)
        name, fn = _pack_program(n_dev=n_dev, cap=cap, kk=kk, mp=mp)
        aotcache.cached_compile(
            name, fn, (table[0], table[1], table[3], table[2]), x64=True)
        if mp >= cap:
            break
        mp *= 2


def warm_device_fold(mesh: Mesh, *, u_cap: int, kk: int = 4,
                     table_rungs: int = 2, mesh_shards: int = 0) -> None:
    """Compile + persist the fold/clear/pack shapes a device-accumulated
    stream reaches at this step capacity: the rung-0 table (cap = step
    rows) plus ``table_rungs - 1`` x4 widenings, from shape structs alone
    (no data, nothing executed) — so a fresh axon process only ever
    loads.  Callers warm per step-cap rung, mirroring
    ``streaming.warm_stream_aot``'s caps ladder.  With ``mesh_shards``
    the mesh variants are warmed INSTEAD: the ``mesh_fold_*``
    shuffle-fold at each rung plus the ``mesh_grow_*`` per-shard widen
    reallocation between adjacent rungs."""
    from dsi_tpu.backends import aotcache

    n_dev = mesh.devices.size
    rows = n_dev * u_cap
    # Same rounding DeviceTable applies to its rung-0 capacity — warmed
    # keys must be, by construction, the keys a run compiles first.
    cap = _pow2(rows)
    for rung in range(max(1, table_rungs)):
        table = _table_structs(n_dev, cap, kk)
        step = _step_structs(n_dev, rows, kk)
        if mesh_shards:
            _warm_mesh_fold_rung(mesh, n_dev=n_dev, n_shards=mesh_shards,
                                 cap=cap, kk=kk, rows=rows,
                                 grow=rung + 1 < max(1, table_rungs))
        else:
            name, fn = _fold_program(mesh=mesh, n_dev=n_dev, cap=cap,
                                     kk=kk, rows=rows)
            with _quiet_unusable_donation():
                aotcache.cached_compile(name, fn, table + step,
                                        donate_argnums=_TABLE_DONATE,
                                        x64=True)
        name, fn = _clear_program(mesh=mesh, n_dev=n_dev, cap=cap, kk=kk)
        with _quiet_unusable_donation():
            aotcache.cached_compile(name, fn, table,
                                    donate_argnums=_TABLE_DONATE, x64=True)
        _warm_pack_shapes(n_dev=n_dev, cap=cap, kk=kk,
                          mesh_shards=mesh_shards)
        cap *= 4


def device_fold_persisted(mesh: Mesh, *, u_cap: int, kk: int = 4,
                          mesh_shards: int = 0) -> bool:
    """True when the rung-0 fold/clear/pack programs for this shape are
    already in the persistent AOT cache — the stream-row gate's
    device-accumulate extension (see ``stream_programs_persisted``).
    With ``mesh_shards`` the probe keys on the ``mesh_fold_*``
    shuffle-fold instead (the program a mesh run compiles first)."""
    from dsi_tpu.backends.aotcache import is_persisted

    n_dev = mesh.devices.size
    rows = n_dev * u_cap
    cap = _pow2(rows)  # mirror DeviceTable's rung-0 rounding exactly
    table = _table_structs(n_dev, cap, kk)
    step = _step_structs(n_dev, rows, kk)
    if mesh_shards:
        name, fn = _mesh_fold_program(mesh=mesh, n_dev=n_dev,
                                      n_shards=mesh_shards, cap=cap,
                                      kk=kk, rows=rows)
        if not is_persisted(name, fn,
                            table + step + (_apply_struct(n_dev),),
                            donate_argnums=_TABLE_DONATE):
            return False
    else:
        name, fn = _fold_program(mesh=mesh, n_dev=n_dev, cap=cap, kk=kk,
                                 rows=rows)
        if not is_persisted(name, fn, table + step,
                            donate_argnums=_TABLE_DONATE):
            return False
    name, fn = _clear_program(mesh=mesh, n_dev=n_dev, cap=cap, kk=kk)
    if not is_persisted(name, fn, table, donate_argnums=_TABLE_DONATE):
        return False
    name, fn = _pack_program(n_dev=n_dev, cap=cap, kk=kk, mp=cap)
    return is_persisted(name, fn, (table[0], table[1], table[3], table[2]))


def _pow2(n: int) -> int:
    return 1 << max(0, int(n) - 1).bit_length()


class DeviceTable:
    """Persistent on-device merged word/count table, folded per step,
    drained per sync window.

    ``acc`` is the host :class:`~dsi_tpu.parallel.merge.PackedCounts`
    every drain merges into; ``stats``, if given, receives the service's
    counters (``folds``, ``fold_overflows``, ``sync_pulls``, ``widens``,
    ``table_cap``, and ``fold_s``/``sync_s``/``widen_s`` wall seconds).
    ``lag`` is how many folds may stay unconfirmed before the oldest's
    flags are checked (the streaming engine passes its pipeline depth);
    ``sync()``/``close()``/``widen`` flush the lag entirely.

    ``mesh_shards`` > 0 switches the fold to the mesh-sharded
    shuffle-fold (module docstring): keys route to ``ihash % n_shards``
    inside the compiled program, overflow flags and the widen protocol
    become per-shard (``shard_widens``), and ``shard_imbalance`` tracks
    max/mean occupancy.  ``pull_bytes`` counts every D2H drain payload
    in BOTH modes — the bench mesh A/B row's evidence.
    """

    def __init__(self, mesh: Mesh, *, kk: int, cap: int, acc,
                 aot: bool = False, lag: int = 1,
                 stats: Optional[dict] = None, mesh_shards: int = 0):
        self.mesh = mesh
        self.n_dev = int(mesh.devices.size)
        self.kk = int(kk)
        self.cap = _pow2(cap)
        self.acc = acc
        self.aot = bool(aot)
        self.lag = max(0, int(lag))
        self.mesh_shards = max(0, int(mesh_shards))
        if self.mesh_shards > self.n_dev:
            raise ValueError(
                f"mesh_shards={self.mesh_shards} exceeds the mesh size "
                f"({self.n_dev} devices); shards map 1:1 onto devices")
        self.stats = stats if stats is not None else {}
        for key in ("folds", "fold_overflows", "sync_pulls", "widens",
                    "pull_bytes"):
            self.stats.setdefault(key, 0)
        for key in ("fold_s", "sync_s", "widen_s"):
            self.stats.setdefault(key, 0.0)
        if self.mesh_shards:
            self.stats.setdefault("mesh_shards", self.mesh_shards)
            self.stats.setdefault("shard_widens", [0] * self.n_dev)
            self.stats.setdefault("shard_imbalance", 0.0)
        self._apply_dev = None  # cached all-shards apply mask (mesh mode)
        # Delta-checkpoint log (enable_delta): confirmed step payloads
        # retained since the last capture — the rows APPENDED to the
        # table, which is what an incremental save ships instead of the
        # whole image.  Step tensors are never donated (they are the
        # widen-recovery payload), so retaining the handles is safe.
        self._delta_log: list = []
        self._delta_max = 0
        self._delta_invalid = False
        self._state = self._alloc(self.cap, self.kk)
        # Occupancy per device after the last CONFIRMED fold (a no-op'd
        # fold reports the old occupancy, so this stays exact either way).
        self._nrows = np.zeros(self.n_dev, dtype=np.int64)
        # (flags_handle, packed_dev, scal_dev) per unconfirmed fold — the
        # step tensors stay referenced until their fold is proven clean,
        # so an overflowed (no-op) fold can be replayed after a widen.
        self._pending: Deque[Tuple] = collections.deque()
        self.stats["table_cap"] = self.cap

    # ── allocation / compiled-program plumbing ──

    def _alloc(self, cap: int, kk: int):
        """Fresh empty table arrays, sharded over the mesh.  One H2D
        upload per (re)allocation — allocation happens once per stream
        plus once per widen; per-sync resets go through the compiled
        ``clear`` program instead (no upload)."""
        sh3 = NamedSharding(self.mesh, P(AXIS, None, None))
        sh2 = NamedSharding(self.mesh, P(AXIS, None))
        sh1 = NamedSharding(self.mesh, P(AXIS))
        with enable_x64(True):  # keep the u64 counts u64 through the put
            return (
                jax.device_put(
                    np.full((self.n_dev, cap, kk), _PAD_KEY, np.uint32), sh3),
                jax.device_put(
                    np.zeros((self.n_dev, cap), np.int32), sh2),
                jax.device_put(
                    np.zeros((self.n_dev, cap), np.uint64), sh2),
                jax.device_put(
                    np.zeros((self.n_dev, cap), np.int32), sh2),
                jax.device_put(np.zeros((self.n_dev,), np.int32), sh1))

    def _fold_fn(self, rows: int):
        if not self.aot:
            return functools.partial(fold_step, mesh=self.mesh)
        from dsi_tpu.backends import aotcache

        name, fn = _fold_program(mesh=self.mesh, n_dev=self.n_dev,
                                 cap=self.cap, kk=self.kk, rows=rows)
        examples = (_table_structs(self.n_dev, self.cap, self.kk)
                    + _step_structs(self.n_dev, rows, self.kk))
        with _quiet_unusable_donation():  # a cold entry compiles here
            return aotcache.cached_compile(name, fn, examples,
                                           donate_argnums=_TABLE_DONATE,
                                           x64=True)

    def _mesh_fold_fn(self, rows: int):
        if not self.aot:
            return functools.partial(mesh_fold_step, mesh=self.mesh,
                                     n_shards=self.mesh_shards)
        from dsi_tpu.backends import aotcache

        name, fn = _mesh_fold_program(mesh=self.mesh, n_dev=self.n_dev,
                                      n_shards=self.mesh_shards,
                                      cap=self.cap, kk=self.kk, rows=rows)
        examples = (_table_structs(self.n_dev, self.cap, self.kk)
                    + _step_structs(self.n_dev, rows, self.kk)
                    + (_apply_struct(self.n_dev),))
        with _quiet_unusable_donation():
            return aotcache.cached_compile(name, fn, examples,
                                           donate_argnums=_TABLE_DONATE,
                                           x64=True)

    def _grow_fn(self, new_cap: int):
        if not self.aot:
            return functools.partial(grow_table, mesh=self.mesh,
                                     new_cap=new_cap)
        from dsi_tpu.backends import aotcache

        name, fn = _grow_program(mesh=self.mesh, n_dev=self.n_dev,
                                 old_cap=self.cap, new_cap=new_cap,
                                 kk=self.kk)
        examples = (_table_structs(self.n_dev, self.cap, self.kk)
                    + (_apply_struct(self.n_dev),))
        with _quiet_unusable_donation():
            return aotcache.cached_compile(name, fn, examples,
                                           donate_argnums=_TABLE_DONATE,
                                           x64=True)

    def _put_apply(self, mask: np.ndarray):
        """Upload a per-shard apply mask (tiny [n_dev] int32)."""
        sh1 = NamedSharding(self.mesh, P(AXIS))
        return jax.device_put(np.asarray(mask, np.int32), sh1)

    def _apply_all(self):
        """The all-shards apply mask, uploaded once and reused by every
        normal fold (it is never donated)."""
        if self._apply_dev is None:
            self._apply_dev = self._put_apply(
                np.ones(self.n_dev, np.int32))
        return self._apply_dev

    def _clear_fn(self):
        if not self.aot:
            return functools.partial(clear_table, mesh=self.mesh)
        from dsi_tpu.backends import aotcache

        name, fn = _clear_program(mesh=self.mesh, n_dev=self.n_dev,
                                  cap=self.cap, kk=self.kk)
        with _quiet_unusable_donation():
            return aotcache.cached_compile(
                name, fn, _table_structs(self.n_dev, self.cap, self.kk),
                donate_argnums=_TABLE_DONATE, x64=True)

    def _pack_fn(self, mp: int):
        if not self.aot:
            return functools.partial(_pack_prefix, mp=mp)
        from dsi_tpu.backends import aotcache

        name, fn = _pack_program(n_dev=self.n_dev, cap=self.cap, kk=self.kk,
                                 mp=mp)
        t = _table_structs(self.n_dev, self.cap, self.kk)
        return aotcache.cached_compile(name, fn, (t[0], t[1], t[3], t[2]),
                                       x64=True)

    # ── the fold path ──

    def fold(self, packed_dev, scal_dev, scal_np: np.ndarray) -> None:
        """Dispatch one confirmed step's fold (async, no blocking) and
        lazily confirm folds older than ``lag``.  ``packed_dev`` is the
        step's full-capacity packed reduce output ``[n_dev, rows, kk+3]``
        (``shuffle._slice_pack`` layout); ``scal_np`` is the already
        host-checked scalar block (the caller's exactness confirmation —
        the fold LAGS that window by construction, because only callers
        holding a confirmed step reach here)."""
        step_kk = int(packed_dev.shape[2]) - 3
        if step_kk != self.kk:
            # The word window widened mid-stream (e.g. 16 -> 64 bytes):
            # the table's key lanes can no longer represent this step's
            # words.  Re-key via the widen protocol: drain what we have,
            # reallocate at the new width, resume folding.
            self._rekey(step_kk, int(packed_dev.shape[1]))
        if self._delta_max:
            # Record the step's appended rows for the next delta save —
            # exactly once per confirmed step (recovery re-folds go
            # through _dispatch_fold and never re-enter here).  A log
            # outgrowing its cap invalidates THIS window only: the next
            # save falls back to a full image and re-arms the log —
            # and an already-invalid window retains nothing (take_delta
            # would discard it anyway; don't pin dead HBM).
            if self._delta_invalid:
                pass
            elif len(self._delta_log) >= self._delta_max:
                self._delta_invalid = True
                self._delta_log.clear()
            else:
                self._delta_log.append(
                    (packed_dev, scal_np[:, 0].astype(np.int64).copy()))
        with _span("fold", lane="shuffle" if self.mesh_shards else "fold",
                   stats=self.stats, key="fold_s",
                   fold=self.stats["folds"]):
            out = self._dispatch_fold(packed_dev, scal_dev)
            self._pending.append((out, packed_dev, scal_dev))
            self.stats["folds"] += 1
            while len(self._pending) > self.lag:
                self._confirm_oldest()

    def _dispatch_fold(self, packed_dev, scal_dev, apply_np=None):
        """Launch one fold (async).  ``apply_np`` restricts a MESH fold
        to the masked shards — the recovery re-fold's lever; normal
        folds apply everywhere."""
        if self.mesh_shards:
            fn = self._mesh_fold_fn(int(packed_dev.shape[1]))
            apply_dev = (self._apply_all() if apply_np is None
                         else self._put_apply(apply_np))
            with _quiet_unusable_donation():
                *state, flags = fn(*self._state, packed_dev, scal_dev,
                                   apply_dev)
        else:
            fn = self._fold_fn(int(packed_dev.shape[1]))
            with _quiet_unusable_donation():
                *state, flags = fn(*self._state, packed_dev, scal_dev)
        self._state = tuple(state)
        return flags

    def _note_flags(self, flags_np: np.ndarray) -> None:
        self._nrows = flags_np[:, 1].astype(np.int64)
        if self.mesh_shards:
            occ = self._nrows[:self.mesh_shards]
            tot = int(occ.sum())
            if tot:
                self.stats["shard_imbalance"] = round(
                    float(occ.max()) * self.mesh_shards / tot, 3)

    def _confirm_oldest(self) -> None:
        flags, packed_dev, scal_dev = self._pending.popleft()
        flags_np = np.asarray(flags)  # blocks until this fold lands
        self._note_flags(flags_np)
        if flags_np[:, 0].any():
            self.stats["fold_overflows"] += 1
            self._recover([(packed_dev, scal_dev, flags_np[:, 0] > 0)])

    def _flush_pending(self):
        """Confirm every outstanding fold; return the (packed, scal,
        overflow-mask) triples of folds that no-op'd, oldest first (the
        mask is per-shard in mesh mode, all-shards otherwise)."""
        orphans = []
        while self._pending:
            flags, packed_dev, scal_dev = self._pending.popleft()
            flags_np = np.asarray(flags)
            self._note_flags(flags_np)
            if flags_np[:, 0].any():
                self.stats["fold_overflows"] += 1
                orphans.append((packed_dev, scal_dev, flags_np[:, 0] > 0))
        return orphans

    # ── overflow / widen protocol ──

    def _recover(self, orphans) -> None:
        """A fold overflowed (and was therefore a no-op — globally
        without mesh sharding, on the overflowed shards with it).  Later
        folds may already sit in the queue — flush them first (successes
        merged into the old table and drain with it; further overflows
        join the orphan list), then widen and re-fold every orphan."""
        with _span("widen", stats=self.stats, key="widen_s"):
            orphans = list(orphans) + self._flush_pending()
            if self.mesh_shards:
                self._recover_mesh(orphans)
                return
            while orphans:
                rows = max(int(p.shape[1]) for p, _, _ in orphans)
                self._widen(_pow2(max(4 * self.cap, rows)), self.kk)
                still = []
                for packed_dev, scal_dev, _ in orphans:
                    flags_np = np.asarray(
                        self._dispatch_fold(packed_dev, scal_dev))
                    self._note_flags(flags_np)
                    if flags_np[:, 0].any():  # rung still too narrow
                        still.append((packed_dev, scal_dev, None))
                orphans = still

    def _recover_mesh(self, orphans) -> None:
        """Per-shard recovery: only the HOT shards (union of the
        orphans' overflow masks) drain to the host, come back empty in
        the wider allocation, and receive the orphaned steps' re-folds
        — each orphan re-applied ONLY to its failed shards, so the
        shards that committed the first time never double-count.  Cold
        shards are copied on device (``mesh_grow_*``) and never touch
        the wire."""
        while orphans:
            hot = np.zeros(self.n_dev, dtype=bool)
            for _, _, mask in orphans:
                hot |= np.asarray(mask, dtype=bool)
            rows = max(int(p.shape[1]) for p, _, _ in orphans)
            # Stay on the x4 rung ladder the warmer persists (worst-case
            # skew can deliver n_dev * rows rows to one shard, but
            # jumping straight to that bound would reach capacities
            # `warm_device_fold` never compiled — cold remote compiles
            # mid-widen).  The loop re-widens x4 while orphans remain,
            # so termination costs at most log4(n_dev) extra rounds.
            self._widen(_pow2(max(4 * self.cap, rows)), self.kk,
                        keep=~hot)
            hot_list = [int(s) for s in np.flatnonzero(hot)]
            for s in hot_list:
                self.stats["shard_widens"][s] += 1
            _trace_event("shard_widen", lane="shuffle", shards=hot_list,
                         cap=self.cap)
            still = []
            for packed_dev, scal_dev, mask in orphans:
                flags_np = np.asarray(self._dispatch_fold(
                    packed_dev, scal_dev,
                    apply_np=np.asarray(mask, dtype=bool)))
                self._note_flags(flags_np)
                if flags_np[:, 0].any():
                    still.append((packed_dev, scal_dev,
                                  flags_np[:, 0] > 0))
            orphans = still

    def _widen(self, new_cap: int, new_kk: int, keep=None) -> None:
        """Drain into the host accumulator and reallocate at
        ``new_cap``/``new_kk``.  Into an empty table at ``cap >= rows``
        a single step always fits (its uniques are bounded by its row
        count), so the re-fold loop above terminates in one widen per
        distinct rows shape.  With ``keep`` (the per-shard protocol)
        only the dropped shards drain — one single-shard D2H each — and
        kept shards carry over via the compiled grow program."""
        if keep is None or new_kk != self.kk:
            self._pull_merge()
            self.cap, self.kk = new_cap, new_kk
            self._state = self._alloc(self.cap, self.kk)
            self._nrows[:] = 0
        else:
            drain = ~np.asarray(keep, dtype=bool)
            self._pull_merge(only=drain)
            fn = self._grow_fn(new_cap)
            keep_dev = self._put_apply(np.asarray(keep, np.int32))
            with _quiet_unusable_donation():
                self._state = tuple(fn(*self._state, keep_dev))
            self.cap = new_cap
            self._nrows[drain] = 0
        self.stats["widens"] += 1
        self.stats["table_cap"] = self.cap
        _trace_event("table_widen", lane="widen", cap=self.cap,
                     kk=self.kk)

    def _rekey(self, new_kk: int, rows: int) -> None:
        with _span("widen", stats=self.stats, key="widen_s", rekey=True):
            # Outstanding folds still match the OLD width: confirm them
            # first (overflow here recovers at the old width, which is
            # fine — their steps' words provably fit the old window).
            orphans = self._flush_pending()
            if orphans:
                self._recover(orphans)
            self._widen(_pow2(max(self.cap, rows)), new_kk)

    # ── checkpoint image (dsi_tpu/ckpt) ──

    def checkpoint_capture(self):
        """Drain-free snapshot image, capture half: flush the lagged
        flags first (so the image reflects exactly the CONFIRMED folds
        — recovery of a late-detected overflow may widen, whose drain
        lands in ``acc``, which is why callers capture the device
        services BEFORE the host accumulator), then DISPATCH the
        occupied-prefix pack (a fresh buffer: later folds donate the
        live table arrays, never this) and kick its D2H — returning a
        deferred whose ``materialize()`` (in the commit writer, or
        inline for a sync save) reconstructs the five-array image the
        restore path has always consumed.  Rows beyond each device's
        occupancy are pad by the fold invariant, so prefix + pad
        reconstruction is the live image."""
        from dsi_tpu.ckpt.delta import Deferred

        orphans = self._flush_pending()
        if orphans:
            self._recover(orphans)
        n_dev, cap, kk = self.n_dev, self.cap, self.kk
        nrows = self._nrows.copy()
        m = int(nrows.max())
        if m:
            mp = cap if (self.aot and not self.mesh_shards) \
                else occupied_prefix(m, cap)
            tkeys, tlens, tcnts, tparts, _ = self._state
            packed_dev, cnts_dev = self._pack_fn(mp)(tkeys, tlens, tparts,
                                                     tcnts)
            _copy_to_host_async(packed_dev)
            _copy_to_host_async(cnts_dev)
        else:
            packed_dev = cnts_dev = None

        def _image() -> dict:
            keys = np.full((n_dev, cap, kk), _PAD_KEY, np.uint32)
            lens = np.zeros((n_dev, cap), np.int32)
            cnts = np.zeros((n_dev, cap), np.uint64)
            parts = np.zeros((n_dev, cap), np.int32)
            if packed_dev is not None:
                p = np.asarray(packed_dev)
                c = np.asarray(cnts_dev)
                for d in range(n_dev):
                    n = int(nrows[d])
                    if n:
                        keys[d, :n] = p[d, :n, :kk]
                        lens[d, :n] = p[d, :n, kk].astype(np.int32)
                        parts[d, :n] = p[d, :n, kk + 1].astype(np.int32)
                        cnts[d, :n] = c[d, :n]
            return {"keys": keys, "lens": lens, "cnts": cnts,
                    "parts": parts, "tn": nrows.astype(np.int32),
                    "nrows": nrows.copy()}

        return Deferred(_image)

    def checkpoint_state(self) -> dict:
        """The synchronous spelling: capture + immediate materialize —
        what every PR-5 call site (and the sync save path) still
        gets."""
        return self.checkpoint_capture().materialize()

    # ── incremental (delta) checkpoints ──

    def enable_delta(self, max_steps: int = 64) -> None:
        """Arm the delta log: every confirmed fold retains its step
        payload handle until the next ``take_delta``.  ``max_steps``
        bounds the retained HBM (a window past it falls back to a full
        save)."""
        self._delta_max = max(1, int(max_steps))
        self._delta_log.clear()
        self._delta_invalid = False

    def take_delta(self):
        """The rows appended since the last capture, as ordered
        ``(sliced_rows_handle, nus)`` entries with their D2H already
        kicked — or None when this window cannot be expressed as a
        delta (log overflow), which tells the engine to write a full
        image instead.  Always re-arms the log for the next window."""
        if self._delta_invalid:
            self._delta_invalid = False
            self._delta_log.clear()
            return None
        entries = []
        for packed_dev, nus in self._delta_log:
            mp = occupied_prefix(int(nus.max()),
                                 int(packed_dev.shape[1]))
            sliced = _rows_prefix(packed_dev, mp=mp)
            _copy_to_host_async(sliced)
            entries.append((sliced, nus))
        self._delta_log.clear()
        return entries

    def restore_state(self, img: dict) -> None:
        """Re-upload a :meth:`checkpoint_state` image — re-entering
        ``device_accumulate`` mid-table on resume.  Capacity and key
        width follow the image (a widen before the crash is preserved,
        so the resumed stream starts at the rung that had already
        cleared)."""
        keys = np.asarray(img["keys"], dtype=np.uint32)
        self.cap = int(keys.shape[1])
        self.kk = int(keys.shape[2])
        sh3 = NamedSharding(self.mesh, P(AXIS, None, None))
        sh2 = NamedSharding(self.mesh, P(AXIS, None))
        sh1 = NamedSharding(self.mesh, P(AXIS))
        with enable_x64(True):  # keep the u64 counts u64 through the put
            self._state = (
                jax.device_put(keys, sh3),
                jax.device_put(np.asarray(img["lens"], np.int32), sh2),
                jax.device_put(np.asarray(img["cnts"], np.uint64), sh2),
                jax.device_put(np.asarray(img["parts"], np.int32), sh2),
                jax.device_put(np.asarray(img["tn"], np.int32), sh1))
        self._nrows = np.asarray(img["nrows"], dtype=np.int64).copy()
        self._pending.clear()
        self.stats["table_cap"] = self.cap

    # ── drains ──

    def _pull_merge(self, only=None) -> bool:
        """Pull the occupied table prefix and merge it into the host
        accumulator.  Returns True if anything crossed the wire.  With
        ``only`` (a per-shard bool mask — the per-shard widen's drain)
        just the masked shards' slices cross, one addressable-shard
        D2H each.  Mesh mode always pulls the occupied prefix (the
        pre-merged table is hash-balanced, so the prefix tracks
        vocabulary/n_shards); the non-mesh aot path keeps its
        deterministic full-capacity pulls.  ``pull_bytes`` counts the
        actual payload either way."""
        sel = self._nrows if only is None else \
            np.where(np.asarray(only, dtype=bool), self._nrows, 0)
        m = int(sel.max())
        if m == 0:
            return False
        mp = self.cap if (self.aot and not self.mesh_shards) \
            else occupied_prefix(m, self.cap)
        tkeys, tlens, tcnts, tparts, _ = self._state
        packed_dev, cnts_dev = self._pack_fn(mp)(tkeys, tlens, tparts, tcnts)
        if only is None:
            packed = np.asarray(packed_dev)
            cnts = np.asarray(cnts_dev)
            self.stats["pull_bytes"] += packed.nbytes + cnts.nbytes
            for d in range(self.n_dev):
                n = int(self._nrows[d])
                if n == 0:
                    continue
                r = packed[d, :n]
                self.acc.add(r[:, :self.kk], r[:, self.kk],
                             cnts[d, :n].astype(np.int64),
                             r[:, self.kk + 1])
        else:
            for d in np.flatnonzero(np.asarray(only, dtype=bool)):
                d = int(d)
                n = int(self._nrows[d])
                if n == 0:
                    continue
                r = _pull_shard(packed_dev, d)
                c = _pull_shard(cnts_dev, d)
                self.stats["pull_bytes"] += r.nbytes + c.nbytes
                self.acc.add(r[:n, :self.kk], r[:n, self.kk],
                             c[:n].astype(np.int64), r[:n, self.kk + 1])
        return True

    @staticmethod
    def drain_image(acc, img: dict) -> None:
        """Merge a :meth:`checkpoint_state` image into a host
        accumulator WITHOUT re-uploading it — the resume path when the
        checkpoint's sharding degree differs from the live table's
        (``mesh_shards`` recorded in the manifest): the image's merged
        rows re-enter through the drain, the table starts empty at the
        new degree, and the next folds re-shuffle ownership."""
        keys = np.asarray(img["keys"], dtype=np.uint32)
        lens = np.asarray(img["lens"])
        cnts = np.asarray(img["cnts"])
        parts = np.asarray(img["parts"])
        nrows = np.asarray(img["nrows"], dtype=np.int64)
        for d in range(keys.shape[0]):
            n = int(nrows[d])
            if n:
                acc.add(keys[d, :n], lens[d, :n],
                        cnts[d, :n].astype(np.int64), parts[d, :n])

    def sync(self) -> bool:
        """The K-step host pull: flush the fold lag, drain the table
        into the accumulator, reset it to empty ON DEVICE (compiled
        clear, no upload).  Returns True when a pull happened (an empty
        window skips the wire and is not counted)."""
        with _span("sync", stats=self.stats, key="sync_s"):
            orphans = self._flush_pending()
            if orphans:
                self._recover(orphans)
            pulled = self._pull_merge()
            if pulled:
                self.stats["sync_pulls"] += 1
                with _quiet_unusable_donation():
                    self._state = tuple(self._clear_fn()(*self._state))
                self._nrows[:] = 0
        return pulled

    def close(self) -> None:
        """Stream-end drain: flush + final pull, no reset (the table is
        dropped with the service)."""
        with _span("sync", stats=self.stats, key="sync_s", close=True):
            orphans = self._flush_pending()
            if orphans:
                self._recover(orphans)
            if self._pull_merge():
                self.stats["sync_pulls"] += 1
            self._state = None
