"""Append-only device-resident postings buffer for the TF-IDF wave walk.

TF-IDF's per-wave output is postings — (word, len, tf, doc, part) rows
that accumulate rather than merge — so the word-count ``DeviceTable``'s
sort+segment-sum fold is the wrong program.  What the wave walk shares
with the stream is the COST SHAPE: one D2H pull per wave, each charged
the tunnel's fixed per-transfer latency regardless of size
(ROADMAP item 2).  This buffer batches those pulls: waves append their
valid rows into a persistent on-device buffer with a compiled scatter
(same dump-row idiom as ``shuffle.shuffle_rows``), and the host pulls
once per K waves (``device/policy.py`` cadence) or when the buffer
fills.

Unlike the merge table there is no capacity *ladder*: a drain empties
the buffer, and the capacity is chosen >= one wave's worst-case row
count (``n_dev * u_cap``), so an append that overflows simply drains
and retries — overflow is an early sync, never a loss.  The commit is
still all-or-nothing across devices (``pmax`` on the overflow bit) so a
drained-and-retried wave cannot double-append its already-committed
shards.
"""

from __future__ import annotations

import functools
import time
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dsi_tpu.parallel.shuffle import AXIS, occupied_prefix
from dsi_tpu.utils.jaxcompat import shard_map


def _append_device(buf, n, rows, scal, *, cap: int, width: int):
    """Per-device body: scatter this wave's valid rows at the write
    offset.  Rows beyond the wave's valid count and rows past the
    capacity land on the dump row / out of bounds (dropped — identical
    either way because an overflowing append keeps the OLD buffer)."""
    buf = buf.reshape(cap, width)
    n0 = n.reshape(())
    r = rows.shape[-2]
    rows = rows.reshape(r, width)
    nr = scal.reshape(-1)[0]

    valid = jnp.arange(r, dtype=jnp.int32) < nr
    idx = jnp.where(valid, n0 + jnp.arange(r, dtype=jnp.int32), cap)
    target = jnp.concatenate([buf, jnp.zeros((1, width), jnp.uint32)], axis=0)
    new_buf = target.at[idx].set(rows)[:cap]
    new_n = n0 + nr
    ov = lax.pmax((new_n > cap).astype(jnp.int32), AXIS)
    keep_old = ov > 0
    out_buf = jnp.where(keep_old, buf, new_buf)
    out_n = jnp.where(keep_old, n0, new_n)
    flags = jnp.stack([ov, out_n])
    return out_buf[None], out_n[None], flags[None]


def _append_impl(buf, n, rows, scal, *, mesh: Mesh):
    cap, width = buf.shape[1], buf.shape[2]
    body = functools.partial(_append_device, cap=cap, width=width)
    return shard_map(
        body, mesh=mesh,
        in_specs=(P(AXIS, None, None), P(AXIS), P(AXIS, None, None),
                  P(AXIS, None)),
        out_specs=(P(AXIS, None, None), P(AXIS), P(AXIS, None)),
    )(buf, n, rows, scal)


_append_step = jax.jit(_append_impl, static_argnames=("mesh",),
                       donate_argnums=(0, 1))


@functools.partial(jax.jit, static_argnames=("mp",))
def _buf_prefix(buf, *, mp: int):
    return buf[:, :mp]


class DevicePostings:
    """Persistent ``[n_dev, cap, width]`` uint32 append buffer over the
    mesh.  ``append`` scatters one wave's rows (synchronously checked —
    the wave walk already blocks on its scalars each wave, so the tiny
    flags pull costs nothing extra); ``drain`` pulls the occupied prefix
    and hands each device's rows to the caller, then resets.

    ``stats``, if given, receives ``appends``, ``append_overflows``,
    ``sync_pulls``, ``append_s``, ``drain_s``.
    """

    def __init__(self, mesh: Mesh, *, width: int, cap: int,
                 stats: Optional[dict] = None):
        self.mesh = mesh
        self.n_dev = int(mesh.devices.size)
        self.width = int(width)
        self.cap = 1 << max(0, int(cap) - 1).bit_length()
        self.stats = stats if stats is not None else {}
        for key in ("appends", "append_overflows", "sync_pulls"):
            self.stats.setdefault(key, 0)
        for key in ("append_s", "drain_s"):
            self.stats.setdefault(key, 0.0)
        sh3 = NamedSharding(mesh, P(AXIS, None, None))
        sh1 = NamedSharding(mesh, P(AXIS))
        self._buf = jax.device_put(
            np.zeros((self.n_dev, self.cap, self.width), np.uint32), sh3)
        self._n = jax.device_put(np.zeros((self.n_dev,), np.int32), sh1)
        self._nrows = np.zeros(self.n_dev, dtype=np.int64)

    def append(self, rows_dev, scal_dev) -> bool:
        """Append one wave's valid rows.  Returns False when the buffer
        was full (a global no-op): the caller drains and retries — which
        always succeeds, because ``cap`` >= one wave's row count."""
        t0 = time.perf_counter()
        self._buf, self._n, flags = _append_step(
            self._buf, self._n, rows_dev, scal_dev, mesh=self.mesh)
        flags_np = np.asarray(flags)
        self._nrows = flags_np[:, 1].astype(np.int64)
        overflowed = bool(flags_np[:, 0].any())
        if overflowed:
            self.stats["append_overflows"] += 1
        else:
            self.stats["appends"] += 1
        self.stats["append_s"] += time.perf_counter() - t0
        return not overflowed

    @property
    def pending_rows(self) -> int:
        return int(self._nrows.sum())

    def drain(self) -> List[np.ndarray]:
        """Pull every device's occupied rows (ONE sliced transfer for
        the whole buffer) and reset the buffer.  Returns one
        ``[n_d, width]`` uint32 array per device — the caller applies
        its own filters (padding docs, partition slices) before
        accumulating, exactly as it did on the per-wave pull path."""
        t0 = time.perf_counter()
        out: List[np.ndarray] = []
        m = int(self._nrows.max())
        if m == 0:
            self.stats["drain_s"] += time.perf_counter() - t0
            return [np.zeros((0, self.width), np.uint32)] * self.n_dev
        mp = occupied_prefix(m, self.cap)
        pulled = np.asarray(_buf_prefix(self._buf, mp=mp))
        for d in range(self.n_dev):
            out.append(pulled[d, :int(self._nrows[d])])
        self.stats["sync_pulls"] += 1
        # Reset is host-side bookkeeping only: rows beyond the write
        # offset are never read, so the buffer bytes can stay stale.
        sh1 = NamedSharding(self.mesh, P(AXIS))
        self._n = jax.device_put(np.zeros((self.n_dev,), np.int32), sh1)
        self._nrows[:] = 0
        self.stats["drain_s"] += time.perf_counter() - t0
        return out
