"""Append-only device-resident postings buffer for the TF-IDF wave walk.

TF-IDF's per-wave output is postings — (word, len, tf, doc, part) rows
that accumulate rather than merge — so the word-count ``DeviceTable``'s
sort+segment-sum fold is the wrong program.  What the wave walk shares
with the stream is the COST SHAPE: one D2H pull per wave, each charged
the tunnel's fixed per-transfer latency regardless of size
(ROADMAP item 2).  This buffer batches those pulls: waves append their
valid rows into a persistent on-device buffer with a compiled scatter
(same dump-row idiom as ``shuffle.shuffle_rows``), and the host pulls
once per K waves (``device/policy.py`` cadence) or when the buffer
fills.

Append flags are confirmed ``lag`` appends late (the wave walk passes
its pipeline depth, ``parallel/pipeline.py``): blocking on an append's
tiny flags pull the moment it is dispatched would wait out every wave
kernel queued behind it on the in-order device stream — the
serialization the pipeline window exists to avoid.  Late detection is
safe because overflow is ORDER-PRESERVING: an append that overflows is
a global no-op that also sets a sticky ``dirty`` bit in device state,
so every LATER append no-ops too until the host drains — recovery
drains the committed prefix (strictly the waves before the first
overflow), resets, and re-appends the orphaned waves oldest-first.
Wave order in the per-device row streams is therefore an invariant,
which is what keeps the accumulated postings (``merge.PostingsTable``
preserves insertion order within a word) bit-identical to the per-wave
pull path.

Unlike the merge table the capacity has no standing *ladder*: a drain
empties the buffer, so overflow is normally just an early sync.  The
one exception — a single wave with more valid rows than the whole
buffer (a forced-tiny ``DSI_DEVICE_POSTINGS_CAP``, or a mid-walk
capacity-rung widening) — reallocates the empty buffer at the wave's
row count instead of failing: overflow is an early sync or a widen,
never a loss.
"""

from __future__ import annotations

import collections
import functools
from typing import Callable, Deque, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dsi_tpu.obs import span as _span
from dsi_tpu.ops.meshroute import compact_received, exchange_rows, route_dest
from dsi_tpu.ops.wordcount import _PAD_KEY
from dsi_tpu.parallel.shuffle import AXIS, occupied_prefix
from dsi_tpu.utils.jaxcompat import shard_map


def _append_device(buf, n, dirty, rows, scal, *, cap: int, width: int):
    """Per-device body: scatter this wave's valid rows at the write
    offset.  Rows beyond the wave's valid count and rows past the
    capacity land on the dump row / out of bounds (dropped — identical
    either way because a no-op'd append keeps the OLD buffer).  The
    ``dirty`` bit is the sticky overflow shadow: once any append
    no-ops, every later append no-ops too, so the committed buffer is
    always an order-exact prefix of the appended waves."""
    buf = buf.reshape(cap, width)
    n0 = n.reshape(())
    d0 = dirty.reshape(())
    r = rows.shape[-2]
    rows = rows.reshape(r, width)
    nr = scal.reshape(-1)[0]

    valid = jnp.arange(r, dtype=jnp.int32) < nr
    idx = jnp.where(valid, n0 + jnp.arange(r, dtype=jnp.int32), cap)
    target = jnp.concatenate([buf, jnp.zeros((1, width), jnp.uint32)], axis=0)
    new_buf = target.at[idx].set(rows)[:cap]
    new_n = n0 + nr
    ov = lax.pmax((new_n > cap).astype(jnp.int32), AXIS)
    # Commit is all-or-nothing across devices (pmax) AND across waves
    # (sticky dirty): a mixed commit would break either the exactly-once
    # guarantee or the wave order of the per-device row streams.
    no_op = jnp.maximum(ov, d0)
    keep_old = no_op > 0
    out_buf = jnp.where(keep_old, buf, new_buf)
    out_n = jnp.where(keep_old, n0, new_n)
    flags = jnp.stack([no_op, out_n])
    return out_buf[None], out_n[None], no_op[None], flags[None]


def _append_impl(buf, n, dirty, rows, scal, *, mesh: Mesh):
    cap, width = buf.shape[1], buf.shape[2]
    body = functools.partial(_append_device, cap=cap, width=width)
    return shard_map(
        body, mesh=mesh,
        in_specs=(P(AXIS, None, None), P(AXIS), P(AXIS),
                  P(AXIS, None, None), P(AXIS, None)),
        out_specs=(P(AXIS, None, None), P(AXIS), P(AXIS), P(AXIS, None)),
    )(buf, n, dirty, rows, scal)


_append_step = jax.jit(_append_impl, static_argnames=("mesh",),
                       donate_argnums=(0, 1, 2))


def _mesh_append_device(buf, n, dirty, rows, scal, *, cap: int, width: int,
                        kk: int, n_dev: int, n_shards: int):
    """Mesh-sharded append body: the wave's rows are RE-ROUTED to their
    owning shard (``ihash(word) % n_shards``, ``ops/meshroute.py``)
    before the scatter, so a word's postings always buffer on one shard
    regardless of how ``n_reduce % n_dev`` placed them.  Per-word order
    survives: a word's rows arrive from exactly one source device (the
    step's shuffle already grouped them) and the exchange concatenates
    source blocks in device order.  Overflow stays GLOBAL (pmax +
    sticky dirty) — a postings overflow is an early sync, not a
    capacity ladder, so the per-shard machinery buys nothing here."""
    buf = buf.reshape(cap, width)
    n0 = n.reshape(())
    d0 = dirty.reshape(())
    r = rows.shape[-2]
    rows = rows.reshape(r, width)
    nr = scal.reshape(-1)[0]

    valid = jnp.arange(r, dtype=jnp.int32) < nr
    keys = jnp.where(valid[:, None], rows[:, :kk], jnp.uint32(_PAD_KEY))
    lens = jnp.where(valid, rows[:, kk].astype(jnp.int32), 0)
    dest = route_dest(keys, lens, valid, n_shards=n_shards, park=n_dev)
    recv = exchange_rows(rows, dest, n_dev=n_dev, kk=kk)
    crows, n_recv = compact_received(recv)

    idx = jnp.where(jnp.arange(n_dev * r, dtype=jnp.int32) < n_recv,
                    n0 + jnp.arange(n_dev * r, dtype=jnp.int32), cap)
    target = jnp.concatenate([buf, jnp.zeros((1, width), jnp.uint32)],
                             axis=0)
    new_buf = target.at[idx].set(crows)[:cap]
    new_n = n0 + n_recv
    ov = lax.pmax((new_n > cap).astype(jnp.int32), AXIS)
    no_op = jnp.maximum(ov, d0)
    keep_old = no_op > 0
    out_buf = jnp.where(keep_old, buf, new_buf)
    out_n = jnp.where(keep_old, n0, new_n)
    flags = jnp.stack([no_op, out_n])
    return out_buf[None], out_n[None], no_op[None], flags[None]


def _mesh_append_impl(buf, n, dirty, rows, scal, *, mesh: Mesh, kk: int,
                      n_shards: int):
    cap, width = buf.shape[1], buf.shape[2]
    body = functools.partial(_mesh_append_device, cap=cap, width=width,
                             kk=kk, n_dev=int(mesh.devices.size),
                             n_shards=n_shards)
    return shard_map(
        body, mesh=mesh,
        in_specs=(P(AXIS, None, None), P(AXIS), P(AXIS),
                  P(AXIS, None, None), P(AXIS, None)),
        out_specs=(P(AXIS, None, None), P(AXIS), P(AXIS), P(AXIS, None)),
    )(buf, n, dirty, rows, scal)


_mesh_append_step = jax.jit(_mesh_append_impl,
                            static_argnames=("mesh", "kk", "n_shards"),
                            donate_argnums=(0, 1, 2))


# Fresh-buffer prefix slice shared with the table service (one jitted
# program for both consumers).
from dsi_tpu.device.table import _rows_prefix as _buf_prefix  # noqa: E402


class DevicePostings:
    """Persistent ``[n_dev, cap, width]`` uint32 append buffer over the
    mesh.  ``append`` scatters one wave's rows asynchronously; its flags
    are confirmed ``lag`` appends late.  Drains hand each device's
    occupied rows to ``sink`` (one callback per device, wave order
    preserved) — triggered by ``sync`` (the K-wave cadence), ``close``
    (end of walk), or overflow recovery.

    ``stats``, if given, receives ``appends``, ``append_overflows``,
    ``sync_pulls``, ``postings_widens``, ``append_s``, ``drain_s``.

    ``mesh_shards`` > 0 re-routes every appended row to shard
    ``ihash(word) % n_shards`` inside the compiled append (the
    shuffle-fold treatment; ``kk`` names the key-lane count, default
    ``width - 4`` — the (keys, len, payload...) row layout both wave
    walks use).  Buffered postings then shard by KEY rather than by the
    step's partition placement; the drain contract and the sticky
    global overflow protocol are unchanged.
    """

    def __init__(self, mesh: Mesh, *, width: int, cap: int,
                 sink: Callable[[np.ndarray], None],
                 lag: int = 0, stats: Optional[dict] = None,
                 mesh_shards: int = 0, kk: Optional[int] = None):
        self.mesh = mesh
        self.n_dev = int(mesh.devices.size)
        self.width = int(width)
        self.cap = 1 << max(0, int(cap) - 1).bit_length()
        self.sink = sink
        self.lag = max(0, int(lag))
        self.mesh_shards = max(0, int(mesh_shards))
        self.kk = int(kk) if kk is not None else self.width - 4
        if self.mesh_shards > self.n_dev:
            raise ValueError(
                f"mesh_shards={self.mesh_shards} exceeds the mesh size "
                f"({self.n_dev} devices)")
        self.stats = stats if stats is not None else {}
        for key in ("appends", "append_overflows", "sync_pulls",
                    "postings_widens", "pull_bytes"):
            self.stats.setdefault(key, 0)
        for key in ("append_s", "drain_s"):
            self.stats.setdefault(key, 0.0)
        self._alloc(self.cap)
        self._nrows = np.zeros(self.n_dev, dtype=np.int64)
        # (flags, rows_dev, scal_dev) per unconfirmed append — the wave
        # tensors stay referenced until their append is proven committed,
        # so a no-op'd append can be replayed after the drain.
        self._pending: Deque[Tuple] = collections.deque()
        # Delta-checkpoint log (enable_delta): wave payloads appended
        # since the last capture — wave tensors are never donated, so
        # retaining the handles is safe (same discipline as
        # ``DeviceTable``'s step log).
        self._delta_log: list = []
        self._delta_max = 0
        self._delta_invalid = False

    def _alloc(self, cap: int) -> None:
        sh3 = NamedSharding(self.mesh, P(AXIS, None, None))
        sh1 = NamedSharding(self.mesh, P(AXIS))
        self._buf = jax.device_put(
            np.zeros((self.n_dev, cap, self.width), np.uint32), sh3)
        self._n = jax.device_put(np.zeros((self.n_dev,), np.int32), sh1)
        self._dirty = jax.device_put(np.zeros((self.n_dev,), np.int32), sh1)

    # ── the append path ──

    def _dispatch(self, rows_dev, scal_dev):
        if self.mesh_shards:
            self._buf, self._n, self._dirty, flags = _mesh_append_step(
                self._buf, self._n, self._dirty, rows_dev, scal_dev,
                mesh=self.mesh, kk=self.kk, n_shards=self.mesh_shards)
        else:
            self._buf, self._n, self._dirty, flags = _append_step(
                self._buf, self._n, self._dirty, rows_dev, scal_dev,
                mesh=self.mesh)
        return flags

    def append(self, rows_dev, scal_dev, nvalid=None) -> None:
        """Append one wave's valid rows (async) and lazily confirm
        appends older than ``lag``.  ``rows_dev`` is the wave's sorted
        received-row tensor ``[n_dev, r, width]``; ``scal_dev`` the
        per-device scalar block whose column 0 is the valid row count
        (already host-confirmed exact by the caller).  ``nvalid`` is
        that column as host ints — required only when the delta log is
        armed (it is the trim vector an incremental save ships with the
        wave's rows)."""
        if self._delta_max and not self._delta_invalid:
            # An already-invalid window retains nothing — take_delta
            # would discard it anyway; don't pin dead HBM.
            if nvalid is None or len(self._delta_log) >= self._delta_max:
                self._delta_invalid = True
                self._delta_log.clear()
            else:
                self._delta_log.append(
                    (rows_dev, np.asarray(nvalid, np.int64).copy()))
        with _span("append", lane="fold", stats=self.stats,
                   key="append_s"):
            flags = self._dispatch(rows_dev, scal_dev)
            self._pending.append((flags, rows_dev, scal_dev))
            while len(self._pending) > self.lag:
                self._confirm_oldest()

    def _confirm_oldest(self) -> None:
        flags, rows_dev, scal_dev = self._pending.popleft()
        flags_np = np.asarray(flags)  # blocks until this append lands
        if flags_np[:, 0].any():
            self.stats["append_overflows"] += 1
            self._recover([(rows_dev, scal_dev)])
        else:
            self._nrows = flags_np[:, 1].astype(np.int64)
            self.stats["appends"] += 1

    def _flush_pending(self) -> list:
        """Confirm every outstanding append; return the (rows, scal)
        pairs that no-op'd, oldest first."""
        orphans = []
        while self._pending:
            flags, rows_dev, scal_dev = self._pending.popleft()
            flags_np = np.asarray(flags)
            if flags_np[:, 0].any():
                self.stats["append_overflows"] += 1
                orphans.append((rows_dev, scal_dev))
            else:
                self._nrows = flags_np[:, 1].astype(np.int64)
                self.stats["appends"] += 1
        return orphans

    def _recover(self, orphans: list) -> None:
        """An append no-op'd.  Every append dispatched after it no-op'd
        too (the sticky dirty bit), so flushing collects the orphans in
        dispatch order: drain the committed prefix, then re-append the
        orphans oldest-first — wave order in the sink is preserved by
        construction."""
        orphans = orphans + self._flush_pending()
        self._drain()
        for rows_dev, scal_dev in orphans:
            flags_np = np.asarray(self._dispatch(rows_dev, scal_dev))
            if flags_np[:, 0].any():
                # Cumulative overflow mid-recovery (earlier orphans
                # refilled the buffer): drain what fit — in order — and
                # retry into the empty buffer at the CURRENT cap first.
                self._drain()
                flags_np = np.asarray(self._dispatch(rows_dev, scal_dev))
            if flags_np[:, 0].any():
                # Only now is this provably a lone wave larger than the
                # whole empty buffer (forced-tiny cap, or a capacity-rung
                # widening mid-walk): grow the buffer to hold it —
                # overflow widens, it never drops.  _alloc resets the
                # sticky dirty bit along with the rest of the state.
                # Mesh routing can deliver every device's rows of one
                # wave to a single shard, so its bound is n_dev * rows.
                wave_rows = int(rows_dev.shape[-2]) * (
                    self.n_dev if self.mesh_shards else 1)
                new_cap = max(4 * self.cap, wave_rows)
                self.cap = 1 << max(0, new_cap - 1).bit_length()
                self._alloc(self.cap)
                self._nrows[:] = 0
                self.stats["postings_widens"] += 1
                flags_np = np.asarray(self._dispatch(rows_dev, scal_dev))
                if flags_np[:, 0].any():  # cap >= rows: cannot happen
                    raise RuntimeError(
                        "device postings buffer smaller than one wave"
                        f" (cap={self.cap})")
            self._nrows = flags_np[:, 1].astype(np.int64)
            self.stats["appends"] += 1

    @property
    def pending_rows(self) -> int:
        return int(self._nrows.sum())

    # ── checkpoint image (dsi_tpu/ckpt) ──

    def checkpoint_capture(self):
        """Drain-free snapshot, capture half: flush the lagged append
        flags (an overflow recovery drains into the sink, so callers
        capture this buffer BEFORE the host table), then DISPATCH the
        committed-prefix slice (a fresh buffer — later appends donate
        the live buffer, never this) and kick its D2H; ``materialize``
        in the commit writer finds the transfer draining.  After the
        flush the sticky dirty bit is provably clear — a dirty buffer
        is resolved by recovery before this returns — so the image
        needs only rows + counts."""
        from dsi_tpu.ckpt.delta import Deferred

        orphans = self._flush_pending()
        if orphans:
            self._recover(orphans)
        n_dev, width, cap = self.n_dev, self.width, self.cap
        nrows = self._nrows.copy()
        m = int(nrows.max())
        if m:
            buf_dev = _buf_prefix(self._buf, mp=occupied_prefix(m, cap))
            from dsi_tpu.device.table import _copy_to_host_async

            _copy_to_host_async(buf_dev)
        else:
            buf_dev = None

        def _image() -> dict:
            buf = (np.asarray(buf_dev) if buf_dev is not None
                   else np.zeros((n_dev, 0, width), dtype=np.uint32))
            return {"buf": buf, "nrows": nrows.copy(),
                    "cap": np.array(cap, dtype=np.int64)}

        return Deferred(_image)

    def checkpoint_state(self) -> dict:
        """The synchronous spelling: capture + immediate materialize."""
        return self.checkpoint_capture().materialize()

    # ── incremental (delta) checkpoints ──

    def enable_delta(self, max_steps: int = 64) -> None:
        """Arm the delta log (``DeviceTable.enable_delta`` contract):
        every appended wave retains its payload handle until the next
        ``take_delta``; a window past ``max_steps`` falls back to a
        full save."""
        self._delta_max = max(1, int(max_steps))
        self._delta_log.clear()
        self._delta_invalid = False

    def take_delta(self):
        """The waves appended since the last capture, as ordered
        ``(sliced_rows_handle, nvalid)`` entries with their D2H kicked —
        or None when the window cannot be a delta (log overflow, or an
        append without ``nvalid``); always re-arms the log."""
        from dsi_tpu.device.table import _copy_to_host_async

        if self._delta_invalid:
            self._delta_invalid = False
            self._delta_log.clear()
            return None
        entries = []
        for rows_dev, nus in self._delta_log:
            mp = occupied_prefix(max(1, int(nus.max())),
                                 int(rows_dev.shape[1]))
            sliced = _buf_prefix(rows_dev, mp=mp)
            _copy_to_host_async(sliced)
            entries.append((sliced, nus))
        self._delta_log.clear()
        return entries

    @staticmethod
    def drain_image(sink, img: dict) -> None:
        """Feed a :meth:`checkpoint_state` image's committed rows to
        ``sink`` (one ``[n, width]`` block per device, device order)
        WITHOUT re-uploading it — the resume path when the checkpoint's
        sharding degree differs from the live buffer's (``mesh_shards``
        in the manifest): the rows re-enter through the host table and
        the buffer starts empty at the new routing.  Device order is
        per-word order for rows that predate every resumed wave, so the
        append-order invariant survives re-routing."""
        buf = np.asarray(img["buf"])
        nrows = np.asarray(img["nrows"])
        for d in range(buf.shape[0]):
            n = int(nrows[d])
            if n:
                sink(buf[d, :n])

    def restore_state(self, img: dict) -> None:
        """Re-upload a :meth:`checkpoint_state` image (resume):
        reallocate at the image's capacity (a pre-crash widen sticks),
        scatter the committed prefix back, clear the dirty bit."""
        self.cap = int(img["cap"])
        buf = np.asarray(img["buf"], dtype=np.uint32)
        full = np.zeros((self.n_dev, self.cap, self.width), dtype=np.uint32)
        if buf.shape[1]:
            full[:, :buf.shape[1]] = buf
        sh3 = NamedSharding(self.mesh, P(AXIS, None, None))
        sh1 = NamedSharding(self.mesh, P(AXIS))
        nrows = np.asarray(img["nrows"], dtype=np.int64)
        self._buf = jax.device_put(full, sh3)
        self._n = jax.device_put(nrows.astype(np.int32), sh1)
        self._dirty = jax.device_put(np.zeros(self.n_dev, np.int32), sh1)
        self._nrows = nrows.copy()
        self._pending.clear()

    # ── drains ──

    def _drain(self) -> None:
        """Pull every device's committed rows (ONE sliced transfer for
        the whole buffer), hand them to the sink, reset.  The reset
        re-uploads only the two tiny per-device scalars; buffer bytes
        beyond the write offset are never read and can stay stale."""
        with _span("drain", lane="sync", stats=self.stats,
                   key="drain_s"):
            m = int(self._nrows.max())
            if m:
                mp = occupied_prefix(m, self.cap)
                pulled = np.asarray(_buf_prefix(self._buf, mp=mp))
                self.stats["pull_bytes"] += pulled.nbytes
                for d in range(self.n_dev):
                    nr = int(self._nrows[d])
                    if nr:
                        self.sink(pulled[d, :nr])
                self.stats["sync_pulls"] += 1
            sh1 = NamedSharding(self.mesh, P(AXIS))
            self._n = jax.device_put(np.zeros((self.n_dev,), np.int32),
                                     sh1)
            self._dirty = jax.device_put(
                np.zeros((self.n_dev,), np.int32), sh1)
            self._nrows[:] = 0

    def sync(self) -> None:
        """The K-wave host pull: flush the append lag (recovering any
        late-detected overflow), then drain to the sink."""
        orphans = self._flush_pending()
        if orphans:
            self._recover(orphans)
        self._drain()

    def close(self) -> None:
        """End-of-walk drain; the buffer is dropped with the service."""
        self.sync()
        self._buf = None
