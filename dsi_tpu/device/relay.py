"""Device-resident stage relay: the plan layer's inter-stage byte buffer.

A multi-stage plan (``dsi_tpu/plan``) chains engines so that stage N+1's
upload IS stage N's device-resident output.  The unit of that handoff is
a byte stream in the engines' native batch layout — ``[n_dev, cap]``
uint8 rows, zero-padded past the fill point — and this module owns the
two relay flavors the plan driver chooses between:

* :class:`DeviceRelay` — the chained path.  A producing stage appends
  each confirmed step's compacted output (e.g. the grep emit kernel's
  matching-line bytes) WITHOUT pulling it: a compiled per-row pack
  program concatenates the new bytes after the current fill point of a
  device-resident accumulation buffer, sealing a buffer when the next
  append would overflow it and starting the next one from the appended
  chunk itself.  The consuming stage iterates :meth:`batches` and feeds
  the buffers straight into its step program — zero intermediate bytes
  cross the host (``plan_intermediate_bytes`` stays 0) unless a spill
  budget forces the oldest sealed buffers out (the spill-compacted
  fallback for intermediates wider than HBM).
* :class:`HostRelay` — the staged baseline.  Every append pulls the
  compacted bytes to the host (the full host round-trip the plan layer
  exists to remove), and the consumer reads a plain block stream.  Same
  byte content as the device path by construction, which is what makes
  the two modes bit-comparable end to end.

Byte-stream contract (what makes the handoff chunking-safe): producers
append whole newline-terminated lines per device row, so every relay row
boundary falls on a line boundary and the zero tail of a buffer row
terminates any final token — a downstream word-count over the relay sees
exactly the same token multiset as the staged baseline's contiguous
stream, whatever the buffer chunking.

Durability: :meth:`DeviceRelay.capture` pulls a NON-destructive image of
every live buffer (the stage-commit payload — device copies stay
resident for the downstream stage), and :meth:`DeviceRelay.restore`
rebuilds a relay from that image in host mode, which is how a crashed
chain resumes from the last completed stage's commit instead of from
zero.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dsi_tpu.parallel.shuffle import AXIS

#: jax.jit donate_argnums for the pack program: both the accumulation
#: buffer (rebound to the program's output) and the appended chunk are
#: consumed by the concatenation.
_RELAY_DONATE = (0, 2)


def _pack_impl(acc, off, new):
    """Per-row concatenation at a dynamic offset: ``out[r, i] = acc[r, i]``
    for ``i < off[r]`` else ``new[r, i - off[r]]``.  Pure elementwise +
    per-row gather, so a ``[AXIS, None]``-sharded call stays shard-local
    (no collectives — each device packs its own row)."""
    n = acc.shape[1]
    idx = jnp.arange(n, dtype=jnp.int32)[None, :]
    offc = off[:, None].astype(jnp.int32)
    shifted = jnp.take_along_axis(new, jnp.clip(idx - offc, 0, n - 1),
                                  axis=1)
    return jnp.where(idx < offc, acc, shifted)


_pack_jit = jax.jit(_pack_impl, donate_argnums=_RELAY_DONATE)


def _relay_pack_program(*, n_dev: int, cap: int):
    """(name, fn) for one compiled relay pack shape — the shared
    definition discipline (``streaming._step_program``)."""

    def fn(acc, off, new):
        return _pack_impl(acc, off, new)

    return f"plan_pack_d{n_dev}_c{cap}", fn


def _relay_structs(n_dev: int, cap: int):
    sds = jax.ShapeDtypeStruct
    return (sds((n_dev, cap), jnp.uint8), sds((n_dev,), jnp.int32),
            sds((n_dev, cap), jnp.uint8))


def _pack_fn(aot: bool, *, n_dev: int, cap: int):
    if not aot:
        return _pack_jit
    from dsi_tpu.backends import aotcache
    from dsi_tpu.device.table import _quiet_unusable_donation

    name, fn = _relay_pack_program(n_dev=n_dev, cap=cap)
    with _quiet_unusable_donation():
        return aotcache.cached_compile(name, fn, _relay_structs(n_dev, cap),
                                       donate_argnums=_RELAY_DONATE)


class DeviceRelay:
    """Device-resident inter-stage byte buffer (module docstring).

    ``stats`` is the plan run's metrics scope: ``plan_intermediate_bytes``
    counts bytes that crossed the host on the HANDOFF path (0 here unless
    spilled), ``plan_relay_buffers`` the sealed-buffer count, and
    ``plan_spilled_bytes`` the spill volume.  ``spill_bytes`` bounds
    device residency: when the relay's buffer bytes exceed it, the oldest
    sealed buffers are pulled to the host (counted) until back under.
    """

    def __init__(self, mesh: Mesh, *, cap: int, aot: bool = False,
                 stats: Optional[dict] = None, spill_bytes: int = 0):
        self.mesh = mesh
        self.n_dev = int(mesh.devices.size)
        self.cap = int(cap)
        self.aot = bool(aot)
        self.stats = stats if stats is not None else {}
        self.stats.setdefault("plan_intermediate_bytes", 0)
        self.stats.setdefault("plan_handoff_bytes", 0)
        self.stats.setdefault("plan_relay_buffers", 0)
        self.stats.setdefault("plan_spilled_bytes", 0)
        self.spill_bytes = max(0, int(spill_bytes))
        self._sh = NamedSharding(mesh, P(AXIS, None))
        self._sh1 = NamedSharding(mesh, P(AXIS))
        #: Sealed buffers in append order: jax.Array (device-resident)
        #: or np.ndarray (spilled / restored), each with its fill lens.
        self._sealed: List = []
        self._sealed_lens: List[np.ndarray] = []
        self._acc = None
        self._lens = np.zeros(self.n_dev, dtype=np.int64)
        #: Total content bytes appended (the logical intermediate size).
        self.total_bytes = 0

    # ── producer side ──

    def append(self, comp_dev, kept: np.ndarray) -> None:
        """Append one confirmed step's compacted ``[n_dev, cap]`` output
        (fill ``kept[r]`` bytes per row, zero tail).  ``comp_dev`` is
        consumed (donated to the pack program or adopted as the next
        accumulation buffer) — the producer must not reuse it."""
        kept = np.asarray(kept, dtype=np.int64)
        if int(kept.sum()) == 0:
            return
        self.total_bytes += int(kept.sum())
        self.stats["plan_handoff_bytes"] += int(kept.sum())
        if self._acc is None:
            self._acc = comp_dev
            self._lens = kept.copy()
        elif bool(((self._lens + kept) > self.cap).any()):
            self._seal()
            self._acc = comp_dev
            self._lens = kept.copy()
        else:
            off = jax.device_put(self._lens.astype(np.int32), self._sh1)
            fn = _pack_fn(self.aot, n_dev=self.n_dev, cap=self.cap)
            self._acc = fn(self._acc, off, comp_dev)
            self._lens += kept
        self._maybe_spill()

    def _seal(self) -> None:
        self._sealed.append(self._acc)
        self._sealed_lens.append(self._lens.copy())
        self._acc = None
        self.stats["plan_relay_buffers"] += 1

    def _maybe_spill(self) -> None:
        if not self.spill_bytes:
            return
        buf_bytes = self.n_dev * self.cap

        def resident() -> int:
            live = sum(1 for b in self._sealed
                       if not isinstance(b, np.ndarray))
            return (live + (1 if self._acc is not None else 0)) * buf_bytes

        i = 0
        while resident() > self.spill_bytes and i < len(self._sealed):
            if not isinstance(self._sealed[i], np.ndarray):
                host = np.asarray(self._sealed[i])
                content = int(self._sealed_lens[i].sum())
                self._sealed[i] = host
                self.stats["plan_spilled_bytes"] += content
                self.stats["plan_intermediate_bytes"] += content
            i += 1

    # ── consumer side ──

    def batches(self) -> Iterator:
        """Yield every buffer (sealed first, then the open tail) in
        append order, dropping the relay's own reference as each is
        handed over — the downstream stage owns (and may donate) it.
        Host-resident buffers (spills, restores) yield as np.ndarray;
        the consumer's upload of those is the counted fallback path."""
        if self._acc is not None:
            self._seal()
        while self._sealed:
            yield self._sealed.pop(0)
            self._sealed_lens.pop(0)

    def take_sealed(self) -> List:
        """Pop the currently SEALED buffers (append order) WITHOUT
        sealing the open accumulation buffer — the pipelined driver's
        seal-driven handoff: the consumer takes these while the
        producer keeps appending into the open tail.  Call
        :meth:`finish` then take once more when the producer is done."""
        out: List = []
        while self._sealed:
            out.append(self._sealed.pop(0))
            self._sealed_lens.pop(0)
        return out

    def finish(self) -> None:
        """Seal the open tail: the producer has appended its last byte,
        so the final partial buffer becomes consumable."""
        if self._acc is not None:
            self._seal()

    def host_blocks(self) -> Iterator[bytes]:
        """Destructively materialize every buffer as per-row byte
        blocks — the counted host-fallback consumption path for a
        downstream engine with no device-batch input mode (the
        grep→grep cascade).  Rows hold whole newline-terminated lines,
        so the blocks are a valid line stream in any order; the pull
        is charged to ``plan_intermediate_bytes`` like any other
        host-crossing handoff."""
        if self._acc is not None:
            self._seal()
        while self._sealed:
            buf = self._sealed.pop(0)
            lens = self._sealed_lens.pop(0)
            host = np.asarray(buf)
            self.stats["plan_intermediate_bytes"] += int(lens.sum())
            for r in range(host.shape[0]):
                k = int(lens[r])
                if k:
                    yield host[r, :k].tobytes()

    # ── durability (the stage-commit payload) ──

    def capture(self) -> Dict[str, np.ndarray]:
        """NON-destructive host image of every live buffer: the stage
        commit's payload.  Device copies stay resident — the downstream
        stage still consumes them directly; these pulls are durability
        cost (``plan_commit_bytes``), not handoff bytes."""
        arrays: Dict[str, np.ndarray] = {}
        bufs = list(self._sealed) + (
            [self._acc] if self._acc is not None else [])
        lens = list(self._sealed_lens) + (
            [self._lens] if self._acc is not None else [])
        for i, (b, ln) in enumerate(zip(bufs, lens)):
            arrays[f"rbuf{i}"] = np.asarray(b)
            arrays[f"rlen{i}"] = np.asarray(ln, dtype=np.int64)
        arrays["rcount"] = np.array([len(bufs)], dtype=np.int64)
        return arrays

    @classmethod
    def restore(cls, mesh: Mesh, arrays: Dict[str, np.ndarray], *,
                cap: int, stats: Optional[dict] = None) -> "DeviceRelay":
        """Rebuild a relay from a :meth:`capture` image, host-resident
        (the consumer re-uploads — the resume path's restaging cost,
        counted under ``plan_restored_bytes``)."""
        relay = cls(mesh, cap=cap, stats=stats)
        relay.stats.setdefault("plan_restored_bytes", 0)
        n = int(arrays.get("rcount", np.zeros(1))[0])
        for i in range(n):
            relay._sealed.append(np.asarray(arrays[f"rbuf{i}"],
                                            dtype=np.uint8))
            ln = np.asarray(arrays[f"rlen{i}"], dtype=np.int64)
            relay._sealed_lens.append(ln)
            relay.total_bytes += int(ln.sum())
            relay.stats["plan_restored_bytes"] += int(ln.sum())
        relay.stats["plan_relay_buffers"] += n
        return relay


class HostRelay:
    """The staged-baseline handoff: every append pulls the compacted
    bytes to the host; the consumer reads one contiguous block stream —
    the full host round-trip between stages, byte-identical content to
    :class:`DeviceRelay`'s by construction."""

    def __init__(self, stats: Optional[dict] = None):
        self.stats = stats if stats is not None else {}
        self.stats.setdefault("plan_intermediate_bytes", 0)
        self.stats.setdefault("plan_handoff_bytes", 0)
        self._chunks: List[bytes] = []
        self.total_bytes = 0

    def append(self, comp_dev, kept: np.ndarray) -> None:
        comp_np = np.asarray(comp_dev)
        kept = np.asarray(kept, dtype=np.int64)
        for r in range(comp_np.shape[0]):
            k = int(kept[r])
            if k:
                self._chunks.append(comp_np[r, :k].tobytes())
        content = int(kept.sum())
        self.total_bytes += content
        self.stats["plan_handoff_bytes"] += content
        self.stats["plan_intermediate_bytes"] += content

    def blocks(self) -> Iterator[bytes]:
        yield from self._chunks

    def capture(self) -> Dict[str, np.ndarray]:
        """Stage-commit payload: the materialized stream as one array."""
        joined = b"".join(self._chunks)
        return {"hbytes": np.frombuffer(joined, dtype=np.uint8).copy()}

    @classmethod
    def restore(cls, arrays: Dict[str, np.ndarray],
                stats: Optional[dict] = None) -> "HostRelay":
        relay = cls(stats=stats)
        raw = np.asarray(arrays.get("hbytes", np.zeros(0, np.uint8)),
                         dtype=np.uint8).tobytes()
        if raw:
            relay._chunks.append(raw)
            relay.total_bytes = len(raw)
        return relay
