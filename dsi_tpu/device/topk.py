"""On-device top-k / histogram service for grep & indexer workloads.

The streaming grep and indexer engines (``parallel/grepstream.py``)
produce per-step *statistics* — per-line match-occurrence counts, and
per-word posting (document-frequency) increments — whose host merge is
tiny but whose per-step D2H pull carries the tunnel's fixed transfer
latency every single step, exactly the cost shape ``DeviceTable`` solved
for the word-count stream.  This module grows the ROADMAP's named next
consumer on the same fold machinery:

* :class:`DeviceTopK` — a persistent donated (key, count) table with one
  compiled merge program per confirmed step, built directly ON
  :class:`~dsi_tpu.device.table.DeviceTable`: folds lag the engines'
  deferred-exactness window (``lag`` = pipeline depth), a fold whose
  merged uniques overflow the capacity rung is a global no-op recovered
  by the drain→realloc×4→re-fold orphan protocol, and counts are uint64
  (cross-step sums outlive uint32 long before a stream ends).  What the
  subclass changes is the SYNC shape: instead of drain+clear, a sync
  pulls a compiled count-sorted **top-k snapshot** — ``k`` rows over the
  wire, not capacity — leaving the table resident so the final
  ``close()`` drain (into the host accumulator) stays exact.  The engine
  therefore reports the current leaders every K folds for the price of
  k rows, and host *data* pulls drop from one-per-step to
  ``widens + 1`` (the close), with ``ceil(folds/K)`` snapshot pulls on
  top — the amortization ``step_pulls`` vs ``sync_pulls``/``widens``/
  ``topk_snapshots`` makes visible.
* :class:`DeviceHistogram` — a persistent uint64 slot vector (per-line
  match-count buckets plus running totals) folded with one compiled
  donated add per confirmed step.  Addition cannot overflow a rung
  (slots are static, counts uint64), so there is no widen path and no
  flags to confirm — the degenerate, always-exact end of the fold
  machinery.  Syncs pull the tiny vector without clearing (running
  totals stay device-resident); ``close`` returns the final totals.
* :class:`KeyCounts` — the host accumulator for DeviceTable drains whose
  keys are opaque u64 identities (grep's global line numbers) rather
  than word spellings; ``PackedCounts`` keeps serving the word-keyed
  tables (the indexer's document-frequency drain).

Exactness contract, same as every service here: the engines' results are
bit-identical to their depth=1 host-merge paths because folds consume
exactly the confirmed per-step tensors the host merge would, widen
drains never drop keys, and the final close drain hands the host the
complete remainder.  Snapshots are observability only — they are never
an input to the result.
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dsi_tpu.device.table import (
    DeviceTable,
    _clear_program,
    _fold_program,
    _pack_program,
    _pow2,
    _quiet_unusable_donation,
    _step_structs,
    _table_structs,
)
from dsi_tpu.obs import span as _span
from dsi_tpu.parallel.shuffle import AXIS
from dsi_tpu.utils.jaxcompat import enable_x64, x64_scoped


class KeyCounts:
    """Host accumulator for drains whose kk=2 key lanes encode one opaque
    uint64 identity (hi, lo) — e.g. grep's global line numbers.  Mirrors
    the slice of the ``PackedCounts`` interface ``DeviceTable._pull_merge``
    drives (``add(keys, lens, cnts, parts)``); lens/parts are carried by
    the wire format but meaningless for opaque keys and ignored."""

    def __init__(self):
        self._counts: Dict[int, int] = {}

    def add(self, keys: np.ndarray, lens, cnts, parts) -> None:
        k = np.asarray(keys, dtype=np.uint64)
        key64 = (k[:, 0] << np.uint64(32)) | k[:, 1]
        for key, c in zip(key64.tolist(), np.asarray(cnts).tolist()):
            self._counts[key] = self._counts.get(key, 0) + int(c)

    def finalize(self) -> Dict[int, int]:
        return dict(self._counts)

    # ── checkpoint image (dsi_tpu/ckpt) ──

    def snapshot(self) -> Dict[str, np.ndarray]:
        if not self._counts:
            return {}
        n = len(self._counts)
        return {"keys": np.fromiter(self._counts.keys(), dtype=np.uint64,
                                    count=n),
                "cnts": np.fromiter(self._counts.values(), dtype=np.int64,
                                    count=n)}

    def restore(self, arrays: Dict[str, np.ndarray]) -> None:
        self._counts = {}
        if not arrays or "keys" not in arrays:
            return
        for k, c in zip(np.asarray(arrays["keys"], np.uint64).tolist(),
                        np.asarray(arrays["cnts"], np.int64).tolist()):
            self._counts[int(k)] = int(c)


def _topk_impl(tkeys, tlens, tcnts, *, k: int):
    """Count-descending top-``k`` slice of each device's table shard:
    sort along the capacity dimension by bitwise-NOT count (uint64
    descending as an ascending sort; empty rows carry count 0 → ~0 =
    u64-max → they sort last) with the key lanes as ascending
    tie-breakers, then take the first k rows.  Per-row sort along dim 1
    needs no cross-device communication, so the sharded table sorts in
    place."""
    kk = tkeys.shape[2]
    with enable_x64(True):
        neg = ~tcnts
        ops = (neg,) + tuple(tkeys[:, :, j] for j in range(kk)) + (tlens,)
        s = lax.sort(ops, dimension=1, num_keys=1 + kk)
        scnts = ~s[0][:, :k]
    skeys = jnp.stack([s[1 + j][:, :k] for j in range(kk)], axis=2)
    slens = s[1 + kk][:, :k]
    return skeys, slens, scnts


def _topk_program(*, n_dev: int, cap: int, kk: int, k: int):
    def fn(tkeys, tlens, tcnts):
        return _topk_impl(tkeys, tlens, tcnts, k=k)

    return f"topk_pack_d{n_dev}_c{cap}_k{kk}_t{k}", fn


_topk_jit = x64_scoped(jax.jit(_topk_impl, static_argnames=("k",)))


class DeviceTopK(DeviceTable):
    """Persistent on-device (key, count) table with count-sorted top-k
    snapshot syncs.

    Everything about folding, lagged confirmation, overflow recovery and
    the final drain is inherited verbatim from :class:`DeviceTable`; the
    one behavioral change is :meth:`sync`, which pulls the k heaviest
    rows (``snapshot``) instead of draining — the table stays resident
    so cross-window counts keep summing on device and the ``close()``
    drain remains the single exact hand-off to the host accumulator.

    Counting contract: ``topk_snapshots`` counts snapshot pulls (k rows
    each); ``sync_pulls`` counts DATA drains only (the close, inherited)
    and ``widens`` the recovery drains — so an engine's host pulls are
    ``topk_snapshots + widens + 1`` against ``steps`` on the per-step
    path.

    ``mesh_shards`` is inherited whole from :class:`DeviceTable`: folds
    become the shuffle-fold (keys — opaque line identities or word
    spellings alike — route to ``ihash(key bytes) % n_shards``), widens
    go per-shard.  The snapshot stays per-shard top-k + host merge of
    ``n_dev * k`` rows: a global winner is necessarily in its OWNING
    shard's top-k under the same order, so the pruning stays exact.
    """

    def __init__(self, mesh: Mesh, *, kk: int, cap: int, k: int, acc,
                 aot: bool = False, lag: int = 1,
                 stats: Optional[dict] = None, mesh_shards: int = 0):
        super().__init__(mesh, kk=kk, cap=cap, acc=acc, aot=aot, lag=lag,
                         stats=stats, mesh_shards=mesh_shards)
        self.k = int(k)
        self.stats.setdefault("topk_snapshots", 0)
        #: Last snapshot: ((count, key_lanes_tuple, len), ...) count
        #: desc, key asc — observability only, never a result input.
        self.snapshot: Tuple = ()

    def _topk_fn(self):
        if not self.aot:
            return functools.partial(_topk_jit, k=self.k)
        from dsi_tpu.backends import aotcache

        name, fn = _topk_program(n_dev=self.n_dev, cap=self.cap,
                                 kk=self.kk, k=self.k)
        t = _table_structs(self.n_dev, self.cap, self.kk)
        return aotcache.cached_compile(name, fn, (t[0], t[1], t[2]),
                                       x64=True)

    def sync(self) -> bool:
        """The K-fold snapshot pull: flush the fold lag (recovering any
        late-detected overflow), then pull the top-k rows — no drain, no
        clear.  Returns True when a snapshot crossed the wire (an empty
        table skips it)."""
        with _span("sync", stats=self.stats, key="sync_s",
                   snapshot=True):
            orphans = self._flush_pending()
            if orphans:
                self._recover(orphans)
            pulled = False
            if int(self._nrows.max()):
                tkeys, tlens, tcnts, _, _ = self._state
                skeys, slens, scnts = self._topk_fn()(tkeys, tlens, tcnts)
                keys_np = np.asarray(skeys)
                lens_np = np.asarray(slens)
                cnts_np = np.asarray(scnts)
                rows: List[Tuple] = []
                for d in range(self.n_dev):
                    # Rows past this shard's occupancy sorted last with
                    # count 0 (pad) — drop them by count, not by
                    # position, so a shard with < k rows contributes
                    # exactly its own.
                    for i in range(min(self.k, int(self._nrows[d]))):
                        c = int(cnts_np[d, i])
                        if c <= 0:
                            break
                        rows.append((c, tuple(keys_np[d, i].tolist()),
                                     int(lens_np[d, i])))
                rows.sort(key=lambda r: (-r[0], r[1]))
                self.snapshot = tuple(rows[:self.k])
                self.stats["topk_snapshots"] += 1
                pulled = True
        return pulled


def warm_topk_service(mesh: Mesh, *, kk: int, rows: int, cap: int, k: int,
                      table_rungs: int = 2, mesh_shards: int = 0) -> None:
    """Compile + persist the fold/clear/pack/snapshot shapes a
    :class:`DeviceTopK` reaches at this per-fold ``rows`` shape: the
    given capacity rung plus ``table_rungs - 1`` ×4 widenings, from
    shape structs alone — same discipline as ``table.warm_device_fold``
    (which also owns the ``mesh_fold_*``/``mesh_grow_*`` variants the
    ``mesh_shards`` flag switches to)."""
    from dsi_tpu.backends import aotcache
    from dsi_tpu.device.table import (_warm_mesh_fold_rung,
                                      _warm_pack_shapes)

    n_dev = mesh.devices.size
    cap = _pow2(cap)
    for rung in range(max(1, table_rungs)):
        table = _table_structs(n_dev, cap, kk)
        step = _step_structs(n_dev, rows, kk)
        if mesh_shards:
            _warm_mesh_fold_rung(mesh, n_dev=n_dev, n_shards=mesh_shards,
                                 cap=cap, kk=kk, rows=rows,
                                 grow=rung + 1 < max(1, table_rungs))
        else:
            name, fn = _fold_program(mesh=mesh, n_dev=n_dev, cap=cap,
                                     kk=kk, rows=rows)
            with _quiet_unusable_donation():
                aotcache.cached_compile(name, fn, table + step,
                                        donate_argnums=(0, 1, 2, 3, 4),
                                        x64=True)
        name, fn = _clear_program(mesh=mesh, n_dev=n_dev, cap=cap, kk=kk)
        with _quiet_unusable_donation():
            aotcache.cached_compile(name, fn, table,
                                    donate_argnums=(0, 1, 2, 3, 4),
                                    x64=True)
        _warm_pack_shapes(n_dev=n_dev, cap=cap, kk=kk,
                          mesh_shards=mesh_shards)
        name, fn = _topk_program(n_dev=n_dev, cap=cap, kk=kk, k=k)
        aotcache.cached_compile(name, fn, (table[0], table[1], table[2]),
                                x64=True)
        cap *= 4


def topk_service_persisted(mesh: Mesh, *, kk: int, rows: int, cap: int,
                           k: int, mesh_shards: int = 0) -> bool:
    """True when the rung-0 programs a :class:`DeviceTopK` executes at
    this shape are already in the persistent AOT cache.  With
    ``mesh_shards`` the probe keys on the ``mesh_fold_*`` shuffle-fold
    (the program a mesh run compiles first), mirroring
    ``table.device_fold_persisted``."""
    from dsi_tpu.backends.aotcache import is_persisted
    from dsi_tpu.device.table import (_TABLE_DONATE, _apply_struct,
                                      _mesh_fold_program)

    n_dev = mesh.devices.size
    cap = _pow2(cap)
    table = _table_structs(n_dev, cap, kk)
    step = _step_structs(n_dev, rows, kk)
    if mesh_shards:
        name, fn = _mesh_fold_program(mesh=mesh, n_dev=n_dev,
                                      n_shards=mesh_shards, cap=cap,
                                      kk=kk, rows=rows)
        if not is_persisted(name, fn,
                            table + step + (_apply_struct(n_dev),),
                            donate_argnums=_TABLE_DONATE):
            return False
    else:
        name, fn = _fold_program(mesh=mesh, n_dev=n_dev, cap=cap, kk=kk,
                                 rows=rows)
        if not is_persisted(name, fn, table + step,
                            donate_argnums=_TABLE_DONATE):
            return False
    name, fn = _pack_program(n_dev=n_dev, cap=cap, kk=kk, mp=cap)
    if not is_persisted(name, fn, (table[0], table[1], table[3], table[2])):
        return False
    name, fn = _topk_program(n_dev=n_dev, cap=cap, kk=kk, k=k)
    return is_persisted(name, fn, (table[0], table[1], table[2]))


# ── histogram ──────────────────────────────────────────────────────────


def _hist_fold_impl(state, step):
    with enable_x64(True):
        return state + step.astype(jnp.uint64)


_hist_fold_jit = x64_scoped(jax.jit(_hist_fold_impl, donate_argnums=(0,)))


def _hist_program(*, n_dev: int, slots: int):
    def fn(state, step):
        return _hist_fold_impl(state, step)

    return f"topk_hist_fold_d{n_dev}_s{slots}", fn


def _hist_premerge_impl(state):
    """Cross-shard reduction ON DEVICE: the mesh-sharded pull sums the
    per-device slot vectors over the mesh (one all-reduce) so the host
    pulls ONE pre-merged ``[slots]`` vector instead of N partials —
    1/n_dev the bytes, zero host merge."""
    with enable_x64(True):
        return jnp.sum(state, axis=0, dtype=jnp.uint64)


_hist_premerge_jit = x64_scoped(jax.jit(_hist_premerge_impl))


def _hist_premerge_program(*, n_dev: int, slots: int):
    def fn(state):
        return _hist_premerge_impl(state)

    return f"mesh_hist_pull_d{n_dev}_s{slots}", fn


def _hist_structs(n_dev: int, slots: int):
    sds = jax.ShapeDtypeStruct
    return (sds((n_dev, slots), jnp.uint64), sds((n_dev, slots), jnp.uint32))


class DeviceHistogram:
    """Persistent ``[n_dev, slots]`` uint64 accumulation vector over the
    mesh, folded with one compiled donated add per confirmed step.  The
    engines use the slots for per-line match-count buckets plus running
    totals (lines/matched/occurrences ride the same vector, so one fold
    program and one pull cover all the stream's scalars).

    No flags, no lag, no widen: a uint64 add cannot overflow a rung and
    cannot fail, so confirmation is trivially the dispatch itself — the
    degenerate end of the fold machinery, by design.

    ``pull()`` returns the running totals summed over devices without
    clearing; ``close()`` is the final pull.  ``stats`` receives
    ``hist_folds``/``hist_pulls``/``hist_s``/``pull_bytes``.

    ``mesh_shards`` > 0 pre-merges the pull ON DEVICE (one all-reduce
    over the mesh): the host receives a single ``[slots]`` vector
    instead of the ``[n_dev, slots]`` partials it used to sum itself —
    the literal N-partial-tables → one-pre-merged-table reduction,
    visible in ``pull_bytes``.
    """

    def __init__(self, mesh: Mesh, *, slots: int, aot: bool = False,
                 stats: Optional[dict] = None, mesh_shards: int = 0):
        self.mesh = mesh
        self.n_dev = int(mesh.devices.size)
        self.slots = int(slots)
        self.aot = bool(aot)
        self.mesh_shards = max(0, int(mesh_shards))
        self.stats = stats if stats is not None else {}
        for key in ("hist_folds", "hist_pulls", "pull_bytes"):
            self.stats.setdefault(key, 0)
        self.stats.setdefault("hist_s", 0.0)
        if self.mesh_shards:
            self.stats.setdefault("mesh_shards", self.mesh_shards)
        sh = NamedSharding(mesh, P(AXIS, None))
        with enable_x64(True):
            self._state = jax.device_put(
                np.zeros((self.n_dev, self.slots), np.uint64), sh)

    def _fold_fn(self):
        if not self.aot:
            return _hist_fold_jit
        from dsi_tpu.backends import aotcache

        name, fn = _hist_program(n_dev=self.n_dev, slots=self.slots)
        with _quiet_unusable_donation():
            return aotcache.cached_compile(
                name, fn, _hist_structs(self.n_dev, self.slots),
                donate_argnums=(0,), x64=True)

    def fold(self, step_dev) -> None:
        """Add one confirmed step's ``[n_dev, slots]`` uint32 vector into
        the running totals (async, donated state)."""
        with _span("hist_fold", lane="fold", stats=self.stats,
                   key="hist_s"):
            with _quiet_unusable_donation():
                self._state = self._fold_fn()(self._state, step_dev)
            self.stats["hist_folds"] += 1

    def _premerge_fn(self):
        if not self.aot:
            return _hist_premerge_jit
        from dsi_tpu.backends import aotcache

        name, fn = _hist_premerge_program(n_dev=self.n_dev,
                                          slots=self.slots)
        return aotcache.cached_compile(
            name, fn, (_hist_structs(self.n_dev, self.slots)[0],),
            x64=True)

    def pull(self) -> np.ndarray:
        """Running totals summed over devices — ``[slots]`` int64.  No
        clear: the vector keeps accumulating on device.  Mesh-sharded
        mode sums on device first and pulls one pre-merged vector
        (lane: the shuffle is the merge)."""
        with _span("hist_pull", lane="sync", stats=self.stats,
                   key="hist_s"):
            if self.mesh_shards:
                merged = np.asarray(self._premerge_fn()(self._state))
                self.stats["pull_bytes"] += merged.nbytes
                out = merged.astype(np.int64)
            else:
                full = np.asarray(self._state)
                self.stats["pull_bytes"] += full.nbytes
                out = full.astype(np.int64).sum(axis=0)
            self.stats["hist_pulls"] += 1
        return out

    def close(self) -> np.ndarray:
        out = self.pull()
        self._state = None
        return out

    # ── checkpoint image (dsi_tpu/ckpt) ──

    def checkpoint_state(self) -> dict:
        """Drain-free image of the running totals.  A histogram fold is
        a donated add with no flags, so the last dispatched fold IS
        confirmed the moment the pull lands — no lag to flush.  The
        pull is synchronous even under an async capture: the vector is
        KBs, and the live state is donated to the very next fold, so a
        deferred read could find the buffer gone."""
        return {"hist": np.asarray(self._state)}

    def checkpoint_capture(self):
        """Capture-API spelling (``ckpt/writer.py`` parts): the tiny
        vector is pulled eagerly, so the deferred is already ready."""
        from dsi_tpu.ckpt.delta import Deferred

        img = self.checkpoint_state()
        return Deferred(lambda: img)

    def restore_state(self, img: dict) -> None:
        sh = NamedSharding(self.mesh, P(AXIS, None))
        with enable_x64(True):  # keep the u64 totals u64 through the put
            self._state = jax.device_put(
                np.asarray(img["hist"], np.uint64), sh)


def warm_histogram(mesh: Mesh, *, slots: int, mesh_shards: int = 0) -> None:
    """Compile + persist the histogram fold at this slot count (plus,
    with ``mesh_shards``, the pre-merged ``mesh_hist_pull_*`` pull)."""
    from dsi_tpu.backends import aotcache

    name, fn = _hist_program(n_dev=mesh.devices.size, slots=slots)
    with _quiet_unusable_donation():
        aotcache.cached_compile(name, fn,
                                _hist_structs(mesh.devices.size, slots),
                                donate_argnums=(0,), x64=True)
    if mesh_shards:
        name, fn = _hist_premerge_program(n_dev=mesh.devices.size,
                                          slots=slots)
        aotcache.cached_compile(
            name, fn, (_hist_structs(mesh.devices.size, slots)[0],),
            x64=True)


def histogram_persisted(mesh: Mesh, *, slots: int,
                        mesh_shards: int = 0) -> bool:
    from dsi_tpu.backends.aotcache import is_persisted

    name, fn = _hist_program(n_dev=mesh.devices.size, slots=slots)
    if not is_persisted(name, fn,
                        _hist_structs(mesh.devices.size, slots),
                        donate_argnums=(0,)):
        return False
    if mesh_shards:
        name, fn = _hist_premerge_program(n_dev=mesh.devices.size,
                                          slots=slots)
        return is_persisted(
            name, fn, (_hist_structs(mesh.devices.size, slots)[0],))
    return True
