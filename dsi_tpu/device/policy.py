"""Sync cadence for device-resident accumulators.

The whole point of a device-resident merge table (``device/table.py``) is
that the host does NOT see every step: confirmed step outputs fold into
the table on-device and the host pulls the merged table only at sync
points.  ``SyncPolicy`` is the one place that cadence is decided, so the
streaming engine, the TF-IDF wave walk, and any future consumer (a
training-stack metrics loop is the same shape) agree on what "every K
steps" means and where the knob lives.

The policy is deliberately trivial — count confirmed folds, fire every
``sync_every`` — because the *correctness* story never depends on it:
every path also drains at stream end, and the widen protocol drains on
demand.  A missed sync costs host-visibility latency, never data.
"""

from __future__ import annotations

import os

#: Environment default for the fold-to-pull ratio (K).  8 amortizes the
#: per-pull wire latency to ~12% of the synchronous cost while keeping the
#: host view at most 8 steps stale; raise it on high-latency links.
_SYNC_EVERY_ENV = "DSI_STREAM_SYNC_EVERY"
_SYNC_EVERY_DEFAULT = 8


def sync_every_default(sync_every: int | None = None) -> int:
    """Resolve K: an explicit value wins, else ``DSI_STREAM_SYNC_EVERY``
    (default 8), floored at 1 (sync after every fold — the degenerate
    cadence that still exercises the fold path)."""
    if sync_every is None:
        try:
            sync_every = int(os.environ.get(_SYNC_EVERY_ENV,
                                            str(_SYNC_EVERY_DEFAULT)))
        except ValueError:
            sync_every = _SYNC_EVERY_DEFAULT
    return max(1, sync_every)


#: Environment default for the mesh-sharded service degree (0 = off =
#: the host-merge path, bit-identical historical behavior).
_MESH_SHARDS_ENV = "DSI_STREAM_MESH_SHARDS"


def mesh_shards_default(mesh_shards: int | None = None) -> int:
    """Resolve the mesh-sharding degree the engines hand their device
    services: an explicit value wins, else ``DSI_STREAM_MESH_SHARDS``
    (default 0 = off).  One resolver so the four engines, the CLIs and
    the soaks cannot read the knob differently — the ``sync_every``
    discipline."""
    if mesh_shards is None:
        try:
            mesh_shards = int(os.environ.get(_MESH_SHARDS_ENV, "0"))
        except ValueError:
            mesh_shards = 0
    return max(0, int(mesh_shards))


class SyncPolicy:
    """Pull the device table to the host every ``sync_every`` confirmed
    folds (plus, by caller contract, once at stream end).

    Counts *folds*, not steps: an empty step (tail batch with no tokens)
    contributes nothing to the table, so pulling for it would be a wasted
    round-trip — and the K-pull accounting the bench reports
    (``sync_pulls == ceil(folds / K)`` absent widens) stays exact.
    """

    def __init__(self, sync_every: int | None = None):
        self.sync_every = sync_every_default(sync_every)
        self._since = 0

    def note_fold(self) -> None:
        self._since += 1

    def due(self) -> bool:
        return self._since >= self.sync_every

    def reset(self) -> None:
        self._since = 0

    # Checkpoint image of the cadence position (dsi_tpu/ckpt): a
    # resumed stream must sync at the SAME step the uninterrupted one
    # would, so the folds-since-last-pull counter rides the manifest.
    def snapshot(self) -> int:
        return self._since

    def restore(self, since: int) -> None:
        self._since = max(0, int(since))
