"""Consumer-side fetch: CRC-verified shuffle over TCP, attributed.

The reducer half of the network data plane.  :func:`fetch_partition`
pulls one spooled payload from a producer's partition server (or reads
it locally when the producer is THIS worker — the locality hit the
coordinator's placement policy works to maximize) and unwraps the
one-byte codec flag (``partsrv.CODEC_KV``/``CODEC_RAW``).  Every fetch
is attributed in the ``net`` trace lane and a ``net`` metrics scope:
``net_bytes_raw`` (what the consumer got), ``net_bytes_wire`` (what
crossed the link), ``net_ratio`` (their quotient — the PR-13 codec's
evidence on this link), ``net_fetches``/``net_local_reads``/
``net_fetch_failures``.

Failure taxonomy, matching the RPC layer's:

* :class:`dsi_tpu.mr.rpc.ProtocolMismatch` / ``AuthError`` —
  mis-deployed fleet; NEVER absorbed here, the run must fail loudly.
* everything else (dead server, mid-stream death, CRC mismatch,
  server-side missing file) → :class:`FetchFailure`, carrying which
  producer task's bytes were lost — the caller reports it to the
  coordinator, which re-executes the producer (§3.4) and the consumer
  re-fetches from the replacement.
"""

from __future__ import annotations

import json
import os
from typing import Dict

from dsi_tpu.mr import rpc
from dsi_tpu.net.partsrv import CODEC_KV, CODEC_RAW
from dsi_tpu.obs import span


class FetchFailure(Exception):
    """A partition fetch failed for reasons a producer re-execution can
    cure (dead/dying server, torn stream, missing spool entry)."""

    def __init__(self, task: int, addr: str, name: str, cause: Exception):
        super().__init__(f"fetching {name} from {addr}: {cause}")
        self.task = task
        self.addr = addr
        self.name = name
        self.cause = cause


def _unwrap(payload: bytes) -> bytes:
    """Strip the codec flag byte; unpack when the producer packed."""
    flag, body = payload[:1], payload[1:]
    if flag == CODEC_KV:
        from dsi_tpu.ops.wirecodec import unpack_kv

        return unpack_kv(body)
    if flag == CODEC_RAW:
        return body
    raise rpc.StreamError(f"unknown codec flag {flag!r}")


def _attribute(stats, raw_n: int, wire_n: int, local: bool) -> None:
    if stats is None:
        return
    if local:
        stats["net_local_reads"] = stats.get("net_local_reads", 0) + 1
        return
    stats["net_fetches"] = stats.get("net_fetches", 0) + 1
    stats["net_bytes_raw"] = stats.get("net_bytes_raw", 0) + raw_n
    stats["net_bytes_wire"] = stats.get("net_bytes_wire", 0) + wire_n
    wire = stats["net_bytes_wire"]
    stats["net_ratio"] = round(stats["net_bytes_raw"] / wire, 3) \
        if wire else 0.0


def fetch_partition(addr: str, name: str, *, stats=None,
                    own_addr: str | None = None,
                    local_root: str | None = None,
                    timeout: float = 30.0,
                    secret: str | None = None) -> bytes:
    """One partition's bytes, wherever they live.

    When ``addr`` is our own advertised address the bytes are already in
    our spool (``local_root``) — read them directly, no socket, counted
    as ``net_local_reads`` (the §3.1-step-4 locality win).  Otherwise a
    streaming fetch with the codec flag unwrapped and the raw/wire bytes
    attributed.  Raises :class:`FetchFailure` (with ``task=-1``; callers
    that know the producer task re-raise with it filled) on anything a
    re-execution can cure."""
    if own_addr is not None and addr == own_addr and local_root:
        try:
            with span("net", lane="net", part=name, local=1):
                with open(os.path.join(local_root, name), "rb") as f:
                    raw = f.read()
        except OSError as e:
            raise FetchFailure(-1, addr, name, e) from e
        _attribute(stats, len(raw), 0, local=True)
        return raw
    try:
        with span("net", lane="net", part=name, addr=addr):
            payload = rpc.stream_fetch(addr, "Fetch", {"Name": name},
                                       timeout=timeout, secret=secret)
            raw = _unwrap(payload)
    except (rpc.ProtocolMismatch, rpc.AuthError):
        raise  # mis-deployed fleet: no replacement will cure it
    except (rpc.CoordinatorGone, OSError, ValueError) as e:
        if stats is not None:
            stats["net_fetch_failures"] = \
                stats.get("net_fetch_failures", 0) + 1
        raise FetchFailure(-1, addr, name, e) from e
    _attribute(stats, len(raw), len(payload), local=False)
    return raw


def run_reduce_task_net(reducef, reduce_task: int, map_locs: Dict,
                        *, workdir: str = ".",
                        own_addr: str | None = None,
                        stats=None, timeout: float = 30.0,
                        secret: str | None = None) -> str:
    """One reduce task with the shuffle over TCP.

    ``map_locs`` maps map-task number (possibly a JSON-string key — RPC
    round-trip) to the producer's partition-server address.  Each
    ``mr-<m>-<r>`` is fetched from the host that produced it, decoded
    with the reference's lenient record semantics, then sorted, grouped,
    reduced, and committed FIRST-WINS to this worker's private workdir
    (``mr-out-<r>``) exactly like the shared-dir path.  No intermediate
    GC — the producers' spools are on other machines; retention aging
    (``partsrv.reap_spool``) owns their lifetime.  Returns the committed
    output's basename.  Raises :class:`FetchFailure` with the producer
    map task filled in when any partition cannot be fetched."""
    from dsi_tpu.mr.types import KeyValue
    from dsi_tpu.mr.worker import group_and_reduce, output_name
    from dsi_tpu.utils.atomicio import atomic_write

    intermediate: list = []
    for m_key in sorted(map_locs, key=lambda k: int(k)):
        m = int(m_key)
        name = f"mr-{m}-{reduce_task}"
        try:
            raw = fetch_partition(map_locs[m_key], name, stats=stats,
                                  own_addr=own_addr, local_root=workdir,
                                  timeout=timeout, secret=secret)
        except FetchFailure as e:
            raise FetchFailure(m, e.addr, e.name, e.cause) from e
        for line in raw.decode("utf-8").splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                break  # truncated record: the reference's decoder break
            intermediate.append(KeyValue(obj["Key"], obj["Value"]))
    out = output_name(reduce_task, workdir)
    with atomic_write(out, first_wins=True) as f:
        group_and_reduce(intermediate, reducef, f)
    return os.path.basename(out)
