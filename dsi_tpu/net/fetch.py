"""Consumer-side fetch: CRC-verified shuffle over TCP, attributed.

The reducer half of the network data plane.  :func:`fetch_partition`
pulls one spooled payload from a producer's partition server (or reads
it locally when the producer is THIS worker — the locality hit the
coordinator's placement policy works to maximize) and unwraps the
one-byte codec flag (``partsrv.CODEC_KV``/``CODEC_RAW``).  Every fetch
is attributed in the ``net`` trace lane and a ``net`` metrics scope:
``net_bytes_raw`` (what the consumer got), ``net_bytes_wire`` (what
crossed the link), ``net_ratio`` (their quotient — the PR-13 codec's
evidence on this link), ``net_fetches``/``net_local_reads``/
``net_fetch_failures``.

## Overlapped shuffle (ISSUE 18)

:class:`FetchPipeline` turns the reducer's serial
fetch→decode→fetch→... loop into a bounded producer/consumer pool:
``window`` dialer threads pull partitions over per-producer keep-alive
connections (:class:`dsi_tpu.mr.rpc.StreamConn`) while the consumer
thread decodes the PREVIOUS partition — the wire time of fetch ``i+1``
hides behind the decode of fetch ``i``, so the shuffle wall tends to
``max(slowest producer, decode+sort)`` instead of the serial sum.
Determinism is structural: raw payloads land in per-item buffers and
the consumer walks them in submission (producer) order, decoding on ONE
thread — output bytes are identical at any window, and ``window=1``
bypasses the pool entirely (today's serial path, bit-identically).

Attribution: ``net_prefetch_window`` (the effective window),
``net_fetch_wait_s`` (consumer time blocked waiting for bytes the
dialers hadn't landed yet) and ``net_overlap_s`` (dialer wire time
hidden behind the consumer's decode — fetch seconds NOT visible as
waits) make the overlap auditable; serial mode reports 0 overlap by
construction.

Failure taxonomy, matching the RPC layer's:

* :class:`dsi_tpu.mr.rpc.ProtocolMismatch` / ``AuthError`` —
  mis-deployed fleet; NEVER absorbed here, the run must fail loudly.
* everything else (dead server, mid-stream death, CRC mismatch,
  server-side missing file, an unknown codec flag, a torn local spool
  read) → :class:`FetchFailure`, carrying which producer task's bytes
  were lost — the caller reports it to the coordinator, which
  re-executes the producer (§3.4) and the consumer re-fetches from the
  replacement.  Under the pipeline the FIRST failure wins: in-flight
  peers are drained, queued fetches are cancelled, and exactly one
  ``FetchFailure`` (the lowest failed producer) surfaces.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, Iterable, Iterator, List, Tuple

from dsi_tpu.mr import rpc
from dsi_tpu.net.partsrv import CODEC_KV, CODEC_RAW
from dsi_tpu.obs import span

#: Default bounded-prefetch window (fetches in flight + buffered but not
#: yet consumed).  ``DSI_NET_FETCH_WINDOW=1`` degenerates to the serial
#: fetch→decode loop bit-identically.
DEFAULT_FETCH_WINDOW = 4


def fetch_window_from_env(default: int = DEFAULT_FETCH_WINDOW) -> int:
    """The ``DSI_NET_FETCH_WINDOW`` knob, clamped to >= 1."""
    try:
        w = int(os.environ.get("DSI_NET_FETCH_WINDOW", "") or default)
    except ValueError:
        w = default
    return max(1, w)


def fetch_window_max_from_env(window: int) -> int:
    """The ``DSI_NET_FETCH_WINDOW_MAX`` adaptive-widening ceiling,
    clamped to >= ``window``.  Unset (or malformed) means the ceiling
    IS the window — adaptation off, exactly yesterday's behavior."""
    try:
        mx = int(os.environ.get("DSI_NET_FETCH_WINDOW_MAX", "")
                 or window)
    except ValueError:
        mx = window
    return max(int(window), mx)


class FetchFailure(Exception):
    """A partition fetch failed for reasons a producer re-execution can
    cure (dead/dying server, torn stream, missing spool entry)."""

    def __init__(self, task: int, addr: str, name: str, cause: Exception):
        super().__init__(f"fetching {name} from {addr}: {cause}")
        self.task = task
        self.addr = addr
        self.name = name
        self.cause = cause


def _unwrap(payload: bytes) -> bytes:
    """Strip the codec flag byte; unpack when the producer packed."""
    flag, body = payload[:1], payload[1:]
    if flag == CODEC_KV:
        from dsi_tpu.ops.wirecodec import unpack_kv

        return unpack_kv(body)
    if flag == CODEC_RAW:
        return body
    raise rpc.StreamError(f"unknown codec flag {flag!r}")


def _attribute(stats, raw_n: int, wire_n: int, local: bool) -> None:
    if stats is None:
        return
    if local:
        stats["net_local_reads"] = stats.get("net_local_reads", 0) + 1
        return
    stats["net_fetches"] = stats.get("net_fetches", 0) + 1
    stats["net_bytes_raw"] = stats.get("net_bytes_raw", 0) + raw_n
    stats["net_bytes_wire"] = stats.get("net_bytes_wire", 0) + wire_n
    wire = stats["net_bytes_wire"]
    stats["net_ratio"] = round(stats["net_bytes_raw"] / wire, 3) \
        if wire else 0.0


def _count_failure(stats) -> None:
    if stats is not None:
        stats["net_fetch_failures"] = \
            stats.get("net_fetch_failures", 0) + 1


class ConnPool:
    """Per-dialer-thread cache of keep-alive :class:`rpc.StreamConn`
    objects keyed by producer address.  NOT thread-safe — each dialer
    owns its own pool, so a producer serving several partitions to one
    reducer is dialed once per dialer thread, not once per partition."""

    def __init__(self, timeout: float = 30.0, secret: str | None = None):
        self._timeout = timeout
        self._secret = secret
        self._conns: Dict[str, rpc.StreamConn] = {}

    def fetch(self, addr: str, method: str, args: dict) -> bytes:
        """Fetch over a cached connection, dialing fresh on a miss.  A
        reused connection that fails with a curable error is retried
        ONCE on a fresh dial (the cached socket may simply have idled
        past the server's timeout); a fresh connection's failure
        propagates — that producer is really gone."""
        conn = self._conns.pop(addr, None)
        if conn is not None:
            try:
                payload = conn.fetch(method, args)
            except (rpc.ProtocolMismatch, rpc.AuthError):
                conn.close()
                raise  # mis-deployed fleet: a redial cannot cure it
            except (rpc.CoordinatorGone, OSError):
                conn.close()  # stale keep-alive; fall through to redial
            else:
                self._conns[addr] = conn
                return payload
        conn = rpc.StreamConn(addr, timeout=self._timeout,
                              secret=self._secret)
        try:
            payload = conn.fetch(method, args)
        except BaseException:
            conn.close()
            raise
        self._conns[addr] = conn
        return payload

    def close(self) -> None:
        for conn in self._conns.values():
            conn.close()
        self._conns.clear()

    def __enter__(self) -> "ConnPool":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def fetch_partition(addr: str, name: str, *, stats=None,
                    own_addr: str | None = None,
                    local_root: str | None = None,
                    timeout: float = 30.0,
                    secret: str | None = None,
                    pool: ConnPool | None = None) -> bytes:
    """One partition's bytes, wherever they live.

    When ``addr`` is our own advertised address the bytes are already in
    our spool (``local_root``) — read them directly, no socket, counted
    as ``net_local_reads`` (the §3.1-step-4 locality win).  Otherwise a
    streaming fetch with the codec flag unwrapped and the raw/wire bytes
    attributed; ``pool`` (if given) reuses per-producer keep-alive
    connections instead of dialing per fetch.  Raises
    :class:`FetchFailure` (with ``task=-1``; callers that know the
    producer task re-raise with it filled) on anything a re-execution
    can cure — including a torn local spool read and an unknown codec
    flag, both counted in ``net_fetch_failures``."""
    if own_addr is not None and addr == own_addr and local_root:
        try:
            with span("net", lane="net", part=name, local=1):
                with open(os.path.join(local_root, name), "rb") as f:
                    raw = f.read()
        except OSError as e:
            _count_failure(stats)
            raise FetchFailure(-1, addr, name, e) from e
        _attribute(stats, len(raw), 0, local=True)
        return raw
    try:
        with span("net", lane="net", part=name, addr=addr):
            if pool is not None:
                payload = pool.fetch(addr, "Fetch", {"Name": name})
            else:
                payload = rpc.stream_fetch(addr, "Fetch", {"Name": name},
                                           timeout=timeout, secret=secret)
            raw = _unwrap(payload)
    except (rpc.ProtocolMismatch, rpc.AuthError):
        raise  # mis-deployed fleet: no replacement will cure it
    except (rpc.CoordinatorGone, OSError, ValueError) as e:
        # rpc.StreamError ⊂ ConnectionError ⊂ OSError, so _unwrap's
        # unknown-codec-flag raise lands here too — wrapped and counted
        # like every other curable failure, never a bare StreamError.
        _count_failure(stats)
        raise FetchFailure(-1, addr, name, e) from e
    _attribute(stats, len(raw), len(payload), local=False)
    return raw


class FetchPipeline:
    """Bounded prefetch pool over :func:`fetch_partition`.

    ``items`` are ``(task, addr, name)`` fetch descriptors in the order
    the consumer wants their bytes.  Up to ``window`` payloads may be in
    flight or landed-but-unconsumed at once (a semaphore token is held
    from claim to consumption, so a slow consumer backpressures the
    dialers instead of buffering the whole shuffle).  Iterating the
    pipeline yields ``(task, raw_bytes)`` strictly in submission order —
    the overlap never reorders the merge.

    Failure: the first dialer error sets the cancel flag; dialers finish
    (drain) their in-flight fetch and exit without claiming more work;
    the consumer joins them and re-raises the lowest failed item's error
    as a :class:`FetchFailure` with its task filled in.
    ``ProtocolMismatch``/``AuthError`` propagate unwrapped (fatal).

    Attribution lands in ``stats`` under the pipeline's lock:
    per-fetch scratch scopes merge after each fetch, so the shared
    ``net`` scope never sees a torn read-modify-write from two dialers.

    Adaptive widening (ISSUE 19): with ``max_window > window`` the
    consumer watches its own stall fraction — when, since the last
    adjustment, it spent more than half its wall blocked in
    ``wait_s`` (the dialers can't keep up: bandwidth-delay product
    exceeds the window), the effective window DOUBLES (clamped to
    ``max_window``): extra semaphore permits are released and extra
    dialer threads spawned mid-iteration.  Widening only deepens
    prefetch — consumption order, decode thread, and therefore output
    bytes are unchanged at any effective window, and a pipeline that
    never stalls never widens.  ``window_effective`` (also attributed
    as ``net_prefetch_window``) is the audit trail.
    """

    def __init__(self, items: Iterable[Tuple[int, str, str]], *,
                 window: int = DEFAULT_FETCH_WINDOW, stats=None,
                 own_addr: str | None = None,
                 local_root: str | None = None,
                 timeout: float = 30.0, secret: str | None = None,
                 max_window: int | None = None):
        self._items: List[Tuple[int, str, str]] = list(items)
        self._window = max(1, int(window))
        self._max_window = max(self._window,
                               int(max_window or self._window))
        if self._window <= 1:
            self._max_window = self._window  # serial stays serial
        self.window_effective = self._window
        self._stats = stats
        self._own_addr = own_addr
        self._local_root = local_root
        self._timeout = timeout
        self._secret = secret
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._cancel = threading.Event()
        self._slots = threading.Semaphore(self._window)
        self._next = 0
        self._results: Dict[int, bytes] = {}
        self._errors: Dict[int, Exception] = {}
        self._fetch_s = 0.0  # Σ dialer seconds spent fetching
        self.wait_s = 0.0    # Σ consumer seconds blocked on a fetch
        self.overlap_s = 0.0  # fetch seconds hidden behind the consumer
        self._mark_t = 0.0    # widening epoch start (consumer clock)
        self._mark_wait = 0.0  # wait_s at the epoch start
        n = min(self._window, len(self._items))
        self._threads = [
            threading.Thread(target=self._dialer, name=f"dsi-fetch-{i}",
                             daemon=True)
            for i in range(n)]

    def _maybe_widen(self, now: float) -> None:
        """Consumer-side widening check, once per consumed item (class
        docstring).  Runs on the consumer thread only — the effective
        window is read by nobody else mid-flight."""
        if self.window_effective >= self._max_window:
            return
        with self._lock:
            remaining = len(self._items) - self._next
        if remaining <= 0:
            return  # every fetch already claimed: nothing to deepen
        elapsed = now - self._mark_t
        waited = self.wait_s - self._mark_wait
        if elapsed < 0.01 or waited <= 0.5 * elapsed:
            return
        new = min(self._max_window, self.window_effective * 2)
        delta = new - self.window_effective
        self.window_effective = new
        for _ in range(delta):
            self._slots.release()
        # More permits deserve more dialers (each blocks on one fetch
        # at a time), capped by the work left to claim.
        base = len(self._threads)
        for j in range(max(0, min(new, len(self._items)) - base)):
            t = threading.Thread(target=self._dialer,
                                 name=f"dsi-fetch-w{base + j}",
                                 daemon=True)
            self._threads.append(t)
            t.start()
        self._mark_t = now
        self._mark_wait = self.wait_s

    def _merge(self, scratch: dict) -> None:
        stats = self._stats
        if stats is None or not scratch:
            return
        with self._lock:
            for k, v in scratch.items():
                if k == "net_ratio":
                    continue
                stats[k] = stats.get(k, 0) + v
            wire = stats.get("net_bytes_wire", 0)
            if wire:
                stats["net_ratio"] = round(
                    stats.get("net_bytes_raw", 0) / wire, 3)

    def _dialer(self) -> None:
        with ConnPool(timeout=self._timeout, secret=self._secret) as pool:
            while True:
                self._slots.acquire()
                if self._cancel.is_set():
                    self._slots.release()
                    return
                with self._lock:
                    if self._next >= len(self._items):
                        self._slots.release()
                        return
                    i = self._next
                    self._next += 1
                task, addr, name = self._items[i]
                scratch: dict = {}
                t0 = time.perf_counter()
                try:
                    raw = fetch_partition(
                        addr, name, stats=scratch, own_addr=self._own_addr,
                        local_root=self._local_root, timeout=self._timeout,
                        secret=self._secret, pool=pool)
                except Exception as e:
                    self._merge(scratch)
                    self._cancel.set()
                    with self._cond:
                        self._errors[i] = e
                        self._cond.notify_all()
                    return
                self._merge(scratch)
                with self._cond:
                    self._fetch_s += time.perf_counter() - t0
                    self._results[i] = raw
                    self._cond.notify_all()

    def _drain(self) -> None:
        """Cancel queued work and unblock+join every dialer."""
        self._cancel.set()
        for _ in self._threads:
            self._slots.release()
        for t in self._threads:
            t.join()

    def _raise_first(self) -> None:
        i = min(self._errors)
        task, addr, name = self._items[i]
        e = self._errors[i]
        if isinstance(e, FetchFailure):
            raise FetchFailure(task, e.addr, e.name, e.cause) from e
        raise e  # ProtocolMismatch / AuthError / programming error

    def __iter__(self) -> Iterator[Tuple[int, bytes]]:
        for t in self._threads:
            t.start()
        self._mark_t = time.perf_counter()
        self._mark_wait = 0.0
        try:
            for i, (task, addr, name) in enumerate(self._items):
                t0 = time.perf_counter()
                with self._cond:
                    # First failure wins: stop waiting as soon as ANY
                    # dialer errored (peers blocked on the window's
                    # semaphore would otherwise never land item i) —
                    # the finally-drain below cancels and joins them.
                    while i not in self._results and not self._errors:
                        self._cond.wait(0.05)
                    if i not in self._results:
                        self._raise_first()
                    raw = self._results.pop(i)
                now = time.perf_counter()
                self.wait_s += now - t0
                self._maybe_widen(now)
                yield task, raw
                self._slots.release()
            self.overlap_s = max(0.0, self._fetch_s - self.wait_s)
            if self._stats is not None:
                with self._lock:
                    self._stats["net_fetch_wait_s"] = self._stats.get(
                        "net_fetch_wait_s", 0.0) + round(self.wait_s, 6)
                    self._stats["net_overlap_s"] = self._stats.get(
                        "net_overlap_s", 0.0) + round(self.overlap_s, 6)
                    self._stats["net_prefetch_window"] = max(
                        self._stats.get("net_prefetch_window", 0),
                        self.window_effective)
        finally:
            self._drain()


def _decode_lines(raw: bytes, intermediate: list, kv_type) -> None:
    """The reference's lenient record decoder — shared by the serial and
    pipelined paths so their output bytes are identical by construction."""
    for line in raw.decode("utf-8").splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            obj = json.loads(line)
        except json.JSONDecodeError:
            break  # truncated record: the reference's decoder break
        intermediate.append(kv_type(obj["Key"], obj["Value"]))


def run_reduce_task_net(reducef, reduce_task: int, map_locs: Dict,
                        *, workdir: str = ".",
                        own_addr: str | None = None,
                        stats=None, timeout: float = 30.0,
                        secret: str | None = None,
                        window: int | None = None,
                        max_window: int | None = None) -> str:
    """One reduce task with the shuffle over TCP.

    ``map_locs`` maps map-task number (possibly a JSON-string key — RPC
    round-trip) to the producer's partition-server address.  Each
    ``mr-<m>-<r>`` is fetched from the host that produced it, decoded
    with the reference's lenient record semantics, then sorted, grouped,
    reduced, and committed FIRST-WINS to this worker's private workdir
    (``mr-out-<r>``) exactly like the shared-dir path.  ``window``
    (default ``DSI_NET_FETCH_WINDOW``, 4) bounds the prefetch pool;
    ``window=1`` runs the literal serial fetch→decode loop, so it is
    bit-identical to the pre-pipeline path AND reports
    ``net_overlap_s == 0``.  ``max_window`` (default
    ``DSI_NET_FETCH_WINDOW_MAX``, = window → off) lets the pipeline
    widen itself when consumer waits dominate (class docstring).  At
    any window — widened or not — the merge order is the sorted
    producer order, so ``mr-out-<r>`` bytes are window-invariant.  No
    intermediate GC — the producers' spools are on other machines;
    retention aging (``partsrv.reap_spool``) owns their lifetime.
    Returns the committed output's basename.  Raises
    :class:`FetchFailure` with the producer map task filled in when any
    partition cannot be fetched."""
    from dsi_tpu.mr.types import KeyValue
    from dsi_tpu.mr.worker import group_and_reduce, output_name
    from dsi_tpu.utils.atomicio import atomic_write

    if window is None:
        window = fetch_window_from_env()
    window = max(1, int(window))
    if max_window is None:
        max_window = fetch_window_max_from_env(window)
    max_window = max(window, int(max_window))
    m_keys = sorted(map_locs, key=lambda k: int(k))
    if stats is not None:
        stats["net_prefetch_window"] = max(
            stats.get("net_prefetch_window", 0), window)
    intermediate: list = []
    if window <= 1 or len(m_keys) <= 1:
        for m_key in m_keys:
            m = int(m_key)
            name = f"mr-{m}-{reduce_task}"
            try:
                raw = fetch_partition(map_locs[m_key], name, stats=stats,
                                      own_addr=own_addr, local_root=workdir,
                                      timeout=timeout, secret=secret)
            except FetchFailure as e:
                raise FetchFailure(m, e.addr, e.name, e.cause) from e
            _decode_lines(raw, intermediate, KeyValue)
    else:
        items = [(int(k), map_locs[k], f"mr-{int(k)}-{reduce_task}")
                 for k in m_keys]
        pipe = FetchPipeline(items, window=window, stats=stats,
                             own_addr=own_addr, local_root=workdir,
                             timeout=timeout, secret=secret,
                             max_window=max_window)
        for m, raw in pipe:
            with span("decode", lane="net", part=f"mr-{m}-{reduce_task}"):
                _decode_lines(raw, intermediate, KeyValue)
    out = output_name(reduce_task, workdir)
    with atomic_write(out, first_wins=True) as f:
        group_and_reduce(intermediate, reducef, f)
    return os.path.basename(out)
