"""Worker-side partition server: spool locally, serve over TCP.

The producer half of the network data plane.  Each worker owns ONE
private spool directory (its private workdir in the share-nothing
harness); everything it must make fetchable — map-side shuffle
partitions, committed shard outputs, reduce outputs — either already
lives there (the classic ``mr-<m>-<r>``/``mr-out-<r>`` commit paths
write into the worker's workdir) or is spooled explicitly via
:meth:`PartitionServer.put` (the durable-write path: temp + fsync +
rename + CRC32 sidecar).  Consumers fetch by basename over the
:class:`dsi_tpu.mr.rpc.StreamServer` chunked transport.

Wire codec: when enabled (default), payloads that the PR-13 line codec
(``ops/wirecodec.pack_kv``) actually shrinks ship packed, prefixed with
a one-byte flag — ``b"K"`` (packed) or ``b"R"`` (raw) — so the consumer
never guesses from content.  Exactness never depends on the codec: a
payload the dictionary does not help ships raw.

Boot hygiene (satellite): a kill-9'd predecessor leaves ``.tmp-*``
orphans mid-commit and whole dead-task spools nobody will ever fetch.
:func:`reap_spool` runs at server construction — ``reap_tmp_files``
plus retention-aged file GC, the serve daemon's ``_boot_hygiene`` /
``_gc_aged_chains`` discipline scaled down to one flat directory.

Fault injection: the ``mid-serve`` point (``ckpt/fault.py``) and the
``mid-serve`` chaos boundary both fire after the FIRST chunk of a
response hits the socket, so a killed server leaves the consumer a
half-sent payload and a dead peer — the exact failure the coordinator's
re-fetch-from-replacement machinery must absorb.
"""

from __future__ import annotations

import os
import time
from typing import Tuple

from dsi_tpu.mr import rpc
from dsi_tpu.utils.atomicio import reap_tmp_files, write_bytes_durable

#: One-byte wire flags: packed with the line codec vs raw bytes.
CODEC_KV = b"K"
CODEC_RAW = b"R"


def reap_spool(spool_dir: str,
               retention_s: float = 3600.0) -> Tuple[int, int]:
    """Boot hygiene for one spool directory: remove ``.tmp-*`` orphans
    (``atomic_write`` temps from a writer killed mid-commit) and age out
    files untouched past ``retention_s`` (dead-task spools — their job
    finished or was re-executed elsewhere; nothing will fetch them).
    Returns ``(tmp_reaped, aged_out)``.  Safe only at boot, before this
    process starts writing — exactly when it is called."""
    reaped = reap_tmp_files(spool_dir)
    aged = 0
    now = time.time()
    try:
        names = os.listdir(spool_dir)
    except OSError:
        return reaped, 0
    for name in names:
        path = os.path.join(spool_dir, name)
        try:
            if os.path.isfile(path) and \
                    now - os.path.getmtime(path) > retention_s:
                os.remove(path)
                aged += 1
        except OSError:
            pass
    return reaped, aged


class PartitionServer:
    """Serve one private spool directory's files over the stream
    transport.

    ``bind`` defaults to ``tcp:127.0.0.1:0`` (an OS-assigned loopback
    port — the localhost harness); multi-host fleets bind a reachable
    host and MUST set ``DSI_MR_SECRET`` (the StreamServer refuses
    non-loopback TCP without it).  :attr:`address` is the dialable
    form to register with the coordinator.
    """

    def __init__(self, spool_dir: str, bind: str = "",
                 secret: str | None = None,
                 retention_s: float = 3600.0, codec: bool = True):
        self.spool_dir = os.path.abspath(spool_dir)
        os.makedirs(self.spool_dir, exist_ok=True)
        self.boot_reaped, self.boot_aged = reap_spool(self.spool_dir,
                                                      retention_s)
        self.codec = codec
        self.served = 0
        # Injected per-chunk serve latency (seconds) — the bench's
        # synthetic slow link (ISSUE 18): on localhost the wire is too
        # fast for fetch pipelining to show, so the A/B row inflates
        # every chunk's serve time deterministically on BOTH arms.
        try:
            self._chunk_sleep_s = float(
                os.environ.get("DSI_NET_CHUNK_SLEEP_S", "") or 0.0)
        except ValueError:
            self._chunk_sleep_s = 0.0
        self._srv = rpc.StreamServer(bind or "tcp:127.0.0.1:0",
                                     {"Fetch": self._fetch},
                                     secret=secret,
                                     chunk_hook=self._chunk_hook)

    # ── spool ──

    def path_of(self, name: str) -> str:
        """Spool path for ``name``; rejects anything that is not a
        plain visible basename (path escapes, ``.tmp-*`` temps, CRC
        sidecars) — the fetch surface must not read outside the
        spool."""
        if (not name or name != os.path.basename(name)
                or name.startswith(".")):
            raise ValueError(f"bad partition name {name!r}")
        return os.path.join(self.spool_dir, name)

    def put(self, name: str, data: bytes) -> int:
        """Spool ``data`` durably under ``name``; returns its CRC32
        (``write_bytes_durable``: temp + fsync + rename + sidecar)."""
        return write_bytes_durable(self.path_of(name), data)

    # ── serving ──

    def _chunk_hook(self, chunk_index: int) -> None:
        if self._chunk_sleep_s > 0.0:
            time.sleep(self._chunk_sleep_s)
        # After the first chunk is on the wire: the consumer has the
        # header + a partial payload when the kill lands.
        if chunk_index == 0:
            from dsi_tpu.ckpt.fault import chaos_kill_point, fault_point

            fault_point("mid-serve")
            chaos_kill_point("mid-serve")

    def _fetch(self, args: dict) -> bytes:
        name = args.get("Name")
        if not isinstance(name, str):
            raise ValueError("Fetch needs a Name")
        with open(self.path_of(name), "rb") as f:
            raw = f.read()
        self.served += 1
        if self.codec:
            from dsi_tpu.ops.wirecodec import pack_kv

            packed = pack_kv(raw)
            if len(packed) < len(raw):
                return CODEC_KV + packed
        return CODEC_RAW + raw

    # ── lifecycle ──

    @property
    def address(self) -> str:
        return self._srv.address

    def start(self) -> None:
        self._srv.start()

    def close(self) -> None:
        self._srv.close()
