"""Network data plane: worker-served shuffle over TCP (ISSUE 17).

Dean & Ghemawat's data plane is not a shared filesystem: map workers
write intermediate partitions to LOCAL disk and serve them to reducers
over RPC, with the master scheduling for locality (OSDI'04 §3.1 step 4)
and re-executing completed map tasks whose disk died (§3.4).  The
6.5840 lab contract this repo reproduces punts on that with one shared
working directory — the single remaining reason the framework is
one-machine.  This package severs it:

* :mod:`dsi_tpu.net.partsrv` — the worker-side partition server: spools
  bytes to a PRIVATE local dir through the durable-write path and
  serves them over the :class:`dsi_tpu.mr.rpc.StreamServer` chunked
  transport (per-chunk CRC32 + whole-payload trailer, hello-frame
  version gate).
* :mod:`dsi_tpu.net.fetch` — the consumer side: CRC-verified streaming
  fetch with the PR-13 line codec on the wire
  (``net_bytes_raw``/``net_bytes_wire``/``net_ratio`` attribution, the
  ``net`` trace lane), plus the reducer that shuffles over TCP instead
  of reading ``mr-*-<r>`` from a shared directory.

The coordinator half (location registry, locality-aware placement,
re-fetch-from-replacement via producer re-execution) lives in
``mr/coordinator.py``; the harness half (``mrrun --net``,
``shardrun --hosts``, per-process private workdirs) in the CLIs.
"""

from dsi_tpu.net.partsrv import PartitionServer, reap_spool
from dsi_tpu.net.fetch import (ConnPool, FetchFailure, FetchPipeline,
                               fetch_partition, fetch_window_from_env,
                               run_reduce_task_net)

__all__ = ["PartitionServer", "reap_spool", "ConnPool", "FetchFailure",
           "FetchPipeline", "fetch_partition", "fetch_window_from_env",
           "run_reduce_task_net"]
