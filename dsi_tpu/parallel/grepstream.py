"""Streaming grep / indexer engines on the shared pipeline core.

The grep and indexer apps (``apps/tpu_grep.py``, ``apps/tpu_indexer.py``
— the working realizations of the reference's ``mrapps/dgrep.go`` /
``mrapps/indexer.go`` intent) run per-file through the MR framework:
every file pays a full host round-trip, and no cross-step state lives on
device.  This module gives both workloads the treatment word count and
TF-IDF already got — engines that consume the shared dispatch/finish
pipeline core (``parallel/pipeline.py``) with the same contract those
engines honor bit-identically:

* a background producer feeds a bounded queue (``batch_lines`` /
  ``_wave_chunk`` materialization off the critical path),
* a ``depth``-deep in-flight window of donated per-step uploads through
  ``aotcache.cached_compile(donate_argnums)``,
* per-step scalar checks DEFERRED until a step leaves the window, with
  exactly-once replay at sticky rungs — for grep that rung is the
  ``l_cap`` line-capacity ladder (``ops/grepk.line_cap_rungs``): the
  kernel's former host-fallback escalation folded into the pipeline's
  replay protocol, so a short-line stream replays one step at the wider
  compiled shape and the shape sticks, instead of abandoning the device
  path,
* cross-step state on device via ``dsi_tpu/device/``: grep folds
  per-line match-count histograms (:class:`DeviceHistogram`) and top-k
  match candidates (:class:`DeviceTopK`), the indexer appends postings
  (:class:`DevicePostings`) and folds per-word document-frequency rows
  into the same top-k table — all lagging the deferred-exactness window
  and syncing under ``SyncPolicy``, so host pulls drop from one-per-step
  to the K-fold cadence plus widens.

Grep semantics, stated exactly (the oracle below implements the same
rules byte-for-byte): the stream is '\\n'-delimited byte lines (a
trailing newline opens no final empty line); a line's match count is the
number of positions where the literal pattern's bytes occur (overlapping
occurrences count); the engine reports total lines / matched lines /
occurrences, a ``bins``-bucket per-line match-count histogram (bucket =
``min(occ, bins-1)``), and the top-k lines by occurrence count (ties to
the earlier line).  Per-(step, device) top-k candidate pruning on device
is EXACT: a line in the global top-k is necessarily in the top-k of its
own step and device under the same (count desc, line asc) order, so the
pruned candidate multiset always contains the global winners.

Indexer semantics: documents are processed in waves of ``n_dev`` (one
per device, ``plan_waves`` sizing), the posting step is the word-count
map prologue with a (tf ≡ 1, doc, part) payload — one posting row per
distinct word per document — shuffled to the partition owner exactly as
in ``parallel/shuffle.py``; the result is ``{word: (part, [doc ids in
wave order])}`` plus the top-k words by document frequency.  Posting
order is an invariant through every path (the per-wave pull path and the
``DevicePostings`` sticky-overflow recovery both preserve it).

Both engines return None only when the input needs the host path (a
non-literal pattern or a line wider than the chunk for grep; non-ASCII
bytes or >64-byte words for the indexer) — correctness never depends on
a kernel (``backends/tpu.py`` contract).
"""

from __future__ import annotations

import functools
import os
import time
from typing import Iterable, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dsi_tpu.ckpt import (
    CheckpointPolicy,
    CheckpointStore,
    CheckpointWriter,
    DeltaSteps,
    HostDeltaLog,
    checkpoint_async_default,
    checkpoint_delta_default,
    drain_packed_steps,
    drain_posting_steps,
    fault_point,
    skip_stream,
)
from dsi_tpu.device.policy import SyncPolicy, mesh_shards_default
from dsi_tpu.device.table import (DeviceTable, _pow2,
                                  _quiet_unusable_donation)
from dsi_tpu.device.topk import DeviceHistogram, DeviceTopK, KeyCounts
from dsi_tpu.obs import metrics_scope, span as _span
from dsi_tpu.ops.grepk import is_literal_pattern, line_cap_rungs
from dsi_tpu.ops.wordcount import (
    _PAD_KEY64,
    _shift_left,
    grouper_ladder,
    pack_key_lanes,
    rung0_cap,
    unpack_key_lanes,
    warm_groupers,
)
from dsi_tpu.parallel.merge import PackedCounts, PostingsTable
from dsi_tpu.parallel.pipeline import (
    BufferPool,
    StepPipeline,
    fold_source_stats,
    pipeline_depth,
)
from dsi_tpu.parallel.stepobj import EngineStep
from dsi_tpu.parallel.shuffle import (
    AXIS,
    default_mesh,
    map_prologue,
    occupied_prefix,
    shuffle_rows,
)
from dsi_tpu.utils.jaxcompat import enable_x64, shard_map

import dsi_tpu.ops.grepk as _grepk_mod
import dsi_tpu.ops.wordcount as _wc_mod
import dsi_tpu.parallel.shuffle as _sh_mod

#: Histogram buckets for per-line match counts: bucket b < bins-1 holds
#: lines with exactly b occurrences, the last bucket everything wider.
GREP_BINS = 8

#: Bench grep-row chunk shape — ONE definition shared by the bench's
#: cache-existence gate, the row's run, and scripts/warm_kernels.py
#: --phase grep, so the probed key cannot drift from the key the run
#: compiles (the STREAM_CHUNK_BYTES discipline).
GREP_CHUNK_BYTES = 1 << 21

#: jax.jit donate_argnums for the grep step program: the chunk upload is
#: consumed by the kernel (pattern/lens/bases survive — the pattern is
#: uploaded once per stream and reused every step).
_GREP_DONATE = (0,)

#: Default top-k candidate rows kept per stream/walk.
DEFAULT_TOPK = 16


class _LineTooLong(Exception):
    """A line wider than one chunk row: the stream needs the host path."""


def _topk_cap_env() -> int:
    """The ``DSI_DEVICE_TOPK_CAP`` override (0 = unset/malformed) — the
    HBM lever for the top-k candidate table's starting rung, and the
    test hook that forces the widen path mid-stream.  One parser for
    both engines, so the knob cannot be read differently."""
    try:
        return max(0, int(os.environ.get("DSI_DEVICE_TOPK_CAP", "0")))
    except ValueError:
        return 0


def _default_topk_cap(n_dev: int, k: int) -> int:
    """Rung-0 capacity for grep's candidate table: enough for ~hundreds
    of folds between widens at the default shapes, overridable by
    ``DSI_DEVICE_TOPK_CAP``."""
    return _topk_cap_env() or _pow2(max(1 << 14, n_dev * k))


# ── line batching ──────────────────────────────────────────────────────


def batch_lines(blocks: Iterable[bytes], n_dev: int, chunk_bytes: int,
                pool: Optional[BufferPool] = None,
                offsets: Optional[list] = None):
    """Slice a byte-block stream into zero-padded ``[n_dev, chunk_bytes]``
    batches, cutting rows only at newline boundaries so no line straddles
    a row.  Yields ``(batch, lens, row_lines)`` — per-row valid byte
    counts and per-row line counts (the host side of the device's line
    accounting: newlines plus an unterminated tail line).

    With ``pool`` batches come from the engine's rotating buffer set;
    the consumer hands each batch back via ``pool.give`` once its step
    is confirmed.  A line wider than ``chunk_bytes`` raises
    :class:`_LineTooLong` — the stream is the host path's then.

    With ``offsets`` (the checkpoint cursor hook, the ``batch_stream``
    contract) the stream offset just past each yielded batch's content
    is appended, before the yield.
    """
    carry = bytearray()
    consumed = 0

    def new_batch() -> np.ndarray:
        if pool is not None:
            return pool.take()
        return np.zeros((n_dev, chunk_bytes), dtype=np.uint8)

    batch = new_batch()
    lens = np.zeros(n_dev, dtype=np.int32)
    row_lines = np.zeros(n_dev, dtype=np.int64)
    row = 0

    def fill_rows(final: bool):
        nonlocal batch, lens, row_lines, row, consumed
        while carry and (len(carry) > chunk_bytes or final):
            if len(carry) <= chunk_bytes:
                cut = len(carry)  # final tail: whole remainder fits
            else:
                win = np.frombuffer(memoryview(carry)[:chunk_bytes],
                                    dtype=np.uint8)
                hits = np.flatnonzero(win == 10)
                del win  # release the export before the carry resize
                if hits.size == 0:
                    raise _LineTooLong
                cut = int(hits[-1]) + 1  # cut AFTER the last newline
            view = np.frombuffer(carry, dtype=np.uint8, count=cut)
            batch[row, :cut] = view
            n_nl = int(np.count_nonzero(view == 10))
            del view
            del carry[:cut]
            consumed += cut
            batch[row, cut:] = 0
            lens[row] = cut
            row_lines[row] = n_nl + (1 if batch[row, cut - 1] != 10 else 0)
            row += 1
            if row == n_dev:
                if offsets is not None:
                    offsets.append(consumed)
                yield batch, lens, row_lines
                batch = new_batch()
                lens = np.zeros(n_dev, dtype=np.int32)
                row_lines = np.zeros(n_dev, dtype=np.int64)
                row = 0

    for block in blocks:
        carry.extend(block)
        yield from fill_rows(final=False)
    yield from fill_rows(final=True)
    if row:
        batch[row:] = 0  # recycled buffer: stale tail rows must not count
        if offsets is not None:
            offsets.append(consumed)
        yield batch, lens, row_lines
    elif pool is not None:
        pool.give(batch)


# ── the grep step program ──────────────────────────────────────────────


def _grep_step_device(chunk, pat, dlen, base, *, l_cap: int, bins: int,
                      k: int, emit: bool = False):
    """Per-device step body (runs under shard_map): literal match mask
    (``len(pattern)`` shifted compares, the ``ops/grepk.py`` idiom) →
    per-line occurrence counts (cumsum line ids + segment-sum) →
    histogram, totals, and the top-k candidate rows in DeviceTable's
    packed (key lanes, len, count, part) layout with the GLOBAL line
    number (``base`` + local) as the kk=2 key.

    ``emit=True`` (the plan layer's stage handoff, ``dsi_tpu/plan``)
    additionally COMPACTS the matching lines' bytes to the front of a
    ``[n]`` output row (stable partition, zero tail) plus the kept byte
    count — the device-resident intermediate a downstream stage consumes
    without any host round-trip."""
    n = chunk.shape[-1]
    m = pat.shape[-1]
    chunk = chunk.reshape(-1)
    pat = pat.reshape(-1)
    dlen0 = dlen.reshape(())
    base0 = base.reshape(())

    match = jnp.ones(n, jnp.bool_)
    for j in range(m):  # static unroll over the (short) pattern
        match &= _shift_left(chunk, j) == pat[j]

    pos = jnp.arange(n, dtype=jnp.int32)
    valid = pos < dlen0
    is_nl = (chunk == 10) & valid
    nl_i32 = is_nl.astype(jnp.int32)
    line_id = jnp.cumsum(nl_i32) - nl_i32  # newlines strictly before i
    nl_total = jnp.sum(nl_i32)
    last = jnp.where(dlen0 > 0, chunk[jnp.maximum(dlen0 - 1, 0)],
                     jnp.uint8(10))
    n_lines = nl_total + jnp.where((dlen0 > 0) & (last != 10), 1, 0)
    overflow = n_lines > l_cap

    # Padding bytes are zeros and the pattern is printable ASCII, so a
    # match can neither start in nor extend into padding; occurrences
    # therefore attribute to real lines only.
    seg = jnp.minimum(line_id, l_cap)
    occ = jax.ops.segment_sum(match.astype(jnp.int32), seg,
                              num_segments=l_cap + 1,
                              indices_are_sorted=True)[:l_cap]
    lrange = jnp.arange(l_cap, dtype=jnp.int32)
    line_valid = lrange < n_lines
    occv = jnp.where(line_valid, occ, 0)
    matched = jnp.sum((occv > 0).astype(jnp.int32))
    occurrences = jnp.sum(occv)

    bucket = jnp.where(line_valid, jnp.minimum(occv, bins - 1), bins)
    hist = jax.ops.segment_sum(jnp.ones(l_cap, jnp.uint32), bucket,
                               num_segments=bins + 1)[:bins]
    hist_ext = jnp.concatenate(
        [hist, jnp.stack([n_lines, matched, occurrences]).astype(jnp.uint32)])

    # Top-k candidates among matched lines, (count desc, line asc): the
    # per-device pruning that keeps candidate folds k rows per step.
    is_cand = line_valid & (occ > 0)
    big = jnp.int32(0x7FFFFFFF)
    neg = jnp.where(is_cand, big - occv, big)
    sneg, slid = lax.sort((neg, lrange), num_keys=2)
    top_occ = jnp.where(sneg[:k] < big, big - sneg[:k], 0)
    top_lid = slid[:k]
    n_cand = jnp.minimum(matched, k)
    cvalid = jnp.arange(k, dtype=jnp.int32) < n_cand
    with enable_x64(True):
        gline = base0 + top_lid.astype(jnp.uint64)
        hi = jnp.where(cvalid, (gline >> 32).astype(jnp.uint32),
                       jnp.uint32(0))
        lo = jnp.where(cvalid, gline.astype(jnp.uint32), jnp.uint32(0))
    cand = jnp.stack(
        [hi, lo,
         jnp.where(cvalid, jnp.uint32(8), jnp.uint32(0)),
         jnp.where(cvalid, top_occ.astype(jnp.uint32), jnp.uint32(0)),
         jnp.zeros(k, jnp.uint32)], axis=1)

    # Pin to int32: under the x64-scoped compile, literal-int promotion
    # would widen these to int64 and drift off the struct-warmed fold
    # program's [n_dev, 5] int32 contract (device/table._step_structs).
    scal = jnp.stack([n_cand, n_lines, overflow.astype(jnp.int32),
                      matched, occurrences]).astype(jnp.int32)
    if not emit:
        return hist_ext[None], cand[None], scal[None]
    # Matching-line compaction: keep every byte whose line matched (the
    # terminating newline included — a newline at position i has
    # line_id == its own line's id), stable-partition kept bytes to the
    # front (sort by (dropped, position) — order-preserving), zero the
    # tail.  Rows past l_cap attribute arbitrarily, but such a step
    # raises the overflow flag and replays wider before confirmation,
    # so a confirmed emit is always exact.
    keep = valid & (jnp.take(occv, jnp.minimum(line_id, l_cap - 1)) > 0)
    keep_inv = jnp.where(keep, jnp.int32(0), jnp.int32(1))
    _, _, comp = lax.sort((keep_inv, pos, chunk), num_keys=2)
    kept_n = jnp.sum(keep.astype(jnp.int32))
    comp = jnp.where(pos < kept_n, comp, 0)
    return (hist_ext[None], cand[None], scal[None], comp[None],
            kept_n.reshape(1))


def _grep_step_impl(chunks, pats, lens, bases, *, l_cap: int, bins: int,
                    k: int, mesh: Mesh, emit: bool = False):
    body = functools.partial(_grep_step_device, l_cap=l_cap, bins=bins,
                             k=k, emit=emit)
    out_specs = (P(AXIS, None), P(AXIS, None, None), P(AXIS, None))
    if emit:
        out_specs += (P(AXIS, None), P(AXIS))
    return shard_map(
        body, mesh=mesh,
        in_specs=(P(AXIS, None), P(AXIS, None), P(AXIS), P(AXIS)),
        out_specs=out_specs,
    )(chunks, pats, lens, bases)


def _grep_program(*, n_dev: int, chunk_bytes: int, m: int, l_cap: int,
                  bins: int, k: int, mesh: Mesh, emit: bool = False):
    """(name, fn) for one compiled grep step shape — single definition
    shared by the run, the warmer, and the cache-existence probe (the
    ``streaming._step_program`` discipline).  The emit variant (the plan
    handoff's extra compaction outputs) is a distinct executable and
    gets a distinct name."""

    def fn(chunks, pats, lens, bases):
        return _grep_step_impl(chunks, pats, lens, bases, l_cap=l_cap,
                               bins=bins, k=k, mesh=mesh, emit=emit)

    fn._aot_code_deps = (_wc_mod, _grepk_mod)
    name = (f"grep_stream_d{n_dev}_c{chunk_bytes}_m{m}_l{l_cap}"
            f"_b{bins}_t{k}" + ("_em" if emit else ""))
    return name, fn


def _grep_examples(n_dev: int, chunk_bytes: int, m: int):
    sds = jax.ShapeDtypeStruct
    return (sds((n_dev, chunk_bytes), jnp.uint8),
            sds((n_dev, m), jnp.uint8),
            sds((n_dev,), jnp.int32),
            sds((n_dev,), jnp.uint64))


def _grep_fn(example_args, **kw):
    """Compiled grep step via the persistent AOT executable cache —
    serialized loads for fresh single-device axon processes, per-shape
    memo on the virtual multi-device mesh (the ``tfidf._wave_fn``
    rationale)."""
    from dsi_tpu.backends import aotcache

    name, fn = _grep_program(**kw)
    with _quiet_unusable_donation():  # a cold entry compiles right here
        return aotcache.cached_compile(name, fn, example_args,
                                       donate_argnums=_GREP_DONATE,
                                       x64=True)


# ── grep engine ────────────────────────────────────────────────────────


class GrepStreamResult(NamedTuple):
    """Whole-stream grep statistics.  ``hist[b]`` is the number of lines
    with ``min(occurrences, bins-1) == b``; ``topk`` is ``((line_no,
    occ), ...)`` count desc, line asc — exact, not approximate."""

    lines: int
    matched: int
    occurrences: int
    hist: Tuple[int, ...]
    topk: Tuple[Tuple[int, int], ...]


def _count_occurrences(line: bytes, pat: bytes) -> int:
    """Overlapping occurrence count — the engine counts every position
    where the pattern starts, so the oracle must too (``bytes.count`` is
    non-overlapping and would disagree on self-overlapping patterns)."""
    n = 0
    i = line.find(pat)
    while i >= 0:
        n += 1
        i = line.find(pat, i + 1)
    return n


def grep_host_oracle(blocks: Iterable[bytes], pattern: str, *,
                     bins: int = GREP_BINS,
                     topk: int = DEFAULT_TOPK) -> GrepStreamResult:
    """Single-pass host oracle with the engine's exact semantics — the
    parity ground truth for the bench row, the CLI ``--check``, and the
    test grid (one definition so the three cannot drift)."""
    pat = pattern.encode("ascii")
    hist = [0] * bins
    matched = occurrences = line_no = 0
    cands: List[Tuple[int, int]] = []
    carry = b""

    def take(line: bytes) -> None:
        nonlocal matched, occurrences, line_no
        occ = _count_occurrences(line, pat)
        hist[min(occ, bins - 1)] += 1
        if occ:
            matched += 1
            occurrences += occ
            cands.append((line_no, occ))
        line_no += 1

    for block in blocks:
        parts = (carry + bytes(block)).split(b"\n")
        carry = parts.pop()  # the unterminated tail stays pending
        for line in parts:
            take(line)
    if carry:
        take(carry)  # a final line without a trailing newline
    top = tuple(sorted(cands, key=lambda r: (-r[1], r[0]))[:topk])
    return GrepStreamResult(line_no, matched, occurrences, tuple(hist), top)


def merge_topk(cands: Iterable[Tuple[int, int]],
               k: int) -> Tuple[Tuple[int, int], ...]:
    """Exact global top-k from a union of per-step top-k candidate
    lists (``(line_no, occurrences)`` pairs, line numbers disjoint
    across steps).  Exact because any line in the global top-k is, with
    the same ``k``, necessarily in its own step's top-k: a step holding
    ``k`` lines that all beat it would beat it globally too.  One
    definition shared by the packed serving lanes and their tests."""
    return tuple(sorted(cands, key=lambda r: (-r[1], r[0]))[:k])


def grep_pack_fn(n_dev: int, chunk_bytes: int, m: int, l_cap: int, *,
                 bins: int = GREP_BINS, k: int = DEFAULT_TOPK,
                 mesh: Mesh):
    """The compiled packed-grep step for one ``(shape, rung)`` — the
    serving packer's entry (``serve/pack.py PackedGrepScheduler``) to
    the per-row grep program.  The kernel body runs per device row
    under ``shard_map`` with no collectives, so each row may carry a
    DIFFERENT pattern of the same length ``m``: K tenants whose
    patterns share a length share one executable and one dispatch.
    Same persistent-AOT cache entry the streaming engine uses — a
    daemon and a one-shot CLI warm each other."""
    return _grep_fn(_grep_examples(n_dev, chunk_bytes, m), n_dev=n_dev,
                    chunk_bytes=chunk_bytes, m=m, l_cap=l_cap, bins=bins,
                    k=k, mesh=mesh)


class GrepStep(EngineStep):
    """Resumable step object over the streaming grep engine — the
    ``{advance, confirm, checkpoint, restore, close}`` lifecycle
    (``parallel/stepobj.py``) with :func:`grep_streaming`'s parameters
    and semantics.  A non-literal pattern routes to the host path at
    construction (the object is already terminal, ``close()`` → None);
    ``resume=True`` restores the newest valid chain before the first
    dispatch.

    ``line_sink`` (the plan layer's stage handoff, ``dsi_tpu/plan``) is
    a relay — :class:`~dsi_tpu.device.relay.DeviceRelay` or
    :class:`~dsi_tpu.device.relay.HostRelay` — receiving every confirmed
    step's compacted matching-line bytes via ``append(comp, kept)``:
    the step program grows the emit outputs and the downstream stage's
    upload becomes this stage's device-resident output."""

    def __init__(self, blocks: Iterable[bytes], pattern: str,
                 mesh: Mesh | None = None, chunk_bytes: int = 1 << 20,
                 depth: Optional[int] = None, aot: bool = False,
                 device_accumulate: bool = False,
                 sync_every: Optional[int] = None,
                 mesh_shards: Optional[int] = None,
                 topk: int = DEFAULT_TOPK, bins: int = GREP_BINS,
                 pipeline_stats: Optional[dict] = None,
                 checkpoint_dir: Optional[str] = None,
                 checkpoint_every: Optional[int] = None,
                 checkpoint_async: Optional[bool] = None,
                 checkpoint_delta: Optional[bool] = None,
                 resume: bool = False, line_sink=None,
                 input_range: Optional[Tuple[int, int]] = None):
        super().__init__()
        _grep_setup(self, blocks, pattern, mesh, chunk_bytes, depth, aot,
                    device_accumulate, sync_every, mesh_shards, topk,
                    bins, pipeline_stats, checkpoint_dir,
                    checkpoint_every, checkpoint_async, checkpoint_delta,
                    resume, line_sink, input_range)


def grep_streaming(
        blocks: Iterable[bytes], pattern: str, mesh: Mesh | None = None,
        chunk_bytes: int = 1 << 20, depth: Optional[int] = None,
        aot: bool = False, device_accumulate: bool = False,
        sync_every: Optional[int] = None,
        mesh_shards: Optional[int] = None, topk: int = DEFAULT_TOPK,
        bins: int = GREP_BINS, pipeline_stats: Optional[dict] = None,
        checkpoint_dir: Optional[str] = None,
        checkpoint_every: Optional[int] = None,
        checkpoint_async: Optional[bool] = None,
        checkpoint_delta: Optional[bool] = None, resume: bool = False,
) -> Optional[GrepStreamResult]:
    """Whole-stream literal grep with bounded memory, pipelined.

    Returns a :class:`GrepStreamResult`, or None when the stream needs
    the host path (non-literal pattern, or a line wider than
    ``chunk_bytes``).  Every step runs one compiled program per
    ``l_cap`` rung; a step whose line count overflows the optimistic
    rung (average line >= 8 bytes) is detected ``depth - 1`` steps late
    and replays exactly that step at the ``n + 1`` hard-bound rung —
    which then STICKS for every later step (``ops/grepk.line_cap_rungs``
    escalation as pipeline replay, not host fallback).  Results are
    bit-identical to ``depth=1`` because the accumulators only ever
    ingest confirmed per-step tensors, which the replay reproduces
    exactly (occurrence counts do not depend on the rung).

    ``device_accumulate=True`` folds each confirmed step's histogram
    vector into a persistent :class:`DeviceHistogram` and its top-k
    candidate rows into a :class:`DeviceTopK` (lag = pipeline depth),
    pulling only a top-k snapshot + the histogram vector every
    ``sync_every`` folds (``DSI_STREAM_SYNC_EVERY`` default) plus the
    final close drain — ``step_pulls`` drops to 0 and ``sync_pulls``
    counts the K-fold windows (+1 close), with ``widens`` the
    drain→realloc×4→re-fold recoveries of a candidate table that
    outgrew its rung.  Results stay bit-identical: histogram folds are
    exact uint64 adds, candidate keys (global line numbers) are unique,
    and the close drain hands the host the complete multiset the
    per-step path would have pulled.

    ``mesh_shards`` (default ``DSI_STREAM_MESH_SHARDS``, 0 = off;
    implies ``device_accumulate``) mesh-shards both services: candidate
    folds route line keys by ``ihash % n_shards`` with an in-program
    all-to-all (per-shard widens, ``shard_widens``/``shard_imbalance``)
    and histogram pulls pre-merge on device to one ``[slots]`` vector.
    Results stay bit-identical.

    ``pipeline_stats`` mirrors ``wordcount_streaming``'s dict
    (``batch_s``/``batch_wait_s``/``upload_s``/``kernel_s``/``pull_s``/
    ``merge_s``/``replay_s``, ``steps``/``replays``/``step_pulls``/
    ``sync_pulls``/``l_cap`` plus the service counters).

    ``checkpoint_dir``/``checkpoint_every``/``resume`` follow the
    ``wordcount_streaming`` crash-resume contract (``dsi_tpu/ckpt``):
    snapshots at confirmed-step boundaries carry the host accumulators
    (or the device histogram/top-k images), the global line counter,
    the sticky ``l_cap`` rung, and the byte cursor; resumed output is
    bit-identical to an uninterrupted run.  ``checkpoint_async`` /
    ``checkpoint_delta`` (env twins ``DSI_STREAM_CKPT_ASYNC`` /
    ``DSI_STREAM_CKPT_DELTA``, both default off = bit-identical PR-5
    behavior) follow the ``wordcount_streaming`` capture/commit and
    incremental-save contracts: an async save captures at the boundary
    and commits in the background writer; a delta save ships only the
    candidate rows appended since the previous save (the histogram is
    cumulative KBs and rides every delta whole, newest-wins).
    """
    return GrepStep(
        blocks, pattern, mesh=mesh, chunk_bytes=chunk_bytes, depth=depth,
        aot=aot, device_accumulate=device_accumulate,
        sync_every=sync_every, mesh_shards=mesh_shards, topk=topk,
        bins=bins, pipeline_stats=pipeline_stats,
        checkpoint_dir=checkpoint_dir, checkpoint_every=checkpoint_every,
        checkpoint_async=checkpoint_async,
        checkpoint_delta=checkpoint_delta, resume=resume).close()


def _grep_setup(step, blocks, pattern, mesh, chunk_bytes, depth, aot,
                device_accumulate, sync_every, mesh_shards, topk, bins,
                pipeline_stats, checkpoint_dir, checkpoint_every,
                checkpoint_async, checkpoint_delta, resume,
                line_sink=None, input_range=None):
    """The engine body behind :class:`GrepStep`: full setup (resume
    restore included) ending with the pipeline armed and the lifecycle
    hooks attached to ``step``."""
    emit = line_sink is not None
    if emit and checkpoint_dir:
        # The relay's content is not part of the engine checkpoint, so a
        # mid-stage resume would drop already-emitted lines; chains
        # commit at stage boundaries instead (plan/driver.py).
        raise ValueError("line_sink and checkpoint_dir are exclusive: "
                         "chained stages commit at stage boundaries")
    if not is_literal_pattern(pattern):
        step._phase = "hostpath"  # terminal before any device work
        return
    if mesh is None:
        mesh = default_mesh()
    n_dev = mesh.devices.size
    depth = pipeline_depth(depth)
    m = len(pattern)
    rungs = line_cap_rungs(chunk_bytes)
    state = {"l_cap": rungs[0]}
    # Registry scope (dsi_tpu/obs): grep_phases is a view over the one
    # schema, not its own dialect.
    stats = metrics_scope("grep")
    stats.update({"depth": depth, "steps": 0, "replays": 0,
                  "step_pulls": 0, "sync_pulls": 0,
                  "device_accumulate": device_accumulate,
                  "l_cap": rungs[0], "batch_s": 0.0, "batch_wait_s": 0.0,
                  "upload_s": 0.0, "kernel_s": 0.0, "pull_s": 0.0,
                  "merge_s": 0.0, "replay_s": 0.0})
    sh2 = NamedSharding(mesh, P(AXIS, None))
    sh1 = NamedSharding(mesh, P(AXIS))
    pat_np = np.tile(np.frombuffer(pattern.encode("ascii"), np.uint8),
                     (n_dev, 1))
    pat_dev = jax.device_put(pat_np, sh2)  # once per stream, never donated
    pool = BufferPool((n_dev, chunk_bytes), retain=2 * depth + 3)
    next_line = [0]

    # Host-merge accumulators (the depth=1-equivalent path).
    hist_h = np.zeros(bins, dtype=np.int64)
    totals = np.zeros(3, dtype=np.int64)  # lines, matched, occurrences
    cand_h: List[Tuple[int, int]] = []

    # Device services.  ``mesh_shards`` makes them mesh-sharded
    # (device/table.py module docs): candidate keys — global line
    # numbers — route to ``ihash % n_shards`` inside the fold, the
    # top-k widen goes per-shard, and the histogram pull pre-merges on
    # device (one [slots] vector instead of n_dev partials).
    mesh_shards = mesh_shards_default(mesh_shards)
    if mesh_shards:
        device_accumulate = True
        stats["device_accumulate"] = True
    acc = KeyCounts()
    hist_svc: Optional[DeviceHistogram] = None
    topk_svc: Optional[DeviceTopK] = None
    policy: Optional[SyncPolicy] = None
    if device_accumulate:
        policy = SyncPolicy(sync_every)
        stats["sync_every"] = policy.sync_every
        stats["mesh_shards"] = mesh_shards
        hist_svc = DeviceHistogram(mesh, slots=bins + 3, aot=aot,
                                   stats=stats, mesh_shards=mesh_shards)
        topk_svc = DeviceTopK(mesh, kk=2, cap=_default_topk_cap(n_dev, topk),
                              k=topk, acc=acc, aot=aot,
                              lag=max(0, depth - 1), stats=stats,
                              mesh_shards=mesh_shards)

    # ── checkpoint/restore (dsi_tpu/ckpt) ──
    ck_store: Optional[CheckpointStore] = None
    ck_policy: Optional[CheckpointPolicy] = None
    ck_writer: Optional[CheckpointWriter] = None
    ck_cursor = {"offset": 0, "lines": 0}
    offsets: Optional[list] = None
    dispatch_idx = [0]
    start_offset = 0
    ck_async = checkpoint_async_default(checkpoint_async)
    ck_delta = checkpoint_delta_default(checkpoint_delta)
    cand_mark = [0]  # non-dacc delta watermark into the cand_h append log
    if checkpoint_dir:
        # input_range = the shard scheduler's cursor range: part of the
        # chain identity so a shard attempt can never restore another
        # range's (range-relative) cursors (mr/shards.py).
        ident = {"n_dev": n_dev, "chunk_bytes": chunk_bytes,
                 "pattern": pattern, "bins": bins, "topk": topk,
                 "device_accumulate": bool(device_accumulate)}
        if input_range is not None:
            ident["input_range"] = [int(input_range[0]),
                                    int(input_range[1])]
        ck_store = CheckpointStore(checkpoint_dir, "grep", ident)
        ck_policy = CheckpointPolicy(checkpoint_every)
        offsets = []
        stats.update({"ckpt_saves": 0, "ckpt_s": 0.0,
                      "ckpt_every": ck_policy.every,
                      "ckpt_capture_s": 0.0,
                      "ckpt_async": ck_async, "ckpt_delta": ck_delta})
        ck_writer = CheckpointWriter(ck_store, stats, async_=ck_async,
                                     delta=ck_delta)
        if ck_delta and topk_svc is not None:
            topk_svc.enable_delta()
        if resume:
            t_res = time.perf_counter()
            loaded = ck_store.load_latest_chain()
            if loaded is not None:
                meta, arrays, deltas = loaded
                # Cursor/rung state is newest-wins: the final delta's
                # meta IS the restore point; the base meta only names
                # the image shapes.
                eff = deltas[-1][0] if deltas else meta
                start_offset = int(eff["cursor"])
                ck_cursor.update(offset=start_offset,
                                 lines=int(eff["lines"]))
                next_line[0] = int(eff["lines"])
                state["l_cap"] = int(eff["l_cap"])
                stats["l_cap"] = state["l_cap"]
                if device_accumulate:
                    acc.restore({k[3:]: v for k, v in arrays.items()
                                 if k.startswith("kc_")})
                    # The histogram vector is cumulative and rides
                    # every delta whole: the newest copy wins.
                    hist_img = arrays.get("hist")
                    for _, darr in deltas:
                        if "hist" in darr:
                            hist_img = darr["hist"]
                    if hist_img is not None:
                        hist_svc.restore_state({"hist": hist_img})
                    if meta.get("table_cap"):
                        img = {k[6:]: v for k, v in arrays.items()
                               if k.startswith("table_")}
                        same_degree = (int(meta.get("mesh_shards", 0))
                                       == mesh_shards)
                        if deltas or not same_degree:
                            # Chain restore (and the sharding-degree
                            # change) re-enters via the drain path:
                            # the image's merged rows flow into the
                            # KeyCounts accumulator, the candidate
                            # table starts empty, and the resumed
                            # folds rebuild device state.
                            DeviceTable.drain_image(acc, img)
                            if not same_degree:
                                stats["resharded_resume"] = int(
                                    meta.get("mesh_shards", 0))
                        else:
                            topk_svc.restore_state(img)
                            if ck_delta:
                                topk_svc.enable_delta()
                    policy.restore(eff.get("sync_since", 0))
                    for _, darr in deltas:
                        # Each delta's retained candidate steps re-enter
                        # the accumulator in save order — the drain-path
                        # argument, same as the cross-degree resume.
                        drain_packed_steps(acc, darr)
                else:
                    if "gs_hist" in arrays:
                        hist_h[:] = arrays["gs_hist"]
                        totals[:] = arrays["gs_totals"]
                    if "gs_cands" in arrays:
                        cand_h.extend(
                            (int(a), int(b))
                            for a, b in arrays["gs_cands"].tolist())
                    for _, darr in deltas:
                        # Cumulative counters newest-wins; candidate
                        # rows are the append-only log's increments.
                        hist_h[:] = darr["gs_hist"]
                        totals[:] = darr["gs_totals"]
                        if "gs_cands" in darr:
                            cand_h.extend(
                                (int(a), int(b))
                                for a, b in darr["gs_cands"].tolist())
                    cand_mark[0] = len(cand_h)
            stats["resume_gap_s"] = round(time.perf_counter() - t_res, 4)
            stats["resume_cursor"] = start_offset
        else:
            ck_store.reset()

    def save_ckpt() -> None:
        """Consistent snapshot at a confirmed-step boundary — capture
        here (device images first: flushing the top-k lag can widen,
        whose drain lands in the KeyCounts accumulator; host residue
        second), commit inline or in the background writer
        (``ckpt/writer.py``).  A delta save ships the candidate rows
        appended since the previous save plus the cumulative histogram
        vector (KBs — newest copy wins on restore); every
        ``DSI_STREAM_CKPT_REBASE``-th save is a full re-base (an
        invalid delta window forces one)."""
        with _span("ckpt", stats=stats, key="ckpt_s",
                   lines=ck_cursor["lines"]):
            meta = {"cursor": ck_cursor["offset"],
                    "lines": ck_cursor["lines"], "l_cap": state["l_cap"]}
            kind = "full"
            parts = None
            with _span("ckpt_capture", lane="ckpt", stats=stats,
                       key="ckpt_capture_s"):
                if ck_writer.want_delta():
                    if device_accumulate:
                        entries = topk_svc.take_delta()
                        if entries is not None:
                            parts = [("", DeltaSteps(entries)),
                                     ("", {"hist": hist_svc
                                           .checkpoint_state()["hist"]})]
                            meta["sync_since"] = policy.snapshot()
                            kind = "delta"
                    else:
                        new_cands = cand_h[cand_mark[0]:]
                        cand_mark[0] = len(cand_h)
                        d_arrays = {"gs_hist": hist_h.copy(),
                                    "gs_totals": totals.copy()}
                        if new_cands:
                            d_arrays["gs_cands"] = np.array(new_cands,
                                                            dtype=np.int64)
                        parts = [("", d_arrays)]
                        kind = "delta"
                if parts is None:
                    # Full image — the PR-5 arrays (device pulls
                    # dispatched, not awaited), and a fresh delta
                    # window: payloads recorded before this base are in
                    # the image, so the logs reset here.
                    parts = []
                    if device_accumulate:
                        parts.append(("table_",
                                      topk_svc.checkpoint_capture()))
                        meta["table_cap"] = topk_svc.cap
                        meta["table_kk"] = topk_svc.kk
                        meta["mesh_shards"] = topk_svc.mesh_shards
                        parts.append(("", hist_svc.checkpoint_capture()))
                        parts.append(("kc_", acc.snapshot()))
                        meta["sync_since"] = policy.snapshot()
                        if ck_delta:
                            topk_svc.take_delta()
                    else:
                        arrays = {"gs_hist": hist_h.copy(),
                                  "gs_totals": totals.copy()}
                        if cand_h:
                            arrays["gs_cands"] = np.array(cand_h,
                                                          dtype=np.int64)
                        parts.append(("", arrays))
                    cand_mark[0] = len(cand_h)
            fault_point("mid-capture")
            ck_writer.commit(parts, meta, kind=kind)

    def step_call(buf, lens_np, bases_np, l_cap):
        with _span("upload", stats=stats, key="upload_s",
                   step=stats["steps"]):
            chunks = jax.device_put(buf, sh2)
            lens = jax.device_put(lens_np, sh1)
            with enable_x64(True):  # keep the u64 bases u64 through it
                bases = jax.device_put(bases_np.astype(np.uint64), sh1)
        fn = _grep_fn((chunks, pat_dev, lens, bases), n_dev=n_dev,
                      chunk_bytes=chunk_bytes, m=m, l_cap=l_cap, bins=bins,
                      k=topk, mesh=mesh, emit=emit)
        with _quiet_unusable_donation():
            outs = fn(chunks, pat_dev, lens, bases)
        if emit:
            return outs  # (hist, cand, scal, comp, kept)
        return outs + (None, None)

    def dispatch(item):
        buf, lens_np, row_lines = item
        bases = np.zeros(n_dev, dtype=np.int64)
        bases[0] = next_line[0]
        np.cumsum(row_lines[:-1], out=bases[1:])
        bases[1:] += next_line[0]
        next_line[0] += int(row_lines.sum())
        hist_d, cand_d, scal, comp_d, kept_d = step_call(
            buf, lens_np, bases, state["l_cap"])
        stats["steps"] += 1
        rec_offset = 0
        if offsets is not None:
            rec_offset = start_offset + offsets[dispatch_idx[0]]
            dispatch_idx[0] += 1
        fault_point("post-dispatch")
        return (buf, lens_np, row_lines, bases, state["l_cap"],
                hist_d, cand_d, scal, comp_d, kept_d, rec_offset,
                next_line[0])

    def replay_step(buf, lens_np, bases_np, used_l_cap):
        """Late-detected line-capacity overflow: replay just this step
        at the wider sticky rung.  Exactly-once — the optimistic
        attempt's tensors are dropped unmerged (the emit outputs
        included: occurrence counts and kept bytes do not depend on the
        rung, so the replay reproduces them exactly)."""
        stats["replays"] += 1
        with _span("replay", stats=stats, key="replay_s"):
            for l_cap in rungs:
                if l_cap <= used_l_cap:
                    continue
                hist_d, cand_d, scal, comp_d, kept_d = step_call(
                    buf, lens_np, bases_np, l_cap)
                scal_np = np.asarray(scal)
                if not scal_np[:, 2].any():
                    state["l_cap"] = max(state["l_cap"], l_cap)
                    stats["l_cap"] = state["l_cap"]
                    return hist_d, cand_d, scal, comp_d, kept_d, scal_np
        raise RuntimeError("grep l_cap ladder exhausted (n+1 must fit)")

    def finish_one(record) -> None:
        buf, lens_np, row_lines, bases_np, l_cap_used, hist_d, cand_d, \
            scal, comp_d, kept_d, rec_offset, rec_lines = record
        with _span("kernel", stats=stats, key="kernel_s"):
            scal_np = np.asarray(scal)  # blocks until the kernel lands
        if scal_np[:, 2].any():  # l_cap overflow: replay wider, sticky
            hist_d, cand_d, scal, comp_d, kept_d, scal_np = replay_step(
                buf, lens_np, bases_np, l_cap_used)
        if not np.array_equal(scal_np[:, 1].astype(np.int64), row_lines):
            # The global line numbering depends on host/device agreeing
            # on per-row line counts; a disagreement is an engine bug and
            # must fail loudly, never skew the keys silently.
            pool.give(buf)
            raise RuntimeError(
                f"host/device line-count disagreement: "
                f"{row_lines.tolist()} vs {scal_np[:, 1].tolist()}")
        if device_accumulate:
            hist_svc.fold(hist_d)
            if int(scal_np[:, 0].max()) > 0:
                topk_svc.fold(cand_d, scal, scal_np)
            policy.note_fold()
            if policy.due():
                fault_point("pre-sync")
                topk_svc.sync()
                hist_svc.pull()
                stats["sync_pulls"] += 1
                policy.reset()
        else:
            with _span("pull", stats=stats, key="pull_s"):
                hist_np = np.asarray(hist_d)
                cand_np = np.asarray(cand_d)
                stats["step_pulls"] += 1
            with _span("merge", stats=stats, key="merge_s"):
                hist_h[:] += hist_np[:, :bins].astype(np.int64).sum(axis=0)
                totals[:] += hist_np[:, bins:].astype(np.int64).sum(axis=0)
                for d in range(n_dev):
                    nc = int(scal_np[d, 0])
                    for i in range(nc):
                        line = (int(cand_np[d, i, 0]) << 32) | int(
                            cand_np[d, i, 1])
                        cand_h.append((line, int(cand_np[d, i, 3])))
        if emit:
            # The stage handoff: this confirmed step's compacted
            # matching-line bytes flow into the relay — device-resident
            # (DeviceRelay packs on device) or pulled (HostRelay, the
            # staged baseline).  The kept counts are the only host-side
            # metadata (n_dev int32s).
            kept_np = np.asarray(kept_d).astype(np.int64)
            line_sink.append(comp_d, kept_np)
        # Confirmed: merged/folded, nothing later is.  Fault before the
        # cursor advances — the torn-update instant.
        fault_point("mid-fold")
        if ck_store is not None:
            ck_cursor["offset"] = rec_offset
            ck_cursor["lines"] = rec_lines
            ck_policy.note_step()
            if ck_policy.due():
                save_ckpt()
                ck_policy.reset()
        pool.give(buf)

    pipe = StepPipeline(depth=depth, dispatch=dispatch, finish=finish_one,
                        stats=stats, produce_key="batch_s",
                        wait_key="batch_wait_s",
                        inflight_key="max_inflight_chunks",
                        thread_name="dsi-grep-batcher", engine="grep")

    feed = skip_stream(blocks, start_offset) if start_offset else blocks
    step._pipe = pipe
    step._cursor_ref = ck_cursor
    pipe.begin(lambda: batch_lines(feed, n_dev, chunk_bytes,
                                   pool=pool, offsets=offsets))
    step._host_excs = (_LineTooLong,)
    step._save = save_ckpt if ck_store is not None else None
    step._writer = ck_writer
    if resume:
        step._restore_info = {
            "resume_cursor": stats.get("resume_cursor", 0),
            "resume_gap_s": stats.get("resume_gap_s", 0.0)}

    def on_complete():
        h, t, cands = hist_h, totals, cand_h
        if device_accumulate:
            fault_point("pre-sync")
            topk_svc.close()  # the exact final drain into the KeyCounts
            final = hist_svc.close()
            h = final[:bins]
            t = final[bins:]
            cands = [(line, occ) for line, occ in acc.finalize().items()]
        if ck_writer is not None:
            ck_writer.drain()  # surface async commit errors; counters
            # settle before the caller reads them
        top = tuple(sorted(cands, key=lambda r: (-r[1], r[0]))[:topk])
        step.result = GrepStreamResult(int(t[0]), int(t[1]), int(t[2]),
                                       tuple(int(x) for x in h), top)

    released = []

    def release():
        if released:
            return
        released.append(True)
        if ck_writer is not None:
            ck_writer.shutdown()
        fold_source_stats(stats, blocks)
        if pipeline_stats is not None:
            stats["batch_allocs"] = pool.allocs
            for k in ("batch_s", "batch_wait_s", "upload_s", "kernel_s",
                      "pull_s", "merge_s", "replay_s", "fold_s", "sync_s",
                      "widen_s", "hist_s", "ckpt_s", "ckpt_capture_s",
                      "ckpt_commit_s", "ckpt_barrier_s",
                      "ckpt_compress_s"):
                if k in stats:
                    stats[k] = round(stats[k], 4)
            pipeline_stats.update(stats)

    step._on_complete = on_complete
    step._release = release


def warm_grepstream_aot(mesh: Mesh | None = None,
                        chunk_bytes: int = 1 << 20, pattern_len: int = 3,
                        bins: int = GREP_BINS, topk: int = DEFAULT_TOPK,
                        device_accumulate: bool = False,
                        mesh_shards: int = 0, emit: bool = False) -> None:
    """Compile + persist the grep step programs at BOTH ``l_cap`` rungs
    (the optimistic and the ``n + 1`` replay shape — an ungated
    escalation must load, never cold-compile) plus, with
    ``device_accumulate``, the top-k fold/snapshot and histogram fold
    shapes (the ``mesh_*`` shuffle-fold variants under ``mesh_shards``).
    ``emit`` additionally warms the plan handoff's ``*_em`` compaction
    variant (and the relay pack program at this chunk shape).  From
    shape structs alone; mirror of ``warm_stream_aot``."""
    if mesh is None:
        mesh = default_mesh()
    n_dev = mesh.devices.size
    examples = _grep_examples(n_dev, chunk_bytes, pattern_len)
    for l_cap in line_cap_rungs(chunk_bytes):
        _grep_fn(examples, n_dev=n_dev, chunk_bytes=chunk_bytes,
                 m=pattern_len, l_cap=l_cap, bins=bins, k=topk, mesh=mesh)
        if emit:
            _grep_fn(examples, n_dev=n_dev, chunk_bytes=chunk_bytes,
                     m=pattern_len, l_cap=l_cap, bins=bins, k=topk,
                     mesh=mesh, emit=True)
    if emit:
        from dsi_tpu.device.relay import _pack_fn

        _pack_fn(True, n_dev=n_dev, cap=chunk_bytes)
    if device_accumulate:
        from dsi_tpu.device.topk import warm_histogram, warm_topk_service

        warm_topk_service(mesh, kk=2, rows=topk,
                          cap=_default_topk_cap(n_dev, topk), k=topk,
                          table_rungs=2, mesh_shards=mesh_shards)
        warm_histogram(mesh, slots=bins + 3, mesh_shards=mesh_shards)


def grepstream_persisted(mesh: Mesh | None = None,
                         chunk_bytes: int = 1 << 20, pattern_len: int = 3,
                         bins: int = GREP_BINS, topk: int = DEFAULT_TOPK,
                         device_accumulate: bool = False,
                         mesh_shards: int = 0, emit: bool = False) -> bool:
    """True when every program a ``grep_streaming`` run at these shapes
    can reach (both ``l_cap`` rungs; plus the device services', keyed on
    the ``mesh_*`` variants under ``mesh_shards``) is in the persistent
    AOT cache — the bench grep row's cold-compile gate, same discipline
    as ``stream_programs_persisted``."""
    from dsi_tpu.backends.aotcache import is_persisted

    if mesh is None:
        mesh = default_mesh()
    n_dev = mesh.devices.size
    examples = _grep_examples(n_dev, chunk_bytes, pattern_len)
    for l_cap in line_cap_rungs(chunk_bytes):
        for em in ((False, True) if emit else (False,)):
            name, fn = _grep_program(n_dev=n_dev, chunk_bytes=chunk_bytes,
                                     m=pattern_len, l_cap=l_cap, bins=bins,
                                     k=topk, mesh=mesh, emit=em)
            if not is_persisted(name, fn, examples,
                                donate_argnums=_GREP_DONATE):
                return False
    if emit:
        from dsi_tpu.device.relay import (_RELAY_DONATE,
                                          _relay_pack_program,
                                          _relay_structs)

        name, fn = _relay_pack_program(n_dev=n_dev, cap=chunk_bytes)
        if not is_persisted(name, fn, _relay_structs(n_dev, chunk_bytes),
                            donate_argnums=_RELAY_DONATE):
            return False
    if device_accumulate:
        from dsi_tpu.device.topk import (histogram_persisted,
                                         topk_service_persisted)

        if not topk_service_persisted(mesh, kk=2, rows=topk,
                                      cap=_default_topk_cap(n_dev, topk),
                                      k=topk, mesh_shards=mesh_shards):
            return False
        if not histogram_persisted(mesh, slots=bins + 3,
                                   mesh_shards=mesh_shards):
            return False
    return True


# ── the indexer posting step ───────────────────────────────────────────


def _idx_device_step(chunk: jax.Array, doc_id: jax.Array, *, n_dev: int,
                     n_reduce: int, max_word_len: int, u_cap: int,
                     t_cap_frac: int, grouper: str = "sort"):
    """Per-device wave body: the word-count map prologue over its
    document with a (tf ≡ 1, doc, part) payload — one posting row per
    distinct word per document — routed by the shared shuffle primitive
    and partitioned valid-first, exactly the TF-IDF wave discipline
    minus the term frequency.  A second output carries the received
    rows with the doc lane dropped: DeviceTable's packed (keys, len,
    count, part) layout with count ≡ 1, i.e. the wave's
    document-frequency increments ready to fold into the top-k table."""
    k = max_word_len // 4
    chunk = chunk.reshape(-1)
    doc = doc_id.reshape(())

    packed_u, len_u, cnt_u, part, dest, (
        n_unique, max_len, has_high, token_overflow) = map_prologue(
        chunk, n_dev=n_dev, n_reduce=n_reduce, max_word_len=max_word_len,
        u_cap=u_cap, t_cap_frac=t_cap_frac, grouper=grouper)

    rows = jnp.concatenate(
        [packed_u, len_u[:, None].astype(jnp.uint32),
         jnp.ones((u_cap, 1), jnp.uint32),
         jnp.broadcast_to(doc.astype(jnp.uint32), (u_cap,))[:, None],
         part[:, None]], axis=1)
    recv = shuffle_rows(rows, dest, n_dev=n_dev, u_cap=u_cap, k=k)

    with enable_x64(True):  # every op touching u64 operands needs it
        keys64 = pack_key_lanes(tuple(recv[:, j] for j in range(k)))
        pay64 = pack_key_lanes(tuple(recv[:, k + j] for j in range(4)))
        k64 = len(keys64)
        is_pad = (keys64[0] == jnp.array(_PAD_KEY64, jnp.uint64)) \
            .astype(jnp.uint8)
        sorted_cols = lax.sort((is_pad,) + keys64 + pay64, num_keys=1)
        srecv = jnp.stack(
            unpack_key_lanes(sorted_cols[1:1 + k64], k)
            + unpack_key_lanes(sorted_cols[1 + k64:], 4), axis=1)
    n_rows = jnp.sum(sorted_cols[0] == 0, dtype=jnp.int32)

    df = jnp.concatenate([srecv[:, :k + 2], srecv[:, k + 3:k + 4]], axis=1)
    scalars = jnp.stack([n_rows, n_unique, max_len,
                         has_high.astype(jnp.int32),
                         token_overflow.astype(jnp.int32)]) \
        .astype(jnp.int32)  # x64 literal promotion must not widen these
    return srecv[None], df[None], scalars[None]


def _idx_wave_step_impl(chunks, doc_ids, *, n_dev: int, n_reduce: int,
                        max_word_len: int, u_cap: int, mesh: Mesh,
                        t_cap_frac: int = 4, grouper: str = "sort"):
    body = functools.partial(_idx_device_step, n_dev=n_dev,
                             n_reduce=n_reduce, max_word_len=max_word_len,
                             u_cap=u_cap, t_cap_frac=t_cap_frac,
                             grouper=grouper)
    return shard_map(
        body, mesh=mesh,
        in_specs=(P(AXIS, None), P(AXIS)),
        out_specs=(P(AXIS, None, None), P(AXIS, None, None),
                   P(AXIS, None)))(chunks, doc_ids)


#: jax.jit donate_argnums for the wave program (chunk consumed; the tiny
#: doc-id vector is not worth donating) — the TF-IDF wave's contract.
_IDX_DONATE = (0,)


def _idx_program(*, n_dev: int, n_reduce: int, max_word_len: int,
                 u_cap: int, size: int, mesh: Mesh, t_cap_frac: int,
                 grouper: str = "sort"):
    from dsi_tpu.ops.wordcount import grouper_suffix

    def fn(chunk, ids):
        return _idx_wave_step_impl(chunk, ids, n_dev=n_dev,
                                   n_reduce=n_reduce,
                                   max_word_len=max_word_len, u_cap=u_cap,
                                   mesh=mesh, t_cap_frac=t_cap_frac,
                                   grouper=grouper)

    fn._aot_code_deps = (_wc_mod, _sh_mod)
    name = (f"idx_wave_d{n_dev}_r{n_reduce}_w{max_word_len}"
            f"_u{u_cap}_s{size}_f{t_cap_frac}")
    name += grouper_suffix(grouper)
    return name, fn


def _idx_fn(example_args, **kw):
    from dsi_tpu.backends import aotcache

    name, fn = _idx_program(**kw)
    with _quiet_unusable_donation():
        return aotcache.cached_compile(name, fn, example_args,
                                       donate_argnums=_IDX_DONATE,
                                       x64=True)


class _AbortRung(Exception):
    """A wave proved this word-window rung's results will be discarded
    (non-ASCII input, or a word wider than the packed window)."""


class IndexerStep(EngineStep):
    """Resumable step object over the streaming indexer's wave walk —
    :func:`indexer_streaming`'s parameters and semantics behind the
    ``{advance, confirm, checkpoint, restore, close}`` lifecycle.  The
    word-window rung ladder lives INSIDE the lifecycle: a wave proving
    the rung too narrow tears it down and ``advance()`` transparently
    restarts at the 64-byte rung; non-ASCII input (or a word wider than
    64 bytes) routes to the host path (``close()`` → None).

    ``keep_services=True`` (the plan layer's stage handoff) completes
    the walk WITHOUT draining the device services: ``exported`` then
    carries the live :class:`DeviceTopK` df table, the
    :class:`DevicePostings` buffer, and the host accumulators, so a
    downstream stage can take a k-row df snapshot (no drain-to-host)
    and a selective postings join instead of the full materialization;
    ``result`` is a handoff marker, not the (postings, topk) tuple."""

    _rung_excs = (_AbortRung,)

    def __init__(self, docs: Sequence[bytes], mesh: Mesh | None = None,
                 n_reduce: int = 10, max_word_len: int = 16,
                 u_cap: int = 1 << 15, depth: Optional[int] = None,
                 device_accumulate: bool = False,
                 sync_every: Optional[int] = None,
                 mesh_shards: Optional[int] = None,
                 topk: int = DEFAULT_TOPK, stats: Optional[dict] = None,
                 checkpoint_dir: Optional[str] = None,
                 checkpoint_every: Optional[int] = None,
                 checkpoint_async: Optional[bool] = None,
                 checkpoint_delta: Optional[bool] = None,
                 resume: bool = False, keep_services: bool = False,
                 input_range: Optional[Tuple[int, int]] = None):
        super().__init__()
        _indexer_setup(self, docs, mesh, n_reduce, max_word_len, u_cap,
                       depth, device_accumulate, sync_every, mesh_shards,
                       topk, stats, checkpoint_dir, checkpoint_every,
                       checkpoint_async, checkpoint_delta, resume,
                       keep_services, input_range)

    def _next_rung(self) -> bool:
        self._pipe.end()
        if self._writer is not None:
            self._writer.shutdown()  # a rung restart discards rung state
        if not self._outcome["high"]:
            nxt = [m for m in self._rungs if m > self._mwl]
            if nxt:
                self._begin_rung(nxt[0])
                return True
        # Non-ASCII, or a word wider than 64 bytes: the host path's job.
        self.result = None
        self._phase = "hostpath"
        return False


def indexer_streaming(
        docs: Sequence[bytes], mesh: Mesh | None = None, n_reduce: int = 10,
        max_word_len: int = 16, u_cap: int = 1 << 15,
        depth: Optional[int] = None, device_accumulate: bool = False,
        sync_every: Optional[int] = None,
        mesh_shards: Optional[int] = None, topk: int = DEFAULT_TOPK,
        stats: Optional[dict] = None,
        checkpoint_dir: Optional[str] = None,
        checkpoint_every: Optional[int] = None,
        checkpoint_async: Optional[bool] = None,
        checkpoint_delta: Optional[bool] = None, resume: bool = False,
):
    """Whole-corpus inverted index over the mesh, waves of ``n_dev``
    documents, pipelined ``depth`` waves deep.

    Returns ``(postings, topk)`` where ``postings`` is ``{word: (part,
    [doc indices in wave order])}`` and ``topk`` is ``((df, word), ...)``
    — the k words with the highest document frequency, df desc, word asc
    — or None when any document needs the host path (non-ASCII bytes,
    words longer than 64).  Same exactness discipline as
    ``tfidf_sharded``: waves dispatch optimistically at a sticky
    (capacity, grouper, frac) rung, scalar checks are deferred until a
    wave leaves the window, a failed check replays exactly that wave,
    and a word wider than the packed window restarts the walk at the
    64-byte rung.

    ``device_accumulate=True`` appends each confirmed wave's posting
    rows into a persistent :class:`DevicePostings` buffer (the order-
    preserving sticky-overflow protocol from the TF-IDF walk) AND folds
    its document-frequency rows (count ≡ 1 per posting) into a
    :class:`DeviceTopK` table — the host sees postings once per
    ``sync_every`` waves and the df leaders as k-row snapshots, with
    the close drain completing the exact result.  Both the postings
    (including per-word posting order) and the top-k are bit-identical
    to the per-wave pull path.  ``mesh_shards`` (default
    ``DSI_STREAM_MESH_SHARDS``; implies ``device_accumulate``)
    re-routes both services by ``ihash(word) % n_shards`` inside their
    compiled programs — the mesh-sharded treatment, bit-identical
    output included.

    ``checkpoint_dir``/``checkpoint_every``/``resume`` follow the
    streaming engines' crash-resume contract (``dsi_tpu/ckpt``): the
    cursor is the CONFIRMED-wave ordinal (waves are planned
    deterministically from doc lengths, so skipping the first n waves
    on resume reproduces the walk), snapshots carry the postings table
    residue, the device buffers' drain-free images, and the sticky
    rung; the checkpoint records its word-window rung, and a rung that
    widens after resume simply restarts wider, exactly as the
    uninterrupted walk would.  Resumed postings (incl. per-word order)
    and df top-k are bit-identical to an uninterrupted run.
    """
    return IndexerStep(
        docs, mesh=mesh, n_reduce=n_reduce, max_word_len=max_word_len,
        u_cap=u_cap, depth=depth, device_accumulate=device_accumulate,
        sync_every=sync_every, mesh_shards=mesh_shards, topk=topk,
        stats=stats, checkpoint_dir=checkpoint_dir,
        checkpoint_every=checkpoint_every,
        checkpoint_async=checkpoint_async,
        checkpoint_delta=checkpoint_delta, resume=resume).close()


def _indexer_setup(step, docs, mesh, n_reduce, max_word_len, u_cap,
                   depth, device_accumulate, sync_every, mesh_shards,
                   topk, stats, checkpoint_dir, checkpoint_every,
                   checkpoint_async, checkpoint_delta, resume,
                   keep_services=False, input_range=None):
    """The engine body behind :class:`IndexerStep`: corpus-wide setup,
    then ``begin_rung`` (the former per-rung ``run``) arms the pipeline
    and attaches the lifecycle hooks to ``step``.

    ``input_range`` is the shard scheduler's cursor range in DOC
    ordinals (the wave walks' cursor unit, mr/shards.py): the engine
    drives ``docs[start:end]`` and the range joins the chain identity,
    so two attempts over different ranges can never cross-restore."""
    if input_range is not None:
        docs = docs[int(input_range[0]):int(input_range[1])]
    if mesh is None:
        mesh = default_mesh()
    n_dev = mesh.devices.size
    depth = pipeline_depth(depth)
    # ``mesh_shards`` re-routes the postings buffer AND the df top-k by
    # ``ihash(word) % n_shards`` inside their compiled programs — word
    # state shards by key, not by ``n_reduce % n_dev`` placement.
    mesh_shards = mesh_shards_default(mesh_shards)
    if mesh_shards:
        device_accumulate = True
    from dsi_tpu.parallel.tfidf import _wave_chunk, plan_waves

    doc_lens = getattr(docs, "lengths", None)
    if doc_lens is None:
        doc_lens = [len(d) for d in docs]
    waves = plan_waves(doc_lens, n_dev)
    longest = max(doc_lens, default=1)
    size_max = 1 << max(8, int(longest).bit_length())
    n_real = len(docs)
    # Internal registry scope (dsi_tpu/obs); copied out to the caller's
    # ``stats`` dict when the walk ends, like pipeline_stats everywhere.
    st = metrics_scope("indexer")
    st.update({"waves": len(waves), "step_pulls": 0, "depth": depth,
               "replays": 0, "device_accumulate": device_accumulate,
               "upload_s": 0.0, "kernel_s": 0.0, "pull_s": 0.0,
               "merge_s": 0.0, "replay_s": 0.0})
    groupers = grouper_ladder()
    sh_chunk = NamedSharding(mesh, P(AXIS, None))
    sh_ids = NamedSharding(mesh, P(AXIS))

    # ── checkpoint/restore (dsi_tpu/ckpt): wave-cursor variant ──
    ck_store: Optional[CheckpointStore] = None
    resume_meta = None
    resume_arrays = None
    resume_deltas: list = []
    ck_async = checkpoint_async_default(checkpoint_async)
    ck_delta = checkpoint_delta_default(checkpoint_delta)
    if checkpoint_dir:
        import zlib

        # The wave plan — and with it the cursor's meaning — is a
        # function of the full per-doc length vector, so the vector's
        # CRC is part of the job identity: same count + same total with
        # shuffled lengths must refuse, not silently misalign waves.
        lens_crc = zlib.crc32(np.asarray(doc_lens, np.int64).tobytes())
        ident = {"n_dev": n_dev, "n_reduce": n_reduce, "u_cap": u_cap,
                 "n_docs": n_real, "doc_lens_crc32": lens_crc,
                 "topk": topk,
                 "device_accumulate": bool(device_accumulate)}
        if input_range is not None:
            ident["input_range"] = [int(input_range[0]),
                                    int(input_range[1])]
        ck_store = CheckpointStore(checkpoint_dir, "indexer", ident)
        if resume:
            loaded = ck_store.load_latest_chain()
            if loaded is not None:
                resume_meta, resume_arrays, resume_deltas = loaded
        else:
            ck_store.reset()

    def begin_rung(mwl: int):
        kk = mwl // 4
        table = PostingsTable()
        state = {"cap": rung0_cap(size_max, u_cap),
                 "grouper": groupers[0], "frac": 4}
        outcome = {"high": False, "widen": False}

        def buffer_rows(r: np.ndarray) -> None:
            """One device's pulled posting rows into the host table,
            the short last wave's padding documents filtered FIRST."""
            r = r[r[:, kk + 2] < n_real]
            if len(r):
                table.add(r, kk)

        buf_dev = None
        topk_svc: Optional[DeviceTopK] = None
        df_acc = PackedCounts()
        policy = None
        if device_accumulate:
            from dsi_tpu.device import DevicePostings

            try:
                pcap = int(os.environ.get("DSI_DEVICE_POSTINGS_CAP", "0"))
            except ValueError:
                pcap = 0
            buf_dev = DevicePostings(
                mesh, width=kk + 4,
                cap=pcap if pcap > 0 else n_dev * state["cap"],
                sink=buffer_rows, lag=max(0, depth - 1), stats=st,
                mesh_shards=mesh_shards, kk=kk)
            policy = SyncPolicy(sync_every)
            st["sync_every"] = policy.sync_every
            st["mesh_shards"] = mesh_shards

        # A checkpoint belongs to ONE word-window rung (a widen re-keys
        # every row and restarts the walk, discarding rung state): apply
        # the loaded image only when this run() is at its rung.
        ck_policy: Optional[CheckpointPolicy] = None
        ck_writer: Optional[CheckpointWriter] = None
        ck_wave = [0]  # confirmed-wave cursor (absolute ordinal)
        host_delta = HostDeltaLog()  # non-dacc delta log: trimmed copies
        # of the pulled (rows, nrows) waves, bounded like device logs
        start_wave = 0
        if ck_store is not None:
            ck_policy = CheckpointPolicy(checkpoint_every)
            st.setdefault("ckpt_saves", 0)
            st.setdefault("ckpt_s", 0.0)
            st.setdefault("ckpt_capture_s", 0.0)
            st["ckpt_every"] = ck_policy.every
            st["ckpt_async"] = ck_async
            st["ckpt_delta"] = ck_delta
            # A fresh writer per rung: a rung restart discards rung
            # state, so its first save is a full base again.
            ck_writer = CheckpointWriter(ck_store, st, async_=ck_async,
                                         delta=ck_delta)
            if ck_delta and buf_dev is not None:
                buf_dev.enable_delta()
            eff = resume_deltas[-1][0] if resume_deltas else resume_meta
            if eff is not None and int(eff["mwl"]) == mwl:
                t_res = time.perf_counter()
                start_wave = int(eff["wave"])
                ck_wave[0] = start_wave
                state.update({"cap": int(eff["cap"]),
                              "grouper": eff["grouper"],
                              "frac": int(eff["frac"])})
                table.restore({k[3:]: v for k, v in resume_arrays.items()
                               if k.startswith("pt_")})
                if device_accumulate:
                    saved_shards = int(resume_meta.get("mesh_shards", 0))
                    if resume_meta.get("pb_cap"):
                        pb_img = {"buf": resume_arrays["pb_buf"],
                                  "nrows": resume_arrays["pb_nrows"],
                                  "cap": resume_meta["pb_cap"]}
                        if resume_deltas or saved_shards != mesh_shards:
                            # Chain restore (and the sharding-degree
                            # change) re-enters through the drain path:
                            # buffered rows into the host table, buffer
                            # empty; resumed waves rebuild device state.
                            DevicePostings.drain_image(buffer_rows, pb_img)
                            if saved_shards != mesh_shards:
                                st["resharded_resume"] = saved_shards
                        else:
                            buf_dev.restore_state(pb_img)
                            if ck_delta:
                                buf_dev.enable_delta()
                    df_acc.restore(
                        {k[3:]: v for k, v in resume_arrays.items()
                         if k.startswith("df_")})
                    if resume_meta.get("table_cap"):
                        img = {k[6:]: v for k, v in resume_arrays.items()
                               if k.startswith("table_")}
                        if (not resume_deltas
                                and saved_shards == mesh_shards):
                            topk_svc = DeviceTopK(
                                mesh, kk=int(resume_meta["table_kk"]),
                                cap=int(resume_meta["table_cap"]), k=topk,
                                acc=df_acc, aot=False,
                                lag=max(0, depth - 1), stats=st,
                                mesh_shards=mesh_shards)
                            topk_svc.restore_state(img)
                            if ck_delta:
                                topk_svc.enable_delta()
                        else:
                            DeviceTable.drain_image(df_acc, img)
                            if saved_shards != mesh_shards:
                                st["resharded_resume"] = saved_shards
                    policy.restore(eff.get("sync_since", 0))
                for _, darr in resume_deltas:
                    # Each delta's retained wave payloads re-enter the
                    # host side in save order — postings through the
                    # sink (per-word order preserved: the drain-path
                    # argument), df rows through the accumulator.
                    drain_posting_steps(buffer_rows, darr, "pb_")
                    drain_packed_steps(df_acc, darr, "tk_")
                st["resume_gap_s"] = round(time.perf_counter() - t_res, 4)
                st["resume_wave"] = start_wave

        def save_ckpt() -> None:
            """Consistent snapshot at a confirmed-wave boundary —
            capture here, commit inline or in the background writer
            (``ckpt/writer.py``).  Device captures first — flushing the
            postings buffer's lag drains into the host table on
            overflow recovery, and flushing the df top-k's lag can
            widen into ``df_acc`` — host residue second, so both sides
            of any such move land in the same image.  A delta save
            ships only the wave payloads retained since the previous
            save (device logs in dacc mode, the already-pulled host
            rows otherwise); every ``DSI_STREAM_CKPT_REBASE``-th save
            is a full re-base (an invalid delta window forces one)."""
            with _span("ckpt", stats=st, key="ckpt_s", wave=ck_wave[0]):
                meta = {"mwl": mwl, "wave": ck_wave[0],
                        "cap": state["cap"], "grouper": state["grouper"],
                        "frac": state["frac"]}
                kind = "full"
                parts = None
                with _span("ckpt_capture", lane="ckpt", stats=st,
                           key="ckpt_capture_s"):
                    if ck_writer.want_delta():
                        if device_accumulate:
                            pb_entries = buf_dev.take_delta()
                            tk_entries = (topk_svc.take_delta()
                                          if topk_svc is not None else [])
                        else:
                            pb_entries = host_delta.take()
                            tk_entries = []
                        if pb_entries is not None and tk_entries is not None:
                            parts = [("pb_", DeltaSteps(pb_entries)),
                                     ("tk_", DeltaSteps(tk_entries))]
                            if device_accumulate:
                                meta["sync_since"] = policy.snapshot()
                            kind = "delta"
                    if parts is None:
                        # Full image — the PR-5 arrays (device pulls
                        # dispatched, not awaited); the delta logs
                        # reset here: payloads recorded before this
                        # base are inside the image.
                        parts = []
                        if buf_dev is not None:
                            parts.append(("pb_",
                                          buf_dev.checkpoint_capture()))
                            meta["pb_cap"] = buf_dev.cap
                            meta["mesh_shards"] = buf_dev.mesh_shards
                            if topk_svc is not None:
                                parts.append(
                                    ("table_",
                                     topk_svc.checkpoint_capture()))
                                meta["table_cap"] = topk_svc.cap
                                meta["table_kk"] = topk_svc.kk
                            parts.append(("df_", df_acc.snapshot()))
                            meta["sync_since"] = policy.snapshot()
                            if ck_delta:
                                buf_dev.take_delta()
                                if topk_svc is not None:
                                    topk_svc.take_delta()
                        host_delta.reset()
                        parts.append(("pt_", table.snapshot()))
                fault_point("mid-capture")
                ck_writer.commit(parts, meta, kind=kind)

        def materialize():
            for idxs, size in waves[start_wave:]:
                chunk_np = _wave_chunk(docs, idxs, n_dev, size)
                ids_np = np.array(
                    list(idxs) + [n_real] * (n_dev - len(idxs)),
                    dtype=np.int32)
                yield (size, chunk_np, ids_np)

        def wave_call(chunk_np, ids_np, size, cap, frac, g):
            with _span("upload", stats=st, key="upload_s"):
                chunk = jax.device_put(chunk_np, sh_chunk)
                ids = jax.device_put(ids_np, sh_ids)
            fn = _idx_fn((chunk, ids), n_dev=n_dev, n_reduce=n_reduce,
                         max_word_len=mwl, u_cap=cap, size=size, mesh=mesh,
                         t_cap_frac=frac, grouper=g)
            with _quiet_unusable_donation():
                return fn(chunk, ids)

        def dispatch(item):
            size, chunk_np, ids_np = item
            rows, df, scal = wave_call(chunk_np, ids_np, size,
                                       state["cap"], state["frac"],
                                       state["grouper"])
            fault_point("post-dispatch")
            return (size, chunk_np, ids_np, rows, df, scal, state["cap"])

        def replay_wave(size, chunk_np, ids_np):
            st["replays"] += 1
            cap = state["cap"]
            with _span("replay", stats=st, key="replay_s"):
                while True:
                    for g in groupers:
                        for frac in (4, 2):
                            rows, df, scal = wave_call(chunk_np, ids_np,
                                                       size, cap, frac, g)
                            scal_np = np.asarray(scal)
                            if not scal_np[:, 4].any():
                                break
                        if not scal_np[:, 4].any():
                            break
                    if bool(scal_np[:, 3].any()):
                        outcome["high"] = True
                        raise _AbortRung
                    if int(scal_np[:, 2].max()) > mwl:
                        outcome["widen"] = True
                        raise _AbortRung
                    if int(scal_np[:, 1].max()) > cap:
                        cap *= 4  # uniques <= tokens <= size/2: terminates
                        continue
                    break
            state["cap"], state["grouper"], state["frac"] = cap, g, frac
            return rows, df, scal, scal_np

        def commit(rows, df, scal, scal_np):
            nonlocal topk_svc
            m = int(scal_np[:, 0].max())
            if m == 0:
                return
            if buf_dev is not None:
                # The df fold rides the SAME confirmation: only waves the
                # postings path accepted fold their frequency rows.
                if topk_svc is None:
                    # Rung-0 df-table capacity: the wave's row count (a
                    # single fold can never overflow it), unless the
                    # shared DSI_DEVICE_TOPK_CAP override asks smaller.
                    topk_svc = DeviceTopK(
                        mesh, kk=kk,
                        cap=_topk_cap_env() or int(df.shape[1]),
                        k=topk, acc=df_acc, aot=False,
                        lag=max(0, depth - 1), stats=st,
                        mesh_shards=mesh_shards)
                    if ck_store is not None and ck_delta:
                        topk_svc.enable_delta()
                pulls_before = st["sync_pulls"]
                buf_dev.append(rows, scal,
                               nvalid=scal_np[:, 0].astype(np.int64))
                topk_svc.fold(df, scal, scal_np)
                policy.note_fold()
                if st["sync_pulls"] != pulls_before:
                    policy.reset()  # an overflow recovery just drained:
                    # that WAS this window's pull
                elif policy.due():
                    fault_point("pre-sync")
                    buf_dev.sync()
                    topk_svc.sync()
                    policy.reset()
                return
            with _span("pull", stats=st, key="pull_s"):
                mp = occupied_prefix(m, rows.shape[1])
                rows_np = np.asarray(rows[:, :mp])
                st["step_pulls"] += 1
            with _span("merge", stats=st, key="merge_s"):
                for d in range(n_dev):
                    nr = int(scal_np[d, 0])
                    if nr:
                        buffer_rows(rows_np[d, :nr])
                if ck_store is not None and ck_delta:
                    # Host-merge delta log: the wave's payload, window-
                    # bounded like the device logs.
                    host_delta.append(rows_np, scal_np[:, 0])

        def finish(rec):
            size, chunk_np, ids_np, rows, df, scal, cap = rec
            with _span("kernel", stats=st, key="kernel_s"):
                scal_np = np.asarray(scal)  # blocks until the kernel lands
            if bool(scal_np[:, 3].any()):
                outcome["high"] = True
                raise _AbortRung
            if int(scal_np[:, 2].max()) > mwl:
                outcome["widen"] = True
                raise _AbortRung
            if scal_np[:, 4].any() or int(scal_np[:, 1].max()) > cap:
                rows, df, scal, scal_np = replay_wave(size, chunk_np,
                                                      ids_np)
            commit(rows, df, scal, scal_np)
            # Confirmed (empty waves included — the cursor must advance
            # past them too); fault before the cursor moves.
            fault_point("mid-fold")
            if ck_policy is not None:
                ck_wave[0] += 1
                ck_policy.note_step()
                if ck_policy.due():
                    save_ckpt()
                    ck_policy.reset()

        st.setdefault("sync_pulls", 0)
        pipe = StepPipeline(depth=depth, dispatch=dispatch, finish=finish,
                            stats=st, produce_key="materialize_s",
                            wait_key="materialize_wait_s",
                            inflight_key="max_inflight_waves",
                            thread_name="dsi-idx-materializer",
                            engine="indexer")
        step._pipe = pipe
        step._mwl = mwl
        step._outcome = outcome
        step._save = save_ckpt if ck_policy is not None else None
        step._writer = ck_writer
        pipe.begin(materialize)

        def end_ok():
            if keep_services:
                # The plan handoff: finish the walk but leave the
                # device services RESIDENT — no drain-to-host.  The
                # downstream stages pull a k-row df snapshot
                # (DeviceTopK.sync) and close the postings buffer
                # themselves; the host residue travels alongside so a
                # widen that already drained stays accounted for.
                try:
                    if ck_writer is not None:
                        ck_writer.drain()
                finally:
                    if ck_writer is not None:
                        ck_writer.shutdown()
                step.exported = {
                    "kk": kk, "n_real": n_real, "topk": topk,
                    "device_accumulate": device_accumulate,
                    "topk_svc": topk_svc, "postings_svc": buf_dev,
                    "df_acc": df_acc, "table": table,
                    "buffer_rows": buffer_rows}
                step.result = ("plan-handoff",)
                return
            try:
                if buf_dev is not None:
                    fault_point("pre-sync")
                    buf_dev.close()
                    if topk_svc is not None:
                        topk_svc.close()
                if ck_writer is not None:
                    ck_writer.drain()  # surface async commit errors
                    # before the payload (and save counters) are read
            finally:
                if ck_writer is not None:
                    ck_writer.shutdown()
            postings = {
                w: (part, [d for d, _ in pairs])
                for w, (part, pairs) in table.finalize().items()}
            if device_accumulate and topk_svc is not None:
                df_map = {w: c for w, (c, _) in df_acc.finalize().items()}
            else:
                df_map = {w: len(ds) for w, (_, ds) in postings.items()}
            top = tuple(sorted(((c, w) for w, c in df_map.items()),
                               key=lambda r: (-r[0], r[1]))[:topk])
            step.result = (postings, top)

        step._on_complete = end_ok

    rungs = ((max_word_len, 64) if max_word_len < 64 else (max_word_len,))
    if resume_meta is not None:
        # The checkpoint is at a rung: start there (an earlier rung had
        # provably aborted before the checkpointed one began).
        rungs = tuple(m for m in rungs
                      if m >= int(resume_meta["mwl"])) or rungs
    step._rungs = tuple(rungs)
    step._begin_rung = begin_rung

    released = []

    def release():
        if released:
            return
        released.append(True)
        w = step._writer  # the CURRENT rung's writer (re-set per rung)
        if w is not None:
            w.shutdown()
        fold_source_stats(st, docs)  # a doc source may pool-read too
        if stats is not None:
            stats.update(st)

    step._release = release
    begin_rung(rungs[0])


def write_indexer_output(result, doc_names: Sequence[str], n_reduce: int,
                         workdir: str = ".") -> List[str]:
    """Materialise mr-out-<r> files byte-identical to the host indexer
    app's reduce output (``"<count> <doc1>,<doc2>,..."`` with documents
    sorted and deduplicated), via the shared partitioned writer."""
    from dsi_tpu.parallel.shuffle import write_partitioned_output

    postings, _ = result if isinstance(result, tuple) else (result, ())
    formatted = {}
    for w, (part, doc_ids) in postings.items():
        names = sorted({doc_names[d] for d in doc_ids})
        formatted[w] = (f"{len(names)} {','.join(names)}", part)
    return write_partitioned_output(formatted, n_reduce, workdir)


def warm_indexer_aot(mesh: Mesh | None = None, sizes: Sequence[int] = (
        1 << 18,), n_reduce: int = 10, word_lens: Sequence[int] = (16,),
        caps: Sequence[int] = (1 << 14,), fracs: Sequence[int] = (4, 2),
        topk: int = DEFAULT_TOPK, device_accumulate: bool = False,
        mesh_shards: int = 0) -> None:
    """Compile + persist the ``idx_wave_*`` shapes an
    ``indexer_streaming`` run reaches at these wave sizes/capacities
    (both grouper variants), plus — with ``device_accumulate`` — the
    df top-k fold shapes.  From shape structs alone."""
    if mesh is None:
        mesh = default_mesh()
    n_dev = mesh.devices.size
    sds = jax.ShapeDtypeStruct
    for mwl in word_lens:
        for cap in caps:
            for size in sizes:
                examples = (sds((n_dev, size), jnp.uint8),
                            sds((n_dev,), jnp.int32))
                for frac in fracs:
                    for g in sorted(warm_groupers()):
                        _idx_fn(examples, n_dev=n_dev, n_reduce=n_reduce,
                                max_word_len=mwl, u_cap=cap, size=size,
                                mesh=mesh, t_cap_frac=frac, grouper=g)
            if device_accumulate:
                from dsi_tpu.device.topk import warm_topk_service

                warm_topk_service(mesh, kk=mwl // 4, rows=n_dev * cap,
                                  cap=n_dev * cap, k=topk, table_rungs=2,
                                  mesh_shards=mesh_shards)
