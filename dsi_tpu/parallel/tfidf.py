"""SPMD TF-IDF: per-document device map + all_to_all shuffle, host scoring.

The multi-chip composition BASELINE.json's last config calls for.  Documents
are processed in waves of ``n_dev`` (one document per device per wave):

* map   = per-device ``tokenize_group_core`` over its document — the same
  fused kernel as word count, but each unique word row carries the document
  id and in-document count (tf) as payload lanes,
* shuffle = ``jax.lax.all_to_all`` routes every (word, doc, tf) row to the
  device owning the word's reduce partition (``ihash % n_reduce % n_dev``,
  bit-identical to ``mr/worker.go:33-37,76``), replacing the reference's
  ``mr-X-Y`` intermediate files exactly as in ``parallel/shuffle.py``,
* reduce = per-device sort of received rows by word; the host buffers each
  wave's rows as raw uint32 tables (``parallel/merge.py`` PostingsTable),
  groups them once at the end with one lexsort + run detection + one bulk
  spelling decode, and computes ``df``/``tf·ln(N/df)`` at output time via
  the SAME ``apps.tfidf.format_value`` the host Reduce uses — so the SPMD
  job's ``mr-out-*`` files are byte-identical to the sequential oracle's.

Cross-wave state is a host dict, NOT device memory: a wave's device
footprint is bounded by (n_dev x that wave's longest document) regardless of
corpus size, which is what lets the same program scale to the 10 GB config
by adding waves.  Documents are processed longest-first so each wave's
chunk is padded to its OWN longest document's power of two — one 100 MB
outlier in a corpus of 1 MB documents costs one big wave, not big buffers
for every wave — and the power-of-two ladder bounds distinct compiled
shapes to log2(longest/shortest), not n_waves.

Host-memory story, stated honestly: the accumulator holds every posting as
a ~(4·kk+16)-byte uint32 row — O(total postings), the same asymptotic
footprint as the reference's reduce-side in-memory group
(``mr/worker.go:110-124`` holds every record of a partition at once), but
across ALL partitions and several times denser than the Python tuple lists
it replaced.  At the 10 GB config (~1e8 postings x 32 B) this needs GBs of
host RAM; the scale-out lever is implemented: pass
``tfidf_sharded(..., partitions={...})`` to accumulate only a slice of the
reduce partitions (the partition id is already on every row), dividing the
accumulator by the number of slices without touching device code — the
slices' union is exactly the full result.  Device memory is unaffected
either way.
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from dsi_tpu.utils.jaxcompat import (enable_x64, x64_scoped,
                                     shard_map as _shard_map)

from dsi_tpu.ops.wordcount import (
    _PAD_KEY64,
    exactness_retry,
    pack_key_lanes,
    unpack_key_lanes,
)
from dsi_tpu.parallel.merge import PostingsTable
from dsi_tpu.parallel.shuffle import (
    AXIS,
    default_mesh,
    map_prologue,
    occupied_prefix,
    shuffle_rows,
)


def _tfidf_device_step(chunk: jax.Array, doc_id: jax.Array, *, n_dev: int,
                       n_reduce: int, max_word_len: int, u_cap: int,
                       t_cap_frac: int, grouper: str = "sort"):
    """Per-device wave body: map its document, all_to_all, sort received."""
    k = max_word_len // 4
    chunk = chunk.reshape(-1)
    doc = doc_id.reshape(())

    packed_u, len_u, cnt_u, part, dest, (
        n_unique, max_len, has_high, token_overflow) = map_prologue(
        chunk, n_dev=n_dev, n_reduce=n_reduce, max_word_len=max_word_len,
        u_cap=u_cap, t_cap_frac=t_cap_frac, grouper=grouper)

    # Send rows: word key lanes + [len, tf, doc, part] payload, routed by
    # the shared shuffle primitive (parallel/shuffle.py shuffle_rows).
    rows = jnp.concatenate(
        [packed_u, len_u[:, None].astype(jnp.uint32),
         cnt_u[:, None].astype(jnp.uint32),
         jnp.broadcast_to(doc.astype(jnp.uint32), (u_cap,))[:, None],
         part[:, None]], axis=1)
    recv = shuffle_rows(rows, dest, n_dev=n_dev, u_cap=u_cap, k=k)

    # Partition received rows valid-first so the host's occupied-prefix
    # D2H slice works; the host accumulator (parallel/merge.py
    # PostingsTable) groups with its own lexsort at finalize, so the
    # former full by-word device sort bought nothing but the pad
    # partition.  One boolean key with ALL columns packed pairwise into
    # u64 operands (operand count, not comparator width, dominates
    # XLA's CPU sort) measured +20% whole-soak throughput at 256 MB
    # (round 5).  Pad detection on the first PACKED column: a pad row
    # is all-ones in every lane, i.e. uint64-max after packing (a real
    # first lane can be 0xFFFFFFFF only for non-ASCII bytes, which
    # has_high rejects).
    with enable_x64(True):  # every op touching u64 operands needs it
        keys64 = pack_key_lanes(tuple(recv[:, j] for j in range(k)))
        pay64 = pack_key_lanes(tuple(recv[:, k + j] for j in range(4)))
        k64 = len(keys64)
        is_pad = (keys64[0] == jnp.array(_PAD_KEY64, jnp.uint64)) \
            .astype(jnp.uint8)
        sorted_cols = lax.sort((is_pad,) + keys64 + pay64, num_keys=1)
        srecv = jnp.stack(
            unpack_key_lanes(sorted_cols[1:1 + k64], k)
            + unpack_key_lanes(sorted_cols[1 + k64:], 4), axis=1)
    n_rows = jnp.sum(sorted_cols[0] == 0, dtype=jnp.int32)

    scalars = jnp.stack([n_rows, n_unique, max_len,
                         has_high.astype(jnp.int32),
                         token_overflow.astype(jnp.int32)])
    return srecv[None], scalars[None]


def _tfidf_wave_step_impl(chunks: jax.Array, doc_ids: jax.Array, *,
                          n_dev: int, n_reduce: int, max_word_len: int,
                          u_cap: int, mesh: Mesh, t_cap_frac: int = 4,
                          grouper: str = "sort"):
    """One SPMD wave: ``chunks`` [n_dev, L] uint8 (one zero-padded document
    per device), ``doc_ids`` [n_dev] int32.  Returns per-device sorted
    (word, len, tf, doc, part) rows [D, D*u_cap, K+4] and [D, 5] scalars
    (n_rows, n_unique, max_len, has_high, token_overflow)."""
    body = functools.partial(_tfidf_device_step, n_dev=n_dev,
                             n_reduce=n_reduce, max_word_len=max_word_len,
                             u_cap=u_cap, t_cap_frac=t_cap_frac,
                             grouper=grouper)
    return _shard_map(
        body, mesh=mesh,
        in_specs=(P(AXIS, None), P(AXIS)),
        out_specs=(P(AXIS, None, None), P(AXIS, None)))(chunks, doc_ids)


tfidf_wave_step = x64_scoped(jax.jit(
    _tfidf_wave_step_impl,
    static_argnames=("n_dev", "n_reduce", "max_word_len", "u_cap",
                     "t_cap_frac", "mesh", "grouper")))


def plan_waves(doc_lens: Sequence[int],
               n_dev: int) -> List[Tuple[List[int], int]]:
    """Assign documents to waves of ``n_dev``, longest-first.

    Returns ``[(doc_indices, chunk_size), ...]`` where ``chunk_size`` is the
    power of two holding that wave's OWN longest document (min 256).
    Longest-first grouping makes sizes non-increasing across waves, so the
    number of distinct compiled shapes is bounded by the log2 spread of
    document sizes — a single 10x outlier adds exactly one shape
    (VERDICT r2 weakness #3) — and the peak device buffer of a wave tracks
    that wave's documents, not the global maximum.
    """
    order = sorted(range(len(doc_lens)), key=lambda i: doc_lens[i],
                   reverse=True)
    waves = []
    for w in range(0, len(order), n_dev):
        idxs = order[w:w + n_dev]
        longest = max(doc_lens[i] for i in idxs)
        waves.append((idxs, 1 << max(8, int(longest).bit_length())))
    return waves


def _wave_chunk(docs: Sequence[bytes], idxs: Sequence[int], n_dev: int,
                size: int) -> np.ndarray:
    """Materialise ONE wave's [n_dev, size] padded block lazily — padding
    the whole corpus up front would allocate n_docs x pow2(longest) bytes
    (one big document among many small ones inflates it catastrophically);
    per-wave blocks keep host memory O(wave's own longest)."""
    out = np.zeros((n_dev, size), dtype=np.uint8)
    for r, i in enumerate(idxs):
        out[r, :len(docs[i])] = np.frombuffer(docs[i], dtype=np.uint8)
    return out


def tfidf_sharded(
        docs: Sequence[bytes], mesh: Mesh | None = None, n_reduce: int = 10,
        max_word_len: int = 16, u_cap: int = 1 << 15,
        partitions: Optional[set] = None, packed: bool = False,
        device_accumulate: bool = False, sync_every: Optional[int] = None,
        wave_stats: Optional[dict] = None,
):
    """Whole-corpus TF-IDF over the mesh, waves of n_dev documents.

    Returns ``{word: (reduce_partition, [(doc_index, tf), ...])}`` — exact,
    or None when any document needs the host path (non-ASCII bytes, words
    longer than 64).  Same retry discipline as ``wordcount_sharded``.

    ``partitions`` restricts the host accumulator to those reduce
    partitions — the module's large-corpus story made concrete: running the
    job once per partition slice divides the O(total postings) host memory
    by the number of slices (device work repeats per slice; the partition
    id rides every shuffled row, so filtering costs nothing extra).  The
    slices' union is exactly the unfiltered result.

    ``packed=True`` returns the ``merge.PackedPostings`` numpy tables
    instead of the dict — ~32 B/posting instead of ~250 B of Python
    objects, the difference between a bounded and an input-proportional
    host footprint at GB scale.  ``docs`` may be any sequence yielding
    bytes on ``__getitem__`` (e.g. :class:`FileDocs`, which reads each
    document from disk per wave instead of holding the corpus resident);
    a ``lengths`` attribute, when present, avoids loading documents just
    to size the waves.

    ``device_accumulate=True`` batches the wave walk's D2H through the
    device-resident accumulator service: each wave's received rows
    APPEND into a persistent on-device postings buffer
    (``device/postings.py``) and the host pulls once per ``sync_every``
    waves (``DSI_STREAM_SYNC_EVERY`` default, 8) or when the buffer
    fills — amortizing the tunnel's fixed per-pull latency exactly as
    the streaming engine's fold does (ROADMAP item 2: the wave walk has
    the same serialized pull shape).  Results are identical: the same
    rows reach the same ``PostingsTable``, just in per-window batches,
    and the padding-doc/partition filters run at drain time instead of
    per wave.  ``wave_stats``, if given, is populated with
    ``waves``/``appends``/``append_overflows``/``sync_pulls``/
    ``step_pulls`` counters plus ``append_s``/``drain_s`` phases in
    either mode.
    """
    if mesh is None:
        mesh = default_mesh()
    n_dev = mesh.devices.size
    doc_lens = getattr(docs, "lengths", None)
    if doc_lens is None:
        doc_lens = [len(d) for d in docs]
    waves = plan_waves(doc_lens, n_dev)
    longest = max(doc_lens, default=1)
    size_max = 1 << max(8, int(longest).bit_length())  # retry hard-cap
    n_real = len(docs)
    stats = wave_stats if wave_stats is not None else {}
    stats.update({"waves": len(waves), "step_pulls": 0,
                  "device_accumulate": device_accumulate})

    def run(mwl: int, cap: int):
        kk = mwl // 4
        # Buffer each wave's surviving rows AS THE WAVES RUN — raw uint32
        # tables copied out of the wave's transfer buffer (no device-shaped
        # block stays alive), grouped/decoded once at payload time by the
        # vectorized PostingsTable (parallel/merge.py; VERDICT r3 weakness
        # #3 replaced the per-row Python walk).  Host state is O(postings
        # in this slice) — same asymptotics as the dict it replaces, ~5x
        # smaller constant.  A retry rung discards the whole table and
        # starts fresh, so partial rungs can't leak into the result.
        table = PostingsTable()
        part_arr = (None if partitions is None
                    else np.fromiter(partitions, dtype=np.uint32))
        agg_high = False
        agg_nu = 0
        agg_ml = 0
        from dsi_tpu.ops.wordcount import grouper_ladder

        groupers = grouper_ladder()

        def buffer_rows(r: np.ndarray) -> None:
            """One device's pulled rows into the host table, filtered
            FIRST: the short last wave's padding documents and — for a
            partition slice — other slices' rows must cut the per-slice
            host cost, not just the final table (same rule on both the
            per-wave and the drain path)."""
            r = r[r[:, kk + 2] < n_real]
            if part_arr is not None:
                r = r[np.isin(r[:, kk + 3], part_arr)]
            if len(r):
                table.add(r, kk)

        # Device-resident accumulation (fresh per retry rung — a rung
        # restart discards partial device state exactly like the host
        # table): waves append on-device, the host pulls per K-wave
        # window or when the buffer fills (an overflowing append is a
        # global no-op; drain-and-retry always fits, because the buffer
        # holds at least one worst-case wave).
        buf_dev = None
        policy = None
        if device_accumulate:
            import os

            from dsi_tpu.device import DevicePostings, SyncPolicy

            # One worst-case wave by default (so drain-and-retry always
            # fits); DSI_DEVICE_POSTINGS_CAP trims it for HBM-tight
            # meshes (overflow then just syncs earlier) and lets tests
            # force the early-drain path.
            try:
                pcap = int(os.environ.get("DSI_DEVICE_POSTINGS_CAP", "0"))
            except ValueError:
                pcap = 0
            buf_dev = DevicePostings(mesh, width=kk + 4,
                                     cap=pcap if pcap > 0 else n_dev * cap,
                                     stats=stats)
            policy = SyncPolicy(sync_every)
            stats["sync_every"] = policy.sync_every

        def drain_buf() -> None:
            for r in buf_dev.drain():
                buffer_rows(r)

        for idxs, size in waves:
            chunk = jnp.asarray(_wave_chunk(docs, idxs, n_dev, size))
            # Pad rows of a short last wave carry doc id n_real, which the
            # host walk below discards.
            ids = jnp.asarray(
                np.array(list(idxs) + [n_real] * (n_dev - len(idxs)),
                         dtype=np.int32))
            for g in groupers:
                for frac in (4, 2):
                    rows, scal = tfidf_wave_step(
                        chunk, ids, n_dev=n_dev, n_reduce=n_reduce,
                        max_word_len=mwl, u_cap=cap, mesh=mesh,
                        t_cap_frac=frac, grouper=g)
                    scal_np = np.asarray(scal)
                    if not scal_np[:, 4].any():
                        break
                if not scal_np[:, 4].any():
                    break
            agg_high = agg_high or bool(scal_np[:, 3].any())
            agg_nu = max(agg_nu, int(scal_np[:, 1].max()))
            agg_ml = max(agg_ml, int(scal_np[:, 2].max()))
            if agg_high or agg_nu > cap or agg_ml > mwl:
                break  # this rung's results are certain to be discarded
                # (host fallback or wider retry); more waves = pure waste
            m = int(scal_np[:, 0].max())
            if m == 0:
                continue
            if buf_dev is not None:
                # Append this wave's rows on-device; the host pulls per
                # K-wave window instead of per wave.
                if not buf_dev.append(rows, scal):
                    drain_buf()  # buffer full: early sync, then retry
                    policy.reset()  # the drain WAS this window's pull —
                    # without this, due() could fire a second, nearly
                    # empty pull one wave later
                    if not buf_dev.append(rows, scal):
                        # Only reachable when DSI_DEVICE_POSTINGS_CAP was
                        # forced below one wave's rows — losing the wave
                        # silently is never acceptable.
                        raise RuntimeError(
                            "device postings buffer smaller than one wave"
                            f" (cap={buf_dev.cap})")
                policy.note_fold()
                if policy.due():
                    drain_buf()
                    policy.reset()
                continue
            # Pull only the occupied prefix (max per-device received rows,
            # pow2-rounded to bound the slice-program count): the D2H bill
            # tracks this wave's postings, not the worst-case capacity.
            mp = occupied_prefix(m, rows.shape[1])
            rows_np = np.asarray(rows[:, :mp])
            stats["step_pulls"] += 1
            for d in range(n_dev):
                nr = int(scal_np[d, 0])
                if nr == 0:
                    continue
                buffer_rows(rows_np[d, :nr])

        if buf_dev is not None and not (agg_high or agg_nu > cap
                                        or agg_ml > mwl):
            drain_buf()  # end-of-walk sync (a discarded rung skips it)

        return (agg_high, agg_nu, agg_ml,
                table.finalize_packed if packed else table.finalize)

    payload = exactness_retry(run, size_max, max_word_len, u_cap)
    return None if payload is None else payload()


class FileDocs:
    """Lazy document sequence for :func:`tfidf_sharded`: documents load
    from disk per access (one wave's working set at a time) instead of
    holding the whole corpus resident — at the 1 GB soak that was 1.07 GB
    of the peak RSS (VERDICT r4 weakness #4)."""

    def __init__(self, paths: Sequence[str]):
        import os

        self.paths = list(paths)
        self.lengths = [os.path.getsize(p) for p in self.paths]

    def __len__(self) -> int:
        return len(self.paths)

    def __getitem__(self, i: int) -> bytes:
        with open(self.paths[i], "rb") as f:
            return f.read()


def write_tfidf_output(result: Dict[str, Tuple[int, List[Tuple[int, int]]]],
                       doc_names: Sequence[str], n_reduce: int,
                       workdir: str = ".") -> List[str]:
    """Materialise mr-out-<r> files byte-identical to the host tfidf app's
    reduce output: scores via the shared ``format_value``, files via the
    shared partitioned writer (``shuffle.write_partitioned_output``)."""
    from dsi_tpu.apps.tfidf import format_value
    from dsi_tpu.parallel.shuffle import write_partitioned_output

    n_docs = len(doc_names)
    formatted = {
        w: (format_value([(doc_names[d], tf) for d, tf in pairs], n_docs), r)
        for w, (r, pairs) in result.items()}
    return write_partitioned_output(formatted, n_reduce, workdir)
