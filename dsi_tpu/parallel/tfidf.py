"""SPMD TF-IDF: per-document device map + all_to_all shuffle, host scoring.

The multi-chip composition BASELINE.json's last config calls for.  Documents
are processed in waves of ``n_dev`` (one document per device per wave):

* map   = per-device ``tokenize_group_core`` over its document — the same
  fused kernel as word count, but each unique word row carries the document
  id and in-document count (tf) as payload lanes,
* shuffle = ``jax.lax.all_to_all`` routes every (word, doc, tf) row to the
  device owning the word's reduce partition (``ihash % n_reduce % n_dev``,
  bit-identical to ``mr/worker.go:33-37,76``), replacing the reference's
  ``mr-X-Y`` intermediate files exactly as in ``parallel/shuffle.py``,
* reduce = per-device sort of received rows by word; the host buffers each
  wave's rows as raw uint32 tables (``parallel/merge.py`` PostingsTable),
  groups them once at the end with one lexsort + run detection + one bulk
  spelling decode, and computes ``df``/``tf·ln(N/df)`` at output time via
  the SAME ``apps.tfidf.format_value`` the host Reduce uses — so the SPMD
  job's ``mr-out-*`` files are byte-identical to the sequential oracle's.

Cross-wave state is a host dict, NOT device memory: a wave's device
footprint is bounded by (n_dev x that wave's longest document) regardless of
corpus size, which is what lets the same program scale to the 10 GB config
by adding waves.  Documents are processed longest-first so each wave's
chunk is padded to its OWN longest document's power of two — one 100 MB
outlier in a corpus of 1 MB documents costs one big wave, not big buffers
for every wave — and the power-of-two ladder bounds distinct compiled
shapes to log2(longest/shortest), not n_waves.

Host-memory story, stated honestly: the accumulator holds every posting as
a ~(4·kk+16)-byte uint32 row — O(total postings), the same asymptotic
footprint as the reference's reduce-side in-memory group
(``mr/worker.go:110-124`` holds every record of a partition at once), but
across ALL partitions and several times denser than the Python tuple lists
it replaced.  At the 10 GB config (~1e8 postings x 32 B) this needs GBs of
host RAM; the scale-out lever is implemented: pass
``tfidf_sharded(..., partitions={...})`` to accumulate only a slice of the
reduce partitions (the partition id is already on every row), dividing the
accumulator by the number of slices without touching device code — the
slices' union is exactly the full result.  Device memory is unaffected
either way.
"""

from __future__ import annotations

import functools
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dsi_tpu.ckpt import (
    CheckpointPolicy,
    CheckpointStore,
    CheckpointWriter,
    DeltaSteps,
    HostDeltaLog,
    checkpoint_async_default,
    checkpoint_delta_default,
    drain_posting_steps,
    fault_point,
)
from dsi_tpu.obs import metrics_scope, span as _span
from dsi_tpu.utils.jaxcompat import (enable_x64, x64_scoped,
                                     shard_map as _shard_map)

from dsi_tpu.ops.wordcount import (
    _PAD_KEY64,
    grouper_ladder,
    grouper_suffix,
    pack_key_lanes,
    rung0_cap,
    unpack_key_lanes,
)
from dsi_tpu.parallel.merge import PostingsTable
from dsi_tpu.parallel.pipeline import (StepPipeline, fold_source_stats,
                                       pipeline_depth)
from dsi_tpu.parallel.stepobj import EngineStep as _EngineStep
from dsi_tpu.parallel.shuffle import (
    AXIS,
    default_mesh,
    map_prologue,
    occupied_prefix,
    shuffle_rows,
)


def _tfidf_device_step(chunk: jax.Array, doc_id: jax.Array, *, n_dev: int,
                       n_reduce: int, max_word_len: int, u_cap: int,
                       t_cap_frac: int, grouper: str = "sort"):
    """Per-device wave body: map its document, all_to_all, sort received."""
    k = max_word_len // 4
    chunk = chunk.reshape(-1)
    doc = doc_id.reshape(())

    packed_u, len_u, cnt_u, part, dest, (
        n_unique, max_len, has_high, token_overflow) = map_prologue(
        chunk, n_dev=n_dev, n_reduce=n_reduce, max_word_len=max_word_len,
        u_cap=u_cap, t_cap_frac=t_cap_frac, grouper=grouper)

    # Send rows: word key lanes + [len, tf, doc, part] payload, routed by
    # the shared shuffle primitive (parallel/shuffle.py shuffle_rows).
    rows = jnp.concatenate(
        [packed_u, len_u[:, None].astype(jnp.uint32),
         cnt_u[:, None].astype(jnp.uint32),
         jnp.broadcast_to(doc.astype(jnp.uint32), (u_cap,))[:, None],
         part[:, None]], axis=1)
    recv = shuffle_rows(rows, dest, n_dev=n_dev, u_cap=u_cap, k=k)

    # Partition received rows valid-first so the host's occupied-prefix
    # D2H slice works; the host accumulator (parallel/merge.py
    # PostingsTable) groups with its own lexsort at finalize, so the
    # former full by-word device sort bought nothing but the pad
    # partition.  One boolean key with ALL columns packed pairwise into
    # u64 operands (operand count, not comparator width, dominates
    # XLA's CPU sort) measured +20% whole-soak throughput at 256 MB
    # (round 5).  Pad detection on the first PACKED column: a pad row
    # is all-ones in every lane, i.e. uint64-max after packing (a real
    # first lane can be 0xFFFFFFFF only for non-ASCII bytes, which
    # has_high rejects).
    with enable_x64(True):  # every op touching u64 operands needs it
        keys64 = pack_key_lanes(tuple(recv[:, j] for j in range(k)))
        pay64 = pack_key_lanes(tuple(recv[:, k + j] for j in range(4)))
        k64 = len(keys64)
        is_pad = (keys64[0] == jnp.array(_PAD_KEY64, jnp.uint64)) \
            .astype(jnp.uint8)
        sorted_cols = lax.sort((is_pad,) + keys64 + pay64, num_keys=1)
        srecv = jnp.stack(
            unpack_key_lanes(sorted_cols[1:1 + k64], k)
            + unpack_key_lanes(sorted_cols[1 + k64:], 4), axis=1)
    n_rows = jnp.sum(sorted_cols[0] == 0, dtype=jnp.int32)

    scalars = jnp.stack([n_rows, n_unique, max_len,
                         has_high.astype(jnp.int32),
                         token_overflow.astype(jnp.int32)])
    return srecv[None], scalars[None]


def _tfidf_wave_step_impl(chunks: jax.Array, doc_ids: jax.Array, *,
                          n_dev: int, n_reduce: int, max_word_len: int,
                          u_cap: int, mesh: Mesh, t_cap_frac: int = 4,
                          grouper: str = "sort"):
    """One SPMD wave: ``chunks`` [n_dev, L] uint8 (one zero-padded document
    per device), ``doc_ids`` [n_dev] int32.  Returns per-device sorted
    (word, len, tf, doc, part) rows [D, D*u_cap, K+4] and [D, 5] scalars
    (n_rows, n_unique, max_len, has_high, token_overflow)."""
    body = functools.partial(_tfidf_device_step, n_dev=n_dev,
                             n_reduce=n_reduce, max_word_len=max_word_len,
                             u_cap=u_cap, t_cap_frac=t_cap_frac,
                             grouper=grouper)
    return _shard_map(
        body, mesh=mesh,
        in_specs=(P(AXIS, None), P(AXIS)),
        out_specs=(P(AXIS, None, None), P(AXIS, None)))(chunks, doc_ids)


tfidf_wave_step = x64_scoped(jax.jit(
    _tfidf_wave_step_impl,
    static_argnames=("n_dev", "n_reduce", "max_word_len", "u_cap",
                     "t_cap_frac", "mesh", "grouper")))

#: jax.jit donate_argnums for the pipelined wave program: the chunk
#: upload is consumed by the kernel (the window re-uploads per attempt),
#: so an in-flight window never doubles chunk residency in HBM.  The
#: tiny doc-id vector is not worth donating.
_WAVE_DONATE = (0,)


def _wave_program(*, n_dev: int, n_reduce: int, max_word_len: int,
                  u_cap: int, size: int, mesh: Mesh, t_cap_frac: int,
                  grouper: str = "sort"):
    """The (name, fn) pair for one compiled wave-step shape — same
    single-definition discipline as ``streaming._step_program``, so a
    cache-existence probe's key is by construction the key a run
    compiles.  ``size`` enters the name for readability only (the cache
    key already hashes the example avals)."""
    import dsi_tpu.ops.wordcount as _wc
    import dsi_tpu.parallel.shuffle as _sh

    def fn(chunk, ids):
        return _tfidf_wave_step_impl(chunk, ids, n_dev=n_dev,
                                     n_reduce=n_reduce,
                                     max_word_len=max_word_len,
                                     u_cap=u_cap, mesh=mesh,
                                     t_cap_frac=t_cap_frac,
                                     grouper=grouper)

    fn._aot_code_deps = (_wc, _sh)
    name = (f"tfidf_wave_d{n_dev}_r{n_reduce}_w{max_word_len}"
            f"_u{u_cap}_s{size}_f{t_cap_frac}")
    name += grouper_suffix(grouper)
    return name, fn


def _wave_fn(example_args, **kw):
    """Compiled wave step via the AOT executable cache
    (``backends/aotcache.py``), chunk donated.  On a single real device
    the compiled program persists to disk (a fresh process loads instead
    of re-paying the remote compile — the stream-step rationale); on the
    multi-device virtual mesh the cache compiles in-process and serves
    as the per-shape memo, skipping jit's per-call dispatch machinery on
    the wave hot path."""
    from dsi_tpu.backends import aotcache
    from dsi_tpu.device.table import _quiet_unusable_donation

    name, fn = _wave_program(**kw)
    with _quiet_unusable_donation():  # a cold entry compiles right here
        return aotcache.cached_compile(name, fn, example_args,
                                       donate_argnums=_WAVE_DONATE,
                                       x64=True)


def plan_waves(doc_lens: Sequence[int],
               n_dev: int) -> List[Tuple[List[int], int]]:
    """Assign documents to waves of ``n_dev``, longest-first.

    Returns ``[(doc_indices, chunk_size), ...]`` where ``chunk_size`` is the
    power of two holding that wave's OWN longest document (min 256).
    Longest-first grouping makes sizes non-increasing across waves, so the
    number of distinct compiled shapes is bounded by the log2 spread of
    document sizes — a single 10x outlier adds exactly one shape
    (VERDICT r2 weakness #3) — and the peak device buffer of a wave tracks
    that wave's documents, not the global maximum.
    """
    order = sorted(range(len(doc_lens)), key=lambda i: doc_lens[i],
                   reverse=True)
    waves = []
    for w in range(0, len(order), n_dev):
        idxs = order[w:w + n_dev]
        longest = max(doc_lens[i] for i in idxs)
        waves.append((idxs, 1 << max(8, int(longest).bit_length())))
    return waves


def _wave_chunk(docs: Sequence[bytes], idxs: Sequence[int], n_dev: int,
                size: int) -> np.ndarray:
    """Materialise ONE wave's [n_dev, size] padded block lazily — padding
    the whole corpus up front would allocate n_docs x pow2(longest) bytes
    (one big document among many small ones inflates it catastrophically);
    per-wave blocks keep host memory O(wave's own longest)."""
    out = np.zeros((n_dev, size), dtype=np.uint8)
    for r, i in enumerate(idxs):
        out[r, :len(docs[i])] = np.frombuffer(docs[i], dtype=np.uint8)
    return out


class _AbortRung(Exception):
    """A wave proved this capacity/word-window rung's results will be
    discarded (non-ASCII input, or a word wider than the packed window):
    unwind the pipeline — dispatching more waves is pure waste."""


class TfidfStep(_EngineStep):
    """Resumable step object over the TF-IDF wave walk —
    :func:`tfidf_sharded`'s parameters and semantics behind the
    ``{advance, confirm, checkpoint, restore, close}`` lifecycle
    (``parallel/stepobj.py``).  The word-window rung ladder lives
    inside the lifecycle: a wave proving the rung too narrow tears it
    down and ``advance()`` restarts at the 64-byte rung; non-ASCII
    input (or a word wider than 64 bytes) routes to the host path."""

    _rung_excs = (_AbortRung,)

    def __init__(self, docs: Sequence[bytes], mesh: Mesh | None = None,
                 n_reduce: int = 10, max_word_len: int = 16,
                 u_cap: int = 1 << 15, partitions: Optional[set] = None,
                 packed: bool = False, device_accumulate: bool = False,
                 sync_every: Optional[int] = None,
                 mesh_shards: Optional[int] = None,
                 wave_stats: Optional[dict] = None,
                 depth: Optional[int] = None,
                 checkpoint_dir: Optional[str] = None,
                 checkpoint_every: Optional[int] = None,
                 checkpoint_async: Optional[bool] = None,
                 checkpoint_delta: Optional[bool] = None,
                 resume: bool = False,
                 input_range: Optional[tuple] = None):
        super().__init__()
        _tfidf_setup(self, docs, mesh, n_reduce, max_word_len, u_cap,
                     partitions, packed, device_accumulate, sync_every,
                     mesh_shards, wave_stats, depth, checkpoint_dir,
                     checkpoint_every, checkpoint_async,
                     checkpoint_delta, resume, input_range)

    def _next_rung(self) -> bool:
        self._pipe.end()
        if self._writer is not None:
            self._writer.shutdown()  # a rung restart discards rung state
        if not self._outcome["high"]:
            nxt = [m for m in self._rungs if m > self._mwl]
            if nxt:
                self._begin_rung(nxt[0])
                return True
        # Non-ASCII, or a word wider than 64 bytes: the host path's job.
        self.result = None
        self._phase = "hostpath"
        return False


def tfidf_sharded(
        docs: Sequence[bytes], mesh: Mesh | None = None, n_reduce: int = 10,
        max_word_len: int = 16, u_cap: int = 1 << 15,
        partitions: Optional[set] = None, packed: bool = False,
        device_accumulate: bool = False, sync_every: Optional[int] = None,
        mesh_shards: Optional[int] = None,
        wave_stats: Optional[dict] = None, depth: Optional[int] = None,
        checkpoint_dir: Optional[str] = None,
        checkpoint_every: Optional[int] = None,
        checkpoint_async: Optional[bool] = None,
        checkpoint_delta: Optional[bool] = None, resume: bool = False,
):
    """Whole-corpus TF-IDF over the mesh, waves of n_dev documents,
    pipelined ``depth`` waves deep.

    Returns ``{word: (reduce_partition, [(doc_index, tf), ...])}`` — exact,
    or None when any document needs the host path (non-ASCII bytes, words
    longer than 64).  Same exactness discipline as ``wordcount_streaming``:
    waves dispatch optimistically at a sticky (capacity, grouper, frac)
    rung, their scalar checks are deferred until they leave the in-flight
    window (``depth - 1`` waves late), and a failed check replays exactly
    that wave through the ladder at the wider — then sticky — shape.
    Results are bit-identical to the ``depth=1`` lockstep path: the
    accumulator only ever ingests a wave already proven exact, in wave
    order, and a wave's valid rows (content and device-sorted order) do
    not depend on the capacity rung that produced them.

    ``depth`` (default ``DSI_STREAM_PIPELINE_DEPTH``, 2) is the in-flight
    wave window, driven by the shared dispatch/finish pipeline core
    (``parallel/pipeline.py``): a background materializer thread builds
    ``_wave_chunk`` blocks into a bounded queue while the main thread
    uploads (chunk DONATED to the kernel — an in-flight window holds at
    most ``depth`` chunk buffers in HBM) and dispatches ahead without
    synchronizing.  ``depth=1`` is fully synchronous: no thread,
    dispatch then check.

    ``partitions`` restricts the host accumulator to those reduce
    partitions — the module's large-corpus story made concrete: running the
    job once per partition slice divides the O(total postings) host memory
    by the number of slices (device work repeats per slice; the partition
    id rides every shuffled row, so filtering costs nothing extra).  The
    slices' union is exactly the unfiltered result.

    ``packed=True`` returns the ``merge.PackedPostings`` numpy tables
    instead of the dict — ~32 B/posting instead of ~250 B of Python
    objects, the difference between a bounded and an input-proportional
    host footprint at GB scale.  ``docs`` may be any sequence yielding
    bytes on ``__getitem__`` (e.g. :class:`FileDocs`, which reads each
    document from disk per wave instead of holding the corpus resident);
    a ``lengths`` attribute, when present, avoids loading documents just
    to size the waves.

    ``device_accumulate=True`` batches the wave walk's D2H through the
    device-resident accumulator service: each CONFIRMED wave's received
    rows APPEND into a persistent on-device postings buffer
    (``device/postings.py``, append flags lagged by the pipeline depth)
    and the host pulls once per ``sync_every`` waves
    (``DSI_STREAM_SYNC_EVERY`` default, 8) or when the buffer fills —
    amortizing the tunnel's fixed per-pull latency exactly as the
    streaming engine's fold does.  Results are identical: the same rows
    reach the same ``PostingsTable`` in the same per-device order (the
    buffer's sticky-overflow protocol preserves wave order through
    recovery), and the padding-doc/partition filters run at drain time.
    ``mesh_shards`` (default ``DSI_STREAM_MESH_SHARDS``; implies
    ``device_accumulate``) re-routes the buffered rows by
    ``ihash(word) % n_shards`` inside the compiled append — the
    mesh-sharded service treatment (``device/table.py`` module docs),
    bit-identical results included.

    ``wave_stats``, if given, is populated with the per-phase wall
    seconds ``wave_phases`` mirrors of ``stream_phases``:
    ``materialize_s`` (background wave build), ``materialize_wait_s``
    (main-thread starvation), ``upload_s``, ``kernel_s`` (time blocked
    on a wave's deferred scalar check), ``pull_s``, ``merge_s``,
    ``replay_s`` — plus ``waves``, ``depth``, ``replays``,
    ``max_inflight_waves``, ``step_pulls``, and the device-accumulate
    counters (``appends``/``append_overflows``/``sync_pulls``/
    ``postings_widens``/``append_s``/``drain_s``/``sync_every``).

    ``checkpoint_dir``/``checkpoint_every``/``resume`` follow the
    streaming engines' crash-resume contract (``dsi_tpu/ckpt``): the
    cursor is the CONFIRMED-wave ordinal (``plan_waves`` is
    deterministic in doc lengths), snapshots carry the postings-table
    residue, the device buffer's drain-free image, and the sticky rung,
    tagged with the word-window rung they belong to; resumed output is
    bit-identical to an uninterrupted walk.
    """
    return TfidfStep(
        docs, mesh=mesh, n_reduce=n_reduce, max_word_len=max_word_len,
        u_cap=u_cap, partitions=partitions, packed=packed,
        device_accumulate=device_accumulate, sync_every=sync_every,
        mesh_shards=mesh_shards, wave_stats=wave_stats, depth=depth,
        checkpoint_dir=checkpoint_dir, checkpoint_every=checkpoint_every,
        checkpoint_async=checkpoint_async,
        checkpoint_delta=checkpoint_delta, resume=resume).close()


def _tfidf_setup(step, docs, mesh, n_reduce, max_word_len, u_cap,
                 partitions, packed, device_accumulate, sync_every,
                 mesh_shards, wave_stats, depth, checkpoint_dir,
                 checkpoint_every, checkpoint_async, checkpoint_delta,
                 resume, input_range=None):
    """The engine body behind :class:`TfidfStep`: corpus-wide setup,
    then ``begin_rung`` (the former per-rung ``run``) arms the pipeline
    and attaches the lifecycle hooks to ``step``.

    ``input_range`` is the shard scheduler's cursor range in DOC
    ordinals (mr/shards.py): drive ``docs[start:end]`` and tag the
    chain identity with the range so attempts over different ranges
    can never cross-restore."""
    if input_range is not None:
        docs = docs[int(input_range[0]):int(input_range[1])]
    if mesh is None:
        mesh = default_mesh()
    n_dev = mesh.devices.size
    depth = pipeline_depth(depth)
    from dsi_tpu.device.policy import mesh_shards_default

    mesh_shards = mesh_shards_default(mesh_shards)
    if mesh_shards:
        device_accumulate = True
    doc_lens = getattr(docs, "lengths", None)
    if doc_lens is None:
        doc_lens = [len(d) for d in docs]
    waves = plan_waves(doc_lens, n_dev)
    longest = max(doc_lens, default=1)
    size_max = 1 << max(8, int(longest).bit_length())  # capacity hard ref
    n_real = len(docs)
    # Internal registry scope (dsi_tpu/obs); copied out to the caller's
    # ``wave_stats`` dict when the walk ends — wave_phases is a view
    # over the one documented schema, not its own dialect.
    stats = metrics_scope("tfidf")
    stats.update({"waves": len(waves), "step_pulls": 0, "depth": depth,
                  "replays": 0, "device_accumulate": device_accumulate,
                  "upload_s": 0.0, "kernel_s": 0.0, "pull_s": 0.0,
                  "merge_s": 0.0, "replay_s": 0.0})
    groupers = grouper_ladder()
    sh_chunk = NamedSharding(mesh, P(AXIS, None))
    sh_ids = NamedSharding(mesh, P(AXIS))

    # ── checkpoint/restore (dsi_tpu/ckpt): wave-cursor variant ──
    ck_store: Optional[CheckpointStore] = None
    resume_meta = None
    resume_arrays = None
    resume_deltas: list = []
    ck_async = checkpoint_async_default(checkpoint_async)
    ck_delta = checkpoint_delta_default(checkpoint_delta)
    if checkpoint_dir:
        import zlib

        # The wave plan — and with it the cursor's meaning — is a
        # function of the full per-doc length vector, so the vector's
        # CRC is part of the job identity: same count + same total with
        # shuffled lengths must refuse, not silently misalign waves.
        lens_crc = zlib.crc32(np.asarray(doc_lens, np.int64).tobytes())
        ident = {"n_dev": n_dev, "n_reduce": n_reduce, "u_cap": u_cap,
                 "n_docs": n_real, "doc_lens_crc32": lens_crc,
                 "partitions": (sorted(int(p) for p in partitions)
                                if partitions is not None else None),
                 "device_accumulate": bool(device_accumulate)}
        if input_range is not None:
            ident["input_range"] = [int(input_range[0]),
                                    int(input_range[1])]
        ck_store = CheckpointStore(checkpoint_dir, "tfidf", ident)
        if resume:
            loaded = ck_store.load_latest_chain()
            if loaded is not None:
                resume_meta, resume_arrays, resume_deltas = loaded
        else:
            ck_store.reset()

    def begin_rung(mwl: int):
        """One word-window rung: arm the pipelined wave walk at packed
        width ``mwl`` and attach its hooks to ``step``.  Capacity
        overflow never discards the rung — the overflowing wave alone
        replays wider and the widened capacity sticks; non-ASCII and
        word-window overflow raise ``_AbortRung`` through the
        lifecycle, which restarts wider or routes to the host path."""
        kk = mwl // 4
        # Buffer each wave's surviving rows AS THE WAVES CONFIRM — raw
        # uint32 tables copied out of the wave's transfer buffer (no
        # device-shaped block stays alive), grouped/decoded once at
        # payload time by the vectorized PostingsTable (parallel/
        # merge.py).  Host state is O(postings in this slice).  A
        # discarded rung (word-window widen) drops the whole table, so
        # partial rungs can't leak into the result.
        table = PostingsTable()
        part_arr = (None if partitions is None
                    else np.fromiter(partitions, dtype=np.uint32))
        # Sticky dispatch rung, exactly the streaming engine's: only
        # ever moves toward more headroom, so a corpus that widens once
        # doesn't replay every later wave.
        state = {"cap": rung0_cap(size_max, u_cap),
                 "grouper": groupers[0], "frac": 4}
        outcome = {"high": False, "widen": False}

        def buffer_rows(r: np.ndarray) -> None:
            """One device's pulled rows into the host table, filtered
            FIRST: the short last wave's padding documents and — for a
            partition slice — other slices' rows must cut the per-slice
            host cost, not just the final table (same rule on both the
            per-wave and the drain path)."""
            r = r[r[:, kk + 2] < n_real]
            if part_arr is not None:
                r = r[np.isin(r[:, kk + 3], part_arr)]
            if len(r):
                table.add(r, kk)

        # Device-resident accumulation (fresh per rung — a rung restart
        # discards partial device state exactly like the host table):
        # confirmed waves append on-device with lagged flags, the host
        # pulls per K-wave window; overflow drains early (or widens for
        # a lone outsized wave) — never a loss, and wave order survives
        # recovery (device/postings.py sticky-overflow protocol).
        buf_dev = None
        policy = None
        if device_accumulate:
            import os

            from dsi_tpu.device import DevicePostings, SyncPolicy

            # One worst-case wave by default (so drain-and-retry always
            # fits); DSI_DEVICE_POSTINGS_CAP trims it for HBM-tight
            # meshes (overflow then just syncs earlier) and lets tests
            # force the early-drain path.
            try:
                pcap = int(os.environ.get("DSI_DEVICE_POSTINGS_CAP", "0"))
            except ValueError:
                pcap = 0
            buf_dev = DevicePostings(
                mesh, width=kk + 4,
                cap=pcap if pcap > 0 else n_dev * state["cap"],
                sink=buffer_rows, lag=max(0, depth - 1), stats=stats,
                mesh_shards=mesh_shards, kk=kk)
            policy = SyncPolicy(sync_every)
            stats["sync_every"] = policy.sync_every
            stats["mesh_shards"] = mesh_shards

        # A checkpoint belongs to ONE word-window rung (the widen
        # restart discards rung state): apply the loaded image only at
        # its own rung.
        ck_policy: Optional[CheckpointPolicy] = None
        ck_writer: Optional[CheckpointWriter] = None
        ck_wave = [0]
        host_delta = HostDeltaLog()  # non-dacc delta log: trimmed copies
        # of the pulled (rows, nrows) waves, bounded like device logs
        start_wave = 0
        if ck_store is not None:
            ck_policy = CheckpointPolicy(checkpoint_every)
            stats.setdefault("ckpt_saves", 0)
            stats.setdefault("ckpt_s", 0.0)
            stats.setdefault("ckpt_capture_s", 0.0)
            stats["ckpt_every"] = ck_policy.every
            stats["ckpt_async"] = ck_async
            stats["ckpt_delta"] = ck_delta
            # A fresh writer per rung: a rung restart discards rung
            # state, so its first save is a full base again.
            ck_writer = CheckpointWriter(ck_store, stats, async_=ck_async,
                                         delta=ck_delta)
            if ck_delta and buf_dev is not None:
                buf_dev.enable_delta()
            # Cursor/rung state is newest-wins: the final delta's meta
            # IS the restore point; the base meta names image shapes.
            eff = resume_deltas[-1][0] if resume_deltas else resume_meta
            if eff is not None and int(eff["mwl"]) == mwl:
                t_res = time.perf_counter()
                start_wave = int(eff["wave"])
                ck_wave[0] = start_wave
                state.update({"cap": int(eff["cap"]),
                              "grouper": eff["grouper"],
                              "frac": int(eff["frac"])})
                table.restore({k[3:]: v for k, v in resume_arrays.items()
                               if k.startswith("pt_")})
                if buf_dev is not None and resume_meta.get("pb_cap"):
                    pb_img = {"buf": resume_arrays["pb_buf"],
                              "nrows": resume_arrays["pb_nrows"],
                              "cap": resume_meta["pb_cap"]}
                    saved_shards = int(resume_meta.get("mesh_shards", 0))
                    if resume_deltas or saved_shards != mesh_shards:
                        # Chain restore (and the sharding-degree
                        # change) re-enters via the drain path — the
                        # buffered rows into the host table, buffer
                        # empty; resumed waves rebuild device state.
                        DevicePostings.drain_image(buffer_rows, pb_img)
                        if saved_shards != mesh_shards:
                            stats["resharded_resume"] = saved_shards
                    else:
                        buf_dev.restore_state(pb_img)
                        if ck_delta:
                            buf_dev.enable_delta()
                if policy is not None:
                    policy.restore(eff.get("sync_since", 0))
                for _, darr in resume_deltas:
                    # Each delta's retained wave payloads re-enter the
                    # host table through the sink in save order —
                    # per-word posting order preserved, the drain-path
                    # argument the cross-degree resume rests on.
                    drain_posting_steps(buffer_rows, darr, "pb_")
                stats["resume_gap_s"] = round(
                    time.perf_counter() - t_res, 4)
                stats["resume_wave"] = start_wave

        def save_ckpt() -> None:
            """Consistent snapshot at a confirmed-wave boundary —
            capture here, commit inline or in the background writer
            (``ckpt/writer.py``): the device buffer's capture FIRST
            (flushing its lag can drain into the host table), host
            residue second.  A delta save ships only the wave payloads
            retained since the previous save; every
            ``DSI_STREAM_CKPT_REBASE``-th save is a full re-base (an
            invalid delta window forces one)."""
            with _span("ckpt", stats=stats, key="ckpt_s",
                       wave=ck_wave[0]):
                meta = {"mwl": mwl, "wave": ck_wave[0],
                        "cap": state["cap"], "grouper": state["grouper"],
                        "frac": state["frac"]}
                kind = "full"
                parts = None
                with _span("ckpt_capture", lane="ckpt", stats=stats,
                           key="ckpt_capture_s"):
                    if ck_writer.want_delta():
                        if buf_dev is not None:
                            entries = buf_dev.take_delta()
                        else:
                            entries = host_delta.take()
                        if entries is not None:
                            parts = [("pb_", DeltaSteps(entries))]
                            if policy is not None:
                                meta["sync_since"] = policy.snapshot()
                            kind = "delta"
                    if parts is None:
                        # Full image — the PR-5 arrays (device pull
                        # dispatched, not awaited); the delta logs
                        # reset here: payloads recorded before this
                        # base are inside the image.
                        parts = []
                        if buf_dev is not None:
                            parts.append(("pb_",
                                          buf_dev.checkpoint_capture()))
                            meta["pb_cap"] = buf_dev.cap
                            meta["mesh_shards"] = buf_dev.mesh_shards
                            meta["sync_since"] = policy.snapshot()
                            if ck_delta:
                                buf_dev.take_delta()
                        host_delta.reset()
                        parts.append(("pt_", table.snapshot()))
                fault_point("mid-capture")
                ck_writer.commit(parts, meta, kind=kind)

        def materialize():
            for idxs, size in waves[start_wave:]:
                chunk_np = _wave_chunk(docs, idxs, n_dev, size)
                # Pad rows of a short last wave carry doc id n_real,
                # which buffer_rows discards.
                ids_np = np.array(list(idxs) + [n_real] * (n_dev - len(idxs)),
                                  dtype=np.int32)
                yield (size, chunk_np, ids_np)

        def wave_call(chunk_np, ids_np, size, cap, frac, g):
            """Upload + async wave dispatch at one rung.  Each attempt
            re-uploads: the compiled program donates its chunk."""
            with _span("upload", stats=stats, key="upload_s"):
                chunk = jax.device_put(chunk_np, sh_chunk)
                ids = jax.device_put(ids_np, sh_ids)
            fn = _wave_fn((chunk, ids), n_dev=n_dev, n_reduce=n_reduce,
                          max_word_len=mwl, u_cap=cap, size=size,
                          mesh=mesh, t_cap_frac=frac, grouper=g)
            from dsi_tpu.device.table import _quiet_unusable_donation

            with _quiet_unusable_donation():
                return fn(chunk, ids)

        def dispatch(item):
            size, chunk_np, ids_np = item
            rows, scal = wave_call(chunk_np, ids_np, size, state["cap"],
                                   state["frac"], state["grouper"])
            fault_point("post-dispatch")
            return (size, chunk_np, ids_np, rows, scal, state["cap"])

        def replay_wave(size, chunk_np, ids_np):
            """The full exactness ladder for ONE wave — the replay path
            of a deferred-check failure.  The cleared rung sticks for
            every later dispatch."""
            stats["replays"] += 1
            cap = state["cap"]
            with _span("replay", stats=stats, key="replay_s"):
                while True:
                    for g in groupers:
                        for frac in (4, 2):
                            rows, scal = wave_call(chunk_np, ids_np, size,
                                                   cap, frac, g)
                            scal_np = np.asarray(scal)
                            if not scal_np[:, 4].any():
                                break
                        if not scal_np[:, 4].any():
                            break
                    if bool(scal_np[:, 3].any()):
                        outcome["high"] = True
                        raise _AbortRung
                    if int(scal_np[:, 2].max()) > mwl:
                        outcome["widen"] = True
                        raise _AbortRung
                    if int(scal_np[:, 1].max()) > cap:
                        cap *= 4  # uniques <= tokens <= size/2: terminates
                        continue
                    break
            state["cap"], state["grouper"], state["frac"] = cap, g, frac
            return rows, scal, scal_np

        def commit(rows, scal, scal_np):
            m = int(scal_np[:, 0].max())
            if m == 0:
                return
            if buf_dev is not None:
                pulls_before = stats["sync_pulls"]
                buf_dev.append(rows, scal,
                               nvalid=scal_np[:, 0].astype(np.int64))
                policy.note_fold()
                if stats["sync_pulls"] != pulls_before:
                    policy.reset()  # an overflow recovery just drained:
                    # that WAS this window's pull — without the reset,
                    # due() would fire a second, nearly empty one
                elif policy.due():
                    fault_point("pre-sync")
                    buf_dev.sync()
                    policy.reset()
                return
            # Pull only the occupied prefix (max per-device received
            # rows, pow2-rounded to bound the slice-program count): the
            # D2H bill tracks this wave's postings, not capacity.
            with _span("pull", stats=stats, key="pull_s"):
                mp = occupied_prefix(m, rows.shape[1])
                rows_np = np.asarray(rows[:, :mp])
                stats["step_pulls"] += 1
            with _span("merge", stats=stats, key="merge_s"):
                for d in range(n_dev):
                    nr = int(scal_np[d, 0])
                    if nr:
                        buffer_rows(rows_np[d, :nr])
                if ck_store is not None and ck_delta:
                    # Host-merge delta log: the wave's payload, window-
                    # bounded like the device logs.
                    host_delta.append(rows_np, scal_np[:, 0])

        def finish(rec):
            """Retire the oldest in-flight wave: deferred scalar check,
            then commit (clean) or replay-at-wider-shape (overflow)."""
            size, chunk_np, ids_np, rows, scal, cap = rec
            with _span("kernel", stats=stats, key="kernel_s"):
                scal_np = np.asarray(scal)  # blocks until the kernel lands
            if bool(scal_np[:, 3].any()):
                outcome["high"] = True
                raise _AbortRung
            if int(scal_np[:, 2].max()) > mwl:
                outcome["widen"] = True
                raise _AbortRung
            if scal_np[:, 4].any() or int(scal_np[:, 1].max()) > cap:
                # Late-detected overflow: replay just this wave.
                # Exactly-once by construction — the optimistic attempt's
                # rows are dropped uncommitted, the replay's commit here
                # and nowhere else.
                rows, scal, scal_np = replay_wave(size, chunk_np, ids_np)
            commit(rows, scal, scal_np)
            # Confirmed (empty waves included); fault before the cursor
            # moves — the torn-update instant.
            fault_point("mid-fold")
            if ck_policy is not None:
                ck_wave[0] += 1
                ck_policy.note_step()
                if ck_policy.due():
                    save_ckpt()
                    ck_policy.reset()

        pipe = StepPipeline(depth=depth, dispatch=dispatch, finish=finish,
                            stats=stats, produce_key="materialize_s",
                            wait_key="materialize_wait_s",
                            inflight_key="max_inflight_waves",
                            thread_name="dsi-wave-materializer",
                            engine="tfidf")
        step._pipe = pipe
        step._mwl = mwl
        step._outcome = outcome
        step._save = save_ckpt if ck_policy is not None else None
        step._writer = ck_writer
        pipe.begin(materialize)

        def end_ok():
            try:
                if buf_dev is not None:
                    fault_point("pre-sync")
                    buf_dev.close()  # end-of-walk sync
                if ck_writer is not None:
                    ck_writer.drain()  # surface async commit errors
                    # before the payload (and save counters) are read
            finally:
                if ck_writer is not None:
                    ck_writer.shutdown()
            step.result = (table.finalize_packed() if packed
                           else table.finalize())

        step._on_complete = end_ok

    # The word-window ladder (exactness_retry's outer rung, hand-rolled
    # because capacity now widens per wave INSIDE a rung): a word wider
    # than the packed window re-keys every row, so that one overflow
    # class still restarts the walk.
    rungs = ((max_word_len, 64) if max_word_len < 64 else (max_word_len,))
    if resume_meta is not None:
        # Start at the checkpoint's rung: an earlier rung had provably
        # aborted before the checkpointed rung began its walk.
        rungs = tuple(m for m in rungs
                      if m >= int(resume_meta["mwl"])) or rungs
    step._rungs = tuple(rungs)
    step._begin_rung = begin_rung

    released = []

    def release():
        if released:
            return
        released.append(True)
        w = step._writer  # the CURRENT rung's writer (re-set per rung)
        if w is not None:
            w.shutdown()
        fold_source_stats(stats, docs)  # a doc source may pool-read too
        if wave_stats is not None:
            wave_stats.update(stats)

    step._release = release
    begin_rung(rungs[0])


class FileDocs:
    """Lazy document sequence for :func:`tfidf_sharded`: documents load
    from disk per access (one wave's working set at a time) instead of
    holding the whole corpus resident — at the 1 GB soak that was 1.07 GB
    of the peak RSS (VERDICT r4 weakness #4)."""

    def __init__(self, paths: Sequence[str]):
        import os

        self.paths = list(paths)
        self.lengths = [os.path.getsize(p) for p in self.paths]

    def __len__(self) -> int:
        return len(self.paths)

    def __getitem__(self, i: int) -> bytes:
        with open(self.paths[i], "rb") as f:
            return f.read()


def write_tfidf_output(result: Dict[str, Tuple[int, List[Tuple[int, int]]]],
                       doc_names: Sequence[str], n_reduce: int,
                       workdir: str = ".") -> List[str]:
    """Materialise mr-out-<r> files byte-identical to the host tfidf app's
    reduce output: scores via the shared ``format_value``, files via the
    shared partitioned writer (``shuffle.write_partitioned_output``)."""
    from dsi_tpu.apps.tfidf import format_value
    from dsi_tpu.parallel.shuffle import write_partitioned_output

    n_docs = len(doc_names)
    formatted = {
        w: (format_value([(doc_names[d], tf) for d, tf in pairs], n_docs), r)
        for w, (r, pairs) in result.items()}
    return write_partitioned_output(formatted, n_reduce, workdir)
