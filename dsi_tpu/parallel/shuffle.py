"""SPMD MapReduce step: map + shuffle + reduce as ONE compiled program.

This is the multi-device redesign of the reference's whole data path:

* map phase  = per-device tokenize/group (``tokenize_group_core``), replacing
  the worker's mapf + bucketing hot loops (``mr/worker.go:69-92``),
* shuffle    = ``jax.lax.all_to_all`` over the device mesh, replacing the
  NxM ``mr-<m>-<r>`` intermediate files on a shared filesystem
  (``mr/worker.go:81-92, 102-121``) — the exchange rides ICI, not disk,
* reduce     = per-device sort + segment-sum of the received records,
  replacing the reduce task's decode/sort/group/count
  (``mr/worker.go:110-146``).

Partitioning semantics are bit-identical to the reference: a word belongs to
reduce partition ``r = fnv1a32(word) & 0x7fffffff % NReduce``
(``mr/worker.go:33-37,76``); partitions are mapped to devices round-robin
(``r % n_dev``), so every device ends up owning exactly the reduce partitions
``{r : r % n_dev == device}`` and the map-barrier-then-reduce structure of the
reference (``mr/coordinator.go:47,79``) is preserved *inside* the program: the
all_to_all is the barrier.

Everything is static-shaped for XLA: the send buffer gives each destination a
fixed ``u_cap``-row block (a device has at most ``u_cap`` unique words total,
so a per-destination block of the same size can never overflow); pad rows
carry key ``0xFFFFFFFF`` which sorts after every real ASCII word.  Exactness
escapes (non-ASCII bytes, words longer than ``max_word_len``, more uniques
than ``u_cap``) are returned as per-device flags; the host wrapper retries
with wider shapes or falls back to the host path, so results are always
exact (same discipline as ``ops/wordcount.py``).
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from dsi_tpu.ops.wordcount import (
    _PAD_KEY,
    exactness_retry,
    group_sorted,
    pack_key_lanes,
    tokenize_group_core,
    unpack_key_rows,
)

from dsi_tpu.utils.jaxcompat import (enable_x64, x64_scoped,
                                     shard_map as _shard_map)

AXIS = "workers"


def default_mesh(n_devices: int | None = None) -> Mesh:
    """1-D device mesh over the first n (default: all) local devices."""
    devs = jax.devices()
    if n_devices is not None:
        if len(devs) < n_devices:
            raise ValueError(f"need {n_devices} devices, have {len(devs)}")
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (AXIS,))


def shuffle_rows(rows: jax.Array, dest: jax.Array, *, n_dev: int,
                 u_cap: int, k: int) -> jax.Array:
    """Route per-word rows to their destination devices over ICI.

    The shared shuffle primitive of every SPMD job step (word count here,
    TF-IDF in ``parallel/tfidf.py``): scatter ``rows`` [u_cap, k+p] (k word
    key lanes + p payload lanes) into one fixed ``u_cap``-row block per
    destination — a device has at most ``u_cap`` rows total, so a
    per-destination block of the same size can never overflow — then one
    ``lax.all_to_all``.  ``dest`` must be ``n_dev`` for invalid rows (they
    are parked on the scatter's overflow row and dropped).  Pad rows carry
    key ``0xFFFFFFFF``, which sorts after every real ASCII word.
    """
    p = rows.shape[1] - k
    order = jnp.argsort(dest, stable=True)
    sdest = dest[order]
    srows = rows[order]
    counts = jnp.bincount(sdest, length=n_dev + 1).astype(jnp.int32)
    starts = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(counts)[:-1]])
    pos_in = jnp.arange(u_cap, dtype=jnp.int32) - starts[sdest]
    flat = jnp.where(sdest < n_dev, sdest * u_cap + pos_in, n_dev * u_cap)
    pad_row = jnp.concatenate(
        [jnp.full((k,), _PAD_KEY, jnp.uint32), jnp.zeros((p,), jnp.uint32)])
    sendbuf = jnp.broadcast_to(pad_row, (n_dev * u_cap + 1, k + p))
    sendbuf = sendbuf.at[flat].set(srows)[:n_dev * u_cap]
    return lax.all_to_all(sendbuf, AXIS, split_axis=0, concat_axis=0,
                          tiled=True)


def map_prologue(chunk: jax.Array, *, n_dev: int, n_reduce: int,
                 max_word_len: int, u_cap: int, t_cap_frac: int,
                 grouper: str = "sort"):
    """Shared per-device map phase: tokenize + combine + partition.

    The one place the reference-parity partition rule lives on device:
    ``part = fnv1a32(word) & 0x7fffffff % n_reduce`` (mr/worker.go:33-37,76)
    with destination device ``part % n_dev`` (invalid rows parked on
    ``n_dev`` for :func:`shuffle_rows`).  Used by the word-count step here
    and the TF-IDF step (``parallel/tfidf.py``) so the two SPMD jobs cannot
    drift apart.

    Returns (packed_u, len_u, cnt_u, part, dest, scalars) where scalars =
    (n_unique, max_len, has_high, token_overflow).
    """
    (packed_u, len_u, cnt_u, fnv_u, n_unique, max_len, has_high,
     token_overflow) = tokenize_group_core(
        chunk, max_word_len=max_word_len, u_cap=u_cap, t_cap_frac=t_cap_frac,
        grouper=grouper)
    uvalid = jnp.arange(u_cap, dtype=jnp.int32) < n_unique
    part = (fnv_u & jnp.uint32(0x7FFFFFFF)) % jnp.uint32(n_reduce)
    dest = jnp.where(uvalid, (part % n_dev).astype(jnp.int32), n_dev)
    return (packed_u, len_u, cnt_u, part, dest,
            (n_unique, max_len, has_high, token_overflow))


def _device_step(chunk: jax.Array, *, n_dev: int, n_reduce: int,
                 max_word_len: int, u_cap: int, t_cap_frac: int,
                 grouper: str = "sort"):
    """Per-device body (runs under shard_map): map, all_to_all, reduce."""
    k = max_word_len // 4
    chunk = chunk.reshape(-1)  # [1, L] block -> [L]

    # ── map: tokenize + local combine (one record per unique word) ──
    packed_u, len_u, cnt_u, part, dest, (
        n_unique, max_len, has_high, token_overflow) = map_prologue(
        chunk, n_dev=n_dev, n_reduce=n_reduce, max_word_len=max_word_len,
        u_cap=u_cap, t_cap_frac=t_cap_frac, grouper=grouper)

    # ── shuffle: the mr-X-Y files become one ICI collective ──
    rows = jnp.concatenate(
        [packed_u, len_u[:, None].astype(jnp.uint32),
         cnt_u[:, None].astype(jnp.uint32), part[:, None]], axis=1)
    recv = shuffle_rows(rows, dest, n_dev=n_dev, u_cap=u_cap, k=k)

    # ── reduce: sort received records by word, sum counts per run
    #    (shared grouping idiom, ops/wordcount.py group_sorted; key lanes
    #    packed pairwise into uint64s — same order, half the comparator
    #    keys, see pack_key_lanes) ──
    out_cap = n_dev * u_cap
    with enable_x64(True):  # every op touching u64 operands needs it
        rkeys64 = pack_key_lanes(tuple(recv[:, j] for j in range(k)))
        k64 = len(rkeys64)
        rlen = recv[:, k]
        rcnt = recv[:, k + 1]
        rpart = recv[:, k + 2]
        sorted_ops = lax.sort(rkeys64 + (rlen, rcnt, rpart), num_keys=k64)
        mkeys64, tot, upos, ovalid, m_unique = group_sorted(
            sorted_ops[:k64], sorted_ops[k64 + 1].astype(jnp.int32),
            out_cap)
        mlen = sorted_ops[k64].astype(jnp.int32)
        mpart = sorted_ops[k64 + 2]
        mkeys64_u = jnp.where(ovalid[:, None], mkeys64[upos],
                              jnp.uint64(0))
        out_keys = unpack_key_rows(mkeys64_u, k)
    out_len = jnp.where(ovalid, mlen[upos], 0)
    out_part = jnp.where(ovalid, mpart[upos], 0)

    scalars = jnp.stack([m_unique, n_unique, max_len,
                         has_high.astype(jnp.int32),
                         token_overflow.astype(jnp.int32)])
    return (out_keys[None], out_len[None], tot[None], out_part[None],
            scalars[None])


def _mapreduce_step_impl(chunks: jax.Array, *, n_dev: int, n_reduce: int,
                         max_word_len: int, u_cap: int, mesh: Mesh,
                         t_cap_frac: int = 4, grouper: str = "sort"):
    """The full SPMD job step body — jitted twice below (with and without
    input-buffer donation) so the streaming engine's per-step uploads can
    be consumed by the kernel while ``wordcount_sharded`` keeps reusing
    one uploaded corpus across its retry attempts."""
    body = functools.partial(_device_step, n_dev=n_dev, n_reduce=n_reduce,
                             max_word_len=max_word_len, u_cap=u_cap,
                             t_cap_frac=t_cap_frac, grouper=grouper)
    return _shard_map(
        body, mesh=mesh,
        in_specs=P(AXIS, None),
        out_specs=(P(AXIS, None, None), P(AXIS, None), P(AXIS, None),
                   P(AXIS, None), P(AXIS, None)))(chunks)


_STEP_STATICS = ("n_dev", "n_reduce", "max_word_len", "u_cap", "t_cap_frac",
                 "mesh", "grouper")

#: The full SPMD job step, jitted over the mesh.
#:
#: ``chunks``: [n_dev, L] uint8, one zero-padded text shard per device.
#: Returns per-device arrays stacked on axis 0: packed word keys
#: [D, D*u_cap, K], byte lengths, summed counts, reduce-partition ids, and a
#: [D, 5] scalar block (m_unique, n_unique, max_len, has_high,
#: token_overflow).
#:
#: ``grouper`` (ops/wordcount.py default_grouper): with ``"hash"`` the
#: per-device map groups by scattered hash buckets instead of the big
#: sort; an unresolvable collision rides the token_overflow scalar and
#: the host wrapper re-runs the step with ``"sort"``.
mapreduce_step = x64_scoped(
    jax.jit(_mapreduce_step_impl, static_argnames=_STEP_STATICS))

#: Same program with the chunk buffer DONATED: the caller hands its upload
#: to the kernel, so an in-flight pipeline window holds at most one chunk
#: buffer per step in HBM (parallel/streaming.py).  A donated array cannot
#: be reused — streaming re-uploads per attempt; ``wordcount_sharded``
#: stays on the non-donated entry because it reuses one upload across its
#: whole retry ladder.
mapreduce_step_donate = x64_scoped(
    jax.jit(_mapreduce_step_impl, static_argnames=_STEP_STATICS,
            donate_argnums=(0,)))


def occupied_prefix(m: int, cap_rows: int) -> int:
    """Pow2-rounded occupied prefix of a ``cap_rows``-row result table with
    ``m`` valid rows (m >= 1): the one shape-bounding rule shared by every
    sliced D2H pull (here, streaming, TF-IDF), so the slice-program count
    stays at log2(cap) distinct shapes per path."""
    return min(cap_rows, 1 << max(6, (m - 1).bit_length()))


@functools.partial(jax.jit, static_argnames=("mp",))
def _slice_pack(keys, lens, cnts, parts, *, mp: int):
    """Device-side prefix slice + pack of a step's four result tables into
    ONE uint32 tensor [D, mp, K+3], so the host pays a single D2H
    round-trip per step instead of four (the axon tunnel charges ~0.1 s
    latency per pull regardless of size; D2H sustains only ~25 MB/s).
    ``mp`` is the pow2-rounded occupied prefix, so the bytes pulled track
    vocabulary, not capacity.  Lens/counts/partitions are uint32
    reinterpretations — all are small non-negative ints."""
    return jnp.concatenate(
        [keys[:, :mp],
         lens[:, :mp, None].astype(jnp.uint32),
         cnts[:, :mp, None].astype(jnp.uint32),
         parts[:, :mp, None].astype(jnp.uint32)], axis=2)


def shard_text(data: bytes, n_shards: int) -> Tuple[np.ndarray, int]:
    """Split text into n equal-ish device shards, cutting only at non-letter
    boundaries so no token straddles a shard (SURVEY.md §7 hard part 2), and
    zero-pad all shards to one power-of-two length.

    Returns ([n_shards, L] uint8, L).
    """
    n = len(data)
    cuts = [0]
    for i in range(1, n_shards):
        c = min(i * n // n_shards, n)
        # Advance past any letter run so data[c-1], data[c] are never both
        # letters (a cut inside a run would split a token).
        while 0 < c < n and _is_letter_byte(data[c - 1]) and \
                _is_letter_byte(data[c]):
            c += 1
        cuts.append(min(c, n))
    cuts.append(n)
    cuts = sorted(cuts)
    longest = max(cuts[i + 1] - cuts[i] for i in range(n_shards))
    size = 1 << max(8, longest.bit_length())
    out = np.zeros((n_shards, size), dtype=np.uint8)
    for i in range(n_shards):
        piece = data[cuts[i]:cuts[i + 1]]
        out[i, :len(piece)] = np.frombuffer(piece, dtype=np.uint8)
    return out, size


def _is_letter_byte(b: int) -> bool:
    return (65 <= b <= 90) or (97 <= b <= 122)


def wordcount_sharded(
        data: bytes, mesh: Mesh | None = None, n_reduce: int = 10,
        max_word_len: int = 16,
        u_cap: int = 1 << 15) -> Optional[Dict[str, Tuple[int, int]]]:
    """Count words over the whole corpus with one SPMD program per attempt.

    Returns ``{word: (count, reduce_partition)}`` — exact, or None when the
    input needs the host path (non-ASCII bytes or words longer than 64).
    Retries with wider static shapes on capacity overflow, mirroring
    ``ops.wordcount.count_words_host_result``.
    """
    if mesh is None:
        mesh = default_mesh()
    n_dev = mesh.devices.size
    chunks_np, shard_len = shard_text(data, n_dev)
    chunks = jnp.asarray(chunks_np)
    from dsi_tpu.ops.wordcount import grouper_ladder

    groupers = grouper_ladder()

    def run(mwl: int, cap: int):
        for g in groupers:
            for frac in (4, 2):  # exact token bound is n//2+1
                keys, lens, cnts, parts, scal = mapreduce_step(
                    chunks, n_dev=n_dev, n_reduce=n_reduce, max_word_len=mwl,
                    u_cap=cap, mesh=mesh, t_cap_frac=frac, grouper=g)
                scal = np.asarray(scal)
                if not scal[:, 4].any():
                    break
            if not scal[:, 4].any():
                break

        def payload():
            # One sliced single-pull per attempt (see _slice_pack), merged
            # host-side by the vectorized table (parallel/merge.py) — the
            # devices' tables are disjoint (each owns distinct reduce
            # partitions), so the merge is a pure concatenate+decode.
            from dsi_tpu.parallel.merge import PackedCounts

            m = int(scal[:, 0].max())
            if m == 0:
                return {}
            mp = occupied_prefix(m, keys.shape[1])
            kk = keys.shape[2]
            packed = np.asarray(_slice_pack(keys, lens, cnts, parts, mp=mp))
            acc = PackedCounts()
            for d in range(n_dev):
                nu = int(scal[d, 0])
                r = packed[d, :nu]
                acc.add(r[:, :kk], r[:, kk], r[:, kk + 1], r[:, kk + 2])
            return acc.finalize()

        return (bool(scal[:, 3].any()), int(scal[:, 1].max()),
                int(scal[:, 2].max()), payload)

    payload = exactness_retry(run, shard_len, max_word_len, u_cap)
    return None if payload is None else payload()


def write_partitioned_output(result: Dict[str, Tuple[int, int]],
                             n_reduce: int, workdir: str = ".") -> List[str]:
    """Materialise mr-out-<r> files from a sharded result — same file layout,
    line format ("%v %v\\n", mr/worker.go:144) and within-file key order the
    reference's reduce tasks produce (worker.go:124-146)."""
    import os

    from dsi_tpu.utils.atomicio import atomic_write

    by_part: List[List[Tuple[str, int]]] = [[] for _ in range(n_reduce)]
    for w, (c, r) in result.items():
        by_part[r].append((w, c))
    paths = []
    for r in range(n_reduce):
        path = os.path.join(workdir, f"mr-out-{r}")
        with atomic_write(path) as f:
            for w, c in sorted(by_part[r]):
                f.write(f"{w} {c}\n")
        paths.append(path)
    return paths
