"""Multi-device SPMD execution: the on-chip data plane.

The reference's shuffle is NxM JSON files on a shared filesystem
(``mr/worker.go:81-92, 102-121``).  Here the same exchange is a single
``jax.lax.all_to_all`` over the ICI mesh inside one compiled SPMD program —
SURVEY.md §2's prescribed TPU-native equivalent and §7 step 5.
"""

from dsi_tpu.parallel.shuffle import (  # noqa: F401
    default_mesh,
    shard_text,
    wordcount_sharded,
)
