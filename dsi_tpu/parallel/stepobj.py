"""Resumable step objects: the engines as explicit state machines.

Every streaming engine used to be a run-to-completion function — setup,
``StepPipeline.run``, finalize — which is the wrong shape for a serving
daemon: a resident process multiplexing many tenants needs to *hold* a
partially-run engine, advance it a few steps, checkpoint it at a
confirmed boundary, evict it to disk, and resume it later.  This module
defines the one lifecycle all four engines now implement (the ROADMAP's
serving-daemon prerequisite, and the substrate the multi-stage dataflow
item composes):

* ``advance()``  — one turn of the crank: dispatch the next item,
  retiring the oldest in-flight record when the window is full.
  Returns False when the engine is finished (input exhausted and the
  window drained, result built) or routed to the host path.
* ``confirm()``  — retire EVERY in-flight record, leaving the engine at
  a confirmed boundary (all merged output has passed its deferred
  exactness checks); returns the confirmed-step count.  This is the
  boundary-maker forced checkpoints and eviction stand on.
* ``checkpoint()`` — ``confirm()`` then one durable snapshot through
  the engine's own save path (store + writer + delta chain), drained
  so the manifest is on disk when the call returns.  False when the
  engine was built without a checkpoint dir.
* ``restore()``  — report of the restore performed at construction
  (``resume=True`` loads the newest valid chain before the first
  dispatch — restore is a *constructor-time* act because device state
  and sticky rungs must exist before anything is in flight).
* ``close()``    — finish the run (driving any remaining input),
  release every resource (producer thread, commit writer, stats
  copy-out), and return the engine result — or None on the host path.
* ``suspend()``  — eviction: ``checkpoint()`` then release, leaving a
  dead object whose chain a fresh ``resume=True`` construction
  continues bit-identically.

The state machine is deliberately thin: all engine logic stays in the
engine modules (``parallel/streaming.py``, ``parallel/grepstream.py``,
``parallel/tfidf.py``), whose step classes set the hooks below in their
``__init__`` and inherit the lifecycle.  The legacy functions
(``wordcount_streaming`` et al.) are now drivers over their step class
— construct, ``advance`` to exhaustion, ``close`` — so the pipelined
bit-identity guarantees carry over unchanged.

Subclass contract (attributes set by ``__init__``):

* ``_pipe``       — a begun :class:`~dsi_tpu.parallel.pipeline.StepPipeline`
  (or None when the job was routed to the host path at construction);
* ``_host_excs``  — exception types meaning "this input needs the host
  path" (result None, not an error);
* ``_rung_excs``  — exception types consumed by ``_next_rung()`` (the
  word-window rung restarts of the wave walks; default ());
* ``_on_complete``— zero-arg callable run once after the window drains
  at end of input: device-service close, writer drain, ``self.result``;
* ``_release``    — IDEMPOTENT zero-arg teardown: writer shutdown,
  stats copy-out;
* ``_save``       — zero-arg callable committing one snapshot at the
  current confirmed boundary (None = checkpointing off);
* ``_writer``     — the engine's :class:`~dsi_tpu.ckpt.CheckpointWriter`
  (None when sync or off) so ``checkpoint()`` can drain it durable.
"""

from __future__ import annotations

from typing import Optional


class EngineStep:
    """Base resumable step object (module docstring).  Phases:
    ``running`` → ``done`` | ``hostpath`` | ``failed`` | ``suspended``,
    any of which ``close()`` maps to a returned result (or None)."""

    #: Exception types that route the stream to the host path.
    _host_excs: tuple = ()
    #: Exception types consumed by :meth:`_next_rung`.
    _rung_excs: tuple = ()

    def __init__(self) -> None:
        self.result = None
        #: Stage-handoff surface (dsi_tpu/plan): engines that complete
        #: with live device state to pass downstream (e.g. the indexer's
        #: keep_services mode) publish it here; empty otherwise.
        self.exported: dict = {}
        self._phase = "running"
        self._pipe = None
        self._save = None
        self._writer = None
        self._restore_info: dict = {}
        self._on_complete = lambda: None
        self._release = lambda: None
        #: Live confirmed-cursor ref ({"offset": ...}), attached by
        #: engines that track a byte cursor; None otherwise.
        self._cursor_ref = None

    # ── hooks subclasses may override ──

    def _next_rung(self) -> bool:
        """Consume a rung-restart exception: tear the old rung down and
        begin the next one.  True when a fresh rung is armed (advance
        keeps going); False when the walk is over (phase already moved).
        The base class has no rungs."""
        return False

    # ── the lifecycle ──

    @property
    def phase(self) -> str:
        return self._phase

    @property
    def confirmed(self) -> int:
        """Steps retired through their deferred checks so far (current
        rung for the wave walks)."""
        return self._pipe.finished if self._pipe is not None else 0

    @property
    def cursor(self) -> int:
        """Confirmed input-byte cursor: the stream-relative offset just
        past the last CONFIRMED step's batch, live from the first
        confirmation (NOT only after a durable checkpoint — a young
        attempt's progress is visible before its first save).  0 for
        engines that don't track a byte cursor."""
        ref = self._cursor_ref
        return int(ref.get("offset", 0)) if ref else 0

    def advance(self) -> bool:
        """One turn of the crank; False when there is nothing left to
        do (finished, host path, or already released)."""
        if self._phase != "running":
            return False
        try:
            if self._pipe.pump():
                return True
            # Input exhausted: drain the window (deferred checks of the
            # tail), tear the producer down, then the engine epilogue —
            # the exact order the monolithic functions used.
            self._pipe.drain()
            self._pipe.end()
            self._on_complete()
            self._phase = "done"
            return False
        except self._rung_excs:
            return self._next_rung()
        except self._host_excs:
            self._to_hostpath()
            return False
        except BaseException:
            self._fail()
            raise

    def advance_slice(self, k: int) -> int:
        """Up to ``k`` turns of the crank; returns the turns taken
        (0 when the engine is already finished/routed).  The shared
        time-multiplexing primitive: the serving daemon drives each
        resident grep job one slice per scheduler pass, and a shard
        worker drives its shard one slice per progress heartbeat —
        both ride the same step objects."""
        n = 0
        while n < k and self.advance():
            n += 1
        return n

    def abort(self) -> None:
        """Cancel a running engine WITHOUT driving the remaining input
        — the speculative loser's path (first-commit-wins told it to
        stop): tear the pipeline down, release every resource, leave
        the object terminal with no result.  Idempotent; a no-op once
        the engine left the running phase."""
        if self._phase != "running":
            return
        try:
            if self._pipe is not None:
                self._pipe.end()
        finally:
            self._release()
        self.result = None
        self._phase = "cancelled"

    def confirm(self) -> int:
        """Retire every in-flight record; returns the confirmed count.
        After this the engine sits at a consistent boundary."""
        if self._phase == "running":
            try:
                self._pipe.drain()
            except self._rung_excs:
                self._next_rung()
            except self._host_excs:
                self._to_hostpath()
            except BaseException:
                self._fail()
                raise
        return self.confirmed

    def checkpoint(self) -> bool:
        """Force one durable snapshot at a confirmed boundary (the
        eviction primitive).  Returns False when checkpointing is off
        or the engine left the running phase."""
        self.confirm()
        if self._phase != "running" or self._save is None:
            return False
        self._save()
        if self._writer is not None:
            self._writer.drain()
        return True

    def restore(self) -> dict:
        """What the constructor-time restore did (``resume=True``):
        e.g. ``{"resume_cursor": ..., "resume_gap_s": ...}`` — empty
        when the engine started fresh."""
        return dict(self._restore_info)

    def suspend(self) -> bool:
        """Evict: checkpoint (when enabled) and release everything.
        The object is dead afterwards; a fresh construction with
        ``resume=True`` continues from the chain.  Returns whether a
        snapshot was committed."""
        if self._phase != "running":
            return False
        saved = self.checkpoint()
        if self._phase == "running":
            self._pipe.end()
            self._release()
            self._phase = "suspended"
        return saved

    def close(self):
        """Finish the run (driving any remaining input) and return the
        result — None on the host path or after a suspend.  Always
        releases resources; safe to call more than once."""
        while self.advance():
            pass
        self._release()
        return self.result

    # ── internal transitions ──

    def _to_hostpath(self) -> None:
        if self._pipe is not None:
            self._pipe.end()
        self.result = None
        self._phase = "hostpath"

    def _fail(self) -> None:
        self._phase = "failed"
        try:
            if self._pipe is not None:
                self._pipe.end()
        finally:
            self._release()


class HostPathStep(EngineStep):
    """A step object that was routed to the host path at construction
    (e.g. a non-literal grep pattern): already terminal, result None."""

    def __init__(self) -> None:
        super().__init__()
        self._phase = "hostpath"
