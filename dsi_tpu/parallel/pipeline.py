"""Reusable dispatch/finish pipeline core for device-step engines.

The PR-1 streaming word-count engine earned its throughput from four
mechanics that have nothing to do with word counting: a background
producer thread feeding a bounded queue (host item construction off the
critical path), a ``depth``-deep in-flight window (dispatch step k+1
before step k synchronizes), deferred per-step checks (a step's flags
are read only when it leaves the window, ``depth-1`` steps late), and a
small rotating host buffer pool (O(depth) allocations however long the
stream).  The TF-IDF wave walk has exactly the same cost shape — build
wave, upload, kernel, scalar check, pull, merge, every wave on the
critical path — so this module extracts the mechanics into one core
both engines consume (``parallel/streaming.py``,
``parallel/tfidf.py``).

The core is deliberately ignorant of devices and results: ``dispatch``
launches whatever async work one item needs and returns an opaque
record; ``finish`` retires the OLDEST in-flight record — that is where
a consumer blocks on flags, replays an overflowed step through its
exactness ladder, and merges confirmed output.  The window invariant
the core owns: records finish in dispatch order, a record finishes
exactly once, and at most ``depth`` records are ever in flight.
``depth=1`` degenerates to the fully synchronous loop — no thread, no
queue, dispatch-then-finish — which is why a consumer's pipelined and
lockstep paths are the same function and can be compared bit-for-bit.

Exceptions propagate both ways: a producer error re-raises in the
consumer thread (stop-aware, so it cannot be lost while the consumer
sits in a long replay), and a consumer exception unwinds through
``run`` with the producer thread shut down and its queue drained.
"""

from __future__ import annotations

import collections
import os
import queue
import threading
import time
from typing import Callable, Iterator, Optional, Sequence

import numpy as np

from dsi_tpu.obs import hist as _hist
from dsi_tpu.obs import span as _span


def pipeline_depth(depth: Optional[int] = None) -> int:
    """Resolve an engine's in-flight window: an explicit ``depth`` wins,
    else ``DSI_STREAM_PIPELINE_DEPTH`` (default 2), floored at 1 (the
    synchronous path).  One resolver for every pipeline consumer, so the
    stream and the wave walk cannot read the knob differently."""
    if depth is None:
        try:
            depth = int(os.environ.get("DSI_STREAM_PIPELINE_DEPTH", "2"))
        except ValueError:
            depth = 2
    return max(1, depth)


def fold_source_stats(stats: dict, source) -> None:
    """Fold a block source's ingest counters into an engine's metrics
    scope at release time.  The parallel reader pool
    (``utils/ioread.py ParallelBlocks``) exposes ``ingest_stats()``
    (``ingest_readers``/``ingest_blocks``/``readahead_hit_pct``/
    ``ingest_wait_s`` — all pinned in ``obs/registry.py SCHEMA_KEYS``);
    plain iterables have nothing to report and this is a no-op.  One
    helper for all four engines so the fold — and its
    never-trade-a-result-for-telemetry error policy — cannot drift."""
    fn = getattr(source, "ingest_stats", None)
    if not callable(fn):
        return
    try:
        stats.update(fn())
    except Exception:
        pass


class BufferPool:
    """Small rotating pool of reusable fixed-shape host buffers.

    ``take`` hands out a free buffer, allocating only when the pool is
    dry (startup, or the consumer still holds every buffer in its
    in-flight window); ``give`` returns one for reuse.  Never blocks —
    the pipeline's bounded queue provides the backpressure; the pool
    only removes the per-item ``np.zeros`` allocation + page-fault churn
    from the steady state.  ``allocs`` counts real allocations, so a
    caller can assert reuse (a stream of any length allocates O(depth)
    buffers).
    """

    def __init__(self, shape: Sequence[int], retain: int,
                 dtype=np.uint8):
        self._shape = tuple(shape)
        self._dtype = dtype
        self._free: collections.deque = collections.deque()
        self._lock = threading.Lock()
        self._retain = retain
        self.allocs = 0

    def take(self) -> np.ndarray:
        with self._lock:
            if self._free:
                return self._free.popleft()
            self.allocs += 1
        return np.zeros(self._shape, dtype=self._dtype)

    def give(self, buf: Optional[np.ndarray]) -> None:
        # Only host buffers re-enter the pool: a device-resident batch
        # (the plan layer's stage handoff feeds jax.Arrays through the
        # same dispatch/finish path) must never be handed to a writer.
        if not isinstance(buf, np.ndarray) or buf.shape != self._shape:
            return
        with self._lock:
            if len(self._free) < self._retain:
                self._free.append(buf)


class CommitWorker:
    """Single background worker draining submitted thunks FIFO — the
    consumer-side twin of the producer thread above, shared by the
    async checkpoint writer (``ckpt/writer.py``).

    The discipline mirrors the pipeline's: bounded in-flight work
    (``submit`` blocks while ``max_pending`` submissions are
    outstanding — the "barrier only when the NEXT save would overrun
    the one still draining" rule; the wait is returned so the caller
    can attribute it), strict submission order (one worker), and
    errors that cannot be lost — a thunk's exception is re-raised at
    the next ``submit``/``drain`` in the submitting thread, never
    swallowed while the pipeline keeps stepping.
    """

    def __init__(self, name: str = "dsi-commit-worker",
                 max_pending: int = 1):
        self._q: "queue.Queue" = queue.Queue()
        # The in-flight bound must count the thunk the worker is
        # RUNNING, not just queued ones (a bounded queue alone would
        # admit one running + one queued = max_pending + 1): a slot is
        # taken at submit and released only when the thunk finishes.
        self._slots = threading.BoundedSemaphore(max(1, max_pending))
        self._name = name
        self._thread: Optional[threading.Thread] = None
        self._err: Optional[BaseException] = None
        self._done = threading.Event()

    def _loop(self) -> None:
        while True:
            thunk = self._q.get()
            try:
                if thunk is None:
                    return
                if self._err is None:  # after an error: drain, don't run
                    thunk()
            except BaseException as e:
                self._err = e
            finally:
                self._q.task_done()
                if thunk is not None:
                    self._slots.release()

    def _ensure_thread(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(target=self._loop,
                                            daemon=True, name=self._name)
            self._thread.start()

    def _raise_pending(self) -> None:
        if self._err is not None:
            err, self._err = self._err, None
            raise err

    def submit(self, thunk: Callable[[], None]) -> float:
        """Enqueue one thunk; returns the seconds spent blocked waiting
        for an in-flight slot (0.0 when one was free).  Re-raises a
        prior thunk's error instead of enqueueing more work on a dead
        run."""
        self._raise_pending()
        self._ensure_thread()
        t0 = time.perf_counter()
        self._slots.acquire()
        self._q.put(thunk)
        waited = time.perf_counter() - t0
        return waited if waited > 1e-4 else 0.0

    def drain(self) -> float:
        """Wait until every submitted thunk finished; re-raise the first
        error.  Returns the seconds spent waiting."""
        if self._thread is None:
            self._raise_pending()
            return 0.0
        t0 = time.perf_counter()
        self._q.join()
        self._raise_pending()
        return time.perf_counter() - t0

    def shutdown(self) -> None:
        """Stop the worker after the queue drains, silently (for
        ``finally`` blocks already unwinding another exception — a
        pending commit error stays stored and surfaces if ``drain`` is
        called first on the success path)."""
        if self._thread is None:
            return
        self._q.put(None)
        self._thread.join(timeout=60.0)
        self._thread = None


class _StallWatchdog(threading.Thread):
    """Flags the head-of-line step when its RETIRE age — seconds since
    it became the oldest in-flight record (i.e. since the previous
    finish completed), not since its own dispatch — exceeds
    ``max(k · p99(finish), floor)``.  The percentile-aware straggler
    signal (Dean & Ghemawat §3.6 make backup dispatch a tail-latency
    decision; a flat timeout can't tell "slow step" from "stuck
    step").  Head-of-line age is the right clock: dispatch→finish age
    includes ~``depth-1`` steps of NORMAL window residency, so at
    depth > k it exceeds ``k·p99`` on perfectly healthy pipelines —
    the retire age is depth-independent (steady state ≈ one step
    wall).  One daemon thread per running pipeline, started ONLY when
    the telemetry plane is active (``obs/hist.py``) — the default run
    has zero watchdog threads.

    The p99 comes from the live ``finish`` stage histogram once it has
    ``DSI_STALL_MIN_SAMPLES`` (default 8) steps; before that only the
    floor gates, so early-run compile stalls don't self-trigger.
    Knobs: ``DSI_STALL_K`` (default 4), ``DSI_STALL_FLOOR_S`` (default
    5 s), ``DSI_STALL_CHECK_S`` (default floor/4 capped at 1 s).

    A stalled step is flagged EXACTLY ONCE: a loud stderr line, a
    ``stall`` event in the trace's control lane (step, retire + since-
    dispatch ages, threshold, p99), the ``pipeline_stall`` registry
    gauge, and a ``stalls`` bump in the engine's stats scope.  The
    step may still finish — the flag means "a backup dispatcher should
    be looking", not "dead".
    """

    def __init__(self, pipe: "StepPipeline",
                 hists: "_hist.StageHistograms"):
        super().__init__(name="dsi-stall-watchdog", daemon=True)
        self._pipe = pipe
        self._hists = hists
        self._halt = threading.Event()
        self._flagged: set = set()
        envf = _hist.env_float
        self.k = envf("DSI_STALL_K", 4.0)
        self.floor_s = envf("DSI_STALL_FLOOR_S", 5.0)
        self.check_s = envf("DSI_STALL_CHECK_S",
                            max(0.02, min(1.0, self.floor_s / 4)))
        self.min_samples = int(envf("DSI_STALL_MIN_SAMPLES", 8))

    def threshold_s(self) -> float:
        # THIS pipeline's finish distribution, not the process-global
        # stage histogram: in one bench process the stream row's ~ms
        # finishes would otherwise calibrate the tfidf row's ~s waves
        # (every healthy wave flagged) and vice versa.
        h = self._pipe._finish_hist
        p99 = (h.percentile(0.99)
               if h is not None and h.count >= self.min_samples else 0.0)
        return max(self.k * p99, self.floor_s)

    def stop(self) -> None:
        self._halt.set()

    def run(self) -> None:
        import sys

        from dsi_tpu.obs import event as _event, get_registry

        while not self._halt.wait(self.check_s):
            oldest = self._pipe.oldest_inflight()
            if oldest is None:
                continue
            step, ts = oldest
            if step in self._flagged:
                continue
            now = time.perf_counter()
            # Retire age: since this record reached the head of the
            # line (the later of its dispatch and the previous finish
            # completing) — depth-independent, unlike now - ts.
            age = now - max(ts, self._pipe._last_retire_t)
            thr = self.threshold_s()
            if age <= thr:
                continue
            self._flagged.add(step)
            h = self._hists.get("finish")
            p99_s = round(h.percentile(0.99), 4) if h is not None else 0.0
            engine = self._pipe._engine or "?"
            info = {"engine": engine, "step": step,
                    "age_s": round(age, 3),
                    "inflight_age_s": round(now - ts, 3),
                    "threshold_s": round(thr, 3),
                    "p99_s": p99_s}
            self._pipe._stats["stalls"] = \
                self._pipe._stats.get("stalls", 0) + 1
            _event("stall", lane="control", **info)
            get_registry().set_gauge("pipeline_stall", info)
            print(f"obs: STALL {engine} step {step}: in flight "
                  f"{age:.1f}s > max({self.k:g}*p99={self.k * p99_s:.1f}s,"
                  f" floor={self.floor_s:g}s)", file=sys.stderr)


class StepPipeline:
    """``depth``-deep dispatch/finish window over a produced item stream.

    ``dispatch(item)`` launches one step's async work and returns an
    opaque in-flight record (or None to skip the item); ``finish(record)``
    retires the oldest record — deferred flag check, replay, merge all
    live in the consumer.  ``stats`` receives ``produce_key`` (seconds
    building items — in the producer thread at depth > 1, inline at
    depth 1), ``wait_key`` (consumer starvation on the queue) and
    ``inflight_key`` (peak window occupancy, bounded by ``depth``).

    Tracing (``dsi_tpu/obs``) is instrumented HERE once for all four
    engines: every produced item, dispatch, and finish is a span —
    ``materialize``/``dispatch``/``finish`` carrying the step ordinal
    and the ``engine`` label — so a traced run gets its per-step
    timeline from the core, and the engines only add their
    phase-specific child spans (upload/kernel/pull/merge/replay) inside
    ``finish``.  The spans double as the stats accumulators (the
    ``stats``/``key`` sink), so the trace totals and the phase dict are
    the same measurement.
    """

    def __init__(self, *, depth: int,
                 dispatch: Callable, finish: Callable,
                 stats: dict,
                 produce_key: str = "batch_s",
                 wait_key: str = "batch_wait_s",
                 inflight_key: str = "max_inflight_chunks",
                 thread_name: str = "dsi-pipeline-producer",
                 engine: str = ""):
        self.depth = max(1, int(depth))
        self._dispatch = dispatch
        self._finish = finish
        self._stats = stats
        self._produce_key = produce_key
        self._wait_key = wait_key
        self._inflight_key = inflight_key
        self._thread_name = thread_name
        self._engine = engine or getattr(stats, "engine", "")
        stats.setdefault(produce_key, 0.0)
        stats.setdefault(wait_key, 0.0)
        stats.setdefault(inflight_key, 0)
        # Live telemetry state (obs/live.py statusz + the stall
        # watchdog): (ordinal, dispatch-perf_counter) per in-flight
        # record, plus monotonic dispatched/finished counters.  Plain
        # attribute writes on the hot path — a deque append and two int
        # bumps per step, read from other threads without locks (deque
        # ops are atomic; readers tolerate a racy oldest).
        self._inflight: collections.deque = collections.deque()
        self.dispatched = 0
        self.finished = 0
        #: perf_counter of the most recent finish completing (run start
        #: before any) — the watchdog's head-of-line age baseline.
        self._last_retire_t = 0.0
        #: THIS run's finish-wall histogram (fresh per run, telemetry-
        #: active runs only) — the watchdog's p99 source; the process-
        #: global stage histograms aggregate across engines/runs and
        #: would cross-calibrate their thresholds.
        self._finish_hist: Optional["_hist.LatencyHistogram"] = None

    # ── live telemetry read side ──

    def oldest_inflight(self) -> Optional[tuple]:
        """(step ordinal, dispatch perf_counter) of the oldest record
        still in flight, or None — the watchdog's probe."""
        try:
            return self._inflight[0]
        except IndexError:
            return None

    def live_state(self) -> dict:
        """One JSON-ready line of in-flight window state — what
        ``/statusz`` reports per running pipeline."""
        oldest = self.oldest_inflight()
        now = time.perf_counter()
        return {"engine": self._engine,
                "dispatched": self.dispatched,
                "finished": self.finished,
                "inflight": len(self._inflight),
                "depth": self.depth,
                "step": max(0, self.dispatched - 1),
                "oldest_step": oldest[0] if oldest else None,
                "oldest_age_s": (round(now - oldest[1], 3)
                                 if oldest else 0.0)}

    # ── item feed: inline at depth=1, background thread otherwise ──

    def _producer(self, make_items: Callable[[], Iterator],
                  out_q: queue.Queue, stop: threading.Event) -> None:
        gen = make_items()
        i = 0
        try:
            while True:
                with _span("materialize", stats=self._stats,
                           key=self._produce_key, step=i,
                           engine=self._engine):
                    try:
                        item = next(gen)
                    except StopIteration:
                        break
                i += 1
                while not stop.is_set():
                    try:
                        out_q.put(("item", item), timeout=0.2)
                        break
                    except queue.Full:
                        continue
                if stop.is_set():
                    return
            out_q.put(("done", None))
        except BaseException as e:  # surfaced to the consumer thread
            # Stop-aware retry, like the item put above: a fixed timeout
            # could drop the error while the consumer sits in a long
            # replay (minutes on a tunneled compile), leaving it blocked
            # forever on a queue that will never produce the sentinel.
            while not stop.is_set():
                try:
                    out_q.put(("err", e), timeout=0.2)
                    break
                except queue.Full:
                    continue

    def _feed(self, make_items, out_q, stop,
              started: list) -> Iterator:
        if self.depth == 1:
            gen = make_items()
            i = 0
            while True:
                with _span("materialize", stats=self._stats,
                           key=self._produce_key, step=i,
                           engine=self._engine):
                    try:
                        item = next(gen)
                    except StopIteration:
                        return
                i += 1
                yield item
            return
        thread = threading.Thread(
            target=self._producer, args=(make_items, out_q, stop),
            daemon=True, name=self._thread_name)
        started.append(thread)
        thread.start()
        while True:
            with _span("wait", lane="materialize", stats=self._stats,
                       key=self._wait_key, engine=self._engine):
                kind, item = out_q.get()
            if kind == "done":
                return
            if kind == "err":
                raise item
            yield item

    # ── the window: incremental API ──
    #
    # ``run`` used to own the whole loop; the resumable step objects
    # (``parallel/stepobj.py``) and the serving daemon need to drive it
    # one step at a time, so the loop is split into four primitives —
    # ``begin`` (arm the feed/watchdog), ``pump`` (dispatch the next
    # item, retiring the oldest record when the window is full),
    # ``drain`` (retire everything in flight — the confirmed-boundary
    # maker for forced checkpoints and eviction), and ``end`` (tear the
    # producer/watchdog down, idempotent).  ``run`` is exactly
    # begin → pump* → drain with ``end`` in a finally, so its semantics
    # — dispatch/finish interleaving included — are unchanged.

    def begin(self, make_items: Callable[[], Iterator]) -> None:
        """Arm the pipeline over ``make_items()``'s items.  Must be
        balanced by :meth:`end` (any number of ``pump``/``drain`` calls
        in between)."""
        self._pending: collections.deque = collections.deque()
        self._inflight.clear()
        self._last_retire_t = time.perf_counter()
        self._stop_evt = threading.Event()
        self._out_q: queue.Queue = queue.Queue(maxsize=self.depth + 1)
        self._started: list = []
        self._idx = 0
        self._ended = False
        # The stall watchdog rides only telemetry-active runs: the
        # default path starts zero extra threads.
        self._watchdog: Optional[_StallWatchdog] = None
        hists = _hist.active_histograms()
        if hists is not None:
            self._finish_hist = _hist.LatencyHistogram()
            self._watchdog = _StallWatchdog(self, hists)
            self._watchdog.start()
        _hist.register_pipeline(self)
        self._feed_iter: Optional[Iterator] = self._feed(
            make_items, self._out_q, self._stop_evt, self._started)

    def _finish_oldest(self) -> None:
        # The per-step trace span: its wall IS the step's retire cost
        # (deferred flag wait + merge or replay) — the unit the
        # straggler table in scripts/tracecat.py ranks and the
        # ``finish`` histogram the watchdog thresholds on.
        step, _ts = self._inflight[0]
        with _span("finish", lane="dispatch", step=step,
                   engine=self._engine) as sp:
            self._finish(self._pending.popleft())
        self._inflight.popleft()
        self.finished += 1
        self._last_retire_t = time.perf_counter()
        if self._finish_hist is not None:
            self._finish_hist.record(sp.elapsed_s)

    def pump(self) -> bool:
        """One turn of the crank: dispatch the next produced item,
        retiring the oldest in-flight record first when the window is
        full.  Returns False when the item stream is exhausted (records
        may still be in flight — ``drain`` retires them)."""
        try:
            item = next(self._feed_iter)
        except StopIteration:
            return False
        with _span("dispatch", step=self._idx, engine=self._engine):
            rec = self._dispatch(item)
        self._idx += 1
        self.dispatched = self._idx
        if rec is None:
            return True
        self._pending.append(rec)
        self._inflight.append((self._idx - 1, time.perf_counter()))
        if len(self._pending) > self._stats[self._inflight_key]:
            self._stats[self._inflight_key] = len(self._pending)
        if len(self._pending) >= self.depth:
            self._finish_oldest()
        return True

    def drain(self) -> None:
        """Retire every in-flight record (FIFO).  After this the
        pipeline sits at a CONFIRMED boundary — everything dispatched
        has passed its deferred checks and merged — which is what a
        forced checkpoint or a tenant eviction needs."""
        while self._pending:
            self._finish_oldest()

    @property
    def inflight(self) -> int:
        return len(self._pending)

    def end(self) -> None:
        """Tear down the producer thread and watchdog.  Idempotent, and
        safe mid-stream (an eviction abandons unread items; the resume
        re-reads them from the durable cursor)."""
        if getattr(self, "_ended", True):
            return
        self._ended = True
        _hist.unregister_pipeline(self)
        if self._watchdog is not None:
            self._watchdog.stop()
            self._watchdog.join(timeout=5.0)  # fast: stop() wakes its wait
            self._watchdog = None
        if self._started:
            self._stop_evt.set()
            thread = self._started[0]
            # Unblock a producer stuck on a full queue; bounded — a
            # producer mid-build exits at its next stop check.
            deadline = time.monotonic() + 5.0
            while (thread.is_alive()
                   and time.monotonic() < deadline):
                try:
                    self._out_q.get_nowait()
                except queue.Empty:
                    thread.join(0.05)
        self._feed_iter = None

    def run(self, make_items: Callable[[], Iterator]) -> None:
        """Drive the full pipeline over ``make_items()``'s items: keep up
        to ``depth`` dispatched records in flight, finish each in FIFO
        order as the window fills, drain the window at stream end.  Any
        exception (producer or consumer) unwinds with the producer thread
        stopped and its queue drained."""
        self.begin(make_items)
        try:
            while self.pump():
                pass
            self.drain()
        finally:
            self.end()
