"""Reusable dispatch/finish pipeline core for device-step engines.

The PR-1 streaming word-count engine earned its throughput from four
mechanics that have nothing to do with word counting: a background
producer thread feeding a bounded queue (host item construction off the
critical path), a ``depth``-deep in-flight window (dispatch step k+1
before step k synchronizes), deferred per-step checks (a step's flags
are read only when it leaves the window, ``depth-1`` steps late), and a
small rotating host buffer pool (O(depth) allocations however long the
stream).  The TF-IDF wave walk has exactly the same cost shape — build
wave, upload, kernel, scalar check, pull, merge, every wave on the
critical path — so this module extracts the mechanics into one core
both engines consume (``parallel/streaming.py``,
``parallel/tfidf.py``).

The core is deliberately ignorant of devices and results: ``dispatch``
launches whatever async work one item needs and returns an opaque
record; ``finish`` retires the OLDEST in-flight record — that is where
a consumer blocks on flags, replays an overflowed step through its
exactness ladder, and merges confirmed output.  The window invariant
the core owns: records finish in dispatch order, a record finishes
exactly once, and at most ``depth`` records are ever in flight.
``depth=1`` degenerates to the fully synchronous loop — no thread, no
queue, dispatch-then-finish — which is why a consumer's pipelined and
lockstep paths are the same function and can be compared bit-for-bit.

Exceptions propagate both ways: a producer error re-raises in the
consumer thread (stop-aware, so it cannot be lost while the consumer
sits in a long replay), and a consumer exception unwinds through
``run`` with the producer thread shut down and its queue drained.
"""

from __future__ import annotations

import collections
import os
import queue
import threading
import time
from typing import Callable, Iterator, Optional, Sequence

import numpy as np

from dsi_tpu.obs import span as _span


def pipeline_depth(depth: Optional[int] = None) -> int:
    """Resolve an engine's in-flight window: an explicit ``depth`` wins,
    else ``DSI_STREAM_PIPELINE_DEPTH`` (default 2), floored at 1 (the
    synchronous path).  One resolver for every pipeline consumer, so the
    stream and the wave walk cannot read the knob differently."""
    if depth is None:
        try:
            depth = int(os.environ.get("DSI_STREAM_PIPELINE_DEPTH", "2"))
        except ValueError:
            depth = 2
    return max(1, depth)


class BufferPool:
    """Small rotating pool of reusable fixed-shape host buffers.

    ``take`` hands out a free buffer, allocating only when the pool is
    dry (startup, or the consumer still holds every buffer in its
    in-flight window); ``give`` returns one for reuse.  Never blocks —
    the pipeline's bounded queue provides the backpressure; the pool
    only removes the per-item ``np.zeros`` allocation + page-fault churn
    from the steady state.  ``allocs`` counts real allocations, so a
    caller can assert reuse (a stream of any length allocates O(depth)
    buffers).
    """

    def __init__(self, shape: Sequence[int], retain: int,
                 dtype=np.uint8):
        self._shape = tuple(shape)
        self._dtype = dtype
        self._free: collections.deque = collections.deque()
        self._lock = threading.Lock()
        self._retain = retain
        self.allocs = 0

    def take(self) -> np.ndarray:
        with self._lock:
            if self._free:
                return self._free.popleft()
            self.allocs += 1
        return np.zeros(self._shape, dtype=self._dtype)

    def give(self, buf: Optional[np.ndarray]) -> None:
        if buf is None or buf.shape != self._shape:
            return
        with self._lock:
            if len(self._free) < self._retain:
                self._free.append(buf)


class CommitWorker:
    """Single background worker draining submitted thunks FIFO — the
    consumer-side twin of the producer thread above, shared by the
    async checkpoint writer (``ckpt/writer.py``).

    The discipline mirrors the pipeline's: bounded in-flight work
    (``submit`` blocks while ``max_pending`` submissions are
    outstanding — the "barrier only when the NEXT save would overrun
    the one still draining" rule; the wait is returned so the caller
    can attribute it), strict submission order (one worker), and
    errors that cannot be lost — a thunk's exception is re-raised at
    the next ``submit``/``drain`` in the submitting thread, never
    swallowed while the pipeline keeps stepping.
    """

    def __init__(self, name: str = "dsi-commit-worker",
                 max_pending: int = 1):
        self._q: "queue.Queue" = queue.Queue()
        # The in-flight bound must count the thunk the worker is
        # RUNNING, not just queued ones (a bounded queue alone would
        # admit one running + one queued = max_pending + 1): a slot is
        # taken at submit and released only when the thunk finishes.
        self._slots = threading.BoundedSemaphore(max(1, max_pending))
        self._name = name
        self._thread: Optional[threading.Thread] = None
        self._err: Optional[BaseException] = None
        self._done = threading.Event()

    def _loop(self) -> None:
        while True:
            thunk = self._q.get()
            try:
                if thunk is None:
                    return
                if self._err is None:  # after an error: drain, don't run
                    thunk()
            except BaseException as e:
                self._err = e
            finally:
                self._q.task_done()
                if thunk is not None:
                    self._slots.release()

    def _ensure_thread(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(target=self._loop,
                                            daemon=True, name=self._name)
            self._thread.start()

    def _raise_pending(self) -> None:
        if self._err is not None:
            err, self._err = self._err, None
            raise err

    def submit(self, thunk: Callable[[], None]) -> float:
        """Enqueue one thunk; returns the seconds spent blocked waiting
        for an in-flight slot (0.0 when one was free).  Re-raises a
        prior thunk's error instead of enqueueing more work on a dead
        run."""
        self._raise_pending()
        self._ensure_thread()
        t0 = time.perf_counter()
        self._slots.acquire()
        self._q.put(thunk)
        waited = time.perf_counter() - t0
        return waited if waited > 1e-4 else 0.0

    def drain(self) -> float:
        """Wait until every submitted thunk finished; re-raise the first
        error.  Returns the seconds spent waiting."""
        if self._thread is None:
            self._raise_pending()
            return 0.0
        t0 = time.perf_counter()
        self._q.join()
        self._raise_pending()
        return time.perf_counter() - t0

    def shutdown(self) -> None:
        """Stop the worker after the queue drains, silently (for
        ``finally`` blocks already unwinding another exception — a
        pending commit error stays stored and surfaces if ``drain`` is
        called first on the success path)."""
        if self._thread is None:
            return
        self._q.put(None)
        self._thread.join(timeout=60.0)
        self._thread = None


class StepPipeline:
    """``depth``-deep dispatch/finish window over a produced item stream.

    ``dispatch(item)`` launches one step's async work and returns an
    opaque in-flight record (or None to skip the item); ``finish(record)``
    retires the oldest record — deferred flag check, replay, merge all
    live in the consumer.  ``stats`` receives ``produce_key`` (seconds
    building items — in the producer thread at depth > 1, inline at
    depth 1), ``wait_key`` (consumer starvation on the queue) and
    ``inflight_key`` (peak window occupancy, bounded by ``depth``).

    Tracing (``dsi_tpu/obs``) is instrumented HERE once for all four
    engines: every produced item, dispatch, and finish is a span —
    ``materialize``/``dispatch``/``finish`` carrying the step ordinal
    and the ``engine`` label — so a traced run gets its per-step
    timeline from the core, and the engines only add their
    phase-specific child spans (upload/kernel/pull/merge/replay) inside
    ``finish``.  The spans double as the stats accumulators (the
    ``stats``/``key`` sink), so the trace totals and the phase dict are
    the same measurement.
    """

    def __init__(self, *, depth: int,
                 dispatch: Callable, finish: Callable,
                 stats: dict,
                 produce_key: str = "batch_s",
                 wait_key: str = "batch_wait_s",
                 inflight_key: str = "max_inflight_chunks",
                 thread_name: str = "dsi-pipeline-producer",
                 engine: str = ""):
        self.depth = max(1, int(depth))
        self._dispatch = dispatch
        self._finish = finish
        self._stats = stats
        self._produce_key = produce_key
        self._wait_key = wait_key
        self._inflight_key = inflight_key
        self._thread_name = thread_name
        self._engine = engine or getattr(stats, "engine", "")
        stats.setdefault(produce_key, 0.0)
        stats.setdefault(wait_key, 0.0)
        stats.setdefault(inflight_key, 0)

    # ── item feed: inline at depth=1, background thread otherwise ──

    def _producer(self, make_items: Callable[[], Iterator],
                  out_q: queue.Queue, stop: threading.Event) -> None:
        gen = make_items()
        i = 0
        try:
            while True:
                with _span("materialize", stats=self._stats,
                           key=self._produce_key, step=i,
                           engine=self._engine):
                    try:
                        item = next(gen)
                    except StopIteration:
                        break
                i += 1
                while not stop.is_set():
                    try:
                        out_q.put(("item", item), timeout=0.2)
                        break
                    except queue.Full:
                        continue
                if stop.is_set():
                    return
            out_q.put(("done", None))
        except BaseException as e:  # surfaced to the consumer thread
            # Stop-aware retry, like the item put above: a fixed timeout
            # could drop the error while the consumer sits in a long
            # replay (minutes on a tunneled compile), leaving it blocked
            # forever on a queue that will never produce the sentinel.
            while not stop.is_set():
                try:
                    out_q.put(("err", e), timeout=0.2)
                    break
                except queue.Full:
                    continue

    def _feed(self, make_items, out_q, stop,
              started: list) -> Iterator:
        if self.depth == 1:
            gen = make_items()
            i = 0
            while True:
                with _span("materialize", stats=self._stats,
                           key=self._produce_key, step=i,
                           engine=self._engine):
                    try:
                        item = next(gen)
                    except StopIteration:
                        return
                i += 1
                yield item
            return
        thread = threading.Thread(
            target=self._producer, args=(make_items, out_q, stop),
            daemon=True, name=self._thread_name)
        started.append(thread)
        thread.start()
        while True:
            with _span("wait", lane="materialize", stats=self._stats,
                       key=self._wait_key, engine=self._engine):
                kind, item = out_q.get()
            if kind == "done":
                return
            if kind == "err":
                raise item
            yield item

    # ── the window ──

    def run(self, make_items: Callable[[], Iterator]) -> None:
        """Drive the full pipeline over ``make_items()``'s items: keep up
        to ``depth`` dispatched records in flight, finish each in FIFO
        order as the window fills, drain the window at stream end.  Any
        exception (producer or consumer) unwinds with the producer thread
        stopped and its queue drained."""
        pending: collections.deque = collections.deque()
        steps: collections.deque = collections.deque()  # dispatch ordinals
        stop = threading.Event()
        out_q: queue.Queue = queue.Queue(maxsize=self.depth + 1)
        started: list = []
        idx = 0

        def finish_oldest() -> None:
            # The per-step trace span: its wall IS the step's retire cost
            # (deferred flag wait + merge or replay) — the unit the
            # straggler table in scripts/tracecat.py ranks.
            with _span("finish", lane="dispatch", step=steps.popleft(),
                       engine=self._engine):
                self._finish(pending.popleft())

        try:
            for item in self._feed(make_items, out_q, stop, started):
                with _span("dispatch", step=idx, engine=self._engine):
                    rec = self._dispatch(item)
                idx += 1
                if rec is None:
                    continue
                pending.append(rec)
                steps.append(idx - 1)
                if len(pending) > self._stats[self._inflight_key]:
                    self._stats[self._inflight_key] = len(pending)
                if len(pending) >= self.depth:
                    finish_oldest()
            while pending:
                finish_oldest()
        finally:
            if started:
                stop.set()
                thread = started[0]
                # Unblock a producer stuck on a full queue; bounded — a
                # producer mid-build exits at its next stop check.
                deadline = time.monotonic() + 5.0
                while (thread.is_alive()
                       and time.monotonic() < deadline):
                    try:
                        out_q.get_nowait()
                    except queue.Empty:
                        thread.join(0.05)
