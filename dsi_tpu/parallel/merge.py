"""Vectorized host-side merge tables for the SPMD paths' per-step outputs.

The streaming word-count and TF-IDF paths produce per-step device tables of
packed word keys (big-endian uint32 lanes, ``ops/wordcount.py``
tokenize_group_core) plus payload columns.  Round 3 merged those into Python
dicts one word at a time — O(rows) interpreter iterations with a string
decode per row, which VERDICT r3 measured as the scale ceiling of both paths
(`parallel/streaming.py` weakness #2, `parallel/tfidf.py` weakness #3).

This module replaces the per-row loops with numpy table algebra:

* rows accumulate as raw uint32 arrays (copied out of the step's transfer
  buffer so no device-shaped block stays alive),
* merging is one ``np.lexsort`` over the key lanes + run-boundary detection
  + ``np.add.reduceat`` per compaction window — O(rows log rows) in C,
* word spellings are decoded ONCE, from the final merged table
  (vocabulary-sized), via the same bulk ``decode_packed`` the kernels use.

Zero-padded key lanes make width harmonisation trivial: a word packed into
K lanes and the same word packed into K' > K lanes agree on the first K
lanes and are zero beyond, so narrower tables are right-padded with zero
columns before concatenation.

The reference has no analogue (its reduce merge is the in-memory group of
``mr/worker.go:110-124``); this is that merge re-done as array algebra so
the host side can keep up with the device side at GB scale.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np

from dsi_tpu.ops.wordcount import decode_packed


def _pad_width(keys: np.ndarray, k: int) -> np.ndarray:
    """Right-pad packed-key lanes with zero columns to width ``k``."""
    if keys.shape[1] == k:
        return keys
    out = np.zeros((keys.shape[0], k), dtype=np.uint32)
    out[:, :keys.shape[1]] = keys
    return out


def _group_starts(sorted_keys: np.ndarray) -> np.ndarray:
    """Start indices of equal-key runs in a lexsorted [n, k] table."""
    n = len(sorted_keys)
    boundary = np.empty(n, dtype=bool)
    boundary[0] = True
    np.any(sorted_keys[1:] != sorted_keys[:-1], axis=1, out=boundary[1:])
    return np.flatnonzero(boundary)


def _lexsort_rows(keys: np.ndarray) -> np.ndarray:
    """Row order sorting a [n, k] table lexicographically (lane 0 primary).

    ``np.lexsort`` treats its LAST key as primary, so lanes are passed in
    reverse.
    """
    return np.lexsort(tuple(keys[:, j] for j in range(keys.shape[1] - 1,
                                                      -1, -1)))


class PackedCounts:
    """Word-count accumulator over packed-key row batches.

    ``add`` ingests per-device step outputs (keys [n, K] uint32, byte
    lengths, counts, reduce partitions); batches are compacted into one
    merged table whenever the buffered row count crosses
    ``compact_rows`` — so host memory is O(vocabulary + window), never
    O(corpus).  ``finalize`` decodes spellings once and returns the same
    ``{word: (count, reduce_partition)}`` mapping the dict-based merge
    produced.
    """

    def __init__(self, compact_rows: int = 1 << 21):
        self._bufs: List[Tuple[np.ndarray, np.ndarray, np.ndarray,
                               np.ndarray]] = []
        self._pending = 0
        self._compact_rows = max(1, compact_rows)

    def add(self, keys: np.ndarray, lens: np.ndarray, cnts: np.ndarray,
            parts: np.ndarray) -> None:
        if len(keys) == 0:
            return
        # Copies detach the rows from the step's full-capacity transfer
        # buffer; counts widen to int64 so multi-step sums can't wrap.
        self._bufs.append((
            np.array(keys, dtype=np.uint32),
            np.array(lens, dtype=np.int32),
            np.array(cnts, dtype=np.int64),
            np.array(parts, dtype=np.int32)))
        self._pending += len(keys)
        if self._pending >= self._compact_rows:
            self._compact()

    def add_packed_step(self, packed: np.ndarray, n_uniques,
                        kk: int) -> None:
        """Ingest one pulled step tensor ``[n_dev, mp, kk+3]`` (the
        ``shuffle._slice_pack`` layout: kk key lanes + len/count/partition
        columns), taking the first ``n_uniques[d]`` rows of each device's
        table.  One call per stream step — the merge phase the pipelined
        engine (parallel/streaming.py) runs on the host while later
        steps' kernels are still in flight on device."""
        for d in range(packed.shape[0]):
            nu = int(n_uniques[d])
            r = packed[d, :nu]
            self.add(r[:, :kk], r[:, kk], r[:, kk + 1], r[:, kk + 2])

    def _compact(self) -> None:
        if len(self._bufs) <= 1:
            return
        k = max(b[0].shape[1] for b in self._bufs)
        keys = np.concatenate([_pad_width(b[0], k) for b in self._bufs])
        lens = np.concatenate([b[1] for b in self._bufs])
        cnts = np.concatenate([b[2] for b in self._bufs])
        parts = np.concatenate([b[3] for b in self._bufs])
        order = _lexsort_rows(keys)
        skeys = keys[order]
        starts = _group_starts(skeys)
        # len and partition are functions of the word, so first-of-run is
        # exact; only counts need the segmented sum.
        self._bufs = [(skeys[starts], lens[order][starts],
                       np.add.reduceat(cnts[order], starts),
                       parts[order][starts])]
        self._pending = len(starts)

    def finalize(self) -> Dict[str, Tuple[int, int]]:
        self._compact()
        if not self._bufs:
            return {}
        keys, lens, cnts, parts = self._bufs[0]
        words = decode_packed(keys, lens, len(keys))
        return {w: (int(c), int(p))
                for w, c, p in zip(words, cnts.tolist(), parts.tolist())}

    # ── checkpoint image (dsi_tpu/ckpt) ──

    def snapshot(self) -> Dict[str, np.ndarray]:
        """Checkpoint image: the merged table as four arrays, compacted
        first so the image is bounded by vocabulary, not by the window.
        Empty accumulator -> empty dict (no keys saved)."""
        self._compact()
        if not self._bufs:
            return {}
        keys, lens, cnts, parts = self._bufs[0]
        return {"keys": keys, "lens": lens, "cnts": cnts, "parts": parts}

    def restore(self, arrays: Dict[str, np.ndarray]) -> None:
        """Load a :meth:`snapshot` image, replacing any current state.
        Final results are invariant to how the same (word, count)
        contributions were buffered, so a restored accumulator
        finalizes bit-identically to the uninterrupted one."""
        if not arrays or "keys" not in arrays or len(arrays["keys"]) == 0:
            self._bufs, self._pending = [], 0
            return
        self._bufs = [(np.array(arrays["keys"], dtype=np.uint32),
                       np.array(arrays["lens"], dtype=np.int32),
                       np.array(arrays["cnts"], dtype=np.int64),
                       np.array(arrays["parts"], dtype=np.int32))]
        self._pending = len(self._bufs[0][0])


class PostingsTable:
    """TF-IDF accumulator over packed (word, tf, doc, part) row batches.

    Rows are retained raw (uint32, ~16+4K bytes each — several times
    smaller than the Python tuple lists they replace) and grouped once at
    ``finalize``: one lexsort over the key lanes, run-boundary detection,
    one bulk spelling decode, and per-word postings sliced out with
    C-speed ``tolist``/``zip``.  Output matches the dict-based walk:
    ``{word: (reduce_partition, [(doc_index, tf), ...])}``.
    """

    def __init__(self):
        self._bufs: List[np.ndarray] = []
        self._kk: int | None = None

    def add(self, rows: np.ndarray, kk: int) -> None:
        """Ingest [n, kk+4] rows: kk key lanes + (len, tf, doc, part)."""
        if len(rows) == 0:
            return
        if self._kk is None:
            self._kk = kk
        elif kk != self._kk:  # one retry rung per table by contract
            raise ValueError(f"mixed key widths: {self._kk} vs {kk}")
        self._bufs.append(np.array(rows, dtype=np.uint32))

    # ── checkpoint image (dsi_tpu/ckpt) ──

    def snapshot(self) -> Dict[str, np.ndarray]:
        """Checkpoint image: every buffered row, concatenated in
        insertion order — order is part of the postings contract
        (per-word doc order is an engine invariant), and the stable
        finalize lexsort preserves it, so a restored table groups
        bit-identically."""
        if not self._bufs:
            return {}
        rows = (np.concatenate(self._bufs) if len(self._bufs) > 1
                else self._bufs[0])
        return {"rows": rows, "kk": np.array(self._kk, dtype=np.int64)}

    def restore(self, arrays: Dict[str, np.ndarray]) -> None:
        if not arrays or "rows" not in arrays or len(arrays["rows"]) == 0:
            self._bufs, self._kk = [], None
            return
        self._kk = int(arrays["kk"])
        self._bufs = [np.array(arrays["rows"], dtype=np.uint32)]

    def finalize(self) -> Dict[str, Tuple[int, List[Tuple[int, int]]]]:
        return self.finalize_packed().to_dict()

    def finalize_packed(self) -> "PackedPostings":
        """Group without pythonizing: the full postings stay as numpy
        arrays (~32 B/posting) instead of ~250 B of tuples/lists/ints per
        posting — at GB scale the dict materialization alone was ~2 GB of
        the soak's peak RSS (VERDICT r4 weakness #4).  Use ``to_dict()``
        (or ``lookup_many`` for a few words) only at scales that afford
        it."""
        if not self._bufs:
            return PackedPostings(0)
        kk = self._kk
        rows = np.concatenate(self._bufs) if len(self._bufs) > 1 \
            else self._bufs[0]
        keys = rows[:, :kk]
        order = _lexsort_rows(keys)
        skeys = keys[order]
        starts = _group_starts(skeys)
        out = PackedPostings(kk)
        out.skeys = np.ascontiguousarray(skeys[starts])
        out.starts = starts
        out.ends = np.append(starts[1:], len(rows))
        out.lens = rows[order[starts], kk]
        out.parts = rows[order[starts], kk + 3]
        out.tfs = np.ascontiguousarray(rows[order, kk + 1])
        out.docs = np.ascontiguousarray(rows[order, kk + 2])
        return out


class PackedPostings:
    """Grouped TF-IDF postings as numpy tables (lexicographic word
    order).  ``skeys/lens/parts/starts/ends`` are per-unique-word;
    ``tfs/docs`` are the full postings, ``starts[i]:ends[i]`` slicing
    word i's."""

    __slots__ = ("kk", "skeys", "lens", "parts", "starts", "ends",
                 "tfs", "docs", "_be")

    def __init__(self, kk: int):
        self.kk = kk
        self._be = None  # lazy big-endian key view (lookup_many)
        self.skeys = np.zeros((0, max(kk, 1)), np.uint32)
        self.lens = np.zeros(0, np.uint32)
        self.parts = np.zeros(0, np.uint32)
        self.starts = np.zeros(0, np.int64)
        self.ends = np.zeros(0, np.int64)
        self.tfs = np.zeros(0, np.uint32)
        self.docs = np.zeros(0, np.uint32)

    def __len__(self) -> int:
        return len(self.skeys)

    @property
    def n_postings(self) -> int:
        return len(self.tfs)

    def postings_per_word(self) -> np.ndarray:
        return self.ends - self.starts

    def lookup_many(self, words) -> Dict[str, Tuple[int, List[Tuple[int,
                                                                    int]]]]:
        """{word: (part, [(doc, tf), ...])} for just these words (absent
        words omitted) — dict-shaped output without pythonizing the whole
        table.  Binary search per word over the lexsorted big-endian key
        bytes (uint32 lanes are big-endian packed, so byte order == lane
        order)."""
        n = len(self.skeys)
        if n == 0:
            return {}
        if self._be is None:  # immutable after finalize_packed: cache it
            self._be = np.ascontiguousarray(self.skeys.astype(">u4"))
        be = self._be
        width = 4 * self.kk
        out: Dict[str, Tuple[int, List[Tuple[int, int]]]] = {}
        for w in words:
            try:
                raw = w.encode("ascii")
            except UnicodeEncodeError:
                continue  # non-ASCII cannot be in the table: omit, never
                # alias to an ASCII-stripped spelling
            if not raw or len(raw) > width:
                continue
            q = raw.ljust(width, b"\x00")
            lo, hi = 0, n
            while lo < hi:
                mid = (lo + hi) // 2
                if be[mid].tobytes() < q:
                    lo = mid + 1
                else:
                    hi = mid
            if lo >= n or be[lo].tobytes() != q \
                    or int(self.lens[lo]) != len(raw):
                continue
            s, e = int(self.starts[lo]), int(self.ends[lo])
            out[w] = (int(self.parts[lo]),
                      list(zip(self.docs[s:e].tolist(),
                               self.tfs[s:e].tolist())))
        return out

    def to_dict(self) -> Dict[str, Tuple[int, List[Tuple[int, int]]]]:
        if len(self.skeys) == 0:
            return {}
        words = decode_packed(self.skeys, self.lens, len(self.skeys))
        tfs = self.tfs.tolist()
        docs = self.docs.tolist()
        out: Dict[str, Tuple[int, List[Tuple[int, int]]]] = {}
        for i, w in enumerate(words):
            s, e = int(self.starts[i]), int(self.ends[i])
            out[w] = (int(self.parts[i]), list(zip(docs[s:e], tfs[s:e])))
        return out
