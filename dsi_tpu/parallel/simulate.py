"""Vmapped crash-test model checking of the scheduler state machine.

BASELINE.json's fault config asks for "1000 vmapped job instances, randomized
worker death".  This module expresses the coordinator's entire scheduling +
fault-tolerance state machine (``mr/coordinator.go``: per-task 0/1/2 logs,
first-untouched assignment :50-55, map barrier :47,79, presumed-dead-by-
timeout requeue :70-77,99-106, completion counting :27-41, Done :138-142) as
a pure, static-shape JAX program over integer state, then ``jax.vmap``s it
over thousands of PRNG-seeded instances — every instance a full MapReduce job
with randomized worker crashes, stalls, and duplicate completions, all
advancing in lockstep on one chip.

This is the TPU-native answer to the reference's race-detector testing
(``test-mr.sh:10,19-22`` builds with `-race`; SURVEY.md §5): instead of
hoping 3 OS processes interleave interestingly, we *enumerate* thousands of
adversarial schedules per second and machine-check the invariants:

* liveness  — every instance reaches Done within the horizon,
* safety    — Done implies every task log is COMPLETED,
* barrier   — no reduce task is ever assigned while a map task is incomplete,
* the reference's double-count defect (counters bumped on every completion
  RPC, ``mr/coordinator.go:30-31,38-39``) is simulated side-by-side: the
  checker reports how many instances WOULD have opened the reduce barrier
  early under the buggy counter, demonstrating why this rebuild counts
  unique log transitions instead (coordinator.py).

Worker fault model (mirrors apps/crash.py and the MIT crash.go it's modeled
on): on assignment a worker draws its fate — exit (dies silently; its task
sits in-progress until the timeout requeues it), stall (finishes after the
requeue fires, so a second worker may also run the task and one of the two
completion reports is a duplicate), or normal completion.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

U = 0  # LOG_UNTOUCHED   (mr/coordinator.go task-log states)
P = 1  # LOG_IN_PROGRESS
C = 2  # LOG_COMPLETED


class SimState(NamedTuple):
    t: jnp.ndarray                # current tick
    map_log: jnp.ndarray          # [n_map] {0,1,2}
    map_deadline: jnp.ndarray     # [n_map] requeue tick for in-progress
    c_map: jnp.ndarray            # unique-transition completion counter
    c_map_buggy: jnp.ndarray      # reference-style every-RPC counter
    reduce_log: jnp.ndarray       # [n_reduce]
    reduce_deadline: jnp.ndarray
    c_reduce: jnp.ndarray
    c_reduce_buggy: jnp.ndarray
    busy_until: jnp.ndarray       # [n_workers] 0 = idle
    wkind: jnp.ndarray            # [n_workers] -1 none / 0 map / 1 reduce
    wtask: jnp.ndarray            # [n_workers]
    wfate: jnp.ndarray            # [n_workers] 0 ok / 1 stall / 2 exit
    n_requeues: jnp.ndarray
    n_duplicates: jnp.ndarray
    barrier_violation: jnp.ndarray       # bool, checked invariant
    buggy_early_barrier: jnp.ndarray     # bool, simulated reference defect


def _first_untouched(log: jnp.ndarray) -> jnp.ndarray:
    """Index of the first UNTOUCHED task, or len(log) if none — the
    coordinator's linear scan (mr/coordinator.go:50-55)."""
    n = log.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    return jnp.min(jnp.where(log == U, idx, n))


def _sim_step(state: SimState, key: jnp.ndarray, *, n_workers: int,
              timeout: int, exit_prob: float, stall_prob: float) -> SimState:
    n_map = state.map_log.shape[0]
    n_reduce = state.reduce_log.shape[0]
    t = state.t + 1
    tick_key = jax.random.fold_in(key, t)

    # ── 1. presumed-dead-by-timeout requeue (coordinator.go:70-77,99-106) ──
    map_stale = (state.map_log == P) & (state.map_deadline <= t)
    red_stale = (state.reduce_log == P) & (state.reduce_deadline <= t)
    map_log = jnp.where(map_stale, U, state.map_log)
    reduce_log = jnp.where(red_stale, U, state.reduce_log)
    n_requeues = state.n_requeues + jnp.sum(map_stale) + jnp.sum(red_stale)

    c_map, c_map_b = state.c_map, state.c_map_buggy
    c_red, c_red_b = state.c_reduce, state.c_reduce_buggy
    busy, wkind, wtask, wfate = (state.busy_until, state.wkind, state.wtask,
                                 state.wfate)
    map_deadline, reduce_deadline = state.map_deadline, state.reduce_deadline
    n_dups = state.n_duplicates
    barrier_viol = state.barrier_violation
    buggy_early = state.buggy_early_barrier

    # ── 2. completions / silent deaths, serialized in worker order (the
    #       coordinator mutex serializes RPCs, coordinator.go:28,44) ──
    for w in range(n_workers):
        fires = busy[w] == t
        reports = fires & (wfate[w] != 2)          # exited workers say nothing
        is_map = reports & (wkind[w] == 0)
        is_red = reports & (wkind[w] == 1)
        tm = jnp.clip(wtask[w], 0, n_map - 1)
        tr = jnp.clip(wtask[w], 0, n_reduce - 1)
        dup_m = is_map & (map_log[tm] == C)
        dup_r = is_red & (reduce_log[tr] == C)
        n_dups = n_dups + dup_m + dup_r
        # fixed counters: first transition to COMPLETED only (coordinator.py)
        c_map = c_map + (is_map & ~dup_m)
        c_red = c_red + (is_red & ~dup_r)
        # reference counters: every completion RPC (coordinator.go:30-31,38-39)
        c_map_b = c_map_b + is_map
        c_red_b = c_red_b + is_red
        map_log = map_log.at[tm].set(jnp.where(is_map, C, map_log[tm]))
        reduce_log = reduce_log.at[tr].set(jnp.where(is_red, C,
                                                     reduce_log[tr]))
        busy = busy.at[w].set(jnp.where(fires, 0, busy[w]))
        wkind = wkind.at[w].set(jnp.where(fires, -1, wkind[w]))

    # ── 3. pull-based assignment for idle workers (RequestTask,
    #       coordinator.go:43-114) ──
    for w in range(n_workers):
        idle = busy[w] == 0
        maps_open = c_map < n_map
        reds_open = ~maps_open & (c_red < n_reduce)
        tba_m = _first_untouched(map_log)
        tba_r = _first_untouched(reduce_log)
        take_map = idle & maps_open & (tba_m < n_map)
        take_red = idle & reds_open & (tba_r < n_reduce)

        # invariant: reduce may only be assigned once EVERY map is complete
        barrier_viol = barrier_viol | (take_red & jnp.any(map_log != C))
        # the reference's defect, simulated: double counts can satisfy the
        # cMap==nMap gate (:79) while a map task is still incomplete
        buggy_early = buggy_early | ((c_map_b >= n_map)
                                     & jnp.any(map_log != C))

        u = jax.random.uniform(jax.random.fold_in(tick_key, w))
        fate = jnp.where(u < exit_prob, 2,
                         jnp.where(u < exit_prob + stall_prob, 1, 0))
        # ok: 1-3 ticks; stall: past the requeue deadline; exit: dies at +1
        dur = jnp.where(fate == 1, timeout + 2,
                        jnp.where(fate == 2, 1,
                                  1 + (jnp.uint32(u * 977) % 3)
                                  .astype(jnp.int32)))
        assigned = take_map | take_red
        busy = busy.at[w].set(jnp.where(assigned, t + dur, busy[w]))
        wkind = wkind.at[w].set(jnp.where(take_map, 0,
                                          jnp.where(take_red, 1, wkind[w])))
        wtask = wtask.at[w].set(jnp.where(take_map, tba_m,
                                          jnp.where(take_red, tba_r,
                                                    wtask[w])))
        wfate = wfate.at[w].set(jnp.where(assigned, fate, wfate[w]))
        map_log = map_log.at[jnp.clip(tba_m, 0, n_map - 1)].set(
            jnp.where(take_map, P, map_log[jnp.clip(tba_m, 0, n_map - 1)]))
        map_deadline = map_deadline.at[jnp.clip(tba_m, 0, n_map - 1)].set(
            jnp.where(take_map, t + timeout,
                      map_deadline[jnp.clip(tba_m, 0, n_map - 1)]))
        reduce_log = reduce_log.at[jnp.clip(tba_r, 0, n_reduce - 1)].set(
            jnp.where(take_red, P,
                      reduce_log[jnp.clip(tba_r, 0, n_reduce - 1)]))
        reduce_deadline = reduce_deadline.at[
            jnp.clip(tba_r, 0, n_reduce - 1)].set(
            jnp.where(take_red, t + timeout,
                      reduce_deadline[jnp.clip(tba_r, 0, n_reduce - 1)]))

    return SimState(t, map_log, map_deadline, c_map, c_map_b, reduce_log,
                    reduce_deadline, c_red, c_red_b, busy, wkind, wtask,
                    wfate, n_requeues, n_dups, barrier_viol, buggy_early)


@functools.partial(jax.jit,
                   static_argnames=("n_map", "n_reduce", "n_workers",
                                    "timeout", "horizon", "exit_prob",
                                    "stall_prob"))
def simulate_job(key: jnp.ndarray, *, n_map: int = 8, n_reduce: int = 10,
                 n_workers: int = 3, timeout: int = 10, horizon: int = 500,
                 exit_prob: float = 0.25, stall_prob: float = 0.2):
    """Run ONE randomized MapReduce job to completion (or the horizon).

    vmap this over a batch of keys for fleet-scale model checking.  Returns a
    dict of end-state facts and invariant flags.
    """
    z = jnp.int32(0)
    init = SimState(
        t=z, map_log=jnp.zeros(n_map, jnp.int32),
        map_deadline=jnp.zeros(n_map, jnp.int32), c_map=z, c_map_buggy=z,
        reduce_log=jnp.zeros(n_reduce, jnp.int32),
        reduce_deadline=jnp.zeros(n_reduce, jnp.int32), c_reduce=z,
        c_reduce_buggy=z, busy_until=jnp.zeros(n_workers, jnp.int32),
        wkind=jnp.full(n_workers, -1, jnp.int32),
        wtask=jnp.zeros(n_workers, jnp.int32),
        wfate=jnp.zeros(n_workers, jnp.int32), n_requeues=z, n_duplicates=z,
        barrier_violation=jnp.bool_(False), buggy_early_barrier=jnp.bool_(False))

    step = functools.partial(_sim_step, key=key, n_workers=n_workers,
                             timeout=timeout, exit_prob=exit_prob,
                             stall_prob=stall_prob)
    done = lambda s: (s.c_reduce < n_reduce) & (s.t < horizon)  # noqa: E731
    final = lax.while_loop(done, lambda s: step(s), init)

    finished = final.c_reduce == n_reduce
    consistent = (jnp.all(final.map_log == C) & jnp.all(final.reduce_log == C)
                  & (final.c_map == n_map))
    return {
        "finished": finished,
        "consistent": finished & consistent | ~finished,
        "safe": ~final.barrier_violation,
        "ticks": final.t,
        "requeues": final.n_requeues,
        "duplicates": final.n_duplicates,
        "buggy_would_break_barrier": final.buggy_early_barrier,
    }


def run_crash_model_check(n_instances: int = 1000, seed: int = 0,
                          **kwargs) -> dict:
    """Model-check n_instances randomized jobs in lockstep; aggregate."""
    keys = jax.random.split(jax.random.PRNGKey(seed), n_instances)
    out = jax.vmap(lambda k: simulate_job(k, **kwargs))(keys)
    out = jax.tree.map(lambda x: jax.device_get(x), out)
    agg = {
        "instances": n_instances,
        "all_finished": bool(out["finished"].all()),
        "all_consistent": bool(out["consistent"].all()),
        "all_safe": bool(out["safe"].all()),
        "mean_ticks": float(out["ticks"].mean()),
        "total_requeues": int(out["requeues"].sum()),
        "total_duplicate_completions": int(out["duplicates"].sum()),
        "instances_where_reference_counter_breaks_barrier":
            int(out["buggy_would_break_barrier"].sum()),
    }
    return agg
