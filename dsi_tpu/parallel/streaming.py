"""Streaming SPMD word count: corpus size decoupled from device memory.

``wordcount_sharded`` (parallel/shuffle.py) materialises the whole corpus
host-side and pads every device shard to the longest's power of two — fine
at bench scale, structurally incapable of BASELINE's 10 GB config.  This
module is the chunked multi-step redesign (VERDICT r1 weakness #7):

* the corpus arrives as an **iterator of byte blocks** (files, sockets,
  generators — never required to fit in memory),
* a carry buffer slices it into fixed ``[n_dev, chunk_bytes]`` batches,
  cutting only at non-letter boundaries so no token straddles a chunk
  (same rule as ``shard_text``; the carry makes it exact across batches),
* every batch runs the SAME compiled ``mapreduce_step`` program (static
  shapes: one compile per capacity rung for the whole stream, however
  long),
* per-step per-device grouped counts are merged into a host accumulator
  (``parallel/merge.py`` PackedCounts: raw packed-key tables, numpy
  lexsort + segmented sum, spellings decoded once at the end) — bounded
  by *vocabulary*, not corpus size.

Three scale levers this module owns (VERDICT r3 weakness #2):

* **sticky adaptive capacity** — ``u_cap`` is only the STARTING per-device
  unique capacity; a step that overflows retries itself wider (the shared
  ``exactness_retry`` ladder) and the capacity that worked is reused for
  every later step, so a low-vocabulary stream never pays for a
  worst-case kernel (the sort inside the step is O(cap log cap)) and a
  high-vocabulary stream widens exactly once,
* **prefix-sliced D2H** — only the occupied prefix of the result tables
  (max per-device merged uniques, rounded up to a power of two so the
  slice programs stay bounded) crosses the wire; the pull cost tracks
  vocabulary, not capacity — on the axon tunnel's ~25 MB/s D2H path this
  is the difference between milliseconds and seconds per step,
* **vectorized merge** — no per-word Python in the steady state.

And the lever that makes the stream a *pipeline* rather than a lockstep
loop (BENCH_r05: the serialized batch → upload → kernel → pull → merge
cycle made streaming the slowest row): ``wordcount_streaming`` keeps a
window of ``depth`` steps in flight (default 2, ``DSI_STREAM_PIPELINE_
DEPTH``).  A background batcher thread slices blocks into a bounded
queue; the main thread uploads and dispatches step k+1 without
synchronizing while step k's kernel runs; the overflow-flag check
(``scal[:, 4]`` and friends) is **deferred** until a step leaves the
window, and the host-side merge of a confirmed step overlaps the device
work of the steps behind it.  Deferral is safe because the accumulator
only ever merges a step already proven exact — a late-detected overflow
replays just that step through the shared exactness ladder at the wider
capacity, disturbing nothing merged before it.  ``depth=1`` is the
synchronous path: same function, same ladder, same results dict.

Memory bound, explicitly: device HBM holds at most ``depth`` chunk
buffers (each step's upload is DONATED to its kernel —
`backends/aotcache.cached_compile(donate_argnums=...)` /
``shuffle.mapreduce_step_donate`` — so a window never doubles chunk
residency) plus ``depth`` per-step result sets awaiting their deferred
pull — one packed ``[n_dev, n_dev*u_cap, K+3]`` tensor per in-flight
step under ``aot`` (the four result tables free as soon as the eager
pack consumes them), the four equivalent-size tables per step on the
jit path — plus one kernel's working buffers.  All of it is
capacity-bounded (scales with ``depth x n_dev^2 x u_cap``, never with
corpus bytes); size ``depth``/``u_cap`` together when HBM is tight.
The host holds a small rotating pool of batch buffers (O(depth)), the
carry (< ``n_dev x chunk_bytes + block``) and the accumulator
(O(uniques) merged table plus a bounded compaction window).

The reference has no analogue (its scaling lever is nMap = #input files on
a shared filesystem, ``mr/coordinator.go:152``); this is that lever
re-designed for a device mesh: nMap becomes "number of stream steps", and
the pipeline is the reference's map/shuffle/reduce-of-different-tasks
concurrency re-created inside one process.

``device_accumulate=True`` moves the cross-step merge itself on-device
(``device/table.py``): a confirmed step's packed reduce output FOLDS into
a persistent device-resident table with one compiled merge program, and
the host pulls the merged table only every ``sync_every`` folds (plus
stream end) — ``ceil(steps/K) + widens`` pulls instead of one per step,
which on the tunnel's ~0.1 s/pull, ~25 MB/s D2H path is the difference
the depth-2 window can actually hide.  Folds lag the deferred-exactness
confirmation window: only steps whose overflow checks passed are folded,
and a replayed step folds its replayed (exact) output — so the
bit-identical depth=1 parity guarantee survives unchanged.  A fold whose
merged uniques overflow the table's capacity rung is a global no-op that
surfaces a widen signal; the service drains the table to the host
accumulator, reallocates at the next rung, and re-folds the orphaned
steps (their packed tensors are kept alive until their fold confirms,
exactly for this).

The window/producer/pool mechanics themselves live in the shared
dispatch/finish pipeline core (``parallel/pipeline.py``); this module
supplies the word-count-specific dispatch (sticky-rung step launch) and
finish (deferred exactness check, merge-or-replay) callbacks.  The
TF-IDF wave walk (``parallel/tfidf.py``) consumes the same core.
"""

from __future__ import annotations

import os
import time
from typing import Dict, Iterable, Iterator, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from dsi_tpu.ckpt import (
    CheckpointPolicy,
    CheckpointStore,
    CheckpointWriter,
    DeltaSteps,
    HostDeltaLog,
    checkpoint_async_default,
    checkpoint_delta_default,
    drain_packed_steps,
    fault_point,
    skip_stream,
)
from dsi_tpu.device.policy import SyncPolicy, mesh_shards_default
from dsi_tpu.device.table import DeviceTable, _quiet_unusable_donation
from dsi_tpu.obs import metrics_scope, span as _span
from dsi_tpu.ops.wordcount import (
    exactness_retry,
    grouper_ladder,
    rung0_cap,
    warm_groupers,
)
from dsi_tpu.ops import wirecodec
from dsi_tpu.parallel.merge import PackedCounts
from dsi_tpu.parallel.pipeline import (
    BufferPool,
    StepPipeline,
    fold_source_stats,
    pipeline_depth,
)
from dsi_tpu.parallel.stepobj import EngineStep
from dsi_tpu.parallel.shuffle import (
    AXIS,
    _is_letter_byte,
    _mapreduce_step_impl,
    _slice_pack,
    default_mesh,
    mapreduce_step,
    mapreduce_step_donate,
    occupied_prefix,
)


# A cut never needs to back off further than the longest word the kernels
# can represent (64 bytes, ops/wordcount.py exactness_retry ladder) — if it
# does, the input has a word the device path must hand to the host anyway.
_MAX_BACKOFF = 96

#: jax.jit donate_argnums for the stream step program: the chunk upload is
#: consumed by the kernel.  Shared by the AOT compile, the warmer, and the
#: cache-existence probe so all three agree on the executable's key.
_STEP_DONATE = (0,)


class _TokenTooLong(Exception):
    """A letter run longer than the device word limit spans a cut point."""


class _NeedsHostPath(Exception):
    """A step proved the stream needs the host path (non-ASCII, >64-byte
    word): unwind the pipeline and return None to the caller."""


def _cut_at_boundary(buf, size: int) -> int:
    """Largest c <= size with no letter run crossing buf[c-1]/buf[c]."""
    if len(buf) <= size:
        return len(buf)
    if not (_is_letter_byte(buf[size - 1]) and _is_letter_byte(buf[size])):
        return size  # common case: the natural cut already sits on a gap
    # Back off vectorized: one numpy scan over the candidate window
    # instead of the former per-byte Python loop (~100 interpreter
    # iterations per long-word cut on the hot batching path).
    lo = max(0, size - _MAX_BACKOFF - 1)
    win = np.frombuffer(memoryview(buf)[lo:size + 1], dtype=np.uint8)
    letter = ((win >= 65) & (win <= 90)) | ((win >= 97) & (win <= 122))
    ok = ~(letter[:-1] & letter[1:])  # ok[p] ⇔ cut c = lo+p+1 splits no run
    hits = np.flatnonzero(ok)
    if hits.size:
        return lo + 1 + int(hits[-1])
    if size <= _MAX_BACKOFF:
        return 0  # the whole prefix is one (representable) letter run
    raise _TokenTooLong


def batch_stream(blocks: Iterable[bytes], n_dev: int, chunk_bytes: int,
                 pool: Optional[BufferPool] = None,
                 offsets: Optional[list] = None) -> Iterator[np.ndarray]:
    """Slice a byte-block stream into zero-padded [n_dev, chunk_bytes]
    batches, cutting rows only at non-letter boundaries.

    With ``pool`` (the streaming engine's buffer pool) batches come from a
    small rotating buffer set instead of a fresh ``np.zeros`` per batch;
    the consumer must hand each yielded batch back via ``pool.give`` once
    it no longer reads it (the pipeline returns a buffer when its step is
    confirmed exact).  Rows are always written in full — data then zero
    tail — so a recycled buffer never leaks stale bytes.

    With ``offsets`` (the checkpoint cursor hook), the stream offset
    just past each yielded batch's content is appended per batch —
    appended BEFORE the yield, so the consumer can read ``offsets[i]``
    the moment batch ``i`` arrives.  Batching is a pure function of the
    byte stream, so resuming from ``skip_stream(blocks, offsets[i])``
    reproduces batches ``i+1, i+2, ...`` exactly."""
    carry = bytearray()
    consumed = 0

    def new_batch() -> np.ndarray:
        if pool is not None:
            return pool.take()
        return np.zeros((n_dev, chunk_bytes), dtype=np.uint8)

    batch = new_batch()
    row = 0

    def fill_rows(final: bool):
        nonlocal row, carry, batch, consumed
        while carry and (len(carry) >= chunk_bytes + 1 or final):
            cut = _cut_at_boundary(carry, chunk_bytes)
            if cut == 0:
                # A letter run as wide as the whole row: no cut can make
                # progress at this chunk size, so the word needs the host
                # path.  (The pre-pool code spun forever here, emitting
                # empty rows without ever consuming the carry.)
                raise _TokenTooLong
            view = np.frombuffer(carry, dtype=np.uint8, count=cut)
            batch[row, :cut] = view
            del view           # release the bytearray export before the
            del carry[:cut]    # resize (a live view blocks it)
            consumed += cut
            batch[row, cut:] = 0
            row += 1
            if row == n_dev:
                if offsets is not None:
                    offsets.append(consumed)
                yield batch
                batch = new_batch()
                row = 0

    for block in blocks:
        carry.extend(block)
        yield from fill_rows(final=False)
    yield from fill_rows(final=True)
    if row:
        batch[row:] = 0  # recycled buffer: stale tail rows must not count
        if offsets is not None:
            offsets.append(consumed)
        yield batch      # tail batch; remaining rows are empty chunks
    elif pool is not None:
        pool.give(batch)  # taken but never filled: straight back


def stream_files(paths: Sequence[str],
                 block_bytes: int = 4 << 20) -> Iterator[bytes]:
    """File contents as a block stream, separated by newlines so the last
    word of one file and the first of the next never merge."""
    for i, p in enumerate(paths):
        if i:
            yield b"\n"
        with open(p, "rb") as f:
            while True:
                b = f.read(block_bytes)
                if not b:
                    break
                yield b


def _step_program(*, n_dev: int, n_reduce: int, max_word_len: int,
                  u_cap: int, mesh: Mesh, t_cap_frac: int,
                  grouper: str = "sort"):
    """The (name, fn, code-deps) triple for one compiled
    ``mapreduce_step`` shape — single definition shared by the
    cached-compile path, the warmer, and the cache-existence probe, so a
    probe's key is by construction the key a run compiles.  The sort
    grouper keeps its historical, readable name; the hash grouper gets
    the ``_hg`` suffix (``ops.wordcount.grouper_suffix`` — the warm
    ladder persists BOTH variants, so an env-selected hash run loads
    instead of cold-compiling).  (Naming only — cache invalidation is
    governed by the source fingerprint, so kernel edits recompile either
    way.)"""
    import dsi_tpu.ops.wordcount as _wc
    import dsi_tpu.parallel.shuffle as _sh

    def fn(c):
        return _mapreduce_step_impl(c, n_dev=n_dev, n_reduce=n_reduce,
                                    max_word_len=max_word_len, u_cap=u_cap,
                                    mesh=mesh, t_cap_frac=t_cap_frac,
                                    grouper=grouper)

    fn._aot_code_deps = (_wc, _sh)
    name = (f"stream_step_d{n_dev}_r{n_reduce}_w{max_word_len}"
            f"_u{u_cap}_f{t_cap_frac}")
    name += _wc.grouper_suffix(grouper)
    return name, fn


def _aot_step_fn(example_chunks, donate: bool = True, **kw):
    """Compiled ``mapreduce_step`` via the persistent AOT executable cache
    (``backends/aotcache.py``) — for single-device bench processes on the
    axon platform, where a fresh-process ``jax.jit`` pays a remote compile
    that JAX's own persistent cache never absorbs (VERDICT r2 weakness
    #1a).  Multi-device meshes compile in-process (the cache auto-disables
    disk persistence there).  ``example_chunks`` may be a
    ``ShapeDtypeStruct`` (warming compiles without executing).  The chunk
    argument is donated (the pipeline re-uploads per attempt) unless
    ``donate=False`` — the kernel-only bench row's variant, whose
    HBM-resident chunk must survive every rep (a distinct cache key:
    donation is part of the executable's aliasing config)."""
    from dsi_tpu.backends import aotcache

    name, fn = _step_program(**kw)
    with _quiet_unusable_donation():  # a cold entry compiles right here
        return aotcache.cached_compile(
            name, fn, (example_chunks,),
            donate_argnums=_STEP_DONATE if donate else (),
            x64=True)


def _aot_step(chunks, **kw):
    return _aot_step_fn(chunks, **kw)(chunks)


def _pack_program(*, mp: int):
    """(name, fn) for one compiled ``shuffle._slice_pack`` shape — shared
    like :func:`_step_program`."""
    import dsi_tpu.parallel.shuffle as _sh

    def fn(k, l, c, p):
        return _slice_pack(k, l, c, p, mp=mp)

    fn._aot_code_deps = (_sh,)
    return f"stream_pack_m{mp}", fn


def _aot_pack_fn(example_args, *, mp: int):
    """Compiled ``shuffle._slice_pack`` via the AOT cache (same rationale
    as :func:`_aot_step_fn`).  ``example_args`` may be shape structs."""
    from dsi_tpu.backends import aotcache

    name, fn = _pack_program(mp=mp)
    return aotcache.cached_compile(name, fn, example_args)


def _stream_examples(n_dev: int, chunk_bytes: int, u_cap: int,
                     max_word_len: int):
    """Shape structs for the step input and pack inputs at one rung."""
    import jax

    sds = jax.ShapeDtypeStruct
    chunks = sds((n_dev, chunk_bytes), jnp.uint8)
    rows = n_dev * u_cap
    kk = max_word_len // 4
    pack_args = (sds((n_dev, rows, kk), jnp.uint32),
                 sds((n_dev, rows), jnp.int32),
                 sds((n_dev, rows), jnp.int32),
                 sds((n_dev, rows), jnp.uint32))
    return chunks, rows, pack_args


def stream_programs_persisted(mesh: Mesh | None = None,
                              chunk_bytes: int = 1 << 20,
                              n_reduce: int = 10, max_word_len: int = 16,
                              u_cap: int = 1 << 12,
                              fracs: Sequence[int] = (4, 2),
                              device_accumulate: bool = False,
                              mesh_shards: int = 0) -> bool:
    """True when every starting-rung program
    ``wordcount_streaming(..., aot=True)`` would reach first (step at
    each token-capacity frac, plus the pack program) is already in the
    persistent AOT cache — i.e. running the stream is loads, not
    multi-minute remote compiles.  Same role as
    ``corpus_wc.corpus_executable_persisted``: lets a time-boxed bench
    skip the stream row rather than gamble its budget on cold compiles
    (capacity-widening retries beyond the start rung are not probed;
    they are rare and the headline verdict is already durable by then)."""
    from dsi_tpu.backends.aotcache import is_persisted

    if mesh is None:
        mesh = default_mesh()
    n_dev = mesh.devices.size
    chunks, rows, pack_args = _stream_examples(n_dev, chunk_bytes, u_cap,
                                               max_word_len)
    # Probe every grouper rung the run's ladder can reach (the platform
    # default first, sort as the exact fallback) — probing sort alone
    # would answer "warm" while the first program a DSI_WC_GROUPER-pinned
    # run compiles is cold.
    for g in sorted(set(grouper_ladder())):
        for frac in fracs:
            name, fn = _step_program(n_dev=n_dev, n_reduce=n_reduce,
                                     max_word_len=max_word_len, u_cap=u_cap,
                                     mesh=mesh, t_cap_frac=frac, grouper=g)
            if not is_persisted(name, fn, (chunks,),
                                donate_argnums=_STEP_DONATE):
                return False
    name, fn = _pack_program(mp=rows)
    if not is_persisted(name, fn, pack_args):
        return False
    if device_accumulate:
        # The rung-0 fold/clear/pack programs the device accumulator
        # reaches first (device/table.py) — a cold fold compile is the
        # same multi-minute remote hazard as a cold step compile.
        from dsi_tpu.device.table import device_fold_persisted

        if not device_fold_persisted(mesh, u_cap=u_cap,
                                     kk=max_word_len // 4,
                                     mesh_shards=mesh_shards):
            return False
    return True


def _aot_pack(keys, lens, cnts, parts, *, mp: int):
    return _aot_pack_fn((keys, lens, cnts, parts), mp=mp)(
        keys, lens, cnts, parts)


def warm_stream_aot(mesh: Mesh | None = None, chunk_bytes: int = 1 << 20,
                    n_reduce: int = 10,
                    word_lens: Sequence[int] = (16,),
                    caps: Sequence[int] = (1 << 12, 1 << 14, 1 << 16),
                    fracs: Sequence[int] = (4, 2),
                    device_accumulate: bool = False,
                    mesh_shards: int = 0) -> None:
    """Compile + persist the program shapes
    ``wordcount_streaming(..., aot=True)`` reaches at these parameters,
    from shape structs alone (no data, nothing executed) — so a later
    fresh process (the driver's bench run) only ever loads serialized
    executables.

    ``caps`` must cover every capacity rung reachable from the stream's
    ``u_cap`` start for its vocabulary (the default covers the function
    default 1<<12 plus two x4 widenings); ``fracs`` mirrors the step's
    token-capacity ladder.  The 64-byte word-window rung is NOT warmed by
    default — it is reachable only by streams carrying >``max_word_len``
    -byte words; pass ``word_lens=(16, 64)`` if yours can."""
    if mesh is None:
        mesh = default_mesh()
    n_dev = mesh.devices.size
    # Warm BOTH groupers on every platform (ops/wordcount.warm_groupers):
    # the hash grouper is promoted into the accelerator warm ladder as
    # ``*_hg`` entries, so a DSI_WC_GROUPER=hash run on the chip loads a
    # serialized executable instead of paying the remote cold compile —
    # sort stays the always-exact fallback rung either way.
    groupers = warm_groupers()
    for mwl in word_lens:
        for cap in caps:
            chunks, rows, pack_args = _stream_examples(n_dev, chunk_bytes,
                                                       cap, mwl)
            for frac in fracs:
                for g in sorted(groupers):
                    _aot_step_fn(chunks, n_dev=n_dev, n_reduce=n_reduce,
                                 max_word_len=mwl, u_cap=cap, mesh=mesh,
                                 t_cap_frac=frac, grouper=g)
            _aot_pack_fn(pack_args, mp=rows)
            if device_accumulate:
                # Fold/clear/pack shapes for the device accumulator at
                # this step rung: the rung-0 table (cap = step rows)
                # plus one x4 widening (device/table.py rung ladder).
                from dsi_tpu.device.table import warm_device_fold

                warm_device_fold(mesh, u_cap=cap, kk=mwl // 4,
                                 table_rungs=2, mesh_shards=mesh_shards)


def warm_kernel_row(mesh: Mesh | None = None, chunk_bytes: int = 1 << 21,
                    n_reduce: int = 10, max_word_len: int = 16,
                    u_cap: int = 1 << 15) -> None:
    """Compile + persist the NON-donated step programs the bench's
    kernel-only row runs (both grouper variants), from shape structs
    alone — the rep loop re-executes one program on an HBM-resident
    buffer, so its input cannot be donated, and a non-donated program is
    a distinct cache key from the pipeline's donated one."""
    if mesh is None:
        mesh = default_mesh()
    n_dev = mesh.devices.size
    chunks, _, _ = _stream_examples(n_dev, chunk_bytes, u_cap, max_word_len)
    for g in warm_groupers():
        _aot_step_fn(chunks, donate=False, n_dev=n_dev, n_reduce=n_reduce,
                     max_word_len=max_word_len, u_cap=u_cap, mesh=mesh,
                     t_cap_frac=4, grouper=g)


def kernel_row_persisted(mesh: Mesh | None = None,
                         chunk_bytes: int = 1 << 21, n_reduce: int = 10,
                         max_word_len: int = 16,
                         u_cap: int = 1 << 15) -> bool:
    """True when every program the kernel-only bench row would execute
    (the non-donated step at both grouper rungs) is already persisted —
    the row's cold-compile gate, same discipline as
    ``stream_programs_persisted``."""
    from dsi_tpu.backends.aotcache import is_persisted

    if mesh is None:
        mesh = default_mesh()
    n_dev = mesh.devices.size
    chunks, _, _ = _stream_examples(n_dev, chunk_bytes, u_cap, max_word_len)
    for g in warm_groupers():
        name, fn = _step_program(n_dev=n_dev, n_reduce=n_reduce,
                                 max_word_len=max_word_len, u_cap=u_cap,
                                 mesh=mesh, t_cap_frac=4, grouper=g)
        if not is_persisted(name, fn, (chunks,)):
            return False
    return True


def stream_kernel_reps(chunk_np: np.ndarray, mesh: Mesh | None = None,
                       n_reduce: int = 10, max_word_len: int = 16,
                       u_cap: int = 1 << 15, reps: int = 5,
                       grouper: str = "sort", aot: bool = True):
    """Wire-independent kernel-only measurement: upload ``chunk_np``
    ONCE, run the stream's ``mapreduce_step`` ``reps`` times on the
    HBM-resident buffer (non-donated program, so the buffer survives
    every rep), blocking on the tiny scalar block per rep.  Returns
    ``(times, exact)`` — per-rep wall seconds (one untimed warm call
    first: executable load + first-dispatch costs stay out of the
    kernel number) and whether every rep's exactness flags were clean
    (a rate for an overflowing kernel must never enter a trend).

    This is the number a ~60 s healthy-tunnel window can still produce
    when multi-minute transfers can't: on-chip compute MB/s with exactly
    one chunk upload and ``reps`` scalar pulls on the wire.
    """
    if mesh is None:
        mesh = default_mesh()
    n_dev = mesh.devices.size
    sharding = NamedSharding(mesh, PartitionSpec(AXIS, None))
    chunks = jax.device_put(chunk_np, sharding)
    kw = dict(n_dev=n_dev, n_reduce=n_reduce, max_word_len=max_word_len,
              u_cap=u_cap, mesh=mesh, t_cap_frac=4, grouper=grouper)
    if aot:
        fn = _aot_step_fn(chunks, donate=False, **kw)
    else:
        from dsi_tpu.parallel.shuffle import mapreduce_step

        def fn(c):
            return mapreduce_step(c, **kw)
    exact = True
    times = []
    for rep in range(reps + 1):
        t0 = time.perf_counter()
        keys, lens, cnts, parts, scal = fn(chunks)
        scal_np = np.asarray(scal)  # blocks: the kernel actually ran
        if rep:
            times.append(time.perf_counter() - t0)
        exact = exact and not scal_np[:, 4].any() \
            and int(scal_np[:, 1].max()) <= u_cap \
            and int(scal_np[:, 2].max()) <= max_word_len \
            and not scal_np[:, 3].any()
    return times, exact


class WordcountStep(EngineStep):
    """Resumable step object over the streaming word-count engine: the
    explicit ``{advance, confirm, checkpoint, restore, close}`` state
    machine (``parallel/stepobj.py``) the serving daemon multiplexes.
    Parameters and semantics are exactly :func:`wordcount_streaming`'s
    (now a construct-drive-close wrapper over this class); a
    ``resume=True`` construction restores the newest valid chain BEFORE
    the first dispatch, so device state and sticky rungs exist when the
    window opens.

    ``device_batches`` (the plan layer's stage handoff, ``dsi_tpu/plan``)
    replaces the block stream with an iterator of ready
    ``[n_dev, chunk_bytes]`` batches — jax.Arrays consumed IN PLACE
    (the upstream stage's device-resident output IS this stage's
    upload; no host bytes move) or np.ndarrays (spilled/restored
    buffers, re-uploaded like any batch).  Batch rows must respect the
    engine's cut contract (no token straddles a row's fill point; zero
    tails terminate the last token).  Step programs run NON-donated in
    this mode so a late-detected overflow can replay from the same
    resident buffer; ``checkpoint_dir`` is refused (a byte cursor has
    no meaning over foreign batches — chains commit at stage
    boundaries instead)."""

    def __init__(self, blocks: Iterable[bytes], mesh: Mesh | None = None,
                 n_reduce: int = 10, chunk_bytes: int = 1 << 20,
                 max_word_len: int = 16, u_cap: int = 1 << 12,
                 aot: bool = False, on_attempt=None,
                 depth: Optional[int] = None,
                 pipeline_stats: Optional[dict] = None,
                 device_accumulate: bool = False,
                 sync_every: Optional[int] = None,
                 mesh_shards: Optional[int] = None,
                 checkpoint_dir: Optional[str] = None,
                 checkpoint_every: Optional[int] = None,
                 checkpoint_async: Optional[bool] = None,
                 checkpoint_delta: Optional[bool] = None,
                 resume: bool = False,
                 wire_upload: Optional[bool] = None,
                 device_batches=None,
                 input_range: Optional[Tuple[int, int]] = None):
        super().__init__()
        _wordcount_setup(self, blocks, mesh, n_reduce, chunk_bytes,
                         max_word_len, u_cap, aot, on_attempt, depth,
                         pipeline_stats, device_accumulate, sync_every,
                         mesh_shards, checkpoint_dir, checkpoint_every,
                         checkpoint_async, checkpoint_delta, resume,
                         wire_upload, device_batches, input_range)


def wordcount_streaming(
        blocks: Iterable[bytes], mesh: Mesh | None = None,
        n_reduce: int = 10, chunk_bytes: int = 1 << 20,
        max_word_len: int = 16, u_cap: int = 1 << 12,
        aot: bool = False, on_attempt=None,
        depth: Optional[int] = None,
        pipeline_stats: Optional[dict] = None,
        device_accumulate: bool = False,
        sync_every: Optional[int] = None,
        mesh_shards: Optional[int] = None,
        checkpoint_dir: Optional[str] = None,
        checkpoint_every: Optional[int] = None,
        checkpoint_async: Optional[bool] = None,
        checkpoint_delta: Optional[bool] = None,
        resume: bool = False,
        wire_upload: Optional[bool] = None,
        input_range: Optional[Tuple[int, int]] = None,
) -> Optional[Dict[str, Tuple[int, int]]]:
    """Exact whole-stream word counts with bounded memory, pipelined.

    Returns ``{word: (count, reduce_partition)}``, or None when the stream
    needs the host path (non-ASCII bytes, or a word longer than the device
    limit).  Every step reuses one compiled program per capacity rung; a
    step whose uniques overflow retries itself at a wider capacity without
    disturbing the accumulator (rows are merged only after a step is
    confirmed exact), and the widened capacity — like a widened word
    window — sticks for every later step.

    ``depth`` (default ``DSI_STREAM_PIPELINE_DEPTH``, 2) is the in-flight
    step window.  At ``depth > 1`` a background batcher thread slices
    blocks into a bounded queue while the main thread uploads and
    dispatches ahead without synchronizing; each step's exactness flags
    are checked only when it leaves the window (``depth - 1`` steps
    late), and a failed check replays exactly that step through the
    shared ladder — results are bit-identical to ``depth=1`` because the
    accumulator's inputs (the confirmed per-step tables) are identical.
    ``depth=1`` is fully synchronous: no thread, dispatch then check.

    ``pipeline_stats``, if given, is a dict populated with per-phase wall
    seconds (``batch_s`` build time in the batcher, ``batch_wait_s`` main-
    thread starvation, ``upload_s``, ``kernel_s`` time blocked on step
    flags, ``pull_s``, ``merge_s``, ``replay_s``) plus ``depth``,
    ``steps``, ``replays``, ``max_inflight_chunks`` (peak device chunk
    buffers — bounded by ``depth``) and ``batch_allocs`` (host batch
    buffers ever allocated — O(depth), not O(steps), thanks to the pool).

    ``on_attempt(max_word_len, u_cap)``, if given, is called before every
    kernel attempt — observability for the retry ladder (the driver's
    dryrun uses it to evidence that a capacity retry actually ran).

    ``aot=True`` routes both step and pack programs through the persistent
    AOT executable cache and pulls FULL-capacity packed tables (one
    deterministic shape per rung, so ``warm_stream_aot`` can pre-compile
    everything) instead of data-dependent pow2 prefixes — the right trade
    on the axon platform, where one cold remote compile costs more than
    every capacity-sized pull of a whole bench run.

    ``device_accumulate=True`` folds each confirmed step's reduce output
    into a persistent on-device merge table (``device/table.py``) instead
    of pulling + host-merging it; the host sees the merged table only
    every ``sync_every`` folds (default ``DSI_STREAM_SYNC_EVERY``, 8) and
    at stream end.  Results are bit-identical to the host-merge path —
    folds consume exactly the confirmed per-step tables the host merge
    would, replays fold their replayed exact output, and table-capacity
    overflow widens (drain + realloc + re-fold) rather than dropping
    keys.  ``pipeline_stats`` gains ``folds``/``fold_overflows``/
    ``sync_pulls``/``widens``/``table_cap`` counters and ``fold_s``/
    ``sync_s``/``widen_s`` phases; ``step_pulls`` counts per-step D2H
    result pulls in BOTH modes, so a bench can show the amortization
    (steps vs ``ceil(steps/K) + widens``) directly.

    ``mesh_shards`` (default ``DSI_STREAM_MESH_SHARDS``, 0 = off) makes
    the device table MESH-SHARDED (``device/table.py`` module docs): the
    fold program routes every key to shard ``ihash(key) % mesh_shards``
    with an in-program all-to-all before the merge, so each shard holds
    the complete pre-merged state of its hash range, the widen protocol
    goes per-shard (``shard_widens`` — a hot shard drains, reallocs and
    re-folds alone), and sync pulls one hash-balanced pre-merged table
    (``pull_bytes``/``shard_imbalance`` counters).  Implies
    ``device_accumulate``; results stay bit-identical to the
    host-merge path.

    ``checkpoint_dir`` enables crash-resume (``dsi_tpu/ckpt``): every
    ``checkpoint_every`` CONFIRMED steps (``DSI_STREAM_CKPT_EVERY``
    default) the engine writes a durable snapshot — host accumulator,
    a drain-free image of the device table (if live), the sticky rung
    state, and the input-byte cursor of the last confirmed step
    (in-flight/deferred-check steps are excluded, so replay stays
    exactly-once).  ``resume=True`` restores the newest valid
    checkpoint, seeks the block stream to the cursor, and continues;
    the final result is bit-identical to an uninterrupted run.
    ``pipeline_stats`` gains ``ckpt_saves``/``ckpt_s`` and, on resume,
    ``resume_gap_s``/``resume_cursor``.

    ``checkpoint_async`` (default ``DSI_STREAM_CKPT_ASYNC``, off) splits
    each save into capture (at the boundary: dispatch the image pulls,
    snapshot the host accumulators by reference) and commit (a
    background writer waits on the in-flight pulls, serializes, and
    runs the durable-write path) so steps keep flowing while the
    snapshot drains — the engine blocks only when the NEXT save finds
    the previous commit still draining (``ckpt_barrier_s``).
    ``checkpoint_delta`` (default ``DSI_STREAM_CKPT_DELTA``, off) makes
    saves INCREMENTAL: a delta ships only the confirmed step payloads
    appended since the previous save (the store chains ``delta-<seq>``
    manifests; restore = base + ordered deltas re-ingested through the
    host drain path) with a full re-base every
    ``DSI_STREAM_CKPT_REBASE`` saves.  Both default off = bit-identical
    PR-5 behavior; resume parity is unchanged either way.
    ``pipeline_stats`` gains ``ckpt_capture_s``/``ckpt_commit_s``/
    ``ckpt_barrier_s`` and ``ckpt_deltas``/``ckpt_full_bytes``/
    ``ckpt_delta_bytes``.

    ``wire_upload`` (default ``DSI_STREAM_WIRE``, off) compresses each
    chunk upload host-side (``ops/wirecodec.py``: per-batch
    dictionary-nibble code, 7-bit ASCII fallback) and decodes it ON
    DEVICE with a tiny compiled prologue before the step program, so
    the tunnel/PCIe moves 0.63-0.88x the bytes while HBM sees the
    exact same chunk tensors — results are bit-identical with the knob
    on or off (a batch the codec cannot shrink ships raw;
    ``wire_raw_steps`` counts those).  ``pipeline_stats`` gains
    ``wire_steps``/``wire_raw_steps``/``wire_packed_bytes``/
    ``wire_ratio`` and the ``decode_s`` phase (host encode +
    decode-prologue dispatch).

    A block source with an ``ingest_stats()`` hook — the parallel
    mmap reader pool, ``utils/ioread.py`` — additionally reports
    ``ingest_readers``/``ingest_blocks``/``readahead_hit_pct``/
    ``ingest_wait_s`` in ``pipeline_stats``.
    """
    return WordcountStep(
        blocks, mesh=mesh, n_reduce=n_reduce, chunk_bytes=chunk_bytes,
        max_word_len=max_word_len, u_cap=u_cap, aot=aot,
        on_attempt=on_attempt, depth=depth,
        pipeline_stats=pipeline_stats,
        device_accumulate=device_accumulate, sync_every=sync_every,
        mesh_shards=mesh_shards, checkpoint_dir=checkpoint_dir,
        checkpoint_every=checkpoint_every,
        checkpoint_async=checkpoint_async,
        checkpoint_delta=checkpoint_delta, resume=resume,
        wire_upload=wire_upload, input_range=input_range).close()


def _wordcount_setup(step, blocks, mesh, n_reduce, chunk_bytes,
                     max_word_len, u_cap, aot, on_attempt, depth,
                     pipeline_stats, device_accumulate, sync_every,
                     mesh_shards, checkpoint_dir, checkpoint_every,
                     checkpoint_async, checkpoint_delta, resume,
                     wire_upload=None, device_batches=None,
                     input_range=None):
    """The engine body behind :class:`WordcountStep`: full setup
    (``resume=True`` chain restore included) ending with the pipeline
    armed and the lifecycle hooks attached to ``step``."""
    if device_batches is not None and checkpoint_dir:
        raise ValueError("device_batches and checkpoint_dir are "
                         "exclusive: chained stages commit at stage "
                         "boundaries (dsi_tpu/plan), not byte cursors")
    if mesh is None:
        mesh = default_mesh()
    n_dev = mesh.devices.size
    depth = pipeline_depth(depth)
    acc = PackedCounts()
    groupers = grouper_ladder()
    # Sticky dispatch rung: starts where the sync ladder would, and only
    # ever moves toward more headroom (run_step_sync records the rung
    # that cleared) — cap and word window widen, and grouper/frac follow
    # the last cleared combination so a stream that consistently
    # token-overflows the optimistic frac (dense 1-letter words) or needs
    # the sort fallback doesn't replay every step forever.
    state = {"cap": rung0_cap(chunk_bytes, u_cap), "mwl": max_word_len,
             "grouper": groupers[0], "frac": 4}
    sharding = NamedSharding(mesh, PartitionSpec(AXIS, None))
    # The engine's stats dict IS a registry scope (dsi_tpu/obs): the
    # same keys as ever, readable by any consumer as the one documented
    # schema — stream_phases is a view over this, not a fifth dialect.
    stats = metrics_scope("stream")
    stats.update({"depth": depth, "steps": 0, "replays": 0,
                  "max_inflight_chunks": 0, "donate_chunks": True,
                  "step_pulls": 0, "device_accumulate": device_accumulate,
                  "batch_s": 0.0, "batch_wait_s": 0.0, "upload_s": 0.0,
                  "kernel_s": 0.0, "pull_s": 0.0, "merge_s": 0.0,
                  "replay_s": 0.0})
    # Compressed chunk uploads (ops/wirecodec.py): encode host-side,
    # ship the packed tensor, decode on device as a map prologue.  Off
    # by default = bit-identical raw uploads; on, a batch the codec
    # cannot shrink still ships raw — the knob only ever changes what
    # crosses the wire, never what HBM (and therefore the result) sees.
    # Device-batch input has no wire to compress (nothing is uploaded).
    wire = (wirecodec.wire_upload_default(wire_upload)
            if device_batches is None else False)
    # Device-resident batches replay from the SAME buffer on a
    # late-detected overflow, so their step programs must not consume
    # it — donation is a host-upload optimization only.
    donate_steps = device_batches is None
    wire_raw_total = [0]  # raw-equivalent bytes of the packed uploads
    if wire:
        stats.update({"wire_upload": True, "wire_steps": 0,
                      "wire_raw_steps": 0, "wire_packed_bytes": 0,
                      "decode_s": 0.0})
    # Device-resident accumulation: confirmed steps fold on-device, the
    # host pulls every K folds.  The table allocates lazily at the first
    # fold (its key width and capacity come from that step's shapes); the
    # fold-flag lag is the pipeline window, so confirming a fold never
    # blocks on kernels the window still wants in flight.
    mesh_shards = mesh_shards_default(mesh_shards)
    if mesh_shards:
        device_accumulate = True  # the services ARE the sharded state
        stats["device_accumulate"] = True
    table_svc: Optional[DeviceTable] = None
    policy: Optional[SyncPolicy] = None
    if device_accumulate:
        policy = SyncPolicy(sync_every)
        stats["sync_every"] = policy.sync_every
        stats["mesh_shards"] = mesh_shards

    # ── checkpoint/restore (dsi_tpu/ckpt) ──
    ck_store: Optional[CheckpointStore] = None
    ck_policy: Optional[CheckpointPolicy] = None
    ck_writer: Optional[CheckpointWriter] = None
    ck_cursor = {"offset": 0, "steps": 0}  # last CONFIRMED step's end
    offsets: Optional[list] = None
    dispatch_idx = [0]
    start_offset = 0
    ck_async = checkpoint_async_default(checkpoint_async)
    ck_delta = checkpoint_delta_default(checkpoint_delta)
    host_delta = HostDeltaLog()  # non-dacc delta log: trimmed copies of
    # the pulled (packed, nus) steps, bounded like the device logs
    if checkpoint_dir:
        # ``input_range`` (the shard scheduler's cursor range,
        # mr/shards.py) is part of the chain identity: a chain written
        # while driving shard [a, b) must refuse to restore into an
        # attempt driving any other range — cursors are range-relative,
        # so a cross-range restore would silently misalign the stream.
        ident = {"n_dev": n_dev, "n_reduce": n_reduce,
                 "chunk_bytes": chunk_bytes,
                 "device_accumulate": bool(device_accumulate)}
        if input_range is not None:
            ident["input_range"] = [int(input_range[0]),
                                    int(input_range[1])]
        ck_store = CheckpointStore(checkpoint_dir, "wordcount", ident)
        ck_policy = CheckpointPolicy(checkpoint_every)
        offsets = []
        stats.update({"ckpt_saves": 0, "ckpt_s": 0.0,
                      "ckpt_every": ck_policy.every,
                      "ckpt_capture_s": 0.0,
                      "ckpt_async": ck_async, "ckpt_delta": ck_delta})
        ck_writer = CheckpointWriter(ck_store, stats, async_=ck_async,
                                     delta=ck_delta)
        if resume:
            t_res = time.perf_counter()
            loaded = ck_store.load_latest_chain()
            if loaded is not None:
                meta, arrays, deltas = loaded
                # Cursor/rung state is newest-wins: the final delta's
                # meta IS the restore point; the base meta only names
                # the image shape.
                eff = deltas[-1][0] if deltas else meta
                start_offset = int(eff["cursor"])
                ck_cursor.update(offset=start_offset,
                                 steps=int(eff["steps"]))
                state.update({"cap": int(eff["cap"]),
                              "mwl": int(eff["mwl"]),
                              "grouper": eff["grouper"],
                              "frac": int(eff["frac"])})
                acc.restore({k[4:]: v for k, v in arrays.items()
                             if k.startswith("acc_")})
                if device_accumulate and meta.get("table_cap"):
                    img = {k[6:]: v for k, v in arrays.items()
                           if k.startswith("table_")}
                    same_degree = (int(meta.get("mesh_shards", 0))
                                   == mesh_shards)
                    if deltas or not same_degree:
                        # Chain restore (and the sharding-degree change)
                        # re-enters through the DRAIN path: the image's
                        # merged rows flow into the host accumulator,
                        # the table starts empty, and the resumed folds
                        # rebuild device state.  base + ordered deltas
                        # is content-exact, so the final output stays
                        # bit-identical.
                        DeviceTable.drain_image(acc, img)
                        if not same_degree:
                            stats["resharded_resume"] = int(
                                meta.get("mesh_shards", 0))
                    else:
                        # Re-enter device_accumulate mid-table: the
                        # image's capacity/width win (a pre-crash widen
                        # sticks).
                        table_svc = DeviceTable(
                            mesh, kk=int(meta["table_kk"]),
                            cap=int(meta["table_cap"]), acc=acc, aot=aot,
                            lag=max(0, depth - 1), stats=stats,
                            mesh_shards=mesh_shards)
                        table_svc.restore_state(img)
                        if ck_delta:
                            table_svc.enable_delta()
                    policy.restore(eff.get("sync_since", 0))
                for _, darr in deltas:
                    # Each delta's retained step payloads re-enter the
                    # host accumulator in save order — the same
                    # drain-path argument as the cross-degree resume.
                    drain_packed_steps(acc, darr)
                if aot:
                    # Re-warm the sticky-rung executables now (persistent
                    # cache loads), so the first resumed step dispatches
                    # instead of compiling — the cost lands in
                    # resume_gap_s where it belongs.
                    chunks_sds, rows, pack_args = _stream_examples(
                        n_dev, chunk_bytes, state["cap"], state["mwl"])
                    _aot_step_fn(chunks_sds, n_dev=n_dev,
                                 n_reduce=n_reduce,
                                 max_word_len=state["mwl"],
                                 u_cap=state["cap"], mesh=mesh,
                                 t_cap_frac=state["frac"],
                                 grouper=state["grouper"])
                    _aot_pack_fn(pack_args, mp=rows)
            stats["resume_gap_s"] = round(time.perf_counter() - t_res, 4)
            stats["resume_cursor"] = start_offset
        else:
            ck_store.reset()  # fresh lineage: stale checkpoints must
            # never be resumable into a run that diverged from them

    def fold_confirmed(packed_dev, scal_dev, scal_np) -> None:
        nonlocal table_svc
        if int(scal_np[:, 0].max()) == 0:
            return  # empty step: nothing to fold, nothing to sync for
        if table_svc is None:
            # Rung-0 table capacity: the step's row count (a single fold
            # can never overflow it), unless DSI_DEVICE_TABLE_CAP asks
            # for a smaller start — an HBM lever for low-vocabulary
            # streams (the widen protocol recovers if the guess is
            # wrong), and the test hook that forces mid-stream widens.
            try:
                cap = int(os.environ.get("DSI_DEVICE_TABLE_CAP", "0"))
            except ValueError:
                cap = 0
            table_svc = DeviceTable(
                mesh, kk=int(packed_dev.shape[2]) - 3,
                cap=cap if cap > 0 else int(packed_dev.shape[1]),
                acc=acc, aot=aot, lag=max(0, depth - 1), stats=stats,
                mesh_shards=mesh_shards)
            if ck_delta and ck_store is not None:
                table_svc.enable_delta()
        table_svc.fold(packed_dev, scal_dev, scal_np)
        policy.note_fold()
        if policy.due():
            fault_point("pre-sync")
            table_svc.sync()
            policy.reset()

    def save_ckpt() -> None:
        """One consistent snapshot at a confirmed-step boundary —
        capture here, commit inline (sync) or in the background writer
        (async; ``ckpt/writer.py``).  The device table is captured
        FIRST: flushing its lagged flags can trigger a widen whose
        drain lands in the host accumulator, and the snapshot must hold
        both sides of that move.  Everything in the in-flight window is
        deliberately absent — those steps were never merged, and resume
        re-processes them from the cursor.  A delta save ships only the
        step payloads retained since the previous save (device log in
        dacc mode, the already-pulled host payloads otherwise); every
        ``DSI_STREAM_CKPT_REBASE``-th save is a full re-base (an
        invalid delta window forces one)."""
        with _span("ckpt", stats=stats, key="ckpt_s",
                   step=ck_cursor["steps"]):
            meta = {"cursor": ck_cursor["offset"],
                    "steps": ck_cursor["steps"],
                    "cap": state["cap"], "mwl": state["mwl"],
                    "grouper": state["grouper"], "frac": state["frac"]}
            kind = "full"
            parts = None
            with _span("ckpt_capture", lane="ckpt", stats=stats,
                       key="ckpt_capture_s"):
                if ck_writer.want_delta():
                    if device_accumulate:
                        entries = (table_svc.take_delta()
                                   if table_svc is not None else [])
                    else:
                        entries = host_delta.take()
                    if entries is not None:
                        parts = [("", DeltaSteps(entries))]
                        kind = "delta"
                        if device_accumulate:
                            meta["mesh_shards"] = mesh_shards
                            meta["sync_since"] = policy.snapshot()
                if parts is None:
                    # Full image — the PR-5 arrays, and a fresh delta
                    # window: payloads recorded before this base are in
                    # the image, so both logs reset here.
                    parts = []
                    if table_svc is not None:
                        parts.append(("table_",
                                      table_svc.checkpoint_capture()))
                        meta["table_cap"] = table_svc.cap
                        meta["table_kk"] = table_svc.kk
                        # The manifest records the image's sharding
                        # degree so a resume onto a different mesh
                        # degree re-shuffles via the drain path instead
                        # of misreading shard ownership.
                        meta["mesh_shards"] = table_svc.mesh_shards
                        meta["sync_since"] = policy.snapshot()
                        if ck_delta:
                            table_svc.take_delta()
                    host_delta.reset()
                    parts.append(("acc_", acc.snapshot()))
            fault_point("mid-capture")
            ck_writer.commit(parts, meta, kind=kind)
    # Live host buffers = out queue (≤ depth+1) + in-flight window
    # (≤ depth) + one being filled + one being finished.
    pool = BufferPool((n_dev, chunk_bytes), retain=2 * depth + 3)

    def step_call(chunks_dev, mwl, cap, frac, g):
        kw = dict(n_dev=n_dev, n_reduce=n_reduce, max_word_len=mwl,
                  u_cap=cap, mesh=mesh, t_cap_frac=frac, grouper=g)
        with _quiet_unusable_donation():  # first call per rung compiles
            if aot:
                return _aot_step_fn(chunks_dev, donate=donate_steps,
                                    **kw)(chunks_dev)
            if donate_steps:
                return mapreduce_step_donate(chunks_dev, **kw)
            return mapreduce_step(chunks_dev, **kw)

    def pull_packed(keys, lens, cnts, parts, scal_np):
        """One packed host tensor per step (the single-pull D2H shape,
        shuffle._slice_pack) + per-device occupied counts + key width.
        Under aot the prefix is the full capacity instead of the
        data-dependent pow2 prefix — deterministic shapes beat pull
        volume there (see the aot note in the docstring)."""
        m = int(scal_np[:, 0].max())
        if m == 0:
            return None, None, 0
        kk = keys.shape[2]
        if aot:
            packed = np.asarray(_aot_pack(keys, lens, cnts, parts,
                                          mp=keys.shape[1]))
        else:
            mp = occupied_prefix(m, keys.shape[1])
            packed = np.asarray(_slice_pack(keys, lens, cnts, parts, mp=mp))
        return packed, scal_np[:, 0], kk

    def run_step_sync(chunks_np, device_payload: bool = False):
        """The full exactness ladder for ONE batch — the replay path of a
        deferred-check failure, and the semantics ``depth=1`` reduces to.
        Each attempt re-uploads (the step program donates its input, so a
        device buffer never survives an attempt).  With
        ``device_payload`` the payload returns the cleared attempt's
        DEVICE handles (full-capacity packed tensor + scalars) instead of
        pulling — the replayed step then folds its exact output into the
        device table like any confirmed step."""

        def run(mwl: int, cap: int):
            state["cap"] = cap    # last attempt = the one that succeeded
            state["mwl"] = mwl    # (sticky for later optimistic dispatches)
            if on_attempt is not None:
                on_attempt(mwl, cap)
            for g in groupers:
                for frac in (4, 2):
                    chunks = jax.device_put(chunks_np, sharding)
                    keys, lens, cnts, parts, scal = step_call(
                        chunks, mwl, cap, frac, g)
                    scal_np = np.asarray(scal)
                    if not scal_np[:, 4].any():
                        break
                if not scal_np[:, 4].any():
                    break
            state["grouper"], state["frac"] = g, frac  # cleared rung sticks

            def payload():
                if device_payload:
                    mp = keys.shape[1]
                    packed_dev = (
                        _aot_pack(keys, lens, cnts, parts, mp=mp) if aot
                        else _slice_pack(keys, lens, cnts, parts, mp=mp))
                    return packed_dev, scal, scal_np
                return pull_packed(keys, lens, cnts, parts, scal_np)

            return (bool(scal_np[:, 3].any()), int(scal_np[:, 1].max()),
                    int(scal_np[:, 2].max()), payload)

        return exactness_retry(run, chunk_bytes, state["mwl"], state["cap"])

    def dispatch(buf: np.ndarray):
        """Optimistically launch one step at the sticky rung — upload +
        async kernel dispatch, no synchronization.  Under aot the pack
        program is dispatched HERE too (its full-capacity shape is
        deterministic, no flags needed): on an in-order device stream a
        pack dispatched at finish time would queue behind the NEXT step's
        kernel, serializing exactly what the window exists to overlap —
        and misattributing that kernel's wall to pull_s."""
        mwl, cap = state["mwl"], state["cap"]
        if on_attempt is not None:
            on_attempt(mwl, cap)
        chunks = None
        if not isinstance(buf, np.ndarray):
            # Device-resident handoff (dsi_tpu/plan): the upstream
            # stage's output IS this step's upload — the batch is
            # already a sharded jax.Array, so nothing crosses the host.
            chunks = buf
        if wire:
            # Host-side encode + packed upload + on-device decode
            # prologue.  The decode output feeds the step exactly where
            # the raw upload would — same tensors in HBM, so depth/
            # dacc/mesh parity is bit-identical by construction.
            with _span("decode", lane="upload", stats=stats,
                       key="decode_s", step=stats["steps"]):
                enc = wirecodec.encode_chunk(buf)
            if enc is None:
                stats["wire_raw_steps"] += 1
            else:
                mode, packed_np, wire_lit = enc
                with _span("upload", stats=stats, key="upload_s",
                           step=stats["steps"]):
                    packed_dev = jax.device_put(packed_np, sharding)
                with _span("decode", lane="upload", stats=stats,
                           key="decode_s", step=stats["steps"]):
                    chunks = wirecodec.decode_chunk_device(
                        packed_dev, n=chunk_bytes, lit_cap=wire_lit,
                        mode=mode, aot=aot)
                del packed_dev  # frees as soon as the prologue consumes it
                stats["wire_steps"] += 1
                stats["wire_packed_bytes"] += int(packed_np.nbytes)
                wire_raw_total[0] += n_dev * chunk_bytes
                stats["wire_ratio"] = round(
                    wire_raw_total[0] / stats["wire_packed_bytes"], 3)
        if chunks is None:
            with _span("upload", stats=stats, key="upload_s",
                       step=stats["steps"]):
                chunks = jax.device_put(buf, sharding)
        keys, lens, cnts, parts, scal = step_call(
            chunks, mwl, cap, state["frac"], state["grouper"])
        if aot or device_accumulate:
            # Only scal + the packed tensor stay referenced: the four
            # result tables free as soon as the pack consumes them, so an
            # in-flight step holds one packed copy, not five tables.
            # Device accumulation packs eagerly even under jit — the fold
            # consumes the packed layout, and its full-capacity shape is
            # deterministic (no flags needed at dispatch time).
            mp = keys.shape[1]
            packed_dev = (_aot_pack(keys, lens, cnts, parts, mp=mp) if aot
                          else _slice_pack(keys, lens, cnts, parts, mp=mp))
            handles = (scal, packed_dev, keys.shape[2], None)
        else:
            handles = (scal, None, keys.shape[2],
                       (keys, lens, cnts, parts))
        stats["steps"] += 1
        rec_offset = 0
        if offsets is not None:
            # Cursor of THIS step: absolute stream offset just past its
            # batch's content (offsets[i] is appended before batch i is
            # queued, so it is always present here).
            rec_offset = start_offset + offsets[dispatch_idx[0]]
            dispatch_idx[0] += 1
        fault_point("post-dispatch")
        return (buf, mwl, cap, rec_offset, handles)

    def finish_one(record) -> None:
        """Retire the oldest in-flight step: deferred exactness check,
        then merge (clean) or replay-at-wider-shape (overflow)."""
        buf, mwl, cap, rec_offset, (scal, packed_dev, kk, tables) = record
        with _span("kernel", stats=stats, key="kernel_s"):
            scal_np = np.asarray(scal)  # blocks until the kernel lands
        if scal_np[:, 3].any():      # non-ASCII: the whole stream is host's
            pool.give(buf)
            raise _NeedsHostPath
        exact = (not scal_np[:, 4].any()
                 and int(scal_np[:, 1].max()) <= cap
                 and int(scal_np[:, 2].max()) <= mwl)
        if exact:
            if device_accumulate:
                # Fold instead of pull+merge: the confirmed step's packed
                # output stays on device; the host sees it at the next
                # sync.  This is the lagged-confirmation invariant — a
                # fold happens only HERE, after the exactness flags of
                # its step cleared.
                fold_confirmed(packed_dev, scal, scal_np)
            else:
                with _span("pull", stats=stats, key="pull_s"):
                    if int(scal_np[:, 0].max()) == 0:
                        packed, nus = None, None
                    elif packed_dev is not None:  # aot: pack already ran
                        packed, nus = np.asarray(packed_dev), scal_np[:, 0]
                    else:
                        packed, nus, kk = pull_packed(*tables, scal_np)
                    if packed is not None:
                        stats["step_pulls"] += 1
                with _span("merge", stats=stats, key="merge_s"):
                    if packed is not None:
                        acc.add_packed_step(packed, nus, kk)
                        if ck_delta and ck_store is not None:
                            # Host-merge delta log: the step's payload,
                            # trimmed+copied (an AOT pull is capacity-
                            # shaped) and window-bounded.
                            host_delta.append(packed, nus)
        else:
            # Late-detected overflow: replay just this step through the
            # ladder.  Exactly-once by construction — the optimistic
            # attempt's tables are dropped unmerged, and the replay's
            # payload merges (or folds) here and nowhere else.
            stats["replays"] += 1
            with _span("replay", stats=stats, key="replay_s"):
                payload = run_step_sync(buf,
                                        device_payload=device_accumulate)
                if payload is None:
                    pool.give(buf)
                    raise _NeedsHostPath
                if device_accumulate:
                    packed_dev, scal_dev, scal_np = payload()
                    fold_confirmed(packed_dev, scal_dev, scal_np)
                else:
                    packed, nus, kk = payload()
                    if packed is not None:
                        stats["step_pulls"] += 1
                        acc.add_packed_step(packed, nus, kk)
                        if ck_delta and ck_store is not None:
                            host_delta.append(packed, nus)
        # This step is now CONFIRMED: its output is merged/folded and
        # nothing after it is.  The fault point sits BEFORE the cursor
        # advances — the classic torn-update instant.
        fault_point("mid-fold")
        if ck_store is not None:
            ck_cursor["offset"] = rec_offset
            ck_cursor["steps"] += 1
            ck_policy.note_step()
            if ck_policy.due():
                save_ckpt()
                ck_policy.reset()
        pool.give(buf)

    # ── the window itself: the shared dispatch/finish pipeline core,
    # armed for the step object's {advance, confirm, ...} lifecycle ──
    pipe = StepPipeline(depth=depth, dispatch=dispatch, finish=finish_one,
                        stats=stats, produce_key="batch_s",
                        wait_key="batch_wait_s",
                        inflight_key="max_inflight_chunks",
                        thread_name="dsi-stream-batcher", engine="stream")

    step._pipe = pipe
    step._cursor_ref = ck_cursor
    if device_batches is not None:
        pipe.begin(lambda: iter(device_batches))
    else:
        feed = skip_stream(blocks, start_offset) if start_offset else blocks
        pipe.begin(lambda: batch_stream(feed, n_dev, chunk_bytes,
                                        pool=pool, offsets=offsets))
    step._host_excs = (_TokenTooLong, _NeedsHostPath)
    step._save = save_ckpt if ck_store is not None else None
    step._writer = ck_writer
    if resume:
        step._restore_info = {
            "resume_cursor": stats.get("resume_cursor", 0),
            "resume_gap_s": stats.get("resume_gap_s", 0.0)}

    def on_complete():
        # End-of-stream epilogue, exactly the monolithic function's
        # success path: final device drain, async-commit errors
        # surfaced, then the result.
        if table_svc is not None:
            fault_point("pre-sync")
            table_svc.close()  # the "or at stream end" pull
        if ck_writer is not None:
            ck_writer.drain()  # surface async commit errors; counters
            # settle before the caller reads them
        step.result = acc.finalize()

    released = []

    def release():
        if released:  # idempotent: close() after a suspend/fail re-runs it
            return
        released.append(True)
        if ck_writer is not None:
            ck_writer.shutdown()
        fold_source_stats(stats, blocks)
        if pipeline_stats is not None:
            stats["batch_allocs"] = pool.allocs
            for k in ("batch_s", "batch_wait_s", "upload_s", "kernel_s",
                      "pull_s", "merge_s", "replay_s", "fold_s", "sync_s",
                      "widen_s", "ckpt_s", "ckpt_capture_s",
                      "ckpt_commit_s", "ckpt_barrier_s", "decode_s",
                      "ckpt_compress_s"):
                if k in stats:
                    stats[k] = round(stats[k], 4)
            pipeline_stats.update(stats)

    step._on_complete = on_complete
    step._release = release
