"""Streaming SPMD word count: corpus size decoupled from device memory.

``wordcount_sharded`` (parallel/shuffle.py) materialises the whole corpus
host-side and pads every device shard to the longest's power of two — fine
at bench scale, structurally incapable of BASELINE's 10 GB config.  This
module is the chunked multi-step redesign (VERDICT r1 weakness #7):

* the corpus arrives as an **iterator of byte blocks** (files, sockets,
  generators — never required to fit in memory),
* a carry buffer slices it into fixed ``[n_dev, chunk_bytes]`` batches,
  cutting only at non-letter boundaries so no token straddles a chunk
  (same rule as ``shard_text``; the carry makes it exact across batches),
* every batch runs the SAME compiled ``mapreduce_step`` program (static
  shapes: one compile per capacity rung for the whole stream, however
  long),
* per-step per-device grouped counts are merged into a host accumulator
  (``parallel/merge.py`` PackedCounts: raw packed-key tables, numpy
  lexsort + segmented sum, spellings decoded once at the end) — bounded
  by *vocabulary*, not corpus size.

Three scale levers this module owns (VERDICT r3 weakness #2):

* **sticky adaptive capacity** — ``u_cap`` is only the STARTING per-device
  unique capacity; a step that overflows retries itself wider (the shared
  ``exactness_retry`` ladder) and the capacity that worked is reused for
  every later step, so a low-vocabulary stream never pays for a
  worst-case kernel (the sort inside the step is O(cap log cap)) and a
  high-vocabulary stream widens exactly once,
* **prefix-sliced D2H** — only the occupied prefix of the result tables
  (max per-device merged uniques, rounded up to a power of two so the
  slice programs stay bounded) crosses the wire; the pull cost tracks
  vocabulary, not capacity — on the axon tunnel's ~25 MB/s D2H path this
  is the difference between milliseconds and seconds per step,
* **vectorized merge** — no per-word Python in the steady state.

Memory bound, explicitly: device HBM holds one ``n_dev x chunk_bytes``
batch plus the kernel's fixed-size buffers; the host holds the carry
(< ``n_dev x chunk_bytes + block``) and the accumulator (O(uniques) merged
table plus a bounded compaction window).  Nothing scales with total
corpus bytes.

The reference has no analogue (its scaling lever is nMap = #input files on
a shared filesystem, ``mr/coordinator.go:152``); this is that lever
re-designed for a device mesh: nMap becomes "number of stream steps".
"""

from __future__ import annotations

from typing import Dict, Iterable, Iterator, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from dsi_tpu.ops.wordcount import (
    default_grouper,
    exactness_retry,
    grouper_ladder,
)
from dsi_tpu.parallel.merge import PackedCounts
from dsi_tpu.parallel.shuffle import (
    _is_letter_byte,
    _slice_pack,
    default_mesh,
    mapreduce_step,
    occupied_prefix,
)

# A cut never needs to back off further than the longest word the kernels
# can represent (64 bytes, ops/wordcount.py exactness_retry ladder) — if it
# does, the input has a word the device path must hand to the host anyway.
_MAX_BACKOFF = 96


class _TokenTooLong(Exception):
    """A letter run longer than the device word limit spans a cut point."""


def _cut_at_boundary(buf, size: int) -> int:
    """Largest c <= size with no letter run crossing buf[c-1]/buf[c]."""
    if len(buf) <= size:
        return len(buf)
    c = size
    while c > 0 and _is_letter_byte(buf[c - 1]) and _is_letter_byte(buf[c]):
        c -= 1
        if size - c > _MAX_BACKOFF:
            raise _TokenTooLong
    return c


def batch_stream(blocks: Iterable[bytes], n_dev: int,
                 chunk_bytes: int) -> Iterator[np.ndarray]:
    """Slice a byte-block stream into zero-padded [n_dev, chunk_bytes]
    batches, cutting rows only at non-letter boundaries."""
    carry = bytearray()
    batch = np.zeros((n_dev, chunk_bytes), dtype=np.uint8)
    row = 0

    def fill_rows(final: bool):
        nonlocal row, carry, batch
        while carry and (len(carry) >= chunk_bytes + 1 or final):
            cut = _cut_at_boundary(carry, chunk_bytes)
            piece = carry[:cut]
            del carry[:cut]
            batch[row, :len(piece)] = np.frombuffer(bytes(piece),
                                                    dtype=np.uint8)
            row += 1
            if row == n_dev:
                yield batch
                batch = np.zeros((n_dev, chunk_bytes), dtype=np.uint8)
                row = 0

    for block in blocks:
        carry.extend(block)
        yield from fill_rows(final=False)
    yield from fill_rows(final=True)
    if row:
        yield batch  # tail batch; remaining rows are empty (all-zero) chunks


def stream_files(paths: Sequence[str],
                 block_bytes: int = 4 << 20) -> Iterator[bytes]:
    """File contents as a block stream, separated by newlines so the last
    word of one file and the first of the next never merge."""
    for i, p in enumerate(paths):
        if i:
            yield b"\n"
        with open(p, "rb") as f:
            while True:
                b = f.read(block_bytes)
                if not b:
                    break
                yield b


def _step_program(*, n_dev: int, n_reduce: int, max_word_len: int,
                  u_cap: int, mesh: Mesh, t_cap_frac: int,
                  grouper: str = "sort"):
    """The (name, fn, code-deps) triple for one compiled
    ``mapreduce_step`` shape — single definition shared by the
    cached-compile path, the warmer, and the cache-existence probe, so a
    probe's key is by construction the key a run compiles.  The sort
    grouper keeps its historical, readable name; the hash grouper gets a
    distinct suffix.  (Naming only — cache invalidation is governed by
    the source fingerprint, so kernel edits recompile either way.)"""
    import dsi_tpu.ops.wordcount as _wc
    import dsi_tpu.parallel.shuffle as _sh

    def fn(c):
        return mapreduce_step(c, n_dev=n_dev, n_reduce=n_reduce,
                              max_word_len=max_word_len, u_cap=u_cap,
                              mesh=mesh, t_cap_frac=t_cap_frac,
                              grouper=grouper)

    fn._aot_code_deps = (_wc, _sh)
    name = (f"stream_step_d{n_dev}_r{n_reduce}_w{max_word_len}"
            f"_u{u_cap}_f{t_cap_frac}")
    if grouper != "sort":
        name += f"_g{grouper}"
    return name, fn


def _aot_step_fn(example_chunks, **kw):
    """Compiled ``mapreduce_step`` via the persistent AOT executable cache
    (``backends/aotcache.py``) — for single-device bench processes on the
    axon platform, where a fresh-process ``jax.jit`` pays a remote compile
    that JAX's own persistent cache never absorbs (VERDICT r2 weakness
    #1a).  Multi-device meshes compile in-process (the cache auto-disables
    disk persistence there).  ``example_chunks`` may be a
    ``ShapeDtypeStruct`` (warming compiles without executing)."""
    from dsi_tpu.backends import aotcache

    name, fn = _step_program(**kw)
    return aotcache.cached_compile(name, fn, (example_chunks,))


def _aot_step(chunks, **kw):
    return _aot_step_fn(chunks, **kw)(chunks)


def _pack_program(*, mp: int):
    """(name, fn) for one compiled ``shuffle._slice_pack`` shape — shared
    like :func:`_step_program`."""
    import dsi_tpu.parallel.shuffle as _sh

    def fn(k, l, c, p):
        return _slice_pack(k, l, c, p, mp=mp)

    fn._aot_code_deps = (_sh,)
    return f"stream_pack_m{mp}", fn


def _aot_pack_fn(example_args, *, mp: int):
    """Compiled ``shuffle._slice_pack`` via the AOT cache (same rationale
    as :func:`_aot_step_fn`).  ``example_args`` may be shape structs."""
    from dsi_tpu.backends import aotcache

    name, fn = _pack_program(mp=mp)
    return aotcache.cached_compile(name, fn, example_args)


def _stream_examples(n_dev: int, chunk_bytes: int, u_cap: int,
                     max_word_len: int):
    """Shape structs for the step input and pack inputs at one rung."""
    import jax

    sds = jax.ShapeDtypeStruct
    chunks = sds((n_dev, chunk_bytes), jnp.uint8)
    rows = n_dev * u_cap
    kk = max_word_len // 4
    pack_args = (sds((n_dev, rows, kk), jnp.uint32),
                 sds((n_dev, rows), jnp.int32),
                 sds((n_dev, rows), jnp.int32),
                 sds((n_dev, rows), jnp.uint32))
    return chunks, rows, pack_args


def stream_programs_persisted(mesh: Mesh | None = None,
                              chunk_bytes: int = 1 << 20,
                              n_reduce: int = 10, max_word_len: int = 16,
                              u_cap: int = 1 << 12,
                              fracs: Sequence[int] = (4, 2)) -> bool:
    """True when every starting-rung program
    ``wordcount_streaming(..., aot=True)`` would reach first (step at
    each token-capacity frac, plus the pack program) is already in the
    persistent AOT cache — i.e. running the stream is loads, not
    multi-minute remote compiles.  Same role as
    ``corpus_wc.corpus_executable_persisted``: lets a time-boxed bench
    skip the stream row rather than gamble its budget on cold compiles
    (capacity-widening retries beyond the start rung are not probed;
    they are rare and the headline verdict is already durable by then)."""
    from dsi_tpu.backends.aotcache import is_persisted

    if mesh is None:
        mesh = default_mesh()
    n_dev = mesh.devices.size
    chunks, rows, pack_args = _stream_examples(n_dev, chunk_bytes, u_cap,
                                               max_word_len)
    # Probe every grouper rung the run's ladder can reach (the platform
    # default first, sort as the exact fallback) — probing sort alone
    # would answer "warm" while the first program a DSI_WC_GROUPER-pinned
    # run compiles is cold.
    for g in sorted(set(grouper_ladder())):
        for frac in fracs:
            name, fn = _step_program(n_dev=n_dev, n_reduce=n_reduce,
                                     max_word_len=max_word_len, u_cap=u_cap,
                                     mesh=mesh, t_cap_frac=frac, grouper=g)
            if not is_persisted(name, fn, (chunks,)):
                return False
    name, fn = _pack_program(mp=rows)
    return is_persisted(name, fn, pack_args)


def _aot_pack(keys, lens, cnts, parts, *, mp: int):
    return _aot_pack_fn((keys, lens, cnts, parts), mp=mp)(
        keys, lens, cnts, parts)


def warm_stream_aot(mesh: Mesh | None = None, chunk_bytes: int = 1 << 20,
                    n_reduce: int = 10,
                    word_lens: Sequence[int] = (16,),
                    caps: Sequence[int] = (1 << 12, 1 << 14, 1 << 16),
                    fracs: Sequence[int] = (4, 2)) -> None:
    """Compile + persist the program shapes
    ``wordcount_streaming(..., aot=True)`` reaches at these parameters,
    from shape structs alone (no data, nothing executed) — so a later
    fresh process (the driver's bench run) only ever loads serialized
    executables.

    ``caps`` must cover every capacity rung reachable from the stream's
    ``u_cap`` start for its vocabulary (the default covers the function
    default 1<<12 plus two x4 widenings); ``fracs`` mirrors the step's
    token-capacity ladder.  The 64-byte word-window rung is NOT warmed by
    default — it is reachable only by streams carrying >``max_word_len``
    -byte words; pass ``word_lens=(16, 64)`` if yours can."""
    if mesh is None:
        mesh = default_mesh()
    n_dev = mesh.devices.size
    # Warm the platform's preferred grouper alongside the always-available
    # sort rung (ops/wordcount.default_grouper): on the chip that is sort
    # only (names unchanged — the warmed executables stay valid); on CPU
    # the hash grouper is the first rung a run reaches.
    groupers = {"sort", default_grouper()}
    for mwl in word_lens:
        for cap in caps:
            chunks, rows, pack_args = _stream_examples(n_dev, chunk_bytes,
                                                       cap, mwl)
            for frac in fracs:
                for g in sorted(groupers):
                    _aot_step_fn(chunks, n_dev=n_dev, n_reduce=n_reduce,
                                 max_word_len=mwl, u_cap=cap, mesh=mesh,
                                 t_cap_frac=frac, grouper=g)
            _aot_pack_fn(pack_args, mp=rows)


def wordcount_streaming(
        blocks: Iterable[bytes], mesh: Mesh | None = None,
        n_reduce: int = 10, chunk_bytes: int = 1 << 20,
        max_word_len: int = 16, u_cap: int = 1 << 12,
        aot: bool = False,
        on_attempt=None) -> Optional[Dict[str, Tuple[int, int]]]:
    """Exact whole-stream word counts with bounded memory.

    Returns ``{word: (count, reduce_partition)}``, or None when the stream
    needs the host path (non-ASCII bytes, or a word longer than the device
    limit).  Every step reuses one compiled program per capacity rung; a
    step whose uniques overflow retries itself at a wider capacity without
    disturbing the accumulator (rows are merged only after a step
    succeeds), and the widened capacity sticks for later steps.

    ``on_attempt(max_word_len, u_cap)``, if given, is called before every
    kernel attempt — observability for the retry ladder (the driver's
    dryrun uses it to evidence that a capacity retry actually ran).

    ``aot=True`` routes both step and pack programs through the persistent
    AOT executable cache and pulls FULL-capacity packed tables (one
    deterministic shape per rung, so ``warm_stream_aot`` can pre-compile
    everything) instead of data-dependent pow2 prefixes — the right trade
    on the axon platform, where one cold remote compile costs more than
    every capacity-sized pull of a whole bench run.
    """
    if mesh is None:
        mesh = default_mesh()
    n_dev = mesh.devices.size
    acc = PackedCounts()
    state = {"cap": u_cap}
    step_fn = _aot_step if aot else mapreduce_step
    groupers = grouper_ladder()

    def run_step(chunks_np: np.ndarray):
        chunks = jnp.asarray(chunks_np)

        def run(mwl: int, cap: int):
            state["cap"] = cap  # last attempt = the one that succeeded
            if on_attempt is not None:
                on_attempt(mwl, cap)
            for g in groupers:
                for frac in (4, 2):
                    keys, lens, cnts, parts, scal = step_fn(
                        chunks, n_dev=n_dev, n_reduce=n_reduce,
                        max_word_len=mwl, u_cap=cap, mesh=mesh,
                        t_cap_frac=frac, grouper=g)
                    scal_np = np.asarray(scal)
                    if not scal_np[:, 4].any():
                        break
                if not scal_np[:, 4].any():
                    break

            def payload():
                # Pull only the occupied prefix of each result table (the
                # max per-device merged uniques, pow2-rounded so the slice
                # programs stay bounded at log2(cap) distinct shapes): the
                # D2H bill tracks vocabulary, not capacity.  Under aot the
                # prefix is the full capacity instead — deterministic
                # shapes beat pull volume there (see docstring).
                m = int(scal_np[:, 0].max())
                out = []
                if m == 0:
                    return out
                kk = keys.shape[2]
                if aot:
                    packed = np.asarray(_aot_pack(
                        keys, lens, cnts, parts, mp=keys.shape[1]))
                else:
                    mp = occupied_prefix(m, keys.shape[1])
                    packed = np.asarray(_slice_pack(keys, lens, cnts,
                                                    parts, mp=mp))
                for d in range(n_dev):
                    nu = int(scal_np[d, 0])
                    r = packed[d, :nu]
                    out.append((r[:, :kk], r[:, kk], r[:, kk + 1],
                                r[:, kk + 2]))
                return out

            return (bool(scal_np[:, 3].any()), int(scal_np[:, 1].max()),
                    int(scal_np[:, 2].max()), payload)

        return exactness_retry(run, chunk_bytes, max_word_len, state["cap"])

    try:
        for batch in batch_stream(blocks, n_dev, chunk_bytes):
            payload = run_step(batch)
            if payload is None:
                return None  # caller routes the job to the host path
            for krows, lrows, crows, prows in payload():
                acc.add(krows, lrows, crows, prows)
    except _TokenTooLong:
        return None
    return acc.finalize()
