"""Streaming SPMD word count: corpus size decoupled from device memory.

``wordcount_sharded`` (parallel/shuffle.py) materialises the whole corpus
host-side and pads every device shard to the longest's power of two — fine
at bench scale, structurally incapable of BASELINE's 10 GB config.  This
module is the chunked multi-step redesign (VERDICT r1 weakness #7):

* the corpus arrives as an **iterator of byte blocks** (files, sockets,
  generators — never required to fit in memory),
* a carry buffer slices it into fixed ``[n_dev, chunk_bytes]`` batches,
  cutting only at non-letter boundaries so no token straddles a chunk
  (same rule as ``shard_text``; the carry makes it exact across batches),
* every batch runs the SAME compiled ``mapreduce_step`` program (static
  shapes: one compile for the whole stream, however long),
* per-step per-device grouped counts are merged into a host accumulator
  keyed by word — bounded by *vocabulary*, not corpus size.

Memory bound, explicitly: device HBM holds one ``n_dev x chunk_bytes``
batch plus the kernel's fixed-size buffers; the host holds the carry
(< ``n_dev x chunk_bytes + block``) and the accumulator (O(uniques)).
Nothing scales with total corpus bytes.

The reference has no analogue (its scaling lever is nMap = #input files on
a shared filesystem, ``mr/coordinator.go:152``); this is that lever
re-designed for a device mesh: nMap becomes "number of stream steps".
"""

from __future__ import annotations

from typing import Dict, Iterable, Iterator, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from dsi_tpu.ops.wordcount import decode_packed, exactness_retry
from dsi_tpu.parallel.shuffle import (
    _is_letter_byte,
    default_mesh,
    mapreduce_step,
)

# A cut never needs to back off further than the longest word the kernels
# can represent (64 bytes, ops/wordcount.py exactness_retry ladder) — if it
# does, the input has a word the device path must hand to the host anyway.
_MAX_BACKOFF = 96


class _TokenTooLong(Exception):
    """A letter run longer than the device word limit spans a cut point."""


def _cut_at_boundary(buf, size: int) -> int:
    """Largest c <= size with no letter run crossing buf[c-1]/buf[c]."""
    if len(buf) <= size:
        return len(buf)
    c = size
    while c > 0 and _is_letter_byte(buf[c - 1]) and _is_letter_byte(buf[c]):
        c -= 1
        if size - c > _MAX_BACKOFF:
            raise _TokenTooLong
    return c


def batch_stream(blocks: Iterable[bytes], n_dev: int,
                 chunk_bytes: int) -> Iterator[np.ndarray]:
    """Slice a byte-block stream into zero-padded [n_dev, chunk_bytes]
    batches, cutting rows only at non-letter boundaries."""
    carry = bytearray()
    batch = np.zeros((n_dev, chunk_bytes), dtype=np.uint8)
    row = 0

    def fill_rows(final: bool):
        nonlocal row, carry, batch
        while carry and (len(carry) >= chunk_bytes + 1 or final):
            cut = _cut_at_boundary(carry, chunk_bytes)
            piece = carry[:cut]
            del carry[:cut]
            batch[row, :len(piece)] = np.frombuffer(bytes(piece),
                                                    dtype=np.uint8)
            row += 1
            if row == n_dev:
                yield batch
                batch = np.zeros((n_dev, chunk_bytes), dtype=np.uint8)
                row = 0

    for block in blocks:
        carry.extend(block)
        yield from fill_rows(final=False)
    yield from fill_rows(final=True)
    if row:
        yield batch  # tail batch; remaining rows are empty (all-zero) chunks


def stream_files(paths: Sequence[str],
                 block_bytes: int = 4 << 20) -> Iterator[bytes]:
    """File contents as a block stream, separated by newlines so the last
    word of one file and the first of the next never merge."""
    for i, p in enumerate(paths):
        if i:
            yield b"\n"
        with open(p, "rb") as f:
            while True:
                b = f.read(block_bytes)
                if not b:
                    break
                yield b


def wordcount_streaming(
        blocks: Iterable[bytes], mesh: Mesh | None = None,
        n_reduce: int = 10, chunk_bytes: int = 1 << 20,
        max_word_len: int = 16,
        u_cap: int = 1 << 16) -> Optional[Dict[str, Tuple[int, int]]]:
    """Exact whole-stream word counts with bounded memory.

    Returns ``{word: (count, reduce_partition)}``, or None when the stream
    needs the host path (non-ASCII bytes, or a word longer than the device
    limit).  Every step reuses one compiled program; a step whose uniques
    overflow retries itself at a wider capacity without disturbing the
    accumulator (counts are merged only after a step succeeds).
    """
    if mesh is None:
        mesh = default_mesh()
    n_dev = mesh.devices.size
    acc: Dict[str, Tuple[int, int]] = {}

    def run_step(chunks_np: np.ndarray):
        chunks = jnp.asarray(chunks_np)

        def run(mwl: int, cap: int):
            for frac in (4, 2):
                keys, lens, cnts, parts, scal = mapreduce_step(
                    chunks, n_dev=n_dev, n_reduce=n_reduce,
                    max_word_len=mwl, u_cap=cap, mesh=mesh, t_cap_frac=frac)
                scal_np = np.asarray(scal)
                if not scal_np[:, 4].any():
                    break

            def payload():
                k_np, l_np, c_np = (np.asarray(keys), np.asarray(lens),
                                    np.asarray(cnts))
                p_np = np.asarray(parts)
                out = []
                for d in range(n_dev):
                    nu = int(scal_np[d, 0])
                    words = decode_packed(k_np[d], l_np[d], nu)
                    out.append((words, c_np[d], p_np[d]))
                return out

            return (bool(scal_np[:, 3].any()), int(scal_np[:, 1].max()),
                    int(scal_np[:, 2].max()), payload)

        return exactness_retry(run, chunk_bytes, max_word_len, u_cap)

    try:
        for batch in batch_stream(blocks, n_dev, chunk_bytes):
            payload = run_step(batch)
            if payload is None:
                return None  # caller routes the job to the host path
            for words, cnts, parts in payload():
                for i, w in enumerate(words):
                    ent = acc.get(w)
                    if ent is None:
                        acc[w] = (int(cnts[i]), int(parts[i]))
                    else:
                        acc[w] = (ent[0] + int(cnts[i]), ent[1])
    except _TokenTooLong:
        return None
    return acc
