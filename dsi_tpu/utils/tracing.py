"""Per-task timing + structured event log.

The reference has NO tracing/profiling of any kind (SURVEY.md §5: only
log.Fatalf on errors).  This is the new observability layer SURVEY.md calls
for: ``Span`` wall-clock regions that double as structured events, emitted
as one-line JSON on stderr when ``DSI_TRACE=1`` (off: zero overhead beyond a
perf_counter pair).  The worker loop spans every map/reduce task body
(``mr/worker.py``), so a traced run yields a per-task timeline; ``bench.py``
spans its oracle/warmup phases the same way.
"""

from __future__ import annotations

import json
import os
import sys
import time


class Span:
    """Times one named region; ``elapsed_s`` is set on exit.

    Emits a ``log_event`` (span name + seconds + any keyword fields) so
    DSI_TRACE=1 runs get a structured timeline for free.
    """

    def __init__(self, name: str, **fields) -> None:
        self.name = name
        self.fields = fields
        self.elapsed_s = 0.0

    def __enter__(self) -> "Span":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        self.elapsed_s = time.perf_counter() - self._t0
        log_event("span", name=self.name,
                  seconds=round(self.elapsed_s, 4), **self.fields)


def log_event(event: str, **fields) -> None:
    """Structured one-line JSON event log (stderr), off unless DSI_TRACE=1.

    Every event is ALSO mirrored into the unified tracer's control-plane
    lane (``dsi_tpu/obs``) when that is enabled — so a ``--trace-dir``
    run captures the coordinator/worker timeline (assign/complete/
    requeue, task spans) in its Perfetto trace without DSI_TRACE's
    stderr stream.  Mirroring must never break the caller."""
    try:
        from dsi_tpu.obs.trace import get_tracer

        tracer = get_tracer()
        if tracer.enabled:
            if event == "span" and "seconds" in fields:
                f = dict(fields)
                name = str(f.pop("name", "span"))
                tracer.record_span(name, float(f.pop("seconds")), **f)
            else:
                tracer.event(event, **fields)
    except Exception:
        pass
    if os.environ.get("DSI_TRACE") != "1":
        return
    rec = {"t": time.time(), "event": event}
    rec.update(fields)
    sys.stderr.write(json.dumps(rec) + "\n")
