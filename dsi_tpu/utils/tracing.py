"""Per-task timing + optional JAX profiler hooks.

The reference has NO tracing/profiling of any kind (SURVEY.md §5: only
log.Fatalf on errors).  This is the new observability layer SURVEY.md calls
for: lightweight wall-clock phase timers usable from the worker and the bench
harness, and a context manager gating ``jax.profiler`` traces behind an env
var so production runs pay nothing.
"""

from __future__ import annotations

import contextlib
import json
import os
import sys
import time
from typing import Dict, Iterator


class PhaseTimer:
    """Accumulates wall-clock seconds per named phase."""

    def __init__(self) -> None:
        self.totals: Dict[str, float] = {}
        self.counts: Dict[str, int] = {}

    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self.totals[name] = self.totals.get(name, 0.0) + dt
            self.counts[name] = self.counts.get(name, 0) + 1

    def report(self, stream=sys.stderr) -> None:
        for name in sorted(self.totals, key=self.totals.get, reverse=True):
            stream.write(f"[trace] {name}: {self.totals[name]:.3f}s "
                         f"(x{self.counts[name]})\n")

    def as_dict(self) -> Dict[str, float]:
        return dict(self.totals)


class Span:
    """Times one named region; ``elapsed_s`` is set on exit.

    Emits a ``log_event`` (span name + seconds) so DSI_TRACE=1 runs get a
    structured timeline for free.
    """

    def __init__(self, name: str) -> None:
        self.name = name
        self.elapsed_s = 0.0

    def __enter__(self) -> "Span":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        self.elapsed_s = time.perf_counter() - self._t0
        log_event("span", name=self.name, seconds=round(self.elapsed_s, 4))


@contextlib.contextmanager
def maybe_jax_profile(out_dir: str | None = None) -> Iterator[None]:
    """Wrap a region in jax.profiler.trace when DSI_JAX_PROFILE is set."""
    target = out_dir or os.environ.get("DSI_JAX_PROFILE")
    if not target:
        yield
        return
    import jax

    with jax.profiler.trace(target):
        yield


def log_event(event: str, **fields) -> None:
    """Structured one-line JSON event log (stderr), off unless DSI_TRACE=1."""
    if os.environ.get("DSI_TRACE") != "1":
        return
    rec = {"t": time.time(), "event": event}
    rec.update(fields)
    sys.stderr.write(json.dumps(rec) + "\n")
