"""Parallel mmap'd file ingest with readahead — the disk half of the
compressed-wire PR (ISSUE 13).

The streaming engines consume an *iterator of byte blocks*
(``parallel/streaming.py stream_files``): on the host-read-bound margins
of the stream row, every block is read INSIDE the pipeline's producer
thread — the read wall lands in ``materialize_s`` and serializes with
batch slicing.  This module moves it off: a small pool of reader
threads mmaps the input files and copies fixed-size segments out AHEAD
of the consumer (a bounded readahead window keeps memory O(readahead ×
block)), so by the time the batcher asks for block *i* its bytes are
already host-resident and ``materialize_s`` shrinks to the slicing work
the batcher actually owns.

The contract that makes this safe to drop into the checkpointed
engines: the yielded BYTE STREAM is exactly ``stream_files``' —
per-file bytes in order, a single ``b"\\n"`` separator between files —
and the engines' batchers are pure functions of the byte stream
(``batch_stream``/``batch_lines`` module docs), so cursors, checkpoint
offsets and ``skip_stream`` resume seeks stay byte-exact whatever the
reader count or block boundaries.  Only segment *scheduling* is
parallel; delivery order is total.

No jax, no numpy: importable by no-jax consumers (CLI arg parsing,
bench gating) and by the dsicheck bare-interpreter job.  Read-only by
construction — mmap ``ACCESS_READ`` with a seek/read fallback — so
there is nothing here for the raw-write rule to exempt.

Stats (``ParallelBlocks.ingest_stats()``; the engines fold them into
their metrics scope at release — ``parallel/pipeline.py
fold_source_stats``): ``ingest_readers``, ``ingest_blocks``,
``readahead_hit_pct`` (blocks already resident when the consumer asked
— the "did readahead actually run ahead" evidence), ``ingest_wait_s``
(consumer wall blocked on a block that was NOT ready).
"""

from __future__ import annotations

import mmap
import os
import threading
import time
from typing import Iterator, List, Optional, Sequence, Tuple

_READERS_ENV = "DSI_INGEST_READERS"
#: Default block size — matches ``stream_files``' 4 MiB.
DEFAULT_BLOCK_BYTES = 4 << 20


def ingest_readers_default(readers: Optional[int] = None) -> int:
    """Resolve the reader-pool width: an explicit value wins, else
    ``DSI_INGEST_READERS`` (default 0 = no pool, inline reads — the
    historical ``stream_files`` path, bit-identical by construction)."""
    if readers is None:
        try:
            readers = int(os.environ.get(_READERS_ENV, "0"))
        except ValueError:
            readers = 0
    return max(0, int(readers))


def serial_blocks(paths: Sequence[str],
                  block_bytes: int = DEFAULT_BLOCK_BYTES) -> Iterator[bytes]:
    """File contents as an in-order block stream with ``b"\\n"`` file
    separators — byte-identical to ``parallel/streaming.stream_files``
    (that module needs jax; this one is import-light for the CLIs'
    no-pool path)."""
    for i, p in enumerate(paths):
        if i:
            yield b"\n"
        with open(p, "rb") as f:
            while True:
                b = f.read(block_bytes)
                if not b:
                    break
                yield b


#: Segment plan entries: (path_index, offset, length) for file bytes,
#: or (-1, 0, 0) for the inter-file separator block.
_SEP = (-1, 0, 0)


def _plan_segments(paths: Sequence[str],
                   block_bytes: int) -> List[Tuple[int, int, int]]:
    segs: List[Tuple[int, int, int]] = []
    for i, p in enumerate(paths):
        if i:
            segs.append(_SEP)
        size = os.path.getsize(p)
        off = 0
        while off < size:
            n = min(block_bytes, size - off)
            segs.append((i, off, n))
            off += n
    return segs


class ParallelBlocks:
    """In-order block stream over ``paths`` read by ``readers`` threads
    with a bounded readahead window.

    Iterable (single pass).  Reader threads claim segment ordinals up to
    ``consumed + readahead`` and fill per-segment slots; the consumer
    yields slot *i* strictly in order, blocking only when the pool has
    not reached it yet (counted as a readahead miss).  Abandoning the
    iterator mid-stream (a tenant eviction, an engine unwinding on an
    error) tears the pool down via the generator's ``finally`` —
    threads are daemons and stop at their next claim check either way.
    """

    def __init__(self, paths: Sequence[str],
                 block_bytes: int = DEFAULT_BLOCK_BYTES,
                 readers: Optional[int] = None,
                 readahead: Optional[int] = None):
        self.paths = [str(p) for p in paths]
        self.block_bytes = max(1, int(block_bytes))
        self.readers = max(1, ingest_readers_default(readers))
        #: In-flight + ready-but-unconsumed segments the pool may hold:
        #: the memory bound (readahead × block_bytes) and the distance
        #: the pool can run ahead of the consumer.
        self.readahead = (max(2, 2 * self.readers) if readahead is None
                          else max(1, int(readahead)))
        self._segs = _plan_segments(self.paths, self.block_bytes)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._slots: dict = {}
        self._next_claim = 0
        self._consumed = 0
        self._closed = False
        self._err: Optional[BaseException] = None
        self._threads: List[threading.Thread] = []
        self._mmaps: dict = {}
        self._hits = 0
        self._misses = 0
        self._wait_s = 0.0

    # ── reading (reader threads) ──

    def _read_segment(self, seg: Tuple[int, int, int]) -> bytes:
        pi, off, n = seg
        if pi < 0:
            return b"\n"
        mm = self._file_map(pi)
        if mm is not None:
            return bytes(mm[off:off + n])
        with open(self.paths[pi], "rb") as f:  # mmap-refusing file
            f.seek(off)
            return f.read(n)

    def _file_map(self, pi: int):
        """One shared read-only mmap per file, opened lazily (None for
        files mmap refuses — zero-length, special files — which fall
        back to seek/read)."""
        with self._lock:
            if pi in self._mmaps:
                return self._mmaps[pi]
        try:
            with open(self.paths[pi], "rb") as f:
                mm = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
        except (ValueError, OSError):
            mm = None
        with self._lock:
            # First opener wins; a racing duplicate closes itself.
            cur = self._mmaps.setdefault(pi, mm)
            if cur is not mm and mm is not None:
                mm.close()
            return cur

    def _reader_loop(self) -> None:
        while True:
            with self._cond:
                while (not self._closed
                       and (self._next_claim >= len(self._segs)
                            or self._next_claim
                            >= self._consumed + self.readahead)):
                    if self._next_claim >= len(self._segs):
                        return
                    self._cond.wait(0.2)
                if self._closed:
                    return
                i = self._next_claim
                self._next_claim += 1
            try:
                data = self._read_segment(self._segs[i])
            except BaseException as e:
                with self._cond:
                    self._err = self._err or e
                    self._cond.notify_all()
                return
            with self._cond:
                self._slots[i] = data
                self._cond.notify_all()

    def _start(self) -> None:
        if self._threads:
            return
        n = min(self.readers, max(1, len(self._segs)))
        for r in range(n):
            t = threading.Thread(target=self._reader_loop, daemon=True,
                                 name=f"dsi-ingest-reader-{r}")
            self._threads.append(t)
            t.start()

    # ── consuming ──

    def __iter__(self) -> Iterator[bytes]:
        if self._closed:
            # Single-pass source: after exhaustion/abandonment no reader
            # will ever fill another slot — a second pass would wait
            # forever on slot 0.  Fail loudly instead of hanging.
            raise RuntimeError("ParallelBlocks is single-pass and was "
                               "already consumed/closed; construct a "
                               "fresh pool to re-read")
        self._start()
        try:
            for i in range(len(self._segs)):
                with self._cond:
                    if i in self._slots:
                        self._hits += 1
                    else:
                        self._misses += 1
                        t0 = time.perf_counter()
                        while i not in self._slots and self._err is None:
                            self._cond.wait(0.2)
                        self._wait_s += time.perf_counter() - t0
                    if self._err is not None and i not in self._slots:
                        raise self._err
                    data = self._slots.pop(i)
                    self._consumed = i + 1
                    self._cond.notify_all()
                yield data
        finally:
            self.close()

    def close(self) -> None:
        """Stop the pool and release the file maps.  Idempotent; called
        by the iterator's own ``finally`` (stream end OR mid-stream
        abandonment)."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
            maps, self._mmaps = self._mmaps, {}
        for t in self._threads:
            t.join(timeout=5.0)
        for mm in maps.values():
            if mm is not None:
                try:
                    mm.close()
                except (ValueError, OSError):
                    pass

    def ingest_stats(self) -> dict:
        """The engines' release-time fold (``fold_source_stats``):
        schema-pinned keys only (``obs/registry.py SCHEMA_KEYS``)."""
        asked = self._hits + self._misses
        return {"ingest_readers": self.readers,
                "ingest_blocks": asked,
                "readahead_hit_pct": round(100.0 * self._hits / asked, 1)
                if asked else 0.0,
                "ingest_wait_s": round(self._wait_s, 4)}


def open_blocks(paths: Sequence[str],
                readers: Optional[int] = None,
                block_bytes: int = DEFAULT_BLOCK_BYTES,
                readahead: Optional[int] = None):
    """The one ingest entry point the CLIs/bench use: a
    :class:`ParallelBlocks` pool when the resolved reader count
    (``--ingest-readers`` / ``DSI_INGEST_READERS``) is >= 1, else the
    plain in-order generator — byte-identical streams either way."""
    n = ingest_readers_default(readers)
    if n >= 1:
        return ParallelBlocks(paths, block_bytes=block_bytes,
                              readers=n, readahead=readahead)
    return serial_blocks(paths, block_bytes=block_bytes)
