"""Pin the JAX platform through jax.config from DSI_JAX_PLATFORM.

Setting the ``JAX_PLATFORMS`` env var is NOT enough on hosts where a
sitecustomize pre-registers a TPU plugin (observed: the plugin initializes —
and can hang on a wedged device — even with ``JAX_PLATFORMS=cpu``); pinning
through ``jax.config`` before the first backend access is the reliable
override.  One shared helper so every entry point (bench, CLIs, the TPU
task backend) stays in sync.
"""

from __future__ import annotations

import os


def pin_platform_from_env(var: str = "DSI_JAX_PLATFORM") -> str | None:
    """If env ``var`` (or standard ``JAX_PLATFORMS``) is set, route JAX to
    that platform through jax.config; returns the platform string.

    Honoring ``JAX_PLATFORMS`` here matters: the env var alone is silently
    ignored by this host's pre-registered TPU plugin (observed: a CLI run
    with ``JAX_PLATFORMS=cpu`` still initialized — and hung on — the
    remote TPU backend during an outage), while the config pin is
    reliable.  So the standard JAX knob behaves as users expect at every
    entry point that calls this."""
    plat = os.environ.get(var) or os.environ.get("JAX_PLATFORMS")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)
    return plat
