"""Pin the JAX platform through jax.config from DSI_JAX_PLATFORM.

Setting the ``JAX_PLATFORMS`` env var is NOT enough on hosts where a
sitecustomize pre-registers a TPU plugin (observed: the plugin initializes —
and can hang on a wedged device — even with ``JAX_PLATFORMS=cpu``); pinning
through ``jax.config`` before the first backend access is the reliable
override.  One shared helper so every entry point (bench, CLIs, the TPU
task backend) stays in sync.
"""

from __future__ import annotations

import os


def pin_platform_from_env(var: str = "DSI_JAX_PLATFORM") -> str | None:
    """If env ``var`` is set, route JAX to that platform; returns it."""
    plat = os.environ.get(var)
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)
    return plat
