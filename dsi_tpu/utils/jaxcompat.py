"""Version-tolerant aliases for jax APIs that moved between releases.

The kernels target the modern spellings (``jax.shard_map``,
``jax.enable_x64``); on installs that predate their graduation from
``jax.experimental`` the experimental originals are re-exported instead.
One module so every kernel resolves the same implementation — a per-file
try/except drift here would let two modules disagree mid-upgrade.
"""

from __future__ import annotations

import functools

import jax

try:
    enable_x64 = jax.enable_x64
except AttributeError:  # pre-graduation jax (e.g. 0.4.x)
    from jax.experimental import enable_x64  # noqa: F401

try:
    shard_map = jax.shard_map
except AttributeError:  # pre-graduation jax (e.g. 0.4.x)
    from jax.experimental.shard_map import shard_map  # noqa: F401


def x64_scoped(fn):
    """Run every invocation of ``fn`` under ``enable_x64(True)``.

    The kernels write their uint64 blocks inside scoped ``enable_x64``
    contexts; on jax versions where lowering reads the flag at the
    jit-call boundary rather than at trace time, the scoped block alone
    fails stablehlo verification ("shift_left op requires compatible
    types") — the *call* must sit inside the scope so trace, lower, and
    compile all see x64.  Wrapping only the u64-bearing entry points
    keeps the flag out of the global config (which would change dtype
    inference package-wide)."""
    @functools.wraps(fn)
    def call(*args, **kwargs):
        with enable_x64(True):
            return fn(*args, **kwargs)

    return call
