"""Deterministic synthetic corpus generator.

The reference's inputs are Project Gutenberg texts ``pg-*.txt`` which are NOT
in its repo (gitignored, reference .gitignore:36; referenced by
test-mr.sh:30,36).  SURVEY.md §7 step 1 requires this rebuild to generate its
own corpus.  This produces Gutenberg-like ASCII text — Zipf-distributed words,
punctuation, line breaks — deterministically from a seed, vectorized with
numpy so multi-hundred-MB corpora generate in seconds.

ASCII-only by construction, so the byte-level letter classification used by
the TPU kernels agrees exactly with Unicode ``IsLetter`` semantics on this
corpus (SURVEY.md §7 hard part 1).
"""

from __future__ import annotations

import os
from typing import List

import numpy as np

from dsi_tpu.utils.atomicio import atomic_write

_PUNCT = np.frombuffer(b".,;:!?", dtype=np.uint8)


def _make_vocab(rng: np.random.Generator, size: int) -> List[bytes]:
    """Random lowercase words, length ~ 2..12, plus some Capitalized forms."""
    lengths = rng.integers(2, 13, size=size)
    letters = rng.integers(ord("a"), ord("z") + 1, size=int(lengths.sum()),
                           dtype=np.uint8)
    out: List[bytes] = []
    pos = 0
    for L in lengths:
        w = letters[pos:pos + L].tobytes()
        pos += L
        out.append(w)
    # Capitalize ~10% to widen the key space like real prose.
    for i in range(0, size, 10):
        out[i] = out[i][:1].upper() + out[i][1:]
    return out


def generate_file(path: str, size_bytes: int, seed: int,
                  vocab_size: int = 20000) -> None:
    rng = np.random.default_rng(seed)
    vocab = _make_vocab(rng, vocab_size)
    # Zipf-ish rank weights: p(r) ~ 1/(r+2.7)
    ranks = np.arange(vocab_size, dtype=np.float64)
    probs = 1.0 / (ranks + 2.7)
    probs /= probs.sum()
    avg_word = sum(len(w) for w in vocab[:2000]) / 2000 + 1.0
    n_words = int(size_bytes / avg_word) + 16

    idx = rng.choice(vocab_size, size=n_words, p=probs)
    # Separators: mostly space, some punctuation+space, some newlines.
    sep_kind = rng.random(n_words)
    pieces: List[bytes] = []
    vocab_arr = vocab  # local ref
    for k, i in enumerate(idx):
        pieces.append(vocab_arr[i])
        s = sep_kind[k]
        if s < 0.80:
            pieces.append(b" ")
        elif s < 0.92:
            pieces.append(bytes([_PUNCT[int(s * 1000) % len(_PUNCT)]]) + b" ")
        else:
            pieces.append(b"\n")
    blob = b"".join(pieces)[:size_bytes]
    # Atomic commit (temp + rename, utils/atomicio): a generator killed
    # mid-write must not leave a torn pg-*.txt that happens to pass
    # ensure_corpus's size check on a later retry, and two processes
    # generating the same corpus dir concurrently (bench + soak) must
    # never interleave writes into one file.  Durability (fsync) is
    # deliberately not needed — the corpus is deterministic from its
    # seed and regenerates.
    with atomic_write(path, "wb") as f:
        f.write(blob)


def ensure_corpus(directory: str, n_files: int = 8,
                  file_size: int = 2 << 20, seed: int = 1234) -> List[str]:
    """Create pg-like input files if absent; return sorted paths."""
    os.makedirs(directory, exist_ok=True)
    paths = []
    for i in range(n_files):
        p = os.path.join(directory, f"pg-{i:02d}.txt")
        if not (os.path.exists(p) and os.path.getsize(p) == file_size):
            generate_file(p, file_size, seed + i)
        paths.append(p)
    return paths
