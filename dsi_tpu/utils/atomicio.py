"""Atomic file commit: write to a temp file, then rename.

Reference: map-side intermediate commit ``os.CreateTemp`` + ``os.Rename``
(``mr/worker.go:83,91``) and reduce-side output commit (``mr/worker.go:127,148``).
Atomic rename is the framework's entire checkpoint/idempotence story
(SURVEY.md §5): re-executed tasks overwrite with a complete file, and readers
never observe a partial file.

Two commit disciplines:

* default (last-writer-wins ``os.rename``) — the reference's semantics for
  map intermediates, where every writer produced identical content;
* ``first_wins=True`` (``os.link``; an existing target wins) — for the
  reduce output commit.  The reference's last-writer-wins reduce commit has
  a latent duplicate-execution race (worker.go:148,151-154): a re-queued
  reduce B that reads ``mr-*-<r>`` *after* the original completer A
  garbage-collected them sees an empty partition (missing files are
  tolerated, worker.go:106-108) and renames an EMPTY ``mr-out-<r>`` over
  A's full one.  Under the reference's 10 s timeout this never fires; under
  tiny task timeouts the race-soak test catches it losing whole partitions.
  First-writer-wins closes it: any reducer that observed GC'd inputs
  necessarily commits after the reducer that did the GC, so its commit is
  discarded.  Output-invariant vs the reference on every non-racy schedule
  (duplicate executions of a deterministic reduce produce identical bytes).
"""

from __future__ import annotations

import os
import tempfile
import zlib
from contextlib import contextmanager
from typing import IO, Iterator, Optional


@contextmanager
def atomic_write(path: str, mode: str = "w",
                 first_wins: bool = False) -> Iterator[IO]:
    """Open a temp file in the destination directory; rename onto `path` on
    successful exit.  On exception the temp file is removed and nothing is
    committed (mirrors the reference: a crashed worker leaves no partial
    mr-X-Y / mr-out-Y file, mr/worker.go:81-92,126-148).

    ``first_wins=True`` commits with ``os.link`` instead: if ``path``
    already exists the new content is discarded and the existing file kept
    (see module docstring for why the reduce output needs this)."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    # The ".tmp-" prefix keeps uncommitted temp files out of the harness's
    # "mr-out*" merge glob if a worker dies (os._exit) mid-write.
    fd, tmp = tempfile.mkstemp(prefix=".tmp-" + os.path.basename(path) + ".", dir=d)
    # Text mode pins utf-8: output bytes must not depend on the host locale
    # (a worker under an ASCII locale would otherwise crash writing any
    # non-ASCII key, and mixed-locale fleets would diverge).
    f = os.fdopen(fd, mode, encoding=None if "b" in mode else "utf-8")
    try:
        yield f
        f.flush()
        os.fsync(f.fileno())
        f.close()
        if first_wins:
            try:
                os.link(tmp, path)  # atomic; fails iff path exists
            except FileExistsError:
                pass  # a complete commit already landed; keep it
            except OSError:
                # Filesystem without hardlinks (some NFS/CIFS): degrade to
                # the reference's last-writer-wins rename rather than fail
                # every commit.  The duplicate-reduce window reopens there,
                # exactly as in the reference.
                os.rename(tmp, path)
                tmp = None
            if tmp is not None:
                os.remove(tmp)
        else:
            os.rename(tmp, path)  # atomic commit
    except BaseException:
        try:
            f.close()
        finally:
            try:
                os.remove(tmp)
            except OSError:
                pass
        raise


def fsync_dir(path: str) -> None:
    """fsync a DIRECTORY so a just-created/renamed entry survives power
    loss: rename makes the new name visible, but only the directory
    fsync makes the entry durable.  ``atomic_write`` alone shipped with
    this gap (as did the journal's create-then-append); the checkpoint
    manifests and the control-plane journal both close it through this
    one helper.  Best-effort: some filesystems refuse O_RDONLY
    directory fsync, and losing the optimization there must not fail
    the commit."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def write_bytes_durable(path: str, data: bytes) -> int:
    """The shared durable-write path for checkpoint payloads and
    manifests: ``atomic_write`` (temp + file fsync + rename) + a CRC32
    sidecar (``<path>.crc32``) + parent-dir fsync.  Returns the CRC32.

    The sidecar is written AFTER the payload commits: a crash between
    the two leaves a payload without a sidecar, which
    :func:`read_bytes_verified` treats exactly like a torn payload —
    invisible, fall back to the previous generation."""
    crc = zlib.crc32(data)
    with atomic_write(path, "wb") as f:
        f.write(data)
    with atomic_write(path + ".crc32", "w") as f:
        f.write(f"{crc:08x}\n")
    fsync_dir(os.path.dirname(os.path.abspath(path)) or ".")
    return crc


def read_bytes_verified(path: str) -> Optional[bytes]:
    """Read ``path`` and verify it against its CRC32 sidecar; None when
    the file or sidecar is missing, unparsable, or mismatched — the
    loader's cue to fall back to an older generation rather than trust
    bytes that survived a rename but not the crash."""
    try:
        with open(path, "rb") as f:
            data = f.read()
        with open(path + ".crc32", encoding="ascii") as f:
            want = int(f.read().strip(), 16)
    except (OSError, ValueError):
        return None
    if zlib.crc32(data) != want:
        return None
    return data


def reap_tmp_files(directory: str, prefix: str = ".tmp-") -> int:
    """Remove ``.tmp-*`` orphans left by writers killed mid-commit
    (``atomic_write``'s temp prefix).  Safe in a quiesced directory by
    construction: a live writer's temp file disappears at rename, so
    anything still named ``.tmp-*`` once the writers are dead is
    garbage.  In a directory SHARED by live writers (mrrun's trace dir),
    pass a narrower ``prefix`` — ``.tmp-<target-name>.`` — so one
    process only reaps its own orphans, never a committing sibling's
    in-flight temp.  Returns the number removed."""
    n = 0
    try:
        names = os.listdir(directory)
    except OSError:
        return 0
    for name in names:
        if name.startswith(prefix):
            try:
                os.remove(os.path.join(directory, name))
                n += 1
            except OSError:
                pass
    return n
