"""Atomic file commit: write to a temp file, then rename.

Reference: map-side intermediate commit ``os.CreateTemp`` + ``os.Rename``
(``mr/worker.go:83,91``) and reduce-side output commit (``mr/worker.go:127,148``).
Atomic rename is the framework's entire checkpoint/idempotence story
(SURVEY.md §5): re-executed tasks overwrite with a complete file, last writer
wins, readers never observe a partial file.
"""

from __future__ import annotations

import os
import tempfile
from contextlib import contextmanager
from typing import IO, Iterator


@contextmanager
def atomic_write(path: str, mode: str = "w") -> Iterator[IO]:
    """Open a temp file in the destination directory; rename onto `path` on
    successful exit.  On exception the temp file is removed and nothing is
    committed (mirrors the reference: a crashed worker leaves no partial
    mr-X-Y / mr-out-Y file, mr/worker.go:81-92,126-148)."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    # The ".tmp-" prefix keeps uncommitted temp files out of the harness's
    # "mr-out*" merge glob if a worker dies (os._exit) mid-write.
    fd, tmp = tempfile.mkstemp(prefix=".tmp-" + os.path.basename(path) + ".", dir=d)
    # Text mode pins utf-8: output bytes must not depend on the host locale
    # (a worker under an ASCII locale would otherwise crash writing any
    # non-ASCII key, and mixed-locale fleets would diverge).
    f = os.fdopen(fd, mode, encoding=None if "b" in mode else "utf-8")
    try:
        yield f
        f.flush()
        os.fsync(f.fileno())
        f.close()
        os.rename(tmp, path)  # atomic commit
    except BaseException:
        try:
            f.close()
        finally:
            try:
                os.remove(tmp)
            except OSError:
                pass
        raise
