"""Durable per-node Raft state under the journal's record framing.

Each replica persists exactly what Raft §5 requires before a message
leaves the node: ``current_term`` + ``voted_for`` (a vote revealed and
then forgotten could elect two leaders in one term) and the log
entries themselves (an acknowledged append that evaporates breaks the
majority-commit arbitration the shard journal now rides on).

The file is append-only JSON lines with the ``mr/journal.py``
replicated-record framing (``rcrc`` CRC32 per record, torn tail
truncated on load) — three record kinds:

* ``{"kind": "term", "term": T, "voted": id-or-null}`` — last wins;
* ``{"kind": "entry", "index": i, "term": t, "data": ...}`` — must
  extend the log densely (``index == len+1``) or overwrite a truncated
  suffix previously cut by
* ``{"kind": "trunc", "from": i}`` — drop every entry ``>= i`` (the
  log-divergence repair a new leader forces on a stale follower).

A record that parses but does not FIT (gap in indexes, bad types) is
corruption, not a logical state: load() stops there and truncates, so
replay is always a clean prefix — the same contract the task journal's
property test pins (tests/test_journal_framing.py).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, TextIO, Tuple

from dsi_tpu.mr.journal import frame_record, unframe_record
from dsi_tpu.utils.atomicio import fsync_dir


class RaftStore:
    """Durable (term, voted_for, log) for one replica."""

    def __init__(self, path: str):
        self.path = path
        self._fh: Optional[TextIO] = None
        self._term = 0
        self._voted: Optional[int] = None
        self._entries: List[Dict[str, Any]] = []

    # ---- load ----

    def load(self) -> Tuple[int, Optional[int], List[Dict[str, Any]]]:
        """Replay the file (truncating at the first corrupt/torn
        record), open for appending, and return
        ``(term, voted_for, entries)``."""
        trunc_at: Optional[int] = None
        if os.path.exists(self.path):
            with open(self.path, "rb") as f:
                data = f.read()
            pos = 0
            while pos < len(data):
                nl = data.find(b"\n", pos)
                rec_start = pos
                if nl == -1:
                    trunc_at = rec_start
                    break
                line = data[rec_start:nl].strip()
                pos = nl + 1
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except (json.JSONDecodeError, UnicodeDecodeError):
                    trunc_at = rec_start
                    break
                if not isinstance(rec, dict):
                    trunc_at = rec_start
                    break
                rec = unframe_record(rec)
                if rec is None or not self._apply(rec):
                    trunc_at = rec_start
                    break
            if trunc_at is not None:
                # dsicheck: allow[raw-write] in-place truncation IS the
                # torn-tail repair, same as the task journal's open()
                with open(self.path, "rb+") as f:
                    f.truncate(trunc_at)
        # dsicheck: allow[raw-write] append-only raft log: per-record
        # fsync + parent-dir fsync below; rename cannot express appends
        self._fh = open(self.path, "a")
        fsync_dir(os.path.dirname(os.path.abspath(self.path)) or ".")
        return self._term, self._voted, list(self._entries)

    def _apply(self, rec: Dict[str, Any]) -> bool:
        """Fold one replayed record; False == structurally corrupt."""
        kind = rec.get("kind")
        if kind == "term":
            term, voted = rec.get("term"), rec.get("voted")
            if (not isinstance(term, int) or isinstance(term, bool)
                    or term < 0):
                return False
            if voted is not None and (not isinstance(voted, int)
                                      or isinstance(voted, bool)):
                return False
            self._term, self._voted = term, voted
            return True
        if kind == "trunc":
            frm = rec.get("from")
            if (not isinstance(frm, int) or isinstance(frm, bool)
                    or frm < 1):
                return False
            del self._entries[frm - 1:]
            return True
        if kind == "entry":
            idx, term = rec.get("index"), rec.get("term")
            if any(not isinstance(v, int) or isinstance(v, bool) or v < 0
                   for v in (idx, term)):
                return False
            if idx != len(self._entries) + 1:  # gaps are corruption
                return False
            self._entries.append({"term": term, "data": rec.get("data")})
            return True
        return False

    # ---- writes (RaftCore persistence hooks) ----

    def save_term(self, term: int, voted: Optional[int]) -> None:
        self._term, self._voted = term, voted
        self._write({"kind": "term", "term": int(term), "voted": voted})

    def append(self, start_index: int, entries) -> None:
        for k, e in enumerate(entries):
            self._write({"kind": "entry", "index": int(start_index + k),
                         "term": int(e["term"]), "data": e["data"]})

    def truncate(self, from_index: int) -> None:
        self._write({"kind": "trunc", "from": int(from_index)})

    def _write(self, rec: Dict[str, Any]) -> None:
        assert self._fh is not None, "RaftStore.load() before writes"
        self._fh.write(frame_record(rec) + "\n")
        self._fh.flush()
        os.fsync(self._fh.fileno())

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None
