"""Driver-side handle on a spawned coordinator-replica group.

``shardrun/mrrun/mrserve --replicas N`` use this to (1) write the group
spec and spawn N ``dsi_tpu.cli.replicad`` processes, (2) stand in for
the in-process coordinator the single-node drivers poll directly
(``done()/spec_stats()/final_outputs()`` ride ``Coordinator.*`` RPCs
through :func:`replica.client.group_call`), and (3) run the chaos the
differential harness and the bench row need: ``kill -9`` the CURRENT
leader and measure the failover wall — kill instant to the first
successful post-kill coordinator answer from the NEW leader.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional

from dsi_tpu.mr import rpc
from dsi_tpu.replica import client as rclient


class ReplicaGroup:
    """N ``replicad`` subprocesses plus the RPC plumbing to drive them.

    ``config`` is the JobConfig-kwarg subset every replica's leader
    coordinator is built with; it must be identical across replicas
    (it ships via the one shared spec file, so it is)."""

    def __init__(self, mode: str, workdir: str, *, replicas: int = 3,
                 files: Optional[List[str]] = None, n_reduce: int = 0,
                 n_shards: int = 0, knobs: Optional[dict] = None,
                 config: Optional[dict] = None,
                 spool: Optional[str] = None,
                 serve: Optional[dict] = None,
                 env: Optional[dict] = None,
                 election_timeout_s: Optional[tuple] = None,
                 heartbeat_s: Optional[float] = None):
        if replicas < 2:
            raise ValueError("a replica group needs >= 2 members "
                             "(3 for kill-tolerance)")
        self.workdir = os.path.abspath(workdir)
        os.makedirs(self.workdir, exist_ok=True)
        self.addrs = [os.path.join(self.workdir, f"replica-{i}.sock")
                      for i in range(replicas)]
        self.spec = ",".join(self.addrs)
        self.env = dict(env if env is not None else os.environ)
        spec_doc = {"mode": mode, "addrs": self.addrs,
                    "workdir": self.workdir}
        if mode in ("shard", "classic"):
            spec_doc.update({"files": list(files or []),
                             "n_reduce": int(n_reduce),
                             "n_shards": int(n_shards),
                             "knobs": dict(knobs or {}),
                             "config": dict(config or {})})
        else:
            spec_doc.update({"spool": spool, "serve": dict(serve or {})})
        if election_timeout_s is not None:
            spec_doc["election_timeout_s"] = list(election_timeout_s)
        if heartbeat_s is not None:
            spec_doc["heartbeat_s"] = heartbeat_s
        self.spec_path = os.path.join(self.workdir, "replica-spec.json")
        # dsicheck: allow[raw-write] process-spawn config, consumed
        # immediately by the children; not durable job state
        with open(self.spec_path, "w", encoding="utf-8") as f:
            json.dump(spec_doc, f, sort_keys=True, indent=1)
        self.procs: Dict[int, subprocess.Popen] = {}
        self.kills = 0
        self.respawns = 0
        for i in range(replicas):
            self.spawn(i)

    # ---- process control ----

    def spawn(self, i: int) -> None:
        cmd = [sys.executable, "-m", "dsi_tpu.cli.replicad",
               "--index", str(i), "--spec", self.spec_path]
        self.procs[i] = subprocess.Popen(cmd, env=self.env,
                                         cwd=self.workdir)

    def statuses(self, timeout: float = 2.0) -> Dict[str, dict]:
        return rclient.group_status(self.spec, timeout=timeout)

    def leader(self) -> Optional[dict]:
        """``{"index", "addr", "pid", "term", "app_ready"}`` of the
        replica that currently believes it leads, or None."""
        for addr, st in self.statuses().items():
            s = st.get("status") or {}
            if s.get("role") == "leader":
                return {"index": int(s.get("node", -1)), "addr": addr,
                        "pid": int(st.get("pid", 0)),
                        "term": int(s.get("term", 0)),
                        "app_ready": bool(st.get("app_ready"))}
        return None

    def wait_leader(self, timeout: float = 30.0, *,
                    app_ready: bool = True) -> dict:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            info = self.leader()
            if info is not None and (info["app_ready"]
                                     or not app_ready):
                return info
            time.sleep(0.05)
        raise rpc.CoordinatorGone(
            f"replica group {self.spec}: no leader within {timeout:.0f}s")

    def kill_leader(self, *, respawn: bool = True,
                    probe_method: str = "Coordinator.Stats",
                    probe_args: Optional[dict] = None,
                    timeout: float = 60.0) -> dict:
        """The differential-harness chaos move: SIGKILL the current
        leader and measure kill→served failover.  Returns
        ``{"killed_index", "old_term", "new_term", "new_index",
        "failover_s"}``.  ``respawn`` brings the killed replica back
        (as a follower that catches up from the new leader's log)."""
        info = self.wait_leader(timeout=timeout)
        os.kill(info["pid"], signal.SIGKILL)
        self.kills += 1
        t_kill = time.monotonic()
        try:
            self.procs[info["index"]].wait(timeout=10)
        except subprocess.TimeoutExpired:
            pass
        rclient.forget_leader(self.spec)
        ok, reply = rclient.group_call(self.spec, probe_method,
                                       probe_args or {},
                                       give_up_s=timeout)
        failover_s = time.monotonic() - t_kill
        if not ok:
            raise rpc.CoordinatorGone(
                f"post-kill probe failed: {reply!r}")
        new = self.wait_leader(timeout=timeout)
        if respawn:
            self.spawn(info["index"])
            self.respawns += 1
        return {"killed_index": info["index"],
                "old_term": info["term"],
                "new_term": new["term"], "new_index": new["index"],
                "failover_s": round(failover_s, 4)}

    def close(self) -> None:
        for p in self.procs.values():
            if p.poll() is None:
                p.terminate()
        for p in self.procs.values():
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()

    # ---- the in-process coordinator's driver surface, over RPC ----

    def _call(self, method: str, args: Optional[dict] = None,
              give_up_s: float = 30.0):
        return rclient.group_call(self.spec, method, args or {},
                                  give_up_s=give_up_s)

    def done(self) -> bool:
        try:
            ok, reply = self._call("Coordinator.Done", give_up_s=10.0)
        except rpc.CoordinatorGone:
            return False  # mid-election; the driver loop polls again
        return bool(ok and isinstance(reply, dict) and reply.get("done"))

    def spec_stats(self) -> dict:
        ok, reply = self._call("Coordinator.Stats")
        if not ok or not isinstance(reply, dict) or "stats" not in reply:
            raise rpc.CoordinatorGone(f"Coordinator.Stats: {reply!r}")
        return reply["stats"]

    def final_outputs(self) -> List[str]:
        ok, reply = self._call("Coordinator.Outputs")
        if not ok or not isinstance(reply, dict) \
                or "outputs" not in reply:
            raise rpc.CoordinatorGone(f"Coordinator.Outputs: {reply!r}")
        return list(reply["outputs"])

    # ---- the replication-audit surface (tests, CI smoke) ----

    def journal_paths(self) -> List[str]:
        return [os.path.join(self.workdir, f"replica-{i}.journal")
                for i in range(len(self.addrs))]
