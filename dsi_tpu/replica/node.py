"""The replica process harness: RaftCore + RPC transport + application.

One :class:`ReplicaNode` per coordinator process.  It pumps the
deterministic core (``replica/raft.py``) with real traffic over the
existing ``mr/rpc.py`` transport (same auth, same framing, same
``DSI_MR_SECRET``), applies committed log entries to the node's LOCAL
journal file, and hosts the application — the shard/classic
``Coordinator`` or the serve daemon — on the leader only.

The contract every piece of the failover story hangs off:

* **Appliers run on every replica**, leader or not: each committed
  entry lands in each node's own journal file (``replica-<i>.journal``)
  in log order, so the journal a follower replays on winning an
  election IS the task table the dead leader acked.
* **The application exists only on the leader**, and only once the
  node has applied up to its own election no-op — i.e. once its local
  journal provably contains every record any previous leader ever
  acked.  Application RPCs reaching a follower get the typed
  ``NotLeader{hint}`` redirect (``replica/client.py``).
* :meth:`propose_and_wait` is the exactly-once arbitration point: the
  coordinator's journal writes block here until the record is
  replicated to a MAJORITY and applied locally.  A leader cut off from
  the majority times out instead of acking — it cannot finalize a
  shard, which is precisely what keeps ``duplicate_commits == 0``
  across a partition (tests/test_raft.py pins the core property,
  tests/test_replica_group.py the end-to-end one).

Threads: one ticker (timers, apply, leadership transitions — the only
thread that touches the application lifecycle), one sender per peer
(latest-message slot: Raft state is cumulative, so a superseded
message is garbage, not loss), plus the RpcServer's handler threads
feeding ``on_message``.  All core state is guarded by ``self.mu``.
"""

from __future__ import annotations

import os
import random
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from dsi_tpu.mr import rpc
from dsi_tpu.mr.journal import Journal
from dsi_tpu.obs import get_registry, trace_event
from dsi_tpu.replica import client as rclient
from dsi_tpu.replica.raft import (APPEND, APPEND_RESP, LEADER, RaftCore,
                                  VOTE_REQ, VOTE_RESP)
from dsi_tpu.replica.rlog import RaftStore


class NotLeaderError(Exception):
    """Raised by propose on a non-leader; carries the redirect hint."""

    def __init__(self, hint: str = ""):
        super().__init__(f"not leader (hint={hint or '?'})")
        self.hint = hint


class ReplicationError(Exception):
    """A proposal that could not reach a majority (partition, lost
    leadership, group death).  The record was NOT acked — the caller's
    commit is not final and must not be reported as such."""


#: Election timeouts for real process groups (seconds).  Wide enough
#: that one scheduling hiccup doesn't trigger spurious elections on a
#: loaded CI box, tight enough that failover lands well under the
#: shard watchdog's presumed-dead window.
ELECTION_TIMEOUT_S = (0.4, 0.9)
HEARTBEAT_S = 0.1
TICK_S = 0.02

_RAFT_METHOD = {VOTE_REQ: "Raft.RequestVote", VOTE_RESP: "Raft.RequestVote",
                APPEND: "Raft.AppendEntries",
                APPEND_RESP: "Raft.AppendEntries"}


class ReplicaNode:
    """One replica of the coordinator group (see module docstring).

    ``applier(index, data)`` is called for every committed entry in
    log order (Raft no-ops included) on whichever thread advances the
    commit — always serialized, never concurrently.

    ``app_factory() -> (app, {rpc_name: handler})`` builds the
    leader-side application once leadership is stable;
    ``app.close()`` tears it down on loss.  ``app_methods`` names the
    RPC surface to register up front (followers must answer those
    methods with redirects before any app exists anywhere).
    """

    def __init__(self, index: int, addrs: List[str], store_path: str, *,
                 applier: Callable[[int, Any], None],
                 app_factory: Optional[Callable[[], Tuple[Any, Dict]]] = None,
                 app_methods: Tuple[str, ...] = (),
                 secret: Optional[str] = None,
                 clock: Callable[[], float] = time.monotonic,
                 rng=None,
                 election_timeout_s: Tuple[float, float] = ELECTION_TIMEOUT_S,
                 heartbeat_s: float = HEARTBEAT_S):
        self.index = index
        self.addrs = list(addrs)
        self.clock = clock
        self.applier = applier
        self.app_factory = app_factory
        self.secret = secret
        self.mu = threading.Lock()
        self._applied_cv = threading.Condition(self.mu)
        self.store = RaftStore(store_path)
        self.core = RaftCore(
            index, len(addrs),
            rng=rng if rng is not None else random.Random(
                os.getpid() * 1000003 + index),
            now=clock(), store=self.store,
            election_timeout_s=election_timeout_s,
            heartbeat_s=heartbeat_s)
        self.applied_index = 0
        self._app: Any = None
        self._app_methods: Optional[Dict[str, Callable]] = None
        self._lead_barrier: Optional[int] = None
        self._role_seen = self.core.role
        self._term_seen = self.core.current_term
        self._failovers = 0
        self._closing = False

        methods: Dict[str, Callable] = {
            "Raft.RequestVote": self._rpc_raft,
            "Raft.AppendEntries": self._rpc_raft,
            "Replica.Status": self._rpc_status,
        }
        for name in app_methods:
            methods[name] = (lambda args, _n=name:
                             self._app_call(_n, args))
        self._server = rpc.RpcServer(addrs[index], methods, secret=secret)

        # Per-peer latest-message slots + sender threads.
        self._slots: Dict[int, Optional[dict]] = {}
        self._slot_cv = threading.Condition()
        self._senders = []
        for p in range(len(addrs)):
            if p == index:
                continue
            self._slots[p] = None
            t = threading.Thread(target=self._sender, args=(p,),
                                 name=f"dsi-replica-send-{p}",
                                 daemon=True)
            self._senders.append(t)
        self._ticker = threading.Thread(target=self._tick_loop,
                                        name="dsi-replica-tick",
                                        daemon=True)

    # ---- lifecycle ----

    def start(self) -> "ReplicaNode":
        self._server.start()
        for t in self._senders:
            t.start()
        self._ticker.start()
        return self

    def close(self) -> None:
        with self.mu:
            self._closing = True
            self._applied_cv.notify_all()
        with self._slot_cv:
            self._slot_cv.notify_all()
        self._ticker.join(timeout=5.0)
        self._server.close()
        with self.mu:
            app, self._app, self._app_methods = self._app, None, None
        if app is not None:  # closed outside mu: app teardown joins
            app.close()      # threads that may still take RPCs
        self.store.close()

    @property
    def address(self) -> str:
        return self._server.address

    def app(self):
        """The live leader application, or None (driver convenience)."""
        return self._app

    # ---- RPC handlers ----

    def _rpc_raft(self, args: dict) -> dict:
        with self.mu:
            out = self.core.on_message(args, self.clock())
        frm = args.get("from")
        back = [m for m in out if m.get("to") == frm]
        rest = [m for m in out if m.get("to") != frm]
        if rest:
            self._post(rest)
        return {"msgs": back}

    def _rpc_status(self, args: dict) -> dict:
        with self.mu:
            st = self.core.status()
            st["applied_index"] = self.applied_index
            st["failovers"] = self._failovers
            app_ready = self._app is not None
        return {"status": st, "pid": os.getpid(), "addr": self.address,
                "app_ready": app_ready}

    def _leader_hint_locked(self) -> str:
        lid = self.core.leader_id
        if lid is None or not 0 <= lid < len(self.addrs):
            return ""
        return self.addrs[lid]

    def _app_call(self, name: str, args: dict) -> dict:
        with self.mu:
            app_methods = self._app_methods
            is_leader = self.core.is_leader()
            hint = self._leader_hint_locked()
        if app_methods is None:
            if is_leader:
                return {"error": "leader is replaying the log",
                        "error_type": rclient.RETRY}
            return {"error": "not leader", "error_type": rclient.NOT_LEADER,
                    "hint": hint}
        fn = app_methods.get(name)
        if fn is None:
            return {"error": f"no such app method {name!r}"}
        try:
            return fn(args)
        except NotLeaderError as e:
            return {"error": str(e), "error_type": rclient.NOT_LEADER,
                    "hint": e.hint}
        except ReplicationError as e:
            # The commit did not finalize; the worker retries and the
            # (possibly new) leader re-arbitrates.
            return {"error": f"replication stalled: {e}",
                    "error_type": rclient.RETRY}

    # ---- proposals (the ReplicatedJournal hook) ----

    def propose_and_wait(self, data: Any, timeout: float = 15.0) -> int:
        """Append ``data`` to the replicated log; block until it is
        majority-committed AND applied locally.  Returns the log index.
        Raises :class:`NotLeaderError` / :class:`ReplicationError`."""
        with self.mu:
            now = self.clock()
            idx, msgs = self.core.propose(data, now)
            if idx is None:
                raise NotLeaderError(self._leader_hint_locked())
            term = self.core.current_term
        self._post(msgs)
        deadline = self.clock() + timeout
        with self._applied_cv:
            while self.applied_index < idx:
                if self._closing:
                    raise ReplicationError("node closing")
                if (self.core.current_term != term
                        or not self.core.is_leader()):
                    raise NotLeaderError(self._leader_hint_locked())
                left = deadline - self.clock()
                if left <= 0:
                    raise ReplicationError(
                        f"no majority within {timeout:.0f}s "
                        f"(entry {idx}, term {term})")
                self._applied_cv.wait(min(left, 0.05))
            # Committed — but OUR entry, not a same-index survivor of a
            # truncation race (impossible while we stayed leader in
            # ``term``; belt and braces against future edits).
            if self.core._term_at(idx) != term:
                raise ReplicationError(
                    f"entry {idx} superseded (term {term} -> "
                    f"{self.core._term_at(idx)})")
        return idx

    # ---- ticker: timers, apply, leadership ----

    def _tick_loop(self) -> None:
        while True:
            with self.mu:
                if self._closing:
                    return
                now = self.clock()
                msgs = self.core.tick(now)
                committed = self.core.take_committed()
                for idx, data in committed:
                    # The applier is journal appends + spool writes —
                    # holding mu serializes it with propose/apply
                    # waiters, which is exactly the ordering we want.
                    self.applier(idx, data)
                    self.applied_index = idx
                if committed:
                    self._applied_cv.notify_all()
                role = self.core.role
                term = self.core.current_term
                barrier_ok = (self._lead_barrier is not None
                              and self.applied_index >= self._lead_barrier)
            self._post(msgs)
            self._leadership(role, term, barrier_ok)
            time.sleep(TICK_S)

    def _leadership(self, role: str, term: int, barrier_ok: bool) -> None:
        """Application lifecycle — ticker thread only."""
        if term != self._term_seen:
            trace_event("replica.term", lane="replica", node=self.index,
                        term=term, role=role)
            get_registry().set_gauge("dsi_replica_term", term)
            self._term_seen = term
        if role != self._role_seen:
            if role == LEADER:
                with self.mu:
                    self._lead_barrier = self.core.last_index()
                self._failovers += 1
                trace_event("replica.elected", lane="replica",
                            node=self.index, term=term,
                            barrier=self._lead_barrier)
                get_registry().set_gauge("dsi_replica_elections",
                                         self.core.elections_won)
                print(f"replica {self.index}: elected leader "
                      f"(term {term})", file=sys.stderr)
            elif self._role_seen == LEADER:
                trace_event("replica.stepdown", lane="replica",
                            node=self.index, term=term)
                print(f"replica {self.index}: stepped down "
                      f"(term {term})", file=sys.stderr)
            self._role_seen = role
        if role != LEADER and self._app is not None:
            app = self._app
            with self.mu:
                self._app = None
                self._app_methods = None
                self._lead_barrier = None
            app.close()
            trace_event("replica.app_down", lane="replica",
                        node=self.index, term=term)
        elif (role == LEADER and self._app is None
                and self.app_factory is not None and barrier_ok):
            t0 = self.clock()
            app, methods = self.app_factory()
            with self.mu:
                if self.core.is_leader():
                    self._app, self._app_methods = app, methods
                    app = None
            if app is not None:  # lost leadership mid-build
                app.close()
            else:
                trace_event("replica.app_up", lane="replica",
                            node=self.index, term=term,
                            build_s=round(self.clock() - t0, 4),
                            applied=self.applied_index)
                get_registry().set_gauge("dsi_replica_applied_index",
                                         self.applied_index)

    # ---- outbound raft traffic ----

    def _post(self, msgs: List[dict]) -> None:
        if not msgs:
            return
        with self._slot_cv:
            for m in msgs:
                to = int(m["to"])
                if to in self._slots:
                    self._slots[to] = m  # latest message supersedes
            self._slot_cv.notify_all()

    def _sender(self, peer: int) -> None:
        while True:
            with self._slot_cv:
                while self._slots.get(peer) is None and not self._closing:
                    self._slot_cv.wait(0.5)
                if self._closing:
                    return
                msg = self._slots[peer]
                self._slots[peer] = None
            try:
                ok, reply = rpc.call(self.addrs[peer],
                                     _RAFT_METHOD[msg["type"]], msg,
                                     timeout=2.0, secret=self.secret)
            except rpc.CoordinatorGone:
                continue  # dead peer; the next timer regenerates state
            if not ok or not isinstance(reply, dict):
                continue
            for m in reply.get("msgs") or []:
                with self.mu:
                    out = self.core.on_message(m, self.clock())
                self._post(out)


class ReplicatedJournal(Journal):
    """The leader coordinator's journal whose writes are replicated log
    proposals.  Same record surface as :class:`Journal` — every
    ``record*`` call funnels through ``_write`` — but a record is
    durable (and the call returns) only once a MAJORITY of replicas
    committed it and this node applied it to its local journal file
    (the applier owns the actual file handle; this class never writes
    bytes itself).  ``replay()`` is inherited and reads that same local
    file, which is how a follower-turned-leader reconstructs the exact
    task table."""

    def __init__(self, path: str, files: List[str], n_reduce: int,
                 n_shards: int, propose: Callable[[Any], int]):
        super().__init__(path, files, n_reduce, n_shards=n_shards)
        self._propose = propose

    def open(self) -> None:
        # The applier created the file + header before any leadership;
        # arm the record*() gate with a non-file sentinel — _write is
        # overridden, so nothing ever treats it as a handle.
        self._fh = self  # type: ignore[assignment]

    def _write(self, rec: dict) -> None:
        if rec.get("kind") == "header":
            return  # the applier journal owns the header
        self._propose({"j": rec})

    def close(self) -> None:
        self._fh = None


class JournalApplier:
    """Committed-entry applier for coordinator groups: every replica
    appends each arbitrated journal record to its OWN journal file,
    deduplicating on record identity so a restart (which re-delivers
    the whole committed log) or a crash between append and ack never
    yields a double record — ``duplicate_commits`` stays structurally
    0 in every replica's journal, not just the leader's."""

    def __init__(self, path: str, files: List[str], n_reduce: int,
                 n_shards: int):
        self.journal = Journal(path, files, n_reduce, n_shards=n_shards)
        maps, reduces = self.journal.replay()
        self.seen = {("map", t) for t in maps}
        self.seen.update(("reduce", t) for t in reduces)
        self.seen.update(("shard", s) for s in self.journal.shard_commits)
        self.seen.update(("resplit", s) for s in self.journal.resplits)
        self.seen.update(("subshard", s, k)
                         for s, k in self.journal.subshard_commits)
        self.journal.open()

    @staticmethod
    def _key(rec: dict):
        kind = rec.get("kind")
        if kind == "subshard":
            return (kind, rec.get("task"), rec.get("sub"))
        return (kind, rec.get("task"))

    def __call__(self, index: int, data: Any) -> None:
        if not isinstance(data, dict):
            return
        rec = data.get("j")
        if not isinstance(rec, dict):
            return  # raft no-op or a foreign entry kind
        key = self._key(rec)
        if key in self.seen:
            return
        self.seen.add(key)
        self.journal.append_replicated(rec)

    def close(self) -> None:
        self.journal.close()


class AdmissionApplier:
    """Committed-entry applier for serve groups: an ``admit`` entry
    materializes the accepted job's spool record on every replica, so
    the daemon a new leader boots (``ServeDaemon._load_journal``)
    re-queues every job any previous leader ever acked."""

    def __init__(self, spool: str):
        self.jobs_dir = os.path.join(os.path.abspath(spool), "jobs")
        os.makedirs(self.jobs_dir, exist_ok=True)

    def __call__(self, index: int, data: Any) -> None:
        if not isinstance(data, dict):
            return
        job = data.get("admit")
        if not isinstance(job, dict) or not job.get("job_id"):
            return
        import json

        from dsi_tpu.utils.atomicio import write_bytes_durable

        path = os.path.join(self.jobs_dir, f"{job['job_id']}.json")
        if os.path.exists(path):
            return  # the leader's own _persist (or a replay) beat us
        write_bytes_durable(
            path, json.dumps(job, sort_keys=True).encode("utf-8"))

    def close(self) -> None:
        pass
