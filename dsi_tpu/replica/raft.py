"""The Raft election/replication state machine — deterministic core.

This is the 6.5840-Lab-2 shape: ONE single-threaded state machine per
node, driven entirely from outside by ``tick(now)`` (timers) and
``on_message(msg, now)`` (peer traffic), both returning the outbound
messages to deliver.  No sockets, no threads, no wall clock, no jax —
the election timeout is drawn from an INJECTED rng and every time
comparison uses the caller's ``now``, so a unit test can play out a
split vote, a partition, or a log-divergence healing byte-for-byte
reproducibly (tests/test_raft.py).  The process harness that pumps
real RPC traffic through this core lives in :mod:`replica.node`.

Safety properties this module owns (Raft §5, the ones the failover
harness leans on):

* **Election safety** — one leader per term: a vote is granted at most
  once per term (``voted_for`` is persisted BEFORE the grant leaves).
* **Leader completeness** — a candidate whose log is behind (last term,
  then last index) is refused, so a winner holds every committed entry.
* **Commit = majority replication, current term only** (§5.4.2): the
  leader advances ``commit_index`` only over entries of ITS OWN term
  replicated on a majority.  This is exactly why a partitioned old
  leader can never finalize a shard commit: its appends cannot reach a
  majority, and the new leader's first no-op entry commits the log the
  majority agreed on.
* **Log matching** — a follower truncates its log at the first entry
  conflicting with the leader's and never rewrites a committed prefix.

Entries are ``{"term": int, "data": <json>}``; the log is 1-indexed
(index 0 is the empty sentinel).  Durability is delegated to an
optional ``store`` (``rlog.RaftStore``): ``save_term`` before any
message that reveals a vote or term bump, ``append``/``truncate``
before an append-entries reply acknowledges the entries.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

FOLLOWER = "follower"
CANDIDATE = "candidate"
LEADER = "leader"

# Message types on the wire (replica/node.py maps these onto
# ``Raft.RequestVote`` / ``Raft.AppendEntries`` RPC methods).
VOTE_REQ = "vote_req"
VOTE_RESP = "vote_resp"
APPEND = "append"
APPEND_RESP = "append_resp"

#: The entry a fresh leader appends immediately on winning: committing
#: it (its own term) is the §5.4.2-safe way to also commit every older
#: inherited entry — without it, a failover with no new client traffic
#: would leave the dead leader's tail uncommitted forever.
NOOP = {"kind": "raft_noop"}


class RaftCore:
    """One node's Raft state machine (see module docstring).

    ``rng`` needs only ``uniform(a, b)`` (``random.Random`` works);
    ``store`` (optional) persists term/vote and the log.  All state
    lives on the instance; the caller serializes access (the node
    harness holds one lock, tests are single-threaded).
    """

    def __init__(self, node_id: int, n_nodes: int, *,
                 rng, now: float = 0.0,
                 election_timeout_s: Tuple[float, float] = (0.15, 0.30),
                 heartbeat_s: float = 0.05,
                 store=None):
        if not 0 <= node_id < n_nodes:
            raise ValueError(f"node_id {node_id} out of group "
                             f"0..{n_nodes - 1}")
        self.node_id = node_id
        self.n_nodes = n_nodes
        self.peers = [i for i in range(n_nodes) if i != node_id]
        self.rng = rng
        self.election_timeout_s = election_timeout_s
        self.heartbeat_s = heartbeat_s
        self.store = store

        self.role = FOLLOWER
        self.current_term = 0
        self.voted_for: Optional[int] = None
        #: entries[i] is log index i+1.
        self.log: List[Dict[str, Any]] = []
        self.commit_index = 0
        #: Highest index already handed to :meth:`take_committed`.
        self.delivered_index = 0
        #: The node we last heard a valid append from this term — the
        #: redirect hint followers serve to lost workers.
        self.leader_id: Optional[int] = None

        # Leader volatile state.
        self.next_index: Dict[int, int] = {}
        self.match_index: Dict[int, int] = {}
        self._votes: set = set()

        # Counters the obs/replica lane and Replica.Status export.
        self.elections_started = 0
        self.elections_won = 0
        self.stepdowns = 0

        if store is not None:
            term, voted, entries = store.load()
            self.current_term = term
            self.voted_for = voted
            self.log = list(entries)

        self._election_due = now + self._timeout()
        self._hb_due = now

    # ---- small helpers ----

    def _timeout(self) -> float:
        lo, hi = self.election_timeout_s
        return self.rng.uniform(lo, hi)

    def _majority(self) -> int:
        return self.n_nodes // 2 + 1

    def last_index(self) -> int:
        return len(self.log)

    def _term_at(self, index: int) -> int:
        if index <= 0 or index > len(self.log):
            return 0
        return int(self.log[index - 1]["term"])

    def _persist_term(self) -> None:
        if self.store is not None:
            self.store.save_term(self.current_term, self.voted_for)

    def _msg(self, mtype: str, to: int, **fields) -> Dict[str, Any]:
        m = {"type": mtype, "from": self.node_id, "to": to,
             "term": self.current_term}
        m.update(fields)
        return m

    def is_leader(self) -> bool:
        return self.role == LEADER

    def status(self) -> Dict[str, Any]:
        """The ``Replica.Status`` surface (any replica answers it)."""
        return {"node": self.node_id, "role": self.role,
                "term": self.current_term,
                "leader": self.leader_id,
                "last_index": self.last_index(),
                "commit_index": self.commit_index,
                "elections_started": self.elections_started,
                "elections_won": self.elections_won,
                "stepdowns": self.stepdowns}

    # ---- timers ----

    def tick(self, now: float) -> List[Dict[str, Any]]:
        """Advance timers; returns messages to send."""
        if self.role == LEADER:
            if now >= self._hb_due:
                self._hb_due = now + self.heartbeat_s
                return self._appends_for_all()
            return []
        if now >= self._election_due:
            return self._start_election(now)
        return []

    def _start_election(self, now: float) -> List[Dict[str, Any]]:
        self.role = CANDIDATE
        self.current_term += 1
        self.voted_for = self.node_id
        self.leader_id = None
        self._persist_term()
        self._votes = {self.node_id}
        self.elections_started += 1
        self._election_due = now + self._timeout()
        if self._majority() == 1:  # single-node group
            return self._become_leader(now)
        li = self.last_index()
        return [self._msg(VOTE_REQ, p, last_log_index=li,
                          last_log_term=self._term_at(li))
                for p in self.peers]

    def _become_leader(self, now: float) -> List[Dict[str, Any]]:
        self.role = LEADER
        self.leader_id = self.node_id
        self.elections_won += 1
        nxt = self.last_index() + 1
        self.next_index = {p: nxt for p in self.peers}
        self.match_index = {p: 0 for p in self.peers}
        # The no-op that makes the inherited tail committable (§5.4.2).
        self._append_local({"term": self.current_term, "data": dict(NOOP)})
        self._maybe_advance_commit()
        self._hb_due = now + self.heartbeat_s
        return self._appends_for_all()

    # ---- client proposals (leader only) ----

    def propose(self, data: Any,
                now: float) -> Tuple[Optional[int], List[Dict[str, Any]]]:
        """Append ``data`` to the leader's log; returns ``(index,
        immediate replication traffic)``, or ``(None, [])`` when this
        node is not the leader (the caller redirects)."""
        if self.role != LEADER:
            return None, []
        self._append_local({"term": self.current_term, "data": data})
        self._maybe_advance_commit()  # 1-node group commits instantly
        self._hb_due = now + self.heartbeat_s
        return self.last_index(), self._appends_for_all()

    def _append_local(self, entry: Dict[str, Any]) -> None:
        self.log.append(entry)
        if self.store is not None:
            self.store.append(self.last_index(), [entry])

    def _appends_for_all(self) -> List[Dict[str, Any]]:
        return [self._append_for(p) for p in self.peers]

    def _append_for(self, peer: int) -> Dict[str, Any]:
        nxt = self.next_index[peer]
        prev = nxt - 1
        entries = self.log[prev:]
        return self._msg(APPEND, peer, prev_index=prev,
                         prev_term=self._term_at(prev),
                         entries=list(entries),
                         commit=self.commit_index)

    # ---- message handling ----

    def on_message(self, msg: Dict[str, Any],
                   now: float) -> List[Dict[str, Any]]:
        """Feed one peer message in; returns messages to send."""
        term = int(msg.get("term", 0))
        if term > self.current_term:
            # §5.1: any newer term demotes us on the spot.
            if self.role != FOLLOWER:
                self.stepdowns += 1
            self.role = FOLLOWER
            self.current_term = term
            self.voted_for = None
            self.leader_id = None
            self._persist_term()
        mtype = msg.get("type")
        if mtype == VOTE_REQ:
            return self._on_vote_req(msg, now)
        if mtype == VOTE_RESP:
            return self._on_vote_resp(msg, now)
        if mtype == APPEND:
            return self._on_append(msg, now)
        if mtype == APPEND_RESP:
            return self._on_append_resp(msg)
        return []

    def _on_vote_req(self, msg: Dict[str, Any],
                     now: float) -> List[Dict[str, Any]]:
        frm = int(msg["from"])
        term = int(msg["term"])
        if term < self.current_term:
            # Stale-term candidate: refuse, teach it the current term.
            return [self._msg(VOTE_RESP, frm, granted=False)]
        li, lt = self.last_index(), self._term_at(self.last_index())
        cand_lt = int(msg.get("last_log_term", 0))
        cand_li = int(msg.get("last_log_index", 0))
        up_to_date = (cand_lt, cand_li) >= (lt, li)
        if self.voted_for in (None, frm) and up_to_date:
            self.voted_for = frm
            self._persist_term()  # the vote must be durable before it leaves
            self._election_due = now + self._timeout()
            return [self._msg(VOTE_RESP, frm, granted=True)]
        return [self._msg(VOTE_RESP, frm, granted=False)]

    def _on_vote_resp(self, msg: Dict[str, Any],
                      now: float) -> List[Dict[str, Any]]:
        if (self.role != CANDIDATE
                or int(msg["term"]) != self.current_term
                or not msg.get("granted")):
            return []
        self._votes.add(int(msg["from"]))
        if len(self._votes) >= self._majority():
            return self._become_leader(now)
        return []

    def _on_append(self, msg: Dict[str, Any],
                   now: float) -> List[Dict[str, Any]]:
        frm = int(msg["from"])
        term = int(msg["term"])
        if term < self.current_term:
            return [self._msg(APPEND_RESP, frm, ok=False,
                              hint=self.last_index() + 1)]
        # A valid leader for our term: (re)settle into follower.
        if self.role != FOLLOWER:
            self.stepdowns += 1
            self.role = FOLLOWER
        self.leader_id = frm
        self._election_due = now + self._timeout()
        prev = int(msg["prev_index"])
        if prev > self.last_index():
            # We are missing the predecessor entirely: hint our end so
            # the leader skips the one-at-a-time walk.
            return [self._msg(APPEND_RESP, frm, ok=False,
                              hint=self.last_index() + 1)]
        if prev >= 1 and self._term_at(prev) != int(msg["prev_term"]):
            # Conflicting predecessor: hint the FIRST index of the
            # conflicting term (§5.3's fast backoff).
            bad_term = self._term_at(prev)
            first = prev
            while first > 1 and self._term_at(first - 1) == bad_term:
                first -= 1
            return [self._msg(APPEND_RESP, frm, ok=False, hint=first)]
        entries = list(msg.get("entries") or [])
        idx = prev
        for k, entry in enumerate(entries):
            idx = prev + 1 + k
            if idx <= self.last_index():
                if self._term_at(idx) == int(entry["term"]):
                    continue  # already have it (duplicate append)
                # Divergence: drop OUR uncommitted suffix, take theirs.
                assert idx > self.commit_index, \
                    "leader tried to rewrite a committed entry"
                del self.log[idx - 1:]
                if self.store is not None:
                    self.store.truncate(idx)
            self.log.append(dict(entry))
            if self.store is not None:
                self.store.append(idx, [entry])
        match = prev + len(entries)
        leader_commit = int(msg.get("commit", 0))
        if leader_commit > self.commit_index:
            self.commit_index = min(leader_commit, match,
                                    self.last_index())
        return [self._msg(APPEND_RESP, frm, ok=True, match=match)]

    def _on_append_resp(self, msg: Dict[str, Any]) -> List[Dict[str, Any]]:
        if self.role != LEADER or int(msg["term"]) != self.current_term:
            return []
        frm = int(msg["from"])
        if msg.get("ok"):
            match = int(msg.get("match", 0))
            if match > self.match_index.get(frm, 0):
                self.match_index[frm] = match
            self.next_index[frm] = max(self.next_index.get(frm, 1),
                                       match + 1)
            self._maybe_advance_commit()
            if self.next_index[frm] <= self.last_index():
                return [self._append_for(frm)]  # more to stream
            return []
        # Rejected: jump back to the follower's hint and retry now.
        hint = int(msg.get("hint", 0)) or (self.next_index.get(frm, 2) - 1)
        self.next_index[frm] = max(1, min(hint, self.last_index() + 1))
        return [self._append_for(frm)]

    def _maybe_advance_commit(self) -> None:
        """§5.4.2: commit the highest index of OUR term a majority
        holds (self counts).  Never moves backwards."""
        for n in range(self.last_index(), self.commit_index, -1):
            if self._term_at(n) != self.current_term:
                break  # older-term entries commit only via a newer one
            held = 1 + sum(1 for p in self.peers
                           if self.match_index.get(p, 0) >= n)
            if held >= self._majority():
                self.commit_index = n
                break

    # ---- committed-entry delivery ----

    def take_committed(self) -> List[Tuple[int, Any]]:
        """Newly committed ``(index, data)`` pairs since the last call
        — the apply stream (exactly once, in order, no-ops included so
        the applier can track the applied index densely)."""
        out = []
        while self.delivered_index < self.commit_index:
            self.delivered_index += 1
            out.append((self.delivered_index,
                        self.log[self.delivered_index - 1]["data"]))
        return out
