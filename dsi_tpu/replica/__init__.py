"""Replicated control plane (ISSUE 20).

A 3-process Raft-style coordinator group: leader election and log
replication over the existing ``mr/rpc.py`` transport, with the
replicated log subsuming the ``mr/journal.py`` commit records so a
follower that wins an election replays to the exact task table the
dead leader had.  Commit arbitration moves INSIDE the replicated log —
a record is final only once a majority holds it, so two leaders across
a partition can never both finalize a shard.

Layering (each importable on a bare interpreter, no jax):

* :mod:`dsi_tpu.replica.raft` — the deterministic election/replication
  state machine (injectable clock + rng, message dicts in / message
  dicts out; unit-tested like 6.5840 Lab 2);
* :mod:`dsi_tpu.replica.rlog` — the durable per-node Raft state
  (term/vote + log entries) under the journal's CRC record framing;
* :mod:`dsi_tpu.replica.node` — the process harness: RPC transport,
  tick thread, leader-side application hosting (shard/classic
  coordinator or serve admission), committed-entry application into
  the local journal;
* :mod:`dsi_tpu.replica.client` — leader discovery for workers and
  drivers (dial the group, follow ``NotLeader{hint}`` redirects).
"""

from dsi_tpu.replica.raft import (CANDIDATE, FOLLOWER, LEADER,  # noqa: F401
                                  RaftCore)
