"""Leader discovery for workers and drivers dialing a replica group.

The wire convention (replica/node.py): an application RPC sent to a
FOLLOWER is answered with a typed redirect instead of being served —

    {"error": "...", "error_type": "not_leader", "hint": "<addr or ''>"}

and a freshly elected leader whose coordinator is still replaying the
log answers ``{"error_type": "retry"}``.  :func:`group_call` hides
both: give it a comma-separated address list (the ``DSI_MR_SOCKET``
a ``--replicas`` driver exports) and it dials the cached leader first,
follows redirect hints, rotates through the group on dead sockets, and
only raises :class:`rpc.CoordinatorGone` once the WHOLE group stayed
unreachable past the failover budget — a single dead coordinator used
to be job-over; a dead leader is now just an election away.

With a single address (no comma) this is a plain ``rpc.call``
passthrough, so the worker loops run one code path in both modes.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from dsi_tpu.mr import rpc

#: How long a caller keeps cycling a group that answers nothing before
#: concluding the GROUP is gone.  Covers several election timeouts plus
#: leader app rebuild (journal replay) with margin.
GROUP_GIVE_UP_S = 30.0

#: error_type values on the redirect protocol (single-sourced here;
#: node.py imports them).
NOT_LEADER = "not_leader"
RETRY = "retry"

# Last-known leader per address-list (workers dial per-call, so the
# cache is what turns N redirects into one).
_mu = threading.Lock()
_leader_cache: Dict[str, str] = {}


def split_group(spec: str):
    """``"a,b,c"`` -> ["a", "b", "c"] (single address -> [it])."""
    return [a for a in (spec or "").split(",") if a]


def forget_leader(spec: str) -> None:
    with _mu:
        _leader_cache.pop(spec, None)


def group_call(spec: str, method: str, args: dict | None = None,
               timeout: float = 60.0, give_up_s: float = GROUP_GIVE_UP_S,
               sleep=time.sleep, clock=time.monotonic):
    """``rpc.call`` against a replica group (see module docstring).

    Returns the served ``(ok, reply)``; raises ``rpc.CoordinatorGone``
    when no replica serves within ``give_up_s``.  ``sleep``/``clock``
    are injectable for tests.
    """
    addrs = split_group(spec)
    if len(addrs) <= 1:
        return rpc.call(spec, method, args, timeout=timeout)
    deadline = clock() + give_up_s
    rr = 0  # round-robin cursor for leaderless probing
    last_err: Optional[Exception] = None
    while True:
        with _mu:
            leader = _leader_cache.get(spec)
        addr = leader if leader else addrs[rr % len(addrs)]
        try:
            ok, reply = rpc.call(addr, method, args, timeout=timeout)
        except rpc.AuthError:
            raise  # wrong secret never self-heals; stay loud
        except rpc.CoordinatorGone as e:
            last_err = e
            if leader == addr:
                forget_leader(spec)
            else:
                rr += 1
            if clock() >= deadline:
                raise rpc.CoordinatorGone(
                    f"replica group {spec}: no reachable leader within "
                    f"{give_up_s:.0f}s (last: {last_err})") from e
            sleep(0.05)
            continue
        etype = reply.get("error_type") if isinstance(reply, dict) else None
        if etype == NOT_LEADER:
            hint = str(reply.get("hint") or "")
            with _mu:
                if hint and hint != addr:
                    _leader_cache[spec] = hint
                else:
                    _leader_cache.pop(spec, None)
            if not hint or hint == addr:
                rr += 1
            if clock() >= deadline:
                raise rpc.CoordinatorGone(
                    f"replica group {spec}: no leader emerged within "
                    f"{give_up_s:.0f}s")
            sleep(0.02 if hint else 0.05)
            continue
        if etype == RETRY:
            # A real leader, app still replaying the log: short wait.
            with _mu:
                _leader_cache[spec] = addr
            if clock() >= deadline:
                raise rpc.CoordinatorGone(
                    f"replica group {spec}: leader stuck replaying "
                    f"({reply.get('error')})")
            sleep(0.05)
            continue
        with _mu:
            _leader_cache[spec] = addr
        return ok, reply


def group_status(spec: str, timeout: float = 2.0):
    """``Replica.Status`` from every reachable replica — the driver's
    leader-finding/kill-9 surface: ``{addr: status-dict}``."""
    out = {}
    for addr in split_group(spec):
        try:
            ok, reply = rpc.call(addr, "Replica.Status", {},
                                 timeout=timeout)
        except rpc.CoordinatorGone:
            continue
        if ok and isinstance(reply, dict) and "status" in reply:
            out[addr] = reply
    return out
