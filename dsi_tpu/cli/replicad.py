"""One coordinator-group replica process (``python -m
dsi_tpu.cli.replicad --index I --spec spec.json``).

``mrrun/shardrun/mrserve --replicas N`` spawn N of these.  Each hosts
a :class:`dsi_tpu.replica.node.ReplicaNode` — the deterministic Raft
core pumped over the real ``mr/rpc.py`` transport — plus the
mode-specific applier and leader application:

* ``shard`` / ``classic`` — a :class:`JournalApplier` appends every
  majority-committed journal record to this replica's OWN
  ``replica-<i>.journal``; the elected leader builds a ``Coordinator``
  whose injected :class:`ReplicatedJournal` turns each ``record*``
  call into a propose-and-wait.  The coordinator is built WITHOUT its
  own socket: its RPC surface is registered on the replica node, so
  followers answer every coordinator method with the typed
  ``NotLeader{hint}`` redirect.
* ``serve`` — an :class:`AdmissionApplier` materializes accepted jobs
  into the shared spool on every replica; the leader boots the
  ``ServeDaemon`` whose ``admit_hook`` proposes each admission before
  it is persisted or acked (and whose ``_load_journal`` re-queues
  everything earlier leaders accepted).

The spec file carries everything three replicas must agree on (input
files, shard plan inputs, knobs) so the group is started with three
identical commands differing only in ``--index``.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time


def _load_spec(path: str) -> dict:
    with open(path, encoding="utf-8") as f:
        spec = json.load(f)
    if not isinstance(spec, dict):
        raise SystemExit(f"replicad: malformed spec {path!r}")
    for key in ("mode", "addrs", "workdir"):
        if key not in spec:
            raise SystemExit(f"replicad: spec missing {key!r}")
    return spec


def _coordinator_factory(spec: dict, node, journal_path: str):
    """``app_factory`` for shard/classic mode: a Coordinator over the
    replicated journal, its wire methods keyed exactly as
    ``Coordinator.serve()`` registers them (plus the driver-facing
    Done/Stats/Outputs polls the in-process driver used to read as
    attributes)."""
    from dsi_tpu.config import JobConfig
    from dsi_tpu.mr import shards as sh
    from dsi_tpu.mr.coordinator import Coordinator
    from dsi_tpu.replica.node import ReplicatedJournal

    mode = spec["mode"]
    files = [str(f) for f in spec.get("files") or []]
    n_reduce = int(spec.get("n_reduce") or 0)
    n_shards = int(spec.get("n_shards") or 0)
    cfg_kw = dict(spec.get("config") or {})
    cfg_kw.setdefault("workdir", spec["workdir"])
    # journal_path points at THIS replica's applier journal: the
    # resuming check must see it (it exists — the applier created it at
    # boot), or a new leader's Coordinator would clear the committed
    # mr-*-out files of the term it is taking over.  The injected
    # journal below is what actually gets written.
    cfg_kw["journal_path"] = journal_path
    cfg = JobConfig(**cfg_kw)

    def factory():
        jr = ReplicatedJournal(journal_path, files, n_reduce,
                               n_shards, node.propose_and_wait)
        if mode == "shard":
            plan = sh.plan_shards(files, n_shards)
            coord = Coordinator(files, 0, cfg, shard_plan=plan,
                                shard_opts={"knobs":
                                            dict(spec.get("knobs") or {})},
                                journal=jr)
        else:
            coord = Coordinator(files, n_reduce, cfg, journal=jr)
        methods = {
            "Coordinator.RequestTask": coord.request_task,
            "Coordinator.RecieveMapComplete": coord.map_complete,
            "Coordinator.RecieveReduceComplete": coord.reduce_complete,
            "Coordinator.MapComplete": coord.map_complete,
            "Coordinator.ReduceComplete": coord.reduce_complete,
            "Coordinator.FetchFailed": coord.fetch_failed,
            "Coordinator.Done": lambda a: {"done": coord.done()},
            "Coordinator.Stats": lambda a: {"stats": dict(
                coord.spec_stats(), c_map=coord.c_map,
                c_reduce=coord.c_reduce)},
            "Coordinator.Outputs": lambda a: (
                {"outputs": coord.final_outputs()} if coord.done()
                else {"error": "job not done"}),
        }
        if mode == "shard":
            methods.update({
                "Coordinator.RequestShard": coord.request_shard,
                "Coordinator.ShardProgress": coord.shard_progress,
                "Coordinator.CommitShard": coord.commit_shard,
                "Coordinator.ShardFailed": coord.shard_failed,
            })
        return coord, methods

    return factory


#: Every coordinator method a replica must answer (with a redirect,
#: before any app exists) — superset of both modes; an off-mode call on
#: the leader gets the app's method table, which simply lacks it.
COORD_METHODS = (
    "Coordinator.RequestTask", "Coordinator.RecieveMapComplete",
    "Coordinator.RecieveReduceComplete", "Coordinator.MapComplete",
    "Coordinator.ReduceComplete", "Coordinator.FetchFailed",
    "Coordinator.RequestShard", "Coordinator.ShardProgress",
    "Coordinator.CommitShard", "Coordinator.ShardFailed",
    "Coordinator.Done", "Coordinator.Stats", "Coordinator.Outputs",
)

SERVE_METHODS = ("Submit", "Status", "Ping", "Shutdown")


def _serve_factory(spec: dict, node, index: int):
    """``app_factory`` for serve mode: the resident daemon, admission
    gated through the replicated log.  Deferred import — the daemon
    pulls the device stack; followers must stay cheap."""

    def factory():
        from dsi_tpu.serve.daemon import ServeDaemon

        kw = dict(spec.get("serve") or {})
        # Per-replica daemon socket: a new leader's daemon must not
        # unlink the socket of a predecessor still tearing down.
        # Clients never dial it — they dial the replica group.
        kw.setdefault("socket_path",
                      os.path.join(spec["workdir"],
                                   f"mrserve-{index}.sock"))
        daemon = ServeDaemon(
            spec["spool"],
            admit_hook=lambda rec: node.propose_and_wait({"admit": rec}),
            **kw)
        daemon.start()
        methods = {
            "Submit": daemon._rpc_submit,
            "Status": daemon._rpc_status,
            "Ping": daemon._rpc_ping,
            "Shutdown": daemon._rpc_shutdown,
        }
        return daemon, methods

    return factory


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--index", type=int, required=True,
                   help="this replica's slot in the address list")
    p.add_argument("--spec", required=True,
                   help="group spec JSON (mode, addrs, workdir, job)")
    args = p.parse_args(argv)

    spec = _load_spec(args.spec)
    mode = spec["mode"]
    if mode not in ("shard", "classic", "serve"):
        raise SystemExit(f"replicad: unknown mode {mode!r}")
    addrs = [str(a) for a in spec["addrs"]]
    i = args.index
    if not 0 <= i < len(addrs):
        raise SystemExit(f"replicad: --index {i} outside group "
                         f"of {len(addrs)}")
    workdir = os.path.abspath(spec["workdir"])
    os.makedirs(workdir, exist_ok=True)

    trace_dir = os.environ.get("DSI_TRACE_DIR")
    if trace_dir:
        from dsi_tpu.obs import configure_tracing

        configure_tracing(trace_dir=trace_dir,
                          basename=f"trace-replicad-{i}")

    from dsi_tpu.replica.node import (ELECTION_TIMEOUT_S, AdmissionApplier,
                                      JournalApplier, ReplicaNode)

    store_path = os.path.join(workdir, f"replica-{i}.rlog")
    if mode == "serve":
        applier = AdmissionApplier(spec["spool"])
        node_ref: list = []
        factory = _serve_factory(spec, _Late(node_ref), i)
        app_methods = SERVE_METHODS
    else:
        journal_path = os.path.join(workdir, f"replica-{i}.journal")
        applier = JournalApplier(journal_path,
                                 [str(f) for f in spec.get("files") or []],
                                 int(spec.get("n_reduce") or 0),
                                 int(spec.get("n_shards") or 0))
        node_ref = []
        factory = _coordinator_factory(spec, _Late(node_ref),
                                       journal_path)
        app_methods = COORD_METHODS

    timeouts = spec.get("election_timeout_s")
    node = ReplicaNode(
        i, addrs, store_path,
        applier=applier,
        app_factory=factory,
        app_methods=tuple(app_methods),
        secret=spec.get("secret"),
        election_timeout_s=(tuple(float(t) for t in timeouts)
                            if timeouts else ELECTION_TIMEOUT_S),
        heartbeat_s=float(spec.get("heartbeat_s") or 0.1))
    node_ref.append(node)
    node.start()
    print(f"replicad: replica {i}/{len(addrs)} up on {node.address} "
          f"(mode {mode}, pid {os.getpid()})", file=sys.stderr)

    stop = {"flag": False}

    def _term(signum, frame):
        stop["flag"] = True

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    try:
        while not stop["flag"]:
            time.sleep(0.1)
    finally:
        node.close()
        applier.close()
        if trace_dir:
            from dsi_tpu.obs import flush_tracing

            flush_tracing()
    return 0


class _Late:
    """Forward the app factory's ``propose_and_wait`` to the node that
    is constructed AFTER the factory (the factory only runs on
    election, long after the list is populated)."""

    def __init__(self, ref: list):
        self._ref = ref

    def propose_and_wait(self, data, timeout: float = 15.0):
        return self._ref[0].propose_and_wait(data, timeout=timeout)


if __name__ == "__main__":
    sys.exit(main())
