"""Submit jobs to (and query) a running ``mrserve`` daemon.

No jax import ever: this is the thin control-plane client
(``serve/client.py``) — submitting costs one framed-JSON RPC on the
daemon's Unix socket, which is the whole point of the resident daemon.

Usage:
    python -m dsi_tpu.cli.mrsubmit --spool DIR --tenant T [--app wc]
        [--pattern P] [--priority {0,1,2}] [--retries N]
        [--wait] [--timeout S] inputfiles...
    python -m dsi_tpu.cli.mrsubmit --spool DIR --status [JOB_ID]
    python -m dsi_tpu.cli.mrsubmit --spool DIR --shutdown
"""

from __future__ import annotations

import argparse
import json
import sys

from dsi_tpu.serve import client


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("files", nargs="*")
    p.add_argument("--spool", default=None,
                   help="the daemon's spool (socket defaults to "
                        "<spool>/mrserve.sock)")
    p.add_argument("--socket", default=None,
                   help="explicit control socket path (wins over "
                        "--spool)")
    p.add_argument("--tenant", default="default")
    p.add_argument("--app", choices=("wc", "grep"), default="wc")
    p.add_argument("--pattern", default=None,
                   help="literal pattern (grep)")
    p.add_argument("--nreduce", type=int, default=None,
                   help="must match the daemon's degree (default: the "
                        "daemon's)")
    p.add_argument("--priority", type=int, choices=(0, 1, 2),
                   default=None,
                   help="admission lane: 0 interactive, 1 default, "
                        "2 batch (strict priority; quota eviction "
                        "prevents starvation)")
    p.add_argument("--retries", type=int, default=0,
                   help="on a backpressure (queue full / rate limited) "
                        "answer, retry up to N times honoring the "
                        "daemon's retry-after hint")
    p.add_argument("--wait", action="store_true",
                   help="block until the job finishes; rc 0 only when "
                        "it is done")
    p.add_argument("--timeout", type=float, default=600.0)
    p.add_argument("--status", nargs="?", const="", default=None,
                   metavar="JOB_ID",
                   help="query one job (or, with no id, every job + "
                        "the tenant table) instead of submitting")
    p.add_argument("--shutdown", action="store_true",
                   help="ask the daemon to stop")
    args = p.parse_args(argv)

    sock = args.socket or (client.default_socket(args.spool)
                           if args.spool else None)
    if not sock:
        p.error("need --socket or --spool")

    if args.shutdown:
        client.shutdown(sock)
        print("mrsubmit: shutdown requested", file=sys.stderr)
        return 0
    if args.status is not None:
        out = client.status(sock, job_id=args.status or None)
        print(json.dumps(out, indent=2, sort_keys=True))
        return 0
    if not args.files:
        p.error("nothing to submit (no input files)")

    try:
        rep = client.submit(sock, args.tenant, args.files, app=args.app,
                            pattern=args.pattern, n_reduce=args.nreduce,
                            priority=args.priority,
                            retries=args.retries)
    except client.ServeBusy as e:
        print(f"mrsubmit: shed by the daemon: {e} "
              f"(retry after ~{e.retry_after_s}s, or use --retries)",
              file=sys.stderr)
        return 2
    except Exception as e:  # noqa: BLE001 — the CLI reports, rc says it
        print(f"mrsubmit: submit failed: {e}", file=sys.stderr)
        return 1
    jid = rep["job_id"]
    print(json.dumps(rep))
    if not args.wait:
        return 0
    try:
        final = client.wait(sock, [jid], timeout=args.timeout)[jid]
    except TimeoutError as e:
        print(f"mrsubmit: {e}", file=sys.stderr)
        return 1
    print(json.dumps({"job": final}, sort_keys=True))
    if final["state"] != "done":
        print(f"mrsubmit: job {jid} {final['state']}: "
              f"{final.get('error')}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
