"""Sequential oracle entry point.

Reference: ``main/mrsequential.go:25-31`` — argv is a plugin followed by input
files; output is a single ``mr-out-0``.

Usage: python -m dsi_tpu.cli.mrsequential <app> inputfiles...
"""

from __future__ import annotations

import argparse

from dsi_tpu.mr.plugin import load_plugin
from dsi_tpu.mr.sequential import run_sequential


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("app")
    p.add_argument("files", nargs="+")
    p.add_argument("--out", default="mr-out-0")
    args = p.parse_args(argv)
    mapf, reducef = load_plugin(args.app)
    run_sequential(mapf, reducef, args.files, args.out)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
