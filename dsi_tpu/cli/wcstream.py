"""Streaming SPMD word-count entry point — the corpus-bigger-than-memory
scaling path (``parallel/streaming.py``) as a user-facing command.

The reference's scaling lever is nMap = #input files on a shared filesystem
(``mr/coordinator.go:152``); this is that lever re-designed for a device
mesh: files become one bounded-memory block stream, every stream step runs
ONE compiled SPMD map/all_to_all/reduce program, and the output is the same
partitioned ``mr-out-<r>`` file set (``mr/worker.go:126-148`` layout,
``ihash % NReduce`` partitioning).  Falls back to the sequential host path
when the stream needs it (non-ASCII bytes, words > 64 chars) — correctness
never depends on the device kernel.

Usage:
    python -m dsi_tpu.cli.wcstream [--nreduce N] [--chunk-bytes B]
        [--devices D] [--workdir DIR] [--check] [--aot] [--u-cap U]
        [--pipeline-depth D] [--device-accumulate] [--sync-every K]
        [--checkpoint-dir DIR] [--checkpoint-every K] [--resume]
        [--ckpt-async] [--ckpt-delta] [--ingest-readers N]
        [--wire-upload] [--grouper sort|hash] [--stats] inputfiles...
"""

from __future__ import annotations

import argparse
import os
import sys


def _positive_int(s: str) -> int:
    """argparse type: capacities/sizes must be >= 1 (a 0 capacity could
    never widen in the exactness_retry ladder — cap*4 stays 0 — and a
    negative one breaks kernel shape construction)."""
    v = int(s)
    if v < 1:
        raise argparse.ArgumentTypeError(f"must be >= 1, got {v}")
    return v


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("files", nargs="+")
    p.add_argument("--nreduce", type=_positive_int, default=10)
    p.add_argument("--chunk-bytes", type=_positive_int, default=1 << 20,
                   help="per-device bytes per stream step")
    p.add_argument("--devices", type=int, default=None,
                   help="mesh size (default: all local devices)")
    p.add_argument("--workdir", default=".")
    p.add_argument("--check", action="store_true",
                   help="run the sequential oracle and verify parity "
                        "(sort mr-out-* | grep . vs oracle, test-mr.sh:52-53)")
    p.add_argument("--aot", action="store_true",
                   help="route the stream's programs through the "
                        "persistent AOT executable cache (single-device "
                        "axon runs: load serialized executables instead "
                        "of paying a fresh-process remote compile)")
    p.add_argument("--u-cap", type=_positive_int, default=1 << 12,
                   help="starting per-device unique capacity (sticky; "
                        "widens on overflow)")
    p.add_argument("--pipeline-depth", type=_positive_int, default=None,
                   help="in-flight stream steps (default: "
                        "DSI_STREAM_PIPELINE_DEPTH or 2; 1 = synchronous)")
    p.add_argument("--device-accumulate", action="store_true",
                   help="fold confirmed steps into the device-resident "
                        "merge table (dsi_tpu/device/) and pull to the "
                        "host only every --sync-every steps — amortizes "
                        "the per-step D2H pull; results are bit-identical")
    p.add_argument("--sync-every", type=_positive_int, default=None,
                   help="folds between host pulls with "
                        "--device-accumulate (default: "
                        "DSI_STREAM_SYNC_EVERY or 8)")
    p.add_argument("--mesh-shards", type=int, default=None,
                   help="mesh-shard the device table across N shards "
                        "(ihash(key) %% N routing inside the fold "
                        "program, per-shard widens, pre-merged sync "
                        "pulls; implies --device-accumulate; default: "
                        "DSI_STREAM_MESH_SHARDS or 0 = off; results "
                        "are bit-identical either way)")
    p.add_argument("--checkpoint-dir", default=None,
                   help="enable crash-resume checkpoints (dsi_tpu/ckpt): "
                        "durable snapshots of the accumulators + device "
                        "table + input cursor land here; see --resume")
    p.add_argument("--ckpt-async", action="store_true", default=None,
                   dest="ckpt_async",
                   help="overlap checkpoint commits with the pipeline "
                        "(capture at the boundary, durable write in a "
                        "background writer; env DSI_STREAM_CKPT_ASYNC)")
    p.add_argument("--ckpt-delta", action="store_true", default=None,
                   dest="ckpt_delta",
                   help="incremental checkpoints: ship only the step "
                        "payloads appended since the previous save, "
                        "full re-base every DSI_STREAM_CKPT_REBASE "
                        "saves (env DSI_STREAM_CKPT_DELTA)")
    p.add_argument("--checkpoint-every", type=_positive_int, default=None,
                   help="confirmed steps between checkpoints (default: "
                        "DSI_STREAM_CKPT_EVERY or 32)")
    p.add_argument("--resume", action="store_true",
                   help="resume from the newest valid checkpoint in "
                        "--checkpoint-dir (restores state, seeks the "
                        "input to the confirmed cursor; final output is "
                        "bit-identical to an uninterrupted run)")
    p.add_argument("--ingest-readers", type=int, default=None,
                   dest="ingest_readers",
                   help="parallel mmap'd input readers with readahead "
                        "(utils/ioread.py): N threads fill blocks ahead "
                        "of the batcher so materialize_s overlaps disk; "
                        "cursors/checkpoints stay byte-exact (default: "
                        "DSI_INGEST_READERS or 0 = inline reads)")
    p.add_argument("--wire-upload", action="store_true", default=None,
                   dest="wire_upload",
                   help="compress chunk uploads host-side and decode on "
                        "device as a compiled map prologue "
                        "(ops/wirecodec.py): the tunnel/PCIe moves "
                        "0.63-0.88x the bytes, HBM sees identical "
                        "tensors (env DSI_STREAM_WIRE; results are "
                        "bit-identical either way)")
    p.add_argument("--grouper", choices=("sort", "hash"), default=None,
                   help="pin the kernel's token-grouping strategy "
                        "(DSI_WC_GROUPER): 'hash' is the measured ~1.8x "
                        "kernel win the warm ladder now pre-compiles for "
                        "accelerators too (*_hg AOT entries); sort stays "
                        "the always-exact fallback rung either way")
    p.add_argument("--stats", action="store_true",
                   help="print the pipeline_stats dict (phase walls + "
                        "fold/sync/widen counters) to stderr")
    p.add_argument("--trace-dir", default=None,
                   help="write this run's unified trace (dsi_tpu/obs) "
                        "there: trace.json (Perfetto-loadable, one lane "
                        "per pipeline stage) + trace.jsonl (event log); "
                        "render with scripts/tracecat.py")
    p.add_argument("--statusz-port", type=int, default=None,
                   help="serve live telemetry on 127.0.0.1:PORT — "
                        "/statusz (plain text: current step, stage "
                        "p50/p99, in-flight window) + /metrics "
                        "(Prometheus); 0 picks a free port (printed to "
                        "stderr); default off (env DSI_STATUSZ_PORT) = "
                        "zero threads; also arms the stall watchdog "
                        "and, with --trace-dir, a bounded live.jsonl "
                        "sample ring there")
    args = p.parse_args(argv)

    if args.resume and not args.checkpoint_dir:
        p.error("--resume requires --checkpoint-dir")

    if args.grouper:
        os.environ["DSI_WC_GROUPER"] = args.grouper

    if args.trace_dir:
        from dsi_tpu.obs import configure_tracing

        configure_tracing(trace_dir=args.trace_dir)

    # Live telemetry BEFORE the jax import below: /statusz answers
    # during device init, the slowest silent phase of a tunnel run.
    if args.statusz_port is not None or os.environ.get("DSI_STATUSZ_PORT"):
        from dsi_tpu.obs.live import start_from_args

        start_from_args(args.statusz_port, live_dir=args.trace_dir)

    from dsi_tpu.utils.platformpin import pin_platform_from_env

    pin_platform_from_env()

    from dsi_tpu.parallel.shuffle import default_mesh, write_partitioned_output
    from dsi_tpu.parallel.streaming import wordcount_streaming
    from dsi_tpu.utils.ioread import open_blocks

    from dsi_tpu.ckpt import CheckpointMismatch

    mesh = default_mesh(args.devices)
    pstats: dict = {}
    try:
        acc = wordcount_streaming(
            open_blocks(args.files, readers=args.ingest_readers),
            mesh=mesh, n_reduce=args.nreduce,
            chunk_bytes=args.chunk_bytes, u_cap=args.u_cap, aot=args.aot,
            depth=args.pipeline_depth,
            device_accumulate=args.device_accumulate,
            sync_every=args.sync_every, mesh_shards=args.mesh_shards,
            checkpoint_dir=args.checkpoint_dir,
            checkpoint_every=args.checkpoint_every,
            checkpoint_async=args.ckpt_async,
            checkpoint_delta=args.ckpt_delta, resume=args.resume,
            wire_upload=args.wire_upload,
            pipeline_stats=pstats)
    except CheckpointMismatch as e:
        # A valid checkpoint for a DIFFERENT job (other corpus shape /
        # mesh / mode): resuming would corrupt it, starting fresh would
        # overwrite it — the caller must fix the command or the dir.
        print(f"wcstream: {e}", file=sys.stderr)
        return 1
    if args.resume and not pstats.get("resume_cursor"):
        # Legitimate when the crash predated the first checkpoint, but a
        # typo'd --checkpoint-dir looks identical — say it out loud so a
        # GB-scale from-scratch replay is never a silent surprise.
        print("wcstream: --resume found no usable checkpoint in "
              f"{args.checkpoint_dir}; started from scratch",
              file=sys.stderr)
    if args.stats:
        print(f"wcstream: pipeline_stats={pstats}", file=sys.stderr)
    if args.trace_dir:
        from dsi_tpu.obs import flush_tracing_report

        flush_tracing_report(args.trace_dir, "wcstream")
    if acc is None:
        # Host fallback: the sequential oracle semantics, partitioned
        # output — the ONE shared implementation (serve/pack.py), so the
        # CLI and the serving daemon cannot drift.
        print("wcstream: stream needs the host path; running host word count",
              file=sys.stderr)
        from dsi_tpu.serve.pack import host_wordcount

        acc = host_wordcount(args.files, args.nreduce)
    os.makedirs(args.workdir, exist_ok=True)
    write_partitioned_output(acc, args.nreduce, args.workdir)

    if args.check:
        from dsi_tpu.apps import wc
        from dsi_tpu.mr.sequential import run_sequential

        oracle_out = os.path.join(args.workdir, "mr-correct.txt")
        run_sequential(wc.Map, wc.Reduce, args.files, oracle_out)
        got: list = []
        for r in range(args.nreduce):
            with open(os.path.join(args.workdir, f"mr-out-{r}"),
                      encoding="utf-8") as f:
                got.extend(l for l in f if l.strip())
        with open(oracle_out, encoding="utf-8") as f:
            want = sorted(l for l in f if l.strip())
        if sorted(got) != want:
            print("wcstream: PARITY FAILURE vs sequential oracle",
                  file=sys.stderr)
            return 2
        print("wcstream: parity OK", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
