"""Streaming SPMD word-count entry point — the corpus-bigger-than-memory
scaling path (``parallel/streaming.py``) as a user-facing command.

The reference's scaling lever is nMap = #input files on a shared filesystem
(``mr/coordinator.go:152``); this is that lever re-designed for a device
mesh: files become one bounded-memory block stream, every stream step runs
ONE compiled SPMD map/all_to_all/reduce program, and the output is the same
partitioned ``mr-out-<r>`` file set (``mr/worker.go:126-148`` layout,
``ihash % NReduce`` partitioning).  Falls back to the sequential host path
when the stream needs it (non-ASCII bytes, words > 64 chars) — correctness
never depends on the device kernel.

Usage:
    python -m dsi_tpu.cli.wcstream [--nreduce N] [--chunk-bytes B]
        [--devices D] [--workdir DIR] inputfiles...
"""

from __future__ import annotations

import argparse
import sys


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("files", nargs="+")
    p.add_argument("--nreduce", type=int, default=10)
    p.add_argument("--chunk-bytes", type=int, default=1 << 20,
                   help="per-device bytes per stream step")
    p.add_argument("--devices", type=int, default=None,
                   help="mesh size (default: all local devices)")
    p.add_argument("--workdir", default=".")
    args = p.parse_args(argv)

    from dsi_tpu.utils.platformpin import pin_platform_from_env

    pin_platform_from_env()

    from dsi_tpu.parallel.shuffle import default_mesh, write_partitioned_output
    from dsi_tpu.parallel.streaming import stream_files, wordcount_streaming

    mesh = default_mesh(args.devices)
    acc = wordcount_streaming(stream_files(args.files), mesh=mesh,
                              n_reduce=args.nreduce,
                              chunk_bytes=args.chunk_bytes)
    if acc is None:
        # Host fallback: the sequential oracle semantics, partitioned output.
        print("wcstream: stream needs the host path; running host word count",
              file=sys.stderr)
        from dsi_tpu.apps import wc
        from dsi_tpu.mr.worker import ihash

        counts: dict = {}
        for f in args.files:
            with open(f, "rb") as fh:
                text = fh.read().decode("utf-8", errors="replace")
            for kv in wc.Map(f, text):
                counts[kv.key] = counts.get(kv.key, 0) + 1
        acc = {w: (c, ihash(w) % args.nreduce) for w, c in counts.items()}
    write_partitioned_output(acc, args.nreduce, args.workdir)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
