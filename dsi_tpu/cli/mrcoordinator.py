"""Coordinator process entry point.

Reference: ``main/mrcoordinator.go:17-29`` — parse argv (input files), build a
coordinator with nReduce=10, poll Done() at 1 Hz, sleep one extra second after
done so workers can observe TaskStatus=DONE, then exit (the dying socket kills
any remaining workers' dials).

Usage: python -m dsi_tpu.cli.mrcoordinator [--nreduce N] inputfiles...
"""

from __future__ import annotations

import argparse
import time

from dsi_tpu.config import JobConfig
from dsi_tpu.mr.coordinator import make_coordinator


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--nreduce", type=int, default=10)  # mrcoordinator.go:23
    p.add_argument("--task-timeout", type=float, default=10.0)
    p.add_argument("--journal", default="",
                   help="checkpoint journal path; an existing journal for "
                        "the same job resumes it (new capability — the "
                        "reference loses the job on coordinator death)")
    p.add_argument("files", nargs="+")
    args = p.parse_args(argv)
    cfg = JobConfig(n_reduce=args.nreduce, task_timeout_s=args.task_timeout,
                    journal_path=args.journal)
    c = make_coordinator(args.files, args.nreduce, cfg)
    addr = c.address()
    if addr and addr.startswith("tcp:"):
        import sys

        # With tcp:HOST:0 the port is kernel-assigned; tell the operator
        # what workers should set DSI_MR_SOCKET to.
        print(f"mrcoordinator: listening on {addr}",
              file=sys.stderr, flush=True)
    while not c.done():  # mrcoordinator.go:24-26
        time.sleep(cfg.done_poll_s)
    time.sleep(cfg.exit_grace_s)  # mrcoordinator.go:28
    c.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
