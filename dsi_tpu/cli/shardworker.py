"""Shard-worker process entry point (``mr/shardworker.py``).

Spawned by ``shardrun`` with cwd=workdir and the coordinator socket in
``DSI_MR_SOCKET``; every engine knob arrives over the wire in the shard
assignment, so the process needs no app argument.  Commits a
trace-<pid> file at exit when ``DSI_TRACE_DIR`` is inherited.
"""

from __future__ import annotations

import argparse


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--workdir", default=".")
    p.add_argument("--progress-s", type=float, default=None,
                   help="ShardProgress heartbeat cadence, seconds")
    p.add_argument("--shard-timeout", type=float, default=None,
                   help="mirror of the coordinator's presumed-dead "
                        "silence (informational on the worker side)")
    args = p.parse_args(argv)
    import os
    import time

    from dsi_tpu.config import JobConfig
    from dsi_tpu.mr.shardworker import shard_worker_loop

    kw = {"workdir": args.workdir}
    if args.progress_s is not None:
        kw["shard_progress_s"] = args.progress_s
    if args.shard_timeout is not None:
        kw["shard_timeout_s"] = args.shard_timeout
    # NET data plane (ISSUE 17, ``shardrun --hosts``): DSI_NET_SPOOL
    # names this worker's PRIVATE spool dir — boot a partition server
    # over it, advertise its address on every RPC, and LINGER after the
    # job so the driver can still fetch committed outputs; the driver
    # terminates the process once everything is fetched.
    spool = os.environ.get("DSI_NET_SPOOL")
    partsrv = None
    if spool:
        from dsi_tpu.net import PartitionServer

        kw["net_shuffle"] = True
        cfg0 = JobConfig(**kw)
        partsrv = PartitionServer(
            spool, bind=os.environ.get("DSI_NET_BIND", ""),
            retention_s=cfg0.net_spool_retention_s,
            codec=cfg0.net_codec)
        partsrv.start()
    # Tracing: DSI_TRACE_DIR (inherited from shardrun) arms the global
    # tracer with a durable atexit flush; chaos/fault kills flush
    # explicitly before os._exit (ckpt/fault.py).
    try:
        shard_worker_loop(JobConfig(**kw), partsrv=partsrv)
        if partsrv is not None:
            while True:
                time.sleep(3600)
    finally:
        if partsrv is not None:
            partsrv.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
