"""Shard-worker process entry point (``mr/shardworker.py``).

Spawned by ``shardrun`` with cwd=workdir and the coordinator socket in
``DSI_MR_SOCKET``; every engine knob arrives over the wire in the shard
assignment, so the process needs no app argument.  Commits a
trace-<pid> file at exit when ``DSI_TRACE_DIR`` is inherited.
"""

from __future__ import annotations

import argparse


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--workdir", default=".")
    p.add_argument("--progress-s", type=float, default=None,
                   help="ShardProgress heartbeat cadence, seconds")
    p.add_argument("--shard-timeout", type=float, default=None,
                   help="mirror of the coordinator's presumed-dead "
                        "silence (informational on the worker side)")
    args = p.parse_args(argv)
    from dsi_tpu.config import JobConfig
    from dsi_tpu.mr.shardworker import shard_worker_loop

    kw = {"workdir": args.workdir}
    if args.progress_s is not None:
        kw["shard_progress_s"] = args.progress_s
    if args.shard_timeout is not None:
        kw["shard_timeout_s"] = args.shard_timeout
    # Tracing: DSI_TRACE_DIR (inherited from shardrun) arms the global
    # tracer with a durable atexit flush; chaos/fault kills flush
    # explicitly before os._exit (ckpt/fault.py).
    shard_worker_loop(JobConfig(**kw))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
