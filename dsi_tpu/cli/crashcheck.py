"""CLI for the vmapped crash-test model checker.

Usage: python -m dsi_tpu.cli.crashcheck [-n 1000] [--exit-prob 0.25]
           [--stall-prob 0.2] [--timeout 10] [--horizon 800]
           [--platform cpu|tpu|default]

Prints one JSON line of aggregate invariant results (see
``dsi_tpu/parallel/simulate.py``).  ``--platform cpu`` pins JAX to the host
CPU before backend init — on this machine the TPU's first-contact compile
latency makes CPU the right place for quick checks; the TPU is the right
place for very large fleets.
"""

from __future__ import annotations

import argparse
import json


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("-n", "--instances", type=int, default=1000)
    p.add_argument("--exit-prob", type=float, default=0.25)
    p.add_argument("--stall-prob", type=float, default=0.2)
    p.add_argument("--timeout", type=int, default=10)
    p.add_argument("--horizon", type=int, default=800)
    p.add_argument("--n-map", type=int, default=8)
    p.add_argument("--n-reduce", type=int, default=10)
    p.add_argument("--n-workers", type=int, default=3)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--platform", choices=("cpu", "tpu", "default"),
                   default="cpu")
    args = p.parse_args(argv)

    import jax

    if args.platform == "cpu":
        jax.config.update("jax_platforms", "cpu")
    elif args.platform == "tpu":
        pass  # whatever accelerator the environment registers

    from dsi_tpu.parallel.simulate import run_crash_model_check

    agg = run_crash_model_check(
        args.instances, seed=args.seed, n_map=args.n_map,
        n_reduce=args.n_reduce, n_workers=args.n_workers,
        timeout=args.timeout, horizon=args.horizon,
        exit_prob=args.exit_prob, stall_prob=args.stall_prob)
    print(json.dumps(agg))
    ok = agg["all_finished"] and agg["all_consistent"] and agg["all_safe"]
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
