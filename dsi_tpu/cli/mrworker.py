"""Worker process entry point.

Reference: ``main/mrworker.go:19-28`` — argv is one plugin; load its
Map/Reduce, then run the worker loop.  Extended with ``--backend=tpu``
(the BASELINE.json north-star flag) routing execution to the JAX backend,
and with the NET data plane (ISSUE 17): when ``DSI_NET_SPOOL`` is set
(by ``mrrun --net``) the worker boots a partition server over that
private spool directory, runs the loop in net mode, and LINGERS after
the job completes so consumers can still fetch its spooled bytes — the
driver terminates it once every output is safely fetched.

Usage: python -m dsi_tpu.cli.mrworker [--backend host|tpu] <app-name-or-path.py>
"""

from __future__ import annotations

import argparse
import os
import time

from dsi_tpu.config import JobConfig
from dsi_tpu.mr.plugin import load_plugin
from dsi_tpu.mr.worker import worker_loop


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--backend", choices=("host", "tpu", "native"),
                   default="host")
    p.add_argument("app")
    args = p.parse_args(argv)
    mapf, reducef = load_plugin(args.app)
    # Build/load the native decoder NOW, before the task loop: the first
    # lazy build (up to 120 s of g++) must not land inside a live reduce
    # task, where it would blow straight through the coordinator's 10 s
    # requeue timeout and cause spurious task duplication (worst on NFS
    # fleets where many hosts race the same build).
    from dsi_tpu import native

    native.available()
    cfg = JobConfig(backend=args.backend)
    runner = None
    if args.backend == "tpu":
        from dsi_tpu.backends.tpu import TpuTaskRunner

        runner = TpuTaskRunner.for_app(args.app)
    elif args.backend == "native":
        from dsi_tpu.backends.native import NativeTaskRunner

        runner = NativeTaskRunner.for_app(args.app)
    spool = os.environ.get("DSI_NET_SPOOL")
    partsrv = None
    if spool:
        from dsi_tpu.net import PartitionServer, fetch_window_from_env

        cfg = JobConfig(backend=args.backend, net_shuffle=True,
                        net_fetch_window=fetch_window_from_env())
        partsrv = PartitionServer(
            spool, bind=os.environ.get("DSI_NET_BIND", ""),
            retention_s=cfg.net_spool_retention_s,
            codec=cfg.net_codec)
        partsrv.start()
    try:
        worker_loop(mapf, reducef, cfg, task_runner=runner,
                    partsrv=partsrv)
        if partsrv is not None:
            # Linger: the job is done but the driver may not have
            # fetched this spool's outputs yet — serve until killed.
            while True:
                time.sleep(3600)
    finally:
        if partsrv is not None:
            partsrv.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
