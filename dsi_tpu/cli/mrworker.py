"""Worker process entry point.

Reference: ``main/mrworker.go:19-28`` — argv is one plugin; load its
Map/Reduce, then run the worker loop.  Extended with ``--backend=tpu``
(the BASELINE.json north-star flag) routing execution to the JAX backend.

Usage: python -m dsi_tpu.cli.mrworker [--backend host|tpu] <app-name-or-path.py>
"""

from __future__ import annotations

import argparse

from dsi_tpu.config import JobConfig
from dsi_tpu.mr.plugin import load_plugin
from dsi_tpu.mr.worker import worker_loop


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--backend", choices=("host", "tpu", "native"),
                   default="host")
    p.add_argument("app")
    args = p.parse_args(argv)
    mapf, reducef = load_plugin(args.app)
    # Build/load the native decoder NOW, before the task loop: the first
    # lazy build (up to 120 s of g++) must not land inside a live reduce
    # task, where it would blow straight through the coordinator's 10 s
    # requeue timeout and cause spurious task duplication (worst on NFS
    # fleets where many hosts race the same build).
    from dsi_tpu import native

    native.available()
    cfg = JobConfig(backend=args.backend)
    runner = None
    if args.backend == "tpu":
        from dsi_tpu.backends.tpu import TpuTaskRunner

        runner = TpuTaskRunner.for_app(args.app)
    elif args.backend == "native":
        from dsi_tpu.backends.native import NativeTaskRunner

        runner = NativeTaskRunner.for_app(args.app)
    worker_loop(mapf, reducef, cfg, task_runner=runner)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
