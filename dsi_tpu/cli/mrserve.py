"""The resident MapReduce-as-a-service daemon (``dsi_tpu/serve``).

Boots once — device mesh init, AOT warm, spool hygiene — then serves
job submissions over a Unix-socket control plane until shut down.  Many
small jobs amortize the start cost K one-shot CLIs would each pay, and
word-count tenants additionally PACK into shared device steps (K
tenants ≈ 1 dispatch; ``serve/pack.py``).  Kill it however you like:
accepted jobs are journaled durably and per-tenant delta-checkpoint
chains make the restart resume every in-flight tenant with
byte-identical output.

Usage:
    python -m dsi_tpu.cli.mrserve --spool DIR [--socket PATH]
        [--nreduce N] [--chunk-bytes B] [--devices D]
        [--max-resident K] [--quota-steps Q] [--checkpoint-every K]
        [--max-queue N] [--rate-limit R] [--rate-burst B]
        [--no-pack-grep] [--retention-days D] [--statusz-port P]
        [--trace-dir DIR] [--no-warm]
"""

from __future__ import annotations

import argparse
import os
import signal
import sys


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--spool", required=True,
                   help="daemon state root: control socket, job "
                        "journal, per-tenant checkpoint chains, job "
                        "outputs")
    p.add_argument("--socket", default=None,
                   help="control socket path (default: "
                        "<spool>/mrserve.sock)")
    p.add_argument("--nreduce", type=int, default=10,
                   help="the daemon's reduce-partition degree (packed "
                        "steps share it; submissions must match)")
    p.add_argument("--chunk-bytes", type=int, default=1 << 16,
                   help="per-lane bytes per packed step (rounded up to "
                        "a power of two, min 256)")
    p.add_argument("--devices", type=int, default=None,
                   help="mesh size = packing lanes (default: all local "
                        "devices)")
    p.add_argument("--max-resident", type=int, default=8,
                   help="jobs held in memory at once; the rest park as "
                        "checkpoint chains until scheduled")
    p.add_argument("--quota-steps", type=int, default=64,
                   help="confirmed steps a resident job may take while "
                        "others queue before it is evicted to its "
                        "chain")
    p.add_argument("--checkpoint-every", type=int, default=8,
                   help="confirmed packed steps between per-tenant "
                        "snapshots (delta chains; eviction and crash "
                        "recovery both resume from them)")
    p.add_argument("--max-queue", type=int, default=1024,
                   help="queued jobs past which submissions are SHED "
                        "with a typed backpressure error (the journal "
                        "is never written for a shed job)")
    p.add_argument("--rate-limit", type=float, default=None,
                   help="per-tenant submit rate (jobs/second, token "
                        "bucket; default: unlimited)")
    p.add_argument("--rate-burst", type=int, default=4,
                   help="token-bucket burst capacity per tenant")
    p.add_argument("--no-pack-grep", action="store_true",
                   help="run grep jobs as time-multiplexed step "
                        "objects instead of packed lanes (the bench "
                        "row's control arm; env DSI_SERVE_PACK_GREP=0)")
    p.add_argument("--retention-days", type=float, default=14.0,
                   help="age after which a DONE tenant's checkpoint "
                        "chains are garbage-collected at boot (live "
                        "chains are never touched)")
    p.add_argument("--statusz-port", type=int, default=None,
                   help="serve live telemetry on 127.0.0.1:PORT — "
                        "/statusz gains a per-tenant section and "
                        "/metrics dsi_serve_* series; 0 picks a free "
                        "port (env DSI_STATUSZ_PORT)")
    p.add_argument("--trace-dir", default=None,
                   help="unified trace output dir (dsi_tpu/obs)")
    p.add_argument("--no-warm", action="store_true",
                   help="skip the boot-time AOT warm (tests)")
    p.add_argument("--replicas", type=int, default=0,
                   help="run N coordinator replicas (Raft group, "
                        "dsi_tpu/replica) instead of one daemon; the "
                        "leader hosts the daemon, admissions commit to "
                        "the replicated log before acking, and clients "
                        "dial the printed comma-separated socket list")
    args = p.parse_args(argv)

    if args.replicas:
        if args.replicas < 2:
            p.error("--replicas needs >= 2 (3 for kill-tolerance)")
        if args.socket:
            p.error("--socket conflicts with --replicas (each replica "
                    "binds <spool>/replica-<i>.sock)")
        return _replica_serve(args)

    if args.trace_dir:
        from dsi_tpu.obs import configure_tracing

        configure_tracing(trace_dir=args.trace_dir)

    # Live telemetry BEFORE jax init, the wcstream discipline: /statusz
    # answers while the mesh is still coming up.
    if args.statusz_port is not None or os.environ.get("DSI_STATUSZ_PORT"):
        from dsi_tpu.obs.live import start_from_args

        start_from_args(args.statusz_port, live_dir=args.trace_dir)

    from dsi_tpu.utils.platformpin import pin_platform_from_env

    pin_platform_from_env()

    from dsi_tpu.serve.daemon import ServeDaemon

    daemon = ServeDaemon(
        args.spool, socket_path=args.socket, n_reduce=args.nreduce,
        chunk_bytes=args.chunk_bytes, devices=args.devices,
        max_resident=args.max_resident, quota_steps=args.quota_steps,
        checkpoint_every=args.checkpoint_every,
        retention_s=args.retention_days * 86400.0,
        warm=not args.no_warm, max_queue=args.max_queue,
        rate_limit=args.rate_limit, rate_burst=args.rate_burst,
        pack_grep=False if args.no_pack_grep else None)

    def _stop(_sig, _frm):
        daemon.stop()

    signal.signal(signal.SIGTERM, _stop)
    signal.signal(signal.SIGINT, _stop)
    daemon.start()
    print(f"mrserve: spool={daemon.spool} socket={daemon.socket_path} "
          f"lanes={args.devices or 'auto'} (boot reaped "
          f"{daemon.boot_reaped} tmp orphans, gc'd "
          f"{daemon.boot_gc_chains} aged chains)",
          file=sys.stderr, flush=True)
    daemon.ready.wait()
    print("mrserve: ready", file=sys.stderr, flush=True)
    try:
        while daemon._thread.is_alive():
            daemon.join(timeout=0.5)
    finally:
        daemon.close()
        if args.trace_dir:
            from dsi_tpu.obs import flush_tracing_report

            flush_tracing_report(args.trace_dir, "mrserve")
    print("mrserve: stopped", file=sys.stderr, flush=True)
    return 0


def _replica_serve(args) -> int:
    """``--replicas N``: spawn the coordinator group and supervise it.

    The leader replica hosts the real ServeDaemon; this process only
    writes the group spec, babysits the N ``replicad`` children, and
    prints the comma-separated socket spec clients (``serve/client.py``,
    ``mrsubmit``) dial — the group dialer follows leader redirects, so
    a ``kill -9`` of the leader is invisible to submitters beyond the
    election wall."""
    import time as _time

    from dsi_tpu.replica.driver import ReplicaGroup

    spool = os.path.abspath(args.spool)
    serve_kw = {
        "n_reduce": args.nreduce, "chunk_bytes": args.chunk_bytes,
        "devices": args.devices, "max_resident": args.max_resident,
        "quota_steps": args.quota_steps,
        "checkpoint_every": args.checkpoint_every,
        "retention_s": args.retention_days * 86400.0,
        "warm": not args.no_warm, "max_queue": args.max_queue,
        "rate_limit": args.rate_limit, "rate_burst": args.rate_burst,
        "pack_grep": False if args.no_pack_grep else None,
    }
    env = dict(os.environ)
    pkg_root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    env["PYTHONPATH"] = pkg_root + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    if args.trace_dir:
        env["DSI_TRACE_DIR"] = os.path.abspath(args.trace_dir)

    group = ReplicaGroup("serve", spool, replicas=args.replicas,
                         spool=spool, serve=serve_kw, env=env)
    stop = {"flag": False}

    def _stop(_sig, _frm):
        stop["flag"] = True

    signal.signal(signal.SIGTERM, _stop)
    signal.signal(signal.SIGINT, _stop)
    rc = 0
    try:
        info = group.wait_leader(timeout=180.0)
        print(f"mrserve: replica group up, leader is replica "
              f"{info['index']} (term {info['term']})",
              file=sys.stderr, flush=True)
        print(f"mrserve: sockets {group.spec}", file=sys.stderr,
              flush=True)
        print("mrserve: ready", file=sys.stderr, flush=True)
        while not stop["flag"]:
            _time.sleep(0.2)
            for i, proc in group.procs.items():
                code = proc.poll()
                if code not in (None, 0, -signal.SIGTERM):
                    # A replica died outside our control (OOM, chaos
                    # harness): respawn it — the group tolerates a
                    # minority down, but not forever.
                    group.spawn(i)
                    group.respawns += 1
    except KeyboardInterrupt:
        pass
    except Exception as e:  # no leader ever emerged: say so, clean up
        print(f"mrserve: replica group failed: {e}", file=sys.stderr,
              flush=True)
        rc = 1
    finally:
        group.close()
    print("mrserve: stopped", file=sys.stderr, flush=True)
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
