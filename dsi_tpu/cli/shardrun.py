"""One-command speculative shard job: coordinator + N shard workers.

The ``mrrun`` shape for streaming-shard jobs (ISSUE 15): plan the input
into newline-aligned cursor-range shards, run the shard-scheduler
coordinator IN-PROCESS (it is jax-free, and the driver reads its
speculation counters directly), spawn N ``shardworker`` subprocesses,
wait for every shard to commit exactly once, then merge the committed
per-shard outputs into ``mr-out-0``.

Chaos/straggler injection for grids and the bench A/B:

* ``--slow-worker I:SECONDS`` — worker I sleeps that long per advance
  slice (``DSI_SHARD_SLOW_S``): the forced straggler the backup
  dispatcher must fire on;
* ``--fault-worker I:POINT[:STEP]`` — worker I inherits
  ``DSI_FAULT_POINT``/``DSI_FAULT_STEP`` (``ckpt/fault.py``): a real
  ``os._exit`` mid-shard, whose takeover must resume from the chain;
* ``DSI_CHAOS_WORKER_KILL=p[,seed]`` passes through to every worker
  (each stamped with ``DSI_CHAOS_WORKER_INDEX`` for determinism).

``--resplit`` arms dynamic straggler re-split (ISSUE 16): instead of
one whole-range backup, the coordinator cuts the straggler's REMAINING
cursor range (from its live reported cursor) into newline-aligned
sub-shards and fans them out to idle workers — each sub-range is its
own first-commit-wins race, and the merge consumes the coordinator's
``final_outputs()`` (full-range file, or sub-range files in order).

``--check`` runs the sequential host oracle over the whole input and
byte-compares the merged output.  ``--stats-json`` dumps the
coordinator's ``spec_stats()`` (backup_dispatches, requeues, commits,
duplicate_commits, resume cursors) plus walls — the evidence surface
the CI smoke and the bench row assert on.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time


def _fetch_window() -> int:
    from dsi_tpu.net.fetch import fetch_window_from_env

    return fetch_window_from_env()


def _parse_worker_knob(text: str, what: str):
    i, _, rest = text.partition(":")
    if not rest:
        raise SystemExit(f"shardrun: malformed {what}: {text!r} "
                         f"(want INDEX:VALUE)")
    return int(i), rest


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("files", nargs="+")
    p.add_argument("--engine", choices=("wordcount", "grep"),
                   default="wordcount")
    p.add_argument("--pattern", default="",
                   help="literal pattern (grep engine)")
    p.add_argument("--workers", type=int, default=3)
    p.add_argument("--shards", type=int, default=0,
                   help="shard count (default 2x workers)")
    p.add_argument("--workdir", default=".")
    p.add_argument("--chunk-bytes", type=int, default=1 << 20)
    p.add_argument("--nreduce", type=int, default=10)
    p.add_argument("--ckpt-every", type=int, default=32,
                   help="engine checkpoint cadence, confirmed steps")
    p.add_argument("--ckpt-secs", type=float, default=1.0,
                   help="worker-driven durable checkpoint cadence, "
                        "seconds (the resume-granularity knob)")
    p.add_argument("--progress-s", type=float, default=0.25,
                   help="worker heartbeat cadence, seconds")
    p.add_argument("--shard-timeout", type=float, default=10.0,
                   help="presumed-dead progress silence, seconds")
    p.add_argument("--spec-floor", type=float, default=2.0,
                   help="backup-dispatch staleness floor, seconds")
    p.add_argument("--no-spec", action="store_true",
                   help="disable speculative backup dispatch (the "
                        "bench A/B's control arm)")
    p.add_argument("--resplit", action="store_true",
                   help="dynamic straggler re-split: cut a straggling "
                        "attempt's REMAINING range into sub-shards for "
                        "idle workers instead of one whole-range backup")
    p.add_argument("--resplit-ways", type=int, default=2,
                   help="sub-shard count per re-split (default 2)")
    p.add_argument("--journal", default="",
                   help="commit journal (default <workdir>/shards."
                        "journal; exactly-once needs it)")
    p.add_argument("--slow-worker", default="",
                   help="I:SECONDS — straggler injection for worker I")
    p.add_argument("--fault-worker", default="",
                   help="I:POINT[:STEP] — DSI_FAULT_POINT kill for "
                        "worker I")
    p.add_argument("--hosts", action="store_true",
                   help="NET data plane (ISSUE 17): per-worker PRIVATE "
                        "workdirs, coordinator control plane on "
                        "localhost TCP, committed shard outputs served "
                        "from each worker's spool and fetched by the "
                        "driver over the stream transport — the share-"
                        "nothing multi-host shape on one machine")
    p.add_argument("--replicas", type=int, default=0,
                   help="replicated control plane (dsi_tpu/replica): "
                        "run the coordinator as an N-member Raft group "
                        "of replicad processes; workers discover the "
                        "leader via NotLeader redirects, and a dead "
                        "leader is an election away instead of job-over")
    p.add_argument("--kill-leader-after", type=float, default=0.0,
                   help="chaos (needs --replicas): SIGKILL the leader "
                        "this many seconds into the job, measure the "
                        "kill->served failover wall, respawn the "
                        "victim as a follower")
    p.add_argument("--timeout", type=float, default=600.0)
    p.add_argument("--check", action="store_true",
                   help="byte-compare the merged output vs the "
                        "sequential host oracle")
    p.add_argument("--stats-json", default="")
    p.add_argument("--trace-dir", default=None)
    p.add_argument("--out", default="mr-out-0",
                   help="merged output name (relative to workdir)")
    args = p.parse_args(argv)

    workdir = os.path.abspath(args.workdir)
    os.makedirs(workdir, exist_ok=True)
    files = [os.path.abspath(f) for f in args.files]
    n_shards = args.shards or 2 * args.workers
    if args.hosts and args.resplit:
        p.error("--hosts does not support --resplit (the sub-range "
                "merge reads committed files from a shared directory)")
    if args.replicas and args.hosts:
        p.error("--hosts does not support --replicas yet (the driver "
                "reads the coordinator's location registry in-process)")
    if args.replicas and args.replicas < 2:
        p.error("--replicas wants >= 2 (3 tolerates one kill)")
    if args.kill_leader_after and not args.replicas:
        p.error("--kill-leader-after needs --replicas")
    journal = os.path.abspath(args.journal) if args.journal \
        else os.path.join(workdir, "shards.journal")

    from dsi_tpu.config import JobConfig
    from dsi_tpu.mr import shards as sh
    from dsi_tpu.mr.coordinator import Coordinator

    env = dict(os.environ)
    env.setdefault("DSI_MR_SOCKET", os.path.join(workdir, "mr.sock"))
    # Workers run with cwd=workdir; make the package importable there
    # even when it is not installed (the test-sandbox case).
    import dsi_tpu as _pkg

    pkg_root = os.path.dirname(os.path.dirname(
        os.path.abspath(_pkg.__file__)))
    env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
    if args.trace_dir:
        trace_dir = os.path.abspath(args.trace_dir)
        env["DSI_TRACE_DIR"] = trace_dir
        from dsi_tpu.obs import configure_tracing, trace_event

        configure_tracing(trace_dir=trace_dir, basename="trace-shardrun")
        trace_event("shardrun.start", engine=args.engine,
                    workers=args.workers, shards=n_shards,
                    files=len(files))

    plan = sh.plan_shards(files, n_shards)
    if not plan:
        print("shardrun: empty input", file=sys.stderr)
        return 1
    knobs = {"engine": args.engine, "chunk_bytes": args.chunk_bytes,
             "n_reduce": args.nreduce, "ckpt_every": args.ckpt_every,
             "ckpt_secs": args.ckpt_secs}
    if args.engine == "grep":
        if not args.pattern:
            p.error("--engine grep requires --pattern")
        knobs["pattern"] = args.pattern
    cfg = JobConfig(workdir=workdir,
                    socket_path=("tcp:127.0.0.1:0" if args.hosts
                                 else env["DSI_MR_SOCKET"]),
                    journal_path=journal,
                    shard_timeout_s=args.shard_timeout,
                    spec_backup=not args.no_spec,
                    spec_floor_s=args.spec_floor,
                    spec_resplit=args.resplit,
                    spec_resplit_ways=args.resplit_ways,
                    shard_progress_s=args.progress_s,
                    net_shuffle=args.hosts,
                    net_fetch_window=_fetch_window())
    group = None
    failover = None
    if args.replicas:
        # Replicated control plane: no in-process coordinator — an
        # N-member replicad group owns the task table, and this driver
        # talks to whoever leads.  Fresh-run hygiene the single-node
        # coordinator does itself (clearing a PREVIOUS job's outputs)
        # happens here: the leader's resuming check sees the replica
        # journal, which the appliers create at boot, so it never
        # clears — exactly what failover needs and fresh runs don't.
        if not os.path.exists(os.path.join(workdir,
                                           "replica-0.journal")):
            for name in os.listdir(workdir):
                if name.startswith(("mr-out-", "mr-shard-out-")):
                    try:
                        os.remove(os.path.join(workdir, name))
                    except OSError:
                        pass
        from dsi_tpu.replica.driver import ReplicaGroup

        group = ReplicaGroup(
            "shard", workdir, replicas=args.replicas, files=files,
            n_shards=n_shards, knobs=knobs,
            config={"shard_timeout_s": args.shard_timeout,
                    "spec_backup": not args.no_spec,
                    "spec_floor_s": args.spec_floor,
                    "spec_resplit": args.resplit,
                    "spec_resplit_ways": args.resplit_ways,
                    "shard_progress_s": args.progress_s},
            env=env)
        env["DSI_MR_SOCKET"] = group.spec
        coord = group
    else:
        coord = Coordinator(files, 0, cfg, shard_plan=plan,
                            shard_opts={"knobs": knobs})
        coord.serve()
    if args.hosts:
        # Workers dial the coordinator's REAL TCP port, not a path.
        env["DSI_MR_SOCKET"] = coord.address()

    slow = _parse_worker_knob(args.slow_worker, "--slow-worker") \
        if args.slow_worker else None
    fault = _parse_worker_knob(args.fault_worker, "--fault-worker") \
        if args.fault_worker else None

    def worker_dir(i: int) -> str:
        """--hosts: each worker's PRIVATE workdir (cwd + spool); the
        shared-dir plane runs every worker in the job workdir."""
        if not args.hosts:
            return workdir
        wdir = os.path.join(workdir, f"worker-{i}")
        os.makedirs(wdir, exist_ok=True)
        return wdir

    def worker_env(i: int) -> dict:
        we = dict(env)
        we["DSI_CHAOS_WORKER_INDEX"] = str(i)
        if args.hosts:
            we["DSI_NET_SPOOL"] = worker_dir(i)
        if slow is not None and i == slow[0]:
            we["DSI_SHARD_SLOW_S"] = slow[1]
        if fault is not None and i == fault[0]:
            point, _, step_n = fault[1].partition(":")
            we["DSI_FAULT_POINT"] = point
            if step_n:
                we["DSI_FAULT_STEP"] = step_n
        return we

    worker_cmd = [sys.executable, "-m", "dsi_tpu.cli.shardworker",
                  "--progress-s", str(args.progress_s)]
    t0 = time.monotonic()
    deadline = t0 + args.timeout
    workers = [subprocess.Popen(worker_cmd, env=worker_env(i),
                                cwd=worker_dir(i))
               for i in range(args.workers)]
    envs = [worker_env(i) for i in range(args.workers)]
    dirs = [worker_dir(i) for i in range(args.workers)]
    next_idx = args.workers
    # A worker that died crashed (chaos/fault kill) is respawned WITHOUT
    # its kill knobs — the grid's "the fleet recovers" arm; budget keeps
    # a truly broken setup from spinning.
    respawn_budget = max(8, 2 * len(plan))
    fetched: set = set()
    net_io: dict = {}  # driver-side fetch attribution (hosts mode)
    rc = 0

    def fetch_committed() -> bool:
        """--hosts: pull each newly committed shard's bytes from the
        winner's spool into the shared workdir the moment its location
        registers (the merge below then reads the exact same paths the
        shared-dir plane commits to).  A dead server means the only
        copy is gone: ``refetch_shard`` forgets the commit and a
        REPLACEMENT worker re-executes the producer — lingering
        workers left the request loop, so one is spawned (clean env:
        the chaos/fault knobs that killed the original stay off).
        Returns False when the respawn budget is exhausted."""
        nonlocal next_idx, respawn_budget
        import zlib

        from dsi_tpu.net.fetch import (FetchFailure, FetchPipeline,
                                       fetch_partition)
        from dsi_tpu.utils.atomicio import atomic_write

        todo = [(sid, loc) for sid, loc in
                sorted(coord.final_locations().items())
                if sid not in fetched]
        if not todo:
            return True

        def commit(sid, a, name, crc, raw) -> None:
            if crc and zlib.crc32(raw) != crc:
                raise FetchFailure(sid, a, name,
                                   ValueError("crc mismatch"))
            with atomic_write(os.path.join(workdir,
                                           f"mr-shard-out-{sid}"),
                              mode="wb") as f:
                f.write(raw)
            fetched.add(sid)

        def reexecute(sid, e) -> bool:
            nonlocal next_idx, respawn_budget
            print(f"shardrun: shard {sid} output fetch failed "
                  f"({e}); re-executing", file=sys.stderr)
            coord.refetch_shard(sid)
            if respawn_budget <= 0:
                print("shardrun: workers failing repeatedly; "
                      "giving up", file=sys.stderr)
                return False
            respawn_budget -= 1
            i = next_idx
            next_idx += 1
            clean = {k: v for k, v in worker_env(i).items()
                     if k not in ("DSI_FAULT_POINT",
                                  "DSI_FAULT_STEP",
                                  "DSI_CHAOS_WORKER_KILL")}
            envs.append(clean)
            dirs.append(worker_dir(i))
            workers.append(subprocess.Popen(worker_cmd, env=clean,
                                            cwd=dirs[i]))
            return True

        window = cfg.net_fetch_window
        if window <= 1 or len(todo) == 1:
            for sid, (a, name, crc) in todo:
                try:
                    raw = fetch_partition(a, name, stats=net_io,
                                          timeout=cfg.net_fetch_timeout_s)
                    commit(sid, a, name, crc, raw)
                except FetchFailure as e:
                    return reexecute(sid, e)
            return True
        # Overlapped collection (ISSUE 18): prefetch the committed
        # shards' payloads while earlier ones CRC-check and write.
        locs = {sid: loc for sid, loc in todo}
        pipe = FetchPipeline(
            [(sid, a, name) for sid, (a, name, crc) in todo],
            window=window, stats=net_io,
            timeout=cfg.net_fetch_timeout_s)
        try:
            for sid, raw in pipe:
                a, name, crc = locs[sid]
                commit(sid, a, name, crc, raw)
        except FetchFailure as e:
            return reexecute(e.task, e)
        return True

    try:
        while True:
            if args.hosts and not fetch_committed():
                rc = 1
                break
            if group is not None and args.kill_leader_after > 0 \
                    and failover is None \
                    and time.monotonic() - t0 >= args.kill_leader_after:
                print("shardrun: chaos: kill -9 the leader replica",
                      file=sys.stderr)
                from dsi_tpu.mr import rpc as _rpc

                try:
                    failover = group.kill_leader()
                except _rpc.CoordinatorGone as e:
                    print(f"shardrun: failover FAILED: {e}",
                          file=sys.stderr)
                    rc = 1
                    break
                print(f"shardrun: failover in "
                      f"{failover['failover_s']}s (term "
                      f"{failover['old_term']} -> "
                      f"{failover['new_term']}, leader "
                      f"{failover['killed_index']} -> "
                      f"{failover['new_index']})", file=sys.stderr)
            if coord.done() and (not args.hosts
                                 or len(fetched) == len(plan)
                                 or coord.spec_stats()["job_failed"]):
                break
            if time.monotonic() > deadline:
                print("shardrun: job exceeded --timeout; killing",
                      file=sys.stderr)
                rc = 1
                break
            for i, w in enumerate(workers):
                if w.poll() is not None and w.returncode != 0 \
                        and not coord.done():
                    if respawn_budget <= 0:
                        print("shardrun: workers failing repeatedly; "
                              "giving up", file=sys.stderr)
                        rc = 1
                        break
                    respawn_budget -= 1
                    clean = {k: v for k, v in envs[i].items()
                             if k not in ("DSI_FAULT_POINT",
                                          "DSI_FAULT_STEP",
                                          "DSI_CHAOS_WORKER_KILL")}
                    workers[i] = subprocess.Popen(worker_cmd, env=clean,
                                                  cwd=dirs[i])
            if rc:
                break
            time.sleep(0.1)
    finally:
        if group is not None:
            try:
                run_stats = coord.spec_stats()
            except Exception as e:  # noqa: BLE001 — group dead late
                print(f"shardrun: replica group unreachable at exit: "
                      f"{e}", file=sys.stderr)
                run_stats = {"job_failed": True, "shards": len(plan)}
                rc = rc or 1
        else:
            run_stats = coord.spec_stats()
        if args.hosts:
            run_stats.update(coord.net_stats())
            # The shard plane's only remote reads are the DRIVER's
            # output fetches — fold their attribution in.
            for k in ("net_fetches", "net_local_reads", "net_bytes_raw",
                      "net_bytes_wire", "net_fetch_failures"):
                run_stats[k] = run_stats.get(k, 0) + net_io.get(k, 0)
            for k in ("net_fetch_wait_s", "net_overlap_s"):
                run_stats[k] = round(run_stats.get(k, 0.0)
                                     + net_io.get(k, 0.0), 6)
            run_stats["net_prefetch_window"] = max(
                run_stats.get("net_prefetch_window", 0),
                net_io.get("net_prefetch_window", 0),
                cfg.net_fetch_window)
            wire = run_stats["net_bytes_wire"]
            run_stats["net_ratio"] = round(
                run_stats["net_bytes_raw"] / wire, 3) if wire else 0.0
        run_stats["wall_s"] = round(time.monotonic() - t0, 3)
        if group is not None:
            run_stats["replicas"] = args.replicas
            run_stats["replica_kills"] = group.kills
            if failover is not None:
                run_stats["replica_failover_s"] = failover["failover_s"]
                run_stats["replica_old_term"] = failover["old_term"]
                run_stats["replica_new_term"] = failover["new_term"]
        # A re-split shard commits as SUB-RANGE files, not one full-
        # range file: the coordinator knows the committed layout.
        if group is not None:
            out_paths = []
            if rc == 0 and not run_stats.get("job_failed"):
                try:
                    out_paths = coord.final_outputs()
                except Exception as e:  # noqa: BLE001
                    print(f"shardrun: could not read final outputs "
                          f"from the group: {e}", file=sys.stderr)
                    rc = 1
        else:
            out_paths = coord.final_outputs()
        coord.close()
        for w in workers:
            if w.poll() is None:
                w.terminate()
        for w in workers:
            try:
                w.wait(timeout=10)
            except subprocess.TimeoutExpired:
                w.kill()

    if rc == 0 and run_stats.get("job_failed"):
        print("shardrun: job failed (shard attempts exhausted)",
              file=sys.stderr)
        rc = 1

    merged_path = os.path.join(workdir, args.out)
    if rc == 0 and args.hosts:
        # Share-nothing audit: the ONLY job artifacts in the shared
        # workdir must be the ones the DRIVER fetched and wrote — a
        # worker-written mr-* / .part / .shards entry here means some
        # path escaped the private per-worker dirs and the run silently
        # leaned on the shared-directory assumption again.
        expect = {f"mr-shard-out-{sid}" for sid in fetched}
        leaked = [n for n in os.listdir(workdir)
                  if (n.startswith("mr-") or n.endswith(".part")
                      or n == ".shards")
                  and n not in expect and n != args.out]
        if leaked:
            print("shardrun: SHARE-NOTHING VIOLATION: worker artifacts "
                  f"in shared workdir: {sorted(leaked)[:8]}",
                  file=sys.stderr)
            rc = 1
    if rc == 0:
        from dsi_tpu.utils.atomicio import atomic_write

        payloads = []
        for path in out_paths:
            try:
                with open(path, "rb") as f:
                    payloads.append(f.read())
            except OSError as e:
                print(f"shardrun: missing committed shard output: {e}",
                      file=sys.stderr)
                rc = 1
                break
        if rc == 0:
            merged = (sh.merge_grep(payloads) if args.engine == "grep"
                      else sh.merge_wordcount(payloads))
            with atomic_write(merged_path, mode="wb") as f:
                f.write(merged)
            run_stats["merged_bytes"] = len(merged)
            # Every shard committed durably: the checkpoint chains are
            # dead weight now (a resume keys off the journal, which
            # says there is nothing left to run).
            import shutil

            shutil.rmtree(os.path.join(workdir, ".shards"),
                          ignore_errors=True)
            if args.hosts:
                # Spools served their purpose once the merge is durable.
                for d in dirs:
                    shutil.rmtree(d, ignore_errors=True)

    if args.stats_json:
        # dsicheck: allow[raw-write] bench/CI parse surface, not durable state
        with open(args.stats_json, "w", encoding="utf-8") as f:
            json.dump(run_stats, f, sort_keys=True, indent=1)
    if args.trace_dir:
        from dsi_tpu.obs import flush_tracing, trace_event

        trace_event("shardrun.exit", rc=rc,
                    backups=run_stats.get("backup_dispatches"),
                    commits=run_stats.get("commits"))
        flush_tracing()
    print(f"shardrun: {len(plan)} shards, "
          f"{run_stats.get('commits', 0)} commits, "
          f"{run_stats.get('backup_dispatches', 0)} backups, "
          f"{run_stats.get('requeues', 0)} requeues, "
          f"{run_stats.get('duplicate_commits', 0)} duplicate commits, "
          f"wall {run_stats.get('wall_s')}s", file=sys.stderr)
    if run_stats.get("resplits"):
        print(f"shardrun: {run_stats['resplits']} resplits -> "
              f"{run_stats.get('subshard_dispatches', 0)} sub-shard "
              f"dispatches, {run_stats.get('subshard_commits', 0)} "
              f"sub commits, {run_stats.get('split_shards', 0)} shards "
              f"resolved split", file=sys.stderr)
    if rc != 0:
        return rc

    if args.check:
        if args.engine == "grep":
            from dsi_tpu.parallel.grepstream import grep_host_oracle

            # format_grep drops topk exactly like merge_grep, so the
            # oracle bytes and the merged bytes share one shape.
            want = sh.format_grep(grep_host_oracle(
                sh.read_stream_range(files, 0,
                                     sh.stream_total_bytes(files)),
                args.pattern))
        else:
            want = sh.format_wordcount_counts(sh.wordcount_host_oracle(
                sh.read_stream_range(files, 0,
                                     sh.stream_total_bytes(files))))
        with open(merged_path, "rb") as f:
            got = f.read()
        if got != want:
            print("shardrun: PARITY FAILURE vs sequential oracle",
                  file=sys.stderr)
            return 2
        print("shardrun: parity OK", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
