"""Streaming grep entry point — the grep engine on the shared pipeline
core (``parallel/grepstream.py``) as a user-facing command, mirroring
``wcstream``'s knobs.

Files become one bounded-memory block stream cut at newline boundaries;
every stream step runs ONE compiled literal-match program (the
``ops/grepk.py`` shifted-compare idiom) whose ``l_cap`` escalation is
the pipeline's sticky-rung replay, and the result is the whole-stream
match statistics: total/matched lines, occurrences, the per-line
match-count histogram, and the exact top-k lines by occurrence count.
``--device-accumulate`` keeps the histogram and the top-k candidate
table ON DEVICE (``dsi_tpu/device/topk.py``), pulling every
``--sync-every`` steps instead of every step.

Falls back to the host oracle scan when the engine declines (non-literal
pattern, or a line wider than ``--chunk-bytes``) — correctness never
depends on the device kernel.

Usage:
    python -m dsi_tpu.cli.grepstream --pattern PAT [--chunk-bytes B]
        [--devices D] [--pipeline-depth D] [--device-accumulate]
        [--sync-every K] [--checkpoint-dir DIR] [--checkpoint-every K]
        [--ckpt-async] [--ckpt-delta]
        [--resume] [--topk K] [--aot] [--stats] [--check]
        inputfiles...
"""

from __future__ import annotations

import argparse
import os
import sys


def _positive_int(s: str) -> int:
    v = int(s)
    if v < 1:
        raise argparse.ArgumentTypeError(f"must be >= 1, got {v}")
    return v


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("files", nargs="+")
    p.add_argument("--pattern", default=None,
                   help="literal pattern (default: DSI_GREP_PATTERN)")
    p.add_argument("--chunk-bytes", type=_positive_int, default=1 << 20,
                   help="per-device bytes per stream step (also the line "
                        "length ceiling: a wider line routes the stream "
                        "to the host scan)")
    p.add_argument("--devices", type=int, default=None,
                   help="mesh size (default: all local devices)")
    p.add_argument("--pipeline-depth", type=_positive_int, default=None,
                   help="in-flight stream steps (default: "
                        "DSI_STREAM_PIPELINE_DEPTH or 2; 1 = synchronous)")
    p.add_argument("--device-accumulate", action="store_true",
                   help="fold histograms + top-k candidates into the "
                        "device-resident service (dsi_tpu/device/topk.py) "
                        "and pull only every --sync-every steps — results "
                        "are bit-identical")
    p.add_argument("--sync-every", type=_positive_int, default=None,
                   help="folds between host pulls with --device-accumulate "
                        "(default: DSI_STREAM_SYNC_EVERY or 8)")
    p.add_argument("--mesh-shards", type=int, default=None,
                   help="mesh-shard the device services across N shards "
                        "(ihash %% N routing inside the fold, per-shard "
                        "widens, pre-merged histogram pulls; implies "
                        "--device-accumulate; default: "
                        "DSI_STREAM_MESH_SHARDS or 0 = off)")
    p.add_argument("--checkpoint-dir", default=None,
                   help="enable crash-resume checkpoints (dsi_tpu/ckpt)")
    p.add_argument("--ckpt-async", action="store_true", default=None,
                   dest="ckpt_async",
                   help="overlap checkpoint commits with the pipeline "
                        "(env DSI_STREAM_CKPT_ASYNC)")
    p.add_argument("--ckpt-delta", action="store_true", default=None,
                   dest="ckpt_delta",
                   help="incremental checkpoints (env "
                        "DSI_STREAM_CKPT_DELTA)")
    p.add_argument("--checkpoint-every", type=_positive_int, default=None,
                   help="confirmed steps between checkpoints (default: "
                        "DSI_STREAM_CKPT_EVERY or 32)")
    p.add_argument("--resume", action="store_true",
                   help="resume from the newest valid checkpoint in "
                        "--checkpoint-dir; results are bit-identical to "
                        "an uninterrupted run")
    p.add_argument("--topk", type=_positive_int, default=16,
                   help="top-k lines by occurrence count to report")
    p.add_argument("--aot", action="store_true",
                   help="route the device services through the persistent "
                        "AOT executable cache (single-device axon runs "
                        "load serialized executables; the step programs "
                        "always go through the cache)")
    p.add_argument("--stats", action="store_true",
                   help="print the pipeline_stats dict (phase walls + "
                        "fold/sync/widen/snapshot counters) to stderr")
    p.add_argument("--check", action="store_true",
                   help="run the host oracle scan over the same stream "
                        "and verify parity (exit 2 on mismatch)")
    p.add_argument("--ingest-readers", type=int, default=None,
                   dest="ingest_readers",
                   help="parallel mmap'd input readers with readahead "
                        "(utils/ioread.py): N threads fill blocks ahead "
                        "of the batcher; cursors/checkpoints stay "
                        "byte-exact (default: DSI_INGEST_READERS or 0 "
                        "= inline reads)")
    p.add_argument("--trace-dir", default=None,
                   help="write this run's unified trace (dsi_tpu/obs): "
                        "Perfetto trace.json + trace.jsonl event log; "
                        "render with scripts/tracecat.py")
    p.add_argument("--statusz-port", type=int, default=None,
                   help="serve live telemetry on 127.0.0.1:PORT — "
                        "/statusz + /metrics (0 = pick a free port; "
                        "default off, env DSI_STATUSZ_PORT); arms the "
                        "stall watchdog and the live.jsonl ring")
    args = p.parse_args(argv)

    if args.resume and not args.checkpoint_dir:
        p.error("--resume requires --checkpoint-dir")

    if args.trace_dir:
        from dsi_tpu.obs import configure_tracing

        configure_tracing(trace_dir=args.trace_dir)

    if args.statusz_port is not None or os.environ.get("DSI_STATUSZ_PORT"):
        from dsi_tpu.obs.live import start_from_args

        start_from_args(args.statusz_port, live_dir=args.trace_dir)

    pattern = args.pattern or os.environ.get("DSI_GREP_PATTERN")
    if not pattern:
        print("grepstream: no pattern (--pattern or DSI_GREP_PATTERN)",
              file=sys.stderr)
        return 1

    from dsi_tpu.utils.platformpin import pin_platform_from_env

    pin_platform_from_env()

    from dsi_tpu.parallel.grepstream import grep_host_oracle, grep_streaming
    from dsi_tpu.parallel.shuffle import default_mesh
    from dsi_tpu.parallel.streaming import stream_files
    from dsi_tpu.utils.ioread import open_blocks

    from dsi_tpu.ckpt import CheckpointMismatch

    mesh = default_mesh(args.devices)
    pstats: dict = {}
    try:
        res = grep_streaming(
            open_blocks(args.files, readers=args.ingest_readers),
            pattern, mesh=mesh,
            chunk_bytes=args.chunk_bytes, depth=args.pipeline_depth,
            aot=args.aot, device_accumulate=args.device_accumulate,
            sync_every=args.sync_every, mesh_shards=args.mesh_shards,
            topk=args.topk,
            checkpoint_dir=args.checkpoint_dir,
            checkpoint_every=args.checkpoint_every,
            checkpoint_async=args.ckpt_async,
            checkpoint_delta=args.ckpt_delta, resume=args.resume,
            pipeline_stats=pstats)
    except CheckpointMismatch as e:
        # A valid checkpoint for a DIFFERENT job (other pattern/shape):
        # refuse loudly rather than corrupt or overwrite the lineage.
        print(f"grepstream: {e}", file=sys.stderr)
        return 1
    if args.resume and not pstats.get("resume_cursor"):
        # Legitimate when the crash predated the first checkpoint, but a
        # typo'd --checkpoint-dir looks identical — never replay a whole
        # stream silently.
        print("grepstream: --resume found no usable checkpoint in "
              f"{args.checkpoint_dir}; started from scratch",
              file=sys.stderr)
    if args.stats:
        print(f"grepstream: pipeline_stats={pstats}", file=sys.stderr)
    if args.trace_dir:
        from dsi_tpu.obs import flush_tracing_report

        flush_tracing_report(args.trace_dir, "grepstream")
    host_path = res is None
    if host_path:
        try:
            res = grep_host_oracle(stream_files(args.files), pattern,
                                   topk=args.topk)
        except UnicodeEncodeError:
            print("grepstream: pattern is not plain ASCII; use the "
                  "tpu_grep MR app for regex tiers", file=sys.stderr)
            return 1
        print("grepstream: stream needed the host path; ran the host scan",
              file=sys.stderr)

    print(f"lines={res.lines} matched={res.matched} "
          f"occurrences={res.occurrences}")
    print("hist=" + ",".join(str(h) for h in res.hist))
    for line_no, occ in res.topk:
        print(f"top line={line_no} occ={occ}")

    if args.check and not host_path:
        want = grep_host_oracle(stream_files(args.files), pattern,
                                topk=args.topk)
        if res != want:
            print("grepstream: PARITY FAILURE vs host oracle",
                  file=sys.stderr)
            return 2
        print("grepstream: parity OK", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
