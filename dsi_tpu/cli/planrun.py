"""Multi-stage dataflow plan runner — chained jobs, no host round-trip.

Runs one of the canonical plans (``dsi_tpu/plan``) end to end: stages
execute as resumable step objects and the intermediate between them
stays DEVICE-RESIDENT (stage N+1's upload is stage N's output —
``device/relay.py``), against the ``--staged`` baseline that
materializes every intermediate through the host the way the 6.5840
contract does.  Stage boundaries are durable commit points
(``--checkpoint-dir``): a crash anywhere in the chain resumes from the
last COMPLETED stage (``--resume``), never from zero.

Chains:
  grep-wc   — grep → word count over exactly the matching lines;
              writes the word counts as mr-out-<r> files in --workdir.
  grep-grep — grep → grep: a narrowing filter cascade (lines with
              --pattern, of those, lines with --pattern2); writes
              plan-grep.json with the final match counts.
  wc-topk   — word count → top-k highest-count words (host reduction
              over the full table); writes plan-topk.json.
  indexer   — indexer → df-top-k (k-row snapshot off the resident df
              table) → per-term postings join; writes plan-join.json.

Elastic execution (ISSUE 16): ``--pipeline`` overlaps a grep→wordcount
pair (the wordcount consumes relay buffers as they SEAL while the grep
is still producing; strict/staged stays the bit-parity oracle);
``--stage-shards K`` runs a file-backed source stage as K concurrent
newline-aligned shard attempts merged through the deterministic shard
codecs.

Usage:
    python -m dsi_tpu.cli.planrun --chain grep-wc --pattern PAT
        [--pattern2 PAT] [--pipeline] [--stage-shards K]
        [--staged] [--chunk-bytes B] [--devices D] [--pipeline-depth K]
        [--device-accumulate] [--sync-every K] [--mesh-shards N]
        [--nreduce N] [--u-cap U] [--topk K] [--aot]
        [--checkpoint-dir DIR] [--resume] [--workdir DIR] [--check]
        [--stats] [--stats-json FILE] [--trace-dir DIR] inputfiles...
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _positive_int(s: str) -> int:
    v = int(s)
    if v < 1:
        raise argparse.ArgumentTypeError(f"must be >= 1, got {v}")
    return v


def _plan_spec(args) -> dict:
    """The plan-rebuild spec (``plan.stagehost.build_plan`` input) this
    argv describes — the single source both the in-process paths and
    every ``--hosts`` stage host rebuild the plan from."""
    return {"chain": args.chain, "pattern": args.pattern,
            "pattern2": args.pattern2, "files": list(args.files),
            "chunk_bytes": args.chunk_bytes, "depth": args.pipeline_depth,
            "device_accumulate": args.device_accumulate,
            "sync_every": args.sync_every,
            "mesh_shards": args.mesh_shards, "aot": args.aot,
            "n_reduce": args.nreduce, "u_cap": args.u_cap,
            "topk": args.topk, "devices": args.devices}


def _run_hosts(args, spec: dict, mesh):
    """``--hosts``: every stage in its OWN process with a PRIVATE
    working directory; inter-stage bytes move ONLY over TCP (net-served
    plan relays, ISSUE 18).  Spawns one ``plan.stagehost`` per stage in
    topo order (each handed its deps' ``{addr, name, crc}`` from their
    ready files), then collects every stage's sealed payload over the
    stream transport to assemble the PlanResult.  Returns
    ``(PlanResult, stats_dict)``; raises RuntimeError on a stage
    failure or timeout."""
    import shutil
    import subprocess

    from dsi_tpu.obs import metrics_scope
    from dsi_tpu.plan.driver import PlanResult, _load_commit
    from dsi_tpu.plan.stagehost import build_plan, fetch_stage_payload
    from dsi_tpu.utils.atomicio import atomic_write

    plan = build_plan(spec)
    order = plan.ordered()
    sc = metrics_scope("plan")
    sc.update({"plan_stages": len(order), "plan_intermediate_bytes": 0,
               "plan_commit_bytes": 0, "plan_resumed_stages": 0,
               "plan_handoff": "net", "plan_pipelined": 0,
               "plan_stage_shards": max(0, args.stage_shards),
               "plan_overlap_s": 0.0, "plan_s": 0.0,
               "plan_stage_walls": {}})
    net_io = metrics_scope("net")
    os.makedirs(args.workdir, exist_ok=True)
    procs: list = []
    stage_dirs: list = []
    readies: dict = {}
    deadline = time.monotonic() + args.timeout
    try:
        for i, stage in enumerate(order):
            sdir = os.path.join(args.workdir, f"stage-{i}")
            os.makedirs(os.path.join(sdir, "spool"), exist_ok=True)
            stage_dirs.append(sdir)
            host_spec = {
                "plan": spec, "stage_index": i,
                "stage_shards": max(0, args.stage_shards),
                "spool": os.path.join(sdir, "spool"),
                "ready": os.path.join(sdir, "ready.json"),
                "deps": {d: {"addr": readies[d]["addr"],
                             "name": readies[d]["name"],
                             "crc": readies[d]["crc"]}
                         for d in stage.deps},
            }
            spec_path = os.path.join(sdir, "spec.json")
            with atomic_write(spec_path, mode="w") as f:
                json.dump(host_spec, f, sort_keys=True)
            # dsicheck: allow[raw-write] child console capture, not durable state
            logf = open(os.path.join(sdir, "stage.log"), "wb")
            proc = subprocess.Popen(
                [sys.executable, "-m", "dsi_tpu.plan.stagehost",
                 "--spec", spec_path],
                stdout=logf, stderr=subprocess.STDOUT,
                env=dict(os.environ))
            procs.append((proc, logf))
            ready_path = host_spec["ready"]
            while not os.path.exists(ready_path):
                if proc.poll() is not None:
                    raise RuntimeError(
                        f"stage host {i} ({stage.name}) exited "
                        f"rc={proc.returncode} before ready — see "
                        f"{sdir}/stage.log")
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        f"stage host {i} ({stage.name}) not ready "
                        f"within --timeout {args.timeout}s")
                time.sleep(0.05)
            with open(ready_path, "r", encoding="utf-8") as f:
                readies[stage.name] = json.load(f)
            r = readies[stage.name]
            sc["plan_stage_walls"][stage.name] = r.get("stage_wall_s", 0)
            sc["plan_s"] = round(sc["plan_s"]
                                 + float(r.get("stage_wall_s", 0)), 4)
            # The bytes a stage pulled from its predecessors ARE the
            # inter-stage intermediates — and they crossed only TCP.
            child_net = r.get("net") or {}
            sc["plan_intermediate_bytes"] += \
                int(child_net.get("net_bytes_raw", 0))
            for k, v in child_net.items():
                if k in ("net_ratio",):
                    continue
                if isinstance(v, (int, float)):
                    if k == "net_prefetch_window":
                        net_io[k] = max(int(net_io.get(k, 0) or 0),
                                        int(v))
                    else:
                        net_io[k] = type(v)(net_io.get(k, 0) or 0) + v
        # Share-nothing audit BEFORE any report artifact lands: sealed
        # stage payloads must exist ONLY in the private stage spools —
        # a payload-named file in the SHARED workdir means a stage
        # leaked its relay past the TCP boundary.
        leaked = [n for n in os.listdir(args.workdir)
                  if os.path.isfile(os.path.join(args.workdir, n))
                  and n.startswith("plan-") and n[5:6].isdigit()]
        if leaked:
            raise RuntimeError(
                f"share-nothing audit failed: stage payload(s) "
                f"{leaked} in shared workdir {args.workdir}")
        # Collect: every stage's sealed payload, over TCP, decoded by
        # the stage-commit codec — the same reconstruction the
        # checkpoint/resume path uses, so parity holds by construction.
        ctx = {}
        for i, stage in enumerate(order):
            r = readies[stage.name]
            arrays, meta = fetch_stage_payload(
                r["addr"], r["name"], int(r.get("crc", 0)),
                stats=net_io, timeout=args.timeout)
            ctx[stage.name] = _load_commit(plan, stage, meta, arrays,
                                           mesh, True, sc)
        for k in ("net_fetch_wait_s", "net_overlap_s"):
            if k in net_io:
                net_io[k] = round(float(net_io[k]), 6)
        sc.update(net_io)
        results = {name: out.result for name, out in ctx.items()}
        res = PlanResult(results, ctx[order[-1].name].result, sc)
    finally:
        for proc, logf in procs:
            if proc.poll() is None:
                proc.terminate()
                try:
                    proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait()
            logf.close()
    for sdir in stage_dirs:
        shutil.rmtree(sdir, ignore_errors=True)
    return res, dict(sc)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("files", nargs="+")
    p.add_argument("--chain",
                   choices=("grep-wc", "grep-grep", "wc-topk",
                            "indexer"),
                   default="grep-wc")
    p.add_argument("--pattern", default=None,
                   help="literal grep pattern (required for grep-wc "
                        "and grep-grep)")
    p.add_argument("--pattern2", default=None,
                   help="second-stage literal pattern (required for "
                        "grep-grep)")
    p.add_argument("--pipeline", action="store_true",
                   help="overlap a grep→wordcount pair: stage N+1 "
                        "consumes sealed relay buffers while stage N "
                        "still produces (chained mode only)")
    p.add_argument("--stage-shards", type=int, default=0,
                   help="run a file-backed source stage as K "
                        "concurrent shard attempts (0 = off)")
    p.add_argument("--staged", action="store_true",
                   help="run the HOST-materialization baseline: every "
                        "inter-stage intermediate is pulled to the host "
                        "and re-fed (the 6.5840 shape) — results are "
                        "bit-identical to the chained default")
    p.add_argument("--chunk-bytes", type=_positive_int, default=1 << 20)
    p.add_argument("--devices", type=int, default=None)
    p.add_argument("--pipeline-depth", type=_positive_int, default=None)
    p.add_argument("--device-accumulate", action="store_true")
    p.add_argument("--sync-every", type=_positive_int, default=None)
    p.add_argument("--mesh-shards", type=int, default=None)
    p.add_argument("--nreduce", type=_positive_int, default=10)
    p.add_argument("--u-cap", type=_positive_int, default=1 << 12)
    p.add_argument("--topk", type=_positive_int, default=16)
    p.add_argument("--aot", action="store_true")
    p.add_argument("--checkpoint-dir", default=None,
                   help="stage-manifest commits land here: each "
                        "completed stage writes a durable manifest "
                        "(ckpt/store.py discipline) — see --resume")
    p.add_argument("--resume", action="store_true",
                   help="skip every stage whose manifest verifies and "
                        "continue from the last completed stage's "
                        "commit point")
    p.add_argument("--workdir", default=".")
    p.add_argument("--hosts", action="store_true",
                   help="net-served plan relays: run every stage in its "
                        "OWN process with a PRIVATE working directory; "
                        "inter-stage bytes move only over TCP (the "
                        "share-nothing harness, audited)")
    p.add_argument("--timeout", type=float, default=600.0,
                   help="--hosts per-run deadline: seconds to wait for "
                        "all stage hosts to come ready")
    p.add_argument("--check", action="store_true",
                   help="also run the OTHER handoff mode (staged vs "
                        "chained) in-process and verify the results "
                        "are identical")
    p.add_argument("--stats", action="store_true")
    p.add_argument("--stats-json", default=None,
                   help="write the plan stats scope (plan_* keys) as "
                        "JSON there — the bench row's parse surface")
    p.add_argument("--trace-dir", default=None)
    args = p.parse_args(argv)

    if args.resume and not args.checkpoint_dir:
        p.error("--resume requires --checkpoint-dir")
    if args.chain in ("grep-wc", "grep-grep") and not args.pattern:
        p.error(f"--chain {args.chain} requires --pattern")
    if args.chain == "grep-grep" and not args.pattern2:
        p.error("--chain grep-grep requires --pattern2")
    if args.pipeline and args.staged:
        p.error("--pipeline is chained-mode only (staged execution "
                "stays strictly sequential: it is the parity oracle)")
    if args.hosts and args.pipeline:
        p.error("--hosts runs stages in separate processes; the "
                "in-process relay overlap (--pipeline) cannot cross "
                "them")
    if args.hosts and (args.checkpoint_dir or args.resume):
        p.error("--hosts has its own commit surface (sealed stage "
                "payloads); --checkpoint-dir/--resume are the "
                "in-process stage-manifest path")
    if args.hosts and args.staged:
        p.error("--hosts is its own handoff mode (net); --staged is "
                "the in-process host-materialization baseline")

    if args.trace_dir:
        from dsi_tpu.obs import configure_tracing

        configure_tracing(trace_dir=args.trace_dir)

    from dsi_tpu.utils.platformpin import pin_platform_from_env

    pin_platform_from_env()

    from dsi_tpu.ckpt import CheckpointMismatch
    from dsi_tpu.parallel.shuffle import default_mesh
    from dsi_tpu.plan import PlanHostPath, run_plan
    from dsi_tpu.plan.stagehost import build_plan

    mesh = default_mesh(args.devices)
    spec = _plan_spec(args)

    def build():
        return build_plan(spec)

    stats: dict = {}
    try:
        if args.hosts:
            res, stats = _run_hosts(args, spec, mesh)
        else:
            res = run_plan(build(), mesh=mesh, staged=args.staged,
                           checkpoint_dir=args.checkpoint_dir,
                           resume=args.resume, pipelined=args.pipeline,
                           stage_shards=args.stage_shards, stats=stats)
    except CheckpointMismatch as e:
        print(f"planrun: {e}", file=sys.stderr)
        return 1
    except PlanHostPath as e:
        # The chain contract is device-resident intermediates; a
        # host-path input breaks it loudly — run the standalone engines
        # (wcstream/grepstream) for such inputs.
        print(f"planrun: {e}", file=sys.stderr)
        return 1
    except RuntimeError as e:
        # --hosts orchestration failures (stage host died, deadline,
        # share-nothing audit) — loud, nonzero, no partial artifacts.
        if not args.hosts:
            raise
        print(f"planrun: {e}", file=sys.stderr)
        return 1

    if args.resume:
        print(f"planrun: resumed past "
              f"{stats.get('plan_resumed_stages', 0)} committed "
              f"stage(s)", file=sys.stderr)
    for name, wall in stats.get("plan_stage_walls", {}).items():
        print(f"planrun: stage {name}: {wall}s", file=sys.stderr)
    print(f"planrun: handoff={stats.get('plan_handoff')} "
          f"intermediate_bytes={stats.get('plan_intermediate_bytes')} "
          f"commit_bytes={stats.get('plan_commit_bytes')}",
          file=sys.stderr)

    os.makedirs(args.workdir, exist_ok=True)
    if args.chain == "grep-wc":
        from dsi_tpu.parallel.shuffle import write_partitioned_output

        g = res.results["grep"]
        print(f"planrun: grep lines={g.lines} matched={g.matched} "
              f"occurrences={g.occurrences}", file=sys.stderr)
        write_partitioned_output(res.final, args.nreduce, args.workdir)
    elif args.chain == "grep-grep":
        stages = {name: {"lines": r.lines, "matched": r.matched,
                         "occurrences": r.occurrences}
                  for name, r in res.results.items()}
        path = os.path.join(args.workdir, "plan-grep.json")
        # dsicheck: allow[raw-write] report artifact, not durable state
        with open(path, "w", encoding="utf-8") as f:
            json.dump(stages, f, sort_keys=True, indent=1)
        g2 = res.final
        print(f"planrun: cascade matched={g2.matched} "
              f"occurrences={g2.occurrences} -> {path}", file=sys.stderr)
    elif args.chain == "wc-topk":
        path = os.path.join(args.workdir, "plan-topk.json")
        # dsicheck: allow[raw-write] report artifact, not durable state
        with open(path, "w", encoding="utf-8") as f:
            json.dump({"topk": [[int(c), w] for c, w in res.final]},
                      f, sort_keys=True, indent=1)
        print(f"planrun: top-{len(res.final)} words -> {path}",
              file=sys.stderr)
    else:
        out = {w: {"df": df, "part": part, "docs": list(docs)}
               for w, (df, part, docs) in res.final.items()}
        path = os.path.join(args.workdir, "plan-join.json")
        # dsicheck: allow[raw-write] report artifact, not durable state
        with open(path, "w", encoding="utf-8") as f:
            json.dump({"topk": [[c, w] for c, w in
                                res.results.get("dftopk", ())],
                       "join": out}, f, sort_keys=True, indent=1)
        print(f"planrun: join of {len(out)} terms -> {path}",
              file=sys.stderr)

    if args.stats:
        print(f"planrun: plan_stats={stats}", file=sys.stderr)
    if args.stats_json:
        # dsicheck: allow[raw-write] bench parse surface, not durable state
        with open(args.stats_json, "w", encoding="utf-8") as f:
            json.dump({k: v for k, v in stats.items()}, f, default=str)
    if args.trace_dir:
        from dsi_tpu.obs import flush_tracing_report

        flush_tracing_report(args.trace_dir, "planrun")

    if args.check:
        # The twin runs the OTHER handoff mode under the SAME shard
        # fan-out: stage-sharded grep merges zero the order-sensitive
        # topk sample, so parity only holds shard-geometry-to-like.
        # Against --hosts the twin is the in-process chained run — the
        # net-served relays must reproduce it bit-identically.
        twin_staged = False if args.hosts else not args.staged
        twin = run_plan(build(), mesh=mesh, staged=twin_staged,
                        stage_shards=args.stage_shards)
        modes = ("hosts vs chained" if args.hosts
                 else "chained vs staged")
        ok = twin.final == res.final
        if args.chain == "grep-wc":
            ok = ok and twin.results["grep"] == res.results["grep"]
        elif args.chain == "grep-grep":
            ok = ok and twin.results == res.results
        if not ok:
            print(f"planrun: PARITY FAILURE {modes}", file=sys.stderr)
            return 2
        print(f"planrun: parity OK ({modes})", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
