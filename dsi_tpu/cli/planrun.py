"""Multi-stage dataflow plan runner — chained jobs, no host round-trip.

Runs one of the canonical plans (``dsi_tpu/plan``) end to end: stages
execute as resumable step objects and the intermediate between them
stays DEVICE-RESIDENT (stage N+1's upload is stage N's output —
``device/relay.py``), against the ``--staged`` baseline that
materializes every intermediate through the host the way the 6.5840
contract does.  Stage boundaries are durable commit points
(``--checkpoint-dir``): a crash anywhere in the chain resumes from the
last COMPLETED stage (``--resume``), never from zero.

Chains:
  grep-wc   — grep → word count over exactly the matching lines;
              writes the word counts as mr-out-<r> files in --workdir.
  grep-grep — grep → grep: a narrowing filter cascade (lines with
              --pattern, of those, lines with --pattern2); writes
              plan-grep.json with the final match counts.
  wc-topk   — word count → top-k highest-count words (host reduction
              over the full table); writes plan-topk.json.
  indexer   — indexer → df-top-k (k-row snapshot off the resident df
              table) → per-term postings join; writes plan-join.json.

Elastic execution (ISSUE 16): ``--pipeline`` overlaps a grep→wordcount
pair (the wordcount consumes relay buffers as they SEAL while the grep
is still producing; strict/staged stays the bit-parity oracle);
``--stage-shards K`` runs a file-backed source stage as K concurrent
newline-aligned shard attempts merged through the deterministic shard
codecs.

Usage:
    python -m dsi_tpu.cli.planrun --chain grep-wc --pattern PAT
        [--pattern2 PAT] [--pipeline] [--stage-shards K]
        [--staged] [--chunk-bytes B] [--devices D] [--pipeline-depth K]
        [--device-accumulate] [--sync-every K] [--mesh-shards N]
        [--nreduce N] [--u-cap U] [--topk K] [--aot]
        [--checkpoint-dir DIR] [--resume] [--workdir DIR] [--check]
        [--stats] [--stats-json FILE] [--trace-dir DIR] inputfiles...
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _positive_int(s: str) -> int:
    v = int(s)
    if v < 1:
        raise argparse.ArgumentTypeError(f"must be >= 1, got {v}")
    return v


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("files", nargs="+")
    p.add_argument("--chain",
                   choices=("grep-wc", "grep-grep", "wc-topk",
                            "indexer"),
                   default="grep-wc")
    p.add_argument("--pattern", default=None,
                   help="literal grep pattern (required for grep-wc "
                        "and grep-grep)")
    p.add_argument("--pattern2", default=None,
                   help="second-stage literal pattern (required for "
                        "grep-grep)")
    p.add_argument("--pipeline", action="store_true",
                   help="overlap a grep→wordcount pair: stage N+1 "
                        "consumes sealed relay buffers while stage N "
                        "still produces (chained mode only)")
    p.add_argument("--stage-shards", type=int, default=0,
                   help="run a file-backed source stage as K "
                        "concurrent shard attempts (0 = off)")
    p.add_argument("--staged", action="store_true",
                   help="run the HOST-materialization baseline: every "
                        "inter-stage intermediate is pulled to the host "
                        "and re-fed (the 6.5840 shape) — results are "
                        "bit-identical to the chained default")
    p.add_argument("--chunk-bytes", type=_positive_int, default=1 << 20)
    p.add_argument("--devices", type=int, default=None)
    p.add_argument("--pipeline-depth", type=_positive_int, default=None)
    p.add_argument("--device-accumulate", action="store_true")
    p.add_argument("--sync-every", type=_positive_int, default=None)
    p.add_argument("--mesh-shards", type=int, default=None)
    p.add_argument("--nreduce", type=_positive_int, default=10)
    p.add_argument("--u-cap", type=_positive_int, default=1 << 12)
    p.add_argument("--topk", type=_positive_int, default=16)
    p.add_argument("--aot", action="store_true")
    p.add_argument("--checkpoint-dir", default=None,
                   help="stage-manifest commits land here: each "
                        "completed stage writes a durable manifest "
                        "(ckpt/store.py discipline) — see --resume")
    p.add_argument("--resume", action="store_true",
                   help="skip every stage whose manifest verifies and "
                        "continue from the last completed stage's "
                        "commit point")
    p.add_argument("--workdir", default=".")
    p.add_argument("--check", action="store_true",
                   help="also run the OTHER handoff mode (staged vs "
                        "chained) in-process and verify the results "
                        "are identical")
    p.add_argument("--stats", action="store_true")
    p.add_argument("--stats-json", default=None,
                   help="write the plan stats scope (plan_* keys) as "
                        "JSON there — the bench row's parse surface")
    p.add_argument("--trace-dir", default=None)
    args = p.parse_args(argv)

    if args.resume and not args.checkpoint_dir:
        p.error("--resume requires --checkpoint-dir")
    if args.chain in ("grep-wc", "grep-grep") and not args.pattern:
        p.error(f"--chain {args.chain} requires --pattern")
    if args.chain == "grep-grep" and not args.pattern2:
        p.error("--chain grep-grep requires --pattern2")
    if args.pipeline and args.staged:
        p.error("--pipeline is chained-mode only (staged execution "
                "stays strictly sequential: it is the parity oracle)")

    if args.trace_dir:
        from dsi_tpu.obs import configure_tracing

        configure_tracing(trace_dir=args.trace_dir)

    from dsi_tpu.utils.platformpin import pin_platform_from_env

    pin_platform_from_env()

    from dsi_tpu.ckpt import CheckpointMismatch
    from dsi_tpu.parallel.shuffle import default_mesh
    from dsi_tpu.plan import (PlanHostPath, grep_cascade_plan,
                              grep_wordcount_plan, indexer_join_plan,
                              run_plan, wordcount_topk_plan)

    mesh = default_mesh(args.devices)
    defaults = dict(chunk_bytes=args.chunk_bytes,
                    depth=args.pipeline_depth,
                    device_accumulate=args.device_accumulate,
                    sync_every=args.sync_every,
                    mesh_shards=args.mesh_shards, aot=args.aot,
                    n_reduce=args.nreduce, u_cap=args.u_cap,
                    topk=args.topk)

    def build():
        if args.chain == "grep-wc":
            return grep_wordcount_plan(args.pattern, paths=args.files,
                                       **defaults)
        if args.chain == "grep-grep":
            return grep_cascade_plan(args.pattern, args.pattern2,
                                     paths=args.files, **defaults)
        if args.chain == "wc-topk":
            return wordcount_topk_plan(args.topk, paths=args.files,
                                       **defaults)
        docs = []
        for path in args.files:
            with open(path, "rb") as f:
                docs.append(f.read())
        return indexer_join_plan(docs, **defaults)  # topk rides defaults

    stats: dict = {}
    try:
        res = run_plan(build(), mesh=mesh, staged=args.staged,
                       checkpoint_dir=args.checkpoint_dir,
                       resume=args.resume, pipelined=args.pipeline,
                       stage_shards=args.stage_shards, stats=stats)
    except CheckpointMismatch as e:
        print(f"planrun: {e}", file=sys.stderr)
        return 1
    except PlanHostPath as e:
        # The chain contract is device-resident intermediates; a
        # host-path input breaks it loudly — run the standalone engines
        # (wcstream/grepstream) for such inputs.
        print(f"planrun: {e}", file=sys.stderr)
        return 1

    if args.resume:
        print(f"planrun: resumed past "
              f"{stats.get('plan_resumed_stages', 0)} committed "
              f"stage(s)", file=sys.stderr)
    for name, wall in stats.get("plan_stage_walls", {}).items():
        print(f"planrun: stage {name}: {wall}s", file=sys.stderr)
    print(f"planrun: handoff={stats.get('plan_handoff')} "
          f"intermediate_bytes={stats.get('plan_intermediate_bytes')} "
          f"commit_bytes={stats.get('plan_commit_bytes')}",
          file=sys.stderr)

    os.makedirs(args.workdir, exist_ok=True)
    if args.chain == "grep-wc":
        from dsi_tpu.parallel.shuffle import write_partitioned_output

        g = res.results["grep"]
        print(f"planrun: grep lines={g.lines} matched={g.matched} "
              f"occurrences={g.occurrences}", file=sys.stderr)
        write_partitioned_output(res.final, args.nreduce, args.workdir)
    elif args.chain == "grep-grep":
        stages = {name: {"lines": r.lines, "matched": r.matched,
                         "occurrences": r.occurrences}
                  for name, r in res.results.items()}
        path = os.path.join(args.workdir, "plan-grep.json")
        # dsicheck: allow[raw-write] report artifact, not durable state
        with open(path, "w", encoding="utf-8") as f:
            json.dump(stages, f, sort_keys=True, indent=1)
        g2 = res.final
        print(f"planrun: cascade matched={g2.matched} "
              f"occurrences={g2.occurrences} -> {path}", file=sys.stderr)
    elif args.chain == "wc-topk":
        path = os.path.join(args.workdir, "plan-topk.json")
        # dsicheck: allow[raw-write] report artifact, not durable state
        with open(path, "w", encoding="utf-8") as f:
            json.dump({"topk": [[int(c), w] for c, w in res.final]},
                      f, sort_keys=True, indent=1)
        print(f"planrun: top-{len(res.final)} words -> {path}",
              file=sys.stderr)
    else:
        out = {w: {"df": df, "part": part, "docs": list(docs)}
               for w, (df, part, docs) in res.final.items()}
        path = os.path.join(args.workdir, "plan-join.json")
        # dsicheck: allow[raw-write] report artifact, not durable state
        with open(path, "w", encoding="utf-8") as f:
            json.dump({"topk": [[c, w] for c, w in
                                res.results.get("dftopk", ())],
                       "join": out}, f, sort_keys=True, indent=1)
        print(f"planrun: join of {len(out)} terms -> {path}",
              file=sys.stderr)

    if args.stats:
        print(f"planrun: plan_stats={stats}", file=sys.stderr)
    if args.stats_json:
        # dsicheck: allow[raw-write] bench parse surface, not durable state
        with open(args.stats_json, "w", encoding="utf-8") as f:
            json.dump({k: v for k, v in stats.items()}, f, default=str)
    if args.trace_dir:
        from dsi_tpu.obs import flush_tracing_report

        flush_tracing_report(args.trace_dir, "planrun")

    if args.check:
        # The twin runs the OTHER handoff mode under the SAME shard
        # fan-out: stage-sharded grep merges zero the order-sensitive
        # topk sample, so parity only holds shard-geometry-to-like.
        twin = run_plan(build(), mesh=mesh, staged=not args.staged,
                        stage_shards=args.stage_shards)
        ok = twin.final == res.final
        if args.chain == "grep-wc":
            ok = ok and twin.results["grep"] == res.results["grep"]
        elif args.chain == "grep-grep":
            ok = ok and twin.results == res.results
        if not ok:
            print("planrun: PARITY FAILURE chained vs staged",
                  file=sys.stderr)
            return 2
        print("planrun: parity OK (chained == staged)", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
