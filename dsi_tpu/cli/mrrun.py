"""One-command MapReduce job runner: coordinator + N workers + wait.

The reference requires manual orchestration — one terminal for
``mrcoordinator``, more for each ``mrworker`` (``main/test-mr.sh:36-45`` is
that choreography scripted).  This runs the whole job as child processes of
one command, with the same process-level semantics (separate interpreters,
the real RPC control plane, the shared-filesystem data plane — NOT threads),
and exits when the coordinator does.

Usage:
    python -m dsi_tpu.cli.mrrun [--workers 3] [--nreduce 10]
        [--backend host|tpu|native] [--workdir DIR] [--task-timeout S]
        [--journal FILE [--resume]] [--check] <app> inputfiles...

``--check`` additionally runs the sequential oracle and byte-compares the
merged output (sort mr-out-* | grep ., test-mr.sh:52-53), exiting non-zero
on a parity failure.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("app")
    p.add_argument("files", nargs="+")
    p.add_argument("--workers", type=int, default=3)
    p.add_argument("--nreduce", type=int, default=10)
    p.add_argument("--backend", choices=("host", "tpu", "native"),
                   default="host")
    p.add_argument("--workdir", default=".")
    p.add_argument("--task-timeout", type=float, default=10.0)
    p.add_argument("--journal", default="",
                   help="coordinator checkpoint journal (resume support)")
    p.add_argument("--resume", action="store_true",
                   help="assert this run resumes a crashed job from "
                        "--journal: completed tasks replay as DONE (their "
                        "output files were already atomically committed), "
                        "in-progress tasks hand out afresh.  Requires "
                        "--journal and errors if the journal file does "
                        "not exist (nothing to resume is a caller "
                        "mistake, not a fresh start).  NOTE the "
                        "coordinator resumes from any EXISTING --journal "
                        "either way — this flag adds the assertion, and "
                        "mrrun warns when resuming implicitly without it")
    p.add_argument("--replicas", type=int, default=0,
                   help="replicated control plane (dsi_tpu/replica): "
                        "run the coordinator as an N-member Raft group; "
                        "workers follow NotLeader redirects, so a dead "
                        "leader is an election, not a dead job")
    p.add_argument("--kill-leader-after", type=float, default=0.0,
                   help="chaos (needs --replicas): SIGKILL the leader "
                        "this many seconds in; measure failover")
    p.add_argument("--timeout", type=float, default=600.0,
                   help="whole-job wall budget, seconds")
    p.add_argument("--net", action="store_true",
                   help="NET data plane (ISSUE 17): per-worker PRIVATE "
                        "workdirs, worker-served shuffle over localhost "
                        "TCP, coordinator control plane on TCP — the "
                        "share-nothing harness (no worker reads any "
                        "other process's directory)")
    p.add_argument("--fetch-window", type=int, default=0,
                   help="reduce-side prefetch window (ISSUE 18): fetches "
                        "in flight + buffered while the consumer decodes; "
                        "1 = the serial loop bit-identically.  0 (default) "
                        "defers to DSI_NET_FETCH_WINDOW (default 4)")
    p.add_argument("--stats-json", default="",
                   help="dump the coordinator's net_stats() (net mode) "
                        "— the CI smoke's and bench row's evidence "
                        "surface")
    p.add_argument("--check", action="store_true",
                   help="run the sequential oracle and verify parity")
    p.add_argument("--trace-dir", default=None,
                   help="unified job trace (dsi_tpu/obs): the "
                        "coordinator and every worker inherit "
                        "DSI_TRACE_DIR and each commits a "
                        "trace-<pid>.json/.jsonl at exit (assign/"
                        "complete/requeue events, per-task spans, "
                        "heartbeat ages); render the whole directory "
                        "with scripts/tracecat.py")
    args = p.parse_args(argv)

    workdir = os.path.abspath(args.workdir)
    os.makedirs(workdir, exist_ok=True)
    files = [os.path.abspath(f) for f in args.files]
    app = args.app
    if os.sep in app or app.endswith(".py"):
        app = os.path.abspath(app)  # workers run with cwd=workdir
    journal = os.path.abspath(args.journal) if args.journal else ""
    if args.resume:
        if not journal:
            p.error("--resume requires --journal")
        if not os.path.exists(journal):
            print(f"mrrun: --resume: journal not found: {journal}",
                  file=sys.stderr)
            return 1
    elif journal and os.path.exists(journal):
        # The coordinator keys resume off journal existence alone; say
        # so out loud when the caller did not ask for it — a fresh job
        # against a stale journal would silently skip completed tasks.
        print(f"mrrun: existing journal {journal} will be RESUMED "
              "(pass --resume to assert this, or delete the journal "
              "for a fresh job)", file=sys.stderr)
    env = dict(os.environ)
    env.setdefault("DSI_MR_SOCKET", os.path.join(workdir, "mr.sock"))
    if args.trace_dir:
        trace_dir = os.path.abspath(args.trace_dir)
        env["DSI_TRACE_DIR"] = trace_dir
        from dsi_tpu.obs import configure_tracing, trace_event

        # mrrun's own lane records the job lifecycle; children commit
        # their trace-<pid>.* files at exit via the env inheritance.
        configure_tracing(trace_dir=trace_dir, basename="trace-mrrun")
        trace_event("mrrun.start", app=args.app, workers=args.workers,
                    nreduce=args.nreduce, files=len(files))

    # Clear stale oracle files so a failed job can't pass --check against
    # a previous run's ground truth (the reference harness's rm,
    # test-mr.sh:54).  mr-out-* lifecycle belongs to the coordinator alone
    # (Coordinator.__init__ clears stale partitions with the same
    # resume-awareness) — one owner, one predicate.
    for name in os.listdir(workdir):
        if name.startswith("mr-correct"):
            try:
                os.remove(os.path.join(workdir, name))
            except OSError:
                pass

    if args.replicas:
        if args.net:
            p.error("--net does not support --replicas yet")
        if args.replicas < 2:
            p.error("--replicas wants >= 2 (3 tolerates one kill)")
        rc = _replica_job(args, workdir, files, app, env)
        if args.trace_dir:
            from dsi_tpu.obs import flush_tracing, trace_event

            trace_event("mrrun.exit", rc=rc, replicas=args.replicas)
            flush_tracing()
        if rc != 0:
            return rc
        return _parity_check(args, workdir, files) if args.check else 0
    if args.kill_leader_after:
        p.error("--kill-leader-after needs --replicas")

    if args.net:
        rc = _net_job(args, workdir, files, app, env, journal)
        if args.trace_dir:
            from dsi_tpu.obs import flush_tracing, trace_event

            trace_event("mrrun.exit", rc=rc, net=1)
            flush_tracing()
        if rc != 0:
            return rc
        return _parity_check(args, workdir, files) if args.check else 0

    # Children run WITH cwd=workdir — the reference's data plane is "the
    # working directory" (mr-X-Y / mr-out-R relative paths), same as the
    # harness's sandbox cd (test-mr.sh:13-16).
    coord_cmd = [sys.executable, "-m", "dsi_tpu.cli.mrcoordinator",
                 "--nreduce", str(args.nreduce),
                 "--task-timeout", str(args.task_timeout)]
    if journal:
        coord_cmd += ["--journal", journal]
    coord = subprocess.Popen(coord_cmd + files, env=env, cwd=workdir)
    deadline = time.monotonic() + args.timeout
    time.sleep(1.0)  # socket-creation grace (test-mr.sh:39-40)

    worker_cmd = [sys.executable, "-m", "dsi_tpu.cli.mrworker",
                  "--backend", args.backend, app]
    spawn = time.monotonic()
    workers = [subprocess.Popen(worker_cmd, env=env, cwd=workdir)
               for _ in range(args.workers)]
    spawned_at = [spawn] * len(workers)
    # A worker that dies crashed (non-zero) is respawned, but an app that
    # can never start (typo'd name, broken plugin) must not burn the whole
    # wall budget spawning doomed interpreters 3/sec.  Two detectors:
    #
    # * instant-death streak — every death so far was < _INSTANT_S old,
    #   with the SAME exit code, and the job has made zero progress (no
    #   mr-* data-plane file exists): after a streak covering the whole
    #   fleet twice over, the app provably cannot start, and waiting out
    #   the old ~26-respawn budget (~26 x a 1-3 s interpreter startup)
    #   just burned the wall clock (VERDICT r5 weak #5).  Seconds, not
    #   minutes.  Any slow death, differing exit code, or completed task
    #   resets the streak — a legitimate crash-app run (which dies
    #   mid-task AFTER committing output) never trips it.
    # * total budget — scaled to job size, as before: a legitimate
    #   crash-app run kills at most ~one worker per task.
    respawn_budget = max(16, 2 * (len(files) + args.nreduce))
    instant_streak = 0
    streak_code = None
    # High enough that a fault-injecting app (crash exit prob p) has only
    # ~p^cap odds of a spurious all-instant-death streak before its first
    # commit; low enough to fail a broken app in a few respawn rounds.
    streak_cap = max(6, 2 * args.workers + 2)
    _INSTANT_S = 5.0

    def job_progressed() -> bool:
        """Any data-plane artifact (mr-X-Y intermediate or mr-out-R)
        means at least one task body ran — the app starts fine."""
        return any(n.startswith("mr-") and not n.startswith("mr-correct")
                   for n in os.listdir(workdir))

    rc = 0
    try:
        while coord.poll() is None:
            if time.monotonic() > deadline:
                print("mrrun: job exceeded --timeout; killing",
                      file=sys.stderr)
                rc = 1
                break
            # Workers are expendable (the 10 s requeue covers crashes); the
            # crash app even kills them on purpose — respawn CRASHED
            # workers to keep the fleet at full strength, as test_mr.sh's
            # respawner does.  A zero exit is end-of-job, not a crash.
            for i, w in enumerate(workers):
                if (w.poll() is not None and w.returncode != 0
                        and coord.poll() is None):
                    lifetime = time.monotonic() - spawned_at[i]
                    if lifetime >= _INSTANT_S:
                        instant_streak, streak_code = 0, None
                    elif streak_code == w.returncode:
                        instant_streak += 1
                    else:
                        instant_streak, streak_code = 1, w.returncode
                    if (instant_streak >= streak_cap
                            and not job_progressed()):
                        print("mrrun: workers failing repeatedly "
                              f"({instant_streak} consecutive instant "
                              f"deaths, rc={streak_code}, zero tasks "
                              "completed); giving up", file=sys.stderr)
                        rc = 1
                        break
                    if respawn_budget <= 0:
                        print("mrrun: workers failing repeatedly; giving up",
                              file=sys.stderr)
                        rc = 1
                        break
                    respawn_budget -= 1
                    spawned_at[i] = time.monotonic()
                    workers[i] = subprocess.Popen(worker_cmd, env=env,
                                                  cwd=workdir)
            if rc:
                break
            time.sleep(0.3)
    finally:
        for proc in [coord] + workers:
            if proc.poll() is None:
                proc.terminate()
        for proc in [coord] + workers:
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()

    if rc == 0 and coord.returncode not in (0, None):
        print(f"mrrun: coordinator exited rc={coord.returncode}",
              file=sys.stderr)
        rc = 1
    if args.trace_dir:
        from dsi_tpu.obs import flush_tracing, trace_event

        trace_event("mrrun.exit", rc=rc)
        flush_tracing()
        print(f"mrrun: traces in {args.trace_dir} (render: python "
              f"scripts/tracecat.py {args.trace_dir})", file=sys.stderr)
    if rc != 0:
        return rc
    if args.check:
        return _parity_check(args, workdir, files)
    return 0


def _parity_check(args, workdir: str, files: list) -> int:
    """Run the sequential oracle and byte-compare the merged mr-out-*
    lines (sort mr-out-* | grep ., test-mr.sh:52-53)."""
    from dsi_tpu.mr.plugin import load_plugin
    from dsi_tpu.mr.sequential import run_sequential

    # Oracle twins: fault-injecting / device apps check against their
    # deterministic host equivalents (scripts/test_mr.sh:32-43).
    oracle_app = {"crash": "nocrash", "tpu_wc": "wc",
                  "tpu_indexer": "indexer",
                  "tpu_grep": "grep"}.get(args.app, args.app)
    mapf, reducef = load_plugin(oracle_app)
    oracle_out = os.path.join(workdir, "mr-correct.txt")
    run_sequential(mapf, reducef, files, oracle_out)
    got: list = []
    for r in range(args.nreduce):
        path = os.path.join(workdir, f"mr-out-{r}")
        if os.path.exists(path):
            with open(path, encoding="utf-8") as f:
                got.extend(l for l in f if l.strip())
    with open(oracle_out, encoding="utf-8") as f:
        want = sorted(l for l in f if l.strip())
    if sorted(got) != want:
        print("mrrun: PARITY FAILURE vs sequential oracle",
              file=sys.stderr)
        return 2
    print("mrrun: parity OK", file=sys.stderr)
    return 0


def _replica_job(args, workdir: str, files: list, app: str,
                 env: dict) -> int:
    """Classic map/reduce under the replicated control plane: the
    coordinator is an N-member ``replicad`` group, workers dial the
    whole group (``DSI_MR_SOCKET`` comma list) and follow redirects,
    and an optional mid-job ``kill -9`` of the leader exercises the
    failover the single-coordinator plane cannot survive."""
    import json as _json

    from dsi_tpu.mr import rpc as _rpc
    from dsi_tpu.replica.driver import ReplicaGroup

    env = dict(env)
    # replicad + workers must import the package from any cwd.
    import dsi_tpu as _pkg

    pkg_root = os.path.dirname(os.path.dirname(
        os.path.abspath(_pkg.__file__)))
    env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
    # Fresh-run hygiene the leader coordinator skips in replica mode
    # (its resuming check sees the always-present replica journal).
    if not os.path.exists(os.path.join(workdir, "replica-0.journal")):
        for name in os.listdir(workdir):
            if name.startswith("mr-out-"):
                try:
                    os.remove(os.path.join(workdir, name))
                except OSError:
                    pass
    group = ReplicaGroup(
        "classic", workdir, replicas=args.replicas, files=files,
        n_reduce=args.nreduce,
        config={"n_reduce": args.nreduce,
                "task_timeout_s": args.task_timeout},
        env=env)
    env["DSI_MR_SOCKET"] = group.spec
    worker_cmd = [sys.executable, "-m", "dsi_tpu.cli.mrworker",
                  "--backend", args.backend, app]
    t0 = time.monotonic()
    deadline = t0 + args.timeout
    workers = [subprocess.Popen(worker_cmd, env=env, cwd=workdir)
               for _ in range(args.workers)]
    respawn_budget = max(16, 2 * (len(files) + args.nreduce))
    failover = None
    rc = 0
    try:
        while True:
            if time.monotonic() > deadline:
                print("mrrun: job exceeded --timeout; killing",
                      file=sys.stderr)
                rc = 1
                break
            if args.kill_leader_after > 0 and failover is None \
                    and time.monotonic() - t0 >= args.kill_leader_after:
                print("mrrun: chaos: kill -9 the leader replica",
                      file=sys.stderr)
                try:
                    failover = group.kill_leader()
                except _rpc.CoordinatorGone as e:
                    print(f"mrrun: failover FAILED: {e}",
                          file=sys.stderr)
                    rc = 1
                    break
                print(f"mrrun: failover in {failover['failover_s']}s "
                      f"(term {failover['old_term']} -> "
                      f"{failover['new_term']})", file=sys.stderr)
            if group.done():
                break
            for i, w in enumerate(workers):
                if w.poll() is not None and w.returncode != 0:
                    if respawn_budget <= 0:
                        print("mrrun: workers failing repeatedly; "
                              "giving up", file=sys.stderr)
                        rc = 1
                        break
                    respawn_budget -= 1
                    workers[i] = subprocess.Popen(worker_cmd, env=env,
                                                  cwd=workdir)
            if rc:
                break
            time.sleep(0.2)
    finally:
        run_stats = {"wall_s": round(time.monotonic() - t0, 3),
                     "replicas": args.replicas,
                     "replica_kills": group.kills}
        try:
            run_stats.update(group.spec_stats())
        except _rpc.CoordinatorGone:
            pass
        if failover is not None:
            run_stats["replica_failover_s"] = failover["failover_s"]
            run_stats["replica_old_term"] = failover["old_term"]
            run_stats["replica_new_term"] = failover["new_term"]
        group.close()
        for w in workers:
            if w.poll() is None:
                w.terminate()
        for w in workers:
            try:
                w.wait(timeout=10)
            except subprocess.TimeoutExpired:
                w.kill()
    if args.stats_json:
        # dsicheck: allow[raw-write] bench/CI parse surface, not
        # durable state
        with open(args.stats_json, "w", encoding="utf-8") as f:
            _json.dump(run_stats, f, sort_keys=True, indent=1)
    print(f"mrrun: replicated run done rc={rc} "
          f"(c_map={run_stats.get('c_map')}, "
          f"c_reduce={run_stats.get('c_reduce')}, "
          f"wall {run_stats['wall_s']}s)", file=sys.stderr)
    return rc


def _net_job(args, workdir: str, files: list, app: str,
             env: dict, journal: str = "") -> int:
    """The share-nothing job (``--net``): coordinator in-process on
    localhost TCP, each worker in its own PRIVATE workdir serving its
    spool over a partition server, the shuffle and the final output
    collection both over the stream transport.

    The driver fetches each ``mr-out-<r>`` the moment its completion
    registers a location, verifying the completion CRC; a dead server
    at THAT stage triggers ``refetch_reduce`` (the reduce re-executes
    on a fresh worker — lingering workers left the task loop, so one is
    spawned) and, transitively, ``Coordinator.FetchFailed`` re-executes
    any lost producers.  Exit asserts share-nothing really held: the
    shared workdir carries only driver-written outputs."""
    import shutil
    import zlib

    from dsi_tpu.config import JobConfig
    from dsi_tpu.mr.coordinator import Coordinator
    from dsi_tpu.net.fetch import FetchFailure, fetch_partition
    from dsi_tpu.utils.atomicio import atomic_write

    cfg = JobConfig(n_reduce=args.nreduce, workdir=workdir,
                    socket_path="tcp:127.0.0.1:0",
                    task_timeout_s=args.task_timeout,
                    net_shuffle=True,
                    journal_path=journal)
    coord = Coordinator(files, args.nreduce, cfg)
    coord.serve()
    env = dict(env)
    env["DSI_MR_SOCKET"] = coord.address()
    if args.fetch_window > 0:  # CLI twin of DSI_NET_FETCH_WINDOW
        env["DSI_NET_FETCH_WINDOW"] = str(args.fetch_window)
    # Workers run with cwd=their private dir; make the package
    # importable there even when not installed (the test-sandbox case).
    import dsi_tpu as _pkg

    pkg_root = os.path.dirname(os.path.dirname(
        os.path.abspath(_pkg.__file__)))
    env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
    worker_cmd = [sys.executable, "-m", "dsi_tpu.cli.mrworker",
                  "--backend", args.backend, app]

    def spawn(i: int, clean: bool = False):
        wdir = os.path.join(workdir, f"worker-{i}")
        os.makedirs(wdir, exist_ok=True)
        we = dict(env)
        we["DSI_NET_SPOOL"] = wdir
        we["DSI_CHAOS_WORKER_INDEX"] = str(i)
        if clean:
            for k in ("DSI_CHAOS_WORKER_KILL", "DSI_FAULT_POINT",
                      "DSI_FAULT_STEP"):
                we.pop(k, None)
        return subprocess.Popen(worker_cmd, env=we, cwd=wdir)

    t0 = time.monotonic()
    deadline = t0 + args.timeout
    procs = {i: spawn(i) for i in range(args.workers)}
    next_idx = args.workers
    fetched: set = set()
    respawn_budget = max(16, 2 * (len(files) + args.nreduce))
    rc = 0
    try:
        while True:
            if time.monotonic() > deadline:
                print("mrrun: job exceeded --timeout; killing",
                      file=sys.stderr)
                rc = 1
                break
            # Fetch outputs AS they commit — while producers of a
            # possible re-execution round are still in their task loop.
            for r, (a, name, crc) in sorted(
                    coord.output_locations().items()):
                if r in fetched:
                    continue
                try:
                    raw = fetch_partition(a, name,
                                          timeout=cfg.net_fetch_timeout_s)
                    if crc and zlib.crc32(raw) != crc:
                        raise FetchFailure(
                            -1, a, name,
                            ValueError("output crc mismatch"))
                except FetchFailure as e:
                    print(f"mrrun: output fetch failed ({e})",
                          file=sys.stderr)
                    coord.refetch_reduce(r)
                    if respawn_budget <= 0:
                        rc = 1
                    else:
                        respawn_budget -= 1
                        procs[next_idx] = spawn(next_idx, clean=True)
                        next_idx += 1
                    break
                with atomic_write(os.path.join(workdir, f"mr-out-{r}"),
                                  mode="wb") as f:
                    f.write(raw)
                fetched.add(r)
            if rc:
                break
            if coord.done() and len(fetched) == args.nreduce:
                break
            for i, w in list(procs.items()):
                if w.poll() is not None and w.returncode != 0 \
                        and not coord.done():
                    if respawn_budget <= 0:
                        print("mrrun: workers failing repeatedly; "
                              "giving up", file=sys.stderr)
                        rc = 1
                        break
                    respawn_budget -= 1
                    procs[i] = spawn(i, clean=True)
            if rc:
                break
            time.sleep(0.2)
    finally:
        run_stats = coord.net_stats()
        run_stats["wall_s"] = round(time.monotonic() - t0, 3)
        run_stats["workers_spawned"] = next_idx
        coord.close()
        for w in procs.values():
            if w.poll() is None:
                w.terminate()
        for w in procs.values():
            try:
                w.wait(timeout=10)
            except subprocess.TimeoutExpired:
                w.kill()

    # Share-nothing assertion: nothing but DRIVER-written artifacts may
    # exist in the shared workdir — a stray mr-X-Y intermediate there
    # means some worker fell back to the shared-directory data plane.
    leaked = [n for n in os.listdir(workdir)
              if n.startswith("mr-")
              and not n.startswith(("mr-out-", "mr-correct", "mr.sock"))]
    if leaked:
        print(f"mrrun: SHARE-NOTHING VIOLATION: shared workdir has "
              f"{sorted(leaked)}", file=sys.stderr)
        rc = rc or 1
    if rc == 0:
        # The private spools carried the job; reap them (retention GC
        # would otherwise hold gigabytes for an hour).
        for i in range(next_idx):
            shutil.rmtree(os.path.join(workdir, f"worker-{i}"),
                          ignore_errors=True)
    if args.stats_json:
        import json

        # dsicheck: allow[raw-write] bench/CI parse surface, not durable state
        with open(args.stats_json, "w", encoding="utf-8") as f:
            json.dump(run_stats, f, sort_keys=True, indent=1)
    print(f"mrrun: net data plane: {run_stats['net_fetches']} fetches "
          f"({run_stats['net_local_reads']} local), "
          f"{run_stats['net_bytes_raw']}B raw / "
          f"{run_stats['net_bytes_wire']}B wire "
          f"(ratio {run_stats['net_ratio']}), "
          f"{run_stats['locality_hits']} locality hits, "
          f"{run_stats['net_fetch_failures']} fetch failures, "
          f"{run_stats['net_refetches']} refetches, "
          f"window {run_stats.get('net_prefetch_window', 0)} "
          f"(overlap {run_stats.get('net_overlap_s', 0.0)}s, "
          f"wait {run_stats.get('net_fetch_wait_s', 0.0)}s)",
          file=sys.stderr)
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
