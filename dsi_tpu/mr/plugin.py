"""App plugin loading.

Reference: Go plugins built with ``-buildmode=plugin``; ``loadPlugin`` opens
the .so and looks up exactly two exported symbols, ``Map`` and ``Reduce``
(``main/mrworker.go:34-51``, duplicated in ``main/mrsequential.go:93-110``).

Here a "plugin" is a Python module — either a registered name under
``dsi_tpu.apps`` (wc, grep, indexer, crash, ...) or a filesystem path to a
``.py`` file.  The two-symbol contract is preserved: the module must expose
``Map(filename: str, contents: str) -> list[KeyValue]`` and
``Reduce(key: str, values: list[str]) -> str``.
"""

from __future__ import annotations

import importlib
import importlib.util
import os
from typing import Tuple

from dsi_tpu.mr.worker import MapFn, ReduceFn


def load_plugin_module(name_or_path: str):
    """Load the app module itself (the .so analogue, mrworker.go:36-38).

    Path-based plugins are cached in sys.modules so a worker that loads the
    same app twice (e.g. load_plugin + TpuTaskRunner.for_app) gets ONE module
    instance — module-level state must not fork between the host-fallback
    Map and tpu_map.
    """
    if name_or_path.endswith(".py") or os.sep in name_or_path:
        import hashlib
        import sys

        abspath = os.path.abspath(name_or_path)
        mod_name = ("dsi_mr_app_"
                    + os.path.basename(abspath).removesuffix(".py") + "_"
                    + hashlib.md5(abspath.encode()).hexdigest()[:8])
        if mod_name in sys.modules:
            return sys.modules[mod_name]
        spec = importlib.util.spec_from_file_location(mod_name, abspath)
        if spec is None or spec.loader is None:
            raise SystemExit(f"cannot load plugin {name_or_path}")
        mod = importlib.util.module_from_spec(spec)
        sys.modules[mod_name] = mod
        try:
            spec.loader.exec_module(mod)
        except BaseException:
            del sys.modules[mod_name]
            raise
    else:
        try:
            mod = importlib.import_module(f"dsi_tpu.apps.{name_or_path}")
        except ImportError as e:
            raise SystemExit(
                f"cannot load plugin {name_or_path!r}: {e} "
                f"(registered apps: wc, tpu_wc, grep, tpu_grep, indexer, "
                f"tpu_indexer, tfidf, crash, nocrash)")
    return mod


def load_plugin(name_or_path: str) -> Tuple[MapFn, ReduceFn]:
    mod = load_plugin_module(name_or_path)
    try:
        mapf, reducef = mod.Map, mod.Reduce  # the two-symbol lookup (mrworker.go:39-47)
    except AttributeError as e:
        raise SystemExit(f"cannot find Map/Reduce in {name_or_path}: {e}")
    return mapf, reducef
