"""App plugin loading.

Reference: Go plugins built with ``-buildmode=plugin``; ``loadPlugin`` opens
the .so and looks up exactly two exported symbols, ``Map`` and ``Reduce``
(``main/mrworker.go:34-51``, duplicated in ``main/mrsequential.go:93-110``).

Here a "plugin" is a Python module — either a registered name under
``dsi_tpu.apps`` (wc, grep, indexer, crash, ...) or a filesystem path to a
``.py`` file.  The two-symbol contract is preserved: the module must expose
``Map(filename: str, contents: str) -> list[KeyValue]`` and
``Reduce(key: str, values: list[str]) -> str``.
"""

from __future__ import annotations

import importlib
import importlib.util
import os
from typing import Tuple

from dsi_tpu.mr.worker import MapFn, ReduceFn


def load_plugin(name_or_path: str) -> Tuple[MapFn, ReduceFn]:
    if name_or_path.endswith(".py") or os.sep in name_or_path:
        spec = importlib.util.spec_from_file_location(
            "dsi_mr_app_" + os.path.basename(name_or_path).removesuffix(".py"),
            name_or_path)
        if spec is None or spec.loader is None:
            raise SystemExit(f"cannot load plugin {name_or_path}")
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
    else:
        try:
            mod = importlib.import_module(f"dsi_tpu.apps.{name_or_path}")
        except ImportError as e:
            raise SystemExit(
                f"cannot load plugin {name_or_path!r}: {e} "
                f"(registered apps: wc, grep, indexer, crash, nocrash)")
    try:
        mapf, reducef = mod.Map, mod.Reduce  # the two-symbol lookup (mrworker.go:39-47)
    except AttributeError as e:
        raise SystemExit(f"cannot find Map/Reduce in {name_or_path}: {e}")
    return mapf, reducef
