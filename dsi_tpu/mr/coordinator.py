"""Coordinator: job state + pull-based task scheduler + RPC server.

Reference: ``mr/coordinator.go`` (entire file, 160 LoC).  Same state machine:

* per-task logs with states 0=untouched / 1=in-progress / 2=completed
  (coordinator.go:16,20),
* map tasks are assigned first; **no reduce task is assigned until every map
  has completed** — the `cMap == nMap` barrier (coordinator.go:47,79), which is
  load-bearing for correctness (reduce must see all mr-*-r files),
* a task in-progress for `task_timeout_s` (10 s) is re-queued for another
  worker — presumed-dead-by-timeout fault tolerance (coordinator.go:70-77,
  99-106),
* `Done()` is `c_reduce == n_reduce` under the lock (coordinator.go:138-142).

Two reference defects documented in SURVEY.md §5 are fixed here (both
output-invariant):

1. **Unique-transition completion counting.**  The reference increments
   `cMap`/`cReduce` on every completion RPC (coordinator.go:30-31,38-39), so a
   re-queued task finished by two workers double-counts and can prematurely
   satisfy the map barrier or `Done()`.  We count only the first transition of
   a task's log to COMPLETED.
2. The waiting busy-poll fix lives in the worker (see worker.py).
"""

from __future__ import annotations

import heapq
import os
import sys
import threading
import time
from typing import Dict, List, Optional

from dsi_tpu.config import JobConfig
from dsi_tpu.obs import LatencyHistogram, get_registry
from dsi_tpu.mr import rpc
from dsi_tpu.mr.journal import Journal
from dsi_tpu.mr.shards import ShardSpec
from dsi_tpu.mr.types import (LOG_COMPLETED, LOG_IN_PROGRESS, LOG_UNTOUCHED,
                              TaskStatus)
from dsi_tpu.utils.atomicio import fsync_dir
from dsi_tpu.utils.tracing import log_event


class Coordinator:
    """Owns all job state; hands out tasks on pull (mr/coordinator.go:14-25).

    **Shard mode** (``shard_plan`` given): the coordinator is a shard
    scheduler for the streaming engines (ISSUE 15 — the speculative-
    execution loop the PR-9 telemetry armed).  Each :class:`ShardSpec`
    is a cursor-range task a worker drives as a resumable step object;
    the coordinator tracks ATTEMPTS per shard (primary / takeover /
    backup), presumes an attempt dead when its progress RPCs go silent
    past ``shard_timeout_s`` (re-queueing the shard with a resume hint
    pointing at the best checkpoint chain), speculatively hands an idle
    worker a BACKUP attempt of a shard whose newest attempt is silent
    past the percentile-aware suspect threshold (Dean & Ghemawat §3.6),
    and arbitrates FIRST-COMMIT-WINS: the first ``CommitShard`` RPC for
    a shard durably renames that attempt's output and journals the
    commit record (shard id + attempt id + output CRC32) under the
    lock — every later attempt is told it lost and reaps its partials.
    """

    def __init__(self, files: List[str], n_reduce: int,
                 config: JobConfig | None = None,
                 shard_plan: Optional[List[ShardSpec]] = None,
                 shard_opts: Optional[dict] = None,
                 journal: Optional[Journal] = None):
        self.config = config or JobConfig(n_reduce=n_reduce)
        self.files = list(files)
        self.n_map = len(files)
        self.c_map = 0
        self.map_log = [LOG_UNTOUCHED] * self.n_map
        self.n_reduce = n_reduce
        self.c_reduce = 0
        self.reduce_log = [LOG_UNTOUCHED] * n_reduce
        # Assignment heaps: lowest untouched index first — the same order
        # as the reference's linear scan (mr/coordinator.go:50-55), O(log n)
        # per assignment instead of O(n) (which is O(n^2) across a big
        # job).  Entries are lazily invalidated: pop until one is still
        # UNTOUCHED; requeue pushes the index back.
        self._map_ready = list(range(self.n_map))
        self._reduce_ready = list(range(n_reduce))
        self.mu = threading.Lock()
        # ── shard-scheduler state (shard mode only; all guarded by mu) ──
        self.shard_plan = list(shard_plan) if shard_plan else None
        self.shard_opts = dict(shard_opts or {})
        self.n_shards = len(self.shard_plan) if self.shard_plan else 0
        if self.shard_plan:
            self.n_map = 0  # shard jobs have no map/reduce phases
            self.n_reduce = 0
            self._map_ready = []
            self._reduce_ready = []
        self._shards: Dict[int, dict] = {}
        self._shard_ready: list[int] = []
        self.job_failed = False
        #: Speculation counters — the differential harness's evidence
        #: surface (``spec_stats()``).  duplicate_commits counts journal
        #: double-commits and MUST stay 0; commit_losses counts attempts
        #: that finished second (normal when a backup races the primary).
        self._spec = {"backup_dispatches": 0, "requeues": 0, "commits": 0,
                      "commit_losses": 0, "duplicate_commits": 0,
                      "resumed_attempts": 0, "failed_attempts": 0,
                      "resplits": 0, "subshard_dispatches": 0,
                      "subshard_commits": 0,
                      "resume_cursors": {}}
        #: Dispatchable sub-shards of re-split shards: (sid, k) heap,
        #: lazily invalidated like the shard heap.
        self._sub_ready: list[tuple] = []
        #: assignment→commit walls of committed shards — the "normal
        #: shard duration" reference the slow-progress backup trigger
        #: compares against (§3.6: back up what takes abnormally long).
        self._commit_walls: list[float] = []
        if self.shard_plan:
            for spec in self.shard_plan:
                self._shards[spec.sid] = {
                    "spec": spec, "status": LOG_UNTOUCHED,
                    "attempts": {}, "next_aid": 0, "committed": None,
                    "backups": 0, "subs": None}
            self._shard_ready = list(range(self.n_shards))
            heapq.heapify(self._shard_ready)
        # Worker liveness (observability + the speculative-execution
        # hook): last-contact time per WorkerId — every RPC carrying an
        # id refreshes it — and which worker holds each in-progress
        # task, so a requeue can report WHOSE heartbeat went stale and
        # how stale it was (the reference reassigns silently,
        # coordinator.go:70-77).
        self._worker_seen: Dict[str, float] = {}
        self._task_worker: Dict[tuple, str] = {}
        # ── network data plane (dsi_tpu/net, ISSUE 17) ──
        # In net mode workers serve their spooled partitions over TCP;
        # the coordinator is the location registry (Dean & Ghemawat
        # §3.1: "the locations of these buffered pairs ... are passed
        # back to the master, who is responsible for forwarding these
        # locations to the reduce workers") and re-executes completed
        # map tasks whose server died (§3.4).
        self.net = bool(self.config.net_shuffle)
        #: worker id → its partition-server address (from every RPC).
        self._net_addrs: Dict[str, str] = {}
        #: map task → producer's partition-server address.
        self._map_locs: Dict[int, str] = {}
        #: map task → per-reduce-partition byte sizes (locality shares).
        self._map_sizes: Dict[int, List[int]] = {}
        #: reduce task → (addr, name, crc) of the committed output.
        self._out_locs: Dict[int, tuple] = {}
        #: Net-plane counters (schema: obs/registry.COUNTER_KEYS).
        self._net_counters = {
            "net_fetches": 0, "net_local_reads": 0, "net_bytes_raw": 0,
            "net_bytes_wire": 0, "net_ratio": 0.0,
            "net_fetch_failures": 0, "net_refetches": 0,
            "locality_hits": 0,
            "net_fetch_wait_s": 0.0, "net_overlap_s": 0.0,
            "net_prefetch_window": 0}
        # Per-worker contact-GAP histograms (obs/hist.py): every RPC
        # records the gap since the worker's previous contact, so a
        # requeue can compare the stale worker's current silence to its
        # own p99 gap — "presumed dead" (silence way past anything it
        # ever did) vs "slow task" (still phoning home, the task is
        # just long).  The percentile-aware signal the speculative-
        # execution item dispatches backup tasks on.
        self._hb_hist: Dict[str, LatencyHistogram] = {}
        # Straggler watchdog: ONE monitor thread over a deadline heap
        # replaces the reference's goroutine-per-assignment
        # (mr/coordinator.go:70-77,99-106) — a per-task Timer thread melts
        # at ~10^4 tasks (~0.4 ms spawn each, thousands of live threads);
        # the heap is O(log n) per assignment and one thread total.
        # Entries: (due, "map"|"reduce", task_id) or, in shard mode,
        # (due, "shard", sid, attempt_id) — progress-based, re-armed by
        # the watchdog while the attempt keeps phoning home.
        self._deadlines: list[tuple] = []
        self._deadline_cv = threading.Condition(self.mu)
        self._closing = False
        self._monitor = threading.Thread(target=self._watchdog,
                                         name="dsi-mr-watchdog", daemon=True)
        self._monitor.start()
        self._server: Optional[rpc.RpcServer] = None

        # Clear stale mr-out-* so a leftover file from a PREVIOUS job in the
        # same cwd can't win the workers' first-writer-wins output commit
        # (atomicio.py) — preserving reference rerun-overwrites behavior at
        # job granularity.  NOT on journal resume: there, a
        # committed-but-unjournaled mr-out-<r> whose intermediates were
        # already GC'd is the only surviving copy of that partition, and
        # deleting it would make the re-run reducer commit an empty file.
        # This must happen BEFORE the journal file is created below: a crash
        # between journal creation and the clear would otherwise look like a
        # resume forever and skip the clear.
        resuming = bool(self.config.journal_path
                        and os.path.exists(self.config.journal_path))
        if not resuming:
            prefixes = ("mr-out-", "mr-shard-out-") if self.shard_plan \
                else ("mr-out-",)
            try:
                stale = [n for n in os.listdir(self.config.workdir)
                         if n.startswith(prefixes)]
            except OSError:
                stale = []
            for name in stale:  # ALL partitions, incl. a previous job's
                try:            # higher-numbered ones (n_reduce may shrink)
                    os.remove(os.path.join(self.config.workdir, name))
                except OSError:
                    pass

        # Optional checkpoint/resume (journal.py; disabled by default — the
        # reference keeps coordinator state purely in-memory).
        # An INJECTED journal (replica mode) swaps the local append-only
        # file for the replicated log's propose-and-wait path: same
        # record surface, but a record is durable only once a majority
        # of the coordinator group holds it (replica/node.py).
        self._journal: Optional[Journal] = journal
        if self.config.journal_path or journal is not None:
            if self._journal is None:
                self._journal = Journal(self.config.journal_path,
                                        self.files, self.n_reduce,
                                        n_shards=self.n_shards)
            done_maps, done_reduces = self._journal.replay()
            for t in done_maps:
                if self.map_log[t] != LOG_COMPLETED:
                    self.map_log[t] = LOG_COMPLETED
                    self.c_map += 1
            for t in done_reduces:
                if self.reduce_log[t] != LOG_COMPLETED:
                    self.reduce_log[t] = LOG_COMPLETED
                    self.c_reduce += 1
            # Net mode (ISSUE 18): re-learn the partition location
            # registry from the journaled completions.  A replayed
            # address whose server died with the old coordinator is
            # only advisory — the first reducer to hit it reports
            # FetchFailed and the producer re-executes (§3.4), exactly
            # the live-run convergence path.
            if self.net:
                for t, a in self._journal.map_locations.items():
                    self._map_locs.setdefault(t, a)
                for t, sz in self._journal.map_sizes.items():
                    self._map_sizes.setdefault(t, list(sz))
                for t, loc in self._journal.out_locations.items():
                    self._out_locs.setdefault(t, tuple(loc))
            # Shard commits replay as COMMITTED: the journal record was
            # written only after the output file's durable rename, so
            # the shard's output exists and must never be re-run.
            for sid, (aid, crc) in self._journal.shard_commits.items():
                shard = self._shards.get(sid)
                if shard is not None and shard["committed"] is None:
                    shard["committed"] = (aid, crc)
                    shard["status"] = LOG_COMPLETED
            if self._journal.shard_commits:
                self._shard_ready = [
                    s for s in self._shard_ready
                    if self._shards[s]["committed"] is None]
                heapq.heapify(self._shard_ready)
            # Re-split records replay as live sub-shard state: the
            # ranges partition the shard exactly, so the remaining work
            # IS the uncommitted subs — the full range is never
            # re-queued once a re-split was journaled (the dead
            # straggler's chain still serves sub 0 via adoption).
            for sid, ranges in self._journal.resplits.items():
                shard = self._shards.get(sid)
                if shard is None or shard["committed"] is not None:
                    continue
                self._make_subs(sid, ranges, parent_chain=None)
                shard["status"] = LOG_IN_PROGRESS
            for (sid, k), (aid, crc) in \
                    self._journal.subshard_commits.items():
                shard = self._shards.get(sid)
                subs = shard["subs"] if shard is not None else None
                sub = subs.get(k) if subs else None
                if sub is not None and sub["committed"] is None:
                    sub["committed"] = (aid, crc)
                    sub["status"] = LOG_COMPLETED
            for shard in self._shards.values():
                if shard["committed"] is None \
                        and self._split_resolved(shard):
                    shard["status"] = LOG_COMPLETED
            self._journal.open()

    # ---- RPC handlers (the wire API, mr/coordinator.go:27-114) ----

    def request_task(self, args: dict) -> dict:
        """Assign a map task, a reduce task, "waiting", or "done"
        (mr/coordinator.go:43-114)."""
        reply = {"TaskStatus": int(TaskStatus.WAITING), "NMap": self.n_map,
                 "CMap": 0, "NReduce": self.n_reduce, "CReduce": 0, "Filename": ""}
        wid = str(args.get("WorkerId") or "")
        addr = str(args.get("Addr") or "")
        with self.mu:
            if wid:
                self._touch(wid)
                if addr:
                    self._net_addrs[wid] = addr
            if self.c_map < self.n_map:
                tba = self._pop_untouched(self._map_ready, self.map_log)
                if tba is None:
                    reply["TaskStatus"] = int(TaskStatus.WAITING)  # :58-60
                else:
                    self.map_log[tba] = LOG_IN_PROGRESS  # :62
                    reply["TaskStatus"] = int(TaskStatus.MAP)
                    reply["Filename"] = self.files[tba]
                    reply["CMap"] = tba
                    self._arm_timeout(tba, "map")  # :70-77
                    if wid:
                        self._task_worker[("map", tba)] = wid
                    log_event("assign", kind="map", task=tba,
                              file=self.files[tba], worker=wid or None)
            elif self.c_reduce < self.n_reduce:  # map barrier passed (:79)
                tba = self._pick_reduce_locked(addr) if self.net \
                    else self._pop_untouched(self._reduce_ready,
                                             self.reduce_log)
                if tba is None:
                    reply["TaskStatus"] = int(TaskStatus.WAITING)
                else:
                    self.reduce_log[tba] = LOG_IN_PROGRESS
                    reply["TaskStatus"] = int(TaskStatus.REDUCE)
                    reply["CReduce"] = tba
                    if self.net:
                        # §3.1: the master forwards the buffered pairs'
                        # locations to the reduce worker.
                        reply["Net"] = True
                        reply["MapLocs"] = {str(m): a for m, a
                                            in self._map_locs.items()}
                    self._arm_timeout(tba, "reduce")  # :99-106
                    if wid:
                        self._task_worker[("reduce", tba)] = wid
                    log_event("assign", kind="reduce", task=tba,
                              worker=wid or None)
            else:
                reply["TaskStatus"] = int(TaskStatus.DONE)  # :109-112
        return reply

    def map_complete(self, args: dict) -> dict:
        """Reference: RecieveMapComplete [sic] (mr/coordinator.go:27-33), with
        the unique-transition counting fix."""
        t = int(args["TaskNumber"])
        wid = str(args.get("WorkerId") or "")
        addr = str(args.get("Addr") or "")
        with self.mu:
            if wid:
                self._touch(wid)
            self._task_worker.pop(("map", t), None)
            if self.map_log[t] != LOG_COMPLETED:  # fix: count first completion only
                self.map_log[t] = LOG_COMPLETED
                self.c_map += 1
                if addr:
                    # Location registry (§3.1): this producer serves
                    # mr-<t>-* from its spool; the per-partition byte
                    # sizes feed the locality-share placement policy.
                    self._map_locs[t] = addr
                    sizes = args.get("PartSizes")
                    if isinstance(sizes, list):
                        self._map_sizes[t] = [int(x) for x in sizes]
                if self._journal is not None:
                    extra = None
                    if addr:  # net mode: journal the location registry
                        extra = {"addr": addr}
                        if t in self._map_sizes:
                            extra["sizes"] = list(self._map_sizes[t])
                    self._journal.record("map", t, extra)
                log_event("complete", kind="map", task=t, c_map=self.c_map,
                          worker=wid or None)
            else:
                log_event("duplicate_completion", kind="map", task=t)
        return {}

    def reduce_complete(self, args: dict) -> dict:
        """Reference: RecieveReduceComplete [sic] (mr/coordinator.go:35-41)."""
        t = int(args["TaskNumber"])
        wid = str(args.get("WorkerId") or "")
        addr = str(args.get("Addr") or "")
        with self.mu:
            if wid:
                self._touch(wid)
            self._task_worker.pop(("reduce", t), None)
            if self.reduce_log[t] != LOG_COMPLETED:
                self.reduce_log[t] = LOG_COMPLETED
                self.c_reduce += 1
                if addr:
                    # Net mode: mr-out-<t> lives in the reducer's spool;
                    # the driver fetches it by this location.
                    self._out_locs[t] = (addr,
                                         str(args.get("Name") or ""),
                                         int(args.get("Crc", 0) or 0))
                self._absorb_net_locked(args)
                if self._journal is not None:
                    extra = None
                    if addr:  # net mode: where mr-out-<t> is served from
                        extra = {"addr": addr,
                                 "name": str(args.get("Name") or ""),
                                 "crc": int(args.get("Crc", 0) or 0)}
                    self._journal.record("reduce", t, extra)
                log_event("complete", kind="reduce", task=t,
                          c_reduce=self.c_reduce, worker=wid or None)
            else:
                log_event("duplicate_completion", kind="reduce", task=t)
        return {}

    def fetch_failed(self, args: dict) -> dict:
        """Re-fetch-from-replacement (§3.4): a reducer could not fetch
        ``mr-<Map>-<Reduce>`` from its producer's partition server (the
        server died, or died mid-stream).  The completed map task is
        reset to UNTOUCHED — ``c_map`` drops below ``n_map``, so the map
        barrier RE-ENGAGES and the task re-executes on a live worker
        (its completion re-registers a replacement location); the
        reporting reducer's task is re-queued to run after the barrier
        reopens.  Unique-transition counting absorbs the duplicate
        completion a slow original could still send."""
        m = int(args.get("Map", -1))
        r = int(args.get("Reduce", -1))
        wid = str(args.get("WorkerId") or "")
        with self.mu:
            if wid:
                self._touch(wid)
            self._net_counters["net_fetch_failures"] += 1
            requeued_map = False
            if 0 <= m < self.n_map and self.map_log[m] == LOG_COMPLETED:
                self.map_log[m] = LOG_UNTOUCHED
                self.c_map -= 1  # the map barrier re-engages
                heapq.heappush(self._map_ready, m)
                self._map_locs.pop(m, None)
                self._map_sizes.pop(m, None)
                self._net_counters["net_refetches"] += 1
                requeued_map = True
            if 0 <= r < self.n_reduce \
                    and self.reduce_log[r] == LOG_IN_PROGRESS:
                self.reduce_log[r] = LOG_UNTOUCHED
                heapq.heappush(self._reduce_ready, r)
                self._task_worker.pop(("reduce", r), None)
            log_event("fetch_failed", kind="net", task=r, map_task=m,
                      worker=wid or None,
                      addr=str(args.get("Addr") or "") or None,
                      requeued_map=requeued_map)
            if requeued_map:
                print(f"coordinator: fetch of mr-{m}-{r} failed "
                      f"(producer server gone); re-executing map {m}",
                      file=sys.stderr)
        return {"Requeued": requeued_map}

    def _absorb_net_locked(self, args: dict) -> None:
        """Fold one completion RPC's per-task net-attribution deltas
        into the job-wide counters.  Caller holds ``self.mu``."""
        found = False
        for wire, key in (("NetFetches", "net_fetches"),
                          ("NetLocal", "net_local_reads"),
                          ("NetRaw", "net_bytes_raw"),
                          ("NetWire", "net_bytes_wire"),
                          ("NetFailures", "net_fetch_failures")):
            v = args.get(wire)
            if v is not None:
                self._net_counters[key] += int(v)
                found = True
        for wire, key in (("NetWait", "net_fetch_wait_s"),
                          ("NetOverlap", "net_overlap_s")):
            v = args.get(wire)
            if v is not None:
                self._net_counters[key] = round(
                    self._net_counters[key] + float(v), 6)
                found = True
        v = args.get("NetWindow")
        if v is not None:
            self._net_counters["net_prefetch_window"] = max(
                self._net_counters["net_prefetch_window"], int(v))
            found = True
        if found:
            wire_n = self._net_counters["net_bytes_wire"]
            self._net_counters["net_ratio"] = round(
                self._net_counters["net_bytes_raw"] / wire_n, 3) \
                if wire_n else 0.0

    # ---- shard-scheduler RPC handlers (shard mode, mr/shards.py) ----

    def request_shard(self, args: dict) -> dict:
        """Assign a shard attempt: an untouched/re-queued shard first
        (primary or takeover — a takeover carries a resume hint at the
        best known checkpoint chain), else a speculative BACKUP attempt
        of the stalest suspect shard (Dean & Ghemawat §3.6), else
        WAITING/DONE."""
        wid = str(args.get("WorkerId") or "")
        addr = str(args.get("Addr") or "")
        reply: dict = {"TaskStatus": int(TaskStatus.WAITING)}
        now = time.monotonic()
        with self.mu:
            if self.shard_plan is None:
                return {"TaskStatus": int(TaskStatus.DONE)}
            if wid:
                self._touch(wid)
                if addr:
                    self._net_addrs[wid] = addr
            if self.job_failed or all(
                    self._shard_resolved(shard)
                    for shard in self._shards.values()):
                reply["TaskStatus"] = int(TaskStatus.DONE)
                return reply
            assignment = None
            sid = self._pop_untouched_shard(wid)
            if sid is not None:
                shard = self._shards[sid]
                kind = "takeover" if shard["attempts"] else "primary"
                assignment = self._new_attempt(sid, wid, kind, now)
            if assignment is None:
                pick = self._pop_untouched_sub()
                if pick is not None:
                    return self._assign_sub(pick[0], pick[1], wid, now)
            if assignment is None and self.config.spec_resplit \
                    and not self.net:
                # Re-split is a shared-directory optimization: its
                # sub-range merge reads committed files in place.  Net
                # mode covers stragglers with whole-range backups
                # (first-commit-wins is location-agnostic).
                pick = self._maybe_resplit(wid, now)
                if pick is not None:
                    return self._assign_sub(pick[0], pick[1], wid, now)
            if assignment is None and self.config.spec_backup:
                assignment = self._maybe_backup(wid, now)
            if assignment is None:
                return reply
            sid, aid, shard, att = assignment
            spec = shard["spec"]
            reply.update({
                "TaskStatus": int(TaskStatus.SHARD), "Shard": sid,
                "Attempt": aid, "Start": spec.start, "End": spec.end,
                "Files": self.files, "NShards": self.n_shards,
                "ResumeFrom": att["resume_from"],
                "Knobs": self.shard_opts.get("knobs", {}),
                "CkptRoot": self._shard_ckpt_root(),
                "OutPart": self._shard_part_path(sid, aid),
            })
            if self.net:
                # Share-nothing: the partial and the checkpoint chain
                # both resolve RELATIVE to the worker's private cwd; a
                # resume hint only restores when the chain is local
                # (adopt_chain fails soft otherwise — exactly the case
                # the locality preference above works to hit).
                reply["Net"] = True
                reply["OutPart"] = os.path.basename(reply["OutPart"])
                reply["CkptRoot"] = ".shards"
            log_event("assign", kind="shard", task=sid, attempt=aid,
                      attempt_kind=att["kind"], worker=wid or None,
                      resume_from=att["resume_from"])
        return reply

    def shard_progress(self, args: dict) -> dict:
        """Attempt heartbeat: refreshes liveness (the watchdog's
        presumed-dead signal is *progress* silence, not RPC silence) and
        carries the attempt's confirmed-step count, its durable
        checkpoint count (the resume-hint ranking), and — once, after a
        takeover/backup restore — the resume cursor the differential
        harness asserts on.  The reply's ``Cancel`` tells a loser to
        stop and reap (first-commit-wins)."""
        wid = str(args.get("WorkerId") or "")
        sid = int(args.get("Shard", -1))
        aid = int(args.get("Attempt", -1))
        sub = int(args.get("Sub", -1))
        now = time.monotonic()
        with self.mu:
            if wid:
                self._touch(wid)
            shard = self._shards.get(sid)
            owner = shard
            if shard is not None and sub >= 0:
                owner = (shard["subs"] or {}).get(sub)
            att = owner["attempts"].get(aid) if owner is not None else None
            if att is None:
                return {"Cancel": True}
            att["last_progress"] = now
            att["confirmed"] = int(args.get("Confirmed", 0) or 0)
            att["ckpts"] = int(args.get("Ckpts", 0) or 0)
            # The attempt's LIVE confirmed-byte cursor (reported from
            # the first retired step, not only after a checkpoint) —
            # the re-split trigger cuts the remainder from here.
            att["cursor"] = int(args.get("Cursor", 0) or 0)
            # "Progressed" means REAL steps retired, not merely an RPC:
            # the first advance slice pays the engine's jax compiles,
            # and the setup-grace window must cover exactly that.
            if att["confirmed"] > 0 or att["ckpts"] > 0:
                att["progressed"] = True
            rc = args.get("ResumeCursor")
            if rc and not att["resume_cursor"]:
                att["resume_cursor"] = int(rc)
                self._spec["resumed_attempts"] += 1
                key = f"{sid}.s{sub}.a{aid}" if sub >= 0 else f"{sid}.a{aid}"
                self._spec["resume_cursors"][key] = int(rc)
            cancel = att["cancelled"] or owner["committed"] is not None \
                or self._shard_resolved(shard)
            return {"Cancel": bool(cancel)}

    def commit_shard(self, args: dict) -> dict:
        """FIRST-COMMIT-WINS, under the lock: the first attempt to
        report a durably written partial wins — its file is renamed to
        the shard's final output, the commit record (shard + attempt +
        CRC32) is journaled, and every other live attempt is flagged
        for cancellation.  Later commits are told they lost and reap
        their partials; a dead-presumed attempt that was actually just
        slow may still win (liveness never gates commits).

        Re-split arbitration (``Sub >= 0`` commits a SUB-range): each
        sub-range is its own first-commit-wins race journaled as a
        ``subshard`` record; once EVERY sub has committed the shard is
        resolved "split" and the full-range straggler is cancelled.
        Conversely a full-range commit landing while any sub is still
        open WINS the whole shard (the straggler outran the split) and
        every sub is cancelled and its outputs reaped — either way
        exactly one committed copy of every byte survives."""
        wid = str(args.get("WorkerId") or "")
        sid = int(args.get("Shard", -1))
        aid = int(args.get("Attempt", -1))
        sub = int(args.get("Sub", -1))
        crc = int(args.get("Crc", 0) or 0)
        with self.mu:
            if wid:
                self._touch(wid)
            shard = self._shards.get(sid)
            if shard is None:
                return {"Win": False}
            if sub >= 0:
                return self._commit_sub_locked(shard, sid, sub, aid, crc,
                                               wid)
            if shard["committed"] is None and self._split_resolved(shard):
                # The subs got there first: the full-range straggler
                # lost to the split as a whole.
                self._spec["commit_losses"] += 1
                log_event("shard_commit_lose", kind="shard", task=sid,
                          attempt=aid, winner="split",
                          worker=wid or None)
                return {"Win": False}
            if shard["committed"] is not None:
                self._spec["commit_losses"] += 1
                if shard["committed"][0] == aid:
                    # The winner re-reporting would double-journal:
                    # MUST stay 0 (the harness gates on it).
                    self._spec["duplicate_commits"] += 1
                log_event("shard_commit_lose", kind="shard", task=sid,
                          attempt=aid, winner=shard["committed"][0],
                          worker=wid or None)
                return {"Win": False}
            if self.net:
                # Net mode: the winner's bytes stay in ITS private
                # spool; the coordinator records the location (addr +
                # spool name + CRC) and the driver fetches them over
                # the stream transport — the §3.1 contract where the
                # master tracks locations, never the bytes.  Losers
                # reap their own partials (private dirs; nobody else
                # can).
                net_addr = str(args.get("Addr") or "")
                net_name = str(args.get("Name") or "")
                if not net_addr or not net_name:
                    return {"Win": False,
                            "Error": "net commit needs Addr+Name"}
                shard["net_loc"] = (net_addr, net_name)
            else:
                part = self._shard_part_path(sid, aid)
                final = self._shard_out_path(sid)
                try:
                    os.replace(part, final)
                    fsync_dir(os.path.dirname(final) or ".")
                except OSError as e:
                    log_event("shard_commit_missing", kind="shard",
                              task=sid, attempt=aid, error=str(e))
                    return {"Win": False,
                            "Error": f"partial missing: {e}"}
            if self._journal is not None:
                self._journal.record_shard(sid, aid, crc)
            shard["committed"] = (aid, crc)
            shard["status"] = LOG_COMPLETED
            self._spec["commits"] += 1
            if not self.net:
                # Reap sibling partials: an attempt killed between its
                # durable partial write and its commit RPC can never
                # report again, and its orphan .part must not outlive
                # the shard.
                prefixes = (os.path.basename(final) + ".a",
                            os.path.basename(final) + ".s")
                try:
                    for name in os.listdir(os.path.dirname(final)
                                           or "."):
                        if name.startswith(prefixes) \
                                and name.endswith(".part"):
                            os.remove(os.path.join(
                                os.path.dirname(final), name))
                except OSError:
                    pass
            for oaid, oatt in shard["attempts"].items():
                if oaid != aid:
                    oatt["cancelled"] = True
            if shard["subs"]:
                # The straggler outran its own split: cancel every sub
                # attempt and reap any sub output already renamed — the
                # full-range file is now THE copy of these bytes.
                for k, sd in shard["subs"].items():
                    sd["status"] = LOG_COMPLETED  # no further dispatch
                    for satt in sd["attempts"].values():
                        satt["cancelled"] = True
                    for p in (self._sub_out_path(sid, k),):
                        try:
                            os.remove(p)
                        except OSError:
                            pass
                log_event("resplit_overrun", kind="shard", task=sid,
                          attempt=aid,
                          subs=sorted(shard["subs"]))
            att = shard["attempts"].get(aid)
            if att is not None:
                now = time.monotonic()
                att["last_progress"] = now
                # The slow-progress backup trigger's reference: how
                # long a NORMAL shard takes, assignment to commit.
                self._commit_walls.append(now - att["assigned"])
            log_event("shard_commit", kind="shard", task=sid, attempt=aid,
                      crc=crc, worker=wid or None,
                      resume_cursor=att["resume_cursor"] if att else 0)
            get_registry().set_gauge("dsi_shard_commits",
                                     self._spec["commits"])
            return {"Win": True}

    def shard_failed(self, args: dict) -> dict:
        """An attempt reporting it cannot finish (host-path routing,
        engine error): mark it dead and re-queue the shard with a
        resume hint — bounded by ``shard_max_attempts``."""
        wid = str(args.get("WorkerId") or "")
        sid = int(args.get("Shard", -1))
        aid = int(args.get("Attempt", -1))
        sub = int(args.get("Sub", -1))
        with self.mu:
            if wid:
                self._touch(wid)
            shard = self._shards.get(sid)
            owner = shard
            if shard is not None and sub >= 0:
                owner = (shard["subs"] or {}).get(sub)
            att = owner["attempts"].get(aid) if owner is not None else None
            if att is not None and not att["dead"] and not att["cancelled"]:
                att["dead"] = True
                self._spec["failed_attempts"] += 1
                log_event("shard_failed", kind="shard", task=sid,
                          attempt=aid, worker=wid or None,
                          sub=(sub if sub >= 0 else None),
                          reason=str(args.get("Reason", "") or ""))
                if sub >= 0:
                    self._requeue_sub_locked(sid, sub)
                else:
                    self._requeue_shard_locked(sid)
        return {}

    def spec_stats(self) -> dict:
        """Speculation-counter snapshot — the differential harness's
        and the bench row's evidence surface."""
        with self.mu:
            out = {k: (dict(v) if isinstance(v, dict) else v)
                   for k, v in self._spec.items()}
            out["shards"] = self.n_shards
            out["job_failed"] = self.job_failed
            out["committed"] = sum(
                1 for shard in self._shards.values()
                if shard["committed"] is not None)
            out["total_attempts"] = sum(
                shard["next_aid"] for shard in self._shards.values())
            out["winning_attempts"] = {
                str(sid): shard["committed"][0]
                for sid, shard in self._shards.items()
                if shard["committed"] is not None}
            out["subshards"] = sum(
                len(shard["subs"] or {})
                for shard in self._shards.values())
            out["split_shards"] = sum(
                1 for shard in self._shards.values()
                if shard["committed"] is None
                and self._split_resolved(shard))
            out["resolved"] = sum(
                1 for shard in self._shards.values()
                if self._shard_resolved(shard))
        return out

    def final_outputs(self) -> List[str]:
        """The job's committed output files in stream order: each
        shard's full-range file, or — for a shard resolved by re-split
        — its sub-range files in sub order (sub ranges partition the
        shard in order, so concatenation order is preserved).  Only
        complete once :meth:`done` is True."""
        with self.mu:
            out: List[str] = []
            for sid in sorted(self._shards):
                shard = self._shards[sid]
                if shard["committed"] is not None:
                    out.append(self._shard_out_path(sid))
                elif shard["subs"]:
                    out.extend(self._sub_out_path(sid, k)
                               for k in sorted(shard["subs"]))
            return out

    # ---- net-plane driver surface (dsi_tpu/net, ISSUE 17) ----

    def output_locations(self) -> Dict[int, tuple]:
        """Classic net mode: reduce task → (addr, name, crc) of every
        committed ``mr-out-<r>`` so far — the driver fetches these over
        the stream transport as they appear."""
        with self.mu:
            return dict(self._out_locs)

    def final_locations(self) -> Dict[int, tuple]:
        """Shard net mode: sid → (addr, name, crc) of every committed
        shard output so far (the net twin of :meth:`final_outputs`)."""
        with self.mu:
            out: Dict[int, tuple] = {}
            for sid, shard in self._shards.items():
                if shard["committed"] is not None \
                        and shard.get("net_loc"):
                    aid, crc = shard["committed"]
                    a, name = shard["net_loc"]
                    out[sid] = (a, name, crc)
            return out

    def refetch_reduce(self, r: int) -> bool:
        """Driver-side re-fetch-from-replacement: the committed
        ``mr-out-<r>``'s server died before the driver could fetch it.
        Forget the completion — ``c_reduce`` drops, ``done()`` flips
        back, and a live worker re-runs the reduce (its inputs are
        re-fetchable; a lost PRODUCER resurfaces as that re-run's own
        ``FetchFailed``).  Returns True if re-queued."""
        with self.mu:
            if not (0 <= r < self.n_reduce) \
                    or self.reduce_log[r] != LOG_COMPLETED:
                return False
            self.reduce_log[r] = LOG_UNTOUCHED
            self.c_reduce -= 1
            heapq.heappush(self._reduce_ready, r)
            self._out_locs.pop(r, None)
            self._net_counters["net_refetches"] += 1
            log_event("refetch", kind="reduce", task=r)
            print(f"coordinator: output mr-out-{r} unreachable; "
                  f"re-executing reduce {r}", file=sys.stderr)
        return True

    def refetch_shard(self, sid: int) -> bool:
        """Shard-mode re-fetch-from-replacement: the committed copy's
        server died.  Forget the commit and re-queue the shard — a NEW
        attempt id runs the first-commit-wins race afresh, so
        ``duplicate_commits`` (same-attempt double commit) stays
        structurally 0.  Returns True if re-queued."""
        with self.mu:
            shard = self._shards.get(sid)
            if shard is None or shard["committed"] is None:
                return False
            aid, _crc = shard["committed"]
            shard["committed"] = None
            shard.pop("net_loc", None)
            shard["status"] = LOG_UNTOUCHED
            for att in shard["attempts"].values():
                att["cancelled"] = True  # every old attempt is stale
            heapq.heappush(self._shard_ready, sid)
            self._net_counters["net_refetches"] += 1
            self._spec["requeues"] += 1
            log_event("refetch", kind="shard", task=sid,
                      lost_attempt=aid)
            print(f"coordinator: shard {sid} output (attempt a{aid}) "
                  f"unreachable; re-executing", file=sys.stderr)
        return True

    def net_stats(self) -> dict:
        """Net-plane counter snapshot (schema-pinned keys) plus the
        location-registry sizes — the net harness's and bench row's
        evidence surface."""
        with self.mu:
            out = dict(self._net_counters)
            out["map_locations"] = len(self._map_locs)
            out["output_locations"] = len(self._out_locs)
        return out

    # ---- internals ----

    def _touch(self, wid: str) -> None:
        """Refresh a worker's heartbeat and record the contact gap into
        its histogram.  Caller holds ``self.mu``."""
        now = time.monotonic()
        prev = self._worker_seen.get(wid)
        if prev is not None:
            self._hb_hist.setdefault(
                wid, LatencyHistogram()).record(now - prev)
        self._worker_seen[wid] = now

    def _classify(self, wid: str, now: float):
        """``(heartbeat_age_s, p99_s, presumed)`` for a worker —
        percentile-aware: silence beyond 2x the worker's OWN p99
        contact gap reads as a dead worker (its cadence stopped);
        silence still within cadence norms reads as a slow task — the
        case the backup dispatcher should split rather than abandon.
        No gap data yet → unknown, never a guess.  Caller holds
        ``self.mu``."""
        seen = self._worker_seen.get(wid)
        hb_age = round(now - seen, 3) if seen is not None else None
        h = self._hb_hist.get(wid)
        hb_p99 = (round(h.percentile(0.99), 3)
                  if h is not None and h.count else None)
        presumed = "unknown"
        if hb_age is not None and hb_p99 is not None:
            presumed = "dead" if hb_age > 2 * hb_p99 else "slow-task"
        return hb_age, hb_p99, presumed

    # ---- shard-scheduler internals (caller holds self.mu) ----

    def _shard_ckpt_root(self) -> str:
        return (self.shard_opts.get("ckpt_root")
                or os.path.join(os.path.abspath(self.config.workdir),
                                ".shards"))

    def _shard_out_path(self, sid: int) -> str:
        return os.path.join(os.path.abspath(self.config.workdir),
                            f"mr-shard-out-{sid}")

    def _shard_part_path(self, sid: int, aid: int) -> str:
        return self._shard_out_path(sid) + f".a{aid}.part"

    def _pop_untouched_shard(self, wid: str = "") -> Optional[int]:
        if self.net and wid:
            # Locality preference (net mode): a re-queued shard whose
            # best checkpoint chain was written by THIS worker resumes
            # from that chain only here — everywhere else the chain is
            # unreachable (private workdirs) and the attempt restarts
            # from zero.  Prefer it; the stale heap entry is lazily
            # invalidated like any other.
            for sid in sorted(self._shards):
                shard = self._shards[sid]
                if shard["status"] != LOG_UNTOUCHED:
                    continue
                best = self._best_resume_from(shard)
                if best is not None \
                        and shard["attempts"][best]["worker"] == wid:
                    self._net_counters["locality_hits"] += 1
                    log_event("locality_hit", kind="shard", task=sid,
                              worker=wid)
                    return sid
        while self._shard_ready:
            sid = heapq.heappop(self._shard_ready)
            if self._shards[sid]["status"] == LOG_UNTOUCHED:
                return sid
        return None

    # ---- net-plane internals (caller holds self.mu) ----

    def _preferred_host(self, r: int) -> Optional[str]:
        """The address holding the largest share of reduce partition
        ``r``'s input bytes (ties: least-loaded first, then address
        order) — Dean & Ghemawat §3.1 step 4's "takes the location of
        the input into account" applied to the shuffle."""
        share: Dict[str, int] = {}
        for m, a in self._map_locs.items():
            sizes = self._map_sizes.get(m)
            n = sizes[r] if sizes and r < len(sizes) else 0
            share[a] = share.get(a, 0) + n
        if not share:
            return None
        load: Dict[str, int] = {}
        for w in self._task_worker.values():
            a = self._net_addrs.get(w)
            if a:
                load[a] = load.get(a, 0) + 1
        addr, top = max(share.items(),
                        key=lambda kv: (kv[1], -load.get(kv[0], 0),
                                        kv[0]))
        return addr if top > 0 else None

    def _pick_reduce_locked(self, addr: str) -> Optional[int]:
        """Locality-aware reduce assignment: among the untouched reduce
        tasks prefer one whose preferred host IS the requester — its
        largest input share becomes local spool reads instead of wire
        bytes (``locality_hits`` counts these).  Falls back to the
        reference's lowest-index order; the ready heap's stale entry
        for a preferred pick is lazily invalidated."""
        if addr:
            for r in range(self.n_reduce):
                if self.reduce_log[r] != LOG_UNTOUCHED:
                    continue
                if self._preferred_host(r) == addr:
                    self._net_counters["locality_hits"] += 1
                    log_event("locality_hit", kind="reduce", task=r,
                              addr=addr)
                    return r
        return self._pop_untouched(self._reduce_ready, self.reduce_log)

    def _new_attempt(self, sid: int, wid: str, kind: str, now: float):
        """Create + arm one attempt; takeovers/backups carry the best
        known checkpoint chain as their resume hint."""
        shard = self._shards[sid]
        aid = shard["next_aid"]
        shard["next_aid"] = aid + 1
        att = {"worker": wid, "kind": kind, "assigned": now,
               "last_progress": now, "progressed": False, "confirmed": 0,
               "ckpts": 0, "cursor": 0, "resume_cursor": 0, "dead": False,
               "cancelled": False,
               "resume_from": (self._best_resume_from(shard)
                               if kind != "primary" else None)}
        shard["attempts"][aid] = att
        shard["status"] = LOG_IN_PROGRESS
        self._arm_shard_timeout(sid, aid)
        return sid, aid, shard, att

    @staticmethod
    def _best_resume_from(shard: dict) -> Optional[int]:
        """The attempt whose chain a new attempt should adopt: most
        durable checkpoints wins (dead attempts count — their chains
        are on disk; that is the whole point of resuming a killed
        shard), newest attempt breaking ties."""
        best = None
        for aid, att in shard["attempts"].items():
            if att["ckpts"] <= 0:
                continue
            if best is None or (att["ckpts"], aid) > best[1]:
                best = (aid, (att["ckpts"], aid))
        return best[0] if best is not None else None

    def _setup_grace_s(self) -> float:
        """Grace for an attempt that has never progressed: it is still
        paying engine setup (jax init + first compiles), and N cold
        attempts SERIALIZE their compiles when workers share few cores
        — so the expected setup wall is N times the single-attempt
        grace.  Scaling by the live never-progressed attempt count is
        self-correcting: as attempts start progressing the count (and
        the grace) shrinks back to ``spec_setup_s``."""
        n_setup = 0
        for shard in self._shards.values():
            for atts in ([shard["attempts"]]
                         + [s["attempts"] for s in
                            (shard["subs"] or {}).values()]):
                for a in atts.values():
                    if (not a["dead"] and not a["cancelled"]
                            and not a["progressed"]):
                        n_setup += 1
        return self.config.spec_setup_s * max(1, n_setup)

    def _maybe_backup(self, wid: str, now: float):
        """Speculative dispatch: hand this idle worker a BACKUP attempt
        of the worst suspect shard.  Two triggers, both percentile-
        aware (§3.6 — back up remaining in-progress work when it is
        abnormally SILENT or abnormally SLOW):

        * **silent** — the newest live attempt's progress-RPC silence
          exceeds ``max(spec_k * p99(its worker's contact gaps),
          spec_floor_s)``; an attempt that has never reported progress
          is still in engine setup (jax init + compiles) and gets at
          least ``spec_setup_s`` of grace;
        * **slow** — the attempt is heartbeating but its total age
          exceeds ``spec_k`` times the LONGEST committed shard's
          assignment→commit wall (only armed once a reference wall
          exists — early in the job nothing is "abnormal" yet).

        At most two live attempts per shard; never backs a worker up
        with itself."""
        ref_wall = max(self._commit_walls) if self._commit_walls else None
        best = None
        best_age = 0.0
        best_reason = ""
        for sid, shard in self._shards.items():
            if shard["committed"] is not None \
                    or shard["status"] != LOG_IN_PROGRESS:
                continue
            if shard["subs"]:
                # A re-split shard's remaining work is its subs: a
                # whole-range backup would redo bytes the subs own.
                continue
            live = [(aid, a) for aid, a in shard["attempts"].items()
                    if not a["dead"] and not a["cancelled"]]
            if not live or len(live) >= 2:
                continue
            if shard["next_aid"] >= self.config.shard_max_attempts:
                continue
            aid_f, freshest = max(live,
                                  key=lambda kv: kv[1]["last_progress"])
            if freshest["worker"] == wid:
                continue
            age = now - freshest["last_progress"]
            total_age = now - freshest["assigned"]
            h = self._hb_hist.get(freshest["worker"])
            p99 = h.percentile(0.99) if h is not None and h.count else 0.0
            thr = max(self.config.spec_k * p99, self.config.spec_floor_s)
            if not freshest["progressed"]:
                thr = max(thr, self._setup_grace_s())
            silent = age > thr
            slow = (ref_wall is not None and freshest["progressed"]
                    and total_age > self.config.spec_k * ref_wall)
            if not (silent or slow):
                continue
            if total_age > best_age:
                best, best_age = (sid, aid_f, freshest), total_age
                best_reason = "silent" if silent else "slow"
        if best is None:
            return None
        sid, aid_f, freshest = best
        shard = self._shards[sid]
        assignment = self._new_attempt(sid, wid, "backup", now)
        shard["backups"] += 1
        self._spec["backup_dispatches"] += 1
        hb_age, hb_p99, presumed = self._classify(freshest["worker"], now)
        get_registry().set_gauge("dsi_shard_backup_dispatches",
                                 self._spec["backup_dispatches"])
        log_event("backup_dispatch", kind="shard", task=sid,
                  attempt=assignment[1], straggler_attempt=aid_f,
                  straggler_worker=freshest["worker"] or None,
                  backup_worker=wid or None, reason=best_reason,
                  attempt_age_s=round(best_age, 3),
                  heartbeat_age_s=hb_age, heartbeat_p99_s=hb_p99,
                  presumed=presumed,
                  resume_from=assignment[3]["resume_from"])
        print(f"coordinator: backup dispatch shard {sid}: attempt "
              f"a{aid_f} (worker={freshest['worker'] or '?'}) "
              f"{best_reason} for {best_age:.3f}s presumed={presumed}; "
              f"backup a{assignment[1]} -> {wid or '?'} resume_from="
              f"{assignment[3]['resume_from']}", file=sys.stderr)
        return assignment

    def _requeue_shard_locked(self, sid: int) -> None:
        """Back to the ready heap with a resume hint — unless a live
        attempt remains (a backup is still running: it IS the retry),
        the shard already committed, or the attempt budget is spent
        (job fails loudly rather than looping a poisoned shard)."""
        shard = self._shards[sid]
        if shard["committed"] is not None:
            return
        if shard["subs"]:
            # The subs partition the whole range: they ARE the retry of
            # a re-split shard; never re-queue the full range.
            return
        if any(not a["dead"] and not a["cancelled"]
               for a in shard["attempts"].values()):
            return
        if shard["next_aid"] >= self.config.shard_max_attempts:
            self.job_failed = True
            log_event("shard_exhausted", kind="shard", task=sid,
                      attempts=shard["next_aid"])
            print(f"coordinator: shard {sid} failed "
                  f"{shard['next_aid']} attempts; job failed",
                  file=sys.stderr)
            return
        # The resume hint is recomputed at assignment time
        # (_new_attempt → _best_resume_from), so requeueing records
        # nothing here beyond readiness.
        shard["status"] = LOG_UNTOUCHED
        heapq.heappush(self._shard_ready, sid)
        self._spec["requeues"] += 1
        get_registry().set_gauge("dsi_shard_requeues",
                                 self._spec["requeues"])

    def _arm_shard_timeout(self, sid: int, aid: int) -> None:
        """Progress-based deadline for one attempt: the watchdog
        re-arms while progress RPCs keep landing, and presumes the
        attempt dead only after ``shard_timeout_s`` of silence.
        Caller holds ``self.mu``."""
        entry = (time.monotonic() + self.config.shard_timeout_s,
                 "shard", sid, aid)
        heapq.heappush(self._deadlines, entry)
        if self._deadlines[0] is entry:
            self._deadline_cv.notify()

    # ---- re-split internals (caller holds self.mu) ----

    @staticmethod
    def _split_resolved(shard: dict) -> bool:
        """Every sub-range of a re-split shard committed — the split as
        a whole resolved the shard."""
        subs = shard.get("subs")
        return bool(subs) and all(s["committed"] is not None
                                  for s in subs.values())

    def _shard_resolved(self, shard: dict) -> bool:
        """A shard needs no further work: its full range committed, or
        its re-split's sub-ranges all committed."""
        return shard["committed"] is not None \
            or self._split_resolved(shard)

    def _sub_out_path(self, sid: int, k: int) -> str:
        return self._shard_out_path(sid) + f".s{k}"

    def _sub_part_path(self, sid: int, k: int, aid: int) -> str:
        return self._sub_out_path(sid, k) + f".a{aid}.part"

    def _make_subs(self, sid: int, ranges, parent_chain) -> None:
        """Materialize a re-split's sub-shard state and queue every
        sub for dispatch.  ``parent_chain`` names the straggler attempt
        whose checkpoint chain sub 0 (the prefix covering the
        straggler's confirmed progress) adopts."""
        shard = self._shards[sid]
        subs = {}
        for k, (s, e) in enumerate(ranges):
            subs[k] = {"spec": (int(s), int(e)),
                       "status": LOG_UNTOUCHED, "attempts": {},
                       "next_aid": 0, "committed": None,
                       "parent_chain": (parent_chain if k == 0 else None)}
            heapq.heappush(self._sub_ready, (sid, k))
        shard["subs"] = subs

    def _pop_untouched_sub(self) -> Optional[tuple]:
        while self._sub_ready:
            sid, k = heapq.heappop(self._sub_ready)
            shard = self._shards[sid]
            if shard["committed"] is not None:
                continue  # the full-range commit overran the split
            sub = (shard["subs"] or {}).get(k)
            if sub is not None and sub["status"] == LOG_UNTOUCHED:
                return sid, k
        return None

    def _assign_sub(self, sid: int, k: int, wid: str, now: float) -> dict:
        """Create one sub-shard attempt and build its assignment reply:
        ``Start``/``End`` are the sub-range the attempt READS;
        ``TagStart``/``TagEnd`` are the parent shard's range — the
        checkpoint-chain identity tag sub 0 needs to adopt the
        straggler's chain (a chain's cursors are range-relative, and
        the parent's prefix IS sub 0's stream)."""
        shard = self._shards[sid]
        sub = shard["subs"][k]
        aid = sub["next_aid"]
        sub["next_aid"] = aid + 1
        att = {"worker": wid, "kind": "sub", "assigned": now,
               "last_progress": now, "progressed": False, "confirmed": 0,
               "ckpts": 0, "cursor": 0, "resume_cursor": 0, "dead": False,
               "cancelled": False,
               "resume_from": (self._best_resume_from(sub)
                               if sub["attempts"] else None)}
        sub["attempts"][aid] = att
        sub["status"] = LOG_IN_PROGRESS
        self._arm_sub_timeout(sid, k, aid)
        self._spec["subshard_dispatches"] += 1
        spec = shard["spec"]
        s, e = sub["spec"]
        reply = {"TaskStatus": int(TaskStatus.SHARD), "Shard": sid,
                 "Sub": k, "Attempt": aid, "Start": s, "End": e,
                 "TagStart": spec.start, "TagEnd": spec.end,
                 "Files": self.files, "NShards": self.n_shards,
                 "ResumeFrom": att["resume_from"],
                 "ParentChain": sub["parent_chain"],
                 "Knobs": self.shard_opts.get("knobs", {}),
                 "CkptRoot": self._shard_ckpt_root(),
                 "OutPart": self._sub_part_path(sid, k, aid)}
        if self.net:  # same share-nothing shape as the full-range reply
            reply["Net"] = True
            reply["OutPart"] = os.path.basename(reply["OutPart"])
            reply["CkptRoot"] = ".shards"
        log_event("assign", kind="subshard", task=sid, sub=k,
                  attempt=aid, worker=wid or None, start=s, end=e,
                  resume_from=att["resume_from"],
                  parent_chain=sub["parent_chain"])
        return reply

    def _arm_sub_timeout(self, sid: int, k: int, aid: int) -> None:
        entry = (time.monotonic() + self.config.shard_timeout_s,
                 "sub", sid, k, aid)
        heapq.heappush(self._deadlines, entry)
        if self._deadlines[0] is entry:
            self._deadline_cv.notify()

    def _maybe_resplit(self, wid: str, now: float) -> Optional[tuple]:
        """Dynamic re-split — the elastic alternative to a whole-range
        backup: when a shard's single live attempt trips the same
        percentile-aware silent/slow triggers as ``_maybe_backup``, cut
        the REMAINDER of its range (from the attempt's live reported
        cursor, newline-aligned) into sub-shards, journal the split,
        and hand the first sub to this idle worker.  The straggler is
        NOT cancelled: it keeps racing its own split, and
        first-commit-wins arbitrates (``commit_shard``).  Returns a
        dispatchable ``(sid, k)`` or None — None also when the
        remainder is too small to amortize an engine setup
        (``spec_resplit_min_bytes``), in which case the caller's backup
        path still covers the shard.  ONE split level: a sub-shard is
        never re-split, only re-queued."""
        from dsi_tpu.mr.shards import split_remaining
        from dsi_tpu.obs import span

        ref_wall = max(self._commit_walls) if self._commit_walls else None
        best = None
        best_age = 0.0
        best_reason = ""
        for sid, shard in self._shards.items():
            if shard["committed"] is not None or shard["subs"] \
                    or shard["status"] != LOG_IN_PROGRESS:
                continue
            live = [(aid, a) for aid, a in shard["attempts"].items()
                    if not a["dead"] and not a["cancelled"]]
            if len(live) != 1:
                continue  # a backup already races it; don't also split
            aid_f, freshest = live[0]
            if freshest["worker"] == wid:
                continue
            age = now - freshest["last_progress"]
            total_age = now - freshest["assigned"]
            h = self._hb_hist.get(freshest["worker"])
            p99 = h.percentile(0.99) if h is not None and h.count else 0.0
            thr = max(self.config.spec_k * p99, self.config.spec_floor_s)
            if not freshest["progressed"]:
                thr = max(thr, self._setup_grace_s())
            silent = age > thr
            slow = (ref_wall is not None and freshest["progressed"]
                    and total_age > self.config.spec_k * ref_wall)
            if not (silent or slow):
                continue
            if total_age > best_age:
                best, best_age = (sid, aid_f, freshest), total_age
                best_reason = "silent" if silent else "slow"
        if best is None:
            return None
        sid, aid_f, freshest = best
        shard = self._shards[sid]
        ranges = split_remaining(
            self.files, shard["spec"], freshest["cursor"],
            self.config.spec_resplit_ways,
            self.config.spec_resplit_min_bytes)
        if ranges is None:
            return None
        if self._journal is not None:
            # Journaled BEFORE any dispatch: a crash between this record
            # and the first sub assignment replays into exactly this
            # sub-shard state, never a half-split shard.
            self._journal.record_resplit(sid, ranges)
        parent = aid_f if freshest["ckpts"] > 0 else None
        self._make_subs(sid, ranges, parent_chain=parent)
        self._spec["resplits"] += 1
        hb_age, hb_p99, presumed = self._classify(freshest["worker"], now)
        get_registry().set_gauge("dsi_shard_resplits",
                                 self._spec["resplits"])
        with span("resplit", lane="control", task=sid):
            log_event("resplit_dispatch", kind="shard", task=sid,
                      straggler_attempt=aid_f,
                      straggler_worker=freshest["worker"] or None,
                      reason=best_reason, cursor=freshest["cursor"],
                      ranges=[[int(s), int(e)] for s, e in ranges],
                      parent_chain=parent,
                      attempt_age_s=round(best_age, 3),
                      heartbeat_age_s=hb_age, heartbeat_p99_s=hb_p99,
                      presumed=presumed)
        print(f"coordinator: re-split shard {sid}: attempt a{aid_f} "
              f"(worker={freshest['worker'] or '?'}) {best_reason} for "
              f"{best_age:.3f}s presumed={presumed}; cursor="
              f"{freshest['cursor']} -> {len(ranges)} sub-shards "
              f"{[(int(s), int(e)) for s, e in ranges]}",
              file=sys.stderr)
        return self._pop_untouched_sub()

    def _commit_sub_locked(self, shard: dict, sid: int, k: int,
                           aid: int, crc: int, wid: str) -> dict:
        """First-commit-wins for ONE sub-range (caller holds the lock):
        rename, journal the ``subshard`` record, cancel sub siblings;
        when this was the last open sub, the shard resolves "split" and
        the full-range straggler is cancelled."""
        sub = (shard["subs"] or {}).get(k)
        if sub is None:
            return {"Win": False}
        if shard["committed"] is not None or sub["committed"] is not None:
            self._spec["commit_losses"] += 1
            if sub["committed"] is not None \
                    and sub["committed"][0] == aid:
                self._spec["duplicate_commits"] += 1
            log_event("subshard_commit_lose", kind="shard", task=sid,
                      sub=k, attempt=aid, worker=wid or None)
            return {"Win": False}
        part = self._sub_part_path(sid, k, aid)
        final = self._sub_out_path(sid, k)
        try:
            os.replace(part, final)
            fsync_dir(os.path.dirname(final) or ".")
        except OSError as e:
            log_event("shard_commit_missing", kind="shard", task=sid,
                      sub=k, attempt=aid, error=str(e))
            return {"Win": False, "Error": f"partial missing: {e}"}
        if self._journal is not None:
            self._journal.record_subshard(sid, k, aid, crc)
        sub["committed"] = (aid, crc)
        sub["status"] = LOG_COMPLETED
        self._spec["subshard_commits"] += 1
        prefix = os.path.basename(final) + ".a"
        try:
            for name in os.listdir(os.path.dirname(final) or "."):
                if name.startswith(prefix) and name.endswith(".part"):
                    os.remove(os.path.join(
                        os.path.dirname(final), name))
        except OSError:
            pass
        for oaid, oatt in sub["attempts"].items():
            if oaid != aid:
                oatt["cancelled"] = True
        att = sub["attempts"].get(aid)
        if att is not None:
            att["last_progress"] = time.monotonic()
        resolved = self._split_resolved(shard)
        if resolved:
            shard["status"] = LOG_COMPLETED
            for fatt in shard["attempts"].values():
                fatt["cancelled"] = True
        log_event("subshard_commit", kind="shard", task=sid, sub=k,
                  attempt=aid, crc=crc, worker=wid or None,
                  resolved=bool(resolved))
        get_registry().set_gauge("dsi_subshard_commits",
                                 self._spec["subshard_commits"])
        return {"Win": True}

    def _requeue_sub_locked(self, sid: int, k: int) -> None:
        shard = self._shards[sid]
        sub = (shard["subs"] or {}).get(k)
        if sub is None or sub["committed"] is not None \
                or shard["committed"] is not None:
            return
        if any(not a["dead"] and not a["cancelled"]
               for a in sub["attempts"].values()):
            return
        if sub["next_aid"] >= self.config.shard_max_attempts:
            self.job_failed = True
            log_event("shard_exhausted", kind="shard", task=sid, sub=k,
                      attempts=sub["next_aid"])
            print(f"coordinator: shard {sid} sub {k} failed "
                  f"{sub['next_aid']} attempts; job failed",
                  file=sys.stderr)
            return
        sub["status"] = LOG_UNTOUCHED
        heapq.heappush(self._sub_ready, (sid, k))
        self._spec["requeues"] += 1
        get_registry().set_gauge("dsi_shard_requeues",
                                 self._spec["requeues"])

    def _expire_sub_attempt(self, sid: int, k: int, aid: int,
                            now: float) -> None:
        """The sub-shard twin of :meth:`_expire_shard_attempt`: re-arm
        while the sub attempt keeps progressing, else presume it dead
        and re-queue the sub-range."""
        shard = self._shards.get(sid)
        sub = (shard["subs"] or {}).get(k) if shard is not None else None
        att = sub["attempts"].get(aid) if sub is not None else None
        if (att is None or shard["committed"] is not None
                or sub["committed"] is not None or att["dead"]
                or att["cancelled"]):
            return
        idle = now - att["last_progress"]
        timeout = self.config.shard_timeout_s
        if not att["progressed"]:
            timeout = max(timeout, self._setup_grace_s())
        if idle < timeout:
            entry = (att["last_progress"] + timeout, "sub", sid, k, aid)
            heapq.heappush(self._deadlines, entry)
            return
        att["dead"] = True
        hb_age, hb_p99, presumed = self._classify(att["worker"], now)
        log_event("requeue", kind="subshard", task=sid, sub=k,
                  attempt=aid, timeout_s=self.config.shard_timeout_s,
                  worker=att["worker"] or None, idle_s=round(idle, 3),
                  heartbeat_age_s=hb_age, heartbeat_p99_s=hb_p99,
                  presumed=presumed,
                  reason="no progress past shard_timeout_s")
        print(f"coordinator: requeue shard {sid} sub {k} attempt "
              f"a{aid}: no progress for {idle:.3f}s (worker="
              f"{att['worker'] or '?'} presumed={presumed})",
              file=sys.stderr)
        self._requeue_sub_locked(sid, k)

    @staticmethod
    def _pop_untouched(ready: list[int], log: list[int]) -> Optional[int]:
        """Lowest untouched task index — the reference's first-match linear
        scan order (mr/coordinator.go:50-55) at O(log n).  Stale heap
        entries (task started or finished since pushed) are discarded."""
        while ready:
            i = heapq.heappop(ready)
            if log[i] == LOG_UNTOUCHED:
                return i
        return None

    def _arm_timeout(self, task_id: int, kind: str) -> None:
        """Presumed-dead-by-timeout: after task_timeout_s, if the task is
        still in-progress, reset it to untouched for reassignment
        (mr/coordinator.go:70-77,99-106).  Caller holds ``self.mu``."""
        entry = (time.monotonic() + self.config.task_timeout_s,
                 kind, task_id)
        heapq.heappush(self._deadlines, entry)
        # Wake the watchdog only when this entry becomes the earliest
        # deadline (with a constant timeout that means "heap was empty") —
        # otherwise its current sleep already covers it, and waking it on
        # every assignment would contend for self.mu on the hot path.
        if self._deadlines[0] is entry:
            self._deadline_cv.notify()

    def _watchdog(self) -> None:
        """The single straggler-monitor thread: sleep until the earliest
        armed deadline, then requeue any task still in-progress.

        A requeue is never silent (the reference reassigns without a
        word, and debugging a 10 s stall took strace-level archaeology):
        it logs the reason and the assignee's heartbeat age to stderr
        and the trace's control-plane lane, and republishes the
        per-worker heartbeat-age gauge — the signal speculative
        execution will consume (ROADMAP)."""
        with self._deadline_cv:
            while not self._closing:
                if not self._deadlines:
                    self._deadline_cv.wait()
                    continue
                now = time.monotonic()
                entry = self._deadlines[0]
                due, kind = entry[0], entry[1]
                if due > now:
                    self._deadline_cv.wait(timeout=due - now)
                    continue
                heapq.heappop(self._deadlines)
                if kind == "shard":
                    self._expire_shard_attempt(entry[2], entry[3], now)
                    continue
                if kind == "sub":
                    self._expire_sub_attempt(entry[2], entry[3],
                                             entry[4], now)
                    continue
                task_id = entry[2]
                log = self.map_log if kind == "map" else self.reduce_log
                if log[task_id] == LOG_IN_PROGRESS:
                    log[task_id] = LOG_UNTOUCHED
                    heapq.heappush(
                        self._map_ready if kind == "map"
                        else self._reduce_ready, task_id)
                    wid = self._task_worker.pop((kind, task_id), "")
                    ages = {w: round(now - t, 3)
                            for w, t in self._worker_seen.items()}
                    get_registry().set_gauge(
                        "mr_worker_heartbeat_age_s", ages)
                    # Percentile-aware classification (_classify):
                    # "dead" vs "slow-task" vs "unknown".
                    hb_age, hb_p99, presumed = self._classify(wid, now)
                    get_registry().set_gauge(
                        "mr_worker_heartbeat_hist",
                        {w: hh.snapshot()
                         for w, hh in self._hb_hist.items()})
                    log_event("requeue", kind=kind, task=task_id,
                              timeout_s=self.config.task_timeout_s,
                              worker=wid or None, heartbeat_age_s=hb_age,
                              heartbeat_p99_s=hb_p99, presumed=presumed,
                              reason="in-progress past task_timeout_s")
                    print(f"coordinator: requeue {kind} task {task_id}: "
                          f"in-progress past "
                          f"{self.config.task_timeout_s}s (worker="
                          f"{wid or '?'} heartbeat_age="
                          f"{'%.3fs' % hb_age if hb_age is not None else 'n/a'}"
                          f" p99="
                          f"{'%.3fs' % hb_p99 if hb_p99 is not None else 'n/a'}"
                          f" presumed={presumed})",
                          file=sys.stderr)

    def _expire_shard_attempt(self, sid: int, aid: int,
                              now: float) -> None:
        """One popped shard deadline: re-arm while the attempt keeps
        making progress; past ``shard_timeout_s`` of silence, presume
        it dead (percentile-classified) and re-queue the shard with a
        resume hint at its best checkpoint chain — resume-from-
        checkpoint instead of replay-from-zero.  Caller holds
        ``self.mu`` (via the deadline condvar)."""
        shard = self._shards.get(sid)
        att = shard["attempts"].get(aid) if shard is not None else None
        if (att is None or shard["committed"] is not None or att["dead"]
                or att["cancelled"]):
            return
        idle = now - att["last_progress"]
        # An attempt that never retired a step is still paying engine
        # setup (jax init + first compiles): give it the concurrency-
        # scaled setup grace before presuming it dead.
        timeout = self.config.shard_timeout_s
        if not att["progressed"]:
            timeout = max(timeout, self._setup_grace_s())
        if idle < timeout:
            entry = (att["last_progress"] + timeout, "shard", sid, aid)
            heapq.heappush(self._deadlines, entry)
            return
        att["dead"] = True
        hb_age, hb_p99, presumed = self._classify(att["worker"], now)
        log_event("requeue", kind="shard", task=sid, attempt=aid,
                  timeout_s=self.config.shard_timeout_s,
                  worker=att["worker"] or None, idle_s=round(idle, 3),
                  heartbeat_age_s=hb_age, heartbeat_p99_s=hb_p99,
                  presumed=presumed,
                  reason="no progress past shard_timeout_s")
        print(f"coordinator: requeue shard {sid} attempt a{aid}: no "
              f"progress for {idle:.3f}s (worker="
              f"{att['worker'] or '?'} presumed={presumed})",
              file=sys.stderr)
        self._requeue_shard_locked(sid)

    # ---- lifecycle (mr/coordinator.go:121-160) ----

    def serve(self) -> None:
        """Start the RPC server (reference (*Coordinator).server())."""
        methods = {
            "Coordinator.RequestTask": self.request_task,
            # Reference names, [sic] typo preserved as aliases for wire parity:
            "Coordinator.RecieveMapComplete": self.map_complete,
            "Coordinator.RecieveReduceComplete": self.reduce_complete,
            "Coordinator.MapComplete": self.map_complete,
            "Coordinator.ReduceComplete": self.reduce_complete,
            "Coordinator.FetchFailed": self.fetch_failed,
        }
        if self.shard_plan is not None:
            methods.update({
                "Coordinator.RequestShard": self.request_shard,
                "Coordinator.ShardProgress": self.shard_progress,
                "Coordinator.CommitShard": self.commit_shard,
                "Coordinator.ShardFailed": self.shard_failed,
            })
        self._server = rpc.RpcServer(self.config.sock(), methods)
        self._server.start()

    def address(self) -> Optional[str]:
        """The dialable control-plane address, or None before serve()."""
        return self._server.address if self._server is not None else None

    def done(self) -> bool:
        """Job-completion poll (mr/coordinator.go:138-142); in shard
        mode, every shard committed (or the job declared failed)."""
        with self.mu:
            if self.shard_plan is not None:
                return self.job_failed or all(
                    self._shard_resolved(shard)
                    for shard in self._shards.values())
            return self.c_reduce == self.n_reduce

    def worker_heartbeat_ages(self) -> Dict[str, float]:
        """Seconds since each known worker's last RPC — the per-worker
        heartbeat-age gauge (also published to the obs registry at
        requeue time).  The straggler signal the speculative-execution
        item will dispatch backup tasks on."""
        now = time.monotonic()
        with self.mu:
            return {w: round(now - t, 3)
                    for w, t in self._worker_seen.items()}

    def worker_heartbeat_hists(self) -> Dict[str, Dict]:
        """Per-worker contact-gap histogram snapshots (pinned
        ``obs.HIST_SNAPSHOT_KEYS``) — the distribution behind
        :meth:`straggler_suspects`."""
        with self.mu:
            return {w: h.snapshot() for w, h in self._hb_hist.items()}

    def straggler_suspects(self, k: float = 2.0) -> Dict[str, float]:
        """Workers whose current silence exceeds ``max(k · p99(their
        own contact gaps), task_timeout_s)`` — {worker: age_s}.  THE
        armed hook for speculative execution: a backup dispatcher polls
        this instead of re-deriving staleness from raw ages, so its
        decision is percentile-aware per worker (a chatty worker going
        quiet trips far sooner than one that always polled slowly)."""
        now = time.monotonic()
        out: Dict[str, float] = {}
        with self.mu:
            for w, t in self._worker_seen.items():
                age = now - t
                h = self._hb_hist.get(w)
                p99 = h.percentile(0.99) if h is not None and h.count \
                    else 0.0
                if age > max(k * p99, self.config.task_timeout_s):
                    out[w] = round(age, 3)
        return out

    def close(self) -> None:
        with self._deadline_cv:
            self._closing = True
            self._deadline_cv.notify()
        # Join the watchdog (bounded: it wakes on the notify above) so
        # close() returns with no thread still touching coordinator state
        # — daemon-abandonment left a shutdown race window (VERDICT r3
        # nit).  join() on a finished thread returns immediately, so
        # repeated close() calls are safe.
        self._monitor.join(timeout=5.0)
        if self._server is not None:
            self._server.close()
            self._server = None
        if self._journal is not None:
            self._journal.close()


def make_coordinator(files: List[str], n_reduce: int,
                     config: JobConfig | None = None) -> Coordinator:
    """Construct state and start the RPC server (mr/coordinator.go:149-160)."""
    c = Coordinator(files, n_reduce, config)
    c.serve()
    return c
