"""Coordinator: job state + pull-based task scheduler + RPC server.

Reference: ``mr/coordinator.go`` (entire file, 160 LoC).  Same state machine:

* per-task logs with states 0=untouched / 1=in-progress / 2=completed
  (coordinator.go:16,20),
* map tasks are assigned first; **no reduce task is assigned until every map
  has completed** — the `cMap == nMap` barrier (coordinator.go:47,79), which is
  load-bearing for correctness (reduce must see all mr-*-r files),
* a task in-progress for `task_timeout_s` (10 s) is re-queued for another
  worker — presumed-dead-by-timeout fault tolerance (coordinator.go:70-77,
  99-106),
* `Done()` is `c_reduce == n_reduce` under the lock (coordinator.go:138-142).

Two reference defects documented in SURVEY.md §5 are fixed here (both
output-invariant):

1. **Unique-transition completion counting.**  The reference increments
   `cMap`/`cReduce` on every completion RPC (coordinator.go:30-31,38-39), so a
   re-queued task finished by two workers double-counts and can prematurely
   satisfy the map barrier or `Done()`.  We count only the first transition of
   a task's log to COMPLETED.
2. The waiting busy-poll fix lives in the worker (see worker.py).
"""

from __future__ import annotations

import heapq
import os
import sys
import threading
import time
from typing import Dict, List, Optional

from dsi_tpu.config import JobConfig
from dsi_tpu.obs import LatencyHistogram, get_registry
from dsi_tpu.mr import rpc
from dsi_tpu.mr.journal import Journal
from dsi_tpu.mr.types import (LOG_COMPLETED, LOG_IN_PROGRESS, LOG_UNTOUCHED,
                              TaskStatus)
from dsi_tpu.utils.tracing import log_event


class Coordinator:
    """Owns all job state; hands out tasks on pull (mr/coordinator.go:14-25)."""

    def __init__(self, files: List[str], n_reduce: int, config: JobConfig | None = None):
        self.config = config or JobConfig(n_reduce=n_reduce)
        self.files = list(files)
        self.n_map = len(files)
        self.c_map = 0
        self.map_log = [LOG_UNTOUCHED] * self.n_map
        self.n_reduce = n_reduce
        self.c_reduce = 0
        self.reduce_log = [LOG_UNTOUCHED] * n_reduce
        # Assignment heaps: lowest untouched index first — the same order
        # as the reference's linear scan (mr/coordinator.go:50-55), O(log n)
        # per assignment instead of O(n) (which is O(n^2) across a big
        # job).  Entries are lazily invalidated: pop until one is still
        # UNTOUCHED; requeue pushes the index back.
        self._map_ready = list(range(self.n_map))
        self._reduce_ready = list(range(n_reduce))
        self.mu = threading.Lock()
        # Worker liveness (observability + the speculative-execution
        # hook): last-contact time per WorkerId — every RPC carrying an
        # id refreshes it — and which worker holds each in-progress
        # task, so a requeue can report WHOSE heartbeat went stale and
        # how stale it was (the reference reassigns silently,
        # coordinator.go:70-77).
        self._worker_seen: Dict[str, float] = {}
        self._task_worker: Dict[tuple, str] = {}
        # Per-worker contact-GAP histograms (obs/hist.py): every RPC
        # records the gap since the worker's previous contact, so a
        # requeue can compare the stale worker's current silence to its
        # own p99 gap — "presumed dead" (silence way past anything it
        # ever did) vs "slow task" (still phoning home, the task is
        # just long).  The percentile-aware signal the speculative-
        # execution item dispatches backup tasks on.
        self._hb_hist: Dict[str, LatencyHistogram] = {}
        # Straggler watchdog: ONE monitor thread over a deadline heap
        # replaces the reference's goroutine-per-assignment
        # (mr/coordinator.go:70-77,99-106) — a per-task Timer thread melts
        # at ~10^4 tasks (~0.4 ms spawn each, thousands of live threads);
        # the heap is O(log n) per assignment and one thread total.
        self._deadlines: list[tuple[float, str, int]] = []
        self._deadline_cv = threading.Condition(self.mu)
        self._closing = False
        self._monitor = threading.Thread(target=self._watchdog,
                                         name="dsi-mr-watchdog", daemon=True)
        self._monitor.start()
        self._server: Optional[rpc.RpcServer] = None

        # Clear stale mr-out-* so a leftover file from a PREVIOUS job in the
        # same cwd can't win the workers' first-writer-wins output commit
        # (atomicio.py) — preserving reference rerun-overwrites behavior at
        # job granularity.  NOT on journal resume: there, a
        # committed-but-unjournaled mr-out-<r> whose intermediates were
        # already GC'd is the only surviving copy of that partition, and
        # deleting it would make the re-run reducer commit an empty file.
        # This must happen BEFORE the journal file is created below: a crash
        # between journal creation and the clear would otherwise look like a
        # resume forever and skip the clear.
        resuming = bool(self.config.journal_path
                        and os.path.exists(self.config.journal_path))
        if not resuming:
            try:
                stale = [n for n in os.listdir(self.config.workdir)
                         if n.startswith("mr-out-")]
            except OSError:
                stale = []
            for name in stale:  # ALL partitions, incl. a previous job's
                try:            # higher-numbered ones (n_reduce may shrink)
                    os.remove(os.path.join(self.config.workdir, name))
                except OSError:
                    pass

        # Optional checkpoint/resume (journal.py; disabled by default — the
        # reference keeps coordinator state purely in-memory).
        self._journal: Optional[Journal] = None
        if self.config.journal_path:
            self._journal = Journal(self.config.journal_path, self.files,
                                    self.n_reduce)
            done_maps, done_reduces = self._journal.replay()
            for t in done_maps:
                if self.map_log[t] != LOG_COMPLETED:
                    self.map_log[t] = LOG_COMPLETED
                    self.c_map += 1
            for t in done_reduces:
                if self.reduce_log[t] != LOG_COMPLETED:
                    self.reduce_log[t] = LOG_COMPLETED
                    self.c_reduce += 1
            self._journal.open()

    # ---- RPC handlers (the wire API, mr/coordinator.go:27-114) ----

    def request_task(self, args: dict) -> dict:
        """Assign a map task, a reduce task, "waiting", or "done"
        (mr/coordinator.go:43-114)."""
        reply = {"TaskStatus": int(TaskStatus.WAITING), "NMap": self.n_map,
                 "CMap": 0, "NReduce": self.n_reduce, "CReduce": 0, "Filename": ""}
        wid = str(args.get("WorkerId") or "")
        with self.mu:
            if wid:
                self._touch(wid)
            if self.c_map < self.n_map:
                tba = self._pop_untouched(self._map_ready, self.map_log)
                if tba is None:
                    reply["TaskStatus"] = int(TaskStatus.WAITING)  # :58-60
                else:
                    self.map_log[tba] = LOG_IN_PROGRESS  # :62
                    reply["TaskStatus"] = int(TaskStatus.MAP)
                    reply["Filename"] = self.files[tba]
                    reply["CMap"] = tba
                    self._arm_timeout(tba, "map")  # :70-77
                    if wid:
                        self._task_worker[("map", tba)] = wid
                    log_event("assign", kind="map", task=tba,
                              file=self.files[tba], worker=wid or None)
            elif self.c_reduce < self.n_reduce:  # map barrier passed (:79)
                tba = self._pop_untouched(self._reduce_ready, self.reduce_log)
                if tba is None:
                    reply["TaskStatus"] = int(TaskStatus.WAITING)
                else:
                    self.reduce_log[tba] = LOG_IN_PROGRESS
                    reply["TaskStatus"] = int(TaskStatus.REDUCE)
                    reply["CReduce"] = tba
                    self._arm_timeout(tba, "reduce")  # :99-106
                    if wid:
                        self._task_worker[("reduce", tba)] = wid
                    log_event("assign", kind="reduce", task=tba,
                              worker=wid or None)
            else:
                reply["TaskStatus"] = int(TaskStatus.DONE)  # :109-112
        return reply

    def map_complete(self, args: dict) -> dict:
        """Reference: RecieveMapComplete [sic] (mr/coordinator.go:27-33), with
        the unique-transition counting fix."""
        t = int(args["TaskNumber"])
        wid = str(args.get("WorkerId") or "")
        with self.mu:
            if wid:
                self._touch(wid)
            self._task_worker.pop(("map", t), None)
            if self.map_log[t] != LOG_COMPLETED:  # fix: count first completion only
                self.map_log[t] = LOG_COMPLETED
                self.c_map += 1
                if self._journal is not None:
                    self._journal.record("map", t)
                log_event("complete", kind="map", task=t, c_map=self.c_map,
                          worker=wid or None)
            else:
                log_event("duplicate_completion", kind="map", task=t)
        return {}

    def reduce_complete(self, args: dict) -> dict:
        """Reference: RecieveReduceComplete [sic] (mr/coordinator.go:35-41)."""
        t = int(args["TaskNumber"])
        wid = str(args.get("WorkerId") or "")
        with self.mu:
            if wid:
                self._touch(wid)
            self._task_worker.pop(("reduce", t), None)
            if self.reduce_log[t] != LOG_COMPLETED:
                self.reduce_log[t] = LOG_COMPLETED
                self.c_reduce += 1
                if self._journal is not None:
                    self._journal.record("reduce", t)
                log_event("complete", kind="reduce", task=t,
                          c_reduce=self.c_reduce, worker=wid or None)
            else:
                log_event("duplicate_completion", kind="reduce", task=t)
        return {}

    # ---- internals ----

    def _touch(self, wid: str) -> None:
        """Refresh a worker's heartbeat and record the contact gap into
        its histogram.  Caller holds ``self.mu``."""
        now = time.monotonic()
        prev = self._worker_seen.get(wid)
        if prev is not None:
            self._hb_hist.setdefault(
                wid, LatencyHistogram()).record(now - prev)
        self._worker_seen[wid] = now

    @staticmethod
    def _pop_untouched(ready: list[int], log: list[int]) -> Optional[int]:
        """Lowest untouched task index — the reference's first-match linear
        scan order (mr/coordinator.go:50-55) at O(log n).  Stale heap
        entries (task started or finished since pushed) are discarded."""
        while ready:
            i = heapq.heappop(ready)
            if log[i] == LOG_UNTOUCHED:
                return i
        return None

    def _arm_timeout(self, task_id: int, kind: str) -> None:
        """Presumed-dead-by-timeout: after task_timeout_s, if the task is
        still in-progress, reset it to untouched for reassignment
        (mr/coordinator.go:70-77,99-106).  Caller holds ``self.mu``."""
        entry = (time.monotonic() + self.config.task_timeout_s,
                 kind, task_id)
        heapq.heappush(self._deadlines, entry)
        # Wake the watchdog only when this entry becomes the earliest
        # deadline (with a constant timeout that means "heap was empty") —
        # otherwise its current sleep already covers it, and waking it on
        # every assignment would contend for self.mu on the hot path.
        if self._deadlines[0] is entry:
            self._deadline_cv.notify()

    def _watchdog(self) -> None:
        """The single straggler-monitor thread: sleep until the earliest
        armed deadline, then requeue any task still in-progress.

        A requeue is never silent (the reference reassigns without a
        word, and debugging a 10 s stall took strace-level archaeology):
        it logs the reason and the assignee's heartbeat age to stderr
        and the trace's control-plane lane, and republishes the
        per-worker heartbeat-age gauge — the signal speculative
        execution will consume (ROADMAP)."""
        with self._deadline_cv:
            while not self._closing:
                if not self._deadlines:
                    self._deadline_cv.wait()
                    continue
                now = time.monotonic()
                due, kind, task_id = self._deadlines[0]
                if due > now:
                    self._deadline_cv.wait(timeout=due - now)
                    continue
                heapq.heappop(self._deadlines)
                log = self.map_log if kind == "map" else self.reduce_log
                if log[task_id] == LOG_IN_PROGRESS:
                    log[task_id] = LOG_UNTOUCHED
                    heapq.heappush(
                        self._map_ready if kind == "map"
                        else self._reduce_ready, task_id)
                    wid = self._task_worker.pop((kind, task_id), "")
                    seen = self._worker_seen.get(wid)
                    hb_age = (round(now - seen, 3)
                              if seen is not None else None)
                    ages = {w: round(now - t, 3)
                            for w, t in self._worker_seen.items()}
                    get_registry().set_gauge(
                        "mr_worker_heartbeat_age_s", ages)
                    # Percentile-aware classification: silence beyond
                    # 2× the worker's own p99 contact gap reads as a
                    # dead worker (its cadence stopped, not just this
                    # task); silence still within cadence norms reads
                    # as a slow task — the case a backup dispatcher
                    # should prefer to split rather than abandon.  No
                    # gap data yet → unknown, never a guess.
                    h = self._hb_hist.get(wid)
                    hb_p99 = (round(h.percentile(0.99), 3)
                              if h is not None and h.count else None)
                    presumed = "unknown"
                    if hb_age is not None and hb_p99 is not None:
                        presumed = ("dead" if hb_age > 2 * hb_p99
                                    else "slow-task")
                    get_registry().set_gauge(
                        "mr_worker_heartbeat_hist",
                        {w: hh.snapshot()
                         for w, hh in self._hb_hist.items()})
                    log_event("requeue", kind=kind, task=task_id,
                              timeout_s=self.config.task_timeout_s,
                              worker=wid or None, heartbeat_age_s=hb_age,
                              heartbeat_p99_s=hb_p99, presumed=presumed,
                              reason="in-progress past task_timeout_s")
                    print(f"coordinator: requeue {kind} task {task_id}: "
                          f"in-progress past "
                          f"{self.config.task_timeout_s}s (worker="
                          f"{wid or '?'} heartbeat_age="
                          f"{'%.3fs' % hb_age if hb_age is not None else 'n/a'}"
                          f" p99="
                          f"{'%.3fs' % hb_p99 if hb_p99 is not None else 'n/a'}"
                          f" presumed={presumed})",
                          file=sys.stderr)

    # ---- lifecycle (mr/coordinator.go:121-160) ----

    def serve(self) -> None:
        """Start the RPC server (reference (*Coordinator).server())."""
        self._server = rpc.RpcServer(self.config.sock(), {
            "Coordinator.RequestTask": self.request_task,
            # Reference names, [sic] typo preserved as aliases for wire parity:
            "Coordinator.RecieveMapComplete": self.map_complete,
            "Coordinator.RecieveReduceComplete": self.reduce_complete,
            "Coordinator.MapComplete": self.map_complete,
            "Coordinator.ReduceComplete": self.reduce_complete,
        })
        self._server.start()

    def address(self) -> Optional[str]:
        """The dialable control-plane address, or None before serve()."""
        return self._server.address if self._server is not None else None

    def done(self) -> bool:
        """Job-completion poll (mr/coordinator.go:138-142)."""
        with self.mu:
            return self.c_reduce == self.n_reduce

    def worker_heartbeat_ages(self) -> Dict[str, float]:
        """Seconds since each known worker's last RPC — the per-worker
        heartbeat-age gauge (also published to the obs registry at
        requeue time).  The straggler signal the speculative-execution
        item will dispatch backup tasks on."""
        now = time.monotonic()
        with self.mu:
            return {w: round(now - t, 3)
                    for w, t in self._worker_seen.items()}

    def worker_heartbeat_hists(self) -> Dict[str, Dict]:
        """Per-worker contact-gap histogram snapshots (pinned
        ``obs.HIST_SNAPSHOT_KEYS``) — the distribution behind
        :meth:`straggler_suspects`."""
        with self.mu:
            return {w: h.snapshot() for w, h in self._hb_hist.items()}

    def straggler_suspects(self, k: float = 2.0) -> Dict[str, float]:
        """Workers whose current silence exceeds ``max(k · p99(their
        own contact gaps), task_timeout_s)`` — {worker: age_s}.  THE
        armed hook for speculative execution: a backup dispatcher polls
        this instead of re-deriving staleness from raw ages, so its
        decision is percentile-aware per worker (a chatty worker going
        quiet trips far sooner than one that always polled slowly)."""
        now = time.monotonic()
        out: Dict[str, float] = {}
        with self.mu:
            for w, t in self._worker_seen.items():
                age = now - t
                h = self._hb_hist.get(w)
                p99 = h.percentile(0.99) if h is not None and h.count \
                    else 0.0
                if age > max(k * p99, self.config.task_timeout_s):
                    out[w] = round(age, 3)
        return out

    def close(self) -> None:
        with self._deadline_cv:
            self._closing = True
            self._deadline_cv.notify()
        # Join the watchdog (bounded: it wakes on the notify above) so
        # close() returns with no thread still touching coordinator state
        # — daemon-abandonment left a shutdown race window (VERDICT r3
        # nit).  join() on a finished thread returns immediately, so
        # repeated close() calls are safe.
        self._monitor.join(timeout=5.0)
        if self._server is not None:
            self._server.close()
            self._server = None
        if self._journal is not None:
            self._journal.close()


def make_coordinator(files: List[str], n_reduce: int,
                     config: JobConfig | None = None) -> Coordinator:
    """Construct state and start the RPC server (mr/coordinator.go:149-160)."""
    c = Coordinator(files, n_reduce, config)
    c.serve()
    return c
