from dsi_tpu.mr.types import KeyValue, TaskStatus  # noqa: F401
from dsi_tpu.mr.coordinator import Coordinator, make_coordinator  # noqa: F401
from dsi_tpu.mr.worker import worker_loop  # noqa: F401
