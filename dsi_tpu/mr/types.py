"""Core record and protocol types.

Reference: ``KeyValue{Key, Value string}`` (``mr/worker.go:17-20``) and the
``TaskStatus`` integer protocol 0=map, 1=reduce, 2=waiting, 3=done
(``mr/rpc.go:22-33``).
"""

from __future__ import annotations

import enum
from typing import NamedTuple


class KeyValue(NamedTuple):
    """The record type apps produce and consume (mr/worker.go:17-20)."""

    key: str
    value: str


class TaskStatus(enum.IntEnum):
    """Wire-level task status (mr/rpc.go:23: 0 map, 1 reduce, 2 wait, 3 done).

    ``SHARD`` extends the protocol for streaming-shard jobs
    (``mr/shards.py``): the assignment names a cursor range + attempt
    instead of a file — values 0-3 keep their reference meaning."""

    MAP = 0
    REDUCE = 1
    WAITING = 2
    DONE = 3
    SHARD = 4


# Task-log states inside the coordinator (mr/coordinator.go:16: 0 never
# touched, 1 in-progress, 2 completed).
LOG_UNTOUCHED = 0
LOG_IN_PROGRESS = 1
LOG_COMPLETED = 2
