"""Sequential oracle: the semantic definition of correctness.

Reference: ``main/mrsequential.go:25-87`` — read every input file, run the app
Map over each, concatenate, ONE global sort by key (no partitioning,
mrsequential.go:53-59), group runs of equal keys, run Reduce, write every line
to a single ``mr-out-0`` in ``"%v %v\n"`` format (mrsequential.go:61-86).

The distributed system's merged, sorted output must byte-compare equal to this
(test-mr.sh:30-31,52-53) — that differential check is this repo's primary
correctness test and the parity metric in BASELINE.md.
"""

from __future__ import annotations

import os
from typing import List, Sequence

from dsi_tpu.mr.types import KeyValue
from dsi_tpu.mr.worker import MapFn, ReduceFn, group_and_reduce
from dsi_tpu.utils.atomicio import atomic_write


def run_sequential(mapf: MapFn, reducef: ReduceFn, files: Sequence[str],
                   out_path: str = "mr-out-0") -> str:
    intermediate: List[KeyValue] = []
    for filename in files:  # mrsequential.go:39-51
        with open(filename, "rb") as f:
            contents = f.read().decode("utf-8", errors="replace")
        intermediate.extend(mapf(filename, contents))
    with atomic_write(out_path) as out:  # one global sort + group (:59-86)
        group_and_reduce(intermediate, reducef, out)
    return os.path.abspath(out_path)
