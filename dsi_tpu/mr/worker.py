"""Worker: pull-loop task executor.

Reference: ``mr/worker.go`` (188 LoC).  Same loop: request a task; execute a
map or reduce task; report completion; exit when the coordinator says DONE or
becomes unreachable (``worker.go:46-165``).  Same data-plane contract:

* map writes NReduce intermediate files ``mr-<m>-<r>``, JSON records, committed
  by temp-file + atomic rename (worker.go:81-92),
* the partitioner is ``fnv32a(key) & 0x7fffffff  %  NReduce`` — bit-for-bit the
  reference's ``ihash`` (worker.go:33-37,76),
* reduce reads every ``mr-*-<r>``, *tolerating missing files*
  (worker.go:106-108), sorts by key, groups runs of equal keys, calls
  ``reducef(key, values)``, writes lines ``f"{key} {output}\n"`` — the Go
  ``"%v %v\n"`` format (worker.go:144) — commits ``mr-out-<r>`` atomically,
  then garbage-collects its intermediates (worker.go:151-154).

Intermediate record encoding: one JSON object per line, ``{"Key": k,
"Value": v}`` — byte-compatible with Go's ``json.Encoder`` stream of
``mr.KeyValue`` (worker.go:84-90).

Deviation (SURVEY.md §3.3, output-invariant): on TaskStatus=WAITING the
reference busy-polls over RPC with no backoff (no case 2 in its switch);
we sleep ``wait_sleep_s`` between polls.
"""

from __future__ import annotations

import json
import os
import time
import zlib
from typing import Callable, List, Sequence

from dsi_tpu.config import JobConfig
from dsi_tpu.mr import rpc
from dsi_tpu.mr.types import KeyValue, TaskStatus
# Leader-discovery shim (dsi_tpu/replica): DSI_MR_SOCKET may name a
# comma-separated coordinator GROUP; group_call follows NotLeader
# redirects and rides out elections.  A single address passes straight
# through to rpc.call, so the classic plane is unchanged.
from dsi_tpu.replica.client import group_call
from dsi_tpu.utils.atomicio import atomic_write
from dsi_tpu.utils.tracing import Span

MapFn = Callable[[str, str], List[KeyValue]]
ReduceFn = Callable[[str, List[str]], str]


def fnv32a(data: bytes) -> int:
    """FNV-1a 32-bit hash, exactly Go's hash/fnv.New32a (worker.go:33-37)."""
    h = 0x811C9DC5
    for b in data:
        h ^= b
        h = (h * 0x01000193) & 0xFFFFFFFF
    return h


def ihash(key: str) -> int:
    """Reference ihash: fnv32a(key) & 0x7fffffff (worker.go:33-37)."""
    return fnv32a(key.encode("utf-8")) & 0x7FFFFFFF


def intermediate_name(map_task: int, reduce_task: int, workdir: str = ".") -> str:
    return os.path.join(workdir, f"mr-{map_task}-{reduce_task}")


def output_name(reduce_task: int, workdir: str = ".") -> str:
    return os.path.join(workdir, f"mr-out-{reduce_task}")


def write_intermediates(kva: Sequence[KeyValue], map_task: int, n_reduce: int,
                        workdir: str = ".") -> None:
    """Partition by ihash and commit NReduce files atomically
    (worker.go:74-92).

    The partition + serialize pass runs through the native C encoder when
    available (dsi_tpu/native — one pass fusing the per-byte hash,
    json.dumps, and bucketing loops); the Python path below is the exact
    fallback, and both produce records every decoder accepts."""
    from dsi_tpu import native

    blobs = native.encode_partitions(kva, n_reduce)
    if blobs is not None:
        for r, blob in enumerate(blobs):
            with atomic_write(intermediate_name(map_task, r, workdir),
                              mode="wb") as f:
                f.write(blob)
        return
    buckets: list[list[KeyValue]] = [[] for _ in range(n_reduce)]
    for kv in kva:
        buckets[ihash(kv.key) % n_reduce].append(kv)
    for r, bucket in enumerate(buckets):
        with atomic_write(intermediate_name(map_task, r, workdir)) as f:
            for kv in bucket:
                f.write(json.dumps({"Key": kv.key, "Value": kv.value}))
                f.write("\n")


def read_intermediates(reduce_task: int, n_map: int,
                       workdir: str = ".") -> list[KeyValue]:
    """Read all mr-<i>-<r>, skipping missing files (worker.go:102-121).

    Per-file the native C++ decoder (dsi_tpu/native) is tried first; it
    returns None for anything it can't prove it parsed completely, in which
    case the lenient Python decoder below — the reference's exact
    break-on-bad-record semantics — takes over for that file.
    """
    from dsi_tpu import native

    out: list[KeyValue] = []
    for i in range(n_map):
        path = intermediate_name(i, reduce_task, workdir)
        pairs = native.decode_kv_file(path)
        if pairs is not None:
            out.extend(KeyValue(k, v) for k, v in pairs)
            continue
        try:
            # Explicit utf-8: the native encoder writes raw UTF-8, and the
            # locale default must not reinterpret (or reject) those bytes.
            f = open(path, "r", encoding="utf-8")
        except OSError:
            continue  # tolerated: worker.go:106-108
        with f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    obj = json.loads(line)
                except json.JSONDecodeError:
                    break  # truncated record: reference's decoder break (worker.go:117)
                out.append(KeyValue(obj["Key"], obj["Value"]))
    return out


def group_and_reduce(intermediate: list[KeyValue], reducef: ReduceFn, out) -> None:
    """Sort by key, group runs of equal keys, reduce, format "%v %v\n"
    (worker.go:124-146; identical grouping in main/mrsequential.go:59-84)."""
    intermediate.sort(key=lambda kv: kv.key)
    i = 0
    n = len(intermediate)
    while i < n:
        j = i + 1
        while j < n and intermediate[j].key == intermediate[i].key:
            j += 1
        values = [intermediate[k].value for k in range(i, j)]
        out.write(f"{intermediate[i].key} {reducef(intermediate[i].key, values)}\n")
        i = j


def run_map_task(mapf: MapFn, filename: str, map_task: int, n_reduce: int,
                 workdir: str = ".") -> None:
    """One map task: read the split, run the app map, partition + commit
    (worker.go:55-92)."""
    with open(filename, "rb") as f:
        contents = f.read().decode("utf-8", errors="replace")
    kva = mapf(filename, contents)
    write_intermediates(kva, map_task, n_reduce, workdir)


def run_reduce_task(reducef: ReduceFn, reduce_task: int, n_map: int,
                    workdir: str = ".") -> None:
    """One reduce task: gather, sort, group, reduce, commit, GC
    (worker.go:99-154).

    The output commit is FIRST-writer-wins (utils/atomicio.py): a re-queued
    duplicate of this task that read ``mr-*-<r>`` after this run's GC below
    would otherwise rename an empty ``mr-out-<r>`` over the full one — the
    reference's latent duplicate-reduce race (worker.go:148,151-154), which
    its 10 s timeout hides but a tiny-timeout soak reproduces.  The
    coordinator clears stale ``mr-out-*`` at job start so reruns in the
    same cwd still overwrite (reference rerun behavior)."""
    intermediate = read_intermediates(reduce_task, n_map, workdir)
    with atomic_write(output_name(reduce_task, workdir),
                      first_wins=True) as out:
        group_and_reduce(intermediate, reducef, out)
    for i in range(n_map):  # GC intermediates, errors ignored (worker.go:151-154)
        try:
            os.remove(intermediate_name(i, reduce_task, workdir))
        except OSError:
            pass


def worker_loop(mapf: MapFn, reducef: ReduceFn,
                config: JobConfig | None = None,
                task_runner=None, partsrv=None) -> None:
    """The worker's task loop (mr.Worker, worker.go:43-165).

    `task_runner`, if given, is an object with run_map/run_reduce methods used
    instead of the host-Python execution above — this is the backend seam the
    TPU path plugs into (backends/tpu.py).

    `partsrv`, if given, is this worker's :class:`dsi_tpu.net.PartitionServer`
    (already started) and switches the loop to the NET data plane (ISSUE 17):
    every RPC carries the server's address, map completions register the
    partition locations + per-partition byte sizes with the coordinator, and
    a reduce assignment carrying ``Net``/``MapLocs`` shuffles over TCP
    (``net/fetch.run_reduce_task_net``) instead of reading a shared
    directory — a failed fetch is reported as ``Coordinator.FetchFailed``
    (the producer re-executes, §3.4) and the reduce is retried later.
    """
    import sys

    cfg = config or JobConfig()
    sock = cfg.sock()
    tasks_done = 0
    addr = partsrv.address if partsrv is not None else None
    net_stats = None
    if partsrv is not None:
        from dsi_tpu.obs import metrics_scope

        net_stats = metrics_scope("net")
    # Task-latency histogram (obs/hist.py), published as a registry
    # gauge after every task: lands in this process's trace-meta
    # snapshot and any ``/statusz`` peephole, and gives the
    # speculative-execution hook the worker-side view (how long do MY
    # tasks take) to pair with the coordinator's heartbeat percentiles.
    from dsi_tpu.obs import LatencyHistogram, get_registry

    task_hist = LatencyHistogram()

    def note_task(seconds: float) -> None:
        task_hist.record(seconds)
        get_registry().set_gauge("mr_worker_task_hist",
                                 task_hist.snapshot())
    # Stable per-process identity, sent with every RPC: the coordinator
    # keys its per-worker heartbeat-age gauge on it (a requeue can then
    # say WHOSE heartbeat went stale — and the speculative-execution
    # hook reads the same gauge).  Old coordinators ignore the extra key.
    worker_id = f"w{os.getpid()}"

    def report_complete(method: str, task_number: int,
                        extra: dict | None = None) -> bool:
        """Completion RPC; False means the loop must exit.  An auth
        rejection is always LOUD — a misconfigured worker must not look
        like a clean end-of-job exit."""
        args = {"TaskNumber": task_number, "WorkerId": worker_id}
        if extra:
            args.update(extra)
        try:
            group_call(sock, method, args)
            return True
        except rpc.AuthError as e:
            print(f"mrworker: {e}", file=sys.stderr)
            return False
        except rpc.CoordinatorGone:
            return False

    def net_snapshot() -> dict:
        return dict(net_stats) if net_stats is not None else {}

    def net_deltas(before: dict) -> dict:
        """Per-task net-attribution deltas for the completion RPC (the
        coordinator aggregates job-wide; totals would double-count)."""
        if net_stats is None:
            return {}
        out = {wire: int(net_stats.get(k, 0)) - int(before.get(k, 0))
               for wire, k in (("NetFetches", "net_fetches"),
                               ("NetLocal", "net_local_reads"),
                               ("NetRaw", "net_bytes_raw"),
                               ("NetWire", "net_bytes_wire"),
                               ("NetFailures", "net_fetch_failures"))}
        # Overlap attribution (ISSUE 18): wall-second deltas stay float;
        # the prefetch window is a gauge (coordinator folds it as max).
        for wire, k in (("NetWait", "net_fetch_wait_s"),
                        ("NetOverlap", "net_overlap_s")):
            out[wire] = round(float(net_stats.get(k, 0.0))
                              - float(before.get(k, 0.0)), 6)
        out["NetWindow"] = int(net_stats.get("net_prefetch_window", 0))
        return out

    # Chaos injection (DSI_CHAOS_WORKER_KILL=p[,seed], ckpt/fault.py): a
    # real os._exit with probability p at every task boundary, so
    # kill/recovery grids are deterministic and scriptable.  Imported
    # HERE, not at module top: the control plane stays importable on a
    # bare interpreter (the ckpt package init pulls numpy).
    from dsi_tpu.ckpt.fault import chaos_kill_point

    while True:
        chaos_kill_point("task")
        req = {"TaskNumber": 0, "WorkerId": worker_id}
        if addr:
            req["Addr"] = addr
        try:
            ok, reply = group_call(sock, "Coordinator.RequestTask", req)
        except rpc.CoordinatorGone as e:
            # Coordinator exited; the reference worker dies here
            # (worker.go:176-178).  Normal at end-of-job; noteworthy if this
            # worker never got a single task, and always loud for an auth
            # rejection (see report_complete).
            if tasks_done == 0 or isinstance(e, rpc.AuthError):
                print(f"mrworker: coordinator unreachable: {e}", file=sys.stderr)
            break
        if not ok or reply is None or reply["TaskStatus"] == int(TaskStatus.DONE):
            break  # worker.go:51-53
        status = reply["TaskStatus"]
        if status == int(TaskStatus.MAP):
            # Span → DSI_TRACE=1 yields a per-task timeline (the tracing
            # layer the reference lacks entirely, SURVEY.md §5).
            with Span("worker.map", task=reply["CMap"],
                      file=reply["Filename"]) as sp:
                if task_runner is not None:
                    task_runner.run_map(mapf, reply["Filename"], reply["CMap"],
                                        reply["NReduce"], cfg.workdir)
                else:
                    run_map_task(mapf, reply["Filename"], reply["CMap"],
                                 reply["NReduce"], cfg.workdir)
            note_task(sp.elapsed_s)
            tasks_done += 1
            extra = None
            if addr:
                # Register the partition locations (§3.1): this spool
                # serves mr-<m>-*; the byte sizes feed the locality-
                # share placement policy.
                sizes = []
                for r in range(int(reply["NReduce"])):
                    try:
                        sizes.append(os.path.getsize(intermediate_name(
                            reply["CMap"], r, cfg.workdir)))
                    except OSError:
                        sizes.append(0)
                extra = {"Addr": addr, "PartSizes": sizes}
            if not report_complete("Coordinator.RecieveMapComplete",
                                   reply["CMap"], extra):
                break
        elif status == int(TaskStatus.REDUCE):
            if reply.get("Net") and addr:
                # NET data plane: shuffle over TCP from the producers'
                # partition servers (ISSUE 17).
                from dsi_tpu.net.fetch import (FetchFailure,
                                               run_reduce_task_net)

                before = net_snapshot()
                try:
                    with Span("worker.reduce", task=reply["CReduce"],
                              net=1) as sp:
                        out_name = run_reduce_task_net(
                            reducef, reply["CReduce"],
                            reply.get("MapLocs") or {},
                            workdir=cfg.workdir, own_addr=addr,
                            stats=net_stats,
                            timeout=cfg.net_fetch_timeout_s,
                            window=cfg.net_fetch_window)
                except FetchFailure as e:
                    # The producer's server is gone: hand the failure
                    # to the coordinator (it re-executes the map, §3.4)
                    # and go back to the well — this reduce re-runs
                    # after the map barrier reopens.
                    try:
                        group_call(sock, "Coordinator.FetchFailed",
                                   {"Map": e.task,
                                    "Reduce": reply["CReduce"],
                                    "WorkerId": worker_id,
                                    "Addr": e.addr})
                    except rpc.CoordinatorGone:
                        break
                    print(f"mrworker: fetch failed ({e}); reported, "
                          "retrying later", file=sys.stderr)
                    continue
                note_task(sp.elapsed_s)
                tasks_done += 1
                extra = net_deltas(before)
                extra["Addr"] = addr
                extra["Name"] = out_name
                try:
                    with open(os.path.join(cfg.workdir, out_name),
                              "rb") as f:
                        extra["Crc"] = zlib.crc32(f.read())
                except OSError:
                    extra["Crc"] = 0
                if not report_complete("Coordinator.RecieveReduceComplete",
                                       reply["CReduce"], extra):
                    break
                continue
            with Span("worker.reduce", task=reply["CReduce"]) as sp:
                if task_runner is not None:
                    task_runner.run_reduce(reducef, reply["CReduce"],
                                           reply["NMap"], cfg.workdir)
                else:
                    run_reduce_task(reducef, reply["CReduce"], reply["NMap"],
                                    cfg.workdir)
            note_task(sp.elapsed_s)
            tasks_done += 1
            if not report_complete("Coordinator.RecieveReduceComplete",
                                   reply["CReduce"]):
                break
        else:  # WAITING — sleep instead of the reference's RPC busy-poll
            time.sleep(cfg.wait_sleep_s)
