"""Shard worker: drives cursor-range shards as resumable step objects.

The classic worker (``mr/worker.py``) executes tasks as run-to-completion
function calls; a shard worker drives its assignment as an
:class:`~dsi_tpu.parallel.stepobj.EngineStep` — ``advance_slice`` a few
steps, ``checkpoint()`` on a wall-clock cadence through the engine's own
``ckpt/`` chain, phone a ``ShardProgress`` heartbeat home (which is also
where a speculative loser learns it was cancelled), and finally race
``CommitShard`` under the coordinator's first-commit-wins lock:

* the attempt's output is written durably to a PRIVATE partial file
  (``mr-shard-out-<sid>.a<aid>.part``) before the commit RPC — the
  coordinator renames the winner's partial to the final output and
  journals the commit record, so the data-plane commit and the
  control-plane record can never name different bytes;
* a loser (reply ``Win: false``, or ``Cancel`` on a heartbeat) aborts
  the engine, removes its partial, and reaps its checkpoint-chain
  directory — speculative execution must leave no litter;
* a takeover/backup assignment (``ResumeFrom``) ADOPTS the named
  attempt's chain (``mr/shards.adopt_chain``) and resumes the engine
  from its last checkpoint — a killed worker's shard continues from the
  cursor, not from zero; the restore's ``resume_cursor`` is reported on
  every heartbeat so the harness can assert the resume really happened.

Chaos (``DSI_CHAOS_WORKER_KILL``, ``ckpt/fault.py``) fires at the same
task boundaries as the classic loop; ``DSI_SHARD_SLOW_S`` injects a
per-slice sleep — the scriptable straggler for the backup-dispatch
A/B bench and the CI smoke.
"""

from __future__ import annotations

import os
import sys
import threading
import time
import zlib
from typing import Optional

from dsi_tpu.ckpt.fault import chaos_kill_point
from dsi_tpu.config import JobConfig
from dsi_tpu.mr import rpc
from dsi_tpu.mr import shards as sh
from dsi_tpu.mr.types import TaskStatus
# Leader discovery (dsi_tpu/replica): DSI_MR_SOCKET may be a comma-
# separated replica group; a single address is a plain rpc.call.
from dsi_tpu.replica.client import group_call
from dsi_tpu.utils.atomicio import atomic_write

#: advance() turns between straggler-sleep/checkpoint/heartbeat checks.
ADVANCES_PER_SLICE = 4


def _slow_s() -> float:
    """``DSI_SHARD_SLOW_S``: per-slice sleep, the injected straggler."""
    try:
        return float(os.environ.get("DSI_SHARD_SLOW_S", "0") or 0)
    except ValueError:
        return 0.0


def _build_step(engine: str, files, spec: sh.ShardSpec, ckpt_dir: str,
                resume: bool, knobs: dict, tag=None):
    """Construct the engine step over the shard's block slice.  The
    ``input_range`` identity tag means an adopted chain from any OTHER
    cursor range refuses to restore (range-relative cursors must never
    cross ranges).  ``tag`` overrides the tag WITHOUT changing the read
    range: a re-split's sub 0 reads its sub-range but adopts its parent
    straggler's chain under the parent's tag — sound because the
    parent's confirmed prefix is byte-identical to the sub's stream
    (the sub range IS the parent's prefix up to the cut)."""
    blocks = sh.shard_blocks(files, spec)
    common = dict(checkpoint_dir=ckpt_dir,
                  checkpoint_every=int(knobs.get("ckpt_every", 32) or 32),
                  resume=resume,
                  input_range=(tuple(tag) if tag
                               else (spec.start, spec.end)),
                  chunk_bytes=int(knobs.get("chunk_bytes", 1 << 20)),
                  depth=knobs.get("depth"),
                  device_accumulate=bool(knobs.get("device_accumulate",
                                                   False)))
    if engine == "grep":
        from dsi_tpu.parallel.grepstream import GrepStep

        return GrepStep(blocks, str(knobs.get("pattern", "")), **common)
    if engine != "wordcount":
        raise ValueError(f"unknown shard engine: {engine!r}")
    from dsi_tpu.parallel.streaming import WordcountStep

    return WordcountStep(blocks, n_reduce=int(knobs.get("n_reduce", 10)),
                         **common)


def _marker_tag(src_dir: str, default):
    """The ``input_range`` tag the source chain was built under (its
    attempt marker records it) — a takeover must restore under the SAME
    tag or the engine's identity check refuses the chain."""
    m = sh.read_attempt_marker(src_dir)
    t = m.get("tag") if m else None
    return (int(t[0]), int(t[1])) if t else default


def _reap_attempt(part_path: str, ckpt_dir: str) -> None:
    """Remove a lost/cancelled/failed attempt's partial output and its
    checkpoint-chain directory — best-effort hygiene."""
    for p in (part_path,):
        try:
            os.remove(p)
        except OSError:
            pass
    sh.reap_attempt_dir(ckpt_dir)


def run_shard_attempt(reply: dict, cfg: JobConfig, worker_id: str,
                      sock: str, serve_addr: str | None = None) -> None:
    """Drive ONE shard attempt end to end (module docstring).  Raises
    :class:`rpc.CoordinatorGone` through to the caller's loop exit.
    ``serve_addr`` is this worker's partition-server address (net mode):
    a ``Net`` assignment's commit then registers the partial's location
    instead of relying on a shared-directory rename."""
    sid = int(reply["Shard"])
    aid = int(reply["Attempt"])
    sub = int(reply.get("Sub", -1))
    spec = sh.ShardSpec(sid, int(reply["Start"]), int(reply["End"]))
    files = list(reply["Files"])
    knobs = dict(reply.get("Knobs") or {})
    engine = str(knobs.get("engine", "wordcount"))
    ckpt_root = str(reply["CkptRoot"])
    part_path = str(reply["OutPart"])
    resume_from = reply.get("ResumeFrom")
    # A sub-shard attempt (re-split) lives under the parent shard's
    # checkpoint root in its own sub directory: shard-<sid>/s<k>/a<aid>.
    shard_dir = os.path.join(ckpt_root, f"shard-{sid}")
    if sub >= 0:
        shard_dir = os.path.join(shard_dir, f"s{sub}")
    ckpt_dir = os.path.join(shard_dir, f"a{aid}")
    own_tag = (spec.start, spec.end)
    tag = own_tag
    resume = False
    if resume_from is not None:
        src = os.path.join(shard_dir, f"a{int(resume_from)}")
        resume = sh.adopt_chain(src, ckpt_dir, sid, aid)
        if resume:
            tag = _marker_tag(src, own_tag)
    if not resume and aid > 0:
        # No (usable) hinted chain: scan the sibling attempt dirs — an
        # attempt that checkpointed and died before its next heartbeat
        # left a chain the coordinator never heard about.
        src = sh.find_best_chain(shard_dir, exclude_aid=aid)
        if src is not None:
            resume = sh.adopt_chain(src, ckpt_dir, sid, aid)
            if resume:
                tag = _marker_tag(src, own_tag)
    parent_chain = reply.get("ParentChain")
    if not resume and sub >= 0 and parent_chain is not None:
        # Sub 0 of a re-split: adopt the parent STRAGGLER's chain under
        # the parent's range tag — the parent's confirmed prefix is
        # byte-identical to this sub-range's stream.
        src = os.path.join(ckpt_root, f"shard-{sid}",
                           f"a{int(parent_chain)}")
        resume = sh.adopt_chain(src, ckpt_dir, sid, aid)
        if resume:
            tag = (int(reply["TagStart"]), int(reply["TagEnd"]))
    sh.write_attempt_marker(ckpt_dir, sid, aid, tag=tag)

    def call(method: str, args: dict):
        args = dict(args)
        args.update({"WorkerId": worker_id, "Shard": sid,
                     "Attempt": aid, "Sub": sub})
        return group_call(sock, method, args)

    def report_failed(reason: str) -> None:
        try:
            call("Coordinator.ShardFailed", {"Reason": reason})
        except rpc.CoordinatorGone:
            pass

    slow = _slow_s()
    ckpt_secs = float(knobs.get("ckpt_secs", 1.0) or 1.0)
    # Engine setup (jax init + first compiles) serializes for many
    # seconds when several workers contend for few cores; BOUNDED
    # liveness beats cover exactly that window so the watchdog's setup
    # grace measures real silence, not compile contention — and so the
    # per-worker heartbeat-gap histogram (the percentile that arms the
    # backup/re-split silent trigger) is not polluted by one giant
    # setup gap.  A truly hung setup outlives the cap, goes silent,
    # and is requeued; run-phase liveness stays progress-based.
    setup_done = threading.Event()

    def _setup_beats() -> None:
        cap = time.monotonic() + 4.0 * max(cfg.spec_setup_s, 1.0)
        while not setup_done.wait(max(cfg.shard_progress_s, 0.05)):
            if time.monotonic() > cap:
                return
            try:
                call("Coordinator.ShardProgress",
                     {"Confirmed": 0, "Ckpts": 0, "Cursor": 0,
                      "ResumeCursor": 0})
            except Exception:  # noqa: BLE001 — liveness only
                return

    beater = threading.Thread(target=_setup_beats, daemon=True,
                              name=f"setup-beat-{sid}.a{aid}")
    beater.start()
    try:
        try:
            step = _build_step(engine, files, spec, ckpt_dir, resume,
                               knobs, tag=tag)
        except Exception as e:  # noqa: BLE001 — attempt fails, worker lives
            report_failed(f"setup: {type(e).__name__}: {e}")
            _reap_attempt(part_path, ckpt_dir)
            return
        restore = step.restore()
        resume_cursor = int(restore.get("resume_cursor", 0) or 0)
        if resume and resume_cursor > spec.size:
            # The adopted chain's cursor sits PAST this range's end: the
            # straggler confirmed more bytes after the split was
            # computed, so the restored state covers bytes beyond this
            # sub-range — discard the chain and rebuild fresh
            # (correctness over reuse).
            step.abort()
            sh.reap_attempt_dir(ckpt_dir)
            tag = own_tag
            sh.write_attempt_marker(ckpt_dir, sid, aid, tag=tag)
            try:
                step = _build_step(engine, files, spec, ckpt_dir, False,
                                   knobs, tag=tag)
            except Exception as e:  # noqa: BLE001
                report_failed(f"setup: {type(e).__name__}: {e}")
                _reap_attempt(part_path, ckpt_dir)
                return
            restore = step.restore()
            resume_cursor = 0
    finally:
        setup_done.set()
        beater.join(timeout=2.0)
    ckpts = 0
    cancelled = False
    last_ckpt = time.monotonic()
    try:
        # First heartbeat the moment setup (jax init + compiles)
        # finishes: it ends the coordinator's setup-grace window, so
        # silence from here on means a real stall, not a compile.
        ok, prep = call("Coordinator.ShardProgress",
                        {"Confirmed": 0, "Ckpts": ckpts,
                         "Cursor": step.cursor,
                         "ResumeCursor": resume_cursor})
        if ok and prep and prep.get("Cancel"):
            cancelled = True
        last_prog = time.monotonic()
        while not cancelled and step.phase == "running":
            took = step.advance_slice(ADVANCES_PER_SLICE)
            if slow > 0:
                time.sleep(slow)
            now = time.monotonic()
            if (step.phase == "running" and took
                    and now - last_ckpt >= ckpt_secs):
                if step.checkpoint():
                    ckpts += 1
                last_ckpt = now
            if now - last_prog >= cfg.shard_progress_s:
                last_prog = now
                # The LIVE confirmed-byte cursor rides every heartbeat
                # (from the first retired step, not only after a
                # checkpoint) — the re-split trigger cuts from here.
                ok, prep = call("Coordinator.ShardProgress",
                                {"Confirmed": step.confirmed,
                                 "Ckpts": ckpts,
                                 "Cursor": step.cursor,
                                 "ResumeCursor": resume_cursor})
                if ok and prep and prep.get("Cancel"):
                    cancelled = True
                    break
            if not took:
                break
    except rpc.CoordinatorGone:
        step.abort()
        raise
    except Exception as e:  # noqa: BLE001 — engine died: fail the attempt
        report_failed(f"engine: {type(e).__name__}: {e}")
        _reap_attempt(part_path, ckpt_dir)
        return
    if cancelled:
        # First-commit-wins loser: stop mid-flight, leave nothing.
        step.abort()
        _reap_attempt(part_path, ckpt_dir)
        return
    # Terminal either way now — close() releases the engine's resources
    # (checkpoint-writer thread, stats copy-out); skipping it leaked one
    # CommitWorker thread per completed attempt in a long-lived worker.
    result = step.close()
    if step.phase != "done" or result is None:
        report_failed(step.phase)
        _reap_attempt(part_path, ckpt_dir)
        return
    payload = (sh.format_grep(result) if engine == "grep"
               else sh.format_wordcount(result))
    with atomic_write(part_path, mode="wb") as f:
        f.write(payload)
    crc = zlib.crc32(payload)
    chaos_kill_point("pre-commit")
    commit_args = {"Crc": crc, "Confirmed": step.confirmed,
                   "ResumeCursor": resume_cursor}
    if reply.get("Net") and serve_addr:
        # NET data plane (ISSUE 17): the partial stays in THIS worker's
        # private spool; the commit registers its location (the driver
        # fetches the bytes over the stream transport), so a winner's
        # part file must outlive the attempt — only losers reap.
        commit_args["Addr"] = serve_addr
        commit_args["Name"] = os.path.basename(part_path)
    try:
        ok, rep = call("Coordinator.CommitShard", commit_args)
    except rpc.CoordinatorGone:
        raise
    if not ok or rep is None or not rep.get("Win"):
        _reap_attempt(part_path, ckpt_dir)
    else:
        # Winner: the committed output carries everything the chain
        # held — the chain is dead weight on the shared fs now.
        sh.reap_attempt_dir(ckpt_dir)


def _warm_engine() -> None:
    """Pay the jax platform init and a first tiny compile BEFORE the
    first ``RequestShard``: when N cold workers serialize their inits
    on few cores, a cold start paid INSIDE the assignment window reads
    as ``shard_timeout_s`` of silence and the watchdog requeues a
    perfectly healthy attempt (observed: three 1-core workers each
    taking 7-9s to first heartbeat).  Warming outside the window keeps
    the watchdog measuring the work, not the toolchain."""
    try:
        import jax
        import jax.numpy as jnp

        jax.jit(lambda x: (x * x).sum())(
            jnp.ones((8,), jnp.float32)).block_until_ready()
    except Exception:  # noqa: BLE001 — warmup is best-effort
        pass


def shard_worker_loop(config: Optional[JobConfig] = None,
                      partsrv=None) -> None:
    """The shard worker's pull loop — the ``worker_loop`` shape over
    ``RequestShard``: chaos boundary, request, drive, repeat; exits on
    DONE or a dead coordinator.  ``partsrv`` (a started
    :class:`dsi_tpu.net.PartitionServer`) switches to the NET data
    plane: every RPC advertises the server's address and commits
    register partial locations instead of shared-directory renames."""
    cfg = config or JobConfig()
    sock = cfg.sock()
    worker_id = f"w{os.getpid()}"
    serve_addr = partsrv.address if partsrv is not None else None
    shards_done = 0
    _warm_engine()
    while True:
        chaos_kill_point("shard")
        req = {"WorkerId": worker_id}
        if serve_addr:
            req["Addr"] = serve_addr
        try:
            ok, reply = group_call(sock, "Coordinator.RequestShard", req)
        except rpc.CoordinatorGone as e:
            if shards_done == 0 or isinstance(e, rpc.AuthError):
                print(f"shardworker: coordinator unreachable: {e}",
                      file=sys.stderr)
            break
        if not ok or reply is None \
                or reply.get("TaskStatus") == int(TaskStatus.DONE):
            break
        if reply.get("TaskStatus") != int(TaskStatus.SHARD):
            time.sleep(cfg.wait_sleep_s)
            continue
        try:
            run_shard_attempt(reply, cfg, worker_id, sock,
                              serve_addr=serve_addr)
        except rpc.CoordinatorGone:
            break
        shards_done += 1
