"""Shard worker: drives cursor-range shards as resumable step objects.

The classic worker (``mr/worker.py``) executes tasks as run-to-completion
function calls; a shard worker drives its assignment as an
:class:`~dsi_tpu.parallel.stepobj.EngineStep` — ``advance_slice`` a few
steps, ``checkpoint()`` on a wall-clock cadence through the engine's own
``ckpt/`` chain, phone a ``ShardProgress`` heartbeat home (which is also
where a speculative loser learns it was cancelled), and finally race
``CommitShard`` under the coordinator's first-commit-wins lock:

* the attempt's output is written durably to a PRIVATE partial file
  (``mr-shard-out-<sid>.a<aid>.part``) before the commit RPC — the
  coordinator renames the winner's partial to the final output and
  journals the commit record, so the data-plane commit and the
  control-plane record can never name different bytes;
* a loser (reply ``Win: false``, or ``Cancel`` on a heartbeat) aborts
  the engine, removes its partial, and reaps its checkpoint-chain
  directory — speculative execution must leave no litter;
* a takeover/backup assignment (``ResumeFrom``) ADOPTS the named
  attempt's chain (``mr/shards.adopt_chain``) and resumes the engine
  from its last checkpoint — a killed worker's shard continues from the
  cursor, not from zero; the restore's ``resume_cursor`` is reported on
  every heartbeat so the harness can assert the resume really happened.

Chaos (``DSI_CHAOS_WORKER_KILL``, ``ckpt/fault.py``) fires at the same
task boundaries as the classic loop; ``DSI_SHARD_SLOW_S`` injects a
per-slice sleep — the scriptable straggler for the backup-dispatch
A/B bench and the CI smoke.
"""

from __future__ import annotations

import os
import sys
import time
import zlib
from typing import Optional

from dsi_tpu.ckpt.fault import chaos_kill_point
from dsi_tpu.config import JobConfig
from dsi_tpu.mr import rpc
from dsi_tpu.mr import shards as sh
from dsi_tpu.mr.types import TaskStatus
from dsi_tpu.utils.atomicio import atomic_write

#: advance() turns between straggler-sleep/checkpoint/heartbeat checks.
ADVANCES_PER_SLICE = 4


def _slow_s() -> float:
    """``DSI_SHARD_SLOW_S``: per-slice sleep, the injected straggler."""
    try:
        return float(os.environ.get("DSI_SHARD_SLOW_S", "0") or 0)
    except ValueError:
        return 0.0


def _build_step(engine: str, files, spec: sh.ShardSpec, ckpt_dir: str,
                resume: bool, knobs: dict):
    """Construct the engine step over the shard's block slice.  The
    ``input_range`` identity tag means an adopted chain from any OTHER
    cursor range refuses to restore (range-relative cursors must never
    cross ranges)."""
    blocks = sh.shard_blocks(files, spec)
    common = dict(checkpoint_dir=ckpt_dir,
                  checkpoint_every=int(knobs.get("ckpt_every", 32) or 32),
                  resume=resume,
                  input_range=(spec.start, spec.end),
                  chunk_bytes=int(knobs.get("chunk_bytes", 1 << 20)),
                  depth=knobs.get("depth"),
                  device_accumulate=bool(knobs.get("device_accumulate",
                                                   False)))
    if engine == "grep":
        from dsi_tpu.parallel.grepstream import GrepStep

        return GrepStep(blocks, str(knobs.get("pattern", "")), **common)
    if engine != "wordcount":
        raise ValueError(f"unknown shard engine: {engine!r}")
    from dsi_tpu.parallel.streaming import WordcountStep

    return WordcountStep(blocks, n_reduce=int(knobs.get("n_reduce", 10)),
                         **common)


def _reap_attempt(part_path: str, ckpt_dir: str) -> None:
    """Remove a lost/cancelled/failed attempt's partial output and its
    checkpoint-chain directory — best-effort hygiene."""
    for p in (part_path,):
        try:
            os.remove(p)
        except OSError:
            pass
    sh.reap_attempt_dir(ckpt_dir)


def run_shard_attempt(reply: dict, cfg: JobConfig, worker_id: str,
                      sock: str) -> None:
    """Drive ONE shard attempt end to end (module docstring).  Raises
    :class:`rpc.CoordinatorGone` through to the caller's loop exit."""
    sid = int(reply["Shard"])
    aid = int(reply["Attempt"])
    spec = sh.ShardSpec(sid, int(reply["Start"]), int(reply["End"]))
    files = list(reply["Files"])
    knobs = dict(reply.get("Knobs") or {})
    engine = str(knobs.get("engine", "wordcount"))
    ckpt_root = str(reply["CkptRoot"])
    part_path = str(reply["OutPart"])
    ckpt_dir = os.path.join(ckpt_root, f"shard-{sid}", f"a{aid}")
    resume_from = reply.get("ResumeFrom")
    shard_dir = os.path.join(ckpt_root, f"shard-{sid}")
    resume = False
    if resume_from is not None:
        src = os.path.join(shard_dir, f"a{int(resume_from)}")
        resume = sh.adopt_chain(src, ckpt_dir, sid, aid)
    if not resume and aid > 0:
        # No (usable) hinted chain: scan the sibling attempt dirs — an
        # attempt that checkpointed and died before its next heartbeat
        # left a chain the coordinator never heard about.
        src = sh.find_best_chain(shard_dir, exclude_aid=aid)
        if src is not None:
            resume = sh.adopt_chain(src, ckpt_dir, sid, aid)
    sh.write_attempt_marker(ckpt_dir, sid, aid)

    def call(method: str, args: dict):
        args = dict(args)
        args.update({"WorkerId": worker_id, "Shard": sid, "Attempt": aid})
        return rpc.call(sock, method, args)

    def report_failed(reason: str) -> None:
        try:
            call("Coordinator.ShardFailed", {"Reason": reason})
        except rpc.CoordinatorGone:
            pass

    slow = _slow_s()
    ckpt_secs = float(knobs.get("ckpt_secs", 1.0) or 1.0)
    try:
        step = _build_step(engine, files, spec, ckpt_dir, resume, knobs)
    except Exception as e:  # noqa: BLE001 — attempt fails, worker lives
        report_failed(f"setup: {type(e).__name__}: {e}")
        _reap_attempt(part_path, ckpt_dir)
        return
    restore = step.restore()
    resume_cursor = int(restore.get("resume_cursor", 0) or 0)
    ckpts = 0
    cancelled = False
    last_ckpt = time.monotonic()
    try:
        # First heartbeat the moment setup (jax init + compiles)
        # finishes: it ends the coordinator's setup-grace window, so
        # silence from here on means a real stall, not a compile.
        ok, prep = call("Coordinator.ShardProgress",
                        {"Confirmed": 0, "Ckpts": ckpts,
                         "ResumeCursor": resume_cursor})
        if ok and prep and prep.get("Cancel"):
            cancelled = True
        last_prog = time.monotonic()
        while not cancelled and step.phase == "running":
            took = step.advance_slice(ADVANCES_PER_SLICE)
            if slow > 0:
                time.sleep(slow)
            now = time.monotonic()
            if (step.phase == "running" and took
                    and now - last_ckpt >= ckpt_secs):
                if step.checkpoint():
                    ckpts += 1
                last_ckpt = now
            if now - last_prog >= cfg.shard_progress_s:
                last_prog = now
                ok, prep = call("Coordinator.ShardProgress",
                                {"Confirmed": step.confirmed,
                                 "Ckpts": ckpts,
                                 "ResumeCursor": resume_cursor})
                if ok and prep and prep.get("Cancel"):
                    cancelled = True
                    break
            if not took:
                break
    except rpc.CoordinatorGone:
        step.abort()
        raise
    except Exception as e:  # noqa: BLE001 — engine died: fail the attempt
        report_failed(f"engine: {type(e).__name__}: {e}")
        _reap_attempt(part_path, ckpt_dir)
        return
    if cancelled:
        # First-commit-wins loser: stop mid-flight, leave nothing.
        step.abort()
        _reap_attempt(part_path, ckpt_dir)
        return
    # Terminal either way now — close() releases the engine's resources
    # (checkpoint-writer thread, stats copy-out); skipping it leaked one
    # CommitWorker thread per completed attempt in a long-lived worker.
    result = step.close()
    if step.phase != "done" or result is None:
        report_failed(step.phase)
        _reap_attempt(part_path, ckpt_dir)
        return
    payload = (sh.format_grep(result) if engine == "grep"
               else sh.format_wordcount(result))
    with atomic_write(part_path, mode="wb") as f:
        f.write(payload)
    crc = zlib.crc32(payload)
    chaos_kill_point("pre-commit")
    try:
        ok, rep = call("Coordinator.CommitShard",
                       {"Crc": crc, "Confirmed": step.confirmed,
                        "ResumeCursor": resume_cursor})
    except rpc.CoordinatorGone:
        raise
    if not ok or rep is None or not rep.get("Win"):
        _reap_attempt(part_path, ckpt_dir)
    else:
        # Winner: the committed output carries everything the chain
        # held — the chain is dead weight on the shared fs now.
        sh.reap_attempt_dir(ckpt_dir)


def shard_worker_loop(config: Optional[JobConfig] = None) -> None:
    """The shard worker's pull loop — the ``worker_loop`` shape over
    ``RequestShard``: chaos boundary, request, drive, repeat; exits on
    DONE or a dead coordinator."""
    cfg = config or JobConfig()
    sock = cfg.sock()
    worker_id = f"w{os.getpid()}"
    shards_done = 0
    while True:
        chaos_kill_point("shard")
        try:
            ok, reply = rpc.call(sock, "Coordinator.RequestShard",
                                 {"WorkerId": worker_id})
        except rpc.CoordinatorGone as e:
            if shards_done == 0 or isinstance(e, rpc.AuthError):
                print(f"shardworker: coordinator unreachable: {e}",
                      file=sys.stderr)
            break
        if not ok or reply is None \
                or reply.get("TaskStatus") == int(TaskStatus.DONE):
            break
        if reply.get("TaskStatus") != int(TaskStatus.SHARD):
            time.sleep(cfg.wait_sleep_s)
            continue
        try:
            run_shard_attempt(reply, cfg, worker_id, sock)
        except rpc.CoordinatorGone:
            break
        shards_done += 1
