"""Streaming-shard plans: cursor-range tasks over the engines' byte stream.

The control plane's task unit has always been "one input file" (the
reference's nMap, ``mr/coordinator.go:152``); the streaming engines'
unit is "the whole stream".  Speculative execution (Dean & Ghemawat
§3.6) needs something in between: a **shard** — one cursor range
``[start, end)`` of the concatenated input stream — small enough to
re-run or back up, large enough to amortize engine setup.  This module
owns the shard geometry and the pieces of the protocol that are pure
functions of the filesystem (no jax anywhere: the coordinator imports
it):

* :func:`plan_shards` — split ``stream_files(files)``' byte stream into
  ``n`` newline-aligned ranges.  Alignment matters twice over: the
  wordcount cutter never splits a token across a non-letter boundary,
  and the grep engine's ``batch_lines`` counts per *line* — a shard
  edge inside a line would double- or zero-count it.  A ``\\n`` edge is
  safe for every engine (files are already joined by ``\\n`` in
  ``stream_files``, so file boundaries are natural cuts).
* :func:`shard_blocks` — the byte-exact slice ``[start, end)`` of that
  stream as a block iterator, seeking instead of reading the prefix.
  Feeding it to an engine makes every engine cursor (checkpoint
  offsets, ``skip_stream`` resumes) shard-relative — the existing
  crash-resume machinery works unchanged inside a shard.
* :func:`adopt_chain` — the cross-attempt checkpoint handoff: copy the
  newest complete chain of a dead/straggling attempt's store into a NEW
  attempt's (empty) store directory.  Attempts deliberately never share
  a live checkpoint directory — each writes under its own
  ``a<attempt>`` dir with an ``ATTEMPT`` marker, so two concurrent
  attempts of one shard can never cross-restore; adoption is the one
  sanctioned flow, and it validates the marker + the engine-side
  ``input_range`` identity before any byte is trusted.
* :func:`wordcount_host_oracle` / the ``merge_*``/``format_*`` helpers
  — the deterministic shard-output codecs and the sequential ground
  truth the differential harness byte-compares against.
"""

from __future__ import annotations

import json
import os
import re
import shutil
from dataclasses import dataclass
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

#: Marker file naming the attempt a shard-checkpoint directory belongs
#: to; ``adopt_chain`` refuses to copy into a directory already owned by
#: a different live attempt.
ATTEMPT_MARKER = "ATTEMPT"

_CHAIN_FILE_RE = re.compile(
    r"^(manifest|state|delta)-\d{6}\.(json|npz)(\.crc32)?$")


@dataclass(frozen=True)
class ShardSpec:
    """One cursor-range task: ``[start, end)`` over the concatenated
    ``stream_files(files)`` byte stream (files joined by single ``\\n``
    separators)."""

    sid: int
    start: int
    end: int

    @property
    def size(self) -> int:
        return self.end - self.start


def stream_total_bytes(files: Sequence[str]) -> int:
    """Length of ``stream_files(files)``' byte stream: file bytes plus
    one ``\\n`` separator between adjacent files."""
    if not files:
        return 0
    return sum(os.path.getsize(f) for f in files) + (len(files) - 1)


def _file_segments(files: Sequence[str]) -> List[Tuple[int, int, str]]:
    """``(global_start, global_end, path)`` per file — separators live
    in the 1-byte gaps between consecutive segments."""
    segs = []
    pos = 0
    for i, p in enumerate(files):
        if i:
            pos += 1  # the separator byte
        size = os.path.getsize(p)
        segs.append((pos, pos + size, p))
        pos += size
    return segs


def read_stream_range(files: Sequence[str], start: int, end: int,
                      block_bytes: int = 4 << 20) -> Iterator[bytes]:
    """The byte-exact slice ``[start, end)`` of ``stream_files(files)``'
    stream, seeking to ``start`` instead of reading the prefix."""
    if end <= start:
        return
    for seg_start, seg_end, path in _file_segments(files):
        # Separator byte immediately before this file, if in range —
        # checked BEFORE the end-of-range break: a range ending exactly
        # at a file boundary still owns the separator at seg_start - 1
        # (the guard is false for fully-before-start segments).
        if seg_start > 0 and start <= seg_start - 1 < end:
            yield b"\n"
        if seg_start >= end:
            break
        if seg_end <= start:
            continue
        lo = max(start, seg_start) - seg_start
        hi = min(end, seg_end) - seg_start
        if hi <= lo:
            continue
        with open(path, "rb") as f:
            f.seek(lo)
            remaining = hi - lo
            while remaining:
                b = f.read(min(block_bytes, remaining))
                if not b:
                    break
                remaining -= len(b)
                yield b


def shard_blocks(files: Sequence[str], spec: ShardSpec,
                 block_bytes: int = 4 << 20) -> Iterator[bytes]:
    """Block iterator for one shard — :func:`read_stream_range` over the
    spec's cursor range."""
    return read_stream_range(files, spec.start, spec.end, block_bytes)


def _align_to_newline(files: Sequence[str], pos: int, total: int,
                      window: int = 1 << 16) -> int:
    """Smallest cut ``c >= pos`` with ``stream[c-1] == \\n`` (or
    ``total`` when no newline follows).  A cut right after a newline is
    safe for every engine: no token and no line straddles it."""
    if pos <= 0:
        return 0
    if pos >= total:
        return total
    scan = pos - 1
    while scan < total:
        chunk = b"".join(read_stream_range(files, scan,
                                           min(scan + window, total)))
        nl = chunk.find(b"\n")
        if nl >= 0:
            return scan + nl + 1
        scan += len(chunk)
        if not chunk:
            break
    return total


def plan_shards(files: Sequence[str], n_shards: int) -> List[ShardSpec]:
    """Split the stream into up to ``n_shards`` newline-aligned cursor
    ranges covering ``[0, total)`` exactly.  Nominal equal-size
    boundaries are pushed forward to the next newline; boundaries that
    collapse together (a huge single line) merge their shards — the
    plan never returns an empty shard."""
    total = stream_total_bytes(files)
    if total <= 0 or n_shards <= 0:
        return []
    cuts = [0]
    for i in range(1, n_shards):
        c = _align_to_newline(files, i * total // n_shards, total)
        if c > cuts[-1] and c < total:
            cuts.append(c)
    cuts.append(total)
    return [ShardSpec(sid, s, e)
            for sid, (s, e) in enumerate(zip(cuts, cuts[1:]))]


def split_remaining(files: Sequence[str], spec: ShardSpec, cursor: int,
                    ways: int, min_bytes: int = 1 << 16
                    ) -> Optional[List[Tuple[int, int]]]:
    """Sub-shard geometry for a dynamic re-split: partition
    ``[spec.start, spec.end)`` into a PREFIX ``[start, b0)`` covering
    the straggler's confirmed progress (``cursor`` is shard-relative
    bytes; ``b0`` is the next newline-aligned cut at or past it) plus
    up to ``ways`` newline-aligned splits of the remainder.  Returns
    None when the remainder is smaller than ``min_bytes`` (a sub-shard
    must amortize one engine setup — the caller falls back to a plain
    backup) or when alignment collapses everything into one range (a
    giant line: nothing to redistribute).

    The ranges partition the shard exactly: every byte of
    ``[start, end)`` lands in exactly one sub-range, and every cut sits
    just after a ``\\n`` of the concatenated stream — the same
    token/line safety argument as :func:`plan_shards`, so per-sub-range
    results merge to the whole-shard result."""
    total = stream_total_bytes(files)
    base = spec.start + max(0, int(cursor))
    if base >= spec.end:
        return None
    b0 = _align_to_newline(files, base, total) if cursor > 0 \
        else spec.start
    if b0 >= spec.end or spec.end - b0 < max(int(min_bytes), 2):
        return None
    ways = max(2, int(ways))
    cuts = [b0]
    for j in range(1, ways):
        c = _align_to_newline(files, b0 + j * (spec.end - b0) // ways,
                              total)
        if cuts[-1] < c < spec.end:
            cuts.append(c)
    cuts.append(spec.end)
    ranges: List[Tuple[int, int]] = []
    if b0 > spec.start:
        ranges.append((spec.start, b0))
    ranges.extend(zip(cuts, cuts[1:]))
    return ranges if len(ranges) >= 2 else None


# ── cross-attempt checkpoint adoption ──────────────────────────────────


def write_attempt_marker(ckpt_dir: str, sid: int, attempt: int,
                         tag: Optional[Tuple[int, int]] = None) -> None:
    """Stamp ``ckpt_dir`` as owned by (shard, attempt).  Written through
    the durable path BEFORE the engine's first save, so ownership is
    never in doubt for a later adoption.  ``tag`` records the
    ``input_range`` identity the chain was built under — a sub-shard
    attempt that adopted its parent straggler's chain carries the
    PARENT's range tag, and a later takeover must reuse that tag or the
    engine's identity check would refuse the chain."""
    from dsi_tpu.utils.atomicio import write_bytes_durable

    os.makedirs(ckpt_dir, exist_ok=True)
    body = {"shard": sid, "attempt": attempt}
    if tag is not None:
        body["tag"] = [int(tag[0]), int(tag[1])]
    write_bytes_durable(
        os.path.join(ckpt_dir, ATTEMPT_MARKER),
        json.dumps(body, sort_keys=True).encode("utf-8"))


def read_attempt_marker(ckpt_dir: str) -> Optional[Dict]:
    try:
        with open(os.path.join(ckpt_dir, ATTEMPT_MARKER),
                  encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def adopt_chain(src_dir: str, dst_dir: str, sid: int,
                attempt: int) -> bool:
    """Copy a dead/straggling attempt's checkpoint chain files into a
    fresh attempt's directory so the new attempt resumes from the old
    one's last checkpoint instead of replaying the shard from zero.

    Refusals (False, nothing copied): no source chain; source marker
    for a DIFFERENT shard (attempt dirs are per-shard — cross-shard
    adoption would be caught again by the engine's ``input_range``
    identity, but refusing here is cheaper and louder); destination
    already owned by another attempt with chain files present.  The
    copy lands before the destination marker, so a crash mid-adopt
    leaves a directory the next adoption can overwrite."""
    src_marker = read_attempt_marker(src_dir)
    if src_marker is not None and int(src_marker.get("shard", -1)) != sid:
        return False
    try:
        names = [n for n in os.listdir(src_dir) if _CHAIN_FILE_RE.match(n)]
    except OSError:
        return False
    if not names:
        return False
    dst_marker = read_attempt_marker(dst_dir)
    if dst_marker is not None and int(dst_marker.get("attempt", -1)) != attempt:
        return False
    os.makedirs(dst_dir, exist_ok=True)
    for n in os.listdir(dst_dir):  # a half-adopted previous try
        if _CHAIN_FILE_RE.match(n):
            try:
                os.remove(os.path.join(dst_dir, n))
            except OSError:
                pass
    for n in names:
        try:
            shutil.copy2(os.path.join(src_dir, n), os.path.join(dst_dir, n))
        except OSError:
            return False  # torn source (GC race): caller starts fresh
    write_attempt_marker(dst_dir, sid, attempt)
    return True


def find_best_chain(shard_dir: str,
                    exclude_aid: Optional[int] = None) -> Optional[str]:
    """The sibling attempt directory (``a<id>`` under one shard's
    checkpoint root) holding the longest chain — highest manifest seq
    wins (= most saves; content is verified later by the engine's CRC'd
    load, this scan only picks a candidate).  The coordinator's resume
    hint covers checkpoints it was TOLD about; this covers the window
    where an attempt checkpointed and died before its next heartbeat."""
    manifest_re = re.compile(r"^manifest-(\d{6})\.json$")
    best = None
    try:
        names = os.listdir(shard_dir)
    except OSError:
        return None
    for name in names:
        if not name.startswith("a"):
            continue
        try:
            aid = int(name[1:])
        except ValueError:
            continue
        if exclude_aid is not None and aid == exclude_aid:
            continue
        adir = os.path.join(shard_dir, name)
        try:
            seqs = [int(m.group(1)) for n in os.listdir(adir)
                    if (m := manifest_re.match(n))]
        except OSError:
            continue
        if not seqs:
            continue
        key = (max(seqs), aid)
        if best is None or key > best[1]:
            best = (adir, key)
    return best[0] if best is not None else None


def reap_attempt_dir(ckpt_dir: str) -> None:
    """Remove a cancelled/lost attempt's checkpoint directory — the
    loser's partial state must not survive to confuse a later adoption
    scan.  Never raises (reaping is best-effort hygiene)."""
    shutil.rmtree(ckpt_dir, ignore_errors=True)


# ── shard output codecs + the sequential oracle ────────────────────────


def format_wordcount(result: Dict[str, tuple]) -> bytes:
    """Deterministic bytes for a wordcount result ``{word: (count,
    part)}`` — sorted ``"word count\\n"`` lines, the app output shape."""
    return "".join(f"{w} {c}\n" for w, (c, _p) in
                   sorted(result.items())).encode("utf-8")


def parse_wordcount(payload: bytes) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for line in payload.decode("utf-8").splitlines():
        if not line:
            continue
        w, _, c = line.rpartition(" ")
        out[w] = int(c)
    return out


def merge_wordcount(payloads: Iterable[bytes]) -> bytes:
    """Merge per-shard wordcount outputs by summing counts — shards
    partition the stream at token-safe cuts, so the sum IS the
    whole-stream count and the merged bytes match the oracle's."""
    total: Dict[str, int] = {}
    for payload in payloads:
        for w, c in parse_wordcount(payload).items():
            total[w] = total.get(w, 0) + c
    return "".join(f"{w} {c}\n"
                   for w, c in sorted(total.items())).encode("utf-8")


def format_grep(result) -> bytes:
    """Deterministic bytes for a grep shard: the sum-mergeable fields of
    ``GrepStreamResult`` (per-shard top-k is exact per shard but not
    globally mergeable, so the merged artifact omits it)."""
    return json.dumps({"lines": result.lines, "matched": result.matched,
                       "occurrences": result.occurrences,
                       "hist": list(result.hist)},
                      sort_keys=True).encode("utf-8")


def merge_grep(payloads: Iterable[bytes]) -> bytes:
    tot = {"lines": 0, "matched": 0, "occurrences": 0, "hist": None}
    for payload in payloads:
        d = json.loads(payload)
        for k in ("lines", "matched", "occurrences"):
            tot[k] += int(d[k])
        h = [int(x) for x in d["hist"]]
        tot["hist"] = (h if tot["hist"] is None
                       else [a + b for a, b in zip(tot["hist"], h)])
    tot["hist"] = tot["hist"] or []
    return json.dumps(tot, sort_keys=True).encode("utf-8")


def wordcount_host_oracle(blocks: Iterable[bytes]) -> Dict[str, int]:
    """Sequential ground truth with the engine's exact tokenization
    (ASCII letter runs) — the differential harness's byte-compare
    oracle, shard-free by construction."""
    counts: Dict[str, int] = {}
    carry = b""
    letters = re.compile(rb"[A-Za-z]+")

    def eat(buf: bytes, final: bool) -> bytes:
        tail = b""
        if not final:
            m = re.search(rb"[A-Za-z]*\Z", buf)
            tail = m.group(0) if m else b""
            buf = buf[:len(buf) - len(tail)]
        for w in letters.findall(buf):
            key = w.decode("ascii")
            counts[key] = counts.get(key, 0) + 1
        return tail

    for b in blocks:
        carry = eat(carry + b, final=False)
    eat(carry, final=True)
    return counts


def format_wordcount_counts(counts: Dict[str, int]) -> bytes:
    return "".join(f"{w} {c}\n"
                   for w, c in sorted(counts.items())).encode("utf-8")
