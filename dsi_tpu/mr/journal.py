"""Coordinator checkpoint/resume journal.

The reference has task-level checkpointing only: a task's committed output
file is its checkpoint, but coordinator state is purely in-memory
(``mr/coordinator.go:17,21``), so coordinator death kills the job —
SURVEY.md §5 documents this as the gap to close.  This journal closes it:

* every *unique* task completion is appended as one JSON line (the same
  transitions the counters count, coordinator.py),
* on startup with an existing journal for the same job, completed tasks are
  replayed as COMPLETED — sound because a journaled completion implies the
  task's output file was already atomically committed to the shared
  filesystem (``mr/worker.go:91,148`` semantics), so the restarted job
  simply never re-runs it,
* tasks in-progress at the crash were never journaled and are handed out
  afresh, which is exactly the presumed-dead-by-timeout path's semantics.

A header line pins the job identity (input files + n_reduce); resuming with
a different job is refused rather than silently corrupting state.
"""

from __future__ import annotations

import json
import os
import zlib
from typing import List, Optional, TextIO

from dsi_tpu.utils.atomicio import fsync_dir

# ---- replicated-record framing (ISSUE 20) ----
#
# Every record now carries a record-level CRC32 under the ``rcrc`` key,
# computed over the record's CANONICAL serialization (sorted keys,
# compact separators) without ``rcrc`` itself.  Torn tails were always
# caught by the newline discipline; the frame additionally catches
# in-place corruption of a MIDDLE record — which matters once the same
# lines are replicated verbatim into follower journals (replica/), where
# a silently divergent record would mean two coordinators replaying to
# DIFFERENT task tables.  Records without ``rcrc`` (journals written
# before this framing) still replay: the CRC is only checked when
# present, so old spools resume unchanged.

RECORD_CRC_KEY = "rcrc"


def frame_record(rec: dict) -> str:
    """Serialize one record with its framing CRC appended (no newline)."""
    body = json.dumps(rec, sort_keys=True, separators=(",", ":"))
    out = dict(rec)
    out[RECORD_CRC_KEY] = zlib.crc32(body.encode("utf-8"))
    return json.dumps(out, sort_keys=True, separators=(",", ":"))


def unframe_record(rec: dict) -> Optional[dict]:
    """Validate and strip a parsed record's framing CRC.

    Returns the record without ``rcrc`` (legacy records pass through
    unchanged), or ``None`` when the CRC does not match — the caller
    treats that exactly like unparseable JSON (truncate-and-refuse, not
    best-effort repair)."""
    if RECORD_CRC_KEY not in rec:
        return rec
    body = {k: v for k, v in rec.items() if k != RECORD_CRC_KEY}
    want = rec[RECORD_CRC_KEY]
    canon = json.dumps(body, sort_keys=True, separators=(",", ":"))
    if (not isinstance(want, int) or isinstance(want, bool)
            or zlib.crc32(canon.encode("utf-8")) != want):
        return None
    return body


class Journal:
    """Append-only completion log with atomic-enough line writes.

    Shard jobs (``mr/shards.py``) ride the same log: a ``shard`` record
    is the exactly-once COMMIT of one shard's output — it carries the
    winning attempt id and the committed payload's CRC32, and replay
    surfaces them via :attr:`shard_commits` so a restarted coordinator
    never hands the shard out again (its output file was durably
    renamed before the record was written, the same
    commit-before-journal order the map/reduce records rely on)."""

    def __init__(self, path: str, files: List[str], n_reduce: int,
                 n_shards: int = 0):
        self.path = path
        self.files = list(files)
        self.n_reduce = n_reduce
        self.n_shards = n_shards
        #: ``{sid: (attempt, crc32)}`` from replay — exactly one entry
        #: per committed shard (duplicate records would mean the
        #: first-commit-wins lock failed; replay keeps the FIRST).
        self.shard_commits: dict = {}
        #: ``{sid: [(start, end), ...]}`` from replay — the sub-range
        #: geometry of every journaled re-split (written BEFORE the
        #: sub-shards dispatch, so a restart reconstructs the same
        #: partition the commits below refer to).
        self.resplits: dict = {}
        #: ``{(sid, sub): (attempt, crc32)}`` from replay — exactly one
        #: entry per committed sub-range (first record wins, same rule
        #: as :attr:`shard_commits`).
        self.subshard_commits: dict = {}
        #: Net-mode location registry from replay (ISSUE 18): ``map``
        #: records may carry the producer's partition-server address and
        #: per-reduce partition sizes; ``reduce`` records the committed
        #: output's ``(addr, name, crc)``.  LAST record wins — a
        #: re-executed producer journals a fresh completion with its
        #: replacement's address.  Advisory: a replayed address pointing
        #: at a dead server converges through the normal FetchFailure →
        #: producer re-execution path, so malformed extras are IGNORED
        #: rather than treated as corruption.
        self.map_locations: dict = {}
        self.map_sizes: dict = {}
        self.out_locations: dict = {}
        self._fh: Optional[TextIO] = None
        self._trunc_at: Optional[int] = None  # set by replay()

    # ---- replay ----

    def replay(self) -> tuple[List[int], List[int]]:
        """Return (completed map task ids, completed reduce task ids) from an
        existing journal, after validating the job header.  Empty lists when
        no journal exists yet.

        Replay stops at the FIRST corrupt record (torn write, bad JSON, or an
        out-of-range/non-int task id) and remembers its byte offset so
        :meth:`open` can truncate the file there.  Without the truncation, a
        single corrupt mid-file record would poison the journal forever: new
        completions appended after it could never be replayed, and every
        restart would re-run them (re-execution is idempotent, so stopping
        early is always SAFE — truncating just stops it being wasteful)."""
        maps: List[int] = []
        reduces: List[int] = []
        self.shard_commits = {}
        self.resplits = {}
        self.subshard_commits = {}
        self.map_locations = {}
        self.map_sizes = {}
        self.out_locations = {}
        self._trunc_at: Optional[int] = None
        if not os.path.exists(self.path):
            return maps, reduces
        with open(self.path, "rb") as f:
            data = f.read()
        saw_header = False
        pos = 0
        while pos < len(data):
            nl = data.find(b"\n", pos)
            rec_start = pos
            if nl == -1:  # torn tail: no terminating newline
                self._trunc_at = rec_start
                break
            line = data[rec_start:nl].strip()
            pos = nl + 1
            if not line:
                continue
            try:
                rec = json.loads(line)
            except (json.JSONDecodeError, UnicodeDecodeError):
                self._trunc_at = rec_start
                break
            if not isinstance(rec, dict):  # valid JSON but not an object
                self._trunc_at = rec_start
                break
            rec = unframe_record(rec)
            if rec is None:  # framed record whose CRC does not match
                self._trunc_at = rec_start
                break
            if not saw_header:  # first non-blank record must be a header
                if (rec.get("kind") != "header"
                        or rec.get("files") != self.files
                        or rec.get("n_reduce") != self.n_reduce
                        or int(rec.get("n_shards", 0) or 0) != self.n_shards):
                    raise SystemExit(
                        f"journal {self.path} belongs to a different job "
                        f"(files/n_reduce/n_shards mismatch); refusing to "
                        f"resume")
                saw_header = True
                continue
            kind = rec.get("kind")
            if kind not in ("map", "reduce", "shard", "resplit",
                            "subshard"):
                self._trunc_at = rec_start
                break
            task = rec.get("task")
            # Require an actual int (bool is an int subclass; floats would
            # silently truncate to a DIFFERENT task id) and range-check
            # before use: a corrupted-but-parseable id would otherwise crash
            # __init__ (IndexError) or, if negative, silently mark the WRONG
            # task completed via Python negative indexing into map_log/
            # reduce_log.
            bound = (len(self.files) if kind == "map"
                     else self.n_reduce if kind == "reduce"
                     else self.n_shards)
            if (not isinstance(task, int) or isinstance(task, bool)
                    or not 0 <= task < bound):
                self._trunc_at = rec_start
                break
            if kind == "shard":
                attempt = rec.get("attempt")
                if (not isinstance(attempt, int)
                        or isinstance(attempt, bool) or attempt < 0):
                    self._trunc_at = rec_start
                    break
                # First record wins; a duplicate here would mean the
                # first-commit-wins lock failed — keep the winner.
                self.shard_commits.setdefault(
                    task, (attempt, int(rec.get("crc", 0) or 0)))
                continue
            if kind == "resplit":
                ranges = rec.get("ranges")
                ok_ranges = (isinstance(ranges, list) and len(ranges) >= 2
                             and all(isinstance(r, list) and len(r) == 2
                                     and all(isinstance(x, int)
                                             and not isinstance(x, bool)
                                             and x >= 0 for x in r)
                                     for r in ranges))
                if not ok_ranges:
                    self._trunc_at = rec_start
                    break
                # First re-split of a shard wins (there is at most one).
                self.resplits.setdefault(
                    task, [(int(s), int(e)) for s, e in ranges])
                continue
            if kind == "subshard":
                attempt, sub = rec.get("attempt"), rec.get("sub")
                if any(not isinstance(v, int) or isinstance(v, bool)
                       or v < 0 for v in (attempt, sub)):
                    self._trunc_at = rec_start
                    break
                self.subshard_commits.setdefault(
                    (task, sub), (attempt, int(rec.get("crc", 0) or 0)))
                continue
            if kind == "map":
                maps.append(task)
                addr = rec.get("addr")
                if isinstance(addr, str) and addr:
                    self.map_locations[task] = addr
                    sizes = rec.get("sizes")
                    if (isinstance(sizes, list)
                            and all(isinstance(x, int)
                                    and not isinstance(x, bool)
                                    and x >= 0 for x in sizes)):
                        self.map_sizes[task] = [int(x) for x in sizes]
            else:
                reduces.append(task)
                addr = rec.get("addr")
                if isinstance(addr, str) and addr:
                    self.out_locations[task] = (
                        addr, str(rec.get("name") or ""),
                        int(rec.get("crc", 0) or 0))
        return maps, reduces

    # ---- writing ----

    def open(self) -> None:
        # Repair corruption found during replay (torn tail or a bad mid-file
        # record): truncate at the first bad byte so future appends land in
        # replayable territory.  Falls back to plain torn-tail repair when
        # open() is used without a prior replay().
        size = os.path.getsize(self.path) if os.path.exists(self.path) else 0
        trunc_at = getattr(self, "_trunc_at", None)
        if size > 0:
            # dsicheck: allow[raw-write] in-place truncation IS the
            # torn-tail repair — rewriting the whole journal through
            # the atomic path would widen the crash window it closes
            with open(self.path, "rb+") as f:
                if trunc_at is not None and trunc_at < size:
                    f.truncate(trunc_at)
                    size = trunc_at
                else:
                    data = f.read()
                    if not data.endswith(b"\n"):
                        keep = data.rfind(b"\n") + 1
                        f.truncate(keep)
                        size = keep
        # dsicheck: allow[raw-write] append-only commit log: durability
        # comes from the per-record fsync in _write + the parent-dir
        # fsync below, and replay tolerates a torn tail by truncation —
        # the rename discipline cannot express an append stream
        self._fh = open(self.path, "a")
        # Record writes fsync the FILE, but a freshly created journal's
        # directory entry was never made durable — a crash right after
        # open() could lose the whole file and with it every completion
        # appended later.  One parent-dir fsync (the checkpoint store's
        # shared durable-write discipline, utils/atomicio.py) closes it.
        fsync_dir(os.path.dirname(os.path.abspath(self.path)) or ".")
        if size == 0:  # empty counts as fresh: a torn header must be rewritten
            header = {"kind": "header", "files": self.files,
                      "n_reduce": self.n_reduce}
            if self.n_shards:
                header["n_shards"] = self.n_shards
            self._write(header)

    def record(self, kind: str, task: int, extra: dict | None = None) -> None:
        """One completion record; ``extra`` (net mode) carries the
        location-registry fields replay() restores — same line, same
        commit-before-journal order, so fs-mode journals are unchanged
        byte-for-byte."""
        if self._fh is not None:
            rec = {"kind": kind, "task": task}
            if extra:
                rec.update(extra)
            self._write(rec)

    def record_shard(self, sid: int, attempt: int, crc: int) -> None:
        """The exactly-once shard commit record (winning attempt + the
        committed output's CRC32) — written AFTER the output file's
        durable rename, under the coordinator's lock."""
        if self._fh is not None:
            self._write({"kind": "shard", "task": sid,
                         "attempt": attempt, "crc": int(crc)})

    def record_resplit(self, sid: int, ranges) -> None:
        """The re-split dispatch record: the full sub-range geometry,
        written BEFORE any sub-shard is handed out so a restarted
        coordinator reconstructs the partition the sub-range commits
        refer to (a resplit with no commits yet simply re-queues its
        sub-ranges)."""
        if self._fh is not None:
            self._write({"kind": "resplit", "task": sid,
                         "ranges": [[int(s), int(e)] for s, e in ranges]})

    def record_subshard(self, sid: int, sub: int, attempt: int,
                        crc: int) -> None:
        """The exactly-once commit record of ONE sub-range — same
        rename-then-journal order as :meth:`record_shard`."""
        if self._fh is not None:
            self._write({"kind": "subshard", "task": sid, "sub": int(sub),
                         "attempt": attempt, "crc": int(crc)})

    def append_replicated(self, rec: dict) -> None:
        """Append one already-arbitrated record from the replicated log
        (replica/node.py's applier).  The record was framed, majority-
        committed, and ordered by Raft — this is the LOCAL durable copy
        every replica keeps so a follower that wins an election replays
        its own file to the exact task table the dead leader had."""
        if self._fh is not None:
            self._write(dict(rec))

    def _write(self, rec: dict) -> None:
        assert self._fh is not None
        self._fh.write(frame_record(rec) + "\n")
        self._fh.flush()
        os.fsync(self._fh.fileno())

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None
