"""RPC control plane: framed-JSON request/response over a Unix-domain socket.

Reference wire layer: Go ``net/rpc`` + ``rpc.HandleHTTP`` served by
``http.Serve`` on a Unix socket (``mr/coordinator.go:121-132``), client dialing
fresh per call (``mr/worker.go:172-188``), arg/reply structs in ``mr/rpc.go``.

This is a deliberate re-design, not a translation: instead of Go's
HTTP-framed gob RPC we use a minimal length-prefixed JSON protocol —
4-byte big-endian length, then a UTF-8 JSON object.  Request:
``{"method": str, "args": {...}}``; response: ``{"ok": bool, "reply": {...},
"error": str|null}``.  Semantics preserved from the reference:

* one dial per call (``mr/worker.go:175``),
* the server handles calls concurrently (``go http.Serve``,
  ``mr/coordinator.go:131``) — here a thread per connection,
* a dial failure after the coordinator exits is fatal to the worker
  (``log.Fatal``, ``mr/worker.go:176-178``) — surfaced as
  :class:`CoordinatorGone`.

The wire field names (``TaskStatus``, ``NMap``, ``CMap``, ``NReduce``,
``CReduce``, ``Filename``, ``TaskNumber``) are kept identical to
``mr/rpc.go:18-33`` so the protocol is recognizably the same.

Transports: a Unix-domain socket (the reference's live path) or TCP — the
reference carries a commented-out TCP variant for multi-host operation
(``mr/coordinator.go:124``, ``mr/worker.go:173``); here it is a first-class
address form.  Addresses are strings: ``tcp:HOST:PORT`` selects TCP
(prefer ``tcp:127.0.0.1:7777`` unless workers really are on other hosts;
those then use ``tcp:<coordinator-host>:7777`` via ``DSI_MR_SOCKET``);
anything else is a Unix socket path.  The filesystem data plane must be
shared (NFS etc.) for multi-host runs, exactly as the reference assumes.

**Authentication.** The RPC surface accepts task-completion reports, so an
unauthenticated TCP listener would let any reachable peer corrupt job
output.  When ``DSI_MR_SECRET`` is set (or a ``secret=`` is passed
explicitly), every request frame must carry an ``"auth"`` object holding a
nonce and an HMAC-SHA256 over the frame body keyed by the secret — the
secret itself never crosses the wire, so a traffic observer cannot extract
it and forge arbitrary calls.  Mismatches are rejected before method
dispatch.  Binding TCP on a non-loopback interface without a secret is
refused outright — Unix sockets and loopback keep the reference's no-auth
behavior (the reference never enabled TCP at all, mr/coordinator.go:124).

**Replay protection.**  Authenticated frames also carry a timestamp, MACed
together with the nonce and body.  The server rejects frames older than
``DSI_MR_AUTH_WINDOW_S`` (default 300 s — generous for honest clock skew)
and remembers nonces seen inside the window, so a captured frame cannot be
re-sent to the same server process: too old → stale; inside the window →
nonce already seen.  The nonce memory is bounded by the window's call
volume, not job length.  Limits, stated plainly: the guard is per-process
memory, so a frame captured just before a coordinator restart could be
replayed against the restarted process inside the window (handlers are
idempotent and the journal dedups completions, so this is a nuisance, not
corruption); and frames are not encrypted (an on-path observer reads task
filenames).  Treat non-loopback TCP as suitable for trusted/isolated
networks only.

**Dial robustness.** The reference treats any dial failure as
"coordinator gone" (``log.Fatal``, mr/worker.go:176-188) — but its Go
runtime sits behind a 128-backlog listener, so a *busy* coordinator never
looks like a dead one.  Our ``call()`` keeps that distinction explicit:
transient dial errors (EAGAIN from a full accept queue, ECONNREFUSED races,
ECONNRESET) are retried with bounded exponential backoff;
:class:`CoordinatorGone` is raised only when the failure persists through
the retry budget (or the socket path is simply absent).
"""

from __future__ import annotations

import errno
import hmac
import json
import os
import socket
import socketserver
import struct
import threading
import time
from typing import Any, Callable, Dict

_LEN = struct.Struct(">I")
_MAX_FRAME = 16 << 20

# Dial errors worth retrying: a full accept backlog (EAGAIN/ECONNABORTED), a
# listener mid-restart (ECONNREFUSED while the socket path still exists), or
# a reset race.  ENOENT (no socket file) is NOT here: that is the genuine
# coordinator-gone signal on the Unix transport.
_TRANSIENT_DIAL_ERRNOS = frozenset({
    errno.EAGAIN, errno.EWOULDBLOCK, errno.ECONNREFUSED, errno.ECONNRESET,
    errno.ECONNABORTED, errno.EINTR,
})
_DIAL_ATTEMPTS = 6
_DIAL_BACKOFF_S = 0.05  # base; doubled per attempt with jitter below
#: Jitter fraction: each sleep is ``base * 2^i * (1 + J*u)`` with
#: ``u ~ U[0,1)`` — a restarting coordinator's whole fleet must not
#: retry in lockstep (the synchronized-retry thundering herd the fixed
#: doubling schedule produced: every worker that failed the same
#: accept-queue race re-dialed at exactly the same instants).
_DIAL_JITTER = 0.5


def dial_backoff_schedule(attempts: int = _DIAL_ATTEMPTS,
                          base: float = _DIAL_BACKOFF_S,
                          jitter: float = _DIAL_JITTER,
                          rng=None) -> list[float]:
    """The ``attempts - 1`` sleep durations between dial attempts:
    jittered exponential backoff.  ``rng`` is a 0-arg callable in
    [0, 1) (default ``random.random``) — injectable so the unit test
    pins the schedule envelope exactly.  Worst case
    ``sum(base * 2^i * (1 + jitter))``: ~2.3 s at the defaults, the
    give-up bound before :class:`CoordinatorGone`."""
    if rng is None:
        import random

        rng = random.random
    return [base * (2 ** i) * (1.0 + jitter * rng())
            for i in range(max(0, attempts - 1))]


def _canonical_body(method: str, args: dict) -> bytes:
    """Deterministic bytes both sides MAC over (key order must not matter)."""
    return json.dumps({"method": method, "args": args},
                      sort_keys=True, separators=(",", ":")).encode("utf-8")


def _auth_mac(secret: str, nonce: str, ts: str, body: bytes) -> str:
    msg = nonce.encode("ascii") + b"|" + ts.encode("ascii") + b"|" + body
    return hmac.new(secret.encode("utf-8"), msg, "sha256").hexdigest()


def _auth_window_s() -> float:
    try:
        return float(os.environ.get("DSI_MR_AUTH_WINDOW_S", "300"))
    except ValueError:
        return 300.0


class _ReplayGuard:
    """Nonces seen inside the freshness window; per-server, lock-protected.

    Memory is bounded by the window's call volume: expired entries are
    pruned on every insert once the table grows past a small threshold.
    """

    def __init__(self) -> None:
        self._seen: Dict[str, float] = {}
        self._mu = threading.Lock()

    def first_use(self, nonce: str, now: float, window: float) -> bool:
        with self._mu:
            if len(self._seen) > 4096:
                cutoff = now - window
                self._seen = {n: t for n, t in self._seen.items()
                              if t >= cutoff}
            if nonce in self._seen:
                return False
            self._seen[nonce] = now
            return True


def _check_auth(secret: str, req: dict, guard: _ReplayGuard | None) -> bool:
    """Verify the request's auth object without ever learning more than
    pass/fail; malformed auth shapes are just failures."""
    if not isinstance(req, dict):
        return False
    auth = req.get("auth")
    if not isinstance(auth, dict):
        return False
    nonce, mac, ts = auth.get("nonce"), auth.get("mac"), auth.get("ts")
    if not (isinstance(nonce, str) and isinstance(mac, str)
            and isinstance(ts, str)):
        return False
    try:
        nonce.encode("ascii")
        ts_val = float(ts)
    except (UnicodeEncodeError, ValueError):
        return False
    want = _auth_mac(secret, nonce, ts,
                     _canonical_body(req.get("method", ""),
                                     req.get("args") or {}))
    if not hmac.compare_digest(mac.encode("ascii", "replace"),
                               want.encode("ascii")):
        return False
    # Freshness + first-use: a captured frame is either stale (outside the
    # window) or its nonce is already in the guard (inside it).
    now = time.time()
    window = _auth_window_s()
    if abs(now - ts_val) > window:
        return False
    return guard is None or guard.first_use(nonce, now, window)


class CoordinatorGone(Exception):
    """Raised when the coordinator socket cannot be dialed (reference:
    worker's log.Fatal on dial error, mr/worker.go:176-178)."""


class AuthError(CoordinatorGone):
    """The server rejected our auth token.  A worker with a missing or
    wrong DSI_MR_SECRET can never make progress, so this is fatal like
    CoordinatorGone — but it must be LOUD: a silent exit here looks exactly
    like normal end-of-job and the fleet quietly shrinks to zero."""


def parse_address(addr: str):
    """``tcp:HOST:PORT`` -> ("tcp", (host, port)); anything else is a Unix
    socket path -> ("unix", path).  Raises ValueError with a usable message
    on a malformed TCP address (callers on the dial path wrap it)."""
    if addr.startswith("tcp:"):
        host, _, port = addr[4:].rpartition(":")
        try:
            return "tcp", (host or "0.0.0.0", int(port))
        except ValueError:
            raise ValueError(
                f"malformed TCP address {addr!r}: want tcp:HOST:PORT") from None
    return "unix", addr


def _reachable_host(bind_host: str) -> str:
    """A host other machines can dial when we bound a wildcard address.

    ``DSI_MR_ADVERTISE`` overrides (the reliable answer on multi-homed or
    containerized hosts); otherwise the UDP-connect routing trick picks the
    outbound interface, falling back to the hostname — which may resolve to
    loopback on some distros, hence the override.
    """
    env = os.environ.get("DSI_MR_ADVERTISE")
    if env:
        return env
    if bind_host not in ("0.0.0.0", ""):
        return bind_host
    try:
        # Routing trick: connect() on UDP picks the outbound interface
        # without sending a packet.  A public address (8.8.8.8) selects the
        # default route; an RFC1918 probe would pick an unrelated interface
        # on hosts with no 10/8 route.
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect(("8.8.8.8", 53))
            return s.getsockname()[0]
        finally:
            s.close()
    except OSError:
        import sys
        try:
            host = socket.gethostbyname(socket.gethostname())
        except OSError:
            host = socket.gethostname()
        print(f"dsi-mr: cannot determine outbound interface; advertising "
              f"{host!r} — set DSI_MR_ADVERTISE if workers cannot dial it",
              file=sys.stderr)
        return host


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        buf += chunk
    return buf


def _send_frame(sock: socket.socket, obj: Any) -> None:
    payload = json.dumps(obj).encode("utf-8")
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_frame(sock: socket.socket) -> Any:
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    if n > _MAX_FRAME:
        raise ConnectionError(f"frame too large: {n}")
    return json.loads(_recv_exact(sock, n).decode("utf-8"))


class RpcServer:
    """Threaded RPC server over a Unix-domain socket or TCP.

    Mirrors ``(*Coordinator).server()`` (mr/coordinator.go:121-132): removes a
    stale socket file, listens, and serves in background threads.
    """

    def __init__(self, socket_path: str,
                 methods: Dict[str, Callable[[dict], dict]],
                 secret: str | None = None):
        self.socket_path = socket_path
        self.methods = dict(methods)
        self._kind, target = parse_address(socket_path)
        secret = secret if secret is not None else os.environ.get("DSI_MR_SECRET")
        if (self._kind == "tcp" and not secret
                and target[0] not in ("127.0.0.1", "localhost", "::1")):
            raise ValueError(
                f"refusing to bind {socket_path!r} without authentication: "
                "the RPC surface accepts task-completion reports, so an open "
                "TCP listener lets any peer corrupt job output. Set "
                "DSI_MR_SECRET (workers need the same value) or bind "
                "tcp:127.0.0.1:PORT.")
        if self._kind == "unix":
            try:
                os.remove(socket_path)  # mr/coordinator.go:126
            except OSError:
                pass

        handler_methods = self.methods
        replay_guard = _ReplayGuard()

        class Handler(socketserver.BaseRequestHandler):
            def handle(self) -> None:  # one request per connection (dial-per-call)
                try:
                    # A peer that connects and never sends (port scanner,
                    # stalled NAT) must not pin a handler thread + fd
                    # forever — remotely reachable once bound to TCP.
                    self.request.settimeout(60.0)
                    req = _recv_frame(self.request)
                    if not isinstance(req, dict):
                        _send_frame(self.request,
                                    {"ok": False, "reply": None,
                                     "error": "malformed request frame"})
                        return
                    if secret and not _check_auth(secret, req, replay_guard):
                        _send_frame(self.request, {"ok": False, "reply": None,
                                                   "error": "auth failed"})
                        return
                    fn = handler_methods.get(req.get("method", ""))
                    if fn is None:
                        _send_frame(self.request, {"ok": False, "reply": None,
                                                   "error": f"no such method: {req.get('method')}"})
                        return
                    reply = fn(req.get("args") or {})
                    _send_frame(self.request, {"ok": True, "reply": reply, "error": None})
                except (ConnectionError, json.JSONDecodeError, OSError):
                    pass  # client vanished mid-call; the 10 s requeue covers it

        base = (socketserver.ThreadingTCPServer if self._kind == "tcp"
                else socketserver.ThreadingUnixStreamServer)

        class Server(base):
            daemon_threads = True
            allow_reuse_address = True
            # Go's net.Listen backlog is 128; Python's socketserver default
            # of 5 turns a briefly busy coordinator into spurious EAGAIN
            # dial failures for the whole fleet.
            request_queue_size = 128

        self._server = Server(target, Handler)
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name="dsi-mr-rpc", daemon=True)

    @property
    def address(self) -> str:
        """A dialable address: real port when bound to port 0, and a
        reachable host substituted when bound to a wildcard (0.0.0.0 echoed
        back would dial the *worker's* loopback on another machine)."""
        if self._kind == "tcp":
            host, port = self._server.server_address[:2]
            return f"tcp:{_reachable_host(host)}:{port}"
        return self.socket_path

    def start(self) -> None:
        self._thread.start()

    def close(self) -> None:
        if self._thread.is_alive():  # shutdown() hangs unless serve_forever runs
            self._server.shutdown()
        self._server.server_close()
        if self._kind == "unix":
            try:
                os.remove(self.socket_path)
            except OSError:
                pass


def _dial(kind: str, target, socket_path: str,
          timeout: float) -> socket.socket:
    """Connect with bounded retry on transient errors.

    A busy coordinator (full accept backlog → EAGAIN, listener race →
    ECONNREFUSED) must not be mistaken for a dead one: losing a worker to a
    transient dial error silently shrinks the fleet for the rest of the job.
    Retries ``_DIAL_ATTEMPTS`` times with JITTERED exponential backoff
    (:func:`dial_backoff_schedule` — the former fixed doubling sleep
    synchronized a whole fleet's retries after a coordinator restart),
    then gives up with :class:`CoordinatorGone`.  Non-transient errors
    (ENOENT: socket file gone — the coordinator exited and we are on
    the reference's log.Fatal path, mr/worker.go:176-178) raise
    immediately.  Connect *timeouts* are deliberately not retried: a
    host that silently drops SYNs has already cost one full
    ``timeout``, and retrying would turn that into ``_DIAL_ATTEMPTS``
    times as long.
    """
    family = socket.AF_INET if kind == "tcp" else socket.AF_UNIX
    delays = dial_backoff_schedule()
    for attempt in range(_DIAL_ATTEMPTS):
        sock = socket.socket(family, socket.SOCK_STREAM)
        sock.settimeout(timeout)
        try:
            sock.connect(target)
            return sock
        except OSError as e:
            sock.close()
            transient = e.errno in _TRANSIENT_DIAL_ERRNOS
            if not transient or attempt == _DIAL_ATTEMPTS - 1:
                raise CoordinatorGone(f"dialing {socket_path}: {e}") from e
            time.sleep(delays[attempt])
    raise AssertionError("unreachable")


def call(socket_path: str, method: str, args: dict | None = None,
         timeout: float = 60.0, secret: str | None = None) -> tuple[bool, dict | None]:
    """One RPC: dial, send, receive, close.

    Returns ``(ok, reply)`` like the reference's ``call()`` helper
    (mr/worker.go:172-188).  Raises :class:`CoordinatorGone` if the socket
    cannot be dialed after the transient-error retry budget — the reference
    worker dies here (log.Fatal), and our worker loop treats it as job-over.
    ``secret`` (default ``DSI_MR_SECRET``) is attached as the frame's
    ``auth`` field for servers that require it.
    """
    try:
        kind, target = parse_address(socket_path)
    except ValueError as e:
        raise CoordinatorGone(str(e)) from None
    secret = secret if secret is not None else os.environ.get("DSI_MR_SECRET")
    sock = _dial(kind, target, socket_path, timeout)
    try:
        req: dict = {"method": method, "args": args or {}}
        if secret:
            nonce = os.urandom(16).hex()
            ts = repr(time.time())
            req["auth"] = {"nonce": nonce, "ts": ts,
                           "mac": _auth_mac(secret, nonce, ts,
                                            _canonical_body(method,
                                                            args or {}))}
        try:
            _send_frame(sock, req)
            resp = _recv_frame(sock)
        except (OSError, ConnectionError, json.JSONDecodeError):
            return False, None  # RPC-level failure -> ok=false (worker.go:186-188)
        if not isinstance(resp, dict):
            return False, None  # non-object frame: treat as RPC failure
        if not resp.get("ok"):
            if resp.get("error") == "auth failed":
                raise AuthError(
                    f"server at {socket_path} rejected our auth token — "
                    "check DSI_MR_SECRET matches the coordinator's")
            return False, None
        return True, resp.get("reply")
    finally:
        sock.close()
