"""RPC control plane: framed-JSON request/response over a Unix-domain socket.

Reference wire layer: Go ``net/rpc`` + ``rpc.HandleHTTP`` served by
``http.Serve`` on a Unix socket (``mr/coordinator.go:121-132``), client dialing
fresh per call (``mr/worker.go:172-188``), arg/reply structs in ``mr/rpc.go``.

This is a deliberate re-design, not a translation: instead of Go's
HTTP-framed gob RPC we use a minimal length-prefixed JSON protocol —
4-byte big-endian length, then a UTF-8 JSON object.  Request:
``{"method": str, "args": {...}}``; response: ``{"ok": bool, "reply": {...},
"error": str|null}``.  Semantics preserved from the reference:

* one dial per call (``mr/worker.go:175``),
* the server handles calls concurrently (``go http.Serve``,
  ``mr/coordinator.go:131``) — here a thread per connection,
* a dial failure after the coordinator exits is fatal to the worker
  (``log.Fatal``, ``mr/worker.go:176-178``) — surfaced as
  :class:`CoordinatorGone`.

The wire field names (``TaskStatus``, ``NMap``, ``CMap``, ``NReduce``,
``CReduce``, ``Filename``, ``TaskNumber``) are kept identical to
``mr/rpc.go:18-33`` so the protocol is recognizably the same.

Transports: a Unix-domain socket (the reference's live path) or TCP — the
reference carries a commented-out TCP variant for multi-host operation
(``mr/coordinator.go:124``, ``mr/worker.go:173``); here it is a first-class
address form.  Addresses are strings: ``tcp:HOST:PORT`` selects TCP
(prefer ``tcp:127.0.0.1:7777`` unless workers really are on other hosts;
those then use ``tcp:<coordinator-host>:7777`` via ``DSI_MR_SOCKET``);
anything else is a Unix socket path.  The filesystem data plane must be
shared (NFS etc.) for multi-host runs, exactly as the reference assumes.

**Authentication.** The RPC surface accepts task-completion reports, so an
unauthenticated TCP listener would let any reachable peer corrupt job
output.  When ``DSI_MR_SECRET`` is set (or a ``secret=`` is passed
explicitly), every request frame must carry an ``"auth"`` object holding a
nonce and an HMAC-SHA256 over the frame body keyed by the secret — the
secret itself never crosses the wire, so a traffic observer cannot extract
it and forge arbitrary calls.  Mismatches are rejected before method
dispatch.  Binding TCP on a non-loopback interface without a secret is
refused outright — Unix sockets and loopback keep the reference's no-auth
behavior (the reference never enabled TCP at all, mr/coordinator.go:124).

**Replay protection.**  Authenticated frames also carry a timestamp, MACed
together with the nonce and body.  The server rejects frames older than
``DSI_MR_AUTH_WINDOW_S`` (default 300 s — generous for honest clock skew)
and remembers nonces seen inside the window, so a captured frame cannot be
re-sent to the same server process: too old → stale; inside the window →
nonce already seen.  The nonce memory is bounded by the window's call
volume, not job length.  Limits, stated plainly: the guard is per-process
memory, so a frame captured just before a coordinator restart could be
replayed against the restarted process inside the window (handlers are
idempotent and the journal dedups completions, so this is a nuisance, not
corruption); and frames are not encrypted (an on-path observer reads task
filenames).  Treat non-loopback TCP as suitable for trusted/isolated
networks only.

**Dial robustness.** The reference treats any dial failure as
"coordinator gone" (``log.Fatal``, mr/worker.go:176-188) — but its Go
runtime sits behind a 128-backlog listener, so a *busy* coordinator never
looks like a dead one.  Our ``call()`` keeps that distinction explicit:
transient dial errors (EAGAIN from a full accept queue, ECONNREFUSED races,
ECONNRESET) are retried with bounded exponential backoff;
:class:`CoordinatorGone` is raised only when the failure persists through
the retry budget (or the socket path is simply absent).
"""

from __future__ import annotations

import errno
import hmac
import json
import os
import socket
import socketserver
import struct
import threading
import time
import zlib
from typing import Any, Callable, Dict

_LEN = struct.Struct(">I")
_MAX_FRAME = 16 << 20

# Dial errors worth retrying: a full accept backlog (EAGAIN/ECONNABORTED), a
# listener mid-restart (ECONNREFUSED while the socket path still exists), or
# a reset race.  ENOENT (no socket file) is NOT here: that is the genuine
# coordinator-gone signal on the Unix transport.
_TRANSIENT_DIAL_ERRNOS = frozenset({
    errno.EAGAIN, errno.EWOULDBLOCK, errno.ECONNREFUSED, errno.ECONNRESET,
    errno.ECONNABORTED, errno.EINTR,
})
_DIAL_ATTEMPTS = 6
_DIAL_BACKOFF_S = 0.05  # base; doubled per attempt with jitter below
#: Jitter fraction: each sleep is ``base * 2^i * (1 + J*u)`` with
#: ``u ~ U[0,1)`` — a restarting coordinator's whole fleet must not
#: retry in lockstep (the synchronized-retry thundering herd the fixed
#: doubling schedule produced: every worker that failed the same
#: accept-queue race re-dialed at exactly the same instants).
_DIAL_JITTER = 0.5


def dial_backoff_schedule(attempts: int = _DIAL_ATTEMPTS,
                          base: float = _DIAL_BACKOFF_S,
                          jitter: float = _DIAL_JITTER,
                          rng=None) -> list[float]:
    """The ``attempts - 1`` sleep durations between dial attempts:
    jittered exponential backoff.  ``rng`` is a 0-arg callable in
    [0, 1) (default ``random.random``) — injectable so the unit test
    pins the schedule envelope exactly.  Worst case
    ``sum(base * 2^i * (1 + jitter))``: ~2.3 s at the defaults, the
    give-up bound before :class:`CoordinatorGone`."""
    if rng is None:
        import random

        rng = random.random
    return [base * (2 ** i) * (1.0 + jitter * rng())
            for i in range(max(0, attempts - 1))]


def _canonical_body(method: str, args: dict) -> bytes:
    """Deterministic bytes both sides MAC over (key order must not matter)."""
    return json.dumps({"method": method, "args": args},
                      sort_keys=True, separators=(",", ":")).encode("utf-8")


def _auth_mac(secret: str, nonce: str, ts: str, body: bytes) -> str:
    msg = nonce.encode("ascii") + b"|" + ts.encode("ascii") + b"|" + body
    return hmac.new(secret.encode("utf-8"), msg, "sha256").hexdigest()


def _auth_window_s() -> float:
    try:
        return float(os.environ.get("DSI_MR_AUTH_WINDOW_S", "300"))
    except ValueError:
        return 300.0


class _ReplayGuard:
    """Nonces seen inside the freshness window; per-server, lock-protected.

    Memory is bounded by the window's call volume: expired entries are
    pruned on every insert once the table grows past a small threshold.
    """

    def __init__(self) -> None:
        self._seen: Dict[str, float] = {}
        self._mu = threading.Lock()

    def first_use(self, nonce: str, now: float, window: float) -> bool:
        with self._mu:
            if len(self._seen) > 4096:
                cutoff = now - window
                self._seen = {n: t for n, t in self._seen.items()
                              if t >= cutoff}
            if nonce in self._seen:
                return False
            self._seen[nonce] = now
            return True


def _check_auth(secret: str, req: dict, guard: _ReplayGuard | None) -> bool:
    """Verify the request's auth object without ever learning more than
    pass/fail; malformed auth shapes are just failures."""
    if not isinstance(req, dict):
        return False
    auth = req.get("auth")
    if not isinstance(auth, dict):
        return False
    nonce, mac, ts = auth.get("nonce"), auth.get("mac"), auth.get("ts")
    if not (isinstance(nonce, str) and isinstance(mac, str)
            and isinstance(ts, str)):
        return False
    try:
        nonce.encode("ascii")
        ts_val = float(ts)
    except (UnicodeEncodeError, ValueError):
        return False
    want = _auth_mac(secret, nonce, ts,
                     _canonical_body(req.get("method", ""),
                                     req.get("args") or {}))
    if not hmac.compare_digest(mac.encode("ascii", "replace"),
                               want.encode("ascii")):
        return False
    # Freshness + first-use: a captured frame is either stale (outside the
    # window) or its nonce is already in the guard (inside it).
    now = time.time()
    window = _auth_window_s()
    if abs(now - ts_val) > window:
        return False
    return guard is None or guard.first_use(nonce, now, window)


class CoordinatorGone(Exception):
    """Raised when the coordinator socket cannot be dialed (reference:
    worker's log.Fatal on dial error, mr/worker.go:176-178)."""


class AuthError(CoordinatorGone):
    """The server rejected our auth token.  A worker with a missing or
    wrong DSI_MR_SECRET can never make progress, so this is fatal like
    CoordinatorGone — but it must be LOUD: a silent exit here looks exactly
    like normal end-of-job and the fleet quietly shrinks to zero."""


def parse_address(addr: str):
    """``tcp:HOST:PORT`` -> ("tcp", (host, port)); anything else is a Unix
    socket path -> ("unix", path).  Raises ValueError with a usable message
    on a malformed TCP address (callers on the dial path wrap it)."""
    if addr.startswith("tcp:"):
        host, _, port = addr[4:].rpartition(":")
        try:
            return "tcp", (host or "0.0.0.0", int(port))
        except ValueError:
            raise ValueError(
                f"malformed TCP address {addr!r}: want tcp:HOST:PORT") from None
    return "unix", addr


def _reachable_host(bind_host: str) -> str:
    """A host other machines can dial when we bound a wildcard address.

    ``DSI_MR_ADVERTISE`` overrides (the reliable answer on multi-homed or
    containerized hosts); otherwise the UDP-connect routing trick picks the
    outbound interface, falling back to the hostname — which may resolve to
    loopback on some distros, hence the override.
    """
    env = os.environ.get("DSI_MR_ADVERTISE")
    if env:
        return env
    if bind_host not in ("0.0.0.0", ""):
        return bind_host
    try:
        # Routing trick: connect() on UDP picks the outbound interface
        # without sending a packet.  A public address (8.8.8.8) selects the
        # default route; an RFC1918 probe would pick an unrelated interface
        # on hosts with no 10/8 route.
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect(("8.8.8.8", 53))
            return s.getsockname()[0]
        finally:
            s.close()
    except OSError:
        import sys
        try:
            host = socket.gethostbyname(socket.gethostname())
        except OSError:
            host = socket.gethostname()
        print(f"dsi-mr: cannot determine outbound interface; advertising "
              f"{host!r} — set DSI_MR_ADVERTISE if workers cannot dial it",
              file=sys.stderr)
        return host


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        buf += chunk
    return buf


def _send_frame(sock: socket.socket, obj: Any) -> None:
    payload = json.dumps(obj).encode("utf-8")
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_frame(sock: socket.socket) -> Any:
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    if n > _MAX_FRAME:
        raise ConnectionError(f"frame too large: {n}")
    return json.loads(_recv_exact(sock, n).decode("utf-8"))


class RpcServer:
    """Threaded RPC server over a Unix-domain socket or TCP.

    Mirrors ``(*Coordinator).server()`` (mr/coordinator.go:121-132): removes a
    stale socket file, listens, and serves in background threads.
    """

    def __init__(self, socket_path: str,
                 methods: Dict[str, Callable[[dict], dict]],
                 secret: str | None = None):
        self.socket_path = socket_path
        self.methods = dict(methods)
        self._kind, target = parse_address(socket_path)
        secret = secret if secret is not None else os.environ.get("DSI_MR_SECRET")
        if (self._kind == "tcp" and not secret
                and target[0] not in ("127.0.0.1", "localhost", "::1")):
            raise ValueError(
                f"refusing to bind {socket_path!r} without authentication: "
                "the RPC surface accepts task-completion reports, so an open "
                "TCP listener lets any peer corrupt job output. Set "
                "DSI_MR_SECRET (workers need the same value) or bind "
                "tcp:127.0.0.1:PORT.")
        if self._kind == "unix":
            try:
                os.remove(socket_path)  # mr/coordinator.go:126
            except OSError:
                pass

        handler_methods = self.methods
        replay_guard = _ReplayGuard()

        class Handler(socketserver.BaseRequestHandler):
            def handle(self) -> None:  # one request per connection (dial-per-call)
                try:
                    # A peer that connects and never sends (port scanner,
                    # stalled NAT) must not pin a handler thread + fd
                    # forever — remotely reachable once bound to TCP.
                    self.request.settimeout(60.0)
                    req = _recv_frame(self.request)
                    if not isinstance(req, dict):
                        _send_frame(self.request,
                                    {"ok": False, "reply": None,
                                     "error": "malformed request frame"})
                        return
                    if secret and not _check_auth(secret, req, replay_guard):
                        _send_frame(self.request, {"ok": False, "reply": None,
                                                   "error": "auth failed"})
                        return
                    fn = handler_methods.get(req.get("method", ""))
                    if fn is None:
                        _send_frame(self.request, {"ok": False, "reply": None,
                                                   "error": f"no such method: {req.get('method')}"})
                        return
                    reply = fn(req.get("args") or {})
                    _send_frame(self.request, {"ok": True, "reply": reply, "error": None})
                except (ConnectionError, json.JSONDecodeError, OSError):
                    pass  # client vanished mid-call; the 10 s requeue covers it

        base = (socketserver.ThreadingTCPServer if self._kind == "tcp"
                else socketserver.ThreadingUnixStreamServer)

        class Server(base):
            daemon_threads = True
            allow_reuse_address = True
            # Go's net.Listen backlog is 128; Python's socketserver default
            # of 5 turns a briefly busy coordinator into spurious EAGAIN
            # dial failures for the whole fleet.
            request_queue_size = 128

        self._server = Server(target, Handler)
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name="dsi-mr-rpc", daemon=True)

    @property
    def address(self) -> str:
        """A dialable address: real port when bound to port 0, and a
        reachable host substituted when bound to a wildcard (0.0.0.0 echoed
        back would dial the *worker's* loopback on another machine)."""
        if self._kind == "tcp":
            host, port = self._server.server_address[:2]
            return f"tcp:{_reachable_host(host)}:{port}"
        return self.socket_path

    def start(self) -> None:
        self._thread.start()

    def close(self) -> None:
        if self._thread.is_alive():  # shutdown() hangs unless serve_forever runs
            self._server.shutdown()
        self._server.server_close()
        if self._kind == "unix":
            try:
                os.remove(self.socket_path)
            except OSError:
                pass


def _dial(kind: str, target, socket_path: str,
          timeout: float) -> socket.socket:
    """Connect with bounded retry on transient errors.

    A busy coordinator (full accept backlog → EAGAIN, listener race →
    ECONNREFUSED) must not be mistaken for a dead one: losing a worker to a
    transient dial error silently shrinks the fleet for the rest of the job.
    Retries ``_DIAL_ATTEMPTS`` times with JITTERED exponential backoff
    (:func:`dial_backoff_schedule` — the former fixed doubling sleep
    synchronized a whole fleet's retries after a coordinator restart),
    then gives up with :class:`CoordinatorGone`.  Non-transient errors
    (ENOENT: socket file gone — the coordinator exited and we are on
    the reference's log.Fatal path, mr/worker.go:176-178) raise
    immediately.  Connect *timeouts* are deliberately not retried: a
    host that silently drops SYNs has already cost one full
    ``timeout``, and retrying would turn that into ``_DIAL_ATTEMPTS``
    times as long.
    """
    family = socket.AF_INET if kind == "tcp" else socket.AF_UNIX
    delays = dial_backoff_schedule()
    for attempt in range(_DIAL_ATTEMPTS):
        sock = socket.socket(family, socket.SOCK_STREAM)
        sock.settimeout(timeout)
        try:
            sock.connect(target)
            return sock
        except OSError as e:
            sock.close()
            transient = e.errno in _TRANSIENT_DIAL_ERRNOS
            if not transient or attempt == _DIAL_ATTEMPTS - 1:
                raise CoordinatorGone(f"dialing {socket_path}: {e}") from e
            time.sleep(delays[attempt])
    raise AssertionError("unreachable")


def call(socket_path: str, method: str, args: dict | None = None,
         timeout: float = 60.0, secret: str | None = None) -> tuple[bool, dict | None]:
    """One RPC: dial, send, receive, close.

    Returns ``(ok, reply)`` like the reference's ``call()`` helper
    (mr/worker.go:172-188).  Raises :class:`CoordinatorGone` if the socket
    cannot be dialed after the transient-error retry budget — the reference
    worker dies here (log.Fatal), and our worker loop treats it as job-over.
    ``secret`` (default ``DSI_MR_SECRET``) is attached as the frame's
    ``auth`` field for servers that require it.
    """
    try:
        kind, target = parse_address(socket_path)
    except ValueError as e:
        raise CoordinatorGone(str(e)) from None
    secret = secret if secret is not None else os.environ.get("DSI_MR_SECRET")
    sock = _dial(kind, target, socket_path, timeout)
    try:
        req: dict = {"method": method, "args": args or {}}
        if secret:
            nonce = os.urandom(16).hex()
            ts = repr(time.time())
            req["auth"] = {"nonce": nonce, "ts": ts,
                           "mac": _auth_mac(secret, nonce, ts,
                                            _canonical_body(method,
                                                            args or {}))}
        try:
            _send_frame(sock, req)
            resp = _recv_frame(sock)
        except (OSError, ConnectionError, json.JSONDecodeError):
            return False, None  # RPC-level failure -> ok=false (worker.go:186-188)
        if not isinstance(resp, dict):
            return False, None  # non-object frame: treat as RPC failure
        if not resp.get("ok"):
            if resp.get("error") == "auth failed":
                raise AuthError(
                    f"server at {socket_path} rejected our auth token — "
                    "check DSI_MR_SECRET matches the coordinator's")
            return False, None
        return True, resp.get("reply")
    finally:
        sock.close()


# ---------------------------------------------------------------------------
# Streaming fetch transport (the network data plane's bulk link).
#
# The framed-JSON protocol above tops out at _MAX_FRAME and base64 would tax
# every byte; shuffle partitions and shard outputs need a raw-bytes path.
# Wire shape, after the TCP connect:
#
#   both sides:  hello = b"DSN" + version byte       (4 bytes, sent eagerly)
#   client:      framed-JSON request {"method","args"[,"auth"]}  (as above)
#   server:      framed-JSON header {"ok","size","error"}
#   server:      chunks  [4-byte len][payload][4-byte CRC32(payload)] ...
#   server:      trailer [4-byte 0][4-byte CRC32(entire payload)]
#
# The eager hello is the version gate the satellite task names: a
# mixed-version fleet fails in ONE round trip with ProtocolMismatch instead
# of hanging through the dial backoff schedule — connection-refused (dead
# server) stays CoordinatorGone, so callers can tell "re-fetch elsewhere"
# from "this fleet is mis-deployed".  Per-chunk CRCs catch corruption as
# early as the first bad chunk; the whole-payload trailer catches a server
# that died mid-serve and a kernel that flushed a truncated tail.

_HELLO_MAGIC = b"DSN"
PROTOCOL_VERSION = 1
_STREAM_CHUNK = 256 << 10
#: Streamed payloads may exceed _MAX_FRAME (shard outputs, relay buffers);
#: this is the abuse bound, not a design limit.
_MAX_STREAM = 1 << 30


class ProtocolMismatch(CoordinatorGone):
    """The peer's hello frame carried a different protocol version (or no
    recognizable hello at all).  A mixed-version fleet can never make
    progress, so this is fatal like CoordinatorGone — but distinct and
    LOUD: retrying through the backoff schedule would just hang, and a
    silent exit looks exactly like end-of-job."""


class StreamError(ConnectionError):
    """A stream fetch failed after a successful dial: server-side error
    (no such partition), a CRC mismatch, or a peer death mid-stream.  The
    caller's move is re-fetch from a replacement, not retry here."""


def _hello_bytes() -> bytes:
    return _HELLO_MAGIC + bytes((PROTOCOL_VERSION,))


def _check_hello(raw: bytes, peer: str) -> None:
    if len(raw) != 4 or raw[:3] != _HELLO_MAGIC:
        raise ProtocolMismatch(
            f"{peer} did not speak the stream protocol (got {raw!r}); "
            "is the address really a partition server?")
    if raw[3] != PROTOCOL_VERSION:
        raise ProtocolMismatch(
            f"{peer} speaks stream protocol v{raw[3]}, we speak "
            f"v{PROTOCOL_VERSION} — mixed-version fleet, upgrade in lockstep")


class StreamServer:
    """Threaded streaming-fetch server: methods return raw ``bytes``.

    Same address forms and auth policy as :class:`RpcServer` (non-loopback
    TCP without a secret is refused).  ``chunk_hook(i)`` — if given — runs
    after chunk ``i`` of a response hits the socket; the partition server
    threads its ``mid-serve`` fault/chaos point through it so tests can
    kill a server with a half-sent payload on the wire.
    """

    def __init__(self, address: str,
                 methods: Dict[str, Callable[[dict], bytes]],
                 secret: str | None = None,
                 chunk_hook: Callable[[int], None] | None = None,
                 chunk_size: int = _STREAM_CHUNK):
        self.socket_path = address
        self.methods = dict(methods)
        self._kind, target = parse_address(address)
        secret = (secret if secret is not None
                  else os.environ.get("DSI_MR_SECRET"))
        if (self._kind == "tcp" and not secret
                and target[0] not in ("127.0.0.1", "localhost", "::1")):
            raise ValueError(
                f"refusing to bind {address!r} without authentication: an "
                "open partition server serves job bytes to any peer. Set "
                "DSI_MR_SECRET or bind tcp:127.0.0.1:PORT.")
        if self._kind == "unix":
            try:
                os.remove(address)
            except OSError:
                pass

        handler_methods = self.methods
        replay_guard = _ReplayGuard()

        class Handler(socketserver.BaseRequestHandler):
            def handle(self) -> None:  # hello once, then fetches until EOF
                try:
                    self.request.settimeout(60.0)
                    self.request.sendall(_hello_bytes())
                    _check_hello(_recv_exact(self.request, 4), "client")
                    while self._serve_one():
                        pass
                except (ConnectionError, json.JSONDecodeError, OSError):
                    pass  # client vanished mid-fetch; it re-fetches

            def _serve_one(self) -> bool:
                # One request/response round trip on an established
                # connection.  Returns True to keep the connection open
                # for the next request (prefetch pipelines reuse the
                # socket per producer); any error response closes it so
                # the error cannot desynchronize a pipelined client.
                req = _recv_frame(self.request)
                if not isinstance(req, dict):
                    _send_frame(self.request,
                                {"ok": False, "size": 0,
                                 "error": "malformed request frame"})
                    return False
                if secret and not _check_auth(secret, req, replay_guard):
                    _send_frame(self.request,
                                {"ok": False, "size": 0,
                                 "error": "auth failed"})
                    return False
                fn = handler_methods.get(req.get("method", ""))
                if fn is None:
                    _send_frame(self.request,
                                {"ok": False, "size": 0,
                                 "error": "no such method: "
                                          f"{req.get('method')}"})
                    return False
                try:
                    payload = fn(req.get("args") or {})
                except Exception as e:  # handler error -> header frame
                    _send_frame(self.request,
                                {"ok": False, "size": 0,
                                 "error": f"{type(e).__name__}: {e}"})
                    return False
                _send_frame(self.request, {"ok": True,
                                           "size": len(payload),
                                           "error": None})
                for i, off in enumerate(
                        range(0, len(payload), chunk_size)):
                    chunk = payload[off:off + chunk_size]
                    self.request.sendall(
                        _LEN.pack(len(chunk)) + chunk
                        + _LEN.pack(zlib.crc32(chunk)))
                    if chunk_hook is not None:
                        chunk_hook(i)
                self.request.sendall(
                    _LEN.pack(0) + _LEN.pack(zlib.crc32(payload)))
                return True

        base = (socketserver.ThreadingTCPServer if self._kind == "tcp"
                else socketserver.ThreadingUnixStreamServer)

        class Server(base):
            daemon_threads = True
            allow_reuse_address = True
            request_queue_size = 128

        self._server = Server(target, Handler)
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name="dsi-net-stream", daemon=True)

    @property
    def address(self) -> str:
        """Dialable address (real port for port 0, reachable host for
        wildcard binds) — same contract as :attr:`RpcServer.address`."""
        if self._kind == "tcp":
            host, port = self._server.server_address[:2]
            return f"tcp:{_reachable_host(host)}:{port}"
        return self.socket_path

    def start(self) -> None:
        self._thread.start()

    def close(self) -> None:
        if self._thread.is_alive():
            self._server.shutdown()
        self._server.server_close()
        if self._kind == "unix":
            try:
                os.remove(self.socket_path)
            except OSError:
                pass


class StreamConn:
    """A persistent streaming-fetch connection: dial + hello ONCE, then
    any number of :meth:`fetch` round trips over the same socket.

    The prefetch pipeline's per-producer connection reuse: a reducer
    pulling several partitions from one producer pays the dial backoff
    and hello exchange once instead of per partition.  Each request
    still carries its own auth nonce (the server's replay guard sees a
    fresh MAC per fetch), so keep-alive does not weaken the HMAC
    challenge.  After any error the connection is poisoned (the server
    closes its end on error responses, so the stream position is
    unknowable); callers drop it and dial fresh.  Not thread-safe —
    one dialer thread owns one conn."""

    def __init__(self, address: str, timeout: float = 60.0,
                 secret: str | None = None):
        try:
            kind, target = parse_address(address)
        except ValueError as e:
            raise CoordinatorGone(str(e)) from None
        self.address = address
        self._secret = (secret if secret is not None
                        else os.environ.get("DSI_MR_SECRET"))
        self._dead = False
        self.fetches = 0
        self._sock = _dial(kind, target, address, timeout)
        try:
            self._sock.sendall(_hello_bytes())
            try:
                hello = _recv_exact(self._sock, 4)
            except ConnectionError:
                raise StreamError(
                    f"{address} closed before hello — died while accepting")
            _check_hello(hello, address)
        except BaseException:
            self._sock.close()
            raise

    def fetch(self, method: str, args: dict | None = None,
              max_bytes: int = _MAX_STREAM) -> bytes:
        """One request/response round trip.  Raises like
        :func:`stream_fetch`; any raise poisons the connection."""
        if self._dead:
            raise StreamError(
                f"{self.address}: connection already failed, dial fresh")
        try:
            payload = self._fetch(method, args, max_bytes)
        except BaseException:
            self._dead = True
            raise
        self.fetches += 1
        return payload

    def _fetch(self, method: str, args: dict | None,
               max_bytes: int) -> bytes:
        sock, address = self._sock, self.address
        req: dict = {"method": method, "args": args or {}}
        if self._secret:
            nonce = os.urandom(16).hex()
            ts = repr(time.time())
            req["auth"] = {"nonce": nonce, "ts": ts,
                           "mac": _auth_mac(self._secret, nonce, ts,
                                            _canonical_body(method,
                                                            args or {}))}
        try:
            _send_frame(sock, req)
            hdr = _recv_frame(sock)
        except (ConnectionError, json.JSONDecodeError) as e:
            raise StreamError(f"fetching {method} from {address}: {e}") from e
        if not isinstance(hdr, dict) or not hdr.get("ok"):
            err = hdr.get("error") if isinstance(hdr, dict) else "bad header"
            if err == "auth failed":
                raise AuthError(
                    f"stream server at {address} rejected our auth token — "
                    "check DSI_MR_SECRET matches the fleet's")
            raise StreamError(f"fetch {method} from {address}: {err}")
        size = hdr.get("size")
        if not isinstance(size, int) or size < 0 or size > max_bytes:
            raise StreamError(f"fetch from {address}: bad size {size!r}")
        parts: list[bytes] = []
        got = 0
        while True:
            try:
                (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
                if n == 0:
                    (want,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
                    payload = b"".join(parts)
                    if len(payload) != size:
                        raise StreamError(
                            f"fetch from {address}: truncated "
                            f"({len(payload)}/{size} bytes)")
                    if zlib.crc32(payload) != want:
                        raise StreamError(
                            f"fetch from {address}: payload CRC mismatch")
                    return payload
                if n > _MAX_FRAME:
                    raise StreamError(f"fetch from {address}: "
                                      f"oversized chunk {n}")
                chunk = _recv_exact(sock, n)
                (ccrc,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
            except ConnectionError as e:
                if isinstance(e, StreamError):
                    raise
                raise StreamError(
                    f"fetch from {address}: peer died mid-stream "
                    f"({got}/{size} bytes): {e}") from e
            if zlib.crc32(chunk) != ccrc:
                raise StreamError(f"fetch from {address}: chunk CRC "
                                  f"mismatch at byte {got}")
            parts.append(chunk)
            got += n
            if got > max_bytes:
                raise StreamError(f"fetch from {address}: payload exceeds "
                                  f"{max_bytes} bytes")

    def close(self) -> None:
        self._dead = True
        try:
            self._sock.close()
        except OSError:
            pass

    def __enter__(self) -> "StreamConn":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def stream_fetch(address: str, method: str, args: dict | None = None,
                 timeout: float = 60.0, secret: str | None = None,
                 max_bytes: int = _MAX_STREAM) -> bytes:
    """One streaming fetch: dial (with the transient-error backoff budget),
    exchange hellos, send the request, receive and CRC-verify the chunked
    payload, close.  Raises :class:`CoordinatorGone` when the server cannot
    be dialed (dead server — re-fetch from a replacement),
    :class:`ProtocolMismatch` on a version disagreement (mis-deployed
    fleet — do NOT retry), and :class:`StreamError` on a server-side error
    or an integrity failure mid-stream (peer died while serving)."""
    with StreamConn(address, timeout=timeout, secret=secret) as conn:
        return conn.fetch(method, args, max_bytes=max_bytes)
