"""RPC control plane: framed-JSON request/response over a Unix-domain socket.

Reference wire layer: Go ``net/rpc`` + ``rpc.HandleHTTP`` served by
``http.Serve`` on a Unix socket (``mr/coordinator.go:121-132``), client dialing
fresh per call (``mr/worker.go:172-188``), arg/reply structs in ``mr/rpc.go``.

This is a deliberate re-design, not a translation: instead of Go's
HTTP-framed gob RPC we use a minimal length-prefixed JSON protocol —
4-byte big-endian length, then a UTF-8 JSON object.  Request:
``{"method": str, "args": {...}}``; response: ``{"ok": bool, "reply": {...},
"error": str|null}``.  Semantics preserved from the reference:

* one dial per call (``mr/worker.go:175``),
* the server handles calls concurrently (``go http.Serve``,
  ``mr/coordinator.go:131``) — here a thread per connection,
* a dial failure after the coordinator exits is fatal to the worker
  (``log.Fatal``, ``mr/worker.go:176-178``) — surfaced as
  :class:`CoordinatorGone`.

The wire field names (``TaskStatus``, ``NMap``, ``CMap``, ``NReduce``,
``CReduce``, ``Filename``, ``TaskNumber``) are kept identical to
``mr/rpc.go:18-33`` so the protocol is recognizably the same.
"""

from __future__ import annotations

import json
import os
import socket
import socketserver
import struct
import threading
from typing import Any, Callable, Dict

_LEN = struct.Struct(">I")
_MAX_FRAME = 16 << 20


class CoordinatorGone(Exception):
    """Raised when the coordinator socket cannot be dialed (reference:
    worker's log.Fatal on dial error, mr/worker.go:176-178)."""


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        buf += chunk
    return buf


def _send_frame(sock: socket.socket, obj: Any) -> None:
    payload = json.dumps(obj).encode("utf-8")
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_frame(sock: socket.socket) -> Any:
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    if n > _MAX_FRAME:
        raise ConnectionError(f"frame too large: {n}")
    return json.loads(_recv_exact(sock, n).decode("utf-8"))


class RpcServer:
    """Threaded RPC server over a Unix-domain socket.

    Mirrors ``(*Coordinator).server()`` (mr/coordinator.go:121-132): removes a
    stale socket file, listens, and serves in background threads.
    """

    def __init__(self, socket_path: str, methods: Dict[str, Callable[[dict], dict]]):
        self.socket_path = socket_path
        self.methods = dict(methods)
        try:
            os.remove(socket_path)  # mr/coordinator.go:126
        except OSError:
            pass

        handler_methods = self.methods

        class Handler(socketserver.BaseRequestHandler):
            def handle(self) -> None:  # one request per connection (dial-per-call)
                try:
                    req = _recv_frame(self.request)
                    fn = handler_methods.get(req.get("method", ""))
                    if fn is None:
                        _send_frame(self.request, {"ok": False, "reply": None,
                                                   "error": f"no such method: {req.get('method')}"})
                        return
                    reply = fn(req.get("args") or {})
                    _send_frame(self.request, {"ok": True, "reply": reply, "error": None})
                except (ConnectionError, json.JSONDecodeError, OSError):
                    pass  # client vanished mid-call; the 10 s requeue covers it

        class Server(socketserver.ThreadingUnixStreamServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = Server(socket_path, Handler)
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name="dsi-mr-rpc", daemon=True)

    def start(self) -> None:
        self._thread.start()

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        try:
            os.remove(self.socket_path)
        except OSError:
            pass


def call(socket_path: str, method: str, args: dict | None = None,
         timeout: float = 60.0) -> tuple[bool, dict | None]:
    """One RPC: dial, send, receive, close.

    Returns ``(ok, reply)`` like the reference's ``call()`` helper
    (mr/worker.go:172-188).  Raises :class:`CoordinatorGone` if the socket
    cannot be dialed — the reference worker dies here (log.Fatal), and our
    worker loop treats it as job-over.
    """
    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    sock.settimeout(timeout)
    try:
        try:
            sock.connect(socket_path)
        except OSError as e:
            raise CoordinatorGone(f"dialing {socket_path}: {e}") from e
        try:
            _send_frame(sock, {"method": method, "args": args or {}})
            resp = _recv_frame(sock)
        except (OSError, ConnectionError, json.JSONDecodeError):
            return False, None  # RPC-level failure -> ok=false (worker.go:186-188)
        if not resp.get("ok"):
            return False, None
        return True, resp.get("reply")
    finally:
        sock.close()
