"""RPC control plane: framed-JSON request/response over a Unix-domain socket.

Reference wire layer: Go ``net/rpc`` + ``rpc.HandleHTTP`` served by
``http.Serve`` on a Unix socket (``mr/coordinator.go:121-132``), client dialing
fresh per call (``mr/worker.go:172-188``), arg/reply structs in ``mr/rpc.go``.

This is a deliberate re-design, not a translation: instead of Go's
HTTP-framed gob RPC we use a minimal length-prefixed JSON protocol —
4-byte big-endian length, then a UTF-8 JSON object.  Request:
``{"method": str, "args": {...}}``; response: ``{"ok": bool, "reply": {...},
"error": str|null}``.  Semantics preserved from the reference:

* one dial per call (``mr/worker.go:175``),
* the server handles calls concurrently (``go http.Serve``,
  ``mr/coordinator.go:131``) — here a thread per connection,
* a dial failure after the coordinator exits is fatal to the worker
  (``log.Fatal``, ``mr/worker.go:176-178``) — surfaced as
  :class:`CoordinatorGone`.

The wire field names (``TaskStatus``, ``NMap``, ``CMap``, ``NReduce``,
``CReduce``, ``Filename``, ``TaskNumber``) are kept identical to
``mr/rpc.go:18-33`` so the protocol is recognizably the same.

Transports: a Unix-domain socket (the reference's live path) or TCP — the
reference carries a commented-out TCP variant for multi-host operation
(``mr/coordinator.go:124``, ``mr/worker.go:173``); here it is a first-class
address form.  Addresses are strings: ``tcp:HOST:PORT`` selects TCP
(``tcp:0.0.0.0:7777`` to listen on all interfaces; workers on other hosts
then use ``tcp:<coordinator-host>:7777`` via ``DSI_MR_SOCKET``); anything
else is a Unix socket path.  The filesystem data plane must be shared
(NFS etc.) for multi-host runs, exactly as the reference assumes.
"""

from __future__ import annotations

import json
import os
import socket
import socketserver
import struct
import threading
from typing import Any, Callable, Dict

_LEN = struct.Struct(">I")
_MAX_FRAME = 16 << 20


class CoordinatorGone(Exception):
    """Raised when the coordinator socket cannot be dialed (reference:
    worker's log.Fatal on dial error, mr/worker.go:176-178)."""


def parse_address(addr: str):
    """``tcp:HOST:PORT`` -> ("tcp", (host, port)); anything else is a Unix
    socket path -> ("unix", path).  Raises ValueError with a usable message
    on a malformed TCP address (callers on the dial path wrap it)."""
    if addr.startswith("tcp:"):
        host, _, port = addr[4:].rpartition(":")
        try:
            return "tcp", (host or "0.0.0.0", int(port))
        except ValueError:
            raise ValueError(
                f"malformed TCP address {addr!r}: want tcp:HOST:PORT") from None
    return "unix", addr


def _reachable_host(bind_host: str) -> str:
    """A host other machines can dial when we bound a wildcard address.

    ``DSI_MR_ADVERTISE`` overrides (the reliable answer on multi-homed or
    containerized hosts); otherwise the UDP-connect routing trick picks the
    outbound interface, falling back to the hostname — which may resolve to
    loopback on some distros, hence the override.
    """
    env = os.environ.get("DSI_MR_ADVERTISE")
    if env:
        return env
    if bind_host not in ("0.0.0.0", ""):
        return bind_host
    try:
        # Routing trick: connect() on UDP picks the outbound interface
        # without sending a packet.
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect(("10.255.255.255", 1))
            return s.getsockname()[0]
        finally:
            s.close()
    except OSError:
        return socket.gethostname()


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        buf += chunk
    return buf


def _send_frame(sock: socket.socket, obj: Any) -> None:
    payload = json.dumps(obj).encode("utf-8")
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_frame(sock: socket.socket) -> Any:
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    if n > _MAX_FRAME:
        raise ConnectionError(f"frame too large: {n}")
    return json.loads(_recv_exact(sock, n).decode("utf-8"))


class RpcServer:
    """Threaded RPC server over a Unix-domain socket or TCP.

    Mirrors ``(*Coordinator).server()`` (mr/coordinator.go:121-132): removes a
    stale socket file, listens, and serves in background threads.
    """

    def __init__(self, socket_path: str, methods: Dict[str, Callable[[dict], dict]]):
        self.socket_path = socket_path
        self.methods = dict(methods)
        self._kind, target = parse_address(socket_path)
        if self._kind == "unix":
            try:
                os.remove(socket_path)  # mr/coordinator.go:126
            except OSError:
                pass

        handler_methods = self.methods

        class Handler(socketserver.BaseRequestHandler):
            def handle(self) -> None:  # one request per connection (dial-per-call)
                try:
                    # A peer that connects and never sends (port scanner,
                    # stalled NAT) must not pin a handler thread + fd
                    # forever — remotely reachable once bound to TCP.
                    self.request.settimeout(60.0)
                    req = _recv_frame(self.request)
                    fn = handler_methods.get(req.get("method", ""))
                    if fn is None:
                        _send_frame(self.request, {"ok": False, "reply": None,
                                                   "error": f"no such method: {req.get('method')}"})
                        return
                    reply = fn(req.get("args") or {})
                    _send_frame(self.request, {"ok": True, "reply": reply, "error": None})
                except (ConnectionError, json.JSONDecodeError, OSError):
                    pass  # client vanished mid-call; the 10 s requeue covers it

        base = (socketserver.ThreadingTCPServer if self._kind == "tcp"
                else socketserver.ThreadingUnixStreamServer)

        class Server(base):
            daemon_threads = True
            allow_reuse_address = True

        self._server = Server(target, Handler)
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name="dsi-mr-rpc", daemon=True)

    @property
    def address(self) -> str:
        """A dialable address: real port when bound to port 0, and a
        reachable host substituted when bound to a wildcard (0.0.0.0 echoed
        back would dial the *worker's* loopback on another machine)."""
        if self._kind == "tcp":
            host, port = self._server.server_address[:2]
            return f"tcp:{_reachable_host(host)}:{port}"
        return self.socket_path

    def start(self) -> None:
        self._thread.start()

    def close(self) -> None:
        if self._thread.is_alive():  # shutdown() hangs unless serve_forever runs
            self._server.shutdown()
        self._server.server_close()
        if self._kind == "unix":
            try:
                os.remove(self.socket_path)
            except OSError:
                pass


def call(socket_path: str, method: str, args: dict | None = None,
         timeout: float = 60.0) -> tuple[bool, dict | None]:
    """One RPC: dial, send, receive, close.

    Returns ``(ok, reply)`` like the reference's ``call()`` helper
    (mr/worker.go:172-188).  Raises :class:`CoordinatorGone` if the socket
    cannot be dialed — the reference worker dies here (log.Fatal), and our
    worker loop treats it as job-over.
    """
    try:
        kind, target = parse_address(socket_path)
    except ValueError as e:
        raise CoordinatorGone(str(e)) from None
    family = socket.AF_INET if kind == "tcp" else socket.AF_UNIX
    sock = socket.socket(family, socket.SOCK_STREAM)
    sock.settimeout(timeout)
    try:
        try:
            sock.connect(target)
        except OSError as e:
            raise CoordinatorGone(f"dialing {socket_path}: {e}") from e
        try:
            _send_frame(sock, {"method": method, "args": args or {}})
            resp = _recv_frame(sock)
        except (OSError, ConnectionError, json.JSONDecodeError):
            return False, None  # RPC-level failure -> ok=false (worker.go:186-188)
        if not resp.get("ok"):
            return False, None
        return True, resp.get("reply")
    finally:
        sock.close()
