"""tpu_grep: distributed grep with the line filter on device.

Same job and output as ``grep`` (the working realization of the reference's
``mrapps/dgrep.go`` intent — see apps/grep.py): Map emits ``{line, ""}`` per
matching line, Reduce counts occurrences.  Four device tiers: a plain
ASCII literal ``DSI_GREP_PATTERN`` runs as the shifted-compare kernel
(``ops/grepk.py``); fixed-length class patterns (``[Tt]he``, ``w.rd``,
``^\\d\\d`` …) run as the range-compare kernel (``ops/regexk.py``);
top-level alternations of those (``the|and``, ``[Cc]at|[Dd]og``) run one
kernel pass per branch with line flags OR-ed (``ops/altk.py``);
variable-length patterns (``* + ?``, mixed alternation: ``ab*c``,
``[0-9]+``, ``colou?r|gr[ae]y$``) run as a log-depth NFA transition-
matrix scan (``ops/nfak.py``); anything wider (groups, bounded reps,
nullable patterns) falls back to the host Map.
"""

from __future__ import annotations

import os
from typing import List, Optional

from dsi_tpu.apps.grep import Map, Reduce  # noqa: F401  (host fallback)
from dsi_tpu.mr.types import KeyValue

#: C++ task bodies (native/wcjob.cpp via backends/native.py, literal
#: patterns only — regex declines to the host re path).
native_kind = "grep_count"


def tpu_map(filename: str, raw: bytes) -> Optional[List[KeyValue]]:
    from dsi_tpu.ops.altk import altgrep_host_result
    from dsi_tpu.ops.grepk import grep_host_result
    from dsi_tpu.ops.nfak import nfagrep_host_result
    from dsi_tpu.ops.regexk import classgrep_host_result

    pattern = os.environ.get("DSI_GREP_PATTERN", r"(?!x)x")
    lines = grep_host_result(raw, pattern)
    if lines is None:
        lines = classgrep_host_result(raw, pattern)
    if lines is None:
        lines = altgrep_host_result(raw, pattern)
    if lines is None:
        lines = nfagrep_host_result(raw, pattern)
    if lines is None:
        return None
    return [KeyValue(line, "") for line in lines]
