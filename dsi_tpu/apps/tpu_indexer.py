"""tpu_indexer: inverted index with the unique-word extraction on device.

Same job as ``indexer`` (BASELINE.json's string-valued-reduce config): Map
emits one ``{word, document}`` pair per distinct word per document, Reduce
returns ``"<count> <doc1>,<doc2>,..."``.  The per-document distinct-word set
is exactly the unique-word table the fused TPU kernel already produces
(``dsi_tpu/ops/wordcount.py``), so the device map is the kernel minus the
counts.  Host ``Map`` is the exact non-ASCII fallback.
"""

from __future__ import annotations

from typing import List, Optional

from dsi_tpu.apps.indexer import Map, Reduce  # noqa: F401  (host fallback)
from dsi_tpu.mr.types import KeyValue

#: C++ task bodies (native/wcjob.cpp via backends/native.py) implement
#: exactly this app's semantics: Map = distinct words x document, Reduce
#: = "<count> <sorted,docs>".
native_kind = "indexer"


def tpu_map(filename: str, raw: bytes) -> Optional[List[KeyValue]]:
    from dsi_tpu.ops.wordcount import count_words_host_result

    res = count_words_host_result(raw)
    if res is None:
        return None
    return [KeyValue(w, filename) for w in sorted(res)]
