"""TF-IDF app (string-valued reduce over per-document term counts).

Not present in the reference repo; targeted by BASELINE.json's multi-chip
config ("TF-IDF 10 GB shard (multi-chip all_to_all)").  Composes the two
existing kernels' semantics — per-document term counts (the wc path,
``mrapps/wc.go:21-34`` tokenization) and document frequency (the indexer
path) — into tf-idf scores:

* Map(doc, contents) emits one ``{word, "<doc>\\t<tf>"}`` record per
  distinct word per document (a combiner: tf is the in-document count),
* Reduce(word, values) sees one record per document containing the word, so
  ``df = len(distinct docs)``; it scores each document
  ``tf * ln(N / df)`` and returns
  ``"<df> <doc1>:<score1>,<doc2>:<score2>,..."`` with documents sorted.

``N`` (total document count) is job-level config that a per-key reduce
cannot derive, so it arrives via ``DSI_TFIDF_NDOCS`` — the harness, bench
and tests set it to the number of input files.  A missing value is a loud
error: silently wrong idf would defeat the differential-oracle discipline.

``tpu_map`` makes ``--backend=tpu`` route the tokenize/count hot loop
through the fused device kernel (``dsi_tpu/ops/wordcount.py``); the SPMD
whole-corpus path lives in ``dsi_tpu/parallel/tfidf.py`` and produces
byte-identical lines via the shared :func:`format_value`.
"""

from __future__ import annotations

import math
import os
from typing import List, Optional, Sequence, Tuple

from dsi_tpu.apps.wc import tokenize
from dsi_tpu.mr.types import KeyValue

#: C++ map body (native/wcjob.cpp via backends/native.py); the reduce
#: (float scoring) always runs the Python format_value path.
native_kind = "tfidf"


def n_docs_from_env() -> int:
    raw = os.environ.get("DSI_TFIDF_NDOCS")
    if not raw:
        raise RuntimeError(
            "tfidf needs DSI_TFIDF_NDOCS (total document count) — a per-key "
            "reduce cannot derive N, and a silently wrong idf would defeat "
            "output parity checks")
    return int(raw)


def format_value(pairs: Sequence[Tuple[str, int]], n_docs: int) -> str:
    """The reduce output string: ``"<df> doc:score,..."``, docs sorted.

    Shared by the host Reduce and the SPMD path
    (``parallel/tfidf.py``) so both produce byte-identical lines;
    scores are fixed to 6 decimals to keep float formatting deterministic.
    """
    by_doc = dict(pairs)  # defensive dedupe; one entry per doc by contract
    df = len(by_doc)
    idf = math.log(n_docs / df)
    scored = ",".join(f"{d}:{tf * idf:.6f}" for d, tf in sorted(by_doc.items()))
    return f"{df} {scored}"


def Map(filename: str, contents: str) -> List[KeyValue]:
    counts: dict = {}
    for w in tokenize(contents):
        counts[w] = counts.get(w, 0) + 1
    return [KeyValue(w, f"{filename}\t{c}") for w, c in sorted(counts.items())]


def Reduce(key: str, values: List[str]) -> str:
    pairs = []
    for v in values:
        doc, _, tf = v.rpartition("\t")
        pairs.append((doc, int(tf)))
    return format_value(pairs, n_docs_from_env())


def tpu_map(filename: str, raw: bytes) -> Optional[List[KeyValue]]:
    """Device map for ``--backend=tpu``: the fused tokenize/group/count
    kernel; None routes non-ASCII documents to the host Map."""
    from dsi_tpu.ops.wordcount import count_words_host_result

    res = count_words_host_result(raw)
    if res is None:
        return None
    return [KeyValue(w, f"{filename}\t{c}")
            for w, (c, _) in sorted(res.items())]
