"""Word count app.

Reference: ``mrapps/wc.go`` — Map splits contents into maximal runs of Unicode
letters (``strings.FieldsFunc`` with ``!unicode.IsLetter``, wc.go:21-34; note
this splits on digits and underscores too) and emits ``{word, "1"}`` per word;
Reduce returns ``strconv.Itoa(len(values))`` (wc.go:41-44).

``tokenize`` matches Go's ``unicode.IsLetter`` exactly: a letter is a code
point in Unicode category L (Lu/Ll/Lt/Lm/Lo) and nothing else.  A regex like
``[^\\W\\d_]+`` is NOT equivalent: Python's ``\\w`` additionally admits
numeral letters (categories Nl/No — Roman numerals, superscript digits) and
combining marks, which Go splits on — e.g. ``"bⅣc"`` is one Python-regex
token but two Go words (``Ⅳ`` is Nl).  On ASCII the letter class is exactly
``[A-Za-z]`` and a compiled regex is used for speed.
"""

from __future__ import annotations

import re
import unicodedata
from typing import List

from dsi_tpu.mr.types import KeyValue

ASCII_WORD_RE = re.compile(r"[A-Za-z]+")


def is_letter(ch: str) -> bool:
    """Go ``unicode.IsLetter``: Unicode category L, nothing else."""
    return unicodedata.category(ch).startswith("L")


class _NonLettersToSpace(dict):
    """``str.translate`` table mapping non-letters to a space, built and
    memoized lazily per code point (the per-char category lookup happens
    once per distinct character, not once per character of input)."""

    def __missing__(self, cp: int):
        out = chr(cp) if is_letter(chr(cp)) else " "
        self[cp] = out
        return out


_XLATE = _NonLettersToSpace()


def tokenize(contents: str) -> List[str]:
    """Maximal runs of Unicode letters — exactly
    ``strings.FieldsFunc(contents, !unicode.IsLetter)`` (wc.go:21-34)."""
    if contents.isascii():
        return ASCII_WORD_RE.findall(contents)
    # All whitespace is non-letter, so mapping every non-letter to " " and
    # splitting on whitespace yields exactly the maximal letter runs.
    return contents.translate(_XLATE).split()


def Map(filename: str, contents: str) -> List[KeyValue]:
    return [KeyValue(w, "1") for w in tokenize(contents)]


def Reduce(key: str, values: List[str]) -> str:
    return str(len(values))
