"""Word count app.

Reference: ``mrapps/wc.go`` — Map splits contents into maximal runs of Unicode
letters (``strings.FieldsFunc`` with ``!unicode.IsLetter``, wc.go:21-34; note
this splits on digits and underscores too) and emits ``{word, "1"}`` per word;
Reduce returns ``strconv.Itoa(len(values))`` (wc.go:41-44).

``WORD_RE`` = ``[^\\W\\d_]+`` is Python for "one or more Unicode letters":
``\\w`` minus digits minus underscore, i.e. the same token class as Go's
``unicode.IsLetter`` runs (identical on ASCII; both are Unicode category L on
the letters that matter here).
"""

from __future__ import annotations

import re
from typing import List

from dsi_tpu.mr.types import KeyValue

WORD_RE = re.compile(r"[^\W\d_]+", re.UNICODE)


def Map(filename: str, contents: str) -> List[KeyValue]:
    return [KeyValue(w, "1") for w in WORD_RE.findall(contents)]


def Reduce(key: str, values: List[str]) -> str:
    return str(len(values))
