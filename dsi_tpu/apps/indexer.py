"""Inverted-index app (string-valued reduce).

Not present in the reference repo, but targeted by BASELINE.json's configs
("mrapps/indexer.go inverted-index build (string-valued reduce)") — the MIT
6.5840 lab app the reference derives from.  Map emits one ``{word, document}``
pair per word per document (deduplicated within the document); Reduce returns
``"<count> <doc1>,<doc2>,..."`` with documents sorted and deduplicated.
"""

from __future__ import annotations

from typing import List

from dsi_tpu.mr.types import KeyValue
from dsi_tpu.apps.wc import tokenize


def Map(filename: str, contents: str) -> List[KeyValue]:
    words = sorted(set(tokenize(contents)))
    return [KeyValue(w, filename) for w in words]


def Reduce(key: str, values: List[str]) -> str:
    docs = sorted(set(values))
    return f"{len(docs)} {','.join(docs)}"
