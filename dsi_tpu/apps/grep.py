"""Distributed grep app — a *working* version of the reference's intent.

Reference: ``mrapps/dgrep.go`` documents distributed grep but is
non-functional: its symbols are unexported (``grepMap``/``grepReduce``,
dgrep.go:18,44), its Map signature takes ``(contents, pattern)`` instead of
the loader's ``(filename, contents)`` contract (main/mrworker.go:39-41), and
no pattern plumbing exists.  SURVEY.md §2 (C8) directs this rebuild to ship a
working grep with the pattern supplied out-of-band.

Pattern: the ``DSI_GREP_PATTERN`` environment variable (a Python regex;
default matches nothing).  Map emits ``{matching_line, ""}`` per matching
line, like the reference's per-line regex match (dgrep.go:27-35).  Reduce
returns the number of occurrences of the line across the corpus (the
reference's ``return key`` would print the line twice per the "%v %v" output
format; a count is the useful, deliberate choice — documented deviation).
"""

from __future__ import annotations

import os
import re
from typing import List

from dsi_tpu.mr.types import KeyValue

#: C++ task bodies (native/wcjob.cpp via backends/native.py, literal
#: patterns only — regex patterns decline to this module's re path).
native_kind = "grep_count"


def _pattern() -> "re.Pattern[str]":
    return re.compile(os.environ.get("DSI_GREP_PATTERN", r"(?!x)x"))


def Map(filename: str, contents: str) -> List[KeyValue]:
    pat = _pattern()
    return [KeyValue(line, "") for line in contents.split("\n")
            if pat.search(line)]


def Reduce(key: str, values: List[str]) -> str:
    return str(len(values))
