"""The crash app's oracle twin: identical output, no fault injection.

The differential crash test runs the sequential oracle with `nocrash` and the
distributed system with `crash`; outputs must still byte-compare equal.
"""

from dsi_tpu.apps.wc import Map, Reduce  # noqa: F401
