"""Fault-injection app: word count whose Map/Reduce randomly kill the worker.

Not in the reference repo, but the reference *mechanism* it exercises is
(presumed-dead-by-timeout re-queue, mr/coordinator.go:70-77,99-106; idempotent
atomic-rename commits, mr/worker.go:91,148), SURVEY.md §4 flags the missing
crash test as a gap to fill, and BASELINE.json's configs name it.  Modeled on
the MIT lab's crash.go: with some probability the task process exits
immediately; with some probability it stalls long enough to trigger the
straggler re-queue.

Because Reduce is invoked once per distinct key (thousands of times per
reduce task), a naive per-invocation crash probability would make reduce tasks
statistically unable to ever finish.  Each worker process therefore plays the
crash lottery at most DSI_CRASH_MAX_PLAYS times (default 3) over its lifetime;
respawned workers get a fresh allowance.

Env knobs: DSI_CRASH_EXIT_PROB (default 0.25), DSI_CRASH_STALL_PROB (default
0.2), DSI_CRASH_STALL_S (default 3.0), DSI_CRASH_MAX_PLAYS (default 3).
Randomness is seeded per-process.
"""

from __future__ import annotations

import os
import random
import time
from typing import List

from dsi_tpu.mr.types import KeyValue
from dsi_tpu.apps import wc

_rng = random.Random(os.getpid() ^ int(time.time() * 1e6))
_plays = 0


def _maybe_crash() -> None:
    global _plays
    if _plays >= int(os.environ.get("DSI_CRASH_MAX_PLAYS", "3")):
        return
    _plays += 1
    exit_prob = float(os.environ.get("DSI_CRASH_EXIT_PROB", "0.25"))
    stall_prob = float(os.environ.get("DSI_CRASH_STALL_PROB", "0.2"))
    r = _rng.random()
    if r < exit_prob:
        os._exit(1)  # die without cleanup: no completion RPC, no commit
    elif r < exit_prob + stall_prob:
        time.sleep(float(os.environ.get("DSI_CRASH_STALL_S", "3.0")))


def Map(filename: str, contents: str) -> List[KeyValue]:
    _maybe_crash()
    return wc.Map(filename, contents)


def Reduce(key: str, values: List[str]) -> str:
    _maybe_crash()
    return wc.Reduce(key, values)
