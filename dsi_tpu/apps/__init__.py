"""Application plugins (reference: ``mrapps/``).

Each module exposes the two-symbol contract ``Map``/``Reduce``
(mrapps/wc.go:21,41).  Registered names: wc, grep, indexer, tfidf, crash, nocrash.
"""

REGISTERED = ("wc", "grep", "indexer", "tfidf", "crash", "nocrash")
