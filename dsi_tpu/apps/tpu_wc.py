"""tpu_wc: word count with an on-device map-side combiner.

This is the plugin BASELINE.json's north star calls ``mrapps/tpuwc.go``: the
same job as ``wc`` (reference ``mrapps/wc.go:21-44``) but the map task's
tokenize/bucket hot loop (``mr/worker.go:69-78``) runs as the fused TPU
kernel in ``dsi_tpu/ops/wordcount.py`` via the ``--backend=tpu`` worker flag.

Map emits one record per *unique* word per split, valued with its in-split
count (a combiner), so Reduce sums counts instead of counting occurrences.
The merged ``mr-out-*`` output is byte-identical to ``wc``'s — only the
intermediate record multiplicity differs, which the differential harness
deliberately ignores (it compares final output, test-mr.sh:52-53).

The host ``Map`` below is the exact fallback the TPU runner uses for
non-ASCII splits, so correctness never depends on the kernel.
"""

from __future__ import annotations

from collections import Counter
from typing import List, Optional

from dsi_tpu.apps.wc import tokenize
from dsi_tpu.mr.types import KeyValue


def Map(filename: str, contents: str) -> List[KeyValue]:
    counts = Counter(tokenize(contents))
    return [KeyValue(w, str(c)) for w, c in sorted(counts.items())]


def Reduce(key: str, values: List[str]) -> str:
    return str(sum(int(v) for v in values))


def tpu_map(filename: str, raw: bytes) -> Optional[List[KeyValue]]:
    """Device map: fused tokenize/group/count; None -> host fallback."""
    from dsi_tpu.ops.wordcount import count_words_host_result

    res = count_words_host_result(raw)
    if res is None:
        return None
    return [KeyValue(w, str(c)) for w, (c, _) in sorted(res.items())]
